"""Multi-device wide aggregation over a jax.sharding.Mesh.

The reference's only multi-worker execution is a single-JVM ForkJoinPool
(ParallelAggregation.java:160-186).  Here the same rotation scales across
chips: the container-row axis is sharded over the mesh's "rows" axis (the
data-parallel analog), the 2048-word lane axis over "lanes" (tensor-parallel
analog).  Each device reduces its resident rows into a dense per-key
accumulator; cross-device combination is a bitwise OR/XOR/AND tree over ICI.

Collective choice: bitwise ops are not in XLA's reduce-collective vocabulary
(psum/pmax only), so the combine is an explicit log2(D) ppermute butterfly —
each step exchanges accumulators with a partner at doubling distance and
merges locally.  D accumulators of K x 8KB ride the ICI exactly once per
step, and every device finishes with the full result (matching psum
semantics for the downstream popcount).
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level; 0.4.x keeps it
    shard_map = jax.shard_map  # experimental (this image's 0.4.37 has no
except AttributeError:        # top-level alias at all — seed suite red)
    from jax.experimental.shard_map import shard_map as _shard_map_04x

    def shard_map(f, **kw):
        # 0.4.x named the replication check check_rep, not check_vma
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map_04x(f, **kw)

from ..obs import cost as obs_cost
from ..ops import dense, packing

WORDS32 = packing.WORDS32


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical PartitionSpecs for the mesh axes every sharded plan path
    shares (the SNIPPETS [3] pattern: one frozen vocabulary instead of
    hand-rolled ``P(...)`` literals scattered across call sites).

    Axis semantics (docs/BATCH_ENGINE.md "Mesh-sharded execution"):

    - ``row_axis`` ("rows"): the container-row / pooled-row axis — the
      data-parallel direction.  Resident pool images shard here.
    - ``data_axis`` ("data"): query/pool replication.  The sharded batch
      engine spreads a launch's *transient* gathered rows over
      ``(rows, data)`` jointly, so every device carries row work while
      the resident pool stays replicated along data.
    - ``lane_axis`` ("lanes"): the 2048-word lane axis — the
      tensor-parallel direction of the wide-aggregation path.
    """

    row_axis: str = "rows"
    data_axis: str = "data"
    lane_axis: str = "lanes"

    # ---- resident placements
    def pooled_rows(self) -> P:
        """Pooled resident image u32[rows, 2048]: rows data-parallel,
        lanes local, replicated along data (parallel.sharded_engine)."""
        return P(self.row_axis, None)

    def packed_rows(self) -> P:
        """Wide-aggregation pack u32[rows, 2048]: rows x lanes (the
        original shard_packed placement)."""
        return P(self.row_axis, self.lane_axis)

    def row_vec(self) -> P:
        """Per-row metadata (seg_ids, stream parts) sharded with rows."""
        return P(self.row_axis)

    # ---- per-launch transients (sharded batch engine)
    def gather_rows(self) -> P:
        """A launch's gathered operand block: flat rows over EVERY device
        (rows x data jointly), lanes local."""
        return P((self.row_axis, self.data_axis), None)

    def gather_vec(self) -> P:
        """Flat per-gather-row metadata (flat_seg, valid), sharded like
        gather_rows."""
        return P((self.row_axis, self.data_axis))

    # ---- outputs / broadcast operands
    def replicated(self) -> P:
        return P()

    def combined_heads(self) -> P:
        """The sharded batch engine's head accumulator AFTER the
        butterfly combine: every device holds the full reduction."""
        return P(None, None)

    def heads(self) -> P:
        """Wide-aggregation per-key result: replicated rows, lanes
        tensor-parallel."""
        return P(None, self.lane_axis)

    def index_rows(self) -> P:
        """BSI/RangeBitmap (ebm, per-slice) tensors: key rows
        data-parallel, lanes tensor-parallel."""
        return P(self.row_axis, self.lane_axis)

    def sliced_index(self) -> P:
        """Stacked slice planes u32[S, K, 2048]: slice axis local."""
        return P(None, self.row_axis, self.lane_axis)


#: the default axis vocabulary; call sites needing renamed axes build
#: their own SpecLayout(row_axis=..., ...) instead of hand-rolling specs
SPECS = SpecLayout()

#: Per-device dense-accumulator ceiling, in keys.  Each device materializes
#: u32[K+1, 2048] (8 KiB/key) before the butterfly, so K is a direct HBM
#: budget: 4096 keys = 32 MiB.  wide_aggregate_sharded chunks the key axis
#: at this granularity (per-device memory stays bounded for any K up to the
#: 2^16-key universe); make_sharded_aggregator itself refuses larger K with
#: a typed error rather than silently allocating O(K) on every device.
MAX_KEYS_PER_SHARD_PASS = 4096


class ShardedKeyBudgetError(ValueError):
    """num_keys exceeds the per-device dense-accumulator ceiling."""


def _local_dense_accumulate(op: str, words, seg_ids, num_keys: int, n_steps: int):
    """Reduce local rows -> dense u32[K+1, 2048] accumulator over ALL keys.

    Rows are globally sorted by segment, so a shard's rows for one segment
    are contiguous: after the doubling pass the shard-local head row of each
    segment holds the shard's partial reduction.  Heads scatter into the
    global key space; non-head rows land in the K-th scratch row.
    """
    words = dense.doubling_pass(dense.OPS[op], words, seg_ids, n_steps)
    prev = jnp.concatenate([jnp.full((1,), -1, seg_ids.dtype), seg_ids[:-1]])
    is_head = seg_ids != prev
    dest = jnp.where(is_head & (seg_ids < num_keys), seg_ids, num_keys)
    acc = jnp.zeros((num_keys + 1, words.shape[1]), words.dtype)
    # one head per segment per shard -> unique destinations; scatter is exact
    return acc.at[dest].set(words)


def _butterfly_combine(op: str, acc, axis_name: str, axis_size: int):
    """log2(D) ppermute butterfly; all devices end with the full reduction."""
    fn = dense.OPS[op]
    d = 1
    while d < axis_size:
        perm = [(i, i ^ d) for i in range(axis_size)]
        other = jax.lax.ppermute(acc, axis_name, perm)
        acc = fn(acc, other)
        d *= 2
    return acc


_mesh_intern: dict = {}


def _intern_mesh(mesh: Mesh) -> Mesh:
    """Canonical instance per (device ids, shape, axis names).

    Notebooks commonly recreate an equivalent Mesh every call; keying the
    executable caches on the first such instance means they hit instead of
    pinning a fresh compiled program (and its mesh) per call (ADVICE r3).
    """
    key = (tuple(d.id for d in mesh.devices.flat), mesh.devices.shape,
           mesh.axis_names, getattr(mesh, "axis_types", None))
    return _mesh_intern.setdefault(key, mesh)


@functools.lru_cache(maxsize=128)
def _make_sharded_aggregator(mesh: Mesh, op: str, num_keys: int, n_steps: int,
                             row_axis: str, lane_axis: str):
    """Build a jitted SPMD wide-aggregation step for fixed (K, steps),
    cached per (mesh, op, K, steps, axes) so repeated calls with a stable
    workload shape reuse one executable.

    In:  words u32[M, 2048] sharded (rows, lanes); seg_ids i32[M] sharded (rows,)
    Out: (u32[K, 2048] result sharded over lanes, i32[K] cardinalities, replicated)

    op is "or" or "xor"; wide AND goes through the regular workShy path
    (parallel.aggregation.and_), whose key intersection makes the block dense.
    """
    if op not in ("or", "xor"):
        raise ValueError("sharded ragged aggregation supports or/xor only")
    if num_keys > MAX_KEYS_PER_SHARD_PASS:
        raise ShardedKeyBudgetError(
            f"{num_keys} keys would allocate a "
            f"{(num_keys + 1) * 8 // 1024} MiB dense accumulator on EVERY "
            f"row-shard device (ceiling {MAX_KEYS_PER_SHARD_PASS} keys = "
            f"{(MAX_KEYS_PER_SHARD_PASS + 1) * 8 // 1024} MiB); use "
            "wide_aggregate_sharded, which chunks the key axis under the "
            "ceiling")
    axis_size = mesh.shape[row_axis]

    def step(words, seg_ids):
        acc = _local_dense_accumulate(op, words, seg_ids, num_keys, n_steps)
        acc = _butterfly_combine(op, acc, row_axis, axis_size)
        heads = acc[:num_keys]
        cards = jnp.sum(jax.lax.population_count(heads).astype(jnp.int32), axis=-1)
        cards = jax.lax.psum(cards, lane_axis)
        return heads, cards

    # check_vma=False: after the ppermute butterfly every device holds the
    # full reduction, but JAX cannot prove ppermute outputs replicated.
    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(sp.packed_rows(), sp.row_vec()),
        out_specs=(sp.heads(), sp.replicated()),
        check_vma=False,
    )
    return jax.jit(mapped)


def make_sharded_aggregator(mesh: Mesh, op: str, num_keys: int, n_steps: int,
                            row_axis: str = "rows", lane_axis: str = "lanes"):
    """Public entry: interns the mesh (see _intern_mesh) then returns the
    cached jitted SPMD step."""
    return _make_sharded_aggregator(_intern_mesh(mesh), op, num_keys,
                                    n_steps, row_axis, lane_axis)


def shard_packed(mesh: Mesh, packed: packing.PackedAggregation,
                 row_axis: str = "rows", lane_axis: str = "lanes"):
    """Pad rows to the mesh row-axis multiple and device_put with shardings."""
    return _shard_rows(mesh, packed.words, packed.seg_ids, packed.num_keys,
                       row_axis, lane_axis)


def _shard_rows(mesh: Mesh, words: np.ndarray, seg_ids: np.ndarray,
                scratch_seg: int, row_axis: str = "rows",
                lane_axis: str = "lanes"):
    """shard_packed over raw (words, seg_ids) arrays; padding rows target
    the scratch segment (index scratch_seg, one past the real keys)."""
    n_rows = mesh.shape[row_axis]
    m_pad = max(-(-words.shape[0] // n_rows) * n_rows, n_rows)
    if m_pad != words.shape[0]:
        extra = m_pad - words.shape[0]
        words = np.concatenate([words, np.zeros((extra, WORDS32), np.uint32)])
        seg_ids = np.concatenate(
            [seg_ids, np.full(extra, scratch_seg, np.int32)])
    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    words_d = jax.device_put(words, NamedSharding(mesh, sp.packed_rows()))
    segs_d = jax.device_put(seg_ids, NamedSharding(mesh, sp.row_vec()))
    return words_d, segs_d


def _key_chunks(num_keys: int) -> list[tuple[int, int]]:
    step = MAX_KEYS_PER_SHARD_PASS
    return [(k, min(k + step, num_keys)) for k in range(0, num_keys, step)]


def _slice_blocked(blocked: packing.PackedBlockedCompact, k0: int, k1: int
                   ) -> packing.PackedBlockedCompact:
    """Key-range [k0, k1) slice of a blocked compact pack: blocks are sorted
    by segment, so the slice is a contiguous block range whose streams are
    re-based to row 0 — the unit wide_aggregate_sharded feeds the mesh when
    the full key axis would blow the per-device accumulator ceiling."""
    block = blocked.block
    b0 = int(np.searchsorted(blocked.blk_seg, k0, side="left"))
    b1 = int(np.searchsorted(blocked.blk_seg, k1, side="left"))
    row0, row1 = b0 * block, b1 * block
    s = blocked.streams
    dm = (s.dense_dest >= row0) & (s.dense_dest < row1)
    heads = np.concatenate(([0], np.cumsum(s.val_counts)))
    vi = np.flatnonzero((s.val_dest >= row0) & (s.val_dest < row1))
    values = (np.concatenate([s.values[heads[i]:heads[i + 1]] for i in vi])
              if vi.size else np.empty(0, np.uint16))
    streams = packing.CompactStreams(
        n_rows=row1 - row0,
        dense_words=s.dense_words[dm],
        dense_dest=(s.dense_dest[dm] - row0).astype(np.int32),
        values=values,
        val_counts=s.val_counts[vi].astype(np.int32),
        val_dest=(s.val_dest[vi] - row0).astype(np.int32))
    return packing.PackedBlockedCompact(
        keys=blocked.keys[k0:k1],
        blk_seg=(blocked.blk_seg[b0:b1] - k0).astype(np.int32),
        block=block, n_blocks=b1 - b0,
        seg_sizes=blocked.seg_sizes[k0:k1],
        seg_offsets=blocked.seg_offsets[k0:k1] - row0,
        streams=streams, carry_row=-1)


def _split_streams_by_shard(s: packing.CompactStreams, rows_per_shard: int,
                            d: int):
    """Partition compact streams by destination shard, padding each shard's
    sub-stream to the cross-shard maximum (padding rows/values target the
    per-shard scratch row, index rows_per_shard, exactly like
    pad_streams_pow2's sentinel scheme)."""
    # dense sub-streams
    sh = s.dense_dest // rows_per_shard
    md = int(np.bincount(sh, minlength=d).max()) if sh.size else 0
    dense_words = np.zeros((d, max(md, 1), packing.WORDS32), np.uint32)
    dense_dest = np.full((d, max(md, 1)), rows_per_shard, np.int32)
    for k in range(d):
        rows = np.flatnonzero(sh == k)
        dense_words[k, :rows.size] = s.dense_words[rows]
        dense_dest[k, :rows.size] = s.dense_dest[rows] - k * rows_per_shard
    # sparse sub-streams: split the value stream at container boundaries
    heads = np.concatenate(([0], np.cumsum(s.val_counts)))
    shv = s.val_dest // rows_per_shard
    mv = int(np.bincount(shv, minlength=d).max()) if shv.size else 0
    vmax = 0
    per_shard: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    for k in range(d):
        idx = np.flatnonzero(shv == k)
        vals = (np.concatenate([s.values[heads[i]:heads[i + 1]]
                                for i in idx])
                if idx.size else np.empty(0, np.uint16))
        per_shard.append((vals, s.val_counts[idx],
                          s.val_dest[idx] - k * rows_per_shard))
        vmax = max(vmax, vals.size)
    values = np.zeros((d, max(vmax, 1)), np.uint16)
    val_counts = np.zeros((d, max(mv, 1) + 1), np.int32)
    val_dest = np.full((d, max(mv, 1) + 1), rows_per_shard, np.int32)
    for k, (vals, counts, dests) in enumerate(per_shard):
        values[k, :vals.size] = vals
        val_counts[k, :counts.size] = counts
        val_counts[k, -1] = values.shape[1] - vals.size  # sentinel soak
        val_dest[k, :dests.size] = dests
    return dense_words, dense_dest, values, val_counts, val_dest


def shard_streams(mesh: Mesh, blocked: packing.PackedBlockedCompact,
                  row_axis: str = "rows"):
    """Compact multi-chip ingest: ship ~serialized-size streams to the mesh
    and densify per shard ON DEVICE — the host never materializes the dense
    [M, 2048] image (which is 6-1300x the serialized bytes on the SURVEY
    datasets).  Returns (words u32[rows, 2048] sharded over row_axis,
    seg_ids i32[rows] sharded, blk_seg i32[nb_padded] — the block->segment
    map padded for shard divisibility, host-side).
    """
    d = mesh.shape[row_axis]
    block, k = blocked.block, blocked.keys.size
    nb = int(blocked.blk_seg.size)
    nb_pad = -(-nb // d) * d  # block count divisible across shards
    blk_seg = np.full(nb_pad, k, np.int32)
    blk_seg[:nb] = blocked.blk_seg
    rows = nb_pad * block
    rows_per_shard = rows // d
    parts = _split_streams_by_shard(blocked.streams, rows_per_shard, d)
    total_values = int(parts[2].shape[1])

    mapped = _sharded_densify(mesh, row_axis, rows_per_shard, total_values)
    sharding = NamedSharding(mesh, SpecLayout(row_axis=row_axis).row_vec())
    dev = [jax.device_put(a, sharding) for a in parts]
    words = mapped(*dev)
    seg_ids = jax.device_put(
        np.repeat(blk_seg, block).astype(np.int32), sharding)
    return words, seg_ids, blk_seg


@functools.lru_cache(maxsize=64)
def _sharded_densify_cached(mesh: Mesh, row_axis: str, rows_per_shard: int,
                            total_values: int):
    """Cached jitted per-shard densify program — keyed on (mesh, axis,
    shard rows, value-stream length) so repeated compact ingests with a
    stable workload shape reuse one executable instead of re-tracing a
    fresh closure every call."""

    def densify_local(dw, dd, v, vc, vdst):
        # leading shard axis is size 1 inside the shard; drop it
        return dense.densify_streams_impl(
            dw[0], dd[0], v[0], vc[0], vdst[0],
            rows_per_shard, total_values)

    return jax.jit(shard_map(
        densify_local, mesh=mesh,
        in_specs=(P(row_axis), P(row_axis), P(row_axis), P(row_axis),
                  P(row_axis)),
        out_specs=P(row_axis),
    ))


def _sharded_densify(mesh: Mesh, row_axis: str, rows_per_shard: int,
                     total_values: int):
    # hit/miss compile accounting like the batch/multiset program caches
    # (rb_compile_seconds — the sharded lane's cold-path gauge); a miss
    # here only pays the trace, XLA compiles lazily at first call
    before = _sharded_densify_cached.cache_info().hits
    t0 = time.perf_counter()
    fn = _sharded_densify_cached(_intern_mesh(mesh), row_axis,
                                 rows_per_shard, total_values)
    hit = _sharded_densify_cached.cache_info().hits > before
    obs_cost.observe_compile("sharding", "hit" if hit else "miss",
                             time.perf_counter() - t0)
    return fn


def wide_aggregate_sharded(mesh: Mesh, op: str, bitmaps,
                           ingest: str = "dense", fallback: bool = True
                           ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """End to end: pack, shard, reduce across the mesh. Returns (keys, words, cards).

    ingest="dense" host-densifies then scatters (8 KB/container on the
    wire); ingest="compact" ships compact streams (~serialized size) and
    densifies per shard on device — same reduction, same results.  AND
    routes through the workShy key-intersection path for either ingest
    (byte-backed sources are wrapped zero-copy; only surviving containers
    materialize).

    Guarded (runtime.guard): transient mesh/collective failures retry with
    backoff; a classified fault that survives retries degrades to the host
    sequential fold, which returns an equivalent (keys, words, cards)
    triple (zero-cardinality keys normalized away) — a lost mesh costs
    throughput, never availability or bits.  ``fallback=False`` runs the
    sharded path raw (no guard, no injection), the pin parity tests use
    so a sharded-path regression cannot hide behind the host fold.
    """
    if ingest not in ("dense", "compact"):
        raise ValueError(f"unknown ingest {ingest!r}")
    if op not in ("or", "xor", "and"):
        raise ValueError(f"unsupported sharded wide op {op!r}")
    from ..obs import trace as obs_trace
    from ..runtime import faults, guard

    bitmaps = list(bitmaps)
    with obs_trace.span("sharding.wide_aggregate", site="sharding", op=op,
                        ingest=ingest, n=len(bitmaps),
                        devices=mesh.devices.size,
                        fallback=fallback) as sp:
        if not fallback:
            return _wide_aggregate_sharded_device(mesh, op, bitmaps, ingest)

        def attempt(rung):
            faults.maybe_fail("sharding", rung)
            return _wide_aggregate_sharded_device(mesh, op, bitmaps, ingest)

        res, rung = guard.run_with_fallback(
            "sharding", ("sharded",), attempt,
            sequential=lambda: _sequential_sharded(op, bitmaps))
        sp.tag(rung_used=rung)
        return res


def explain_sharded(mesh: Mesh, op: str, bitmaps,
                    ingest: str = "dense") -> dict:
    """Thin plan report for wide_aggregate_sharded (the BatchEngine.explain
    analog for the mesh path): key-chunk schedule under the per-device
    accumulator ceiling and each pass's per-device dense-accumulator bytes
    (the quantity MAX_KEYS_PER_SHARD_PASS bounds), from the unified
    footprint model.  JSON-serializable; no device work."""
    from ..insights import analysis as insights
    from ..runtime import guard

    bitmaps = _wrap_bytes(list(bitmaps))
    keys = np.unique(np.concatenate([_keys_np(b) for b in bitmaps])) \
        if bitmaps else np.empty(0, np.uint16)
    chunks = _key_chunks(int(keys.size))
    passes = [{"keys": [int(k0), int(k1)],
               "per_device_accumulator_bytes":
                   insights.dense_rows_bytes(k1 - k0 + 1)}
              for k0, k1 in chunks] or [
        {"keys": [0, 0], "per_device_accumulator_bytes": 0}]
    peak = max(p["per_device_accumulator_bytes"] for p in passes)
    budget = guard.resolve_hbm_budget()
    return {
        "site": "sharding", "op": op, "ingest": ingest,
        "n": len(bitmaps), "devices": int(mesh.devices.size),
        "num_keys": int(keys.size), "passes": passes,
        "max_keys_per_pass": MAX_KEYS_PER_SHARD_PASS,
        "predicted_hbm_bytes": int(peak),
        "hbm_budget_bytes": budget,
        "within_budget": budget is None or peak <= budget,
        "engine_chain": ["sharded", guard.SEQUENTIAL],
    }


def _keys_np(b) -> np.ndarray:
    return np.asarray(b.keys)


def _sequential_sharded(op: str, bitmaps
                        ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CPU sequential reference for the sharded wide ops, shaped like the
    device result: host container fold, then one dense (keys, words,
    cards) materialization."""
    from .aggregation import _sequential_reduce

    bs = [b for b in _wrap_bytes(bitmaps)]
    empty = (np.empty(0, np.uint16), np.zeros((0, WORDS32), np.uint32),
             np.zeros((0,), np.int32))
    if not bs:
        return empty
    if op == "and" and any(b.is_empty() for b in bs):
        return empty
    if op != "and":
        bs = [b for b in bs if not b.is_empty()]
        if not bs:
            return empty
    acc = _sequential_reduce(op, bs)
    if acc.is_empty():
        return empty
    packed = packing.pack_for_aggregation([acc], pad_rows=False)
    words = np.asarray(packed.words, dtype=np.uint32)
    cards = np.unpackbits(words.view(np.uint8), axis=1).sum(
        axis=1).astype(np.int32)
    return packed.keys, words, cards


def _wide_aggregate_sharded_device(mesh: Mesh, op: str, bitmaps,
                                   ingest: str
                                   ) -> tuple[np.ndarray, np.ndarray,
                                              np.ndarray]:
    # byte-backed sources work on every path: zero-copy wrap for the object
    # consumers (pack_for_aggregation / the AND key intersection); the
    # compact packer handles bytes natively
    if op == "and":
        return wide_and_sharded(mesh, _wrap_bytes(bitmaps))
    if ingest == "dense":
        bitmaps = _wrap_bytes(bitmaps)
    if ingest == "compact":
        blocked = packing.pack_blocked_compact(bitmaps, carry_slot=False)
        heads_parts, cards_parts = [], []
        for k0, k1 in _key_chunks(blocked.keys.size):
            sub = blocked if (k0, k1) == (0, blocked.keys.size) \
                else _slice_blocked(blocked, k0, k1)
            words_d, segs_d, _ = shard_streams(mesh, sub)
            # max padded group size in O(K): groups are block-multiple-padded
            gp_max = int((-(-sub.seg_sizes // sub.block)
                          * sub.block).max()) if sub.keys.size else 0
            step = make_sharded_aggregator(mesh, op, sub.keys.size,
                                           dense.n_steps_for(gp_max))
            heads, cards = step(words_d, segs_d)
            heads_parts.append(np.asarray(heads))
            cards_parts.append(np.asarray(cards))
        return (blocked.keys,
                _concat_chunks(heads_parts, (0, WORDS32), np.uint32),
                _concat_chunks(cards_parts, (0,), np.int32))
    packed = packing.pack_for_aggregation(bitmaps)
    heads_parts, cards_parts = [], []
    for k0, k1 in _key_chunks(packed.num_keys):
        if (k0, k1) == (0, packed.num_keys):
            # single chunk: keep the pow2-padded pack rows so repeated
            # calls with drifting row counts reuse bucketed executables
            words_d, segs_d = shard_packed(mesh, packed)
            step = make_sharded_aggregator(mesh, op, packed.num_keys,
                                           dense.n_steps_for(packed.max_group))
        else:
            row0 = int(packed.head_idx[k0])
            row1 = (int(packed.head_idx[k1]) if k1 < packed.num_keys
                    else packed.m)
            sub_segs = (packed.seg_ids[row0:row1] - k0).astype(np.int32)
            max_group = int(packed.seg_sizes[k0:k1].max())
            words_d, segs_d = _shard_rows(mesh, packed.words[row0:row1],
                                          sub_segs, k1 - k0)
            step = make_sharded_aggregator(mesh, op, k1 - k0,
                                           dense.n_steps_for(max_group))
        heads, cards = step(words_d, segs_d)
        heads_parts.append(np.asarray(heads))
        cards_parts.append(np.asarray(cards))
    return (packed.keys,
            _concat_chunks(heads_parts, (0, WORDS32), np.uint32),
            _concat_chunks(cards_parts, (0,), np.int32))


def _concat_chunks(parts: list[np.ndarray], empty_shape, dtype) -> np.ndarray:
    if not parts:
        return np.zeros(empty_shape, dtype)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def _pad_to_multiple(arr: np.ndarray, multiple: int, fill,
                     axis: int = 0) -> np.ndarray:
    pad = -(-arr.shape[axis] // multiple) * multiple - arr.shape[axis]
    if pad == 0:
        return arr
    shape = list(arr.shape)
    shape[axis] = pad
    return np.concatenate([arr, np.full(shape, fill, arr.dtype)], axis=axis)


def make_sharded_and(mesh: Mesh,
                     row_axis: str = "rows", lane_axis: str = "lanes"):
    """Jitted SPMD wide-AND over a regular block u32[K, N_pad, 2048] with the
    bitmap axis sharded over `row_axis` (padding bitmaps are all-ones, the
    AND identity).  Local AND-reduce, then a ppermute AND butterfly — the
    cross-chip form of workShyAnd's iand chain (FastAggregation.java:393-411)."""
    axis_size = mesh.shape[row_axis]

    def step(words):
        local = jax.lax.reduce(words, jnp.uint32(0xFFFFFFFF),
                               jax.lax.bitwise_and, (1,))
        acc = _butterfly_combine("and", local, row_axis, axis_size)
        cards = jnp.sum(jax.lax.population_count(acc).astype(jnp.int32),
                        axis=-1)
        cards = jax.lax.psum(cards, lane_axis)
        return acc, cards

    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    mapped = shard_map(
        step, mesh=mesh,
        in_specs=(sp.sliced_index(),),
        out_specs=(sp.heads(), sp.replicated()),
        check_vma=False,
    )
    return jax.jit(mapped)


def _wrap_bytes(bitmaps):
    """Byte-backed sources -> zero-copy ImmutableRoaringBitmaps (headers
    parsed, payloads untouched) so the workShy AND path can run key
    intersection and materialize only surviving containers."""
    from ..buffer import ImmutableRoaringBitmap
    from ..format import spec

    out = []
    for b in bitmaps:
        if isinstance(b, (bytes, bytearray, memoryview)):
            out.append(ImmutableRoaringBitmap(b))
        elif isinstance(b, spec.SerializedView):
            out.append(ImmutableRoaringBitmap(b.buf))
        else:
            out.append(b)
    return out


def wide_and_sharded(mesh: Mesh, bitmaps,
                     row_axis: str = "rows", lane_axis: str = "lanes"
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sharded workShyAnd: host key-mask intersection (see
    aggregation._intersect_keys — an 8 KiB AND-reduce never justifies a
    device dispatch), then the bitmap-axis sharded AND butterfly.
    Returns (keys, words, cards)."""
    from .aggregation import _intersect_keys

    if not bitmaps or any(b.is_empty() for b in bitmaps):
        return (np.empty(0, np.uint16), np.zeros((0, WORDS32), np.uint32),
                np.zeros((0,), np.int32))
    keys = _intersect_keys(bitmaps)
    if keys.size == 0:
        return (keys, np.zeros((0, WORDS32), np.uint32),
                np.zeros((0,), np.int32))
    packed = packing.pack_for_intersection(bitmaps, keys=keys)
    # padding bitmaps are all-ones, the AND identity
    words = _pad_to_multiple(packed.words, mesh.shape[row_axis],
                             np.uint32(0xFFFFFFFF), axis=1)
    words_d = jax.device_put(
        words, NamedSharding(
            mesh, SpecLayout(row_axis=row_axis,
                             lane_axis=lane_axis).sliced_index()))
    step = make_sharded_and(mesh, row_axis, lane_axis)
    acc, cards = step(words_d)
    return packed.keys, np.asarray(acc), np.asarray(cards)


# --------------------------------------------------------------- sharded BSI
#
# The BSI/RangeBitmap slice axes shard naturally: slices u32[S, K, 2048]
# puts the container-key axis on "rows" and the 2048-word axis on "lanes".
# The fused O'Neil scan (bsi.device.oneil_scan) is elementwise over
# [K, 2048], so the whole comparator runs with ZERO communication; the only
# collective is the final cardinality psum (compare) / per-slice popcount
# psum (sum).

@functools.lru_cache(maxsize=64)
def _make_sharded_bsi_compare(mesh: Mesh, op: str, row_axis: str,
                              lane_axis: str):
    from ..bsi import device as bsi_dev

    def step(slices, ebm, bits, bits2):
        res = bsi_dev._compare_res(op, slices, ebm, bits, bits2, ebm)
        card = jnp.sum(jax.lax.population_count(res).astype(jnp.int32))
        return jax.lax.psum(card, (row_axis, lane_axis))

    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(sp.sliced_index(), sp.index_rows(),
                  sp.replicated(), sp.replicated()),
        out_specs=sp.replicated(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=64)
def _make_sharded_bsi_topk(mesh: Mesh, row_axis: str, lane_axis: str):
    """Kaser top-K scan over the mesh: the scan body is shard-local except
    the candidate count, which psums each slice step (log2-depth scalar
    collectives); k rides as a replicated traced scalar so one executable
    serves every k.  Returns the pre-trim result cardinality (>= k with
    ties), the quantity DeviceBSI._topk_words proves parity on."""
    from ..bsi import device as bsi_dev

    def step_fn(slices, found, k):
        def step(state, slice_words):
            g, e = state
            x = g | (e & slice_words)
            n = jax.lax.psum(
                jnp.sum(bsi_dev.popcount(x)), (row_axis, lane_axis))
            take = n < k
            g = jnp.where(take, x, g)
            e = jnp.where(take, e & ~slice_words, e & slice_words)
            return (g, e), None

        zero = jnp.zeros_like(found)
        (g, e), _ = jax.lax.scan(step, (zero, found),
                                 jnp.flip(slices, axis=0))
        card = jnp.sum(bsi_dev.popcount(g | e).astype(jnp.int32))
        return jax.lax.psum(card, (row_axis, lane_axis))

    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    return jax.jit(shard_map(
        step_fn, mesh=mesh,
        in_specs=(sp.sliced_index(), sp.index_rows(), sp.replicated()),
        out_specs=sp.replicated(),
        check_vma=False,
    ))


@functools.lru_cache(maxsize=64)
def _make_sharded_range_compare(mesh: Mesh, op: str, row_axis: str,
                                lane_axis: str):
    """Sharded RangeBitmap threshold query: the O'Neil/double-bound scan is
    elementwise over the sharded (slice, key-row, lane) tensor — no
    collective until the final cardinality psum (same structure as the BSI
    compare; RangeBitmap's base-2 slices ARE a BSI over row ids)."""
    from ..bsi import device as bsi_dev

    def step(slices, ebm, bits, bits2):
        res = bsi_dev._range_res(op, slices, ebm, bits, bits2, ebm)
        card = jnp.sum(jax.lax.population_count(res).astype(jnp.int32))
        return jax.lax.psum(card, (row_axis, lane_axis))

    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(sp.sliced_index(), sp.index_rows(),
                  sp.replicated(), sp.replicated()),
        out_specs=sp.replicated(),
        check_vma=False,
    ))


def _shard_index_arrays(mesh: Mesh, ebm_np: np.ndarray,
                        slices_np: np.ndarray, depth: int, row_axis: str,
                        lane_axis: str):
    """Pad the key-row axis to a row-shard multiple (zero rows: no members,
    contribute nothing to any query) and push (ebm, slices) mesh-sharded:
    key rows data-parallel, the 2048-word lane axis tensor-parallel."""
    r = mesh.shape[row_axis]
    k = ebm_np.shape[0]
    kpad = max(-(-k // r) * r, r)
    if kpad != k:
        ebm_np = np.concatenate(
            [ebm_np, np.zeros((kpad - k, WORDS32), np.uint32)])
        slices_np = np.concatenate(
            [slices_np,
             np.zeros((depth, kpad - k, WORDS32), np.uint32)],
            axis=1) if depth else slices_np
    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    ebm = jax.device_put(
        ebm_np, NamedSharding(mesh, sp.index_rows()))
    slices = jax.device_put(
        slices_np, NamedSharding(mesh, sp.sliced_index()))
    return ebm, slices


@functools.lru_cache(maxsize=64)
def _make_sharded_bsi_slice_cards(mesh: Mesh, row_axis: str, lane_axis: str):
    from ..bsi import device as bsi_dev

    def step(slices, found):
        cards = bsi_dev._slice_cards_res(slices, found)
        count = jnp.sum(jax.lax.population_count(found).astype(jnp.int32))
        return (jax.lax.psum(cards, (row_axis, lane_axis)),
                jax.lax.psum(count, (row_axis, lane_axis)))

    sp = SpecLayout(row_axis=row_axis, lane_axis=lane_axis)
    return jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(sp.sliced_index(), sp.index_rows()),
        out_specs=(sp.replicated(), sp.replicated()),
        check_vma=False,
    ))


class ShardedBSI:
    """A RoaringBitmapSliceIndex sharded over a device mesh.

    The multi-device form of bsi.device.DeviceBSI (VERDICT r3 #9): compare
    and sum scale across chips with the key axis data-parallel and the word
    axis tensor-parallel; predicates stay replicated scalars so one
    compiled executable serves every threshold.
    """

    def __init__(self, mesh: Mesh, bsi, row_axis: str = "rows",
                 lane_axis: str = "lanes"):
        from ..bsi import device as bsi_dev

        self.mesh = _intern_mesh(mesh)
        self.row_axis, self.lane_axis = row_axis, lane_axis
        self.depth = bsi.bit_count()
        self.min_value, self.max_value = bsi.min_value, bsi.max_value
        self._ebm_card = bsi.ebm.cardinality
        keys = bsi.ebm.keys.copy()
        ebm_np = bsi_dev._densify(
            bsi.ebm if hasattr(bsi.ebm, "clone") else bsi.ebm.to_bitmap(),
            keys)
        slices_np = (np.stack([bsi_dev._densify(s, keys) for s in bsi.slices])
                     if bsi.slices else
                     np.zeros((0,) + ebm_np.shape, np.uint32))
        self.keys = keys
        self.ebm, self.slices = _shard_index_arrays(
            self.mesh, ebm_np, slices_np, self.depth, row_axis, lane_axis)

    def _bits(self, predicate: int) -> jnp.ndarray:
        from ..bsi.device import predicate_bits

        return predicate_bits(predicate, self.depth)

    def compare_cardinality(self, op, start_or_value: int,
                            end: int = 0) -> int:
        """Cardinality of the fused compare over the whole mesh (found set
        = ebm); min/max pruning + RANGE bound clamping match the host
        comparator."""
        from ..bsi.slice_index import Operation, minmax_decision

        decision = minmax_decision(op, start_or_value, end,
                                   self.min_value, self.max_value)
        if decision == "empty":
            return 0
        if decision == "all":
            return self._ebm_card
        from ..bsi.slice_index import clamp_range_bounds

        start_or_value, end = clamp_range_bounds(
            op, start_or_value, end, self.min_value, self.max_value)
        fn = _make_sharded_bsi_compare(self.mesh, op.value, self.row_axis,
                                       self.lane_axis)
        return int(np.asarray(fn(self.slices, self.ebm,
                                 self._bits(start_or_value),
                                 self._bits(end))))

    def sum(self) -> tuple[int, int]:
        """(sum of values, member count) — per-slice popcounts psum'd over
        the mesh, 2^i weighting in Python ints (no device overflow)."""
        fn = _make_sharded_bsi_slice_cards(self.mesh, self.row_axis,
                                           self.lane_axis)
        cards, count = fn(self.slices, self.ebm)
        total = sum((1 << i) * int(c)
                    for i, c in enumerate(np.asarray(cards)))
        return total, int(np.asarray(count))

    def top_k_cardinality(self, k: int) -> int:
        """Pre-trim cardinality of the Kaser top-K candidate set (>= k when
        the last slice ties; == DeviceBSI._topk_words' device cardinality).
        The tie trim needs value order and stays a host concern."""
        fn = _make_sharded_bsi_topk(self.mesh, self.row_axis, self.lane_axis)
        return int(np.asarray(fn(self.slices, self.ebm, jnp.int32(k))))


class ShardedRangeBitmap:
    """A core.rangebitmap.RangeBitmap sharded over a device mesh.

    Same layout as ShardedBSI (row ids data-parallel over the key axis,
    words tensor-parallel): a RangeBitmap IS a base-2 BSI over row ids with
    an implicit all-rows existence set, so the double-bound between scan
    and the threshold queries shard identically (VERDICT r3 missing #5's
    RangeBitmap half)."""

    def __init__(self, mesh: Mesh, rb, row_axis: str = "rows",
                 lane_axis: str = "lanes"):
        from ..bsi import device as bsi_dev
        from ..core.bitmap import RoaringBitmap
        from ..core.rangebitmap import RangeBitmap as HostRangeBitmap

        if not isinstance(rb, HostRangeBitmap):
            raise TypeError(
                f"ShardedRangeBitmap needs a core.rangebitmap.RangeBitmap, "
                f"got {type(rb).__name__}")
        self.mesh = _intern_mesh(mesh)
        self.row_axis, self.lane_axis = row_axis, lane_axis
        self.rows = rb.row_count
        self.max_value = rb.max_value
        self.depth = len(rb.slices)
        all_rows = RoaringBitmap.from_range(0, self.rows)
        keys = all_rows.keys.copy()
        ebm_np = bsi_dev._densify(all_rows, keys)
        slices_np = (np.stack([bsi_dev._densify(s, keys) for s in rb.slices])
                     if rb.slices else
                     np.zeros((0,) + ebm_np.shape, np.uint32))
        self.keys = keys
        self.ebm, self.slices = _shard_index_arrays(
            self.mesh, ebm_np, slices_np, self.depth, row_axis, lane_axis)

    def _bits(self, threshold: int) -> jnp.ndarray:
        from ..bsi.device import predicate_bits

        return predicate_bits(threshold, self.depth)

    def _query_cardinality(self, op: str, a: int, b: int = 0) -> int:
        fn = _make_sharded_range_compare(self.mesh, op, self.row_axis,
                                         self.lane_axis)
        return int(np.asarray(fn(self.slices, self.ebm,
                                 self._bits(a), self._bits(b))))

    def lte_cardinality(self, threshold: int) -> int:
        if threshold < 0:
            return 0
        if threshold >= self.max_value:
            return self.rows
        return self._query_cardinality("lte", threshold)

    def lt_cardinality(self, threshold: int) -> int:
        return self.lte_cardinality(threshold - 1)

    def gte_cardinality(self, threshold: int) -> int:
        if threshold <= 0:
            return self.rows
        if threshold > self.max_value:
            return 0
        return self._query_cardinality("gte", threshold)

    def gt_cardinality(self, threshold: int) -> int:
        return self.gte_cardinality(threshold + 1)

    def eq_cardinality(self, value: int) -> int:
        if value < 0 or value > self.max_value:
            return 0
        return self._query_cardinality("eq", value)

    def neq_cardinality(self, value: int) -> int:
        return self.rows - self.eq_cardinality(value)

    def between_cardinality(self, lo: int, hi: int) -> int:
        lo, hi = max(lo, 0), min(hi, self.max_value)
        if lo > hi:
            return 0
        if lo <= 0 and hi >= self.max_value:
            return self.rows
        return self._query_cardinality("between", lo, hi)
