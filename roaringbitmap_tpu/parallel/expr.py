"""Expression-DAG query compiler: fuse compositional set algebra into
one launch (ROADMAP item 4).

Every engine before this module executed FLAT single-op queries: a
``BatchQuery`` is one op over one operand subset, so a compositional
request like ``(A | B) & ~C`` paid one launch (plus gather, readback and
guard overhead) per logical node.  The reference never pays that tax —
its lazy ``Container`` ops and the ``FastAggregation`` horizontal chains
evaluate whole expressions without materializing intermediates
(PAPER.md L1/L3).  This module is the device analog: a small logical-
plan IR (an op DAG over set refs and ad-hoc bitmaps) plus a compiler
that lowers a whole expression into the engines' existing one-dispatch
batch programs, so intermediates live in registers/HBM scratch and are
never read back.

IR
--
Leaves: :func:`ref` (an index into the resident set) and :func:`bitmap`
(an ad-hoc host RoaringBitmap, shipped with the plan).  Ops:
:func:`or_`, :func:`and_`, :func:`xor`, :func:`andnot`, :func:`not_`.
An :class:`ExprQuery` wraps a root expression with a result ``form``
("cardinality" or "bitmap") and is accepted by ``BatchEngine``,
``MultiSetBatchEngine`` and ``ShardedBatchEngine`` pools anywhere a
``BatchQuery`` is.

Compilation pipeline (:func:`compile_query`):

1. **canonicalize + CSE** (:func:`canonicalize`): associative chains
   flatten into one wide node (``or(or(a,b),c) -> or(a,b,c)``),
   or/and operands dedupe (idempotent), xor operands cancel pairwise,
   commutative children sort into a canonical order, double negation
   drops, and ``and(x..., not(y)...)`` rewrites to
   ``andnot(and(x...), y...)`` — the only bounded home for a
   complement (a ``not_`` surviving canonicalization is an unbounded
   complement over the 2^32 universe and raises).  Canonical nodes are
   structurally hashable, so identical subtrees collapse to ONE DAG
   node — the CSE; shared nodes compile and execute once.
2. **reduce extraction**: every maximal all-leaf op node lowers to a
   pseudo ``BatchQuery`` that rides the engines' EXISTING machinery —
   ``_plan_query`` row selection, ``plan_bucket`` pow2 shape bucketing,
   the per-op superbucket merge, the mesh lowering — i.e. the wide
   segmented reduces stay the workhorse; the DAG only adds combine
   passes on top.  A node with 2+ leaf children and a non-leaf sibling
   splits its leaf run into a synthetic reduce so wide chains keep
   riding the segmented reduce rather than pairwise combines.
3. **fused combine steps**: interior nodes become elementwise bitwise
   passes over key-aligned ``u32[K, 2048]`` blocks inside the SAME
   compiled program (alignment gathers are plan-time host arrays; a
   child key absent from the node's key space contributes the identity).
   Key spaces: or/xor = union of child keys, and = intersection,
   andnot = the head's keys.
4. **short circuits**: a cardinality-only root never materializes its
   result image (the program outputs i32 per-key cards only — the words
   stay scratch); a node whose key space prunes empty (disjoint AND,
   all-cancelled XOR) is eliminated at plan time and, when the root
   itself prunes, the query never touches the device at all.  An
   ``andnot`` rest that prunes empty is dropped (``x & ~0 == x`` — the
   full-range complement of nothing).

Lowering rungs: the compiled sections lower two ways.  The multi-op
path (this module's ``eval_sections`` + the engines' ``bucket_body``)
runs gather -> segmented reduce -> combine passes as separate XLA ops;
the **megakernel rung** (``ops.megakernel``, the engine ladder's top:
megakernel -> pallas -> xla -> xla-vmap -> sequential) assembles the
same sections into ONE Pallas grid kernel whose reduce heads and
combine intermediates live in a VMEM scratch accumulator — the
intermediates never touch HBM, and per-dispatch transient bytes drop
to outputs-only (docs/EXPRESSIONS.md "Megakernel lowering").

Observability: each compilation emits an ``expr.compile`` span (nodes /
reduce_nodes / combine_nodes / depth / cse_saved tags); every device
dispatch carrying fused expressions bumps ``rb_expr_nodes_fused`` and
``rb_expr_launches_saved_total`` (the node-at-a-time evaluator would
have paid ~one launch per DAG op node; fused they share one), and
megakernel-rung dispatches add an ``expr.megakernel`` event.  See
docs/EXPRESSIONS.md.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops import dense, packing

WORDS32 = packing.WORDS32

#: ops the IR accepts; "not" only survives until canonicalization
OPS = ("or", "and", "xor", "andnot")


# ------------------------------------------------------------------- IR

class Expr:
    """Base marker for expression nodes (never instantiated directly)."""

    __slots__ = ()


@dataclasses.dataclass(frozen=True)
class Ref(Expr):
    """Leaf: index of a bitmap in the resident DeviceBitmapSet."""

    index: int


class AdHoc(Expr):
    """Leaf: an ad-hoc host bitmap (not resident) shipped with the plan.

    The input is SNAPSHOTTED (cloned) at leaf construction: cached plans
    pack the leaf's rows once, so aliasing a caller-mutable bitmap would
    make a plan-cache hit silently replay pre-mutation contents.  The
    snapshot makes the semantics deterministic instead — an AdHoc leaf
    always evaluates the bitmap as it was when the leaf was built.
    Identity equality (two leaves equal iff they share one snapshot)
    keeps structurally-equal but distinct bitmaps from colliding in
    cached plans.
    """

    __slots__ = ("bm",)

    def __init__(self, bm):
        if not hasattr(bm, "containers"):
            bm = bm.to_bitmap()     # buffer.ImmutableRoaringBitmap
        else:
            bm = bm.clone()
        object.__setattr__(self, "bm", bm)

    def __setattr__(self, *a):      # frozen, like the dataclass leaves
        raise AttributeError("AdHoc is immutable")

    def __eq__(self, o):
        return isinstance(o, AdHoc) and o.bm is self.bm

    def __hash__(self):
        return id(self.bm)

    def __repr__(self):
        return f"AdHoc(<bitmap {id(self.bm):#x}>)"


class Node(Expr):
    """Interior op node over child expressions.

    Structural equality/hash with per-node caching: a deeply SHARED dag
    (CSE's whole point) has exponential tree size, so recomputing
    hashes or sort keys per visit would make planning exponential in
    depth — the caches plus canonicalization's interning (equal
    canonical subtrees unify to one object, letting tuple equality
    short-circuit on identity) keep every walk O(dag)."""

    __slots__ = ("op", "children", "_hash", "_skey_c")

    def __init__(self, op: str, children: tuple):
        self.op = op
        self.children = tuple(children)
        self._hash = None
        self._skey_c = None

    def __eq__(self, o):
        if self is o:
            return True
        return (isinstance(o, Node) and self.op == o.op
                and self.children == o.children)

    def __hash__(self):
        h = self._hash
        if h is None:
            h = self._hash = hash((self.op, self.children))
        return h

    def __repr__(self):
        return f"Node({self.op!r}, {self.children!r})"


#: the canonical empty result (e.g. a fully-cancelled xor)
EMPTY = Node("empty", ())


#: predicate ops a value leaf accepts (analytics lane, docs/ANALYTICS.md)
VALUE_OPS = ("eq", "neq", "lt", "le", "gt", "ge", "range")


@dataclasses.dataclass(frozen=True)
class ValuePred(Expr):
    """Leaf: a value-domain predicate over an attached column — the
    rows whose column value satisfies ``op`` against ``lo`` (and ``hi``
    for ``range``).  Evaluates over the column's existence plane and
    lowers to ONE slice-plane scan step inside the same compiled
    program (analytics.plane), so it composes with or/and/xor/andnot
    like any bitmap leaf: ``count((A | B) & range_("price", lo, hi))``
    is one launch."""

    col: str
    op: str
    lo: int
    hi: int = 0


@dataclasses.dataclass(frozen=True)
class Agg(Expr):
    """Aggregate ROOT over a column: ``sum`` (total + member count of
    the found set's stored values) or ``topk`` (the rows holding the k
    largest values).  ``found`` is any bitmap-valued DAG node (None =
    the column's whole stored domain); aggregates cannot nest inside
    an expression — canonicalization raises."""

    kind: str
    col: str
    k: int
    found: object = None


def range_(col, lo: int, hi: int) -> ValuePred:
    """Rows with ``lo <= value(col) <= hi`` — the BETWEEN predicate."""
    return ValuePred(str(col), "range", int(lo), int(hi))


def cmp(col, op: str, value: int) -> ValuePred:
    """Rows with ``value(col) <op> value``; op in eq/neq/lt/le/gt/ge."""
    op = str(op).lower()
    if op not in ("eq", "neq", "lt", "le", "gt", "ge"):
        raise ValueError(f"unsupported value predicate op {op!r} "
                         f"(range predicates spell range_(col, lo, hi))")
    return ValuePred(str(col), op, int(value))


def sum_(col, found=None) -> Agg:
    """Aggregate root: (sum of column values over the found set,
    member count).  ``found`` is any bitmap-valued expression."""
    return Agg("sum", str(col),
               0, None if found is None else _as_expr(found))


def top_k(col, k: int, found=None) -> Agg:
    """Aggregate root: the rows holding the k largest column values
    within the found set (k clamped to the found set's stored rows;
    ties trimmed by dropping the smallest row ids, the Kaser rule)."""
    if int(k) < 0:
        raise ValueError(f"top_k needs k >= 0, got {k}")
    return Agg("topk", str(col),
               int(k), None if found is None else _as_expr(found))


def _as_expr(x) -> Expr:
    if isinstance(x, Expr):
        return x
    if isinstance(x, (int, np.integer)):
        return Ref(int(x))
    raise TypeError(
        f"expression operand must be an Expr or a resident index, got "
        f"{type(x).__name__}")


def ref(i: int) -> Ref:
    return Ref(int(i))


def bitmap(bm) -> AdHoc:
    """Ad-hoc leaf over a host bitmap not resident in the set."""
    return AdHoc(bm)


def or_(*xs) -> Expr:
    return Node("or", tuple(_as_expr(x) for x in xs))


def and_(*xs) -> Expr:
    return Node("and", tuple(_as_expr(x) for x in xs))


def xor(*xs) -> Expr:
    return Node("xor", tuple(_as_expr(x) for x in xs))


def andnot(head, *rest) -> Expr:
    """head minus the union of ``rest`` (the BatchQuery andnot shape)."""
    return Node("andnot", (_as_expr(head),)
                + tuple(_as_expr(x) for x in rest))


def not_(x) -> Expr:
    """Complement — bounded only inside an ``and_`` (where it rewrites
    to ``andnot``); anywhere else canonicalization raises."""
    return Node("not", (_as_expr(x),))


@dataclasses.dataclass(frozen=True)
class ExprQuery:
    """One compositional request against a resident set — the DAG
    generalization of :class:`~.batch_engine.BatchQuery`.  Accepted by
    every engine's ``execute`` next to flat queries; a single-node
    expression IS a flat query (it lowers to the identical plan)."""

    expr: Expr
    form: str = "cardinality"

    def __post_init__(self):
        if not isinstance(self.expr, Expr):
            object.__setattr__(self, "expr", _as_expr(self.expr))
        if self.form not in ("cardinality", "bitmap"):
            raise ValueError(f"unsupported result form {self.form!r}")
        if isinstance(self.expr, Agg) and self.expr.kind == "sum" \
                and self.form == "bitmap":
            raise ValueError(
                "sum_ roots have no bitmap form (the result is a "
                "scalar total + count)")


# --------------------------------------------------- canonicalize + CSE

_ASSOC = ("or", "and", "xor")


def _skey(e: Expr):
    """Deterministic structural sort key for commutative child ordering
    (AdHoc keys by object identity — stable within a process, which is
    all a plan cache needs).  Cached per Node so shared-dag sorting
    stays O(dag)."""
    if isinstance(e, Ref):
        return (0, e.index)
    if isinstance(e, AdHoc):
        return (1, id(e.bm))
    if isinstance(e, ValuePred):
        return (3, e.col, e.op, e.lo, e.hi)
    k = e._skey_c
    if k is None:
        k = e._skey_c = (2, e.op, tuple(_skey(c) for c in e.children))
    return k


def canonicalize(e) -> Expr:
    """Canonical DAG form: flattened associative chains, deduped/sorted
    commutative operands, pairwise-cancelled xor, ``not`` absorbed into
    ``andnot`` (or rejected as unbounded), structural sharing for CSE.
    Aggregate roots (``sum_`` / ``top_k``) canonicalize their found
    sub-DAG and stay at the root — anywhere else they raise.
    Raises ValueError on an unbounded complement or an empty ``and``."""
    e = _as_expr(e)
    if isinstance(e, Agg):
        f = e.found
        if f is None:
            return e
        f_c = _canon(_as_expr(f), {}, {})
        if isinstance(f_c, Node) and f_c.op == "not":
            raise ValueError(
                "unbounded complement: an aggregate's found set is a "
                "bare not_ (complements are bounded only inside and_)")
        return Agg(e.kind, e.col, e.k, f_c)
    out = _canon(e, {}, {})
    if isinstance(out, Node) and out.op == "not":
        raise ValueError(
            "unbounded complement: a bare not_ root spans the whole "
            "2^32 universe (complements are bounded only inside and_)")
    return out


def is_agg(e) -> bool:
    """True when ``e`` is an aggregate-rooted expression (pre- or
    post-canonicalization — Agg only ever lives at the root)."""
    return isinstance(e, Agg)


def _canon(e: Expr, memo: dict, intern: dict) -> Expr:
    got = memo.get(e)
    if got is not None:
        return got
    out = _canon_uncached(e, memo, intern)
    # intern the canonical node: structurally-equal results from
    # different input branches unify to ONE object, so later equality
    # checks short-circuit on identity and every walk stays O(dag)
    out = intern.setdefault(out, out)
    memo[e] = out
    return out


def _canon_uncached(e: Expr, memo: dict, intern: dict) -> Expr:
    if isinstance(e, (Ref, AdHoc, ValuePred)):
        return e
    if isinstance(e, Agg):
        raise ValueError(
            "aggregate roots (sum_/top_k) cannot nest inside an "
            "expression — they consume a bitmap-valued found set and "
            "produce a scalar/top-k result, not a combinable bitmap")
    if e.op == "empty":
        return EMPTY
    if e.op == "not":
        c = _canon(e.children[0], memo, intern)
        if isinstance(c, Node) and c.op == "not":
            return c.children[0]            # double negation
        return Node("not", (c,))
    if e.op == "andnot":
        if not e.children:
            return EMPTY
        head = _canon(e.children[0], memo, intern)
        rest: list = []
        for r in e.children[1:]:
            r = _canon(r, memo, intern)
            if isinstance(r, Node) and r.op == "empty":
                continue                    # x & ~0 == x
            if isinstance(r, Node) and r.op == "or":
                rest.extend(r.children)     # ~(a|b|c): rests ARE a union
            else:
                rest.append(r)
        if isinstance(head, Node):
            if head.op == "empty":
                return EMPTY
            if head.op == "not":
                raise ValueError(
                    "unbounded complement: andnot head is a not_ node "
                    "(complements are bounded only inside and_)")
            if head.op == "andnot":
                # andnot(andnot(h, s...), r...) == andnot(h, s..., r...)
                rest = list(head.children[1:]) + rest
                head = head.children[0]
        if any(isinstance(r, Node) and r.op == "not" for r in rest):
            raise ValueError(
                "unbounded complement: not_ inside an andnot rest")
        seen, uniq = set(), []
        for r in sorted(rest, key=_skey):
            if r not in seen:
                seen.add(r)
                uniq.append(r)
        if head in seen:
            return EMPTY                    # h & ~(h | ...) == 0
        if not uniq:
            return head
        return Node("andnot", (head, *uniq))
    if e.op in _ASSOC:
        flat: list = []
        for c in e.children:
            c = _canon(c, memo, intern)
            if isinstance(c, Node) and c.op == e.op:
                flat.extend(c.children)     # associative flatten
            else:
                flat.append(c)
        if e.op == "and":
            if any(isinstance(c, Node) and c.op == "empty" for c in flat):
                return EMPTY
            neg = [c for c in flat
                   if isinstance(c, Node) and c.op == "not"]
            pos = [c for c in flat if c not in neg]
            if neg:
                if not pos:
                    raise ValueError(
                        "unbounded complement: and_ of only not_ nodes")
                base = _canon(Node("and", tuple(pos)), memo, intern)
                return _canon(
                    Node("andnot",
                         (base, *(n.children[0] for n in neg))), memo,
                    intern)
        else:
            flat = [c for c in flat
                    if not (isinstance(c, Node) and c.op == "empty")]
        if any(isinstance(c, Node) and c.op == "not" for c in flat):
            raise ValueError(
                f"unbounded complement: not_ under {e.op}_ (complements "
                "are bounded only inside and_)")
        flat.sort(key=_skey)
        if e.op == "xor":
            uniq: list = []                 # pairwise cancellation
            for c in flat:
                if uniq and uniq[-1] == c:
                    uniq.pop()
                else:
                    uniq.append(c)
        else:
            uniq = []
            for c in flat:                  # idempotent dedupe
                if not uniq or uniq[-1] != c:
                    uniq.append(c)
        if not uniq:
            if e.op == "and":
                raise ValueError("and_ needs at least one operand")
            return EMPTY
        if len(uniq) == 1:
            return uniq[0]
        return Node(e.op, tuple(uniq))
    raise ValueError(f"unknown expression op {e.op!r}")


def dag_stats(e: Expr) -> dict:
    """Canonical-DAG shape report: unique op-node count, depth, and the
    CSE saving (tree op nodes minus DAG op nodes)."""
    return _dag_stats_canonical(canonicalize(e))


def _dag_stats_canonical(e: Expr) -> dict:
    """`dag_stats` over an ALREADY-canonical node.  Memoized per node:
    the tree-node count of a shared dag is exponential in depth by
    construction (that is cse_saved's whole story), so it is computed
    as per-node sums in O(dag), never by walking the tree."""
    uniq: set = set()
    info: dict = {}          # node -> (tree_nodes, depth)

    def walk(n):
        if not isinstance(n, Node) or n.op == "empty":
            return 0, 0
        got = info.get(n)
        if got is not None:
            return got
        uniq.add(n)
        t, d = 1, 1
        for c in n.children:
            ct, cd = walk(c)
            t += ct
            d = max(d, cd + 1)
        info[n] = (t, d)
        return t, d

    tree_nodes, depth = walk(e)
    return {"nodes": len(uniq), "tree_nodes": tree_nodes,
            "cse_saved": tree_nodes - len(uniq), "depth": depth}


def host_op_count(e: Expr) -> int:
    """Pairwise host container ops a sequential evaluation pays — the
    expression analog of ``len(operands) - 1`` in the explain floor."""
    try:
        return _host_op_count_canonical(canonicalize(e))
    except ValueError:
        return 0


def _host_op_count_canonical(e: Expr) -> int:
    total = 0
    for n in _dag_nodes(e):
        if isinstance(n, Node) and n.op != "empty":
            total += max(0, len(n.children) - 1)
    return total


def _dag_nodes(e: Expr) -> list:
    """Unique nodes of the canonical DAG in topological (children-first)
    order."""
    seen: dict = {}
    order: list = []

    def walk(n):
        if n in seen:
            return
        seen[n] = True
        if isinstance(n, Node):
            for c in n.children:
                walk(c)
        order.append(n)

    walk(e)
    return order


# ------------------------------------------------- host reference rung

def _host_column(columns, name: str):
    """Resolve a column by name for the host evaluator / oracle rung."""
    col = (columns or {}).get(name)
    if col is None:
        raise KeyError(
            f"no column {name!r} attached to the resident set "
            f"(DeviceBitmapSet.attach_column)")
    return col


def evaluate_host(e, sources, columns=None) -> object:
    """Bit-exact host-side evaluation of an expression over ``sources``
    (a list of host RoaringBitmaps) — the sequential reference rung every
    fused engine path is pinned against, and the guard ladder's floor.
    ``columns`` maps column names to attached analytics columns (the
    host BSI/RangeBitmap oracles backing value-predicate leaves)."""
    from ..core.bitmap import RoaringBitmap

    e = canonicalize(e)
    if isinstance(e, Agg):
        raise ValueError(
            "aggregate roots evaluate through evaluate_host_agg (the "
            "result is (cardinality, value, bitmap), not a bitmap)")
    memo: dict = {}

    def ev(n):
        got = memo.get(n)
        if got is not None:
            return got
        if isinstance(n, Ref):
            if n.index < 0 or n.index >= len(sources):
                raise IndexError(
                    f"expression ref out of range 0..{len(sources) - 1}: "
                    f"{n.index}")
            v = sources[n.index]
        elif isinstance(n, AdHoc):
            v = n.bm
        elif isinstance(n, ValuePred):
            v = _host_column(columns, n.col).host_filter(n.op, n.lo,
                                                         n.hi)
        elif n.op == "empty":
            v = RoaringBitmap()
        elif n.op == "andnot":
            v = ev(n.children[0]).clone()
            for r in n.children[1:]:
                v = v - ev(r)
        else:
            import operator

            fn = {"or": operator.or_, "and": operator.and_,
                  "xor": operator.xor}[n.op]
            parts = [ev(c) for c in n.children]
            v = parts[0]
            for p in parts[1:]:
                v = fn(v, p)
        memo[n] = v
        return v

    out = ev(e)
    if isinstance(e, (Ref, AdHoc)):
        # a bare-leaf root must not alias the caller's resident source
        return out.clone()
    return out


def evaluate_host_agg(e, sources, columns=None):
    """Host-oracle evaluation of an aggregate-rooted expression ->
    ``(cardinality, value, bitmap | None)``: ``sum`` returns (found
    count, value total, None) via the host BSI's weighted contraction;
    ``topk`` returns (k_eff, None, rows bitmap) via the Kaser scan over
    the found set's stored rows (k clamped, smallest-id tie trim)."""
    e = canonicalize(e)
    if not isinstance(e, Agg):
        raise ValueError("evaluate_host_agg needs an aggregate root")
    col = _host_column(columns, e.col)
    found = (None if e.found is None
             else evaluate_host(e.found, sources, columns))
    if e.kind == "sum":
        total, count = col.host_sum(found)
        return int(count), int(total), None
    bm = col.host_top_k(e.k, found)
    return bm.cardinality, None, bm


# ----------------------------------------------------- compiled section

@dataclasses.dataclass
class ExprSection:
    """One compiled expression of a batch plan.

    ``kind``: "fused" (combine steps run in-program), "flat" (the root
    lowered to a bare pseudo-query — the single-node case), "empty"
    (root pruned at plan time; never touches the device) or "adhoc"
    (the root is an ad-hoc bitmap; resolved on the host).

    Steps (fused sections), each a static-shaped tuple:
      ("leaf", K)                  value = image[host[g{i}]]        u32[K, W]
      ("adhoc", K)                 value = host[w{i}]               u32[K, W]
      ("reduce", bi, slot, kq)     value = bucket_heads[bi][slot, :kq]
      ("combine", op, children, K) children = ((step, aligned), ...);
                                   non-aligned children gather through
                                   host[i{i}_{k}] masked by host[o{i}_{k}]
    """

    qid: int
    form: str
    kind: str
    steps: list = dataclasses.field(default_factory=list)
    root: int = -1
    root_keys: np.ndarray = None
    host: dict | None = None
    arrays: dict | None = None
    adhoc_bm: object = None
    n_nodes: int = 0
    n_reduce: int = 0
    n_combine: int = 0
    depth: int = 0
    cse_saved: int = 0
    host_ops: int = 0
    #: subtrees served from the materialized result cache at plan time
    #: (mutation.result_cache) — each pruned a reduce/combine lowering
    #: into a pre-computed operand (the "adhoc" step shape)
    n_cached: int = 0
    #: analytics columns this section's vscan/vagg steps read, in step
    #: slot order — resolved Column objects; their (slices, ebm) device
    #: twins ride the program's separate NON-donated cols operand
    cols: list = dataclasses.field(default_factory=list)
    #: (kind, k) of an aggregate-rooted section (sum_/top_k), else None
    agg: tuple | None = None

    @property
    def signature(self):
        return (self.kind, self.form == "bitmap",
                tuple(tuple(s) for s in self.steps), self.root,
                0 if self.root_keys is None else int(self.root_keys.size))

    def device_arrays(self, fresh: bool = False) -> dict:
        if fresh:
            if self.host is None:
                raise RuntimeError(
                    "fresh=True needs the host operand dict, which this "
                    "plan dropped after its cached upload")
            return {k: jnp.asarray(v) for k, v in self.host.items()}
        if self.arrays is None:
            self.arrays = {k: jnp.asarray(v) for k, v in self.host.items()}
        return self.arrays


def _pack_adhoc(bm) -> tuple:
    """Host bitmap -> (u16 keys, u32[K, 2048] dense rows) for plan-time
    shipping of an ad-hoc leaf."""
    keys = packing._keys_of(bm)
    if keys.size == 0:
        return keys, np.zeros((0, WORDS32), np.uint32)
    words = np.stack([packing.container_words_u32(c)
                      for c in bm.containers])
    return keys, words.astype(np.uint32)


def _is_reduce(n: Expr) -> bool:
    return (isinstance(n, Node) and n.op in OPS
            and all(isinstance(c, Ref) for c in n.children))


def compile_query(q: ExprQuery, qid: int, plan_reduce,
                  plan_leaf, cache_probe=None,
                  col_resolve=None) -> ExprSection:
    """Compile one :class:`ExprQuery` against an engine's planner.

    ``plan_reduce(batch_query, owner)`` registers a pseudo flat query
    into the engine's bucketing machinery and returns ``(pid, keys)`` —
    ``owner`` is the original query id when the pseudo IS the root (the
    flat case, read back straight from its bucket) and None for
    internal reduce nodes (consumed in-program, never read back).
    ``plan_leaf(index)`` returns ``(gather_rows, keys)`` for a resident
    leaf, rows in whatever row space the caller's image gather uses.
    ``cache_probe(node)``, when given, returns ``(keys, words)`` of a
    materialized cached result for a canonical interior node (the
    mutation result cache) — the node then lowers as a pre-computed
    operand (the "adhoc" step shape) and its reduce/combine lowering is
    pruned from the program entirely.  ``col_resolve(name)`` resolves an
    attached analytics column (docs/ANALYTICS.md): value-predicate
    leaves lower to in-program slice-plane scan steps over it, and
    aggregate roots (``sum_`` / ``top_k``) append one ``vagg`` step over
    their found sub-DAG.
    """
    from .batch_engine import BatchQuery

    # ONE canonicalization per compile: stats/host-op walks take the
    # already-canonical (interned) dag.  Aggregate roots split into the
    # agg head and the found-set core the normal machinery lowers.
    e = canonicalize(q.expr)
    agg = e if isinstance(e, Agg) else None
    core = e.found if agg is not None else e
    stats = (_dag_stats_canonical(core) if core is not None
             else {"nodes": 0, "tree_nodes": 0, "cse_saved": 0,
                   "depth": 0})
    with obs_trace.span("expr.compile", qid=qid, form=q.form,
                        nodes=stats["nodes"],
                        depth=stats["depth"],
                        cse_saved=stats["cse_saved"]) as sp:
        sec = ExprSection(qid=qid, form=q.form, kind="fused",
                          n_nodes=max(1, stats["nodes"]
                                      + (1 if agg is not None else 0)),
                          depth=stats["depth"],
                          cse_saved=stats["cse_saved"],
                          host_ops=(_host_op_count_canonical(core)
                                    if core is not None else 0))
        if agg is not None:
            sec.agg = (agg.kind, agg.k)
        if agg is None and isinstance(e, Node) and e.op == "empty":
            sec.kind = "empty"
            sp.tag(kind=sec.kind)
            return sec
        if agg is None and isinstance(e, AdHoc):
            sec.kind, sec.adhoc_bm = "adhoc", e.bm
            sp.tag(kind=sec.kind)
            return sec
        if agg is None and isinstance(e, Ref):
            plan_reduce(BatchQuery("or", (e.index,), form=q.form), qid)
            sec.kind, sec.n_reduce = "flat", 1
            sp.tag(kind=sec.kind)
            return sec
        if agg is None and _is_reduce(e):
            # flat root — but prune an empty key space first (disjoint
            # AND, all-empty operands): the empty short circuit applies
            # one level down too, and skips the device entirely
            leaf_keys = [plan_leaf(c.index)[1] for c in e.children]
            if e.op == "and":
                inter = leaf_keys[0]
                for k in leaf_keys[1:]:
                    inter = np.intersect1d(inter, k, assume_unique=True)
                dead = inter.size == 0
            elif e.op == "andnot":
                dead = leaf_keys[0].size == 0
            else:
                dead = all(k.size == 0 for k in leaf_keys)
            if dead:
                sec.kind = "empty"
                sp.tag(kind=sec.kind)
                return sec
            # child order already matches BatchQuery semantics (andnot
            # keeps its head first through canonicalization)
            ops = tuple(c.index for c in e.children)
            plan_reduce(BatchQuery(e.op, ops, form=q.form), qid)
            sec.kind, sec.n_reduce = "flat", 1
            sp.tag(kind=sec.kind)
            return sec

        steps: list = []
        host: dict = {}
        keyof: dict = {}          # step idx -> np u16 key array
        memo: dict = {}           # canonical node -> step idx | None

        def emit_leaf_run(refs: list) -> int | None:
            """2+ sibling leaves of a combine: lower the run as a
            synthetic OR reduce so it rides the wide segmented reduce."""
            # internal pseudos stay cardinality-form: their heads are
            # consumed IN-PROGRAM (the run fn forces head computation
            # for expr-feeding buckets) and must never become program
            # outputs — that readback is what fusion deletes
            bq = BatchQuery("or", tuple(r.index for r in refs),
                            form="cardinality")
            pid, keys = plan_reduce(bq, None)
            if keys.size == 0:
                return None
            sec.n_reduce += 1
            si = len(steps)
            steps.append(("reduce", pid, 0, int(keys.size)))
            keyof[si] = keys
            return si

        def resolve_col(name: str):
            if col_resolve is None:
                raise ValueError(
                    f"value predicate over column {name!r} but this "
                    f"engine path has no column resolver (attach "
                    f"columns via DeviceBitmapSet.attach_column)")
            return col_resolve(name)

        def col_slot(col) -> int:
            for i, c in enumerate(sec.cols):
                if c is col:
                    return i
            sec.cols.append(col)
            return len(sec.cols) - 1

        def emit_scan(col, scan) -> int | None:
            """One value-predicate step: the column's plan-time lowering
            (min/max pruning shared with the host comparator) becomes
            either nothing ("empty"), the existence plane ("all"), or a
            slice-plane scan whose predicate BITS ride as operands —
            the compiled program is shared across predicate values."""
            if scan[0] == "empty":
                return None
            si = len(steps)
            ci = col_slot(col)
            if scan[0] == "all":
                steps.append(("vscan", ci, "col:all", col.depth_pad,
                              int(col.keys.size)))
            else:
                _, tag, bits, bits2 = scan
                steps.append(("vscan", ci, tag, col.depth_pad,
                              int(col.keys.size)))
                host[f"b{si}"] = np.asarray(bits, np.int32)
                host[f"b2{si}"] = np.asarray(bits2, np.int32)
            keyof[si] = col.keys
            return si

        def emit_agg(col, found_si: int) -> int:
            """The aggregate head over the found step: align the found
            set onto the column's key space (plan-time searchsorted,
            the combine-alignment discipline) and append ONE vagg step
            — sum's weighted-popcount contraction or topk's Kaser scan
            (k rides as a traced operand so one program serves all k)."""
            si = len(steps)
            ci = col_slot(col)
            fk, ck = keyof[found_si], col.keys
            aligned = (fk.size == ck.size
                       and bool(np.array_equal(fk, ck)))
            if not aligned:
                idx = np.searchsorted(fk, ck).clip(
                    0, max(0, fk.size - 1)).astype(np.int32)
                host[f"i{si}"] = idx
                host[f"o{si}"] = (fk[idx] == ck) if fk.size else \
                    np.zeros(ck.size, bool)
            if agg.kind == "topk":
                host[f"k{si}"] = np.asarray(agg.k, np.int32)
            steps.append(("vagg", agg.kind, found_si, aligned, ci,
                          col.depth_pad, int(ck.size)))
            keyof[si] = ck
            return si

        def emit(n) -> int | None:
            if n in memo:
                return memo[n]
            si = emit_cached(n)
            if si is _MISS:
                si = _emit(n)
            memo[n] = si
            return si

        _MISS = object()

        def emit_cached(n):
            """Cached-subtree injection (mutation.result_cache): a
            canonical interior node with materialized cached rows
            lowers as a pre-computed operand step — served, not
            planned.  Returns ``_MISS`` when the cache has nothing."""
            if cache_probe is None or not isinstance(n, Node) \
                    or n.op == "empty":
                return _MISS
            hit = cache_probe(n)
            if hit is None:
                return _MISS
            keys_c, words_c = hit
            sec.n_cached += 1
            if keys_c.size == 0:
                # a cached-empty result prunes like any empty operand;
                # _combine's op-specific identity rules apply unchanged
                return None
            si = len(steps)
            steps.append(("adhoc", int(keys_c.size)))
            host[f"w{si}"] = words_c
            keyof[si] = keys_c
            return si

        def _emit(n) -> int | None:
            if isinstance(n, ValuePred):
                col = resolve_col(n.col)
                return emit_scan(col, col.scan_plan(n.op, n.lo, n.hi))
            if isinstance(n, Ref):
                rows, keys = plan_leaf(n.index)
                if keys.size == 0:
                    return None
                si = len(steps)
                steps.append(("leaf", int(keys.size)))
                host[f"g{si}"] = np.asarray(rows, np.int32)
                keyof[si] = keys
                return si
            if isinstance(n, AdHoc):
                keys, words = _pack_adhoc(n.bm)
                if keys.size == 0:
                    return None
                si = len(steps)
                steps.append(("adhoc", int(keys.size)))
                host[f"w{si}"] = words
                keyof[si] = keys
                return si
            if n.op == "empty":
                return None
            if _is_reduce(n):
                ops = tuple(c.index for c in n.children)
                pid, keys = plan_reduce(
                    BatchQuery(n.op, ops, form="cardinality"), None)
                if keys.size == 0:
                    return None
                sec.n_reduce += 1
                si = len(steps)
                steps.append(("reduce", pid, 0, int(keys.size)))
                keyof[si] = keys
                return si
            # interior combine node.  Group sibling leaf runs of
            # or/and/xor into synthetic reduces (>= 2 refs)
            children = list(n.children)
            if n.op in _ASSOC:
                refs = [c for c in children if isinstance(c, Ref)]
                if len(refs) >= 2 and len(refs) < len(children):
                    if n.op == "or":
                        rest = [c for c in children
                                if not isinstance(c, Ref)]
                        run = emit_leaf_run(refs)
                        cis = [run] + [emit(c) for c in rest]
                        return _combine("or", cis)
                    # and/xor leaf runs stay native reduce nodes of
                    # their own op
                    rest = [c for c in children if not isinstance(c, Ref)]
                    sub = Node(n.op, tuple(refs))
                    cis = [emit(sub)] + [emit(c) for c in rest]
                    return _combine(n.op, cis)
            if n.op == "andnot":
                head_ci = emit(children[0])
                rest_cis = [emit(c) for c in children[1:]]
                return _combine("andnot", [head_ci] + rest_cis)
            cis = [emit(c) for c in children]
            return _combine(n.op, cis)

        def _combine(op: str, cis: list) -> int | None:
            if op == "andnot":
                head = cis[0]
                if head is None:
                    return None             # 0 & ~x == 0
                rest = [c for c in cis[1:] if c is not None]
                if not rest:
                    return head             # x & ~0 == x
                cis = [head] + rest
                node_keys = keyof[head]
            elif op == "and":
                if any(c is None for c in cis):
                    return None             # empty annihilates
                node_keys = keyof[cis[0]]
                for c in cis[1:]:
                    node_keys = np.intersect1d(node_keys, keyof[c],
                                               assume_unique=True)
                if node_keys.size == 0:
                    return None             # disjoint key spaces
            else:                           # or / xor
                cis = [c for c in cis if c is not None]
                if not cis:
                    return None
                if len(cis) == 1:
                    return cis[0]
                node_keys = keyof[cis[0]]
                for c in cis[1:]:
                    node_keys = np.union1d(node_keys, keyof[c])
            node_keys = node_keys.astype(np.uint16)
            sec.n_combine += 1
            si = len(steps)
            spec = []
            for k, ci in enumerate(cis):
                ck = keyof[ci]
                aligned = (ck.size == node_keys.size
                           and bool(np.array_equal(ck, node_keys)))
                if not aligned:
                    idx = np.searchsorted(ck, node_keys).clip(
                        0, max(0, ck.size - 1)).astype(np.int32)
                    ok = ck[idx] == node_keys
                    host[f"i{si}_{k}"] = idx
                    host[f"o{si}_{k}"] = ok
                spec.append((ci, aligned))
            steps.append(("combine", op, tuple(spec),
                          int(node_keys.size)))
            keyof[si] = node_keys
            return si

        if agg is not None:
            agg_col = resolve_col(agg.col)
            if core is None:
                # found=None: the column's whole stored domain — the
                # existence plane as the found step
                found_si = emit_scan(agg_col, ("all",)
                                     if agg_col.keys.size else ("empty",))
            else:
                found_si = emit(core)
            if found_si is None:
                sec.kind = "empty"
                sp.tag(kind=sec.kind, agg=agg.kind)
                return sec
            root = emit_agg(agg_col, found_si)
        else:
            root = emit(e)
        if root is None:
            sec.kind = "empty"
            sp.tag(kind=sec.kind)
            return sec
        sec.steps, sec.root = steps, root
        sec.root_keys = keyof[root]
        sec.host = host
        n_value = sum(1 for st in steps
                      if st[0] in ("vscan", "vagg"))
        sp.tag(kind=sec.kind, reduce_nodes=sec.n_reduce,
               combine_nodes=sec.n_combine, steps=len(steps),
               root_keys=int(sec.root_keys.size),
               cached_nodes=sec.n_cached, depth=sec.depth)
        if n_value:
            sp.tag(value_steps=n_value,
                   bsi_depth=value_depth_of([sec]),
                   agg=(agg.kind if agg is not None else None))
        return sec


def fused_of(sections) -> list:
    """The sections whose combine steps run in-program — THE filter
    every plan's ``fused`` property delegates to (one definition of the
    contract across the three engines)."""
    return [s for s in sections if s.kind == "fused"]


def has_value_steps(sections) -> bool:
    """True when any fused section carries analytics steps (vscan /
    vagg).  Since Megakernel v2 these assemble into the one-kernel
    rung like every other step (VSCAN/VAGG opcodes over the column
    operand bank — ops.megakernel), so this is no longer a demotion
    gate: it only decides whether column operands must ship with the
    launch (docs/EXPRESSIONS.md "Megakernel v2")."""
    return any(st[0] in ("vscan", "vagg")
               for s in sections if s.kind == "fused" for st in s.steps)


def value_depth_of(sections) -> int:
    """Max padded slice depth across the sections' analytics steps —
    the ``bsi`` dimension of the lattice snap (0 = no analytics)."""
    depth = 0
    for s in sections:
        if s.kind != "fused":
            continue
        for st in s.steps:
            if st[0] == "vscan":
                depth = max(depth, int(st[3]))
            elif st[0] == "vagg":
                depth = max(depth, int(st[5]))
    return depth


def launch_cols(fused_sections) -> list:
    """Per-section column device operands — the engines' separate
    NON-donated program argument (a donated cols operand would destroy
    the resident planes with the launch)."""
    return [[c.device_operands() for c in s.cols]
            for s in fused_sections]


def signature_of(sections) -> tuple:
    """The expression half of a plan/program cache signature."""
    return tuple(s.signature for s in sections)


def finalize_sections(sections, buckets) -> None:
    """Resolve reduce steps' pseudo-query ids to their bucket slots,
    after ``plan_bucket`` assigned them (bucket ``qids`` carry the
    pids)."""
    loc = {pid: (bi, slot, b.keys[slot].size)
           for bi, b in enumerate(buckets)
           for slot, pid in enumerate(b.qids)}
    for sec in sections:
        if sec.kind != "fused":
            continue
        for si, st in enumerate(sec.steps):
            if st[0] == "reduce":
                bi, slot, kq = loc[st[1]]
                sec.steps[si] = ("reduce", bi, slot, kq)


# -------------------------------------------------------- traced eval

def expr_bucket_ids(sections) -> frozenset:
    """Bucket indices whose heads fused combine steps consume — the run
    fn forces head COMPUTATION for these (traced, in-program) without
    widening the program's OUTPUTS (the bucket's own ``needs_words``
    keeps meaning "some real bitmap-form query reads these back")."""
    return frozenset(
        st[1] for sec in sections if sec.kind == "fused"
        for st in sec.steps if st[0] == "reduce")


def traced_bucket_heads(buckets, op_groups, group_outs,
                        live_ok: bool) -> list:
    """Slice per-op superbucket flat head tensors back into per-bucket
    ``[q, k_pad, W]`` blocks INSIDE the traced program — the traced twin
    of ``MultiSetBatchEngine._bucket_outputs`` — so fused combine steps
    can read reduce-node values without a readback.  ``live_ok`` mirrors
    the engines' regular-fast-path layout rule (live one-slot-per-query
    outputs on non-pallas rungs)."""
    out: list = [None] * len(buckets)
    for grp, (heads_f, _cards) in zip(op_groups, group_outs):
        if heads_f is None:
            continue
        live = live_ok and grp.regular
        for bi, s0 in zip(grp.bucket_idx, grp.seg_offs):
            b = buckets[bi]
            if live:
                s0l = s0 // 2
                out[bi] = heads_f[s0l:s0l + b.q].reshape(b.q, 1, WORDS32)
            else:
                n = b.q * (b.k_pad + 1)
                out[bi] = heads_f[s0:s0 + n].reshape(
                    b.q, b.k_pad + 1, WORDS32)[:, :b.k_pad]
    return out


def eval_section(sec: ExprSection, arrs: dict, words, bucket_heads,
                 cols=()):
    """Traced fused evaluation of one section: walk the compiled steps
    bottom-up, keeping every intermediate a traced value (registers /
    HBM scratch — never read back).  Returns ``(heads_or_None, cards)``
    with heads ``u32[K_root, W]`` only for bitmap-form roots (the
    cardinality short circuit: the popcount is the only root output).
    ``cols`` holds the section's column ``(slices, ebm)`` operands in
    slot order; an aggregate root returns its own output pair — sum:
    ``(i32[S, K] per-(slice, key) cards, i32[K_found] found cards)``,
    topk: ``(u32[K, W] result words, i32[K] cards)``."""
    from ..analytics import plane as _plane

    vals: list = [None] * len(sec.steps)
    for si, st in enumerate(sec.steps):
        kind = st[0]
        if kind == "leaf":
            v = words[arrs[f"g{si}"]]
        elif kind == "adhoc":
            v = arrs[f"w{si}"]
        elif kind == "reduce":
            _, bi, slot, kq = st
            v = bucket_heads[bi][slot, :kq]
        elif kind == "vscan":
            _, ci, tag, _depth, _kc = st
            slices, ebm = cols[ci]
            v = _plane.scan_words(tag, slices, ebm,
                                  arrs.get(f"b{si}"),
                                  arrs.get(f"b2{si}"))
        elif kind == "vagg":
            _, akind, fi, aligned, ci, _depth, _kc = st
            slices, ebm = cols[ci]
            f = vals[fi]
            if akind == "sum":
                found_cards = dense.popcount(f)
            fc = f
            if not aligned:
                fc = f[arrs[f"i{si}"]] if f.shape[0] else jnp.zeros(
                    (st[6], WORDS32), jnp.uint32)
                fc = jnp.where(arrs[f"o{si}"][:, None], fc,
                               jnp.uint32(0))
            if akind == "sum":
                v = (_plane.sum_cards(slices, fc), found_cards)
            else:
                res = _plane.topk_words(slices, fc & ebm,
                                        arrs[f"k{si}"])
                v = (res, dense.popcount(res))
        else:
            _, op, children, _k = st
            parts = []
            for k, (ci, aligned) in enumerate(children):
                cv = vals[ci]
                if not aligned:
                    cv = cv[arrs[f"i{si}_{k}"]]
                    cv = jnp.where(arrs[f"o{si}_{k}"][:, None], cv,
                                   jnp.uint32(0))
                parts.append(cv)
            if op == "andnot":
                rest = parts[1]
                for p in parts[2:]:
                    rest = rest | p
                v = parts[0] & ~rest
            else:
                fn = dense.OPS[op]
                v = parts[0]
                for p in parts[1:]:
                    v = fn(v, p)
        vals[si] = v
    rootv = vals[sec.root]
    if sec.agg is not None:
        # aggregate roots ARE their output pair (assembled host-side)
        return rootv
    cards = dense.popcount(rootv)
    return (rootv if sec.form == "bitmap" else None), cards


def eval_sections(sections, arrays_list, words, bucket_heads,
                  cols_list=None) -> list:
    if cols_list is None:
        cols_list = [()] * len(sections)
    return [eval_section(sec, arrs, words, bucket_heads, cols=cols)
            for sec, arrs, cols in zip(sections, arrays_list, cols_list)]


# ---------------------------------------------------------- accounting

def record_fused_dispatch(site: str, sections) -> None:
    """Metric bump at a device-dispatch site carrying expressions:
    ``rb_expr_nodes_fused`` counts DAG op nodes executed fused;
    ``rb_expr_launches_saved_total`` credits the launches a
    node-at-a-time evaluator (one launch per op node) would have paid
    beyond the expression's share of this one dispatch."""
    sections = [s for s in sections if s is not None]
    if not sections:
        return
    nodes = sum(s.n_nodes for s in sections)
    obs_metrics.counter("rb_expr_nodes_fused", site=site).inc(nodes)
    saved = sum(max(0, s.n_nodes - 1) for s in sections)
    if saved:
        obs_metrics.counter("rb_expr_launches_saved_total",
                            site=site).inc(saved)


def record_analytics_dispatch(site: str, sections, span) -> None:
    """Analytics accounting at a device-dispatch site: count the fused
    vscan/vagg steps (``rb_analytics_scans_total`` /
    ``rb_analytics_aggs_total``) and attach the ``analytics.scan``
    event ``tools/check_trace.py`` validates (docs/ANALYTICS.md)."""
    scans = aggs = 0
    for s in sections:
        if s is None or s.kind != "fused":
            continue
        for st in s.steps:
            if st[0] == "vscan":
                scans += 1
            elif st[0] == "vagg":
                aggs += 1
    if not scans and not aggs:
        return
    obs_metrics.counter("rb_analytics_scans_total", site=site).inc(scans)
    if aggs:
        obs_metrics.counter("rb_analytics_aggs_total",
                            site=site).inc(aggs)
    span.event("analytics.scan", site=site, scans=scans, aggs=aggs,
               bsi_depth=value_depth_of(sections))


def assemble_section_result(sec: ExprSection, out, form: str):
    """Host readback of one section's device outputs -> (cardinality,
    bitmap|None, value|None).  ``out`` is the (heads, cards) pair for
    fused sections — or the aggregate output pair for agg roots —
    ignored for empty/adhoc ones."""
    from ..core.bitmap import RoaringBitmap

    if sec.agg is not None:
        return _assemble_agg(sec, out, form)
    if sec.kind == "empty":
        return 0, (RoaringBitmap() if form == "bitmap" else None), None
    if sec.kind == "adhoc":
        bm = sec.adhoc_bm
        return (bm.cardinality,
                bm.clone() if form == "bitmap" else None, None)
    heads, cards = out
    cards = np.asarray(cards)
    bm = None
    if form == "bitmap":
        bm = packing.unpack_result(sec.root_keys, np.asarray(heads),
                                   cards)
    return int(cards.sum()), bm, None


def _assemble_agg(sec: ExprSection, out, form: str):
    """Aggregate readback: sum weights the per-slice popcounts in host
    Python ints (exact past 32 bits); topk unpacks the result rows and
    applies the smallest-id tie trim the host Kaser rule specifies."""
    from ..core.bitmap import RoaringBitmap

    akind, k = sec.agg
    if sec.kind == "empty":
        if akind == "sum":
            return 0, None, 0
        return 0, (RoaringBitmap() if form == "bitmap" else None), None
    if akind == "sum":
        slice_cards, found_cards = out
        slice_cards = np.asarray(slice_cards)
        total = sum((1 << i) * int(slice_cards[i].sum())
                    for i in range(slice_cards.shape[0]))
        return int(np.asarray(found_cards).sum()), None, total
    words, cards = out
    cards = np.asarray(cards)
    from ..bsi.slice_index import trim_smallest

    bm = trim_smallest(
        packing.unpack_result(sec.root_keys, np.asarray(words), cards),
        k)
    return bm.cardinality, (bm if form == "bitmap" else None), None


def assemble_section_results(sections, expr_outs, results,
                             form_of) -> list:
    """Fill ``results`` in place for every non-flat section (flat roots
    were read back from their buckets) — THE shared readback tail of
    the three engines.  ``form_of(qid)`` resolves a query's result
    form; ``expr_outs`` aligns with the fused subset in order."""
    from .batch_engine import BatchResult

    fi = 0
    for sec in sections:
        if sec.kind == "flat":
            continue
        out = None
        if sec.kind == "fused":
            out = expr_outs[fi]
            fi += 1
        card, bm, value = assemble_section_result(sec, out,
                                                  form_of(sec.qid))
        results[sec.qid] = BatchResult(cardinality=card, bitmap=bm,
                                       value=value)
    return results


# ------------------------------------------------ unfused reference

def execute_node_at_a_time(engine, queries) -> list:
    """The un-fused baseline the bench/acceptance lanes compare against:
    every reduce node of every expression is its OWN single-query device
    launch (``BatchEngine.execute`` of one flat query, intermediate
    bitmaps read back), combines run on the host — the only way the
    pre-expression engines could serve compositional traffic.  Bit-exact
    with the fused path by construction."""
    from .batch_engine import BatchQuery, BatchResult

    out = []
    for q in queries:
        if isinstance(q, BatchQuery):
            out.append(engine.execute([q])[0])
            continue
        e = canonicalize(q.expr)
        if isinstance(e, Agg):
            from ..analytics import two_phase_execute

            out.extend(two_phase_execute(engine, [q]))
            continue
        memo: dict = {}

        def ev(n):
            got = memo.get(n)
            if got is not None:
                return got
            if isinstance(n, Ref):
                v = engine._host_sources()[n.index]
            elif isinstance(n, AdHoc):
                v = n.bm
            elif isinstance(n, ValuePred):
                v = engine._column(n.col).host_filter(n.op, n.lo, n.hi)
            elif n.op == "empty":
                from ..core.bitmap import RoaringBitmap

                v = RoaringBitmap()
            elif _is_reduce(n):
                ops = tuple(c.index for c in n.children)
                v = engine.execute(
                    [BatchQuery(n.op, ops, form="bitmap")])[0].bitmap
            elif n.op == "andnot":
                v = ev(n.children[0]).clone()
                for r in n.children[1:]:
                    v = v - ev(r)
            else:
                import operator

                fn = {"or": operator.or_, "and": operator.and_,
                      "xor": operator.xor}[n.op]
                parts = [ev(c) for c in n.children]
                v = parts[0]
                for p in parts[1:]:
                    v = fn(v, p)
            memo[n] = v
            return v

        rb = ev(e)
        if isinstance(e, (Ref, AdHoc)):
            # a bare-leaf root must not alias the engine's host-source
            # cache (the shadow reference) or the AdHoc snapshot
            rb = rb.clone()
        out.append(BatchResult(
            cardinality=rb.cardinality,
            bitmap=rb if q.form == "bitmap" else None))
    return out


# ------------------------------------------------- workload generators

def random_expr_pool(n_bitmaps: int, q: int, depth: int = 2,
                     seed: int = 0xDA6, form: str = "cardinality",
                     max_fan: int = 3) -> list:
    """Deterministic depth-``depth`` expression pool over ``n_bitmaps``
    residents — the shared workload of the bench expression lane and the
    acceptance tests.  Mixes or/and/xor/andnot interior nodes with
    leaf-level reduce chains; one query in four carries a ``not_`` term
    (exercising the andnot rewrite)."""
    if n_bitmaps < 2:
        raise ValueError("expression pool needs at least 2 residents")
    rng = np.random.default_rng(seed)

    def leaf_chain():
        k = int(rng.integers(2, min(5, n_bitmaps + 1)))
        refs = [int(x) for x in rng.choice(n_bitmaps, size=k,
                                           replace=False)]
        op = ("or", "xor", "and")[int(rng.integers(3))]
        return Node(op, tuple(Ref(r) for r in refs))

    def build(d):
        if d <= 1:
            return leaf_chain()
        fan = int(rng.integers(2, max_fan + 1))
        kids = tuple(build(d - 1) for _ in range(fan))
        op = ("or", "and", "xor", "andnot")[int(rng.integers(4))]
        return Node(op, kids)

    pool = []
    for i in range(q):
        e = build(depth)
        if i % 4 == 3:
            e = Node("and", (e, Node("not", (Ref(int(
                rng.integers(n_bitmaps))),))))
        pool.append(ExprQuery(e, form=form))
    return pool


def rung_expressions(depth: int, n_residents: int,
                     form: str = "cardinality") -> list:
    """Representative depth-``depth`` op-mix shapes for warmup: the
    expression analog of ``BatchEngine._rung_queries`` — deterministic,
    so a warmed serving loop's first matching execute hits the plan AND
    program caches."""
    r = [Ref(i % n_residents) for i in range(4)]
    base = [Node("or", (r[0], r[1])), Node("xor", (r[2], r[3])),
            Node("and", (r[0], r[2]))]
    exprs = [Node("and", (base[0], base[1])),
             Node("or", (base[1], base[2])),
             Node("andnot", (base[0], r[2])),
             Node("and", (base[0], Node("not", (r[3],))))]
    for _ in range(max(0, depth - 2)):
        exprs = [Node("or", (exprs[0], exprs[1])),
                 Node("and", (exprs[1], exprs[2])),
                 Node("andnot", (exprs[2], exprs[3].children[0])),
                 Node("xor", (exprs[3], exprs[0]))]
    return [ExprQuery(e, form=form) for e in exprs]


def parse_warmup_rung(r):
    """Warmup rung vocabulary shared by the three engines: an int is a
    pow2 operand rung (the flat shapes); ``"expr"``, ``"expr:3"`` or
    ``("expr", 3)`` is an expression-shape rung at that depth;
    ``"delta:8"`` / ``("delta", 8)`` is a mutation patch-program rung
    at that many delta rows (docs/MUTATION.md)."""
    if isinstance(r, str) and r.startswith("expr"):
        _, _, d = r.partition(":")
        return "expr", int(d) if d else 2
    if isinstance(r, str) and r.startswith("delta"):
        _, _, d = r.partition(":")
        return "delta", int(d) if d else 8
    if isinstance(r, tuple) and len(r) == 2 and r[0] in ("expr", "delta"):
        return r[0], int(r[1])
    return "flat", int(r)
