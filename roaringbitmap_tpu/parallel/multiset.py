"""Cross-tenant multi-set batching: Q queries over S resident sets, few
device launches, pipelined dispatch.

PR 1's ``BatchEngine`` amortized the device-dispatch floor across Q
queries — but only within ONE resident ``DeviceBitmapSet``.  A serving
front-end holds many tenants' sets resident at once and, per tick, pays
one launch per tenant even when each tenant contributes a handful of
queries; BENCH_r05's dispatch-floor numbers (35-81 us per launch against
~10 us of work) make that the dominant cost of small-Q lanes.  This
module repeats Roaring's own packing move one level up: just as the
container layout packs heterogeneous containers behind one uniform
algebra so aggregation amortizes (Chambi et al.; Lemire et al.), the
pool planner packs heterogeneous *tenants* behind one device launch.

Execution model
---------------
A pool is a list of :class:`BatchGroup` — each group Q_g mixed-op
:class:`~.batch_engine.BatchQuery` requests addressed to one resident
set.  The planner:

1. plans every query against its own set (the per-set ``BatchEngine``
   row selection, unchanged);
2. **remaps row indices by per-set offsets** into one pooled row space —
   the concatenation of the referenced sets' resident images — so one
   flat gather feeds every tenant;
3. buckets the POOLED queries by (op, pow2 operand rung) and pads
   shapes over the pooled row-count distribution
   (``batch_engine.plan_bucket`` — the same bucketing policy, applied
   across tenants, so two tenants' lone OR queries share one padded
   bucket instead of two launches);
4. runs all buckets in ONE jitted program: per-set image rebuild (for
   stream-resident tenants) + concat + the flat segmented reduce
   (``batch_engine.bucket_body``).

Pipelined (depth-N) dispatch
----------------------------
When a pool needs multiple launches — the proactive HBM-budget split, or
``execute_pipelined`` streaming several ticks — launches flow through a
depth-``GuardPolicy.pipeline_depth`` window (depth 1 = strictly serial,
2 = the classic double buffer, N keeps up to N-1 launches in flight
while the N-th is planned — deeper windows keep the device busy across
burstier host-side planning, at N-1 launches of extra transient HBM):
launch k+1 is planned/packed/bucketized on the host *while launch k
runs on device*
(JAX async dispatch — nothing blocks until readback), and launch k-1's
readback is drained as the window slides.  Host planning time spent
while at least one launch was in flight is **hidden** behind device
compute; the ``multiset.pipeline`` span reports
``host_ms`` / ``host_overlapped_ms`` / ``overlap_ratio`` / ``drain_ms``
and the ratio also lands on the
``rb_multiset_pipeline_overlap_ratio`` gauge.  On backends that support
buffer donation (TPU/GPU) the per-launch bucket scratch is uploaded
fresh and *donated*, so the double buffer reuses the dead launch's
arena instead of holding both generations live.

Guard integration (docs/ROBUSTNESS.md): every launch rides
``guard.run_with_fallback`` down the same ``pallas -> xla -> xla-vmap ->
sequential`` ladder, so demotion is per-launch; ``ResourceExhausted``
halves the launch's pooled queries (reactive split,
``rb_multiset_oom_splits_total``); the predicted pooled footprint
(``insights.predict_multiset_dispatch_bytes`` — gather + scratch +
heads + outputs + per-tenant densify + the pooled concat) is checked
against ``ROARING_TPU_HBM_BUDGET`` BEFORE dispatch and halves the pool
proactively (``rb_multiset_proactive_splits_total``); a fault that only
surfaces at drain time re-runs that launch synchronously down the
ladder (``drain_retry``).  Every rung is bit-exact, so degradation and
splitting change throughput only.

An ``execute()`` pool referencing a single set routes through that
set's ``BatchEngine.execute`` verbatim — zero pooled planning, zero
extra device buffers (regression-pinned against the HBM ledger in
tests/test_multiset.py).  ``execute_pipelined`` always builds pooled
launches: a streamed single-tenant tick trades that zero-copy route for
cross-tick overlap.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..insights import analysis as insights
from ..mutation import result_cache as mut_cache
from ..obs import cost as obs_cost
from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..ops import kernels, megakernel, packing
from ..runtime import errors, faults, guard
from ..runtime import lattice as rt_lattice
from ..runtime import warmup as rt_warmup
from ..runtime.cache import LRUCache
from ..ops import dense
from . import expr as expr_mod
from .aggregation import DeviceBitmapSet, _engine
from .batch_engine import (ENGINE_LADDER, PLAN_CACHE_MAX, PROGRAM_CACHE_MAX,
                           WORDS32, _RED_OP, BatchEngine, BatchQuery,
                           BatchResult, bucket_body, plan_bucket,
                           plan_padding, query_desc, resolve_query_engine,
                           snap_plan_groups)

#: the guard/trace/metric site of every pooled dispatch
SITE = "multiset"


@dataclasses.dataclass(frozen=True)
class BatchGroup:
    """Queries addressed to ONE resident set (tenant) of the pool.

    ``set_id`` indexes the engine's resident-set list; ``queries`` are
    ordinary :class:`~.batch_engine.BatchQuery` requests against that
    set's operand space.
    """

    set_id: int
    queries: tuple

    def __init__(self, set_id: int, queries):
        object.__setattr__(self, "set_id", int(set_id))
        object.__setattr__(self, "queries", tuple(queries))


@dataclasses.dataclass
class _OpGroup:
    """Same-op buckets merged for EXECUTION into one flat segmented
    reduce (a "superbucket").  Rung bucketing still governs the plan's
    shapes, padding, and cache signatures; the merge exists because a
    pooled launch would otherwise pay one reduce chain per (op, rung)
    cell — at S tenants x 4 ops x several rungs, fixed per-kernel
    overhead starts to rival the dispatch floor the pool is amortizing.
    Merging is exact: segment ids are globally offset per member bucket,
    so the flat reduce never mixes two buckets' segments, and the
    per-key post passes (presence mask, workShyAnd keep, andnot head
    pass, popcount) act on the flat head axis with plan-time masks."""

    op: str
    bucket_idx: list      # indices into _PoolPlan.buckets, merge order
    seg_offs: list        # per member bucket: its head-slot base in nseg
    nseg: int             # total head slots (sum of q * (k_pad + 1))
    n_rows: int           # total flat gather rows (sum of q * r_pad)
    n_steps: int          # max doubling depth over members
    needs_words: bool
    host: dict            # merged NumPy operands
    arrays: dict = None   # device twins, uploaded lazily on first dispatch
    #                       (budget-probed plans for over-budget pools are
    #                       halved away without ever dispatching)
    #: per member bucket (merge order): (q, r_pad) — when every member
    #: has k_pad == 1 (one key segment per query, the serving-front-end
    #: shape) the reduce is REGULAR: each query's single segment is
    #: exactly its r_pad padded gather rows, so the op body replaces the
    #: doubling-pass segmented scan (n_steps full passes + a head
    #: gather) with one lax.reduce over the row axis per member rung
    member_shapes: tuple = ()
    regular: bool = False

    @property
    def sig(self):
        return (self.op, self.nseg, self.n_rows, self.n_steps,
                self.needs_words,
                self.member_shapes if self.regular else None)

    def device_arrays(self, fresh: bool = False, keys=None) -> dict:
        """Unlike a plain bucket, a group's upload set depends on the
        resolved engine (``_op_group_keys``), so cached twins key by the
        selected tuple — an engine demotion mid-plan gets its own subset
        instead of another engine's mismatched pytree."""
        sel = tuple(keys) if keys is not None else tuple(self.host)
        if fresh:
            return {k: jnp.asarray(self.host[k]) for k in sel}
        if self.arrays is None:
            self.arrays = {}
        got = self.arrays.get(sel)
        if got is None:
            got = self.arrays[sel] = {k: jnp.asarray(self.host[k])
                                      for k in sel}
        return got


@dataclasses.dataclass
class _PoolPlan:
    """One pooled batch plan: shape buckets over a COMPACTED pooled row
    space.  Rather than concatenating whole resident images (whose
    round_blocks padding would dominate the launch on small pools), the
    planner computes the set of rows the pool actually references,
    selects them per set (``row_sel[sid]``, set-local indices), and
    remaps every bucket gather into that compact pool — the program's
    transient image is ``n_pool_rows`` rows, proportional to the pool's
    true work, not to the tenants' resident padding.  ``op_groups`` are
    the per-op execution superbuckets (the xla-vmap cross-check engine
    runs the unmerged per-bucket path instead, proving the merge
    equivalent)."""

    buckets: list
    op_groups: list
    sids: tuple
    row_sel: dict         # sid -> i32 HOST array of set-local rows; the
    #                       device twins upload lazily (row_sel_dev) so
    #                       budget-probe plans that are halved away never
    #                       touch the device
    n_pool_rows: int      # total selected rows (the pooled image height)
    #: fused expression sections (parallel.expr) + the expanded-slot ->
    #: original-query owner map (None-skipped internal reduce pseudos)
    exprs: list = dataclasses.field(default_factory=list)
    owner: dict = dataclasses.field(default_factory=dict)
    #: per-bucket readback constants (operand counts + live-key masks),
    #: computed once per plan — the readback loop runs per dispatch
    rb_meta: dict = dataclasses.field(default_factory=dict)
    #: the assembled one-kernel program (ops.megakernel.MegaPlan) when
    #: the pool has fused sections; its host stream stays alive for the
    #: pipelined dispatcher's fresh (donated) re-uploads
    mega: object = None
    #: covering lattice point (runtime.lattice) when an active lattice
    #: snapped this pool — the plan then references EVERY resident set
    #: with a uniform padded row selection, so the program signature is
    #: drawn from the closed vocabulary; None = exact shapes
    point: object = None
    #: (padding_bytes, padded_fraction) of the snap
    padding: tuple = (0, 0.0)
    _row_sel_dev: dict = dataclasses.field(default_factory=dict)

    def row_sel_dev(self, sid: int):
        dev = self._row_sel_dev.get(sid)
        if dev is None:
            dev = self._row_sel_dev[sid] = jnp.asarray(self.row_sel[sid])
        return dev

    @property
    def fused(self) -> list:
        return expr_mod.fused_of(self.exprs)

    @property
    def expr_signature(self) -> tuple:
        return expr_mod.signature_of(self.exprs)

    @property
    def signature(self):
        return (self.sids,
                tuple(int(self.row_sel[s].shape[0]) for s in self.sids),
                tuple(b.signature for b in self.buckets),
                self.expr_signature)


def _merge_op_groups(buckets) -> list:
    """Build the per-op execution superbuckets from remapped plan
    buckets (see _OpGroup)."""
    by_op: dict = {}
    for bi, b in enumerate(buckets):
        by_op.setdefault(b.op, []).append((bi, b))
    groups = []
    for op in sorted(by_op):
        members = by_op[op]
        row_off = seg_off = 0
        seg_offs: list = []
        parts: dict = {k: [] for k in ("gather", "valid", "flat_seg",
                                       "flat_head", "mask_ok")}
        if op == "andnot":
            parts["head_gather"] = []
            parts["head_ok"] = []
        n_steps = 1
        regular = all(b.k_pad == 1 for _, b in members)
        live: dict = {k: [] for k in (("mask_live", "head_gather_live",
                                       "head_ok_live") if regular else ())}
        for _bi, b in members:
            qn, k_pad = b.q, b.k_pad
            seg_offs.append(seg_off)
            parts["gather"].append(b.host["gather"].reshape(-1))
            parts["valid"].append(b.host["valid"].reshape(-1))
            parts["flat_seg"].append(b.host["flat_seg"] + seg_off)
            parts["flat_head"].append(b.host["flat_head"] + row_off)
            # per-key masks, extended into the (k_pad + 1) padded slot
            # space the flat head axis uses (slot k_pad is always dead)
            mask = np.zeros((qn, k_pad + 1), bool)
            mask[:, :k_pad] = (b.host["heads_ok"] & b.host["key_keep"]
                               if op == "and" else b.host["heads_ok"])
            parts["mask_ok"].append(mask.reshape(-1))
            if op == "andnot":
                hg = np.zeros((qn, k_pad + 1), np.int32)
                hg[:, :k_pad] = b.host["head_gather"]
                ho = np.zeros((qn, k_pad + 1), bool)
                ho[:, :k_pad] = b.host["head_ok"]
                parts["head_gather"].append(hg.reshape(-1))
                parts["head_ok"].append(ho.reshape(-1))
            if regular:
                # live-layout twins for the regular fast path: one slot
                # per query (k_pad == 1), no dead pad slots
                live["mask_live"].append(mask[:, 0])
                if op == "andnot":
                    live["head_gather_live"].append(
                        b.host["head_gather"][:, 0])
                    live["head_ok_live"].append(b.host["head_ok"][:, 0])
            row_off += qn * b.r_pad
            seg_off += qn * (k_pad + 1)
            n_steps = max(n_steps, b.n_steps)
        host = {k: np.concatenate(v) for k, v in parts.items()}
        host.update({k: np.concatenate(v) for k, v in live.items()
                     if v})
        groups.append(_OpGroup(
            op=op, bucket_idx=[bi for bi, _ in members],
            seg_offs=seg_offs, nseg=seg_off, n_rows=row_off,
            n_steps=n_steps,
            needs_words=any(b.needs_words for _, b in members),
            host=host,
            member_shapes=tuple((b.q, b.r_pad) for _, b in members),
            regular=regular))
    return groups


def _op_group_keys(g: _OpGroup, eng: str) -> tuple:
    """The operand keys ``_op_body`` actually reads for ``(eng, g)`` —
    the upload set of per-launch (donating) dispatches.  A regular group
    carries BOTH the padded flat-head operands (pallas / unmerged paths)
    and their live-layout twins (the regular fast path); shipping the
    unused half on every steady-state launch would roughly double the
    host->device traffic the pipeline is trying to hide."""
    if eng == "pallas" or not g.regular:
        keys = ("gather", "valid", "flat_seg", "mask_ok")
        if eng != "pallas":
            keys += ("flat_head",)
        if g.op == "andnot":
            keys += ("head_gather", "head_ok")
        return keys
    keys = ("gather", "valid", "mask_live")
    if g.op == "andnot":
        keys += ("head_gather_live", "head_ok_live")
    return keys


def _fold_rows(fn, blk):
    """Tree-reduce u32[q, r_pad, W] over axis 1 by halving — log2(r_pad)
    elementwise ops that XLA vectorizes on every backend (lax.reduce
    with a custom bitwise computation lowers to scalar loops on CPU)."""
    while blk.shape[1] > 1:
        half = blk.shape[1] // 2
        blk = fn(blk[:, :half], blk[:, half:])
    return blk[:, 0]


def _op_body(words, g_sig, arrays, eng: str, force_heads: bool = False):
    """Traced body for one op superbucket: ONE gather + ONE flat
    segmented reduce for every same-op bucket of the pool, post passes
    on the flat head axis.  Returns (heads_flat or None, cards_flat).
    ``force_heads`` returns heads regardless of the group's own
    needs_words — in-program consumption by fused expression combines
    (program outputs still gate on the original flag)."""
    op, nseg, _n_rows, n_steps, needs_words, reg_shapes = g_sig
    needs_words = needs_words or force_heads
    red = _RED_OP[op]
    g = words[arrays["gather"]]
    ident = jnp.uint32(0xFFFFFFFF if op == "and" else 0)
    g = jnp.where(arrays["valid"][:, None], g, ident)
    if eng == "pallas":
        heads, _ = kernels.segmented_reduce_pallas(
            red, g, arrays["flat_seg"], nseg)
    elif reg_shapes is not None:
        # regular fast path (_OpGroup.regular): every member query's one
        # key segment is exactly its r_pad padded gather rows, so the
        # per-segment reduction is a halving fold per member rung — no
        # doubling passes, no head gather — and the outputs stay in the
        # LIVE layout (one slot per query, no dead pad slots), halving
        # every post pass.  _bucket_outputs knows this layout.
        parts, row0 = [], 0
        for qn, r_pad in reg_shapes:
            blk = g[row0:row0 + qn * r_pad].reshape(qn, r_pad, -1)
            parts.append(_fold_rows(dense.OPS[red], blk))
            row0 += qn * r_pad
        heads = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        heads = jnp.where(arrays["mask_live"][:, None], heads,
                          jnp.uint32(0))
        if op == "andnot":
            hg = words[arrays["head_gather_live"]]
            hg = jnp.where(arrays["head_ok_live"][:, None], hg,
                           jnp.uint32(0))
            heads = hg & ~heads
        cards = dense.popcount(heads)
        return (heads if needs_words else None), cards
    else:
        red_rows = dense.doubling_pass(dense.OPS[red], g,
                                       arrays["flat_seg"], n_steps)
        safe = jnp.minimum(arrays["flat_head"], g.shape[0] - 1)
        heads = red_rows[safe]
    heads = jnp.where(arrays["mask_ok"][:, None], heads, jnp.uint32(0))
    if op == "andnot":
        hg = words[arrays["head_gather"]]
        hg = jnp.where(arrays["head_ok"][:, None], hg, jnp.uint32(0))
        heads = hg & ~heads
    cards = dense.popcount(heads)
    return (heads if needs_words else None), cards


def assemble_pooled_results(bucket_outputs, pooled, rb_meta: dict,
                            owner: dict | None = None) -> list:
    """Normalized per-bucket device outputs -> per-query BatchResults in
    pooled order — the readback assembly shared by
    :class:`MultiSetBatchEngine` and ``parallel.sharded_engine``.  One
    vectorized masked sum per bucket (not per query): a pooled readback
    walks Q x S results, so per-query ndarray reductions would rival the
    launch itself; the mask constants are plan-static and cached in
    ``rb_meta`` keyed by bucket identity.  ``owner`` maps expanded slot
    ids back to pooled query indices (expression plans; None = identity,
    internal reduce pseudos are skipped)."""
    pooled = list(pooled)
    results: list = [None] * len(pooled)
    for b, heads, cards in bucket_outputs:
        meta = rb_meta.get(id(b))
        if meta is None:
            kqs = np.fromiter((k.size for k in b.keys), np.int64,
                              len(b.keys))
            meta = kqs, (np.arange(b.k_pad)[None, :] < kqs[:, None])
            rb_meta[id(b)] = meta
        kqs, live = meta
        sums = np.where(live[:, :cards.shape[1]],
                        cards[:len(b.keys)], 0).sum(axis=1)
        for slot, (pid, keys_q) in enumerate(zip(b.qids, b.keys)):
            qid = pid if owner is None else owner.get(pid)
            if qid is None:
                continue        # internal expr reduce node, in-program
            kq = keys_q.size
            bm = None
            if pooled[qid][1].form == "bitmap":
                bm = packing.unpack_result(
                    keys_q,
                    heads[slot, :kq] if kq else
                    np.zeros((0, WORDS32), np.uint32),
                    cards[slot, :kq])
            results[qid] = BatchResult(cardinality=int(sums[slot]),
                                       bitmap=bm)
    return results


@dataclasses.dataclass
class _Inflight:
    """A dispatched-but-undrained launch of the pipelined dispatcher.

    Carries the launch's cost-model inputs (static cost analysis, word-op
    estimate, predicted peak bytes) plus the launch span id so drain()
    can stamp a ``multiset.cost`` event attributed back to the launch
    that dispatched it — flagged ``async=True`` because the drain wall
    includes queueing behind earlier in-flight launches."""

    plan: _PoolPlan
    outs: list
    queries: tuple
    eng: str
    inject: bool
    span_id: str | None = None
    cost: dict | None = None
    word_ops: float = 0.0
    predicted_peak: int = 0


def _donation_supported() -> bool:
    """Buffer donation is a TPU/GPU capability; the CPU backend ignores
    it with a warning per compile, so the double buffer only requests it
    where it does something."""
    return jax.default_backend() in ("tpu", "gpu")


class MultiSetBatchEngine:
    """Plan + execute mixed-op query pools over S resident sets.

    ``sets`` may mix ``DeviceBitmapSet`` instances and already-built
    ``BatchEngine`` instances (the latter are adopted, so a serving
    process upgrades to pooled execution without re-packing anything).
    """

    def __init__(self, sets: list, result_cache="env"):
        if not sets:
            raise ValueError("multi-set engine needs at least one set")
        rt_warmup.enable_compile_cache()   # ROARING_TPU_COMPILE_CACHE
        #: materialized-result reuse (mutation.result_cache): "env"
        #: resolves ROARING_TPU_RESULT_CACHE; engines built here share
        #: it (already-built BatchEngines keep their own), so the S=1
        #: fast path and the pooled path serve from one cache
        self.result_cache = (mut_cache.from_env()
                             if result_cache == "env" else result_cache)
        self._engines = [
            s if isinstance(s, BatchEngine)
            else BatchEngine(s, result_cache=self.result_cache)
            for s in sets]
        self.n_sets = len(self._engines)
        #: pooled row base per set: set i's resident image occupies rows
        #: [_row_base[i], _row_base[i+1]) of a full-pool concatenation;
        #: per-plan offsets are recomputed over the referenced subset
        self._rows = [int(e._row_src.size) for e in self._engines]
        self._plans = LRUCache(PLAN_CACHE_MAX, name="multiset_plans")
        self._programs = LRUCache(PROGRAM_CACHE_MAX,
                                  name="multiset_programs")
        self.split_count = 0            # reactive (ResourceExhausted) halvings
        self.proactive_split_count = 0  # pre-dispatch HBM-budget halvings
        #: predicted-vs-measured bytes of the most recent pooled dispatch
        #: (the multiset.memory event payload)
        self.last_dispatch_memory: dict | None = None
        #: cost/roofline accounting of the most recent pooled dispatch
        #: (the multiset.cost event payload).  Sync launches stamp it at
        #: dispatch; pipelined launches stamp it at drain time with
        #: ``async=True`` + the originating ``launch_span_id`` — the
        #: drain wall includes pipeline queueing, so async rooflines are
        #: lower bounds, not launch walls
        self.last_dispatch_cost: dict | None = None
        self._first_query_done = False  # rb_first_query_seconds, once
        #: stats of the most recent pipelined run (the multiset.pipeline
        #: span tags: launches, host_ms, host_overlapped_ms,
        #: overlap_ratio, drain_ms)
        self.last_pipeline: dict | None = None

    @classmethod
    def from_bitmap_sets(cls, bitmap_sets: list, layout: str = "auto",
                         **kw) -> "MultiSetBatchEngine":
        return cls([DeviceBitmapSet(b, layout=layout, **kw)
                    for b in bitmap_sets])

    @property
    def sets(self) -> list:
        return [e._ds for e in self._engines]

    # ------------------------------------------------------------- planning

    def _flatten(self, groups):
        """[(set_id, query)] in group order + per-group lengths."""
        pooled, lengths = [], []
        for g in groups:
            if not isinstance(g, BatchGroup):
                g = BatchGroup(*g)
            if g.set_id < 0 or g.set_id >= self.n_sets:
                raise IndexError(
                    f"set_id out of range 0..{self.n_sets - 1}: {g.set_id}")
            pooled.extend((g.set_id, q) for q in g.queries)
            lengths.append(len(g.queries))
        return tuple(pooled), lengths

    @staticmethod
    def _regroup(flat, lengths):
        out, i = [], 0
        for n in lengths:
            out.append(flat[i:i + n])
            i += n
        return out

    def _sync_with_sets(self) -> None:
        """Pick up member-set mutations: a structural repack changes a
        tenant's row count, so the pooled row bases must re-read (the
        version component of the plan key retires stale plans)."""
        for i, e in enumerate(self._engines):
            e._sync_with_ds()
            self._rows[i] = int(e._row_src.size)

    def _cache_probe_for(self, sid: int):
        """Plan-time subtree probe for one tenant, or None.  Pooled
        plans feed the DONATING pipelined dispatcher, so cached rows
        copy to host here — handing the cache's device buffer to a
        donated argument would destroy the entry under it."""
        if self.result_cache is None:
            return None
        e = self._engines[sid]
        rc = self.result_cache

        def probe(node):
            k, _leaves = mut_cache.node_key(node, e._leaf_token,
                                            e._col_token)
            if k is None:
                return None
            got = rc.peek_rows(k)
            if got is None:
                return None
            keys_c, words_c, _cards = got
            return keys_c, np.asarray(words_c)

        return probe

    def _plan_pool(self, pooled) -> _PoolPlan:
        """Pooled plan: per-set row selection, offset remap into the
        referenced-set concatenation, shared shape bucketing.  Cached by
        the exact (set_id, query) tuple plus the referenced sets'
        mutation versions — the prepared-statement pattern across
        tenants, retired exactly when a tenant's data moves."""
        self._sync_with_sets()
        lat = rt_lattice.active()
        # the TENANT-MIX dimension of pool-shape churn: without a
        # lattice, every distinct referenced-set subset is a distinct
        # program arity; under one, every pool references EVERY resident
        # set (unreferenced tenants contribute a minimal padded row
        # selection), so the mix stops being a signature dimension
        sids = (tuple(range(self.n_sets)) if lat is not None
                else tuple(sorted({sid for sid, _ in pooled})))
        key = (tuple(pooled),
               tuple((self._engines[s]._ds.uid,
                      self._engines[s]._ds.version) for s in sids),
               tuple(self._engines[s]._columns_token() for s in sids),
               rt_lattice.plan_token())
        cached = self._plans.get(key)
        if cached is not None:
            return cached
        offsets, base = {}, 0
        for sid in sids:
            offsets[sid] = base
            base += self._rows[sid]
        with obs_slo.phase("plan"), \
                obs_trace.span("multiset.plan", q=len(pooled),
                               sets=len(sids)) as sp:
            groups: dict = {}
            owner: dict = {}
            sections: list = []
            counter = [0]

            def add_item(sid, pq, own):
                pid = counter[0]
                counter[0] += 1
                eng = self._engines[sid]
                rows, segs, keys_q, keep, hrows = eng._plan_query(pq)
                off = offsets[sid]
                rows = rows + off
                if hrows is not None:
                    hrows = hrows + off
                rung = (0 if lat is not None
                        else packing.next_pow2(
                            max(1, len(set(pq.operands)))))
                groups.setdefault((pq.op, rung), []).append(
                    (pid, pq, rows, segs, keys_q, keep, hrows))
                if own is not None:
                    owner[pid] = own
                return pid, keys_q

            def plan_leaf(sid, i):
                rows, keys = self._engines[sid]._plan_leaf(i)
                return rows + offsets[sid], keys

            for qid, (sid, q) in enumerate(pooled):
                if isinstance(q, expr_mod.ExprQuery):
                    sections.append(expr_mod.compile_query(
                        q, qid,
                        lambda pq, own, sid=sid: add_item(sid, pq, own),
                        lambda i, sid=sid: plan_leaf(sid, i),
                        cache_probe=self._cache_probe_for(sid),
                        col_resolve=(lambda name, sid=sid:
                                     self._engines[sid]._column(name))))
                else:
                    add_item(sid, q, qid)
            # the pooled-row dimension must be judged WITH the shape
            # snap (atomically, before dead buckets mutate the plan):
            # the per-set selection need is computable from the raw
            # item gathers — the same refs the compaction below unions
            pool_need = -1
            if lat is not None:
                if all(self._rows[s] >= 1 for s in sids):
                    refs = [it[2] for items in groups.values()
                            for it in items]
                    refs += [it[6] for items in groups.values()
                             for it in items if it[6] is not None]
                    refs += [v.ravel() for sec in sections
                             if sec.kind == "fused" and sec.host
                             for k, v in sec.host.items()
                             if k.startswith("g")]
                    # global row 0 ALWAYS joins the downstream union:
                    # padded bucket cells (dead queries/rows, dead op
                    # buckets, andnot head pads) gather index 0, so the
                    # need judged here must include it or a pool sitting
                    # exactly on a rung boundary would overflow it after
                    # padding (off-vocabulary program despite a snap)
                    refs.append(np.zeros(1, np.int64))
                    allr = np.unique(np.concatenate(
                        [np.asarray(r).ravel() for r in refs]))
                    pool_need = 1
                    for sid in sids:
                        off = offsets[sid]
                        pool_need = max(pool_need, int(
                            ((allr >= off)
                             & (allr < off + self._rows[sid])).sum()))
            pad_to, point = snap_plan_groups(
                lat, groups, sections,
                any(q.form == "bitmap" for _, q in pooled),
                counter, self._engines[0].keys[:0], placement="single",
                pool=pool_need)
            sp.tag(need_q=max((len(i) for i in groups.values()),
                              default=0),
                   need_rows=max((it[2].size for i in groups.values()
                                  for it in i), default=0),
                   need_keys=max((it[4].size for i in groups.values()
                                  for it in i), default=0))
            with obs_trace.span("multiset.pool", groups=len(groups)):
                buckets = [plan_bucket(op, items, pad_to=pad_to)
                           for (op, _), items in sorted(groups.items())]
                # compact the pooled row space: every gather row the
                # pool references, once, sorted — per-set selections
                # concatenate to exactly this order, and the bucket
                # gathers (plus the expression sections' leaf gathers)
                # remap to positions in it
                refs = [b.host["gather"].ravel() for b in buckets]
                refs += [b.host["head_gather"].ravel() for b in buckets
                         if "head_gather" in b.host]
                refs += [v.ravel() for sec in sections
                         if sec.kind == "fused" and sec.host
                         for k, v in sec.host.items()
                         if k.startswith("g")]
                pool_rows = (np.unique(np.concatenate(refs)) if refs
                             else np.zeros(1, np.int64))
                if pool_rows.size == 0:
                    pool_rows = np.zeros(1, np.int64)
                row_sel_raw = {}
                for sid in sids:
                    off = offsets[sid]
                    in_set = pool_rows[(pool_rows >= off)
                                       & (pool_rows < off
                                          + self._rows[sid])]
                    row_sel_raw[sid] = (in_set - off).astype(np.int32)
                # the pooled-row need, PRE-pad — what a lattice's pool
                # rungs cover; insights.recommend_lattice reads it off
                # the plan span
                sp.tag(need_pool=int(max(
                    (s.size for s in row_sel_raw.values()), default=1)))
                # lattice pool-rows dimension: every set's row selection
                # pads to ONE covering rung (dead slots re-gather the
                # set's row 0, which no bucket references), so the
                # pooled image height — a program operand shape — comes
                # from the closed vocabulary.  ``pos`` maps compact
                # pooled positions to their padded homes.  The rung was
                # judged atomically with the shape snap above (the point
                # cannot be abandoned here — dead buckets are already
                # planted); never under-pad, results must stay exact.
                if point is not None:
                    B = max(point.pool, max(
                        s.size for s in row_sel_raw.values()))
                    row_sel, parts, base = {}, [], 0
                    for sid in sids:
                        sel = row_sel_raw[sid]
                        padded_sel = np.zeros(B, np.int32)
                        padded_sel[:sel.size] = sel
                        row_sel[sid] = padded_sel
                        parts.append(base + np.arange(sel.size,
                                                      dtype=np.int64))
                        base += B
                    pos = (np.concatenate(parts) if parts
                           else np.zeros(0, np.int64))
                    n_pool = base
                    point = dataclasses.replace(point, pool=B)
                else:
                    row_sel = row_sel_raw
                    pos = np.arange(pool_rows.size, dtype=np.int64)
                    n_pool = int(pool_rows.size)
                # remap the (host-only, not yet uploaded) bucket gathers
                # into pooled positions — device twins materialize lazily
                # at first dispatch, and only for the rung that needs
                # them (xla-vmap reads buckets, every other rung reads
                # the merged op groups)
                for b in buckets:
                    for k in ("gather", "head_gather"):
                        if k in b.host:
                            b.host[k] = pos[np.searchsorted(
                                pool_rows, b.host[k])].astype(np.int32)
                for sec in sections:
                    if sec.kind != "fused" or not sec.host:
                        continue
                    for k in list(sec.host):
                        if k.startswith("g"):
                            sec.host[k] = pos[np.searchsorted(
                                pool_rows, sec.host[k])].astype(np.int32)
            expr_mod.finalize_sections(sections, buckets)
            # the one-kernel program assembles from the REMAPPED host
            # gathers (pooled row space), after finalize resolved the
            # reduce steps' bucket slots; the pool keeps every host
            # array alive for the donate path, so nothing drops here;
            # analytics sections ride the vscan/vagg opcodes
            # (Megakernel v2 — docs/EXPRESSIONS.md)
            mega = None
            if expr_mod.fused_of(sections):
                mega = megakernel.build_full(buckets, sections)
            occupancy = (len(pooled)
                         / max(1, sum(b.q for b in buckets)))
            obs_metrics.gauge("rb_multiset_pool_occupancy",
                              site=SITE).set(occupancy)
            padding = (0, 0.0)
            if point is not None:
                pb, _pf = plan_padding(buckets, groups)
                pool_pad = (n_pool - int(pool_rows.size))
                pb += pool_pad * insights.ROW_BYTES
                total = sum(b.q * b.r_pad for b in buckets) + n_pool
                padding = (pb, (pb / insights.ROW_BYTES) / max(1, total))
            sp.tag(buckets=len(buckets), occupancy=round(occupancy, 4),
                   pool_rows=n_pool, exprs=len(sections),
                   snapped=point is not None)
        plan = _PoolPlan(buckets=buckets,
                         op_groups=_merge_op_groups(buckets),
                         sids=sids, row_sel=row_sel,
                         n_pool_rows=n_pool,
                         exprs=sections, owner=owner, mega=mega,
                         point=point, padding=padding)
        self._plans.put(key, plan)
        return plan

    def _pool_engine(self, plan: _PoolPlan, engine: str) -> str:
        """Engine resolution over the pooled shape: the flat_seg SMEM
        prefetch bound applies to the pooled bucket sizes, and any
        stream-resident tenant's chunk prefetch bound applies to its
        in-program rebuild (same rules as BatchEngine._bucket_engine,
        taken over every referenced set)."""
        eng = _engine(engine)
        if eng == "megakernel" and not (
                plan.mega is not None and plan.mega.fits()):
            if plan.mega is not None:
                megakernel.note_capacity_demotion(SITE, plan.mega)
            eng = "pallas"
        if eng in ("pallas", "megakernel"):
            for sid in plan.sids:
                ds = self._engines[sid]._ds
                if (ds.words is None and ds._chunks is not None
                        and int(ds._chunks[1].size)
                        > kernels.SMEM_PREFETCH_MAX):
                    eng = "xla"
        if eng == "pallas":
            longest = max((g.n_rows for g in plan.op_groups), default=0)
            if longest > kernels.SMEM_PREFETCH_MAX:
                eng = "xla"
        return eng

    def predict_dispatch_bytes(self, pooled_or_groups,
                               engine: str = "auto") -> int:
        """Predicted transient device bytes of ONE pooled launch — the
        quantity the proactive pool split compares against the HBM
        budget (insights.predict_multiset_dispatch_bytes)."""
        pooled = self._as_pooled(pooled_or_groups)
        plan = self._plan_pool(pooled)
        # mirror execute()'s chain-start resolution so the budgeted
        # figure models the rung that would actually dispatch
        eng = self._pool_engine(plan, resolve_query_engine(
            engine, [q for _, q in pooled]))
        return self._predict(plan, eng)["peak_bytes"]

    def predict_dispatch_seconds(self, pooled_or_groups,
                                 engine: str = "auto") -> float:
        """Pre-dispatch execute-time estimate of ONE pooled launch: the
        unified footprint model's bytes + the pooled word-op count
        (``insights.predict_multiset_dispatch_word_ops``) through
        ``obs.cost.estimate_seconds`` — at the peak-table ceilings until
        dispatches at (multiset, engine) calibrate the achieved rates.
        The quantity the serving loop's deadline-aware pool assembly
        budgets against BEFORE dispatching (docs/SERVING.md): every
        admitted pool shape is an AOT-analyzable program, so the
        admission controller can reason about it up front."""
        pooled = self._as_pooled(pooled_or_groups)
        if not pooled:
            return 0.0
        plan = self._plan_pool(pooled)
        eng = self._pool_engine(plan, resolve_query_engine(
            engine, [q for _, q in pooled]))
        pred = self._predict(plan, eng)
        word_ops = insights.predict_multiset_dispatch_word_ops(
            [b.signature for b in plan.buckets], self._plan_sets(plan),
            eng, pool_rows=plan.n_pool_rows)
        if plan.exprs:
            word_ops += insights.predict_expr_word_ops(
                plan.expr_signature, eng)
        return obs_cost.estimate_seconds(word_ops, pred["peak_bytes"],
                                         SITE, eng)

    def _as_pooled(self, pooled_or_groups):
        seq = list(pooled_or_groups)
        if seq and isinstance(seq[0], (BatchGroup, tuple)) \
                and not (isinstance(seq[0], tuple) and len(seq[0]) == 2
                         and isinstance(seq[0][1],
                                        (BatchQuery, expr_mod.ExprQuery))):
            return self._flatten(seq)[0]
        return tuple(seq)

    def _plan_sets(self, plan: _PoolPlan) -> list:
        """``[(resident kind, n_rows)]`` for every set a plan touches —
        the shared input of the bytes and word-ops footprint models."""
        return [(self._engines[s]._resident_src()[1],
                 self._engines[s]._ds._n_rows) for s in plan.sids]

    def _predict(self, plan: _PoolPlan, eng: str) -> dict:
        out = insights.predict_multiset_dispatch_bytes(
            [b.signature for b in plan.buckets], self._plan_sets(plan),
            eng, pool_rows=plan.n_pool_rows)
        if plan.exprs:
            e = insights.predict_expr_dispatch_bytes(
                plan.expr_signature, eng)
            out["expr_bytes"] = e["peak_bytes"]
            out["peak_bytes"] += e["peak_bytes"]
        return out

    # ------------------------------------------------------------ programs

    def _program(self, plan: _PoolPlan, eng: str, donate: bool = False):
        """AOT-compiled pooled program: per-tenant rebuild + concat + all
        buckets, ONE device dispatch.  ``donate=True`` (pipelined path on
        donation-capable backends) marks the bucket-scratch argument
        donated, so launch k's dead arrays back launch k+1's buffers —
        such a program must be fed FRESH uploads, never the cached plan
        arrays."""
        donate = donate and _donation_supported()
        # referenced residents' structure versions are part of the sig:
        # a structural repack changes their image/stream shapes, and a
        # row_sel/bucket-identical plan must not hit a program compiled
        # against the old operand shapes (mutation.delta)
        sig = (eng, plan.signature, donate,
               tuple((self._engines[s]._ds.uid,
                      self._engines[s]._ds.structure_version)
                     for s in plan.sids))
        if eng == "megakernel":
            sig = sig + (plan.mega.signature,)
        t_get = time.perf_counter()
        cached = self._programs.get(sig)
        if cached is not None:
            obs_cost.observe_compile(SITE, "hit",
                                     time.perf_counter() - t_get)
            return cached
        engines = [self._engines[s] for s in plan.sids]
        srcs = [e._resident_src() for e in engines]
        kinds = [k for _, k in srcs]
        b_sigs = [b.signature for b in plan.buckets]
        g_sigs = [g.sig for g in plan.op_groups]
        fused = plan.fused
        expr_bis = expr_mod.expr_bucket_ids(fused)
        group_force = [any(bi in expr_bis for bi in g.bucket_idx)
                       for g in plan.op_groups]

        with obs_slo.phase("program_build"), \
                obs_trace.span("multiset.program_build", engine=eng,
                               sets=len(engines), buckets=len(b_sigs),
                               donate=donate, exprs=len(fused)) as sp:
            def pooled_words(src_list, sel_list):
                # per-tenant image -> referenced-row selection -> pooled
                # concat: the transient image is the pool's true row
                # footprint, not the tenants' padded residents
                rows = [e._words_from_src(s, k, eng)[sel]
                        for e, s, k, sel in zip(engines, src_list, kinds,
                                                sel_list)]
                return (rows[0] if len(rows) == 1
                        else jnp.concatenate(rows, axis=0))

            if eng == "megakernel":
                mega = plan.mega

                def run(src_list, sel_list, arrays, cols):
                    # one-kernel hot path over the pooled image: every
                    # bucket's reduce + the fused combines + outputs in
                    # one pallas grid kernel (ops.megakernel); the
                    # bucket gathers were offset-remapped into the
                    # pooled row space at plan time
                    words = pooled_words(src_list, sel_list)
                    return megakernel.eval_full(mega, words, arrays[0],
                                                cols=cols)
            elif eng == "xla-vmap":
                # unmerged per-bucket cross-check path: proves the op
                # merge and the query-axis flattening equivalent
                def run(src_list, sel_list, arrays, cols):
                    words = pooled_words(src_list, sel_list)
                    outs, heads_by_bi = [], [None] * len(b_sigs)
                    for bi, (s, a) in enumerate(zip(b_sigs,
                                                    arrays[:len(b_sigs)])):
                        heads, cards = bucket_body(
                            words, s, a, eng,
                            force_heads=bi in expr_bis)
                        heads_by_bi[bi] = heads
                        outs.append((heads if s[5] else None, cards))
                    if not fused:
                        return outs
                    return outs, expr_mod.eval_sections(
                        fused, arrays[len(b_sigs):], words, heads_by_bi,
                        cols_list=cols)
            else:
                def run(src_list, sel_list, arrays, cols):
                    words = pooled_words(src_list, sel_list)
                    outs, group_heads = [], []
                    for gi, (s, a) in enumerate(zip(g_sigs,
                                                    arrays[:len(g_sigs)])):
                        heads, cards = _op_body(
                            words, s, a, eng,
                            force_heads=group_force[gi])
                        group_heads.append((heads, cards))
                        outs.append((heads if s[4] else None, cards))
                    if not fused:
                        return outs
                    bucket_heads = expr_mod.traced_bucket_heads(
                        plan.buckets, plan.op_groups, group_heads,
                        live_ok=(eng != "pallas"))
                    return outs, expr_mod.eval_sections(
                        fused, arrays[len(g_sigs):], words, bucket_heads,
                        cols_list=cols)

            jit_kw = {"donate_argnums": (2,)} if donate else {}
            # donate-variant lowering traces against avals only: caching
            # operand arrays here would pin HBM that donating dispatches
            # never read (they always re-upload), and uploading throwaway
            # twins just to trace shapes would pay the transfer per
            # program-cache miss
            operands = (self._operand_avals(plan, eng) if donate
                        else self._launch_operands(plan, eng))
            t0 = time.perf_counter()
            compiled = jax.jit(run, **jit_kw).lower(
                [s for s, _ in srcs],
                [plan.row_sel_dev(s) for s in plan.sids],
                operands, expr_mod.launch_cols(plan.fused)).compile()
            compile_s = time.perf_counter() - t0
            obs_cost.observe_compile(SITE, "miss", compile_s)
            rt_lattice.note_compile(SITE, eng, plan.point, compile_s)
            predicted = self._predict(plan, eng)
            measured = obs_memory.compiled_memory(compiled)
            cost = obs_cost.compiled_cost(compiled)
            sp.tag(predicted_bytes=predicted["peak_bytes"],
                   measured_peak_bytes=(measured or {}).get("peak_bytes"),
                   compile_ms=round(compile_s * 1e3, 2),
                   flops=(cost or {}).get("flops"),
                   bytes_accessed=(cost or {}).get("bytes_accessed"))
            cached = (run, compiled, predicted, measured, cost)
        self._programs.put(sig, cached)
        return cached

    # ------------------------------------------------------------ execution

    def execute(self, groups, engine: str = "auto", jit: bool = True,
                fallback: bool = True,
                policy: guard.GuardPolicy | None = None) -> list:
        """Run a pool of per-set query groups; returns per-group result
        lists aligned with ``groups``.

        One pooled device launch per budget-respecting sub-pool (usually
        one total); multi-launch pools flow through the pipelined
        dispatcher.  Guarded like ``BatchEngine.execute``: per-launch
        engine demotion, reactive OOM halving, proactive HBM-budget
        halving, optional shadow cross-check.  A pool referencing a
        single set routes through that set's ``BatchEngine.execute``
        with zero pooled overhead.
        """
        groups = list(groups)
        pooled, lengths = self._flatten(groups)
        if not pooled:
            return [[] for _ in groups]
        sids = sorted({sid for sid, _ in pooled})
        with obs_trace.span("multiset.execute", site=SITE, q=len(pooled),
                            sets=len(sids), engine=engine,
                            fallback=fallback):
            obs_metrics.counter("rb_multiset_queries_total",
                                site=SITE).inc(len(pooled))
            if len(sids) == 1:
                # S=1 fast path: the single-set engine IS the pooled
                # engine here — no pooled plan, no concat, no new device
                # buffers (regression-pinned via the HBM ledger)
                flat = self._engines[sids[0]].execute(
                    [q for _, q in pooled], engine=engine, jit=jit,
                    fallback=fallback, policy=policy)
                return self._regroup(flat, lengths)
            if not fallback:
                flat = self._launch_once(pooled, engine, jit, inject=False)
                return self._regroup(flat, lengths)
            t_exec0 = time.perf_counter()
            policy = policy or guard.GuardPolicy.from_env()
            budget = guard.resolve_hbm_budget(policy)
            deadline = guard.Deadline(policy.deadline)

            def run_misses(qs):
                qs = tuple(qs)
                chain = guard.chain_from(
                    resolve_query_engine(engine, [q for _, q in qs]),
                    ENGINE_LADDER)
                # one in-budget launch — the steady-state serving tick
                # — is handed to _pipeline as a materialized single so
                # it dispatches sync with the cached operand arrays; a
                # pool the budget WILL split stays a live generator, so
                # launch k+1's halving/planning runs while launch k is
                # on device (the probe's plan is cached and needed
                # either way)
                if (budget is None or len(qs) < 2
                        or self.predict_dispatch_bytes(qs, chain[0])
                        <= budget):
                    launches = [(0, qs)]
                else:
                    launches = ((0, sub) for sub in
                                self._launch_iter(qs, chain[0], budget))
                return self._pipeline(launches, chain, jit, policy,
                                      deadline, budget)[0]

            with obs_slo.query(SITE, deadline_ms=policy.slo_deadline_ms):
                if self.result_cache is not None:
                    # materialized-result reuse across tenants: probe
                    # per (set, query) before planning, pool only the
                    # misses, fill on the way out
                    self._sync_with_sets()
                    flat, _hits = mut_cache.serve_and_fill(
                        self.result_cache, list(pooled),
                        lambda it: self._engines[it[0]]._cache_key_of(
                            it[1]),
                        run_misses, SITE)
                else:
                    flat = run_misses(pooled)
            if not self._first_query_done:
                self._first_query_done = True
                obs_metrics.histogram(
                    "rb_first_query_seconds", site=SITE).observe(
                        time.perf_counter() - t_exec0)
            if policy.shadow_rate > 0.0:
                self._shadow_check(pooled, flat, policy)
            return self._regroup(flat, lengths)

    def execute_pipelined(self, pools, engine: str = "auto",
                          jit: bool = True,
                          policy: guard.GuardPolicy | None = None) -> list:
        """Stream several pools (serving ticks) through ONE pipeline
        window: pool p+1's planning overlaps pool p's device execution
        even when each pool is a single launch.  Returns per-pool lists
        of per-group result lists (``execute``'s shape, one per pool)."""
        pools = [list(p) for p in pools]
        metas = [self._flatten(p) for p in pools]
        policy = policy or guard.GuardPolicy.from_env()
        chain = guard.chain_from(
            resolve_query_engine(
                engine, [q for pooled, _ in metas for _, q in pooled]),
            ENGINE_LADDER)
        budget = guard.resolve_hbm_budget(policy)
        deadline = guard.Deadline(policy.deadline)
        n_sets = len({sid for pooled, _ in metas for sid, _ in pooled})
        with obs_trace.span("multiset.execute", site=SITE,
                            q=sum(len(p) for p, _ in metas),
                            sets=n_sets, engine=engine, pools=len(pools)):
            for pooled, _ in metas:
                obs_metrics.counter("rb_multiset_queries_total",
                                    site=SITE).inc(len(pooled))

            def launches():
                for pi, (pooled, _) in enumerate(metas):
                    if not pooled:
                        continue
                    for qs in self._launch_iter(pooled, chain[0], budget):
                        yield pi, qs

            # one attribution context over the whole streamed window (a
            # per-pool wall cannot be separated once launches overlap)
            with obs_slo.query(SITE, deadline_ms=policy.slo_deadline_ms):
                by_pool = self._pipeline(launches(), chain, jit, policy,
                                         deadline, budget)
            out = []
            for pi, (pooled, lengths) in enumerate(metas):
                flat = by_pool.get(pi, [])
                if policy.shadow_rate > 0.0 and flat:
                    self._shadow_check(pooled, flat, policy)
                out.append(self._regroup(flat, lengths))
            return out

    def _launch_iter(self, pooled, engine: str, budget: int | None):
        """Left-to-right launch partition of ``pooled``, computed LAZILY:
        a sub-pool predicted past the HBM budget is halved here — the
        proactive split, per-pool — and the halving/planning of launch
        k+1 happens only when the pipeline pulls it, i.e. while launch k
        is already on device."""
        stack = [list(pooled)]
        while stack:
            qs = stack.pop()
            while budget is not None and len(qs) >= 2:
                predicted = self.predict_dispatch_bytes(qs, engine)
                if predicted <= budget:
                    break
                mid = (len(qs) + 1) // 2
                self.proactive_split_count += 1
                obs_metrics.counter("rb_multiset_proactive_splits_total",
                                    site=SITE).inc()
                obs_trace.current().event(
                    "proactive_split", site=SITE, q=len(qs),
                    predicted_bytes=predicted, budget_bytes=budget,
                    halves=(mid, len(qs) - mid))
                stack.append(qs[mid:])
                qs = qs[:mid]
            yield tuple(qs)

    def _pipeline(self, launches, chain, jit, policy, deadline,
                  budget) -> dict:
        """Depth-``policy.pipeline_depth`` double buffer over ``launches``
        (an iterator of ``(tag, queries)``): plan/pack/dispatch launch
        k+1 while up to ``depth`` earlier launches are in flight, then
        drain the oldest.  Returns ``{tag: [BatchResult, ...]}`` with
        per-tag pooled order preserved (drains are FIFO).  Host time
        spent planning while >= 1 launch was in flight is the hidden
        fraction the overlap ratio reports."""
        depth = max(1, policy.pipeline_depth)
        # a known single-launch window (plain execute() of an unsplit
        # pool) has nothing to overlap: dispatch it sync with the cached
        # operand arrays rather than paying the async path's donation
        # discipline — fresh operand re-uploads per launch on TPU/GPU
        single = isinstance(launches, (list, tuple)) and len(launches) == 1
        inflight: deque = deque()
        out: dict = {}
        host_ms = overlapped_ms = drain_ms = 0.0
        n_launches = 0      # window slots; device launches come from the
        #                     counter delta (splits add, sequential lands 0)
        launch_counter = obs_metrics.counter("rb_multiset_launches_total",
                                             site=SITE)
        launches0 = launch_counter.value
        # launches-saved baseline: the per-set sequential loop pays one
        # launch per referenced set PER POOL (tag), not per unique tenant
        # across the stream — a 4-tick stream over the same 4 tenants
        # saves 12 launches, not 0
        tag_sids: dict = {}

        def drain():
            nonlocal drain_ms
            tag, qs, payload = inflight.popleft()
            t0 = time.perf_counter()
            if isinstance(payload, list):   # sequential / split-recovered
                res = payload
            else:
                try:
                    # the drain-time fault seam: a deferred device fault
                    # surfaces here, after the dispatching slot already
                    # returned — injected at its own scope so the re-run
                    # semantics are testable at any pipeline depth
                    if payload.inject:
                        faults.maybe_fail(f"{SITE}.drain", payload.eng)
                    res = self._readback(payload.plan, payload.outs,
                                         payload.queries, payload.eng,
                                         payload.inject)
                except Exception as exc:
                    fault = errors.classify(exc)
                    if fault is None or isinstance(fault,
                                                   errors.ShadowMismatch):
                        raise
                    # a deferred device fault surfaced only at drain
                    # time: re-run this launch synchronously down the
                    # guarded ladder (bit-exact on every rung)
                    obs_metrics.counter("rb_multiset_drain_retries_total",
                                        site=SITE).inc()
                    obs_trace.current().event(
                        "drain_retry", site=SITE, q=len(qs),
                        error_class=type(fault).__name__)
                    res, _ = self._launch_guarded(
                        qs, chain, jit, policy, deadline, budget,
                        sync=True)
                else:
                    # drain-time cost attribution: the launch completed
                    # under this drain, so stamp its multiset.cost here,
                    # flagged async=True and pointing at the launch span
                    # (the drain wall includes pipeline queueing, so the
                    # achieved rates are lower bounds)
                    cost_ev = obs_cost.record_dispatch(
                        SITE, payload.eng, payload.cost,
                        time.perf_counter() - t0,
                        est={"flops": payload.word_ops,
                             "bytes_accessed": payload.predicted_peak},
                        q=len(qs), sets=len(payload.plan.sids),
                        **{"async": True,
                           "launch_span_id": payload.span_id})
                    self.last_dispatch_cost = cost_ev
                    obs_trace.current().event("multiset.cost", **cost_ev)
            drain_ms += (time.perf_counter() - t0) * 1e3
            out.setdefault(tag, []).extend(res)

        with obs_trace.span("multiset.pipeline", depth=depth) as sp:
            it = iter(launches)
            while True:
                t0 = time.perf_counter()
                # pulling the iterator runs the NEXT launch's budget
                # halving + planning — host work the window hides
                nxt = next(it, None)
                if nxt is None:
                    break
                tag, qs = nxt
                tag_sids.setdefault(tag, set()).update(
                    sid for sid, _ in qs)
                payload, _rung = self._launch_guarded(
                    qs, chain, jit, policy, deadline, budget, sync=single)
                h = (time.perf_counter() - t0) * 1e3
                host_ms += h
                # overlapped only when a DEVICE launch was actually in
                # flight: a window full of sequential landings or split
                # recoveries (finished lists) hid nothing, and reporting
                # (n-1)/n overlap in a fully degraded process would make
                # the >= 50% acceptance pin read healthy while the
                # pipeline did no pipelining
                if any(isinstance(p, _Inflight) for _, _, p in inflight):
                    overlapped_ms += h
                n_launches += 1
                inflight.append((tag, qs, payload))
                # drain until at most depth-1 stay undrained: depth=1 is
                # strictly serial (dispatch -> immediate drain), depth=2
                # keeps one launch computing while the next is planned
                while len(inflight) >= depth:
                    drain()
            while inflight:
                drain()
            ratio = (overlapped_ms / host_ms) if host_ms else 0.0
            stats = {"launches": n_launches, "depth": depth,
                     "host_ms": round(host_ms, 3),
                     "host_overlapped_ms": round(overlapped_ms, 3),
                     "overlap_ratio": round(ratio, 4),
                     "drain_ms": round(drain_ms, 3)}
            sp.tag(**stats)
        if n_launches > 1:
            # a single-launch window (every plain execute() of an
            # unsplit pool) has no overlap to measure — reporting it
            # would clobber the last real pipelined measurement with ~0
            obs_metrics.gauge("rb_multiset_pipeline_overlap_ratio",
                              site=SITE).set(stats["overlap_ratio"])
            self.last_pipeline = stats
        device_launches = int(launch_counter.value - launches0)
        per_set_baseline = sum(len(s) for s in tag_sids.values())
        # a window that never reached the device (every slot landed on
        # the sequential floor) amortized nothing — the per-set loop
        # would have landed there too, so no launches were "saved"
        obs_metrics.counter("rb_multiset_launches_saved_total",
                            site=SITE).inc(
                                max(0, per_set_baseline - device_launches)
                                if device_launches else 0)
        return out

    def _launch_guarded(self, qs, chain, jit, policy, deadline, budget,
                        sync: bool):
        """One guarded launch of pooled queries ``qs`` down ``chain``.
        ``sync=False`` returns an :class:`_Inflight` handle (async
        dispatch, drained later); sequential landings and OOM-split
        recoveries return finished result lists either way."""

        def attempt(eng):
            return self._launch_once(qs, eng, jit, sync=sync)

        def on_oom(eng, fault, dl):
            if len(qs) < 2:
                return guard.NO_SPLIT
            sub = chain[chain.index(eng):] if eng in chain else chain
            mid = (len(qs) + 1) // 2
            self.split_count += 1
            obs_metrics.counter("rb_multiset_oom_splits_total",
                                site=SITE).inc()
            obs_trace.current().event(
                "oom_split", site=SITE, engine_from=eng, engine_to=eng,
                q=len(qs), halves=(mid, len(qs) - mid))
            return (self._launch_guarded(qs[:mid], sub, jit, policy, dl,
                                         budget, sync=True)[0]
                    + self._launch_guarded(qs[mid:], sub, jit, policy, dl,
                                           budget, sync=True)[0])

        return guard.run_with_fallback(
            SITE, chain, attempt, policy=policy,
            sequential=lambda: self._sequential(qs),
            on_resource_exhausted=on_oom, deadline=deadline)

    def _launch_once(self, pooled, engine: str, jit: bool,
                     inject: bool = True, sync: bool = True):
        """Raw single-engine pooled launch: plan -> one compiled program
        -> (host assembly | in-flight handle).  The faults hook sits at
        the engine boundary like BatchEngine's."""
        pooled = tuple(pooled)
        plan = self._plan_pool(pooled)
        eng = self._pool_engine(plan, engine)
        obs_slo.note_engine(eng)
        if inject:
            faults.maybe_fail(SITE, eng)
        donate = (not sync) and _donation_supported()
        run, compiled, predicted, measured, cost = self._program(
            plan, eng, donate=donate)
        srcs = [self._engines[s]._resident_src()[0] for s in plan.sids]
        sels = [plan.row_sel_dev(s) for s in plan.sids]
        barrays = self._launch_operands(plan, eng, fresh=donate)
        with obs_trace.span("multiset.dispatch", engine=eng,
                            q=len(pooled), sets=len(plan.sids),
                            buckets=len(plan.buckets),
                            pipelined=not sync) as sp:
            t_launch = time.perf_counter()
            with obs_slo.phase("dispatch"):
                outs = (compiled if jit else run)(
                    srcs, sels, barrays, expr_mod.launch_cols(plan.fused))
            # counted HERE, not per pipeline-window slot: an OOM-split
            # slot dispatches 2+ real launches, a sequential landing
            # dispatches none — the counter must track what actually
            # reached the device (docs/OBSERVABILITY.md)
            obs_metrics.counter("rb_multiset_launches_total",
                                site=SITE).inc()
            if plan.exprs:
                expr_mod.record_fused_dispatch(SITE, plan.exprs)
                expr_mod.record_analytics_dispatch(SITE, plan.exprs, sp)
            if eng == "megakernel":
                sp.event("expr.megakernel", **plan.mega.stats_event())
            if sync:
                with obs_slo.phase("sync"):
                    outs = sp.sync(outs)
                    outs = jax.block_until_ready(outs)
            mem = obs_memory.record_dispatch(
                SITE, predicted["peak_bytes"], measured)
            mem["engine"], mem["q"] = eng, len(pooled)
            mem["sets"] = len(plan.sids)
            if plan.point is not None:
                pb, pf = plan.padding
                mem["lattice_padding_bytes"] = int(pb)
                mem["lattice_padding_fraction"] = round(pf, 6)
                rt_lattice.record_padding(SITE, int(pb), pf)
            self.last_dispatch_memory = mem
            sp.event("multiset.memory", **mem)
            word_ops = insights.predict_multiset_dispatch_word_ops(
                [b.signature for b in plan.buckets],
                self._plan_sets(plan), eng,
                pool_rows=plan.n_pool_rows)
            if plan.exprs:
                word_ops += insights.predict_expr_word_ops(
                    plan.expr_signature, eng)
            if sync:
                # sync launches have a device-complete wall right here;
                # async (pipelined) launches finish at drain time, where
                # drain() stamps the same event flagged async=True
                cost_ev = obs_cost.record_dispatch(
                    SITE, eng, cost, time.perf_counter() - t_launch,
                    est={"flops": word_ops,
                         "bytes_accessed": predicted["peak_bytes"]},
                    q=len(pooled), sets=len(plan.sids))
                self.last_dispatch_cost = cost_ev
                sp.event("multiset.cost", **cost_ev)
        if not sync:
            return _Inflight(plan=plan, outs=outs, queries=pooled,
                             eng=eng, inject=inject,
                             span_id=sp.span_id, cost=cost,
                             word_ops=float(word_ops),
                             predicted_peak=int(predicted["peak_bytes"]))
        return self._readback(plan, outs, pooled, eng, inject)

    def _launch_operands(self, plan: _PoolPlan, eng: str,
                         fresh: bool = False) -> list:
        """The program's bucket-operand argument: per-op superbucket
        arrays normally, per-bucket arrays on the unmerged xla-vmap
        cross-check path.  Either way only the keys ``_op_body`` reads
        for this engine ship (``_op_group_keys``): donating launches
        upload the subset per launch, the sync path uploads it once and
        caches it per keyset."""
        if eng == "megakernel":
            return [plan.mega.device_arrays(fresh=fresh)]
        if eng == "xla-vmap":
            arrays = [b.device_arrays(fresh=fresh) for b in plan.buckets]
        else:
            arrays = [g.device_arrays(fresh=fresh,
                                      keys=_op_group_keys(g, eng))
                      for g in plan.op_groups]
        arrays.extend(s.device_arrays(fresh=fresh) for s in plan.fused)
        return arrays

    def _operand_avals(self, plan: _PoolPlan, eng: str) -> list:
        """ShapeDtypeStruct pytree matching the DONATE-variant
        ``_launch_operands(fresh=True)`` — what donate lowering traces
        against, so no device array is uploaded just to be thrown away
        after the trace (and the donated pytree carries only the keys
        the program reads)."""
        aval = lambda v: jax.ShapeDtypeStruct(
            v.shape, jax.dtypes.canonicalize_dtype(v.dtype))
        if eng == "megakernel":
            return [{k: aval(v) for k, v in plan.mega.host.items()}]
        if eng == "xla-vmap":
            avals = [{k: aval(v) for k, v in b.host.items()}
                     for b in plan.buckets]
        else:
            avals = [{k: aval(g.host[k]) for k in _op_group_keys(g, eng)}
                     for g in plan.op_groups]
        avals.extend({k: aval(v) for k, v in s.host.items()}
                     for s in plan.fused)
        return avals

    def _bucket_outputs(self, plan: _PoolPlan, outs, eng: str):
        """Normalize program outputs to per-bucket (bucket, heads,
        cards) host arrays — op superbuckets slice their members out of
        the flat head axis."""
        if eng in ("xla-vmap", "megakernel"):
            # both return per-BUCKET outputs already (the megakernel's
            # output layout slices per bucket, not per op group)
            for b, (heads, cards) in zip(plan.buckets, outs):
                yield (b, None if heads is None else np.asarray(heads),
                       np.asarray(cards))
            return
        for grp, (heads_f, cards_f) in zip(plan.op_groups, outs):
            heads_f = None if heads_f is None else np.asarray(heads_f)
            cards_f = np.asarray(cards_f)
            live = grp.regular and eng != "pallas"
            for bi, s0 in zip(grp.bucket_idx, grp.seg_offs):
                b = plan.buckets[bi]
                if live:
                    # regular-path outputs carry one LIVE slot per query
                    # (k_pad == 1, no dead pad slots — see _op_body)
                    s0, n = s0 // 2, b.q
                    cards = cards_f[s0:s0 + n].reshape(b.q, 1)
                    heads = (None if heads_f is None else
                             heads_f[s0:s0 + n].reshape(b.q, 1, WORDS32))
                else:
                    n = b.q * (b.k_pad + 1)
                    cards = cards_f[s0:s0 + n].reshape(
                        b.q, b.k_pad + 1)[:, :b.k_pad]
                    heads = (None if heads_f is None else
                             heads_f[s0:s0 + n].reshape(
                                 b.q, b.k_pad + 1, WORDS32)[:, :b.k_pad])
                yield b, heads, cards

    def _readback(self, plan: _PoolPlan, outs, pooled, eng: str,
                  inject: bool) -> list:
        """Device outputs -> per-query BatchResults in pooled order."""
        if plan.fused:
            outs, expr_outs = outs
        else:
            expr_outs = []
        with obs_slo.phase("readback"), \
                obs_trace.span("multiset.readback", engine=eng,
                               q=len(pooled)):
            # the owner map is required whenever the plan carries
            # owner-less pseudo slots: expression reduce nodes AND the
            # lattice's dead op buckets (their pids have no query)
            results = assemble_pooled_results(
                self._bucket_outputs(plan, outs, eng), pooled,
                plan.rb_meta,
                owner=(plan.owner if (plan.exprs
                                      or plan.point is not None)
                       else None))
            expr_mod.assemble_section_results(
                plan.exprs, expr_outs, results,
                lambda qid: pooled[qid][1].form)
        if inject and faults.should_corrupt(SITE, eng):
            results[0] = BatchResult(
                cardinality=results[0].cardinality + 1,
                bitmap=results[0].bitmap)
        return results

    # ----------------------------------------------- CPU sequential rung

    def _sequential(self, pooled) -> list:
        """Terminal fallback: each query on its own set's host container
        algebra — the bit-exact reference every pooled rung is pinned
        against (aggregate roots through the host BSI/RangeBitmap
        oracle, like BatchEngine's floor)."""
        return [self._engines[sid]._sequential_result(q)
                for sid, q in pooled]

    def _shadow_check(self, pooled, results, policy) -> None:
        idx = guard.shadow_sample(len(pooled), policy.shadow_rate,
                                  policy.shadow_seed, SITE)
        for i in idx:
            sid, q = pooled[i]
            ref = self._engines[sid]._sequential_result(q)
            got = results[i]
            bad = (got.cardinality != ref.cardinality
                   or got.value != ref.value)
            if not bad and q.form == "bitmap":
                bad = got.bitmap != ref.bitmap
            if bad:
                raise errors.ShadowMismatch(
                    f"multiset query {i} ({query_desc(q)} on set "
                    f"{sid}) diverged from the sequential reference: got "
                    f"cardinality {got.cardinality}/value {got.value}, "
                    f"want {ref.cardinality}/{ref.value}")

    # --------------------------------------------------------- conveniences

    def cardinalities(self, groups, engine: str = "auto") -> list:
        """Per-group i64 arrays of result cardinalities."""
        return [np.array([r.cardinality for r in rows], dtype=np.int64)
                for rows in self.execute(groups, engine=engine)]

    def _compile_lattice_points(self, lat, engine: str) -> int:
        """Compile the POOLED half of the lattice vocabulary: each flat
        point pins a representative two-tenant mini-pool (single-tenant
        pools route through the per-set engines, warmed separately), so
        the compiled program carries the point's padded bucket shapes,
        the all-sets operand arity, and the pinned pooled-row rung.
        Expression shape-classes compile their representative DAGs;
        delta rungs pre-compile every tenant's patch programs."""
        if self.n_sets < 2:
            return 0
        points = lat.enumerate_points(pooled=True)
        self._programs.maxsize = max(self._programs.maxsize,
                                     2 * len(points) + 8)
        compiled = 0
        for point in points:
            if point.delta:
                for e in self._engines:
                    e._ds.warmup_delta(point.delta)
                compiled += 1
                continue
            if point.bsi:
                # analytics shape-classes warm per tenant through the
                # adopted engines (the S=1 route the loop above took);
                # pooled analytics pools additionally warm here for
                # tenant 0's columns
                from .batch_engine import analytics_rung_queries

                batches = analytics_rung_queries(
                    getattr(self._engines[0]._ds, "columns", {}),
                    point.bsi, self._engines[0].n)
                with lat.pin(point):
                    for batch in batches:
                        pooled, _ = self._flatten(
                            [BatchGroup(0, batch)])
                        plan = self._plan_pool(pooled)
                        for sec in plan.exprs:
                            lat.note_expr(sec.signature)
                        eng = self._pool_engine(plan, engine)
                        self._program(plan, eng)
                        # Megakernel v2: warm the one-kernel analytics
                        # rung too — the resident queue serves sealed
                        # points from this cache and must never compile
                        mega_eng = self._pool_engine(plan, "megakernel")
                        if mega_eng == "megakernel" \
                                and eng != "megakernel":
                            self._program(plan, mega_eng)
                compiled += 1
                continue
            if point.expr:
                # expressions sized PER TENANT: a non-first tenant may
                # hold fewer residents than set 0, and its refs must
                # stay in its own operand range
                pool = [BatchGroup(0, expr_mod.rung_expressions(
                            point.expr, self._engines[0].n)),
                        BatchGroup(1, expr_mod.rung_expressions(
                            point.expr, self._engines[1].n)[:1])]
            else:
                pool = [BatchGroup(0, [BatchQuery(op, (0,))
                                       for op in point.ops]),
                        BatchGroup(1, [BatchQuery(point.ops[0], (0,))])]
            pooled, _ = self._flatten(pool)
            with lat.pin(point):
                plan = self._plan_pool(pooled)
                for sec in plan.exprs:
                    lat.note_expr(sec.signature)
                eng = self._pool_engine(plan, engine)
                self._program(plan, eng)
                if _donation_supported():
                    self._program(plan, eng, donate=True)
                mega_eng = self._pool_engine(plan, "megakernel")
                if mega_eng == "megakernel" and eng != "megakernel":
                    self._program(plan, mega_eng)
            compiled += 1
        return compiled

    def _warmup_lattice(self, profile, engine: str,
                        cache_dir: str | None) -> dict:
        """``warmup(profile=...)`` over the pooled engine: activate the
        lattice, warm every adopted per-set engine's vocabulary (the
        S=1 execute route), warm the pooled vocabulary, seal."""
        t0 = time.perf_counter()
        lat = rt_lattice.activate(profile)
        with obs_trace.span("lattice.warmup", site=SITE,
                            points=lat.n_points(pooled=True),
                            profile=lat.to_profile()) as sp:
            compiled = 0
            for e in self._engines:
                compiled += e._compile_lattice_points(lat, engine)
            compiled += self._compile_lattice_points(lat, engine)
            lat.seal()
            sp.tag(compiled=compiled, sealed=True)
        return {"site": SITE, "compile_cache_dir": cache_dir,
                "lattice": {"profile": lat.to_profile(),
                            "points": lat.n_points(pooled=True),
                            "compiled": compiled, "sealed": True},
                "programs": [],
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    def warmup(self, rungs=(1, 2, 4, 8),
               ops=("or", "and", "xor", "andnot"),
               engine: str = "auto", pools=None, profile=None) -> dict:
        """Pre-compile pooled programs for known pow2 operand rungs (one
        pool per rung: every tenant contributes each op over its first
        ``rung`` residents), or for explicit ``pools=`` (the exact
        serving shapes — those then hit the plan AND program caches on
        their first real execute).  A pool referencing one set warms
        that set's single-set engine instead, matching the S=1 execute
        route.  Compile-only; see ``BatchEngine.warmup``.

        ``profile=`` switches to the closed-lattice boot path
        (docs/LATTICE.md): per-set AND pooled vocabularies pre-compile,
        then the lattice seals — steady state compiles nothing."""
        cache_dir = rt_warmup.enable_compile_cache()
        if profile is not None:
            return self._warmup_lattice(profile, engine, cache_dir)
        t0 = time.perf_counter()
        programs = []
        if pools is None:
            pools = []
            for r in rungs:
                kind, n = expr_mod.parse_warmup_rung(r)
                if kind == "delta":
                    # mutation patch-program rung: one per tenant, so
                    # no tenant's first in-band apply_delta compiles
                    for e in self._engines:
                        rep = e._ds.warmup_delta(n)
                        programs.append({"delta_rung": n,
                                         "engine": "mutation",
                                         "compiled": rep["compiled"]})
                    continue
                pools.append([
                    BatchGroup(sid,
                               expr_mod.rung_expressions(n, e.n)
                               if kind == "expr"
                               else e._rung_queries(n, ops))
                    for sid, e in enumerate(self._engines)])
        for pool in pools:
            pooled, _ = self._flatten(list(pool))
            if not pooled:
                continue
            sids = sorted({sid for sid, _ in pooled})
            if len(sids) == 1:
                rep = self._engines[sids[0]].warmup(
                    queries=[q for _, q in pooled], engine=engine)
                programs.extend(rep["programs"])
                continue
            plan = self._plan_pool(pooled)
            eng = self._pool_engine(plan, engine)
            engs = [eng]
            mega_eng = self._pool_engine(plan, "megakernel")
            if mega_eng == "megakernel" and eng != "megakernel":
                # expression pools warm the one-kernel TOP rung too, so
                # a serving loop requesting it never compiles in-band
                engs.append(mega_eng)
            for e in engs:
                self._program(plan, e)
                if _donation_supported():
                    # the pipelined dispatcher compiles the DONATE
                    # variant (a distinct program-cache key): warm it
                    # too, or the first serving tick pays the compile
                    # warmup exists to remove
                    self._program(plan, e, donate=True)
                programs.append({"q": len(pooled), "sets": len(sids),
                                 "buckets": len(plan.buckets),
                                 "engine": e})
        return {"site": SITE, "compile_cache_dir": cache_dir,
                "programs": programs,
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    def count_cache_hits(self, pooled_or_groups) -> int:
        """How many of a pool's queries the materialized result cache
        would serve right now — count-free (``would_hit``), so the
        serving loop's execute-time predictor can scale a cache-hit
        pool's estimate down without skewing the hit/miss metrics."""
        if self.result_cache is None:
            return 0
        pooled = self._as_pooled(pooled_or_groups)
        n = 0
        for sid, q in pooled:
            key, _leaves, form = self._engines[sid]._cache_key_of(q)
            if self.result_cache.would_hit(key, form):
                n += 1
        return n

    def cache_stats(self) -> dict:
        """Pooled plan/program cache observability + the split counters
        (same shape as ``BatchEngine.cache_stats``)."""
        return {"plans": self._plans.stats(),
                "programs": self._programs.stats(),
                "splits": self.split_count}

    def hbm_bytes(self) -> int:
        return sum(e.hbm_bytes() for e in self._engines)


def random_multiset_pool(set_sizes: list, q: int, seed: int = 0x5E75,
                         max_operands: int = 8) -> list:
    """Deterministic pooled workload: ``q`` mixed-op queries dealt
    round-robin over ``len(set_sizes)`` tenants (set ``i`` holding
    ``set_sizes[i]`` resident bitmaps) — the shared generator of the
    bench multiset lane and the acceptance tests."""
    rng = np.random.default_rng(seed)
    per_set: list = [[] for _ in set_sizes]
    for i in range(q):
        sid = i % len(set_sizes)
        n = set_sizes[sid]
        # op drawn independently of the round-robin tenant index: i % 4
        # would correlate with sid whenever gcd(S, 4) > 1, making every
        # tenant's sub-batch op-homogeneous — the per-set baseline's
        # cheapest case — instead of the mixed-op workload this claims
        op = ("or", "xor", "and", "andnot")[int(rng.integers(4))]
        hi = max(3, min(max_operands + 1, n))
        k = int(rng.integers(2, hi)) if n >= 3 else 2
        per_set[sid].append(BatchQuery(op=op, operands=tuple(
            int(x) for x in rng.choice(n, size=min(k, n), replace=False))))
    return [BatchGroup(sid, qs) for sid, qs in enumerate(per_set) if qs]
