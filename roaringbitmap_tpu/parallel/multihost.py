"""Multi-host bootstrap for the sharded engine (SURVEY §2.7 / brief:
"distributed comm backend that scales to multi-host").

The reference's only parallelism is a single-JVM ForkJoinPool; this
framework's scale-out axis is a jax.sharding.Mesh, and every sharded entry
point (parallel.sharding.wide_aggregate_sharded, ShardedBSI,
ShardedRangeBitmap) takes an arbitrary mesh.  This module provides the two
pieces a multi-host deployment needs around those entry points:

- ``initialize()`` — jax.distributed.initialize wrapper (the NCCL/MPI-rank
  analog: one process per host, a coordinator address, and a process id).
- ``global_mesh()`` — a (rows, lanes) mesh over ALL hosts' devices, laid
  out so the row axis (the ppermute OR/XOR butterfly — the heavy,
  accumulator-sized traffic) stays within each host's ICI domain and the
  lane axis (the final cardinality psum — scalars per key) is the axis
  that crosses DCN.  Collectives ride ICI where the bytes are.

On a single host both degenerate to the local mesh the tests and dryrun
use, so the same program text runs from one chip to a multi-host pod —
that is the whole point of expressing the backend as mesh + collectives
instead of explicit rank-to-rank sends.
"""

from __future__ import annotations

import inspect
import os
import socket
import time

import numpy as np

#: default bound on the coordinator handshake, seconds (overridable per
#: call); a missing peer must become a typed CoordinatorTimeout, not an
#: indefinite hang in jax.distributed.initialize
ENV_COORD_TIMEOUT = "ROARING_TPU_COORD_TIMEOUT_S"
DEFAULT_COORD_TIMEOUT = 120.0

#: last bootstrap's observable state (obs.snapshot()'s "multihost"
#: section): coordinator address, process id, the pre-flight TCP probe's
#: latency, and the outcome — a SLOW coordinator is visible here (and on
#: the rb_multihost_probe_seconds gauge) before it ever times out
_STATE: dict = {}


def snapshot() -> dict:
    """The last ``initialize`` attempt's state as plain JSON ({} when
    never called): coordinator, process_id, probe_ms (pre-flight TCP
    probe latency — the slow-coordinator early warning), timeout_s,
    status ("probing" / "initializing" / "initialized" / "failed"),
    and process_count once joined."""
    return dict(_STATE)


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               timeout: float | None = None) -> None:
    """Join (or bootstrap) the multi-host runtime.

    No-arg form uses the cluster environment (TPU pod metadata / launcher
    env vars), matching jax.distributed.initialize's auto-detection; the
    explicit form mirrors an MPI-style rank launch.  Call once per
    process, before any backend use.  Single-process runs may skip this
    entirely.

    ``timeout`` (default ``ROARING_TPU_COORD_TIMEOUT_S``, 120 s) bounds
    the coordinator handshake: one budget shared by the pre-flight TCP
    probe (non-coordinator ranks with an explicit address) and jax's own
    ``initialization_timeout``.  An unreachable coordinator or a gRPC
    deadline raises ``runtime.errors.CoordinatorTimeout`` naming the
    coordinator address and process id instead of a raw gRPC traceback.
    Other failures (bad arguments, double initialization) propagate
    unchanged.  (On jax builds without ``initialization_timeout`` the
    probe is the only typed protection — the C++ coordination client
    aborts the process on its own internal deadline, so no Python-side
    watchdog can bound the handshake once it is entered.)
    """
    from ..obs import trace as obs_trace
    from ..runtime import errors, faults

    if timeout is None:
        timeout = float(os.environ.get(ENV_COORD_TIMEOUT,
                                       DEFAULT_COORD_TIMEOUT))

    def describe() -> str:
        return (f"coordinator {coordinator_address or '<auto-detected>'}, "
                f"process_id {process_id if process_id is not None else '<auto>'}")

    deadline = time.monotonic() + timeout
    _STATE.clear()
    _STATE.update(coordinator=coordinator_address or "<auto-detected>",
                  process_id=process_id, timeout_s=timeout,
                  probe_ms=None, status="probing")
    with obs_trace.span(
            "multihost.initialize",
            coordinator=coordinator_address or "<auto-detected>",
            process_id=process_id if process_id is not None else "<auto>",
            timeout_s=timeout):
        try:
            faults.maybe_fail("multihost", "coordinator")
            if coordinator_address and process_id not in (None, 0):
                # pre-flight TCP probe with retry-until-deadline: XLA's
                # coordination client LOG(FATAL)s the whole process when
                # its own handshake deadline fires, so an unreachable
                # coordinator must be detected BEFORE the C++ client is
                # entered — that is the only place a typed Python error
                # can still be raised
                _probe_coordinator(coordinator_address, timeout, deadline,
                                   describe, errors)
            import jax

            _STATE["status"] = "initializing"
            # the handshake gets whatever the probe left of the ONE budget
            remaining = max(deadline - time.monotonic(), 1.0)
            kw = {}
            params = inspect.signature(
                jax.distributed.initialize).parameters
            if "initialization_timeout" in params:
                # jax enforces the bound itself: the clean path — the
                # connect loop gives up and raises instead of retrying
                # forever
                kw["initialization_timeout"] = max(int(remaining), 1)
                jax.distributed.initialize(coordinator_address,
                                           num_processes, process_id, **kw)
            else:
                # old jax without the knob: call directly.  A watchdog
                # thread would be worse than nothing — the abandoned C++
                # coordination client LOG(FATAL)s the whole process when
                # ITS handshake deadline fires, after the caller already
                # got a typed error and kept serving.  Without the knob,
                # the pre-flight probe above is the only typed-timeout
                # protection.
                jax.distributed.initialize(coordinator_address,
                                           num_processes, process_id)
            _STATE.update(status="initialized",
                          process_count=int(jax.process_count()))
        except errors.CoordinatorTimeout:
            _STATE["status"] = "failed"
            raise
        except Exception as exc:
            _STATE["status"] = "failed"
            fault = errors.classify(exc)
            if isinstance(fault, (errors.CoordinatorTimeout,
                                  errors.TransientDeviceError)):
                raise errors.CoordinatorTimeout(
                    f"multihost.initialize: {describe()} unreachable "
                    f"within {timeout:g}s: {exc}") from exc
            raise


def _probe_coordinator(address: str, timeout: float, deadline: float,
                       describe, errors) -> None:
    """Block until a TCP connection to the coordinator succeeds or the
    deadline (shared with the handshake stage) passes, raising a typed
    CoordinatorTimeout.  Retries with backoff: the coordinator process
    may legitimately bind a moment after its peers launch, exactly like
    jax's own connect loop."""
    host, _, port_s = address.rpartition(":")
    host = host.strip("[]")   # bracketed IPv6 literals ([::1]:8476)
    if not host or not port_s.isdigit():
        return  # unparseable (unix socket, exotic scheme): let jax try
    from ..obs import metrics as obs_metrics

    t0 = time.monotonic()
    delay = 0.1
    while True:
        budget = deadline - time.monotonic()
        try:
            with socket.create_connection((host, int(port_s)),
                                          timeout=max(0.1, min(2.0, budget))):
                probe_s = time.monotonic() - t0
                # the slow-coordinator early warning: a probe that took
                # most of its budget predicts a handshake that will too
                _STATE["probe_ms"] = round(probe_s * 1e3, 3)
                obs_metrics.gauge("rb_multihost_probe_seconds").set(
                    probe_s)
                return
        except OSError as exc:
            if time.monotonic() >= deadline:
                _STATE["probe_ms"] = round(
                    (time.monotonic() - t0) * 1e3, 3)
                raise errors.CoordinatorTimeout(
                    f"multihost.initialize: {describe()} unreachable "
                    f"within {timeout:g}s: {exc}") from exc
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2.0, 2.0)


def global_mesh(lanes: int | None = None,
                row_axis: str = "rows", lane_axis: str = "lanes"):
    """A (rows, lanes) mesh over every device of every participating host.

    Device placement: each mesh COLUMN (a fixed lane, all rows) is filled
    with devices of a single process wherever the factorization allows,
    grouping by ``device.process_index`` rather than trusting global
    device-id order (which interleaves hosts on some TPU topologies).  The
    row axis carries the ppermute butterfly — accumulator-sized traffic
    that should ride intra-host ICI — while the lane axis (scalar
    cardinality psums) is the one that crosses hosts/DCN.  The default row
    length is the largest power of two dividing every process's local
    device count, making host-pure columns by construction; an explicit
    ``lanes`` that forces rows to span hosts is honored (the user asked
    for it), falling back to process-ordered placement.  Row length must
    be a power of two (the butterfly pairs partners by XOR).
    """
    import jax
    from jax.sharding import Mesh

    arr = _arrange(jax.devices(), lanes)
    return Mesh(arr, (row_axis, lane_axis))


def _arrange(devices, lanes: int | None) -> np.ndarray:
    """Pure placement: (rows, lanes) object array per global_mesh's
    contract — host-pure row columns whenever the factorization allows."""
    n = len(devices)
    by_proc: dict[int, list] = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    local_counts = [len(v) for v in by_proc.values()]
    if lanes is None:
        rows = 1 << (min(local_counts).bit_length() - 1)
        while rows > 1 and any(lc % rows for lc in local_counts):
            rows >>= 1
        lanes = n // rows
    if lanes < 1 or n % lanes:
        raise ValueError(
            f"lane axis {lanes} does not divide the {n} global devices")
    rows = n // lanes
    if rows & (rows - 1):
        raise ValueError(
            f"row axis {rows} (= {n} devices / {lanes} lanes) must be a "
            "power of two: the bitwise reduce butterfly pairs partners by "
            "XOR; pick a different lane count")
    if all(lc % rows == 0 for lc in local_counts):
        # host-pure columns: chunk each process's devices into row groups
        cols = []
        for pid in sorted(by_proc):
            ds = by_proc[pid]
            cols.extend(ds[i:i + rows] for i in range(0, len(ds), rows))
        arr = np.empty((lanes, rows), dtype=object)
        for j, col in enumerate(cols):
            arr[j, :] = col
        return arr.T
    # explicit lanes forcing rows to straddle hosts (the user asked)
    ordered = [d for pid in sorted(by_proc) for d in by_proc[pid]]
    arr = np.empty((lanes, rows), dtype=object)
    for j in range(lanes):
        arr[j, :] = ordered[j * rows:(j + 1) * rows]
    return arr.T
