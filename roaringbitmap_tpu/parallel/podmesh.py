"""Pod-scale topology + tenant placement for the multi-host data plane.

Everything through the sharded engine fits one process; "millions of
users" does not.  This module is the topology half of the pod serving
stack (ROADMAP item 2; ``serving.frontdoor`` is the traffic half): it
joins ``multihost.initialize``'s process bootstrap with the
``ShardedBatchEngine``'s mesh execution model, and decides **where
tenants live**.

Topology
--------
A :class:`PodMesh` is an ordered list of hosts, each owning a device
group.  Two construction modes, one vocabulary:

- **detected** (``PodMesh.detect()`` after ``multihost.initialize``):
  one host per jax process, devices grouped by ``process_index`` — the
  real pod.  Only the local host's devices are addressable; global
  arrays are placed with :func:`global_put` (each host feeds exactly its
  addressable shard — the pjit multi-process model, PAPERS.md §2).
- **simulated** (``PodMesh.simulate(n)``): the visible devices are
  partitioned into ``n`` host groups — the CPU dry-run twin, same
  program text, used by the tests/bench/CI lanes exactly like PR 7's
  virtual 8-device mesh.  ``ROARING_TPU_POD_HOSTS`` sets the default
  simulated host count.

``host_mesh(h)`` is one host's (rows x data) mesh; ``pod_mesh()`` spans
every alive host (the capacity regime's mesh).  Collective dispatch over
a detected multi-process mesh needs a backend with cross-process
collectives (TPU pods; the CPU backend refuses — see
:func:`supports_pod_dispatch`), so on the CI proxy the pod-spanning mesh
is exercised through the simulated pod and the real-pod capture rides
the standing TPU debt (docs/POD.md).

Placement
---------
The container-partitioned layout (PAPERS.md [1]) is what makes placement
cheap: a tenant is a contiguous block of 8 KiB rows, so it moves,
replicates, and routes as a unit.  :func:`place` extends PR 7's
``placement="auto"`` two-regime split with a third regime, per tenant:

========================= =============================================
regime                    meaning
========================= =============================================
``sharded``               capacity: the tenant's rows split across ALL
                          hosts (the pod-spanning ShardedBatchEngine,
                          ``placement="sharded"``) — bigger than one
                          host's comfortable share
``replicated-N``          throughput: a hot small tenant holds a full
                          copy on N hosts, any of which serves it
                          locally; N scales with its observed
                          query-rate share (serving metrics)
``local``                 the default: one host, chosen by greedy
                          least-loaded byte balancing
========================= =============================================

The decision inputs are the HBM ledger / guard budget (per-host bytes)
and the ``insights`` footprint model (``plan_pod_placement`` holds the
pure math); the resulting :class:`PlacementPlan` is deterministic, and
routing over it is **consistent**: :func:`route` rendezvous-hashes the
tenant over its placement hosts, so losing a host only moves that
host's tenants (docs/POD.md "Routing").

Observability: ``pod.place`` spans, ``rb_pod_tenants{regime}`` /
``rb_pod_placement_bytes{host}`` / ``rb_pod_hosts`` metrics; the
front door adds the routing/reroute vocabulary.
"""

from __future__ import annotations

import dataclasses
import os
import zlib

import numpy as np

from ..insights import analysis as insights
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

#: the trace/metric site of pod placement + routing
SITE = "pod"

ENV_POD_HOSTS = "ROARING_TPU_POD_HOSTS"
ENV_REPLICATE_MAX = "ROARING_TPU_POD_REPLICATE_MAX"
ENV_HOT_SHARE = "ROARING_TPU_POD_HOT_SHARE"

#: tenants larger than this never replicate (per-copy cost); also the
#: capacity-regime threshold when no per-host budget resolves — the
#: same 64 MiB knee the sharded engine's placement="auto" uses
REPLICATE_MAX_BYTES = 64 << 20

#: a tenant whose query-rate share is >= HOT_SHARE_X times the uniform
#: share reads hot (replication candidate)
HOT_SHARE_X = 2.0


@dataclasses.dataclass(frozen=True)
class HostInfo:
    """One pod host: a device group owned by one process (detected) or
    one slice of the visible devices (simulated)."""

    host_id: int
    process_index: int
    devices: tuple
    #: True when this process can address the host's devices (always in
    #: a simulated pod; exactly one host in a detected pod)
    local: bool


class PodMesh:
    """Ordered host list + liveness, the pod's topology handle.

    Liveness is advisory (the front door marks hosts down on classified
    host-loss faults and routing skips them); ``mark_up`` restores a
    recovered host.  Meshes are built on demand from the CURRENT alive
    set, so a pod-spanning mesh after a host loss covers the survivors.
    """

    def __init__(self, hosts: list, local_host: int = 0):
        if not hosts:
            raise ValueError("a pod needs at least one host")
        self.hosts = list(hosts)
        self.local_host = int(local_host)
        self._down: set = set()

    # ------------------------------------------------------- construction

    @classmethod
    def detect(cls, n_hosts: int | None = None) -> "PodMesh":
        """The runtime's pod: one host per jax process when
        ``multihost.initialize`` ran (devices grouped by
        ``process_index``), else a simulated pod over the visible
        devices (``n_hosts``, default ``ROARING_TPU_POD_HOSTS`` or 2)."""
        import jax

        if jax.process_count() > 1:
            by_proc: dict[int, list] = {}
            for d in jax.devices():
                by_proc.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            hosts = [HostInfo(h, pid, tuple(by_proc[pid]),
                              local=(pid == jax.process_index()))
                     for h, pid in enumerate(sorted(by_proc))]
            local = next(h.host_id for h in hosts if h.local)
            return cls(hosts, local_host=local)
        if n_hosts is None:
            n_hosts = int(os.environ.get(ENV_POD_HOSTS, "2"))
        return cls.simulate(n_hosts)

    @classmethod
    def simulate(cls, n_hosts: int, devices=None) -> "PodMesh":
        """An in-process pod: the visible devices partitioned into
        ``n_hosts`` contiguous groups (every host addressable — the CPU
        dry-run twin of a detected pod)."""
        import jax

        devices = list(devices if devices is not None else jax.devices())
        n_hosts = int(n_hosts)
        if n_hosts < 1 or n_hosts > len(devices):
            raise ValueError(
                f"cannot simulate {n_hosts} hosts over {len(devices)} "
                f"devices")
        per = len(devices) // n_hosts
        hosts = [HostInfo(h, 0, tuple(devices[h * per:(h + 1) * per]),
                          local=True)
                 for h in range(n_hosts)]
        return cls(hosts, local_host=0)

    # ------------------------------------------------------------ liveness

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    def alive(self) -> tuple:
        return tuple(h.host_id for h in self.hosts
                     if h.host_id not in self._down)

    def is_alive(self, host_id: int) -> bool:
        return host_id not in self._down

    def mark_down(self, host_id: int) -> None:
        self._down.add(int(host_id))
        obs_flight.record("host_down", site=SITE, host=str(host_id),
                          alive=len(self.alive()))
        self._push_gauges()

    def mark_up(self, host_id: int) -> None:
        self._down.discard(int(host_id))
        self._push_gauges()

    def join_host(self, devices=None) -> int:
        """Elasticity: append one host to the pod and return its id.

        In a simulated pod the new host shares the trailing device
        group (simulation models topology + routing, not extra silicon
        — the CPU proxy's devices are interchangeable anyway).  A
        detected multi-process pod cannot grow in place — jax pins the
        process set at initialize — so joining there is typed refusal,
        not a silent no-op."""
        if any(not h.local for h in self.hosts):
            raise ValueError(
                "cannot join_host into a detected multi-process pod: "
                "the jax process set is fixed at initialize() — "
                "restart the pod with the new host enrolled")
        if devices is None:
            devices = self.hosts[-1].devices
        new_id = max(h.host_id for h in self.hosts) + 1
        self.hosts.append(HostInfo(new_id, 0, tuple(devices),
                                   local=True))
        self._push_gauges()
        return new_id

    def _push_gauges(self) -> None:
        obs_metrics.gauge("rb_pod_hosts", state="alive").set(
            len(self.alive()))
        obs_metrics.gauge("rb_pod_hosts", state="down").set(
            len(self._down))

    # -------------------------------------------------------------- meshes

    def host_mesh(self, host_id: int, specs=None, data: int = 1):
        """One host's (rows x data) mesh over its own device group —
        what a per-host sharded engine runs on."""
        from .sharded_engine import default_mesh

        return default_mesh(list(self.hosts[host_id].devices),
                            data=data,
                            **({"specs": specs} if specs else {}))

    def pod_mesh(self, specs=None, data: int = 1):
        """The pod-spanning (rows x data) mesh over every ALIVE host's
        devices, host-major ordered so each host's rows are contiguous
        along the row axis (the butterfly's heavy traffic stays
        host-pure wherever the factorization allows, the
        ``multihost.global_mesh`` argument)."""
        from .sharded_engine import default_mesh

        devices = [d for h in self.hosts
                   if h.host_id not in self._down for d in h.devices]
        return default_mesh(devices, data=data,
                            **({"specs": specs} if specs else {}))

    def snapshot(self) -> dict:
        return {"n_hosts": self.n_hosts,
                "alive": list(self.alive()),
                "down": sorted(self._down),
                "local_host": self.local_host,
                "devices_per_host": [len(h.devices) for h in self.hosts],
                "multi_process": any(not h.local for h in self.hosts)}


def supports_pod_dispatch() -> bool:
    """Whether the backend can EXECUTE computations over a multi-process
    mesh.  Single-process pods (simulated, or one-host detected) always
    can; multi-process pods need cross-process collectives, which the
    CPU backend does not implement ("Multiprocess computations aren't
    implemented on the CPU backend") — there the capacity regime
    demotes typed to per-host placement and the real pod-spanning
    dispatch rides the standing TPU debt (docs/POD.md)."""
    import jax

    if jax.process_count() <= 1:
        return True
    return jax.default_backend() not in ("cpu",)


def global_put(arr, sharding):
    """Place a host array under ``sharding`` across the pod: plain
    ``device_put`` in a single process; in a multi-process pod each host
    feeds exactly its ADDRESSABLE shards via
    ``jax.make_array_from_callback`` (the pjit multi-process note —
    no host ever materializes another host's slice on device)."""
    import jax

    if jax.process_count() <= 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


# ------------------------------------------------------------- placement

@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """One deterministic tenant->host assignment: ``regimes[sid]`` is
    ``"sharded"`` / ``"replicated-N"`` / ``"local"``; ``hosts[sid]`` the
    host ids holding that tenant (all hosts for the sharded regime)."""

    regimes: tuple
    hosts: tuple
    bytes_per_host: tuple
    over_budget: bool = False
    capacity_threshold: int = 0
    #: capacity tenants demoted to local because the backend cannot
    #: dispatch over a multi-process mesh (CPU pod; typed, never silent)
    demoted_capacity: tuple = ()

    @property
    def n_tenants(self) -> int:
        return len(self.regimes)

    def hosts_of(self, sid: int) -> tuple:
        return self.hosts[sid]

    def regime(self, sid: int) -> str:
        return self.regimes[sid]

    def sharded_sids(self) -> tuple:
        return tuple(s for s, r in enumerate(self.regimes)
                     if r == "sharded")

    def regime_counts(self) -> dict:
        out: dict = {}
        for r in self.regimes:
            key = r.split("-")[0]
            out[key] = out.get(key, 0) + 1
        return out

    def table(self) -> dict:
        """The routing table as plain JSON (snapshot / docs)."""
        return {str(s): {"regime": self.regimes[s],
                         "hosts": list(self.hosts[s])}
                for s in range(self.n_tenants)}


def tenant_bytes_of(sets) -> list:
    """Per-tenant resident footprint, bytes — the insights model's
    component walk over each resident set (``DeviceBitmapSet`` /
    ``BatchEngine`` accepted)."""
    out = []
    for s in sets:
        ds = getattr(s, "_ds", s)
        out.append(int(sum(insights.resident_set_bytes(ds).values())))
    return out


def place(sets, pod: PodMesh, budget_per_host: int | None = None,
          qps=None, replicate_max_bytes: int | None = None,
          hot_share_x: float | None = None) -> PlacementPlan:
    """Plan tenant placement over ``pod`` from the footprint model + the
    per-host HBM budget + optional per-tenant query rates (the serving
    metrics feed; ``None`` = no rate data, nothing replicates).

    ``budget_per_host`` defaults to the guard's resolved HBM budget
    (``ROARING_TPU_HBM_BUDGET`` / backend free memory); the pure
    decision math is ``insights.plan_pod_placement``.  Emits the
    ``pod.place`` span + ``rb_pod_*`` placement metrics."""
    from ..runtime import guard

    if budget_per_host is None:
        budget_per_host = guard.resolve_hbm_budget()
    if replicate_max_bytes is None:
        replicate_max_bytes = int(os.environ.get(
            ENV_REPLICATE_MAX, REPLICATE_MAX_BYTES))
    if hot_share_x is None:
        hot_share_x = float(os.environ.get(ENV_HOT_SHARE, HOT_SHARE_X))
    t_bytes = tenant_bytes_of(sets)
    with obs_trace.span("pod.place", site=SITE, hosts=pod.n_hosts,
                        tenants=len(t_bytes)) as sp:
        raw = insights.plan_pod_placement(
            t_bytes, pod.n_hosts, budget_per_host=budget_per_host,
            qps=qps, replicate_max_bytes=replicate_max_bytes,
            hot_share_x=hot_share_x)
        regimes = list(raw["regimes"])
        hosts = [tuple(h) for h in raw["hosts"]]
        demoted = []
        loads = [int(b) for b in raw["bytes_per_host"]]
        if "sharded" in regimes and not supports_pod_dispatch():
            # a CPU multi-process pod cannot dispatch the pod-spanning
            # mesh: demote capacity tenants to local placement, typed —
            # they still serve (one host each), they just cannot span
            for sid, r in enumerate(regimes):
                if r != "sharded":
                    continue
                share = t_bytes[sid] // pod.n_hosts
                loads = [b - share for b in loads]
                anchor = min(range(pod.n_hosts), key=lambda h: loads[h])
                loads[anchor] += t_bytes[sid]
                regimes[sid] = "local"
                hosts[sid] = (anchor,)
                demoted.append(sid)
        plan = PlacementPlan(
            regimes=tuple(regimes), hosts=tuple(hosts),
            bytes_per_host=tuple(loads),
            over_budget=bool(raw["over_budget"]),
            capacity_threshold=int(raw["capacity_threshold"]),
            demoted_capacity=tuple(demoted))
        counts = plan.regime_counts()
        for regime in ("sharded", "replicated", "local"):
            obs_metrics.gauge("rb_pod_tenants", regime=regime).set(
                counts.get(regime, 0))
        for h, b in enumerate(plan.bytes_per_host):
            obs_metrics.gauge("rb_pod_placement_bytes",
                              host=str(h)).set(b)
        pod._push_gauges()
        sp.tag(regimes=counts, over_budget=plan.over_budget,
               capacity_threshold=plan.capacity_threshold,
               bytes_per_host=list(plan.bytes_per_host),
               demoted_capacity=len(demoted))
    return plan


# --------------------------------------------------------------- routing

def route(plan: PlacementPlan, sid: int, alive, salt: int = 0,
          overrides: dict | None = None) -> int | None:
    """Consistent tenant routing: the rendezvous (highest-random-weight)
    winner among the tenant's ALIVE placement hosts.  Deterministic
    across processes (same plan + alive set => same answer everywhere —
    the property that lets every host route without coordination), and
    consistent under host loss: removing a host only re-routes the
    tenants that host was serving.  ``None`` when no placement host is
    alive (the front door's single-host demotion case).

    ``overrides`` (sid -> host_id) is the live-migration flip map
    (serving.migration): an alive override wins over the rendezvous
    draw, so flipping one tenant's route is one dict write — no plan
    rebuild on the admission path — and a dead override falls back to
    rendezvous (the migration target dying mid-window degrades through
    the normal ladder, never strands the tenant)."""
    if overrides:
        ov = overrides.get(sid)
        if ov is not None and ov in set(alive):
            return ov
    alive = set(alive)
    candidates = [h for h in plan.hosts_of(sid) if h in alive]
    if not candidates:
        return None
    return max(candidates,
               key=lambda h: (zlib.crc32(f"{sid}/{h}/{salt}".encode()),
                              -h))
