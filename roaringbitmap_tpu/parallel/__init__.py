from . import (aggregation, batch_engine, expr, multiset, podmesh,
               sharded_engine, sharding)
from .aggregation import DeviceBitmapSet
from .batch_engine import BatchEngine, BatchQuery, BatchResult
from .expr import ExprQuery
from .multiset import BatchGroup, MultiSetBatchEngine
from .podmesh import PlacementPlan, PodMesh
from .sharded_engine import ShardedBatchEngine, default_mesh
from .sharding import SPECS, SpecLayout

__all__ = ["aggregation", "batch_engine", "expr", "multiset", "podmesh",
           "sharded_engine", "sharding", "DeviceBitmapSet", "BatchEngine",
           "BatchQuery", "BatchResult", "BatchGroup", "ExprQuery",
           "MultiSetBatchEngine", "ShardedBatchEngine", "default_mesh",
           "SPECS", "SpecLayout", "PodMesh", "PlacementPlan"]
