from . import aggregation, sharding
from .aggregation import DeviceBitmapSet

__all__ = ["aggregation", "sharding", "DeviceBitmapSet"]
