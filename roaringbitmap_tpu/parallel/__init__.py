from . import aggregation, batch_engine, multiset, sharding
from .aggregation import DeviceBitmapSet
from .batch_engine import BatchEngine, BatchQuery, BatchResult
from .multiset import BatchGroup, MultiSetBatchEngine

__all__ = ["aggregation", "batch_engine", "multiset", "sharding",
           "DeviceBitmapSet", "BatchEngine", "BatchQuery", "BatchResult",
           "BatchGroup", "MultiSetBatchEngine"]
