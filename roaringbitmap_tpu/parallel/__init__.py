from . import aggregation, batch_engine, sharding
from .aggregation import DeviceBitmapSet
from .batch_engine import BatchEngine, BatchQuery, BatchResult

__all__ = ["aggregation", "batch_engine", "sharding", "DeviceBitmapSet",
           "BatchEngine", "BatchQuery", "BatchResult"]
