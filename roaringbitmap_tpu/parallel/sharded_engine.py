"""Mesh-sharded pooled batch serving: the Batch/MultiSet engines over a
device mesh (ROADMAP item 1).

``wide_aggregate_sharded`` sharded ONE wide op; the production path —
``BatchEngine`` / ``MultiSetBatchEngine`` pooled mixed-op query batches —
stayed single-chip.  This module closes that gap: the pooled packed-row
tensors are placed with ``jax.sharding.NamedSharding`` over a 2-D mesh and
one pooled launch spans the whole slice, buying the two scalings the
single-device engines cannot reach:

- **tenants bigger than one chip's HBM**: the pooled resident image
  shards over the ``rows`` axis (``SpecLayout.pooled_rows``), so a
  resident set's 8 KiB/container rows divide across devices;
- **near-linear QPS on replicated small pools**: a launch's transient
  gathered rows spread over ``rows x data`` jointly
  (``SpecLayout.gather_rows``), so every device carries a slice of the
  pool's row work — and because each query's rows are contiguous in the
  flat gather, sharding the row axis effectively partitions *queries*
  across devices.

Execution model
---------------
Planning is the pooled planner unchanged one level down: per-set row
selection (``BatchEngine._plan_query``), global pooled-row offsets,
``plan_bucket`` shape bucketing, and the per-op superbucket merge
(``multiset._merge_op_groups``) — the sharded engine adds only a flat-row
pad to a device-count multiple (padding rows carry the per-op identity
and a dead segment id).  Each op group then runs as:

1. ONE gather from the rows-sharded pooled image (cross-shard, GSPMD);
2. a ``shard_map`` shard-local segmented reduce: the flat rows are
   globally sorted by segment, so each shard's doubling pass reduces its
   contiguous runs and scatters per-segment heads into a full
   identity-initialized accumulator — segments absent from a shard hold
   the identity, segments straddling a shard boundary hold partials;
3. the cross-shard combine: a log2(D) ``ppermute`` butterfly per mesh
   axis (bitwise ops are outside XLA's psum vocabulary — same reasoning
   as ``parallel.sharding``), after which every device holds the exact
   reduction;
4. the per-op post passes (presence/keep masks, andnot head pass,
   popcount) on the replicated head axis.

Everything compiles AOT under the mesh (``jit -> lower -> compile``), so
every cached program carries ``memory_analysis()`` / ``cost_analysis()``
like the PR 4/6 engines; on donation-capable backends the per-launch
bucket scratch uploads fresh and is donated (the PR 5 discipline — CPU
ignores donation, so the dry-run path keeps cached uploads).

Guard & budget integration
--------------------------
Every launch rides ``guard.run_with_fallback`` down the
``mesh -> single -> sequential`` ladder: a classified mesh fault demotes
to the un-sharded pooled engine (``MultiSetBatchEngine`` over the same
adopted ``BatchEngine`` instances — zero re-packing), and from there to
the host sequential reference; every rung is bit-exact.  The HBM budget
is per-DEVICE (each chip protects its own allocator): the proactive
split halves the pool while the **per-shard** predicted transient
(``insights.predict_sharded_dispatch_bytes``) exceeds the budget, so a
D-row mesh admits ~D× the pooled bytes before splitting — the
single-device engine at the same budget proactively splits several times
more (tests/test_sharded_engine.py pins the ratio).

Observability: ``sharded.*`` spans mirror the multiset vocabulary;
every dispatch span carries a ``batch.shard`` event keyed by the mesh
shape (tools/check_trace.py pins the schema), ``sharded.memory`` /
``sharded.cost`` events carry per-shard predictions and a mesh-scaled
roofline, and ``rb_shard_balance{site,mesh}`` gauges max/mean per-shard
resident bytes (1.0 = perfectly balanced row distribution).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..insights import analysis as insights
from ..obs import cost as obs_cost
from ..obs import memory as obs_memory
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace
from ..ops import dense, megakernel, packing
from ..runtime import faults, guard
from ..runtime import lattice as rt_lattice
from ..runtime import warmup as rt_warmup
from ..runtime.cache import LRUCache
from . import expr as expr_mod
from . import podmesh
from .aggregation import DeviceBitmapSet
from .batch_engine import (PLAN_CACHE_MAX, PROGRAM_CACHE_MAX, WORDS32,
                           _RED_OP, BatchEngine, BatchQuery, plan_bucket,
                           plan_padding, query_desc, snap_plan_groups)
from .multiset import (BatchGroup, MultiSetBatchEngine, _donation_supported,
                       _merge_op_groups, assemble_pooled_results)
from .sharding import SPECS, SpecLayout, _butterfly_combine, _intern_mesh, \
    shard_map

#: the guard/trace/metric site of every mesh-sharded dispatch
SITE = "sharded_engine"

#: the sharded fallback ladder (guard appends the sequential reference):
#: a mesh fault demotes to the un-sharded pooled engine, never to a
#: half-dead mesh
ENGINE_LADDER = (guard.MESH, guard.SINGLE_DEVICE)


def default_mesh(devices=None, data: int = 1,
                 specs: SpecLayout = SPECS) -> Mesh:
    """A (rows x data) mesh over the largest power-of-two prefix of the
    available devices: the ppermute butterfly pairs partners by XOR, so
    both axis sizes must be powers of two (same constraint as
    ``dryrun_multichip``)."""
    devices = list(devices if devices is not None else jax.devices())
    if data < 1 or data & (data - 1):
        raise ValueError(f"data axis size must be a power of two: {data}")
    if len(devices) < data:
        raise ValueError(
            f"data axis size {data} needs at least {data} devices, got "
            f"{len(devices)}")
    rows = 1
    while rows * 2 * data <= len(devices):
        rows *= 2
    use = np.array(devices[:rows * data]).reshape(rows, data)
    return _intern_mesh(Mesh(use, (specs.row_axis, specs.data_axis)))


def _check_mesh(mesh: Mesh, specs: SpecLayout) -> Mesh:
    for axis in (specs.row_axis, specs.data_axis):
        if axis not in mesh.axis_names:
            raise ValueError(
                f"sharded engine mesh needs a {axis!r} axis, got "
                f"{mesh.axis_names}")
        n = mesh.shape[axis]
        if n & (n - 1):
            raise ValueError(
                f"mesh axis {axis!r} size must be a power of two for the "
                f"ppermute butterfly combine, got {n}")
    return _intern_mesh(mesh)


@dataclasses.dataclass
class _ShardedPlan:
    """One mesh-sharded pooled plan: the multiset shape buckets + per-op
    superbuckets, plus each group's device-count-padded flat operands
    (padding rows index pool row 0, are masked invalid, and carry the
    group's dead segment id ``nseg``)."""

    buckets: list
    op_groups: list
    sids: tuple
    padded: list          # per group: {key: np array} device-pad layout
    n_pads: tuple         # per group: padded flat row count
    #: fused expression sections (parallel.expr) + expanded-slot owner
    exprs: list = dataclasses.field(default_factory=list)
    owner: dict = dataclasses.field(default_factory=dict)
    rb_meta: dict = dataclasses.field(default_factory=dict)
    #: combine-mode one-kernel program (ops.megakernel.build_combines):
    #: the fused combine passes run as ONE pallas grid kernel on the
    #: replicated post-butterfly side; None when absent or past budget
    mega: object = None
    #: covering lattice point (runtime.lattice) when an active lattice
    #: snapped this plan; None = exact shapes
    point: object = None
    #: (padding_bytes, padded_fraction) of the snap
    padding: tuple = (0, 0.0)
    _arrays: list | None = None   # device twins, uploaded lazily
    _mega_arrays: dict | None = None

    @property
    def fused(self) -> list:
        return expr_mod.fused_of(self.exprs)

    @property
    def expr_signature(self) -> tuple:
        return expr_mod.signature_of(self.exprs)

    @property
    def signature(self):
        # the sharded pool image is the FULL placed concat, so gathers
        # are global rows and the program never depends on WHICH tenants
        # a pool references — under a lattice the tenant mix therefore
        # drops out of the signature (the snapped shapes already close
        # every operand dimension); exact plans keep it conservatively
        return (self.sids if self.point is None else ("lattice",),
                self.n_pads,
                tuple(g.sig for g in self.op_groups),
                self.expr_signature)


class ShardedBatchEngine:
    """Plan + execute mixed-op query pools over S resident sets, one
    pooled launch spanning a device mesh.

    ``sets`` may mix ``DeviceBitmapSet`` and ``BatchEngine`` instances
    (adopted, like ``MultiSetBatchEngine``); a bare single set is
    accepted too.  ``mesh`` defaults to :func:`default_mesh` over every
    visible device; both axes must be power-of-two sized.  The pooled
    resident image is placed ONCE at construction, sharded over the
    ``rows`` axis — compact/counts tenants are densified through their
    own engines' resident path first (the sharded pool is a dense row
    image; their host-side sets keep their layouts for the fallback
    rungs).
    """

    def __init__(self, sets, mesh: Mesh | None = None,
                 placement: str = "auto", specs: SpecLayout = SPECS,
                 result_cache="env"):
        rt_warmup.enable_compile_cache()   # ROARING_TPU_COMPILE_CACHE
        if isinstance(sets, (DeviceBitmapSet, BatchEngine)):
            sets = [sets]
        if placement not in ("auto", "sharded", "replicated"):
            raise ValueError(f"unknown pool placement {placement!r}")
        self._specs = specs
        self._mesh = (_check_mesh(mesh, specs) if mesh is not None
                      else default_mesh(specs=specs))
        self.mesh_shape = (int(self._mesh.shape[specs.row_axis]),
                           int(self._mesh.shape[specs.data_axis]))
        self.mesh_devices = self.mesh_shape[0] * self.mesh_shape[1]
        self._mesh_label = f"{self.mesh_shape[0]}x{self.mesh_shape[1]}"
        #: the single-device demotion rung AND the sequential/shadow
        #: reference: the un-sharded pooled engine over the SAME adopted
        #: BatchEngine instances (shared caches, zero re-packing); the
        #: materialized result cache is shared through it too
        self._single = MultiSetBatchEngine(sets, result_cache=result_cache)
        self._engines = self._single._engines
        self.n_sets = len(self._engines)
        self.result_cache = self._single.result_cache
        self._requested_placement = placement
        self._ledger_handle = None
        self._pool_patch_fn = None
        self._place_pool(placement)
        self._plans = LRUCache(PLAN_CACHE_MAX, name="sharded_plans")
        self._programs = LRUCache(PROGRAM_CACHE_MAX,
                                  name="sharded_programs")
        self.split_count = 0            # reactive (ResourceExhausted)
        self.proactive_split_count = 0  # per-shard HBM-budget halvings
        self.last_dispatch_memory: dict | None = None
        self.last_dispatch_cost: dict | None = None
        self._first_query_done = False

    @classmethod
    def from_bitmap_sets(cls, bitmap_sets: list, mesh: Mesh | None = None,
                         layout: str = "auto", **kw) -> "ShardedBatchEngine":
        return cls([DeviceBitmapSet(b, layout=layout, **kw)
                    for b in bitmap_sets], mesh=mesh)

    # -------------------------------------------------------- pool placement

    #: "auto" placement replicates the pooled image while its per-device
    #: copy stays under this many bytes (64 MiB): a replicated pool makes
    #: every launch's gather SHARD-LOCAL (the only collective left is the
    #: butterfly combine), which is the throughput-replication regime —
    #: row-sharding is the capacity regime for pools past one chip's HBM,
    #: where the cross-shard gather is the price of residency at all.
    REPLICATE_MAX_BYTES = 64 << 20

    @staticmethod
    def _aligned_bases(rows: list, rows_per_shard0: int, r_axis: int):
        """Tenant-aligned row layout for the sharded placement: per-
        tenant base offsets such that no tenant smaller than a row
        shard straddles a shard boundary (a tenant's delta patch is
        then a ONE-shard write — PR 7's named debt).  Tenants larger
        than a shard necessarily span, but still start shard-aligned.
        Grows rows_per_shard until the greedy first-fit layout fits the
        row axis; alignment padding rows stay zero (the reduce
        identity), like round_blocks padding one level down."""
        u = max(1, int(rows_per_shard0))
        while True:
            bases, cur = [], 0
            for n in rows:
                if n and (cur % u) and ((cur % u) + n > u or n > u):
                    cur = -(-cur // u) * u      # advance to a boundary
                bases.append(cur)
                cur += n
            if cur <= u * r_axis:
                return bases, u
            u = -(-cur // r_axis)

    def _place_pool(self, placement: str) -> None:
        """Concatenate every tenant's dense row image and place it over
        the mesh: ``sharded`` = rows over the ``rows`` axis (replicated
        along ``data``) — per-device residency 1/mesh_rows of the pool,
        tenant blocks shard-ALIGNED (``_aligned_bases``) so a tenant's
        delta patch lands in one row shard; ``replicated`` = full copy
        per device — shard-local gathers; ``auto`` = replicate small
        pools (REPLICATE_MAX_BYTES), shard big ones.  One-time ingest
        cost, accounted by the HBM ledger (kind="sharded_pool") at
        mesh-total bytes; ``shard_balance`` = max/mean live rows per
        row-shard (1.0 when replicated)."""
        rows_axis = self.mesh_shape[0]
        self._rows = [int(e._row_src.size) for e in self._engines]
        total = sum(self._rows)
        if placement == "auto":
            placement = ("replicated"
                         if total * insights.ROW_BYTES
                         <= self.REPLICATE_MAX_BYTES else "sharded")
        self.placement = placement
        if placement == "sharded":
            bases, u = self._aligned_bases(
                self._rows, -(-max(total, 1) // rows_axis), rows_axis)
            padded = u * rows_axis
        else:
            bases = np.concatenate(
                ([0], np.cumsum(self._rows)[:-1])).astype(np.int64)
            padded = max(rows_axis, -(-total // rows_axis) * rows_axis)
        end = (int(bases[-1]) + self._rows[-1]) if self._rows else 0
        self._base = np.concatenate(
            (np.asarray(bases, np.int64), [end]))
        img = np.zeros((padded, WORDS32), np.uint32)
        live = np.zeros((padded,), bool)
        for e, b in zip(self._engines, self._base[:-1]):
            n = int(e._row_src.size)
            if n:
                img[int(b):int(b) + n] = np.asarray(
                    e._ds._resident_words("xla"), dtype=np.uint32)
                live[int(b):int(b) + n] = True
        self.pool_rows_live = total
        self.pool_rows = padded
        #: a guaranteed-dead pooled row (alignment/round padding), the
        #: idempotent scatter target of delta-patch padding; -1 when the
        #: image is exactly full
        dead = np.flatnonzero(~live)
        self._pool_pad_row = int(dead[0]) if dead.size else -1
        self._pool_spec = (self._specs.pooled_rows()
                           if placement == "sharded"
                           else self._specs.combined_heads())
        self._pool_patch_fn = None     # re-jit against the new spec
        # global placement: device_put in one process; on a detected
        # multi-process pod each host feeds exactly its ADDRESSABLE
        # shards (podmesh.global_put / make_array_from_callback — the
        # pjit multi-process model, docs/POD.md)
        self.pool_words = podmesh.global_put(
            img, NamedSharding(self._mesh, self._pool_spec))
        #: the mutation watermark per tenant: value deltas replay from
        #: each set's journal (one-shard writes); structural repacks
        #: re-place the whole pool (_sync_pool)
        self._placed_versions = [e._ds.version for e in self._engines]
        self._placed_structures = [e._ds.structure_version
                                   for e in self._engines]
        if placement == "sharded":
            rps = padded // rows_axis
            per_shard = np.bincount(
                np.flatnonzero(live) // rps, minlength=rows_axis)
            mean = float(per_shard.mean()) if total else 1.0
            self.shard_balance = (float(per_shard.max()) / mean
                                  if mean > 0 else 1.0)
            # pooled_rows() = P(rows, None): each row-shard REPLICATES
            # along the data axis, so the mesh holds data_size copies of
            # the pool — the ledger must count what the devices hold
            ledger_bytes = (padded * insights.ROW_BYTES
                            * self.mesh_shape[1])
        else:
            self.shard_balance = 1.0
            ledger_bytes = padded * insights.ROW_BYTES * self.mesh_devices
        obs_metrics.gauge("rb_shard_balance", site=SITE,
                          mesh=self._mesh_label).set(self.shard_balance)
        if self._ledger_handle is not None:
            # re-place (mutation escalation): the old registration must
            # not double-count under the new image
            obs_memory.LEDGER.release(self._ledger_handle)
        self._ledger_handle = obs_memory.LEDGER.register(
            "sharded_pool", "dense", ledger_bytes, owner=self)

    # --------------------------------------------------- mutation sync

    def _sync_pool(self) -> None:
        """Bring the placed pool copy up to date with member-set
        mutations: value-only deltas replay from each set's bounded
        journal as in-place pooled patches (tenant-aligned => one-shard
        writes); a structural repack, or a journal that has already
        dropped the needed entries, re-places the pool wholesale."""
        stale = False
        for i, e in enumerate(self._engines):
            ds = e._ds
            if ds.structure_version != self._placed_structures[i]:
                stale = True
                break
            if ds.version == self._placed_versions[i]:
                continue
            if ds._journal_dropped_version > self._placed_versions[i]:
                # journal lag: the bounded delta journal dropped entries
                # this pool still needed — the silent-overflow cause is
                # now counted + traced so capacity tuning can see it
                # (ROARING_TPU_DELTA_JOURNAL vs mutation rate)
                obs_metrics.counter(
                    "rb_sharded_journal_overflows_total",
                    site=SITE).inc()
                obs_trace.current().event(
                    "sharded.journal_overflow", site=SITE, tenant=i,
                    placed_version=int(self._placed_versions[i]),
                    dropped_through=int(ds._journal_dropped_version),
                    version=int(ds.version))
                stale = True
                break
            if jax.process_count() > 1:
                # a detected multi-process pod: the in-place patch
                # program cannot take host-local operands — re-place
                # wholesale (each host feeds its addressable shard)
                stale = True
                break
        if stale:
            self._single._sync_with_sets()
            self._place_pool(self._requested_placement)
            return
        for i, e in enumerate(self._engines):
            ds = e._ds
            if ds.version == self._placed_versions[i]:
                continue
            for ver, rows, add_m, rem_m in ds._delta_journal:
                if ver <= self._placed_versions[i]:
                    continue
                self._patch_pool(int(self._base[i])
                                 + rows.astype(np.int64), add_m, rem_m)
            self._placed_versions[i] = ds.version

    def _patch_pool(self, rows, add_m, rem_m) -> None:
        """One in-place patch of the placed pool image — the pooled twin
        of ``mutation.delta._patch_rows``'s discipline (donated image,
        pow2 rung padding against a dead row, add/remove planes stacked
        into ONE upload), with the sharding preserved (out_shardings
        pins the pooled spec)."""
        p = int(rows.size)
        if self._pool_pad_row >= 0:
            from ..ops import packing

            p_pad = packing.next_pow2(max(1, p))
            if p_pad != p:
                rows_p = np.full(p_pad, self._pool_pad_row, np.int64)
                rows_p[:p] = rows
                add_p = np.zeros((p_pad, WORDS32), np.uint32)
                add_p[:p] = add_m
                rem_p = np.zeros((p_pad, WORDS32), np.uint32)
                rem_p[:p] = rem_m
                rows, add_m, rem_m = rows_p, add_p, rem_p
        if self._pool_patch_fn is None:
            sharding = NamedSharding(self._mesh, self._pool_spec)

            def patch(words, r, masks):
                cur = words[r]
                return words.at[r].set(
                    (cur | masks[:, 0]) & ~masks[:, 1])

            # donate the old pool: the patch is an in-place row write,
            # not a whole-pool copy (mutation.delta's discipline; the
            # engine reassigns pool_words on every call)
            self._pool_patch_fn = jax.jit(patch, donate_argnums=(0,),
                                          out_shardings=sharding)
        self.pool_words = self._pool_patch_fn(
            self.pool_words, jnp.asarray(rows.astype(np.int32)),
            jnp.asarray(np.stack((add_m, rem_m), axis=1)))
        obs_metrics.counter("rb_sharded_pool_patches_total", site=SITE,
                            mesh=self._mesh_label).inc()
        obs_trace.current().event(
            "mutation.pool_patch", site=SITE, rows=p,
            mesh=list(self.mesh_shape), placement=self.placement)

    @property
    def sets(self) -> list:
        return [e._ds for e in self._engines]

    def hbm_bytes(self) -> int:
        """Mesh-total resident bytes of the pooled image: sharded
        placement holds 1/mesh_rows per row-shard, replicated along the
        data axis (mesh-total = data_size copies); replicated placement
        holds a full copy per device."""
        per = self.pool_rows * insights.ROW_BYTES
        return per * (self.mesh_devices
                      if self.placement == "replicated"
                      else self.mesh_shape[1])

    # ------------------------------------------------------------- planning

    def _normalize(self, groups_or_queries):
        """Accept MultiSet-style groups OR a bare BatchQuery list (single
        tenant sugar).  Returns (groups, bare) where bare=True means the
        caller gets a flat result list back."""
        seq = list(groups_or_queries)
        if seq and isinstance(seq[0], BatchQuery):
            return [BatchGroup(0, seq)], True
        return seq, False

    def _plan(self, pooled) -> _ShardedPlan:
        self._sync_pool()
        lat = rt_lattice.active()
        sids = tuple(sorted({sid for sid, _ in pooled}))
        # referenced tenants' mutation versions key the plan: value
        # patches keep row placement (gathers are global rows) but may
        # have served cached-subtree injections whose leaf versions
        # moved; structural repacks re-lay rows outright
        key = (tuple(pooled),
               tuple((self._engines[s]._ds.uid,
                      self._engines[s]._ds.version) for s in sids),
               tuple(self._engines[s]._columns_token() for s in sids),
               rt_lattice.plan_token())
        cached = self._plans.get(key)
        if cached is not None:
            return cached
        with obs_slo.phase("plan"), \
                obs_trace.span("sharded.plan", q=len(pooled),
                               sets=len(sids), mesh=self._mesh_label) as sp:
            groups: dict = {}
            owner: dict = {}
            sections: list = []
            counter = [0]

            def add_item(sid, pq, own):
                pid = counter[0]
                counter[0] += 1
                eng = self._engines[sid]
                rows, segs, keys_q, keep, hrows = eng._plan_query(pq)
                off = int(self._base[sid])
                rows = rows + off
                if hrows is not None:
                    hrows = hrows + off
                rung = (0 if lat is not None
                        else packing.next_pow2(
                            max(1, len(set(pq.operands)))))
                groups.setdefault((pq.op, rung), []).append(
                    (pid, pq, rows, segs, keys_q, keep, hrows))
                if own is not None:
                    owner[pid] = own
                return pid, keys_q

            def plan_leaf(sid, i):
                # the sharded pool image is the FULL concat, so leaf
                # gathers stay global rows — no compaction remap
                rows, keys = self._engines[sid]._plan_leaf(i)
                return rows + int(self._base[sid]), keys

            for qid, (sid, q) in enumerate(pooled):
                if isinstance(q, expr_mod.ExprQuery):
                    sections.append(expr_mod.compile_query(
                        q, qid,
                        lambda pq, own, sid=sid: add_item(sid, pq, own),
                        lambda i, sid=sid: plan_leaf(sid, i),
                        cache_probe=self._single._cache_probe_for(sid),
                        col_resolve=(lambda name, sid=sid:
                                     self._engines[sid]._column(name))))
                else:
                    add_item(sid, q, qid)
            pad_to, point = snap_plan_groups(
                lat, groups, sections,
                any(q.form == "bitmap" for _, q in pooled),
                counter, self._engines[0].keys[:0],
                placement=self.placement)
            sp.tag(need_q=max((len(i) for i in groups.values()),
                              default=0),
                   need_rows=max((it[2].size for i in groups.values()
                                  for it in i), default=0),
                   need_keys=max((it[4].size for i in groups.values()
                                  for it in i), default=0))
            with obs_trace.span("sharded.pool", groups=len(groups)):
                buckets = [plan_bucket(op, items, pad_to=pad_to)
                           for (op, _), items in sorted(groups.items())]
                op_groups = _merge_op_groups(buckets)
                padded, n_pads = [], []
                d = self.mesh_devices
                for g in op_groups:
                    n = int(g.n_rows)
                    n_pad = max(d, -(-n // d) * d)
                    gather = np.zeros(n_pad, np.int32)
                    gather[:n] = g.host["gather"]
                    valid = np.zeros(n_pad, bool)
                    valid[:n] = g.host["valid"]
                    flat_seg = np.full(n_pad, g.nseg, np.int32)
                    flat_seg[:n] = g.host["flat_seg"]
                    host = {"gather": gather, "valid": valid,
                            "flat_seg": flat_seg,
                            "mask_ok": g.host["mask_ok"]}
                    if g.op == "andnot":
                        host["head_gather"] = g.host["head_gather"]
                        host["head_ok"] = g.host["head_ok"]
                    padded.append(host)
                    n_pads.append(n_pad)
            expr_mod.finalize_sections(sections, buckets)
            # combine-only one-kernel program for the replicated
            # post-butterfly side: reduce heads arrive as bank rows, the
            # combine passes + root outputs fuse into one pallas kernel
            # per SPMD dispatch; past its VMEM/SMEM budget the plan
            # keeps the multi-op eval_sections path (mega=None)
            mega = None
            fused = expr_mod.fused_of(sections)
            if fused:
                mega = megakernel.build_combines(
                    buckets, op_groups, sections,
                    expr_mod.expr_bucket_ids(fused))
                if not mega.fits():
                    megakernel.note_capacity_demotion("sharding", mega)
                    mega = None
            padding = (plan_padding(buckets, groups)
                       if point is not None else (0, 0.0))
            sp.tag(buckets=len(buckets), op_groups=len(op_groups),
                   flat_rows=int(sum(n_pads)), exprs=len(sections),
                   mega=mega is not None, snapped=point is not None)
        plan = _ShardedPlan(buckets=buckets, op_groups=op_groups,
                            sids=sids, padded=padded,
                            n_pads=tuple(n_pads),
                            exprs=sections, owner=owner, mega=mega,
                            point=point, padding=padding)
        self._plans.put(key, plan)
        return plan

    def _operands(self, plan: _ShardedPlan, fresh: bool = False) -> list:
        """Per-group device operands with their canonical placements:
        gather/valid/flat_seg shard with the transient rows
        (``SpecLayout.gather_vec``), per-key masks replicate.
        ``fresh=True`` uploads uncached twins for a donating dispatch."""
        shard_v = NamedSharding(self._mesh, self._specs.gather_vec())
        repl = NamedSharding(self._mesh, self._specs.replicated())

        def upload(host):
            return {k: podmesh.global_put(
                v, shard_v if k in ("gather", "valid", "flat_seg")
                else repl) for k, v in host.items()}

        def expr_upload(sec, f):
            # expression sections run on the replicated post-pass side
            # (combines over butterfly-combined heads), so every operand
            # — leaf gather indices included — places replicated, like
            # the andnot head_gather precedent above
            if f:
                return {k: podmesh.global_put(v, repl)
                        for k, v in sec.host.items()}
            if sec.arrays is None:
                sec.arrays = {k: podmesh.global_put(v, repl)
                              for k, v in sec.host.items()}
            return sec.arrays

        def mega_upload(f):
            # the combine-mode instruction stream replaces the per-
            # section operands wholesale; replicated like everything
            # on the post-butterfly side
            if f:
                return [{k: podmesh.global_put(v, repl)
                         for k, v in plan.mega.host.items()}]
            if plan._mega_arrays is None:
                plan._mega_arrays = {
                    k: podmesh.global_put(v, repl)
                    for k, v in plan.mega.host.items()}
            return [plan._mega_arrays]

        if fresh:
            return ([upload(h) for h in plan.padded]
                    + (mega_upload(True) if plan.mega is not None
                       else [expr_upload(s, True) for s in plan.fused]))
        if plan._arrays is None:
            plan._arrays = [upload(h) for h in plan.padded]
        return plan._arrays + (
            mega_upload(False) if plan.mega is not None
            else [expr_upload(s, False) for s in plan.fused])

    def _launch_cols(self, plan: _ShardedPlan) -> list:
        """Analytics column operands, REPLICATED like everything on the
        post-butterfly side (scan steps run there); uploads cache per
        (column uid, version) so replayed predicate values never
        re-place the planes — but ANY column delta does (a value-only
        patch rewrites plane contents at stable shapes, so caching on
        structure_version alone would serve stale planes)."""
        if not expr_mod.has_value_steps(plan.exprs):
            return [[] for _ in plan.fused]
        repl = NamedSharding(self._mesh, self._specs.replicated())
        cache = getattr(self, "_col_arrays", None)
        if cache is None:
            cache = self._col_arrays = {}

        def put(col):
            key = (col.uid, col.version)
            got = cache.get(key)
            if got is None:
                if len(cache) > 64:
                    cache.clear()      # retired column versions
                got = cache[key] = (
                    podmesh.global_put(col.slices_np, repl),
                    podmesh.global_put(col.ebm_np, repl))
            return got

        return [[put(c) for c in s.cols] for s in plan.fused]

    def _operand_avals(self, plan: _ShardedPlan) -> list:
        """Sharding-carrying avals matching ``_operands(fresh=True)`` —
        what the donate-variant lowering traces against (no throwaway
        uploads, same discipline as the multiset donate path)."""
        shard_v = NamedSharding(self._mesh, self._specs.gather_vec())
        repl = NamedSharding(self._mesh, self._specs.replicated())

        def aval(k, v):
            return jax.ShapeDtypeStruct(
                v.shape, jax.dtypes.canonicalize_dtype(v.dtype),
                sharding=(shard_v if k in ("gather", "valid", "flat_seg")
                          else repl))

        avals = [{k: aval(k, v) for k, v in h.items()}
                 for h in plan.padded]
        repl_aval = lambda v: jax.ShapeDtypeStruct(
            v.shape, jax.dtypes.canonicalize_dtype(v.dtype),
            sharding=repl)
        if plan.mega is not None:
            avals.append({k: repl_aval(v)
                          for k, v in plan.mega.host.items()})
        else:
            avals.extend({k: repl_aval(v) for k, v in s.host.items()}
                         for s in plan.fused)
        return avals

    def predict_dispatch_bytes(self, groups_or_queries) -> dict:
        """Per-shard + mesh-total transient prediction of ONE sharded
        launch (``insights.predict_sharded_dispatch_bytes``) — the
        ``per_shard_bytes`` entry is what the proactive split compares
        against the per-device HBM budget."""
        groups, _ = self._normalize(groups_or_queries)
        pooled, _ = self._single._flatten(groups)
        return self._predict(self._plan(tuple(pooled)))

    def _predict(self, plan: _ShardedPlan) -> dict:
        out = insights.predict_sharded_dispatch_bytes(
            [b.signature for b in plan.buckets], self.pool_rows,
            self.mesh_devices,
            self.mesh_shape[0] if self.placement == "sharded" else 1)
        if plan.exprs:
            # fused combine intermediates live on the replicated side:
            # every device holds them, so they add to BOTH the per-shard
            # figure (the budget-relevant one) and D x to the mesh total
            # — under the combine-mode megakernel they are VMEM slots
            # and only the root outputs remain
            e = insights.predict_expr_dispatch_bytes(
                plan.expr_signature,
                "megakernel" if plan.mega is not None else "xla"
            )["peak_bytes"]
            out["expr_bytes"] = e
            out["per_shard_bytes"] += e
            out["peak_bytes"] += self.mesh_devices * e
        return out

    # ------------------------------------------------------------- programs

    def _group_body(self, g_sig, n_pad: int, arrs, pool_words,
                    force_heads: bool = False):
        """Traced body for one op superbucket on the mesh: gather from
        the rows-sharded pool, shard-local segmented reduce, butterfly
        combine per mesh axis, replicated post passes.  ``force_heads``
        returns heads for in-program fused-expression consumption
        regardless of the group's own needs_words."""
        op, nseg, _n_rows, n_steps, needs_words, _reg = g_sig
        needs_words = needs_words or force_heads
        red = _RED_OP[op]
        mesh, specs = self._mesh, self._specs
        ident = jnp.uint32(0xFFFFFFFF if op == "and" else 0)
        g = pool_words[arrs["gather"]]
        g = jnp.where(arrs["valid"][:, None], g, ident)
        g = jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, specs.gather_rows()))

        def local(g_shard, seg_shard):
            # rows are globally sorted by flat segment, so a shard's rows
            # for one segment are contiguous: reduce local runs, scatter
            # each run's head into an IDENTITY-initialized full
            # accumulator (a segment with no rows on this shard must
            # contribute the identity to the cross-shard combine — zeros
            # would annihilate AND), then butterfly per mesh axis
            rows = dense.doubling_pass(dense.OPS[red], g_shard,
                                       seg_shard, n_steps)
            prev = jnp.concatenate(
                [jnp.full((1,), -1, seg_shard.dtype), seg_shard[:-1]])
            is_head = seg_shard != prev
            dest = jnp.where(is_head, seg_shard, nseg)
            acc = jnp.full((nseg + 1, rows.shape[1]), ident)
            acc = acc.at[dest].set(rows)
            for axis in (specs.row_axis, specs.data_axis):
                if mesh.shape[axis] > 1:
                    acc = _butterfly_combine(red, acc, axis,
                                             mesh.shape[axis])
            return acc

        heads = shard_map(
            local, mesh=mesh,
            in_specs=(specs.gather_rows(), specs.gather_vec()),
            out_specs=specs.combined_heads(),
            check_vma=False)(g, arrs["flat_seg"])
        heads = heads[:nseg]
        heads = jnp.where(arrs["mask_ok"][:, None], heads, jnp.uint32(0))
        if op == "andnot":
            hg = pool_words[arrs["head_gather"]]
            hg = jnp.where(arrs["head_ok"][:, None], hg, jnp.uint32(0))
            heads = hg & ~heads
        cards = dense.popcount(heads)
        return (heads if needs_words else None), cards

    def _program(self, plan: _ShardedPlan, donate: bool = False):
        """AOT-compiled mesh program for this plan's signature — one call
        = one SPMD dispatch over the whole mesh, with memory/cost
        analysis captured per the PR 4/6 contract.  ``donate=True``
        (donation-capable backends only) donates the per-launch group
        scratch like the PR 5 pipelined dispatcher."""
        donate = donate and _donation_supported()
        # the placed pool's shape/placement is a program operand: a
        # mutation-escalated re-place (structural repack) can change
        # both, and a bucket-shape-identical plan must not hit a program
        # compiled against the old image
        sig = (guard.MESH, plan.signature, donate, self.placement,
               self.pool_rows)
        if plan.mega is not None:
            sig = sig + (plan.mega.signature,)
        t_get = time.perf_counter()
        cached = self._programs.get(sig)
        if cached is not None:
            obs_cost.observe_compile(SITE, "hit",
                                     time.perf_counter() - t_get)
            return cached
        g_sigs = [g.sig for g in plan.op_groups]
        n_pads = plan.n_pads
        fused = plan.fused
        expr_bis = expr_mod.expr_bucket_ids(fused)
        group_force = [any(bi in expr_bis for bi in g.bucket_idx)
                       for g in plan.op_groups]

        with obs_slo.phase("program_build"), \
                obs_trace.span("sharded.program_build", mesh=self._mesh_label,
                               groups=len(g_sigs), donate=donate,
                               exprs=len(fused)) as sp:
            def run(pool_words, arrays, cols):
                outs, group_heads = [], []
                for gi, (s, n, a) in enumerate(zip(g_sigs, n_pads,
                                                   arrays[:len(g_sigs)])):
                    heads, cards = self._group_body(
                        s, n, a, pool_words,
                        force_heads=group_force[gi])
                    group_heads.append((heads, cards))
                    outs.append((heads if s[4] else None, cards))
                if not fused:
                    return outs
                if plan.mega is not None:
                    # one-kernel combine passes on the replicated side:
                    # the butterfly-combined flat head tensors feed the
                    # megakernel as bank rows, combines + root outputs
                    # run in one pallas grid kernel per device.  The
                    # kernel runs under a fully-replicated shard_map so
                    # the SPMD partitioner replicates it whole instead
                    # of slicing its grid across the mesh.
                    repl = self._specs.replicated()

                    def wrap(fn):
                        return shard_map(
                            fn, mesh=self._mesh,
                            in_specs=(repl, repl, repl, repl),
                            out_specs=(repl, repl), check_vma=False)

                    return outs, megakernel.eval_combines(
                        plan.mega, group_heads, pool_words,
                        arrays[len(g_sigs)], wrap=wrap, cols=cols)
                # fused combine passes run on the replicated side, after
                # every group's butterfly combine — the padded flat head
                # layout (no live fast path on the mesh)
                bucket_heads = expr_mod.traced_bucket_heads(
                    plan.buckets, plan.op_groups, group_heads,
                    live_ok=False)
                return outs, expr_mod.eval_sections(
                    fused, arrays[len(g_sigs):], pool_words, bucket_heads,
                    cols_list=cols)

            jit_kw = {"donate_argnums": (1,)} if donate else {}
            operands = (self._operand_avals(plan) if donate
                        else self._operands(plan))
            t0 = time.perf_counter()
            compiled = jax.jit(run, **jit_kw).lower(
                self.pool_words, operands,
                self._launch_cols(plan)).compile()
            compile_s = time.perf_counter() - t0
            obs_cost.observe_compile(SITE, "miss", compile_s)
            rt_lattice.note_compile(SITE, guard.MESH, plan.point,
                                    compile_s)
            predicted = self._predict(plan)
            measured = obs_memory.compiled_memory(compiled)
            cost = obs_cost.compiled_cost(compiled)
            sp.tag(per_shard_predicted_bytes=predicted["per_shard_bytes"],
                   measured_peak_bytes=(measured or {}).get("peak_bytes"),
                   compile_ms=round(compile_s * 1e3, 2),
                   flops=(cost or {}).get("flops"))
            cached = (run, compiled, predicted, measured, cost)
        self._programs.put(sig, cached)
        return cached

    # ------------------------------------------------------------ execution

    def execute(self, groups, engine: str = "auto", jit: bool = True,
                fallback: bool = True,
                policy: guard.GuardPolicy | None = None) -> list:
        """Run a pool of per-set query groups as mesh-sharded launches;
        returns per-group result lists (``MultiSetBatchEngine.execute``'s
        shape), or a flat list when called with bare ``BatchQuery``
        sugar.  ``engine`` is accepted for interface parity; the mesh
        rung's reduce engine is the XLA doubling pass (the shard-local
        form), with demotion handling everything else.

        Guarded per launch down ``mesh -> single -> sequential``;
        ``ResourceExhausted`` halves the pool reactively, and the
        proactive split halves it BEFORE dispatch while the per-shard
        predicted transient exceeds the per-device HBM budget."""
        groups, bare = self._normalize(groups)
        pooled, lengths = self._single._flatten(groups)
        if not pooled:
            return [] if bare else [[] for _ in groups]
        t_exec0 = time.perf_counter()
        with obs_trace.span("sharded.execute", site=SITE, q=len(pooled),
                            sets=len({s for s, _ in pooled}),
                            mesh=self._mesh_label, fallback=fallback):
            obs_metrics.counter("rb_sharded_queries_total", site=SITE,
                                mesh=self._mesh_label).inc(len(pooled))
            if not fallback:
                flat = self._launch_once(pooled, jit, inject=False)
                return flat if bare else self._single._regroup(flat,
                                                               lengths)
            policy = policy or guard.GuardPolicy.from_env()
            budget = guard.resolve_hbm_budget(policy)
            deadline = guard.Deadline(policy.deadline)

            def run_misses(qs):
                out = []
                for sub in self._launch_iter(tuple(qs), budget):
                    res, _rung = self._launch_guarded(
                        sub, jit, policy, deadline, budget)
                    out.extend(res)
                return out

            with obs_slo.query(SITE, deadline_ms=policy.slo_deadline_ms):
                rc = self.result_cache
                if rc is not None:
                    from ..mutation import result_cache as mut_cache

                    self._single._sync_with_sets()
                    flat, _hits = mut_cache.serve_and_fill(
                        rc, list(pooled),
                        lambda it: self._engines[it[0]]._cache_key_of(
                            it[1]),
                        run_misses, SITE)
                else:
                    flat = run_misses(pooled)
            if not self._first_query_done:
                self._first_query_done = True
                obs_metrics.histogram(
                    "rb_first_query_seconds", site=SITE).observe(
                        time.perf_counter() - t_exec0)
            if policy.shadow_rate > 0.0:
                self._shadow_check(pooled, flat, policy)
            return flat if bare else self._single._regroup(flat, lengths)

    def _launch_iter(self, pooled, budget: int | None):
        """Left-to-right launch partition: a sub-pool whose PER-SHARD
        predicted transient exceeds the per-device budget is halved
        before dispatch (the mesh form of the proactive split — a D-row
        mesh admits ~D× what the single-device engine would)."""
        stack = [list(pooled)]
        while stack:
            qs = stack.pop()
            while budget is not None and len(qs) >= 2:
                per_shard = self._predict(
                    self._plan(tuple(qs)))["per_shard_bytes"]
                if per_shard <= budget:
                    break
                mid = (len(qs) + 1) // 2
                self.proactive_split_count += 1
                obs_metrics.counter("rb_sharded_proactive_splits_total",
                                    site=SITE,
                                    mesh=self._mesh_label).inc()
                obs_trace.current().event(
                    "proactive_split", site=SITE, q=len(qs),
                    predicted_bytes=per_shard, budget_bytes=budget,
                    mesh=list(self.mesh_shape),
                    halves=(mid, len(qs) - mid))
                stack.append(qs[mid:])
                qs = qs[:mid]
            yield tuple(qs)

    def _launch_guarded(self, qs, jit, policy, deadline, budget):
        """One guarded launch down the mesh -> single -> sequential
        ladder.  The single rung is the un-sharded pooled engine's raw
        xla launch over the SAME resident sets (bit-exact by the PR 5
        parity contract); its own finer ladder is not re-entered — a
        process that lost the mesh should degrade predictably, not
        explore."""

        def attempt(rung):
            if rung == guard.MESH:
                return self._launch_once(qs, jit)
            faults.maybe_fail(SITE, guard.SINGLE_DEVICE)
            obs_slo.note_engine(guard.SINGLE_DEVICE)
            return self._single._launch_once(qs, "xla", jit)

        def on_oom(rung, fault, dl):
            if len(qs) < 2:
                return guard.NO_SPLIT
            mid = (len(qs) + 1) // 2
            self.split_count += 1
            obs_metrics.counter("rb_sharded_oom_splits_total", site=SITE,
                                mesh=self._mesh_label).inc()
            obs_trace.current().event(
                "oom_split", site=SITE, engine_from=rung, engine_to=rung,
                q=len(qs), halves=(mid, len(qs) - mid))
            return (self._launch_guarded(qs[:mid], jit, policy, dl,
                                         budget)[0]
                    + self._launch_guarded(qs[mid:], jit, policy, dl,
                                           budget)[0])

        return guard.run_with_fallback(
            SITE, ENGINE_LADDER, attempt, policy=policy,
            sequential=lambda: self._single._sequential(qs),
            on_resource_exhausted=on_oom, deadline=deadline)

    def _launch_once(self, pooled, jit: bool, inject: bool = True) -> list:
        """Raw mesh launch: plan -> one compiled SPMD program -> host
        assembly.  The faults hook sits at the engine boundary."""
        pooled = tuple(pooled)
        plan = self._plan(pooled)
        obs_slo.note_engine(guard.MESH)
        if inject:
            faults.maybe_fail(SITE, guard.MESH)
        donate = _donation_supported()
        run, compiled, predicted, measured, cost = self._program(
            plan, donate=donate)
        operands = self._operands(plan, fresh=donate)
        with obs_trace.span("sharded.dispatch", engine=guard.MESH,
                            q=len(pooled), sets=len(plan.sids),
                            mesh=self._mesh_label) as sp:
            t_launch = time.perf_counter()
            with obs_slo.phase("dispatch"):
                outs = (compiled if jit else run)(self.pool_words,
                                                  operands,
                                                  self._launch_cols(plan))
            obs_metrics.counter("rb_sharded_launches_total", site=SITE,
                                mesh=self._mesh_label).inc()
            if plan.exprs:
                expr_mod.record_fused_dispatch(SITE, plan.exprs)
                expr_mod.record_analytics_dispatch(SITE, plan.exprs, sp)
            if plan.mega is not None:
                sp.event("expr.megakernel", **plan.mega.stats_event())
            with obs_slo.phase("sync"):
                outs = sp.sync(outs)
                outs = jax.block_until_ready(outs)
            launch_s = time.perf_counter() - t_launch
            mem = obs_memory.record_dispatch(
                SITE, predicted["per_shard_bytes"], measured)
            mem["engine"], mem["q"] = guard.MESH, len(pooled)
            mem["sets"] = len(plan.sids)
            mem["mesh"] = list(self.mesh_shape)
            mem["per_shard_predicted_bytes"] = predicted["per_shard_bytes"]
            mem["mesh_total_predicted_bytes"] = predicted["peak_bytes"]
            if plan.point is not None:
                pb, pf = plan.padding
                mem["lattice_padding_bytes"] = int(pb)
                mem["lattice_padding_fraction"] = round(pf, 6)
                rt_lattice.record_padding(SITE, int(pb), pf)
            self.last_dispatch_memory = mem
            sp.event("sharded.memory", **mem)
            word_ops = insights.predict_batch_dispatch_word_ops(
                [b.signature for b in plan.buckets], "dense", 0, "xla")
            if plan.exprs:
                word_ops += insights.predict_expr_word_ops(
                    plan.expr_signature, "xla")
            cost_ev = obs_cost.record_dispatch(
                SITE, guard.MESH, cost, launch_s,
                devices=self.mesh_devices,
                est={"flops": word_ops,
                     "bytes_accessed": predicted["peak_bytes"]},
                q=len(pooled))
            self.last_dispatch_cost = cost_ev
            sp.event("sharded.cost", **cost_ev)
            # the mesh-keyed shard event (tools/check_trace.py schema):
            # where this launch's rows lived and how balanced the
            # resident row distribution is (replicated placement holds
            # ALL pool rows on every device — report what is resident)
            rows_per_shard = (self.pool_rows // self.mesh_shape[0]
                              if self.placement == "sharded"
                              else self.pool_rows)
            sp.event("batch.shard", site=SITE, mesh=list(self.mesh_shape),
                     placement=self.placement,
                     rows_per_shard=rows_per_shard,
                     flat_rows=int(sum(plan.n_pads)),
                     shard_balance=round(self.shard_balance, 4),
                     per_shard_predicted_bytes=predicted[
                         "per_shard_bytes"])
        return self._readback(plan, outs, pooled, inject)

    def _group_outputs(self, plan: _ShardedPlan, outs):
        """Slice each op superbucket's flat heads/cards back into
        per-bucket (bucket, heads, cards) host arrays — the padded flat
        layout (one dead slot per query's k_pad+1 stride), like the
        multiset pallas path."""
        for grp, (heads_f, cards_f) in zip(plan.op_groups, outs):
            heads_f = None if heads_f is None else np.asarray(heads_f)
            cards_f = np.asarray(cards_f)
            for bi, s0 in zip(grp.bucket_idx, grp.seg_offs):
                b = plan.buckets[bi]
                n = b.q * (b.k_pad + 1)
                cards = cards_f[s0:s0 + n].reshape(
                    b.q, b.k_pad + 1)[:, :b.k_pad]
                heads = (None if heads_f is None else
                         heads_f[s0:s0 + n].reshape(
                             b.q, b.k_pad + 1, WORDS32)[:, :b.k_pad])
                yield b, heads, cards

    def _readback(self, plan: _ShardedPlan, outs, pooled,
                  inject: bool) -> list:
        from .batch_engine import BatchResult

        if plan.fused:
            outs, expr_outs = outs
        else:
            expr_outs = []
        with obs_slo.phase("readback"), \
                obs_trace.span("sharded.readback", q=len(pooled),
                               mesh=self._mesh_label):
            results = assemble_pooled_results(
                self._group_outputs(plan, outs), pooled, plan.rb_meta,
                owner=(plan.owner if (plan.exprs
                                      or plan.point is not None)
                       else None))
            expr_mod.assemble_section_results(
                plan.exprs, expr_outs, results,
                lambda qid: pooled[qid][1].form)
        if inject and faults.should_corrupt(SITE, guard.MESH):
            results[0] = BatchResult(
                cardinality=results[0].cardinality + 1,
                bitmap=results[0].bitmap)
        return results

    def _shadow_check(self, pooled, results, policy) -> None:
        from ..runtime import errors

        idx = guard.shadow_sample(len(pooled), policy.shadow_rate,
                                  policy.shadow_seed, SITE)
        for i in idx:
            sid, q = pooled[i]
            ref = self._engines[sid]._sequential_result(q)
            got = results[i]
            bad = (got.cardinality != ref.cardinality
                   or got.value != ref.value)
            if not bad and q.form == "bitmap":
                bad = got.bitmap != ref.bitmap
            if bad:
                raise errors.ShadowMismatch(
                    f"sharded query {i} ({query_desc(q)} on set "
                    f"{sid}) diverged from the sequential reference: got "
                    f"cardinality {got.cardinality}/value {got.value}, "
                    f"want {ref.cardinality}/{ref.value}")

    # --------------------------------------------------------- conveniences

    def _compile_lattice_points(self, lat) -> int:
        """Compile the mesh half of the lattice vocabulary: one SPMD
        program per flat point (a pinned representative pool — the
        sharded image is the full static concat, so the tenant mix never
        enters the signature), the representative expression DAGs, and
        every tenant's delta-patch rungs."""
        points = lat.enumerate_points(pooled=False)
        self._programs.maxsize = max(self._programs.maxsize,
                                     2 * len(points) + 8)
        compiled = 0
        second = 1 % self.n_sets
        for point in points:
            if point.delta:
                for e in self._engines:
                    e._ds.warmup_delta(point.delta)
                compiled += 1
                continue
            if point.bsi:
                from .batch_engine import analytics_rung_queries

                batches = analytics_rung_queries(
                    getattr(self._engines[0]._ds, "columns", {}),
                    point.bsi, self._engines[0].n)
                with lat.pin(point):
                    for batch in batches:
                        pooled, _ = self._single._flatten(
                            [BatchGroup(0, batch)])
                        plan = self._plan(tuple(pooled))
                        for sec in plan.exprs:
                            lat.note_expr(sec.signature)
                        self._program(plan,
                                      donate=_donation_supported())
                compiled += 1
                continue
            if point.expr:
                qs = expr_mod.rung_expressions(point.expr,
                                               self._engines[0].n)
                pool = [BatchGroup(0, qs)]
            else:
                pool = [BatchGroup(0, [BatchQuery(op, (0,))
                                       for op in point.ops]),
                        BatchGroup(second,
                                   [BatchQuery(point.ops[0], (0,))])]
            pooled, _ = self._single._flatten(pool)
            with lat.pin(point):
                plan = self._plan(tuple(pooled))
                for sec in plan.exprs:
                    lat.note_expr(sec.signature)
                self._program(plan, donate=_donation_supported())
            compiled += 1
        return compiled

    def _warmup_lattice(self, profile, cache_dir: str | None) -> dict:
        """``warmup(profile=...)`` over the mesh: activate, pre-compile
        the mesh vocabulary, seal (docs/LATTICE.md).  The single-device
        demotion rung compiles only on a mesh fault — such a compile is
        an escape by design: an incident, not steady state."""
        t0 = time.perf_counter()
        lat = rt_lattice.activate(profile)
        with obs_trace.span("lattice.warmup", site=SITE,
                            points=lat.n_points(),
                            profile=lat.to_profile()) as sp:
            compiled = self._compile_lattice_points(lat)
            lat.seal()
            sp.tag(compiled=compiled, sealed=True)
        return {"site": SITE, "compile_cache_dir": cache_dir,
                "mesh": list(self.mesh_shape),
                "lattice": {"profile": lat.to_profile(),
                            "points": lat.n_points(),
                            "compiled": compiled, "sealed": True},
                "programs": [],
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    def warmup(self, rungs=(1, 2, 4, 8),
               ops=("or", "and", "xor", "andnot"),
               pools=None, profile=None) -> dict:
        """Pre-compile mesh programs for known pow2 operand rungs (or
        explicit ``pools=``) — ``BatchEngine.warmup`` one level up; the
        persistent compile cache (``ROARING_TPU_COMPILE_CACHE``) makes
        the compiles survive restarts, so a re-booted serving process
        replays them from disk.  ``profile=`` switches to the
        closed-lattice boot path (docs/LATTICE.md)."""
        cache_dir = rt_warmup.enable_compile_cache()
        if profile is not None:
            return self._warmup_lattice(profile, cache_dir)
        t0 = time.perf_counter()
        programs = []
        if pools is None:
            pools = []
            for r in rungs:
                kind, n = expr_mod.parse_warmup_rung(r)
                if kind == "delta":
                    # mutation patch rung per tenant (docs/MUTATION.md);
                    # the pooled image's own patch program jits per
                    # rung on first replay
                    for e in self._engines:
                        rep = e._ds.warmup_delta(n)
                        programs.append({"delta_rung": n,
                                         "engine": "mutation",
                                         "compiled": rep["compiled"]})
                    continue
                pools.append([
                    BatchGroup(sid,
                               expr_mod.rung_expressions(n, e.n)
                               if kind == "expr"
                               else e._rung_queries(n, ops))
                    for sid, e in enumerate(self._engines)])
        for pool in pools:
            groups, _ = self._normalize(pool)
            pooled, _ = self._single._flatten(groups)
            if not pooled:
                continue
            plan = self._plan(tuple(pooled))
            self._program(plan, donate=_donation_supported())
            programs.append({"q": len(pooled), "sets": len(plan.sids),
                             "groups": len(plan.op_groups),
                             "mesh": self._mesh_label})
        return {"site": SITE, "compile_cache_dir": cache_dir,
                "mesh": list(self.mesh_shape), "programs": programs,
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    def cardinalities(self, groups, engine: str = "auto"):
        """Flat/per-group i64 cardinalities, matching the input shape."""
        out = self.execute(groups, engine=engine)
        if out and not isinstance(out[0], list):
            return np.array([r.cardinality for r in out], np.int64)
        return [np.array([r.cardinality for r in rows], np.int64)
                for rows in out]

    def count_cache_hits(self, groups_or_queries) -> int:
        """Delegates to the un-sharded pooled engine's counter — the
        leaf tokens are properties of the shared resident sets, so the
        answer is placement-independent."""
        groups, _ = self._normalize(groups_or_queries)
        return self._single.count_cache_hits(groups)

    def cache_stats(self) -> dict:
        """Sharded plan/program cache observability + the split counter
        (``BatchEngine.cache_stats``'s frozen shape)."""
        return {"plans": self._plans.stats(),
                "programs": self._programs.stats(),
                "splits": self.split_count}
