"""Hardened query runtime: typed errors, guarded dispatch, fault injection.

See docs/ROBUSTNESS.md for the taxonomy, the fallback order, the
``ROARING_TPU_FAULTS`` grammar, and the shadow cross-check knob.
"""

from . import cache, errors, faults, guard
from .cache import LRUCache
from .errors import (
    CoordinatorTimeout,
    CorruptInput,
    EngineLoweringError,
    ResourceExhausted,
    RoaringRuntimeError,
    ShadowMismatch,
    TransientDeviceError,
    classify,
)
from .guard import (Deadline, GuardPolicy, dispatch_stats,
                    reset_dispatch_stats, run_with_fallback)

__all__ = [
    "cache", "errors", "faults", "guard", "LRUCache",
    "RoaringRuntimeError", "TransientDeviceError", "ResourceExhausted",
    "EngineLoweringError", "CoordinatorTimeout", "CorruptInput",
    "ShadowMismatch", "classify", "Deadline", "GuardPolicy",
    "dispatch_stats", "reset_dispatch_stats", "run_with_fallback",
]
