"""Bounded LRU cache with observable counters.

A long-lived serving process replays the prepared-statement pattern: plans
and compiled programs are cached per query/bucket signature.  Unbounded
dicts turn adversarial query shapes into a memory leak (every novel shape
pins a plan + a compiled executable forever), so the batch engine's caches
ride this LRU: size-capped, eviction-counted, and introspectable via
``stats()`` so a server can alarm on churn.
"""

from __future__ import annotations

from collections import OrderedDict

_MISSING = object()


class LRUCache:
    """OrderedDict-backed LRU: ``get`` refreshes recency, ``put`` evicts the
    least-recently-used entry past ``maxsize``.  Not thread-safe (the batch
    engine is per-instance single-dispatcher, like the rest of the stack)."""

    def __init__(self, maxsize: int):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = int(maxsize)
        self._data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key, default=None):
        val = self._data.get(key, _MISSING)
        if val is _MISSING:
            self.misses += 1
            return default
        self.hits += 1
        self._data.move_to_end(key)
        return val

    def put(self, key, value) -> None:
        if key in self._data:
            self._data.move_to_end(key)
        self._data[key] = value
        while len(self._data) > self.maxsize:
            self._data.popitem(last=False)
            self.evictions += 1

    def clear(self) -> None:
        self._data.clear()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data

    def stats(self) -> dict:
        return {"size": len(self._data), "maxsize": self.maxsize,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}
