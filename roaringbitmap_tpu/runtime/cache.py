"""Bounded LRU cache with observable counters.

A long-lived serving process replays the prepared-statement pattern: plans
and compiled programs are cached per query/bucket signature.  Unbounded
dicts turn adversarial query shapes into a memory leak (every novel shape
pins a plan + a compiled executable forever), so the batch engine's caches
ride this LRU: size-capped, eviction-counted, and introspectable via
``stats()`` so a server can alarm on churn.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict

from ..obs import metrics as _metrics

_MISSING = object()

#: live named caches, summed per name by the rb_cache_size collector at
#: scrape time (a pull gauge cannot desync across obs.reset() or clobber
#: across instances the way pushed values can; a name whose caches have
#: all been collected keeps its last value until the next reset)
_named_caches: "weakref.WeakSet" = weakref.WeakSet()


def _collect_cache_sizes(registry) -> None:
    sizes: dict = {}
    for c in list(_named_caches):
        sizes[c.name] = sizes.get(c.name, 0) + len(c._data)
    for name, n in sizes.items():
        registry.gauge("rb_cache_size", cache=name).set(n)


_metrics.REGISTRY.register_collector(_collect_cache_sizes)


class LRUCache:
    """OrderedDict-backed LRU: ``get`` refreshes recency, ``put`` evicts the
    least-recently-used entry past ``maxsize``.  Not thread-safe (the batch
    engine is per-instance single-dispatcher, like the rest of the stack).

    ``name`` opts the cache into the unified metrics registry as a
    first-class instrument: hits/misses/evictions bump
    ``rb_cache_events_total{cache=name,event=...}``, and the entry count
    is computed at scrape time by the ``rb_cache_size`` collector as the
    sum over live instances sharing the name (a server's per-engine view
    stays ``stats()``)."""

    def __init__(self, maxsize: int, name: str | None = None):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = int(maxsize)
        self.name = name
        self._data: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        if name is not None:
            _named_caches.add(self)

    def _count(self, event: str) -> None:
        if self.name is not None:
            _metrics.counter("rb_cache_events_total", cache=self.name,
                             event=event).inc()

    def get(self, key, default=None):
        val = self._data.get(key, _MISSING)
        if val is _MISSING:
            self.misses += 1
            self._count("miss")
            return default
        self.hits += 1
        self._count("hit")
        self._data.move_to_end(key)
        return val

    def put(self, key, value) -> None:
        if key in self._data:
            self._data.move_to_end(key)
        self._data[key] = value
        while len(self._data) > self.maxsize:
            self._data.popitem(last=False)
            self.evictions += 1
            self._count("eviction")

    def clear(self) -> None:
        self._data.clear()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data

    def stats(self) -> dict:
        return {"size": len(self._data), "maxsize": self.maxsize,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}
