"""Deterministic fault injection at the engine dispatch boundary.

The retry/demote/split/degrade machinery in runtime.guard is only worth
shipping if it is exercised continuously — real device faults are rare and
never appear on the CPU CI backend, so this harness injects synthetic ones
at the exact seam where real ones would surface (immediately before an
engine executes), driven by one environment variable:

    ROARING_TPU_FAULTS = "<entry>[,<entry>...]:<seed>"
    entry              = <kind>[@<scope>][=<rate>]

kind   one of ``transient`` (retryable device hiccup), ``oom`` (device
       allocator failure), ``lowering`` (compiler rejection), ``corrupt``
       (corrupt serialized input), ``coordinator`` (distributed barrier
       timeout), ``silent`` (result corrupted WITHOUT an exception — only
       the shadow cross-check can catch it), ``slow`` (injected latency
       before a dispatch: no exception, the **fault clock** below jumps
       forward by SLOW_LATENCY_S — deadlines expire, nothing sleeps),
       ``crash`` (simulated process death at the durability layer's
       journal/apply seams only — ``maybe_crash`` below; the special
       scope ``@torn`` additionally tears the journal's last record
       mid-frame before dying, the classic torn-write shape),
       ``wire`` (RPC-boundary faults only — ``maybe_wire`` below; the
       scope is REQUIRED and picks the shape: ``@conn_drop`` drops the
       socket mid-pipeline, ``@slow_peer`` advances the fault clock on
       the response path, ``@garbage`` corrupts an outgoing frame so
       the receiver dies typed ``CorruptInput``).
scope  optional dispatch-site name ("batch_engine", "aggregation",
       "sharding", "multihost") or engine rung ("pallas", "xla",
       "xla-vmap", "sharded", "coordinator"); omitted = everywhere.
rate   probability per dispatch in (0, 1]; omitted = 1.0.

Examples::

    ROARING_TPU_FAULTS="lowering@pallas=1.0:7"        # kill the top rung
    ROARING_TPU_FAULTS="transient=0.05,oom=0.02:1337" # CI background noise

Determinism: every draw comes from a counter-keyed Philox stream seeded by
(seed, rule index, site hash, call ordinal), so a fixed seed and a fixed
call sequence reproduce the exact same fault schedule in any process — the
property the CI fault shard and failure repros rely on.  Injected
exceptions deliberately take the RAW shapes real faults arrive in (status-
string RuntimeErrors, NotImplementedError) so errors.classify is exercised
end to end, not bypassed.

The fault clock
---------------
``clock()`` is virtual-time monotonic: real ``time.monotonic()`` plus an
injected offset.  A firing ``slow`` rule (``maybe_delay``) and explicit
``advance_clock(seconds)`` both advance the offset WITHOUT sleeping, so
deadline expiry, load shedding, and backpressure paths are CI-testable in
microseconds of wall time — ``runtime.guard.Deadline`` and the serving
loop (``roaringbitmap_tpu.serving``) read this clock, which is why
injected latency actually expires their budgets.  The offset only ever
grows (the clock stays monotonic); ``reset_clock()`` is test hygiene for
suites that assert absolute virtual timestamps.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import time
import zlib

import numpy as np

from . import errors

ENV_VAR = "ROARING_TPU_FAULTS"

KINDS = ("transient", "oom", "lowering", "corrupt", "coordinator", "silent",
         "slow", "crash", "wire")
#: kinds that raise at the boundary (silent corrupts results in place,
#: slow advances the fault clock, crash only fires at the durability
#: layer's journal/apply seams via maybe_crash, wire only fires at the
#: RPC boundary via maybe_wire — none of the four raise from the
#: generic engine-boundary hook)
RAISING_KINDS = KINDS[:5]

#: scopes a ``wire`` rule must name (wire@conn_drop etc.): the peer
#: vanishing mid-pipeline, a slow-loris peer (fault-clock latency on the
#: response path), or a garbled/torn frame on the socket
WIRE_SCOPES = ("conn_drop", "slow_peer", "garbage")

#: virtual latency one firing ``slow`` rule injects, seconds — sized so a
#: handful of fires blows a ms-scale serving deadline but a single fire
#: under a second-scale guard deadline only burns budget
SLOW_LATENCY_S = 0.05


@dataclasses.dataclass(frozen=True)
class FaultRule:
    kind: str
    scope: str | None   # site or engine name; None matches everywhere
    rate: float


class FaultPlan:
    """A parsed spec plus the per-(rule, site) draw counters that make the
    schedule deterministic under a fixed call order."""

    def __init__(self, rules: list[FaultRule], seed: int):
        self.rules = list(rules)
        self.seed = int(seed)
        self._counters: dict = {}

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        body, sep, seed_s = spec.rpartition(":")
        if not sep:
            raise ValueError(
                f"{ENV_VAR} needs a ':<seed>' suffix, got {spec!r}")
        try:
            seed = int(seed_s, 0)
        except ValueError:
            raise ValueError(
                f"{ENV_VAR} seed must be an integer, got {seed_s!r}") from None
        rules = []
        for entry in body.split(","):
            entry = entry.strip()
            if not entry:
                continue
            kind, rate = entry, 1.0
            if "=" in entry:
                kind, rate_s = entry.split("=", 1)
                try:
                    rate = float(rate_s)
                except ValueError:
                    raise ValueError(
                        f"bad fault rate {rate_s!r} in {entry!r}") from None
            scope = None
            if "@" in kind:
                kind, scope = kind.split("@", 1)
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {KINDS})")
            if kind == "wire" and scope not in WIRE_SCOPES:
                raise ValueError(
                    f"wire faults need a scope in {WIRE_SCOPES}, got "
                    f"{scope!r} in {entry!r}")
            if not 0.0 < rate <= 1.0:
                raise ValueError(
                    f"fault rate must be in (0, 1], got {rate} in {entry!r}")
            rules.append(FaultRule(kind, scope or None, rate))
        if not rules:
            raise ValueError(f"{ENV_VAR} spec {spec!r} has no fault entries")
        return cls(rules, seed)

    def _draw(self, rule_index: int, site_key: str) -> float:
        key = (rule_index, site_key)
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        rng = np.random.default_rng(
            (self.seed, rule_index, zlib.crc32(site_key.encode()), n))
        return float(rng.random())

    def pick(self, site: str, engine: str | None,
             kinds: tuple = RAISING_KINDS) -> str | None:
        """First matching rule whose deterministic draw fires, else None."""
        for i, r in enumerate(self.rules):
            if r.kind not in kinds:
                continue
            if r.scope is not None and r.scope not in (site, engine):
                continue
            if self._draw(i, f"{site}/{engine}") < r.rate:
                return r.kind
        return None


# --------------------------------------------------------------- activation

#: plans cached per spec string so env-driven activation keeps ONE counter
#: state per process (fresh spec -> fresh schedule)
_env_plans: dict = {}
#: test/bench override stack (faults.inject) — wins over the environment
_override: list = []


def active() -> FaultPlan | None:
    if _override:
        return _override[-1]
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return None
    plan = _env_plans.get(spec)
    if plan is None:
        plan = _env_plans[spec] = FaultPlan.from_spec(spec)
    return plan


@contextlib.contextmanager
def inject(spec: str):
    """Scoped activation with a FRESH schedule (counters restart), for
    tests and the bench degraded-mode lane."""
    plan = FaultPlan.from_spec(spec)
    _override.append(plan)
    try:
        yield plan
    finally:
        _override.pop()


# -------------------------------------------------------------- fault clock

_clock_offset = 0.0


def clock() -> float:
    """Virtual-time monotonic clock: ``time.monotonic()`` plus every
    injected/advanced offset.  THE clock of deadline-sensitive layers
    (guard.Deadline, the serving loop) — injected ``slow`` latency and
    test-driven ``advance_clock`` expire real budgets through it."""
    return time.monotonic() + _clock_offset


def advance_clock(seconds: float) -> None:
    """Jump the fault clock forward (never backward — monotonicity is the
    one property every Deadline shares)."""
    global _clock_offset
    _clock_offset += max(0.0, float(seconds))


def reset_clock() -> None:
    """Zero the injected offset (test hygiene; live Deadlines started
    under an advanced clock would see time regress, so only reset
    between, not inside, scenarios)."""
    global _clock_offset
    _clock_offset = 0.0


def maybe_delay(site: str, engine: str | None = None) -> float:
    """The pre-dispatch latency hook: when a ``slow`` rule fires for
    (site, engine), advance the fault clock by SLOW_LATENCY_S and return
    the injected seconds (0.0 otherwise).  No sleeping, no exception —
    the latency is visible only to ``clock()`` readers, which is exactly
    the deterministic-deadline-expiry seam the serving loop's shedding
    and the guard's deadline tests need."""
    plan = active()
    if plan is None:
        return 0.0
    if plan.pick(site, engine, kinds=("slow",)) is not None:
        advance_clock(SLOW_LATENCY_S)
        return SLOW_LATENCY_S
    return 0.0


# ---------------------------------------------------------------- injection

def maybe_fail(site: str, engine: str | None = None) -> None:
    """The engine-boundary hook: raise an injected raw-shaped fault when the
    active plan fires for (site, engine).  No active plan = zero work."""
    plan = active()
    if plan is None:
        return
    kind = plan.pick(site, engine)
    if kind is not None:
        raise_fault(kind, site, engine)


def maybe_crash(site: str, point: str | None = None,
                tearable: bool = False) -> str | None:
    """The durability-seam hook: when a ``crash`` rule fires for
    (site, point), return the crash mode — ``"clean"`` (the journal
    record hit the disk whole before the process died) or ``"torn"``
    (the process died mid-``write``, leaving the last record truncated
    mid-frame).  None when no rule fires.

    The caller (mutation.durability) acts on the verdict: tear the
    journal tail for ``"torn"``, then raise ``errors.InjectedCrash`` for
    either mode.  The harness cannot kill the process for real in-test,
    so the contract is that NOTHING between the crash point and the
    recovery entry point may catch InjectedCrash.

    Grammar: ``crash[@scope][=rate]`` where scope is a site/point name
    (``durability``, ``pre_apply``, ``post_apply``, ...) or the special
    scope ``torn``, which switches the mode to a torn write and
    therefore only matches calls with ``tearable=True`` — the one point
    where a frame write is actually in flight (a "torn" crash anywhere
    else would have to tear an ALREADY-COMMITTED record, violating the
    WAL contract the tests pin).  Scheduling is Philox-deterministic
    like every other kind — a fixed seed + call order reproduces the
    exact crash."""
    plan = active()
    if plan is None:
        return None
    for i, r in enumerate(plan.rules):
        if r.kind != "crash":
            continue
        mode = "torn" if r.scope == "torn" else "clean"
        if mode == "torn" and not tearable:
            continue
        if r.scope not in (None, "torn", site, point):
            continue
        if plan._draw(i, f"{site}/{point}") < r.rate:
            return mode
    return None


def maybe_wire(site: str) -> str | None:
    """The RPC-boundary hook (wire/server, wire/client): when a ``wire``
    rule fires for ``site``, return its scope — the fault SHAPE the
    caller must enact:

      ``"conn_drop"``  close the socket mid-pipeline, no goodbye frame
                       (in-flight requests on the peer must fail typed
                       ``PeerClosed``, never raw ConnectionResetError);
      ``"slow_peer"``  advance the fault clock by SLOW_LATENCY_S before
                       the write — a slow-loris peer visible to every
                       deadline reader, with zero real sleeping;
      ``"garbage"``    corrupt the outgoing frame's payload bytes (CRC
                       intact length, broken body) — the receiver must
                       die typed ``CorruptInput``, never a raw struct/
                       json error.

    None when no rule fires.  Grammar: ``wire@<scope>[=rate]`` with the
    scope REQUIRED (validated at parse time) — a scopeless wire fault
    has no defined shape.  ``site`` keys the deterministic draw only
    (``wire.server`` / ``wire.client``), so server- and client-side
    schedules are independent streams off one seed."""
    plan = active()
    if plan is None:
        return None
    for i, r in enumerate(plan.rules):
        if r.kind != "wire":
            continue
        if plan._draw(i, f"{site}/{r.scope}") < r.rate:
            if r.scope == "slow_peer":
                advance_clock(SLOW_LATENCY_S)
            return r.scope
    return None


def should_corrupt(site: str, engine: str | None = None) -> bool:
    """True when a ``silent`` rule fires: the caller must perturb its own
    result (the harness cannot reach into engine outputs generically)."""
    plan = active()
    return (plan is not None
            and plan.pick(site, engine, kinds=("silent",)) is not None)


def raise_fault(kind: str, site: str, engine: str | None):
    tag = f"(injected fault at {site}/{engine or '-'})"
    if kind == "transient":
        raise RuntimeError(f"UNAVAILABLE: device connection dropped {tag}")
    if kind == "oom":
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: out of memory allocating device "
            f"buffer {tag}")
    if kind == "lowering":
        raise NotImplementedError(f"Mosaic lowering failed {tag}")
    if kind == "corrupt":
        raise errors.CorruptInput(f"corrupt serialized input {tag}")
    if kind == "coordinator":
        raise RuntimeError(
            f"DEADLINE_EXCEEDED: coordination service barrier timed "
            f"out {tag}")
    raise ValueError(f"unknown fault kind {kind!r}")
