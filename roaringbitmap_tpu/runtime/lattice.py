"""Closed program-signature lattice: a bounded compile vocabulary
(ROADMAP item 4).

Every engine in this stack compiles one XLA/Pallas program per *plan
signature* — the padded bucket shapes, the op mix, the pooled row
selection, the expression sections.  The pow2 bucketing bounds each
dimension locally, but the cross product of what traffic can request is
unbounded: a diverse (or adversarial) tenant stream makes the serving
loop compile continuously and steady-state p99 tracks traffic *novelty*
instead of hardware.  This module closes the signature space the same
way ``plan_bucket`` closes a single bucket's shape, one level up:

- a :class:`Lattice` is a small per-dimension rung vocabulary
  (op set x pow2 Q x pow2 rows x pow2 key slots x heads plane x
  expression shape-class x pow2 pooled rows x engine rung x placement x
  delta rung);
- :meth:`Lattice.snap` pads any concrete plan shape UP to its covering
  lattice point (dead-query / dead-row / identity padding — the same
  trick the bucket planner already plays below);
- :meth:`Lattice.enumerate_points` materializes the finite vocabulary
  from a traffic profile so ``warmup(profile=...)`` can pre-compile the
  WHOLE lattice at boot (through ``ROARING_TPU_COMPILE_CACHE``);
- after :meth:`Lattice.seal` (the end of warmup), steady state compiles
  **nothing**: any program-cache compile is an *escape* — counted on
  ``rb_lattice_escapes_total{site}``, traced as a ``lattice.escape``
  event, and treated by the serving loop's predictor as an anomaly
  rather than the service time.

The trade is bounded padding waste for a finite program cache; the
waste is measured (``rb_lattice_padding_bytes{site}`` and the
per-dispatch padding fraction on the memory events) so the exchange
stays an engineering number, not a vibe.  ``ROARING_TPU_WARMUP_PROFILE``
activates a lattice from the environment; ``insights.recommend_lattice``
derives a profile from an observed trace dump.  docs/LATTICE.md is the
operator story.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

_log = logging.getLogger("roaringbitmap_tpu.runtime")

ENV_PROFILE = "ROARING_TPU_WARMUP_PROFILE"

#: canonical op order (sorted; ``plan()`` iterates groups sorted by op,
#: so lattice op sets use the same order)
OPS = ("and", "andnot", "or", "xor")


def _pow2_ladder(n: int) -> tuple:
    """(1, 2, 4, ..., next_pow2(n)) — the default rung vector of a
    numeric dimension given only its ceiling."""
    out, v = [], 1
    n = max(1, int(n))
    while v < n:
        out.append(v)
        v *= 2
    out.append(v)
    return tuple(out)


def _cover(value: int, rungs: tuple) -> int | None:
    """Smallest rung >= value, or None when the value is beyond the
    lattice maximum (the out-of-vocabulary case)."""
    for r in rungs:
        if r >= value:
            return r
    return None


@dataclasses.dataclass(frozen=True)
class ProgramSignature:
    """One lattice point: the snapped shape every program-cache key in
    the stack can be derived from.  ``ops`` is the (sorted) op set the
    plan carries one bucket per; ``q``/``rows``/``keys`` are the shared
    padded bucket shape; ``heads`` is whether the bitmap output plane
    compiles; ``expr`` is the expression shape-class depth (0 = flat
    only); ``pool`` is the per-tenant pooled row-selection rung (0 =
    single-set / static pool); ``delta`` is the mutation patch rung
    (0 = a query program)."""

    ops: tuple = OPS
    q: int = 1
    rows: int = 1
    keys: int = 1
    heads: bool = False
    expr: int = 0
    pool: int = 0
    engine: str = "auto"
    placement: str = "auto"
    delta: int = 0
    #: analytics shape-class: the padded slice depth the plan's value
    #: scans cover (0 = no analytics steps) — docs/ANALYTICS.md
    bsi: int = 0

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ops"] = list(self.ops)
        return d


@dataclasses.dataclass
class Lattice:
    """The closed vocabulary.  Dimension fields are ascending tuples;
    mutable bookkeeping (seal state, escape count, warmed expression
    signatures, the warmup pin) is excluded from equality so the
    env-knob round trip compares vocabularies, not lifecycles."""

    q: tuple = _pow2_ladder(64)
    rows: tuple = _pow2_ladder(64)
    keys: tuple = _pow2_ladder(8)
    pool: tuple = _pow2_ladder(256)
    op_sets: tuple = (OPS,)
    heads: tuple = (False, True)
    expr: tuple = (0,)
    engines: tuple = ("auto",)
    placements: tuple = ("auto",)
    delta: tuple = ()
    #: analytics slice-depth rungs (pow2-padded column depths x the
    #: predicate classes their scan tags enumerate); empty = analytics
    #: traffic is out of vocabulary (its compiles are escapes)
    bsi: tuple = ()
    sealed: bool = dataclasses.field(default=False, compare=False)
    escapes: int = dataclasses.field(default=0, compare=False)
    _pin: object = dataclasses.field(default=None, compare=False,
                                     repr=False)
    #: expression signatures the warmup compiled (novel DAGs at a warmed
    #: depth still compile, so they are still escapes — honesty over
    #: optimism); informational, the sealed-compile rule is the gate
    _expr_sigs: set = dataclasses.field(default_factory=set,
                                        compare=False, repr=False)

    def __post_init__(self):
        for name in ("q", "rows", "keys", "pool", "expr", "delta",
                     "bsi"):
            setattr(self, name, tuple(sorted(
                {int(v) for v in getattr(self, name)})))
        self.op_sets = tuple(sorted(
            {tuple(sorted(s)) for s in self.op_sets}))
        self.heads = tuple(sorted(bool(h) for h in self.heads))
        self.engines = tuple(sorted(str(e) for e in self.engines))
        self.placements = tuple(sorted(str(p) for p in self.placements))
        if 0 not in self.expr:
            self.expr = (0,) + self.expr
        for s in self.op_sets:
            bad = [op for op in s if op not in OPS]
            if bad:
                raise ValueError(f"unknown ops in lattice op set: {bad}")

    # ------------------------------------------------------------ snapping

    def _dim(self, value: int, rungs: tuple, pinned: int | None):
        got = _cover(value, rungs)
        if got is None:
            return None
        if pinned is not None and pinned >= value and pinned in rungs:
            return max(got, pinned)
        return got

    def snap_ops(self, present) -> tuple | None:
        """Smallest covering op set in the vocabulary (ties break toward
        fewer dead buckets), or None when nothing covers."""
        need = frozenset(present)
        best = None
        pin = self._pin.ops if self._pin is not None else None
        if pin is not None and need <= frozenset(pin) \
                and tuple(sorted(pin)) in self.op_sets:
            return tuple(sorted(pin))
        for s in self.op_sets:
            if need <= frozenset(s) and (best is None
                                         or len(s) < len(best)):
                best = s
        return best

    def snap(self, *, ops, q: int, rows: int, keys: int, heads: bool,
             expr: int = 0, pool: int = 0, placement: str = "auto",
             bsi: int = 0) -> ProgramSignature | None:
        """The covering lattice point of a concrete plan shape, or None
        when any dimension is beyond the vocabulary (the plan then keeps
        its exact pow2 shapes and its first compile is an escape).
        Inside a warmup ``pin`` the pinned point wins wherever it covers
        the need — that is how warmup compiles the whole vocabulary
        instead of only each point's minimal shadow."""
        p = self._pin
        ops_s = self.snap_ops(ops)
        q_s = self._dim(max(1, q), self.q, p.q if p else None)
        r_s = self._dim(max(1, rows), self.rows, p.rows if p else None)
        k_s = self._dim(max(1, keys), self.keys, p.keys if p else None)
        pool_s = 0
        if pool:
            pool_s = self._dim(pool, self.pool, p.pool if p else None)
        expr_s = 0
        if expr:
            expr_s = _cover(expr, tuple(d for d in self.expr if d))
        bsi_s = 0
        if bsi:
            bsi_s = _cover(bsi, self.bsi)
            if bsi_s is None:
                return None     # analytics depth beyond the vocabulary
        heads_s = bool(heads)
        if p is not None and p.heads and not heads_s:
            heads_s = True
        if heads_s not in self.heads:
            if True in self.heads and not heads_s:
                heads_s = True      # widen: a heads plane covers both
            else:
                return None
        if (ops_s is None or q_s is None or r_s is None or k_s is None
                or (pool and pool_s is None) or (expr and not expr_s)):
            return None
        if placement not in self.placements \
                and "auto" not in self.placements:
            return None
        return ProgramSignature(ops=ops_s, q=q_s, rows=r_s, keys=k_s,
                                heads=heads_s, expr=expr_s, pool=pool_s,
                                placement=placement, bsi=bsi_s)

    def contains(self, point: ProgramSignature | None) -> bool:
        """Vocabulary membership of a point (per-dimension; ``engine``
        and ``placement`` treat a vocabulary ``"auto"`` as a wildcard —
        the resolved rung is a backend fact, not a traffic dimension)."""
        if point is None:
            return False
        if point.delta:
            return point.delta in self.delta
        if point.bsi and point.bsi not in self.bsi:
            return False
        return (tuple(sorted(point.ops)) in self.op_sets
                and point.q in self.q and point.rows in self.rows
                and point.keys in self.keys
                and point.heads in self.heads
                and point.expr in self.expr
                and (point.pool == 0 or point.pool in self.pool)
                and (point.engine in self.engines
                     or "auto" in self.engines)
                and (point.placement in self.placements
                     or "auto" in self.placements))

    @contextlib.contextmanager
    def pin(self, point: ProgramSignature):
        """Warmup context: ``snap`` prefers ``point`` wherever it covers
        the concrete need, so a representative mini-batch compiles the
        program of the TARGET lattice point instead of its own minimal
        covering shape."""
        prev, self._pin = self._pin, point
        try:
            yield self
        finally:
            self._pin = prev

    # --------------------------------------------------------- enumeration

    def enumerate_points(self, pooled: bool = False) -> list:
        """The finite vocabulary, materialized: flat points are the
        cross product of the shape dimensions (pooled engines add the
        pooled-row rung), expression shape-classes contribute one point
        per depth (their reduce buckets snap through the same shape
        rungs; their DAG programs are warmed from the representative
        ``rung_expressions`` shapes), delta rungs one point each."""
        pts = []
        pools = self.pool if pooled else (0,)
        for ops in self.op_sets:
            for q in self.q:
                for rows in self.rows:
                    for keys in self.keys:
                        for heads in self.heads:
                            for pool in pools:
                                pts.append(ProgramSignature(
                                    ops=ops, q=q, rows=rows, keys=keys,
                                    heads=bool(heads), pool=pool))
        for d in self.expr:
            if d:
                pts.append(ProgramSignature(expr=d))
        for d in self.bsi:
            # one analytics shape-class per padded slice depth: the
            # engines warm representative predicate/aggregate programs
            # over every attached column the rung covers
            pts.append(ProgramSignature(bsi=d))
        for d in self.delta:
            pts.append(ProgramSignature(ops=(), delta=d))
        return pts

    def n_points(self, pooled: bool = False) -> int:
        """Vocabulary size, computed arithmetically — health endpoints
        poll this, so it must not materialize the cross product."""
        flat = (len(self.op_sets) * len(self.q) * len(self.rows)
                * len(self.keys) * len(self.heads)
                * (len(self.pool) if pooled else 1))
        return (flat + sum(1 for d in self.expr if d)
                + len(self.bsi) + len(self.delta))

    # ------------------------------------------------------------ lifecycle

    def seal(self) -> None:
        """End of warmup: from here on, steady state compiles nothing —
        every later program-cache compile counts as an escape."""
        self.sealed = True

    def note_expr(self, sig) -> None:
        self._expr_sigs.add(sig)

    def expr_known(self, sig) -> bool:
        return sig in self._expr_sigs

    # --------------------------------------------------------- serialization

    def to_profile(self) -> str:
        """Canonical profile string — ``from_profile`` round-trips it
        (the env-knob contract, pinned by tests/test_lattice.py)."""
        def num(vals):
            # a single rung keeps its trailing comma so the parse stays
            # an explicit list, not a bare-ceiling pow2 ladder
            return (",".join(str(v) for v in vals)
                    + ("," if len(vals) == 1 else ""))

        dims = [
            "q=" + num(self.q),
            "rows=" + num(self.rows),
            "keys=" + num(self.keys),
            "pool=" + num(self.pool),
            "ops=" + "|".join(",".join(s) for s in self.op_sets),
            "heads=" + ("both" if len(self.heads) == 2
                        else ("bitmap" if self.heads[0] else
                              "cardinality")),
            "expr=" + ",".join(str(v) for v in self.expr),
            "engines=" + ",".join(self.engines),
            "placements=" + ",".join(self.placements),
        ]
        if self.bsi:
            dims.append("bsi=" + num(self.bsi))
        if self.delta:
            dims.append("delta=" + num(self.delta))
        return ";".join(dims)

    @classmethod
    def from_profile(cls, spec) -> "Lattice":
        """Build a lattice from a traffic profile: an existing Lattice
        (pass-through), a dict of dimension overrides, or the
        ``ROARING_TPU_WARMUP_PROFILE`` string grammar::

            q=64;rows=256;keys=16;ops=or,and,xor,andnot;heads=both;
            expr=2;pool=512;delta=8

        Numeric dimensions take either one ceiling (expanded to the
        full pow2 ladder) or an explicit comma list of rungs — sparse
        rung lists are how a profile bounds BOTH the vocabulary size
        and the warmup compile count while still covering all traffic
        under the maxima (snap always finds a covering rung)."""
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            spec = parse_profile(spec)
        kw = dict(spec)
        for name in ("q", "rows", "keys", "pool"):
            v = kw.get(name)
            if isinstance(v, int):
                kw[name] = _pow2_ladder(v)
        if isinstance(kw.get("delta"), int):
            kw["delta"] = (kw["delta"],)
        if isinstance(kw.get("bsi"), int):
            kw["bsi"] = (kw["bsi"],)
        if isinstance(kw.get("expr"), int):
            kw["expr"] = (0, kw["expr"]) if kw["expr"] else (0,)
        return cls(**kw)


def parse_profile(s: str) -> dict:
    """``ROARING_TPU_WARMUP_PROFILE`` grammar -> Lattice kwargs."""
    out: dict = {}
    for part in s.split(";"):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key in ("q", "rows", "keys", "pool", "expr", "delta", "bsi"):
            # bare "q=64" = the full pow2 ladder up to 64; a comma makes
            # the list explicit ("q=8,64" — or "q=64," for one sparse
            # rung), which is how profiles keep the vocabulary small
            vals = tuple(int(v) for v in val.split(",") if v != "")
            out[key] = vals[0] if ("," not in val
                                   and key not in ("expr",)) else vals
        elif key == "ops":
            out["op_sets"] = tuple(
                tuple(sorted(op.strip() for op in group.split(",")))
                for group in val.split("|"))
        elif key == "heads":
            out["heads"] = {"both": (False, True), "bitmap": (True,),
                            "cardinality": (False,)}[val]
        elif key == "engines":
            out["engines"] = tuple(v.strip() for v in val.split(","))
        elif key == "placements":
            out["placements"] = tuple(v.strip() for v in val.split(","))
        else:
            raise ValueError(
                f"unknown lattice profile dimension {key!r} in {s!r}")
    return out


# ----------------------------------------------------------- module state

_active: Lattice | None = None
_generation = 0


def activate(lat: Lattice | str | dict) -> Lattice:
    """Make ``lat`` THE process lattice: every engine's planner snaps
    through it from the next plan on (plan caches key on the lattice
    generation, so stale unsnapped plans can never replay)."""
    global _active, _generation
    _active = Lattice.from_profile(lat)
    _generation += 1
    return _active


def deactivate() -> None:
    global _active, _generation
    _active = None
    _generation += 1


def active() -> Lattice | None:
    return _active


def refresh_from_env() -> Lattice | None:
    """Re-read ``ROARING_TPU_WARMUP_PROFILE``: set -> activate a lattice
    from it (idempotent per value), unset -> leave programmatic state
    alone.  Called at import; call again after mutating the env.  A
    malformed profile logs one warning and activates nothing — importing
    the library (read-only tooling included) must survive a typo; the
    explicit ``warmup(profile=...)``/``activate()`` paths still raise."""
    spec = os.environ.get(ENV_PROFILE)
    if spec:
        try:
            lat = Lattice.from_profile(spec)
        except (ValueError, KeyError, TypeError) as exc:
            _log.warning("%s=%r is not a valid lattice profile, no "
                         "lattice activated: %s", ENV_PROFILE, spec, exc)
            return _active
        if _active is None or _active != lat:
            return activate(lat)
        return _active
    return _active


def plan_token():
    """The lattice component of every plan-cache key: None while no
    lattice is active, else (generation, warmup pin) — activation and
    pinned warmup plans must never collide with each other or with
    unsnapped plans."""
    if _active is None:
        return None
    return (_generation, _active._pin)


def note_compile(site: str, engine: str, point, compile_s: float) -> bool:
    """Called by every engine's program-cache MISS path.  Before the
    lattice seals (boot/warmup) compiles are the expected cold path;
    after it, ANY compile is an escape: counted, traced, and visible to
    the serving predictor.  Returns True when an escape was recorded."""
    lat = _active
    if lat is None or not lat.sealed:
        return False
    lat.escapes += 1
    obs_metrics.counter("rb_lattice_escapes_total", site=site).inc()
    ev = {"site": site, "engine": engine,
          "in_vocabulary": lat.contains(point),
          "compile_ms": round(compile_s * 1e3, 3)}
    if point is not None:
        ev["point"] = point.as_dict()
    obs_trace.current().event("lattice.escape", **ev)
    return True


def record_padding(site: str, padding_bytes: int, fraction: float) -> None:
    """Per-dispatch padding accounting: the bytes the snapped shapes
    stream beyond the exact plan (the price of the bounded vocabulary),
    plus the padded fraction as a gauge — what the bench lane and the
    acceptance bound read."""
    if padding_bytes:
        obs_metrics.counter("rb_lattice_padding_bytes",
                            site=site).inc(padding_bytes)
    obs_metrics.gauge("rb_lattice_padding_fraction",
                      site=site).set(round(fraction, 6))


def escape_total() -> int:
    lat = _active
    return int(lat.escapes) if lat is not None else 0


def sealed_active() -> bool:
    """True when a sealed lattice governs the process — the serving
    loop's signal that steady state is supposed to compile nothing."""
    lat = _active
    return lat is not None and lat.sealed


refresh_from_env()
