"""Guarded dispatch: bounded retry, engine fallback chain, deadline, shadow.

Every query-serving entry point (parallel.batch_engine, the
aggregation.wide_* functions, sharding.wide_aggregate_sharded) routes its
engine execution through ``run_with_fallback``.  The contract:

- **Transient faults** (errors.retryable) get bounded retries with
  exponential backoff on the SAME rung; exhausted retries demote.
- **Lowering faults** demote immediately — recompiling the same shape on
  the same engine is deterministic failure.
- **ResourceExhausted** first offers the call site a split (the batch
  engine halves Q — smaller gathers, smaller peak HBM), then demotes.
- **CorruptInput** is the input's fault: fatal immediately, no rung can
  parse garbage into a correct answer.
- Every chain ends at the call site's **CPU sequential reference** — the
  bit-exact host path PR 1's parity suites pinned every engine against —
  so degradation never changes results, only throughput.
- An expired **deadline** stops the whole ladder and re-raises the last
  classified fault (typed, never a bare RuntimeError).
- Exceptions ``errors.classify`` cannot type are programming errors and
  propagate untouched: the fault layer must never mask a real bug.

The opt-in **shadow cross-check** (``ROARING_TPU_SHADOW=<rate>[:<seed>]``
or GuardPolicy.shadow_rate) re-runs a sampled fraction of queries on the
sequential reference after a successful engine dispatch and raises
ShadowMismatch on any divergence — the only detector for an engine that
silently miscompiles.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import zlib
from typing import Callable

import numpy as np

from . import errors, faults
from ..obs import flight as obs_flight
from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import trace as obs_trace

_log = logging.getLogger("roaringbitmap_tpu.runtime")

#: the terminal rung of every chain: the CPU sequential reference path
SEQUENTIAL = "sequential"

#: the mesh-sharded engine's fallback vocabulary (parallel.sharded_engine,
#: docs/BATCH_ENGINE.md "Mesh-sharded execution"): a sharded dispatch
#: demotes MESH -> SINGLE_DEVICE (the un-sharded pooled engine, which owns
#: its own pallas->xla->xla-vmap ladder internals) -> SEQUENTIAL, each
#: rung bit-exact — losing the mesh costs throughput, never availability
#: or bits, the same contract as every other chain here
MESH = "mesh"
SINGLE_DEVICE = "single"

#: the pod front door's top rung, above the mesh ladder
#: (serving.frontdoor, docs/POD.md): a classified host-loss fault
#: (CoordinatorTimeout / HostLost) first RE-ROUTES the affected tenants
#: to an alive replica — same data, different host, zero recompute —
#: before any engine demotion happens; tenants with no replica demote to
#: single-host mode (the authoritative un-sharded pooled engine).  The
#: full pod ladder reads reroute -> mesh -> single -> sequential, every
#: rung bit-exact and typed like the chains below it.
REROUTE = "reroute"

#: sentinel a ResourceExhausted splitter returns to decline (fall through
#: to demotion)
NO_SPLIT = object()

ENV_MAX_ATTEMPTS = "ROARING_TPU_MAX_ATTEMPTS"
ENV_BACKOFF = "ROARING_TPU_BACKOFF_S"
ENV_DEADLINE = "ROARING_TPU_DEADLINE_S"
ENV_SHADOW = "ROARING_TPU_SHADOW"
ENV_HBM_BUDGET = "ROARING_TPU_HBM_BUDGET"
ENV_PIPELINE_DEPTH = "ROARING_TPU_PIPELINE_DEPTH"
ENV_SLO_MS = obs_slo.ENV_SLO_MS


def parse_bytes(spec: str) -> int:
    """``ROARING_TPU_HBM_BUDGET`` value: plain bytes or K/M/G-suffixed
    (binary units — "64M" = 64 MiB).  0 or negative = unlimited."""
    s = spec.strip()
    mult = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}.get(s[-1:].lower())
    if mult is not None:
        s = s[:-1]
    try:
        return int(float(s) * (mult or 1))
    except ValueError:
        raise ValueError(
            f"{ENV_HBM_BUDGET} must be bytes with an optional K/M/G "
            f"suffix, got {spec!r}") from None


@dataclasses.dataclass(frozen=True)
class GuardPolicy:
    """Knobs for one guarded dispatch; ``from_env`` is the serving default."""

    max_attempts: int = 3          # per rung, transient faults only
    backoff_base: float = 0.02    # seconds; doubles per retry
    backoff_factor: float = 2.0
    backoff_max: float = 1.0
    deadline: float | None = None  # whole-dispatch wall budget, seconds
    shadow_rate: float = 0.0       # fraction of queries cross-checked
    shadow_seed: int = 0x5AD0
    #: predicted-peak HBM ceiling per dispatch, bytes: a batch predicted
    #: past it is halved BEFORE dispatch (proactive split).  None =
    #: resolve from the backend (free memory where reported, else
    #: unlimited); <= 0 = explicitly unlimited.
    hbm_budget: int | None = None
    #: in-flight launch window of the multi-set pipelined dispatcher
    #: (parallel.multiset): launch k+1 is planned/packed on the host while
    #: up to this many launches run on device.  1 disables pipelining
    #: (strictly serial plan -> dispatch -> drain); the default 2 is the
    #: classic double buffer (one launch computing, one draining); any
    #: depth N >= 2 keeps up to N-1 launches in flight — bit-exact at
    #: every depth, drain-time faults re-run that launch synchronously
    #: regardless of depth (tests/test_multiset.py pins N in {1, 2, 4}).
    pipeline_depth: int = 2
    #: per-query latency objective, milliseconds (obs.slo.SloPolicy /
    #: ROARING_TPU_SLO_MS): every guarded execute is attributed per phase
    #: and counted attained/missed against it; None disables SLO
    #: accounting (the guard's own hard ``deadline`` is a separate,
    #: enforcement-side knob — an SLO miss is recorded, not raised).
    slo_deadline_ms: float | None = None
    sleep: Callable[[float], None] = time.sleep

    @classmethod
    def from_env(cls, **overrides) -> "GuardPolicy":
        env: dict = {}
        if ENV_MAX_ATTEMPTS in os.environ:
            env["max_attempts"] = max(1, int(os.environ[ENV_MAX_ATTEMPTS]))
        if ENV_BACKOFF in os.environ:
            env["backoff_base"] = float(os.environ[ENV_BACKOFF])
        if ENV_DEADLINE in os.environ:
            env["deadline"] = float(os.environ[ENV_DEADLINE])
        if ENV_SHADOW in os.environ:
            spec = os.environ[ENV_SHADOW]
            rate, _, seed = spec.partition(":")
            env["shadow_rate"] = float(rate)
            if seed:
                env["shadow_seed"] = int(seed, 0)
        if ENV_HBM_BUDGET in os.environ:
            env["hbm_budget"] = parse_bytes(os.environ[ENV_HBM_BUDGET])
        if ENV_PIPELINE_DEPTH in os.environ:
            env["pipeline_depth"] = max(
                1, int(os.environ[ENV_PIPELINE_DEPTH]))
        if ENV_SLO_MS in os.environ:
            env["slo_deadline_ms"] = float(os.environ[ENV_SLO_MS])
        env.update(overrides)
        return cls(**env)

    def for_remaining(self, remaining_s: float) -> "GuardPolicy":
        """Per-dispatch policy derived from an admitted request's
        REMAINING deadline: the hard guard ``deadline`` (what bounds
        retry/backoff inside ``run_with_fallback``) and the SLO
        accounting deadline are both clamped to ``remaining_s``, so a
        retry storm can never spend more wall than the query has left —
        the two knobs cannot disagree past admission (the serving loop's
        deadline-propagation contract, docs/SERVING.md)."""
        remaining_s = max(0.0, float(remaining_s))
        dl = (remaining_s if self.deadline is None
              else min(self.deadline, remaining_s))
        slo = remaining_s * 1e3
        if self.slo_deadline_ms is not None:
            slo = min(self.slo_deadline_ms, slo)
        return dataclasses.replace(self, deadline=dl, slo_deadline_ms=slo)


class Deadline:
    """Monotonic wall budget shared across retries, rungs, and recursive
    batch splits (a split must not reset the clock).  The default clock
    is the FAULT clock (``faults.clock`` — real monotonic plus injected
    ``slow`` latency), so deadline expiry is deterministically testable
    without wall-clock flakiness."""

    def __init__(self, seconds: float | None, clock=faults.clock):
        self.seconds = seconds
        self._clock = clock
        self._t0 = clock()

    def expired(self) -> bool:
        return (self.seconds is not None
                and self._clock() - self._t0 >= self.seconds)

    def remaining(self) -> float:
        if self.seconds is None:
            return float("inf")
        return max(0.0, self.seconds - (self._clock() - self._t0))


#: backend free-memory budget cache: (monotonic deadline, value).  The
#: default budget costs a device.memory_stats() allocator query, which
#: must not ride every dispatch of a serving loop at the dispatch floor —
#: free memory moves slowly next to query rate, so a short TTL is an
#: honest planning input at none of the per-execute cost.
_FREE_BUDGET_TTL_S = 1.0
_free_budget_cache: tuple[float, int | None] | None = None


def resolve_hbm_budget(policy: GuardPolicy | None = None) -> int | None:
    """Effective per-dispatch HBM budget, bytes, or None for unlimited.

    Order: an explicit policy/env value wins (``ROARING_TPU_HBM_BUDGET``,
    <= 0 meaning unlimited); otherwise the backend's reported free memory
    (``device.memory_stats()`` — TPU/GPU; cached for
    ``_FREE_BUDGET_TTL_S`` so the allocator query never rides every
    dispatch); otherwise unlimited (the CPU backend reports nothing, and
    a proxy host has no HBM to protect).  The batch engine compares its
    predicted dispatch peak (``insights.predict_batch_dispatch_bytes``)
    against this and halves Q BEFORE dispatching — the proactive form of
    the reactive OOM split."""
    global _free_budget_cache
    policy = policy or GuardPolicy.from_env()
    if policy.hbm_budget is not None:
        return policy.hbm_budget if policy.hbm_budget > 0 else None
    now = time.monotonic()
    if _free_budget_cache is not None and now < _free_budget_cache[0]:
        return _free_budget_cache[1]
    from ..obs import memory as obs_memory

    free = obs_memory.backend_free_bytes()
    _free_budget_cache = (now + _FREE_BUDGET_TTL_S, free)
    return free


def chain_from(engine: str, ladder: tuple) -> tuple:
    """Fallback chain starting at ``engine``'s rung of ``ladder`` and
    always ending at the sequential reference.  An engine outside the
    ladder (already-resolved special modes) gets itself + sequential."""
    if engine in ladder:
        return tuple(ladder[ladder.index(engine):]) + (SEQUENTIAL,)
    return (engine, SEQUENTIAL)


# --------------------------------------------------------- dispatch stats
#
# A server that silently demotes to a slower rung forever is the incident
# this layer exists to survive — it must not also be invisible.  Every
# retry / demotion / sequential landing bumps a per-site counter (and logs
# at the matching level); operators poll dispatch_stats() next to
# BatchEngine.cache_stats().  The same events are first-class instruments
# in the unified registry (rb_dispatch_events_total{site,event} — see
# docs/OBSERVABILITY.md); this dict is the legacy per-site view whose
# exact shape operator tooling pins.

_dispatch_stats: dict = {}


def _bump(site: str, key: str) -> None:
    row = _dispatch_stats.setdefault(
        site, {"retries": 0, "demotions": 0, "sequential": 0})
    row[key] += 1
    obs_metrics.counter("rb_dispatch_events_total", site=site,
                        event=key).inc()


def dispatch_stats(site: str | None = None) -> dict:
    """Per-site retry/demotion/sequential-landing counters (copies)."""
    if site is not None:
        return dict(_dispatch_stats.get(
            site, {"retries": 0, "demotions": 0, "sequential": 0}))
    return {s: dict(row) for s, row in _dispatch_stats.items()}


def reset_dispatch_stats() -> None:
    _dispatch_stats.clear()


def _deadline_error(site: str, dl: Deadline, last):
    msg = f"{site}: dispatch deadline of {dl.seconds}s exhausted"
    if last is None:
        return errors.TransientDeviceError(msg)
    err = type(last)(f"{msg}; last fault: {last}")
    err.__cause__ = last
    return err


def _log_transition(level: int, site: str, event: str, engine_from: str,
                    engine_to: str | None, fault, span=None,
                    **fields) -> None:
    """One guard decision, emitted through ONE schema on two surfaces:
    a structured log record (``extra=`` fields, ``rb_`` prefixed, for log
    scrapers) and a span event on the enclosing trace span — so scraped
    logs and JSONL traces join on identical (site, engine_from,
    engine_to, error_class) keys."""
    error_class = type(fault).__name__ if fault is not None else None
    _log.log(level, "%s: %s %s -> %s: %s", site, event, engine_from,
             engine_to or "-", fault,
             extra={"rb_site": site, "rb_event": event,
                    "rb_engine_from": engine_from,
                    "rb_engine_to": engine_to,
                    "rb_error_class": error_class,
                    **{f"rb_{k}": v for k, v in fields.items()}})
    (span if span is not None else obs_trace.current()).event(
        event, site=site, engine_from=engine_from, engine_to=engine_to,
        error_class=error_class, **fields)
    if level >= logging.WARNING:
        # demote/fatal/sequential rungs feed the flight ring too: the
        # black box must hold the ladder walk even with tracing off
        obs_flight.record("guard", event=event, site=site,
                          engine_from=engine_from, engine_to=engine_to,
                          error_class=error_class)


def _observe_latency(site: str, engine: str, seconds: float) -> None:
    """Per-(site, engine) execute-latency histogram: the serving-latency
    instrument obs.snapshot() / the Prometheus renderer export."""
    obs_metrics.histogram("rb_execute_latency_seconds", site=site,
                          engine=engine).observe(seconds)


def run_with_fallback(site: str, chain, attempt, *, policy=None,
                      sequential=None, on_resource_exhausted=None,
                      deadline: Deadline | None = None):
    """Run ``attempt(rung)`` down the fallback chain; returns
    ``(result, rung_used)``.

    ``sequential()`` (no args) is the terminal reference path, appended to
    the chain when not already present.  ``on_resource_exhausted(rung,
    fault, deadline)`` may return a recovered result (e.g. from a split
    batch) or NO_SPLIT to decline.
    """
    policy = policy or GuardPolicy.from_env()
    dl = deadline or Deadline(policy.deadline)
    rungs = [r for r in chain if r != SEQUENTIAL]
    if sequential is not None:
        rungs.append(SEQUENTIAL)
    if not rungs:
        raise ValueError(f"{site}: empty fallback chain")
    last = None
    # SLO accounting per guarded dispatch: a no-op when the engines'
    # execute() already opened the per-query context (the outermost owns
    # attribution), the covering context for the sites that have no
    # execute() wrapper (aggregation, sharding).  The span is the OUTER
    # context manager so the query context closes first and its miss
    # event lands on the still-open guard.dispatch span.
    with obs_trace.span("guard.dispatch", site=site) as sp, \
            obs_slo.query(site, deadline_ms=policy.slo_deadline_ms):
        demotion_chain: list = []   # "pallas->xla"-style hops, in order
        retries = 0

        def _done(res, rung, **tags):
            obs_slo.note_engine(rung)
            sp.tag(rung_used=rung, retries=retries,
                   demotions=len(demotion_chain),
                   demotion_chain=demotion_chain, **tags)
            return res, rung

        def _demote(rung, next_rung, fault, **fields):
            _bump(site, "demotions")
            demotion_chain.append(f"{rung}->{next_rung or '-'}")
            _log_transition(logging.WARNING, site, "demote", rung,
                            next_rung, fault, span=sp, **fields)

        for ri, rung in enumerate(rungs):
            next_rung = rungs[ri + 1] if ri + 1 < len(rungs) else None
            backoff = policy.backoff_base
            for att in range(policy.max_attempts):
                # injected pre-dispatch latency (the `slow` fault kind)
                # lands before the expiry check, so a slowed attempt can
                # deterministically exhaust the deadline
                faults.maybe_delay(site, rung)
                if dl.expired():
                    raise _deadline_error(site, dl, last)
                try:
                    if rung == SEQUENTIAL:
                        _bump(site, "sequential")
                        _log_transition(
                            logging.WARNING, site, "sequential",
                            rungs[ri - 1] if ri else SEQUENTIAL,
                            SEQUENTIAL, last, span=sp)
                        t0 = time.perf_counter()
                        res = sequential()
                        _observe_latency(site, SEQUENTIAL,
                                         time.perf_counter() - t0)
                        return _done(res, SEQUENTIAL)
                    t0 = time.perf_counter()
                    res = attempt(rung)
                    _observe_latency(site, rung, time.perf_counter() - t0)
                    return _done(res, rung)
                except Exception as exc:
                    fault = errors.classify(exc)
                    if fault is None or isinstance(fault,
                                                   errors.ShadowMismatch):
                        raise      # programming error / proven corruption
                    last = fault
                    if isinstance(fault, errors.CorruptInput):
                        # the input is garbage on every rung; fatal now
                        _log_transition(logging.ERROR, site, "fatal",
                                        rung, None, fault, span=sp)
                        if fault is exc:
                            raise
                        raise fault from exc
                    if isinstance(fault, errors.ResourceExhausted):
                        if on_resource_exhausted is not None:
                            res = on_resource_exhausted(rung, fault, dl)
                            if res is not NO_SPLIT:
                                return _done(res, rung, split=True)
                        # demote: same shape would OOM again
                        _demote(rung, next_rung, fault)
                        break
                    if isinstance(fault, errors.EngineLoweringError):
                        # demote: deterministic compile failure
                        _demote(rung, next_rung, fault)
                        break
                    # retryable (transient / coordinator): bounded backoff
                    if att + 1 >= policy.max_attempts:
                        _demote(rung, next_rung, fault,
                                reason="retries_exhausted")
                        break
                    _bump(site, "retries")
                    retries += 1
                    _log_transition(logging.DEBUG, site, "retry", rung,
                                    rung, fault, span=sp, attempt=att + 1)
                    policy.sleep(min(backoff, dl.remaining()))
                    backoff = min(backoff * policy.backoff_factor,
                                  policy.backoff_max)
        assert last is not None  # a rung can only exit its loop via a fault
        raise last


# ------------------------------------------------------------ shadow checks

_shadow_counters: dict = {}


def shadow_sample(n: int, rate: float, seed: int, site: str) -> list[int]:
    """Deterministic sample of query indices to cross-check: rate-sized
    Bernoulli per index, keyed by a per-site call counter so repeated
    batches sample different (but reproducible) subsets."""
    if rate <= 0.0 or n == 0:
        return []
    if rate >= 1.0:
        return list(range(n))
    call = _shadow_counters.get(site, 0)
    _shadow_counters[site] = call + 1
    rng = np.random.default_rng((seed, zlib.crc32(site.encode()), call))
    return [i for i in range(n) if rng.random() < rate]
