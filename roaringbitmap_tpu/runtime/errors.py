"""Typed error taxonomy for the hardened query runtime.

The reference library runs in one JVM and lets every failure surface as a
Java exception to the caller; this rebuild dispatches work to accelerators,
distributed coordinators, and serialized byte streams, where raw failures
arrive as stringly-typed XLA status messages, gRPC tracebacks, or numpy
struct errors.  This module is the single place those raw shapes are
classified into a small taxonomy that callers (and runtime.guard) can act
on mechanically:

  retryable              -> TransientDeviceError, CoordinatorTimeout
  demote / split         -> ResourceExhausted
  demote (deterministic) -> EngineLoweringError
  fatal (input's fault)  -> CorruptInput (== format.spec.InvalidRoaringFormat)
  fatal (engine's fault) -> ShadowMismatch
  wire boundary          -> WireError tree (docs/WIRE.md): hello/auth/
                            backpressure/peer-closed/remote-failed

``classify`` maps a raw exception to a taxonomy instance, or ``None`` when
the exception looks like a programming error — the guard re-raises those
untouched so the fault-tolerance layer never masks a real bug.
"""

from __future__ import annotations

# Corrupt serialized input already has a contracted type at the format
# layer; the runtime taxonomy re-exports it rather than inventing a second
# class for the same fault (satellite: format errors surface as
# runtime.errors.CorruptInput).
from ..format.spec import InvalidRoaringFormat

CorruptInput = InvalidRoaringFormat


class RoaringRuntimeError(Exception):
    """Base of the runtime taxonomy (CorruptInput subclasses ValueError
    via InvalidRoaringFormat instead — it predates this module and is
    raised by parse layers that never import the runtime)."""

    #: bounded retry on the same engine rung can plausibly succeed
    retryable = False
    #: falling to the next engine rung can plausibly succeed
    demotable = False


class TransientDeviceError(RoaringRuntimeError):
    """Device/runtime hiccup (UNAVAILABLE, ABORTED, connection drop):
    retry with backoff; exhausted retries demote."""

    retryable = True
    demotable = True


class ResourceExhausted(RoaringRuntimeError):
    """Device OOM / allocator failure: halve the batch (less peak HBM)
    or demote to a cheaper engine; retrying the same shape cannot help."""

    demotable = True


class EngineLoweringError(RoaringRuntimeError):
    """Compiler/lowering failure (Mosaic rejection, unsupported primitive):
    deterministic for a given (engine, shape) — demote immediately."""

    demotable = True


class CoordinatorTimeout(RoaringRuntimeError):
    """Distributed coordinator unreachable / barrier timed out.  Message
    names the coordinator address and process id (multihost.initialize)."""

    retryable = True
    demotable = True


class HostLost(CoordinatorTimeout):
    """A pod host stopped answering (process death, network partition,
    preemption): the host-granular form of :class:`CoordinatorTimeout`.
    Raised typed by the pod front door (serving.frontdoor) when it marks
    a host down; the message names the host id.  Retryable/demotable
    like its base — the pod ladder's ``reroute`` rung serves the
    affected tenants from a replica or the single-host fallback
    (docs/POD.md "Host loss")."""


class ShadowMismatch(RoaringRuntimeError):
    """Shadow cross-check found an engine result diverging from the CPU
    sequential reference: silent corruption — always fatal, never retried
    (a retry that happens to pass would hide a miscompiling engine)."""


class InjectedCrash(RoaringRuntimeError):
    """A ``crash`` fault rule fired (runtime.faults): the process is
    simulating its own death between a journal append and the in-memory
    apply.  Deliberately NOT retryable/demotable — nothing above the
    durability layer may catch-and-continue past a crash point; the only
    legal continuation is a fresh recovery (durability.recover_tenant),
    which is exactly what the crash-recovery property tests drive."""


class WireError(RoaringRuntimeError):
    """Base of the wire-boundary taxonomy (docs/WIRE.md).  Everything
    the binary RPC front door can do to a caller surfaces as one of
    these (or as a re-hydrated serving/runtime type carried inside a
    typed error frame) — raw ``socket``/``struct``/``json`` errors
    never cross the boundary in either direction.  ``code`` is the wire
    error-frame code the class round-trips through."""

    code = "wire"

    def __init__(self, msg: str = "", **context):
        super().__init__(msg)
        #: JSON-able detail that rode the error frame (reason, tenant,
        #: req_id, ...) — mirrors AdmissionRejected's context dict
        self.context = dict(context)


class WireHelloMismatch(WireError):
    """The versioned hello failed: wrong magic, wrong protocol version,
    or a non-hello first frame.  Connection-fatal by contract (there is
    no common dialect to continue in), but still delivered as a typed
    error frame before the close."""

    code = "hello_mismatch"


class AuthRejected(WireError):
    """The boundary check refused the caller BEFORE any bytes reached a
    ServingLoop: unknown token at hello (connection-fatal) or a submit
    naming a tenant outside the token's grant (per-request; the
    connection and its other in-flight requests live on)."""

    code = "auth"


class WireBackpressure(WireError):
    """The per-connection pipelining window is full: the server refuses
    the submit with a typed frame instead of buffering unboundedly or
    dropping the connection.  Retryable — drain some in-flight
    responses and resubmit."""

    code = "backpressure"
    retryable = True


class PeerClosed(WireError):
    """The peer vanished mid-pipeline (conn_drop fault, process death,
    network partition): every in-flight request on the connection fails
    with this, typed, instead of raw ``ConnectionResetError`` /
    ``BrokenPipeError`` shapes.  Retryable on a fresh connection — the
    server never dispatched-and-dropped silently (an admitted request's
    outcome frame was simply lost with the socket)."""

    code = "peer_closed"
    retryable = True


class RemoteFailed(WireError):
    """A server-side ticket failed with an exception class the client
    could not re-hydrate into a local type (the error frame carries the
    class name + message in ``context``).  The catch-all that keeps the
    no-raw-escapes contract total."""

    code = "failed"


class TornJournalTail(CorruptInput):
    """The LAST record of a write-ahead journal is incomplete or fails its
    CRC: the torn-write shape every append-before-apply journal must
    expect after a crash mid-append.  A torn TAIL is recoverable by
    contract (truncate the tail, the record never committed — see
    docs/DURABILITY.md); corruption anywhere BEFORE the tail is not and
    stays plain :class:`CorruptInput`.  Subclasses CorruptInput so
    callers that only care about "durable state is damaged" catch one
    type."""


#: message fragments -> taxonomy, checked in order (first hit wins).  OOM
#: before transient: XLA RESOURCE_EXHAUSTED statuses often also carry
#: "while running replica" noise that the transient patterns would catch.
#: Two pattern tiers per class, both deliberately NARROW — a genuine bug
#: whose message merely brushes a keyword must stay unclassified (the
#: guard re-raises it raw): uppercase absl/gRPC status tokens matched
#: case-SENSITIVELY against the raw message, and multi-word lowercase
#: phrases no plausible programming error emits.  Bare short words
#: ("oom", "aborted", "coordinator") are excluded on purpose — "zoom",
#: "scan aborted: invalid plan state" etc. must not become retryable.
_OOM_TOKENS = ("RESOURCE_EXHAUSTED",)
_OOM_PHRASES = (
    "out of memory", "memory allocation failed", "exceeds the hbm",
    "exceeds available memory",
)
_LOWERING_PHRASES = (
    # "mosaic" is the TPU kernel compiler's own name; bare "pallas" is NOT
    # here — `TypeError: pallas_call() got an unexpected keyword` is a
    # programming error and must propagate raw
    "mosaic", "lowering failed", "unsupported primitive", "cannot lower",
    "unimplemented primitive", "not implemented for platform",
    "mlir translation rule",
)
_COORDINATOR_PHRASES = (
    "coordination service", "barrier timed out", "preemption notice",
    "heartbeat timeout",
)
_TRANSIENT_TOKENS = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
                     "CANCELLED")
_TRANSIENT_PHRASES = (
    "deadline exceeded", "connection reset", "socket closed",
    "failed to connect", "network error", "transient",
)


def classify(exc: BaseException):
    """Raw exception -> taxonomy instance, or None for a programming error.

    Already-typed exceptions pass through unchanged (identity), so
    classification is idempotent and injected typed faults keep their
    class.  Everything else is matched on its message text — the only
    stable surface XLA/gRPC errors offer across jax versions.
    """
    if isinstance(exc, (RoaringRuntimeError, InvalidRoaringFormat)):
        return exc
    msg = f"{type(exc).__name__}: {exc}"
    low = msg.lower()
    if any(t in msg for t in _OOM_TOKENS) \
            or any(p in low for p in _OOM_PHRASES):
        return ResourceExhausted(msg)
    # NOT a blanket NotImplementedError match: a stubbed host method is a
    # programming error and must propagate raw, not demote engines — only
    # compiler-flavored messages classify as lowering failures
    if any(p in low for p in _LOWERING_PHRASES):
        return EngineLoweringError(msg)
    if any(p in low for p in _COORDINATOR_PHRASES):
        return CoordinatorTimeout(msg)
    if any(t in msg for t in _TRANSIENT_TOKENS) \
            or any(p in low for p in _TRANSIENT_PHRASES):
        return TransientDeviceError(msg)
    return None
