"""Cold-path controls: the persistent compilation cache (ROADMAP item 3).

BENCH_r05 still shows ``first_query_ms`` ≈ 258 ms and
``ingest_compile_ms_one_time`` ≈ 1.07 s against a ~10 µs marginal op — a
restart pays five orders of magnitude over steady state, almost all of it
XLA compilation.  JAX ships a persistent on-disk compilation cache that
removes the recompile on every later process; this module wires it behind
one environment variable so a serving deployment opts in without code::

    ROARING_TPU_COMPILE_CACHE=/var/cache/rb_xla  python serve.py

``enable_compile_cache()`` is called lazily by every engine constructor
(``BatchEngine`` / ``MultiSetBatchEngine`` / ``ShardedBatchEngine``), so
the first resident-set build already compiles through the cache.  The
explicit ``warmup(rungs=...)`` API on those engines is the other half of
the cold-path story: it drives the plan -> AOT-compile pipeline for the
known pow2 query rungs ahead of the first real query, so a process boots
hot — ``rb_compile_seconds{cache="hit"|"miss"}`` and
``rb_first_query_seconds`` (obs.cost, PR 6) are the measurement.

The knob is deliberately idempotent and racy-safe: repeated calls with an
unchanged environment are a dict lookup; an explicit ``path=`` argument
overrides the environment (tests point it at a tmpdir).
"""

from __future__ import annotations

import os

ENV_COMPILE_CACHE = "ROARING_TPU_COMPILE_CACHE"

#: last applied cache dir (None = not enabled); keyed against the spec it
#: came from so an env change between engine constructions re-applies
_applied: tuple[str | None, str | None] = (None, None)


def enable_compile_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (or at
    ``$ROARING_TPU_COMPILE_CACHE`` when ``path`` is None).  Returns the
    resolved directory, or None when the knob is unset — in which case
    any process-level cache configuration (e.g. bench.py's own
    ``jax_compilation_cache_dir``) is left untouched.

    The min-compile-time floor is dropped to 0 so even the small pooled
    programs (~100 ms compiles on CPU) persist: the cold path this exists
    to kill is exactly many small compiles, not one big one.
    """
    global _applied
    spec = path if path is not None else os.environ.get(ENV_COMPILE_CACHE)
    if not spec:
        return None
    if _applied[0] == spec:
        return _applied[1]
    import jax

    resolved = os.path.abspath(os.path.expanduser(spec))
    os.makedirs(resolved, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", resolved)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:  # pragma: no cover - very old jax
        pass
    try:
        # cache every entry regardless of size (jax >= 0.4.16 knob)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:  # pragma: no cover
        pass
    try:
        # jax initializes its cache object once, at the first compile —
        # a dir configured after that (engines are often built after the
        # resident set already compiled its pack programs) would be
        # silently ignored for the rest of the process; reset forces the
        # next compile to re-read the config
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - private-API drift
        pass
    _applied = (spec, resolved)
    return resolved


def compile_cache_dir() -> str | None:
    """The directory the cache was last enabled with, or None."""
    return _applied[1]
