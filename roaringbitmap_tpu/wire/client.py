"""Pipelining wire client: many in-flight submits, typed errors only.

``WireClient`` is the caller-side half of docs/WIRE.md: a blocking
socket + one reader thread resolving responses OUT OF ORDER by req_id.
``submit`` returns a :class:`WireTicket` future immediately;
``submit_many`` coalesces a whole batch into one ``sendall`` (the
pipelined arm of the pod_replay bench lane); ``call`` is the
one-request-per-round-trip shape the lane uses as its RTT baseline.

Failure surface is the wire taxonomy, total: a dead peer fails every
in-flight ticket with typed :class:`PeerClosed`, a garbled stream with
typed :class:`CorruptInput` — raw ``socket``/``struct`` errors never
reach the caller.
"""

from __future__ import annotations

import socket
import threading
import time

from ..obs import trace as obs_trace
from ..runtime import errors, faults
from . import protocol as wp

SITE = "wire"


class WireTicket:
    """One in-flight request's caller handle (the wire twin of
    ``serving.Ticket``): ``status`` pending -> done | failed;
    ``result`` a :class:`protocol.WireResult` when done, ``error`` the
    rehydrated typed exception when failed."""

    __slots__ = ("req_id", "request", "status", "result", "error",
                 "sent_at", "done_at", "_event")

    def __init__(self, req_id: int, request=None):
        self.req_id = req_id
        self.request = request
        self.status = "pending"
        self.result = None
        self.error = None
        #: perf_counter stamps (send / response-landed) — the replay
        #: harness's client-observed latency, wire time included
        self.sent_at: float | None = None
        self.done_at: float | None = None
        self._event = threading.Event()

    @property
    def ok(self) -> bool:
        return self.status == "done"

    def wait(self, timeout: float | None = None) -> "WireTicket":
        if not self._event.wait(timeout):
            raise errors.CoordinatorTimeout(
                f"{SITE}: no response for req {self.req_id} within "
                f"{timeout}s (peer wedged?)")
        return self

    def value(self, timeout: float | None = None):
        """Result or typed raise — the blocking accessor."""
        self.wait(timeout)
        if self.error is not None:
            raise self.error
        return self.result


class WireClient:
    """Connect, speak the versioned hello, then pipeline requests."""

    def __init__(self, address, token: str | None = None,
                 client: str = "rb-wire-client", timeout: float = 30.0,
                 connect_timeout: float = 10.0):
        self.address = tuple(address)
        self.timeout = float(timeout)
        self._sock = socket.create_connection(self.address,
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: dict = {}
        self._next_id = 0
        self._dead: BaseException | None = None
        #: req_ids in the order their responses LANDED — the
        #: out-of-order pipelining evidence the tests read
        self.completion_order: list = []
        self.stats = {"submits": 0, "results": 0, "errors": 0,
                      "coalesced_writes": 0}
        self._sock.sendall(wp.WIRE_MAGIC + wp.encode_frame(
            wp.T_HELLO, 0, {"version": wp.WIRE_VERSION,
                            "client": str(client),
                            **({"token": token} if token is not None
                               else {})}))
        ftype, _, h, _ = wp.read_frame(self._sock)
        if ftype == wp.T_ERROR:
            self._sock.close()
            raise wp.rehydrate_error(h)
        if ftype != wp.T_WELCOME:
            self._sock.close()
            raise errors.WireHelloMismatch(
                f"{SITE}: expected welcome, got frame type {ftype}")
        self.server = dict(h)
        self._reader = threading.Thread(target=self._read_loop,
                                        name="wire-client-reader",
                                        daemon=True)
        self._reader.start()

    # ------------------------------------------------------------ plumbing

    def close(self) -> None:
        self._fail_all(errors.PeerClosed(
            f"{SITE}: connection closed locally"))

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = exc
            pending, self._pending = self._pending, {}
        try:
            self._sock.close()
        except OSError:
            pass
        for t in pending.values():
            t.status = "failed"
            t.error = exc
            t._event.set()

    def _read_loop(self) -> None:
        try:
            while True:
                ftype, req_id, h, blobs = wp.read_frame(self._sock)
                if ftype == wp.T_ERROR and req_id == 0:
                    # connection-level typed error: hello/auth refusal
                    # or a garbled-inbound verdict — everything in
                    # flight fails with the server's reason
                    self._fail_all(wp.rehydrate_error(h))
                    return
                with self._lock:
                    t = self._pending.pop(req_id, None)
                    if t is not None:
                        self.completion_order.append(req_id)
                if t is None:
                    continue                    # pong / late duplicate
                t.done_at = time.perf_counter()
                if ftype == wp.T_RESULT:
                    t.result = wp.WireResult(h, blobs)
                    t.status = "done"
                    self.stats["results"] += 1
                elif ftype == wp.T_PONG:
                    t.status = "done"
                elif ftype == wp.T_MIG_ACK:
                    t.result = dict(h)
                    t.status = "done"
                else:
                    t.error = wp.rehydrate_error(h)
                    t.status = "failed"
                    self.stats["errors"] += 1
                t._event.set()
        except errors.CorruptInput as exc:
            self._fail_all(exc)
        except (ConnectionError, OSError) as exc:
            self._fail_all(errors.PeerClosed(
                f"{SITE}: peer vanished mid-pipeline "
                f"({type(exc).__name__}: {exc})"))

    def _write(self, frames: list) -> None:
        if self._dead is not None:
            raise self._dead
        scope = faults.maybe_wire("wire.client")
        if scope == "conn_drop":
            self._fail_all(errors.PeerClosed(
                f"{SITE}: injected conn_drop mid-pipeline "
                f"(ROARING_TPU_FAULTS)"))
            raise self._dead
        if scope == "garbage":
            frames = [wp.garble(frames[0])] + frames[1:]
        try:
            with self._wlock:
                self._sock.sendall(b"".join(frames))
        except OSError as exc:
            self._fail_all(errors.PeerClosed(
                f"{SITE}: send failed ({type(exc).__name__}: {exc})"))
            raise self._dead from None
        self.stats["coalesced_writes"] += 1

    def _reserve(self, request=None) -> WireTicket:
        with self._lock:
            self._next_id += 1
            t = WireTicket(self._next_id, request)
            self._pending[t.req_id] = t
        return t

    # ------------------------------------------------------------- queries

    def _submit_frame(self, t: WireTicket, request) -> bytes:
        qh, blobs = wp.encode_query(request.query)
        with obs_trace.span("rpc.call", site=SITE, req_id=t.req_id,
                            tenant=request.tenant,
                            set_id=request.set_id) as sp:
            header = {"set_id": request.set_id,
                      "tenant": request.tenant, "query": qh,
                      "trace": obs_trace.inject(sp)}
            if request.deadline_ms is not None:
                header["deadline_ms"] = request.deadline_ms
            frame = wp.encode_frame(wp.T_SUBMIT, t.req_id, header,
                                    tuple(blobs))
            sp.tag(frame_bytes=len(frame))
        return frame

    def submit(self, request) -> WireTicket:
        """Pipeline one ServingRequest; returns its future at once."""
        t = self._reserve(request)
        t.sent_at = time.perf_counter()
        self._write([self._submit_frame(t, request)])
        self.stats["submits"] += 1
        return t

    def submit_many(self, requests) -> list:
        """Frame-coalesced pipelined submission: every request encoded
        up front, ONE sendall — the syscall-floor amortization the
        pod_replay lane measures against ``call``."""
        tickets = [self._reserve(r) for r in requests]
        frames = [self._submit_frame(t, r)
                  for t, r in zip(tickets, requests)]
        now = time.perf_counter()
        for t in tickets:
            t.sent_at = now
        if frames:
            self._write(frames)
        self.stats["submits"] += len(tickets)
        return tickets

    def call(self, request, timeout: float | None = None):
        """One request per round trip (the unpipelined baseline):
        submit, block, return the WireResult or raise typed."""
        return self.submit(request).value(timeout or self.timeout)

    def ping(self) -> None:
        """One round trip with no serving work — the RTT floor."""
        t = self._reserve()
        self._write([wp.encode_frame(wp.T_PING, t.req_id, {})])
        t.wait(self.timeout)

    def apply_delta(self, set_id: int, adds=None, removes=None,
                    tenant: str = "default",
                    timeout: float | None = None):
        """Remote mutation: ship a delta, return the apply report."""
        t = self._reserve()
        h = {"set_id": int(set_id), "tenant": tenant}
        if adds:
            h["adds"] = {int(k): [int(x) for x in v]
                         for k, v in adds.items()}
        if removes:
            h["removes"] = {int(k): [int(x) for x in v]
                            for k, v in removes.items()}
        self._write([wp.encode_frame(wp.T_DELTA, t.req_id, h)])
        res = t.value(timeout or self.timeout)
        return res.report if isinstance(res, wp.WireResult) else res

    # ----------------------------------------------------------- migration

    def migrate_frames(self, frames: list, timeout: float | None = None):
        """Send pre-encoded migration frames pipelined, wait for each
        ACK in turn; returns the LAST ack header (the commit report).
        Used by wire/migrate.py — kept here so the reader-thread
        correlation stays in one place."""
        tickets = []
        out = []
        for ftype, header, blobs in frames:
            t = self._reserve()
            out.append(wp.encode_frame(ftype, t.req_id, dict(header),
                                       tuple(blobs)))
            tickets.append(t)
        self._write(out)
        acks = [t.value(timeout or self.timeout) for t in tickets]
        return acks[-1] if acks else None
