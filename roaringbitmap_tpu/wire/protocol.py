"""Binary wire protocol: frame grammar + codecs (docs/WIRE.md).

The RPC data plane reuses the two byte disciplines the repo already
trusts instead of inventing a third:

- every frame is length+CRC framed exactly like a journal record
  (``mutation.durability._FRAME``): ``u32 payload_len | u32
  crc32(payload) | payload`` — a torn or garbled frame fails the CRC
  and dies as typed :class:`CorruptInput`, never a raw struct error;
- bitmap payloads (ad-hoc expression leaves, bitmap-form results,
  migration snapshot sources) are the portable container-partitioned
  ``format/spec.py`` bytes VERBATIM — the durable snapshot format is
  the wire format, so a result can be fed straight back into
  ``RoaringBitmap.deserialize`` / ``durability.restore_state``.

Frame payload grammar::

    payload = u8 ftype | u64 req_id | u32 header_len
            | header_len bytes of UTF-8 JSON header
            | concatenated binary blobs (lengths in header["blobs"])

The JSON header carries the structured fields (queries as a nested DAG
encoding, error taxonomy fields, migration metadata); blobs carry the
opaque bitmap bytes the header references by index.  ``req_id`` is the
client-assigned pipelining correlator: responses complete out of order
and a response's req_id names the submit it answers (req_id 0 is
reserved for connection-level frames: hello, welcome, connection-fatal
errors).

This module is transport-free (bytes in, bytes out) so both the
threaded server and the client — and the tests — share one codec.
"""

from __future__ import annotations

import json
import struct
import zlib

from ..parallel import expr as expr_mod
from ..parallel.batch_engine import BatchQuery
from ..core.bitmap import RoaringBitmap
from ..runtime import errors

#: connection preamble: 8 raw bytes before the first frame, so a
#: non-protocol peer is rejected before any JSON is parsed
WIRE_MAGIC = b"RBWIRE01"
WIRE_VERSION = 1

_FRAME = struct.Struct("<II")     # payload length, crc32(payload)
_HDR = struct.Struct("<BQI")      # ftype, req_id, header_len
#: one frame's payload ceiling — matches the journal's record ceiling
#: (a migration snapshot source above this is chunked across frames)
MAX_FRAME_BYTES = 1 << 28

# frame types ------------------------------------------------------------
T_HELLO = 1        # client -> server: version + auth token
T_WELCOME = 2      # server -> client: hello accepted
T_SUBMIT = 3       # client -> server: one ServingRequest
T_RESULT = 4       # server -> client: a done ticket's result
T_ERROR = 5        # server -> client: typed error frame (never a drop)
T_PING = 6         # client -> server: RTT floor probe
T_PONG = 7         # server -> client
T_DELTA = 8        # client -> server: apply_delta on a resident set
T_MIG_BEGIN = 9    # migration: snapshot metadata
T_MIG_STATE = 10   # migration: snapshot blobs (chunked)
T_MIG_DELTA = 11   # migration: journal-tail / dual-write records
T_MIG_COMMIT = 12  # migration: restore + install on the destination
T_MIG_ACK = 13     # server -> client: migration phase acknowledged

FRAME_NAMES = {
    T_HELLO: "hello", T_WELCOME: "welcome", T_SUBMIT: "submit",
    T_RESULT: "result", T_ERROR: "error", T_PING: "ping",
    T_PONG: "pong", T_DELTA: "delta", T_MIG_BEGIN: "mig_begin",
    T_MIG_STATE: "mig_state", T_MIG_DELTA: "mig_delta",
    T_MIG_COMMIT: "mig_commit", T_MIG_ACK: "mig_ack",
}


# ------------------------------------------------------------- framing

def encode_frame(ftype: int, req_id: int, header: dict,
                 blobs: tuple = ()) -> bytes:
    """One wire frame as bytes (outer length+CRC included)."""
    h = dict(header)
    if blobs:
        h["blobs"] = [len(b) for b in blobs]
    hb = json.dumps(h, separators=(",", ":")).encode()
    payload = _HDR.pack(ftype, req_id, len(hb)) + hb + b"".join(blobs)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(
            f"wire frame payload {len(payload)} bytes exceeds "
            f"MAX_FRAME_BYTES ({MAX_FRAME_BYTES}) — chunk the blobs")
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def decode_payload(payload: bytes) -> tuple:
    """Frame payload -> ``(ftype, req_id, header, blobs)``.  Every
    malformed shape dies typed :class:`CorruptInput` — json/struct
    errors never escape raw."""
    try:
        ftype, req_id, hlen = _HDR.unpack_from(payload, 0)
        off = _HDR.size
        if hlen > len(payload) - off:
            raise errors.CorruptInput(
                f"wire frame header length {hlen} overruns payload")
        header = json.loads(payload[off:off + hlen].decode())
        if not isinstance(header, dict):
            raise errors.CorruptInput("wire frame header is not an object")
        off += hlen
        blobs = []
        for n in header.get("blobs", ()):
            n = int(n)
            if n < 0 or n > len(payload) - off:
                raise errors.CorruptInput(
                    f"wire frame blob length {n} overruns payload")
            blobs.append(bytes(payload[off:off + n]))
            off += n
        if off != len(payload):
            raise errors.CorruptInput(
                f"wire frame has {len(payload) - off} trailing bytes")
        return ftype, req_id, header, blobs
    except errors.CorruptInput:
        raise
    except Exception as exc:
        raise errors.CorruptInput(
            f"undecodable wire frame: {type(exc).__name__}: {exc}") \
            from None


def recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise ConnectionError on EOF (the
    caller maps socket-level failures to typed PeerClosed)."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def read_frame(sock) -> tuple:
    """Blocking read of one frame -> ``(ftype, req_id, header, blobs)``.
    A CRC mismatch or oversized length is a GARBLED stream: typed
    :class:`CorruptInput` (the connection is unrecoverable — framing
    sync is lost)."""
    head = recv_exact(sock, _FRAME.size)
    length, crc = _FRAME.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise errors.CorruptInput(
            f"wire frame length {length} exceeds MAX_FRAME_BYTES "
            f"(garbled stream)")
    payload = recv_exact(sock, length)
    if zlib.crc32(payload) != crc:
        raise errors.CorruptInput(
            f"wire frame CRC mismatch over {length} bytes "
            f"(torn or garbled frame)")
    return decode_payload(payload)


def garble(frame: bytes) -> bytes:
    """Deterministically corrupt one payload byte of an encoded frame
    (length intact, CRC now wrong) — the ``wire@garbage`` fault shape.
    The receiver's CRC check must convert this to CorruptInput."""
    if len(frame) <= _FRAME.size:
        return frame
    i = _FRAME.size + (len(frame) - _FRAME.size) // 2
    out = bytearray(frame)
    out[i] ^= 0xFF
    return bytes(out)


# -------------------------------------------------------- query codec

def _encode_expr(e, blobs: list):
    if isinstance(e, expr_mod.Ref):
        return {"t": "ref", "i": e.index}
    if isinstance(e, expr_mod.AdHoc):
        blobs.append(e.bm.serialize())
        return {"t": "adhoc", "b": len(blobs) - 1}
    if isinstance(e, expr_mod.ValuePred):
        return {"t": "vp", "col": e.col, "op": e.op,
                "lo": e.lo, "hi": e.hi}
    if isinstance(e, expr_mod.Agg):
        return {"t": "agg", "kind": e.kind, "col": e.col, "k": e.k,
                "found": (None if e.found is None
                          else _encode_expr(e.found, blobs))}
    if isinstance(e, expr_mod.Node):
        return {"t": "op", "op": e.op,
                "c": [_encode_expr(c, blobs) for c in e.children]}
    raise TypeError(f"unencodable expression node {type(e).__name__}")


def _decode_expr(n, blobs: list):
    t = n["t"]
    if t == "ref":
        return expr_mod.Ref(int(n["i"]))
    if t == "adhoc":
        return expr_mod.AdHoc(RoaringBitmap.deserialize(blobs[int(n["b"])]))
    if t == "vp":
        return expr_mod.ValuePred(str(n["col"]), str(n["op"]),
                                  int(n["lo"]), int(n["hi"]))
    if t == "agg":
        found = n.get("found")
        return expr_mod.Agg(str(n["kind"]), str(n["col"]), int(n["k"]),
                            None if found is None
                            else _decode_expr(found, blobs))
    if t == "op":
        return expr_mod.Node(str(n["op"]),
                             tuple(_decode_expr(c, blobs)
                                   for c in n["c"]))
    raise errors.CorruptInput(f"unknown wire expression node type {t!r}")


def encode_query(q) -> tuple:
    """BatchQuery | ExprQuery -> ``(header_fragment, blobs)``.  AdHoc
    leaves ship their snapshot as spec.py bytes verbatim."""
    blobs: list = []
    if isinstance(q, BatchQuery):
        return ({"kind": "flat", "op": q.op,
                 "operands": list(q.operands), "form": q.form}, blobs)
    if isinstance(q, expr_mod.ExprQuery):
        return ({"kind": "expr", "form": q.form,
                 "expr": _encode_expr(q.expr, blobs)}, blobs)
    raise TypeError(f"unencodable query type {type(q).__name__}")


def decode_query(h: dict, blobs: list):
    """Inverse of :func:`encode_query`; malformed encodings die typed
    CorruptInput (the server maps that to a per-request error frame)."""
    try:
        kind = h["kind"]
        if kind == "flat":
            return BatchQuery(str(h["op"]),
                              tuple(int(i) for i in h["operands"]),
                              str(h["form"]))
        if kind == "expr":
            return expr_mod.ExprQuery(_decode_expr(h["expr"], blobs),
                                      str(h["form"]))
        raise errors.CorruptInput(f"unknown wire query kind {kind!r}")
    except (errors.CorruptInput, errors.RoaringRuntimeError):
        raise
    except Exception as exc:
        raise errors.CorruptInput(
            f"undecodable wire query: {type(exc).__name__}: {exc}") \
            from None


# ------------------------------------------------------- result codec

def encode_result(res, *, degraded=False, wall_ms=None,
                  missed=False) -> tuple:
    """BatchResult (or delta/migration report dict) -> header + blobs.
    Bitmap-form results ride as one spec.py blob."""
    blobs: list = []
    h = {"degraded": bool(degraded), "missed": bool(missed)}
    if wall_ms is not None:
        h["wall_ms"] = float(wall_ms)
    if isinstance(res, dict):
        h["report"] = res
        return h, blobs
    h["cardinality"] = int(res.cardinality)
    if res.value is not None:
        h["value"] = int(res.value)
    if res.bitmap is not None:
        blobs.append(res.bitmap.serialize())
        h["bitmap"] = 0
    return h, blobs


class WireResult:
    """Client-side view of a RESULT frame — quacks like BatchResult
    (cardinality / bitmap / value) plus the serving-outcome fields the
    replay harness reads (degraded, missed, wall_ms, report)."""

    __slots__ = ("cardinality", "bitmap", "value", "degraded", "missed",
                 "wall_ms", "report")

    def __init__(self, h: dict, blobs: list):
        self.cardinality = int(h.get("cardinality", 0))
        self.value = h.get("value")
        self.degraded = bool(h.get("degraded", False))
        self.missed = bool(h.get("missed", False))
        self.wall_ms = h.get("wall_ms")
        self.report = h.get("report")
        self.bitmap = None
        if h.get("bitmap") is not None:
            self.bitmap = RoaringBitmap.deserialize(
                blobs[int(h["bitmap"])])


# -------------------------------------------------------- error codec

def error_fields(exc: BaseException) -> dict:
    """Exception -> typed error-frame header.  Total: every exception
    shape maps to SOME code (``failed`` is the catch-all), so the
    server can always answer with a frame instead of dropping."""
    h = {"cls": type(exc).__name__, "message": str(exc)}
    context = getattr(exc, "context", None)
    if isinstance(context, dict):
        try:
            json.dumps(context)
            h["context"] = context
        except (TypeError, ValueError):
            h["context"] = {k: repr(v) for k, v in context.items()}
    reason = getattr(exc, "reason", None)
    if isinstance(reason, str):
        h["reason"] = reason
    if isinstance(exc, errors.WireError):
        h["code"] = exc.code
    elif type(exc).__name__ == "AdmissionRejected":
        h["code"] = "admission_rejected"
    elif type(exc).__name__ == "RequestShed":
        h["code"] = "shed"
    elif isinstance(exc, errors.CorruptInput):
        h["code"] = "corrupt_input"
    else:
        h["code"] = "failed"
    h["retryable"] = bool(getattr(exc, "retryable", False))
    return h


def rehydrate_error(h: dict) -> BaseException:
    """Typed error-frame header -> a LOCAL typed exception the caller
    can catch by class — the wire taxonomy round-trips (docs/WIRE.md
    "Error mapping").  Unknown shapes land on :class:`RemoteFailed`,
    never on a raw/untyped error."""
    from ..serving.loop import AdmissionRejected, RequestShed
    code = h.get("code", "failed")
    msg = str(h.get("message", ""))
    context = h.get("context") if isinstance(h.get("context"), dict) else {}
    reason = h.get("reason", code)
    if code == "admission_rejected":
        return AdmissionRejected(msg, str(reason), **context)
    if code == "shed":
        return RequestShed(msg, str(reason), **context)
    if code == "auth":
        return errors.AuthRejected(msg, **context)
    if code == "backpressure":
        return errors.WireBackpressure(msg, **context)
    if code == "hello_mismatch":
        return errors.WireHelloMismatch(msg, **context)
    if code == "peer_closed":
        return errors.PeerClosed(msg, **context)
    if code == "corrupt_input":
        return errors.CorruptInput(msg)
    cls = getattr(errors, str(h.get("cls", "")), None)
    if isinstance(cls, type) and issubclass(cls, errors.RoaringRuntimeError):
        exc = cls(msg)
        exc.context = context
        return exc
    return errors.RemoteFailed(msg, remote_cls=h.get("cls"), **context)
