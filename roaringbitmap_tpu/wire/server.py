"""Threaded binary RPC front door over a ServingLoop / PodFrontDoor.

One ``WireServer`` owns a listening TCP socket, a reader thread per
connection, and ONE pump thread driving the target — the serving loops
stay logically single-threaded (their own locks arbitrate), the wire
layer only adds the boundary:

- **hello/auth first**: the 8-byte magic + versioned HELLO frame and
  the token→tenants grant are checked before any request bytes reach a
  ServingLoop (docs/WIRE.md "Auth model");
- **pipelining**: many in-flight submits per connection, correlated by
  client-assigned req_id; responses complete OUT OF ORDER as pools
  finish, delivered through the target's completion-listener seam so
  every outcome is observed no matter who pumped;
- **frame coalescing**: all completions one pump produced for a
  connection go out as ONE ``sendall`` — the syscall floor amortizes
  the way BatchEngine amortizes the dispatch floor;
- **typed outcomes only**: admission rejections, sheds, failures, auth
  refusals, decode garbage, and backpressure all answer with a typed
  ERROR frame on the live connection — a dropped connection is never an
  error-signaling mechanism (zero silent drops);
- **fault injection**: ``wire@{conn_drop,slow_peer,garbage}`` rules
  (runtime.faults.maybe_wire) fire on the response path, making
  disconnects mid-pipeline, slow-loris peers, and garbled frames
  deterministic in tests.
"""

from __future__ import annotations

import logging
import select
import socket
import threading

from ..mutation import durability
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..runtime import errors, faults
from ..serving.loop import AdmissionRejected, ServingRequest
from . import protocol as wp

_log = logging.getLogger("roaringbitmap_tpu.wire")

SITE = "wire"

#: per-connection in-flight ceiling: past it, submits answer typed
#: WireBackpressure frames instead of buffering unboundedly
DEFAULT_MAX_INFLIGHT = 256
#: how long the pump thread waits for MORE pipelined arrivals before
#: forcing a partial pool out — the wire-side batching window
COALESCE_S = 0.002
#: reader-side burst ceiling: at most this many already-buffered
#: SUBMIT frames are admitted under one loop-lock acquisition
SUBMIT_BATCH_MAX = 512


class _Conn:
    """One accepted connection's state: socket + write lock (the reader
    thread and the completion path both send), auth grant, in-flight
    req_id accounting."""

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.wlock = threading.Lock()
        self.alive = True
        self.tenants: tuple = ()      # granted tenants ("*" = all)
        self.inflight: set = set()    # outstanding req_ids
        self.mig: dict = {}           # mig_id -> in-progress migration

    def allows(self, tenant: str) -> bool:
        return "*" in self.tenants or tenant in self.tenants


class WireServer:
    """Serve a ``ServingLoop`` or ``PodFrontDoor`` over TCP.

    ``auth=None`` runs open (every tenant granted); otherwise a dict
    ``{token: [tenant, ...]}`` (``"*"`` grants all tenants) checked at
    the boundary.  ``on_migrate(tenant, ds)`` receives a live-migrated
    tenant's restored DeviceBitmapSet (default: parked in
    ``self.migrated``)."""

    def __init__(self, target, host: str = "127.0.0.1", port: int = 0,
                 auth: dict | None = None,
                 max_inflight: int = DEFAULT_MAX_INFLIGHT,
                 coalesce_s: float = COALESCE_S,
                 name: str = "server", on_migrate=None):
        self._target = target
        self._auth = None if auth is None else {
            str(k): tuple(str(t) for t in v) for k, v in auth.items()}
        self._max_inflight = int(max_inflight)
        self._coalesce_s = float(coalesce_s)
        self.name = str(name)
        self._on_migrate = on_migrate
        #: tenant -> restored DeviceBitmapSet (wire-migration landing
        #: zone when no ``on_migrate`` installer was given)
        self.migrated: dict = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._alive = False
        self._conns: list = []
        self._lock = threading.Lock()
        #: ticket identity -> (conn, req_id): the pipelining correlator
        self._pending: dict = {}
        self._kick = threading.Event()
        self._threads: list = []
        self.stats = {"connections": 0, "submits": 0, "results": 0,
                      "errors": 0, "deltas": 0, "migrations": 0,
                      "coalesced_writes": 0, "frames_out": 0}

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "WireServer":
        self._alive = True
        self._target.add_completion_listener(self._on_complete)
        for fn, tag in ((self._accept_loop, "accept"),
                        (self._pump_loop, "pump")):
            th = threading.Thread(
                target=fn, name=f"wire-{self.name}-{tag}", daemon=True)
            th.start()
            self._threads.append(th)
        return self

    def stop(self) -> None:
        self._alive = False
        self._target.remove_completion_listener(self._on_complete)
        try:
            self._sock.close()
        except OSError:
            pass
        self._kick.set()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            self._drop_conn(c)
        for th in self._threads:
            th.join(timeout=2.0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ----------------------------------------------------------- accepting

    def _accept_loop(self) -> None:
        while self._alive:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return                       # listener closed: shutdown
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, addr)
            with self._lock:
                self._conns.append(conn)
            self.stats["connections"] += 1
            th = threading.Thread(target=self._conn_loop, args=(conn,),
                                  name=f"wire-{self.name}-conn", daemon=True)
            th.start()

    def _drop_conn(self, conn: _Conn) -> None:
        conn.alive = False
        try:
            conn.sock.close()
        except OSError:
            pass
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)
            # orphan this connection's pending tickets: the loop will
            # still complete them (no cancellation mid-pool), but their
            # response frames have nowhere to go — the client sees
            # typed PeerClosed, never a silent drop of a LIVE socket
            for key in [k for k, (c, _) in self._pending.items()
                        if c is conn]:
                del self._pending[key]

    # ------------------------------------------------------------- serving

    def _send(self, conn: _Conn, frames: list) -> None:
        """Coalesced write of ``frames`` (already-encoded bytes) with
        the wire fault hook on the response path."""
        if not frames or not conn.alive:
            return
        scope = faults.maybe_wire("wire.server")
        if scope == "conn_drop":
            self._drop_conn(conn)
            return
        if scope == "garbage":
            frames = [wp.garble(frames[0])] + frames[1:]
        buf = b"".join(frames)
        try:
            with conn.wlock:
                conn.sock.sendall(buf)
        except OSError:
            self._drop_conn(conn)
            return
        self.stats["coalesced_writes"] += 1
        self.stats["frames_out"] += len(frames)

    def _send_error(self, conn: _Conn, req_id: int,
                    exc: BaseException) -> None:
        self.stats["errors"] += 1
        obs_metrics.counter("rb_wire_error_frames_total",
                            code=wp.error_fields(exc)["code"]).inc()
        self._send(conn, [wp.encode_frame(wp.T_ERROR, req_id,
                                          wp.error_fields(exc))])

    def _conn_loop(self, conn: _Conn) -> None:
        try:
            if not self._handshake(conn):
                return
            while self._alive and conn.alive:
                ftype, req_id, header, blobs = wp.read_frame(conn.sock)
                if ftype != wp.T_SUBMIT:
                    self._handle(conn, ftype, req_id, header, blobs)
                    continue
                # pipelined burst: a submit_many lands as ONE TCP write,
                # so its sibling frames are already in the kernel buffer
                # — gather them and admit the whole batch under one
                # loop-lock acquisition.  Admitting one-at-a-time would
                # convoy with the pump (each lock-held pool dispatch
                # lets only ~1 admit through), collapsing pools toward
                # singletons and erasing the batching win the pipelining
                # exists for (docs/WIRE.md "Pipelining semantics").
                batch = [(req_id, header, blobs)]
                tail = None
                while (len(batch) < SUBMIT_BATCH_MAX
                       and conn.alive
                       and select.select([conn.sock], [], [], 0)[0]):
                    nxt = wp.read_frame(conn.sock)
                    if nxt[0] != wp.T_SUBMIT:
                        tail = nxt       # first non-submit ends the burst
                        break
                    batch.append(nxt[1:])
                self._handle_submits(conn, batch)
                if tail is not None:
                    self._handle(conn, *tail)
        except errors.CorruptInput as exc:
            # garbled inbound stream: framing sync is lost — answer
            # with a connection-level typed error frame, then close
            self._send_error(conn, 0, exc)
            self._drop_conn(conn)
        except (ConnectionError, OSError):
            self._drop_conn(conn)
        except Exception:
            _log.exception("%s: connection handler died", SITE)
            self._drop_conn(conn)

    def _handshake(self, conn: _Conn) -> bool:
        magic = wp.recv_exact(conn.sock, len(wp.WIRE_MAGIC))
        if magic != wp.WIRE_MAGIC:
            self._send_error(conn, 0, errors.WireHelloMismatch(
                f"{SITE}: bad magic {magic!r} (want {wp.WIRE_MAGIC!r})"))
            self._drop_conn(conn)
            return False
        ftype, _, h, _ = wp.read_frame(conn.sock)
        with obs_trace.span("rpc.hello", site=SITE,
                            client=str(h.get("client", "?"))) as sp:
            if ftype != wp.T_HELLO or int(h.get("version", -1)) \
                    != wp.WIRE_VERSION:
                sp.tag(outcome="hello_mismatch")
                self._send_error(conn, 0, errors.WireHelloMismatch(
                    f"{SITE}: hello version "
                    f"{h.get('version')!r} != {wp.WIRE_VERSION} "
                    f"(frame type {ftype})"))
                self._drop_conn(conn)
                return False
            if self._auth is None:
                conn.tenants = ("*",)
            else:
                token = h.get("token")
                grant = self._auth.get(str(token)) \
                    if token is not None else None
                if grant is None:
                    sp.tag(outcome="auth_rejected")
                    self._send_error(conn, 0, errors.AuthRejected(
                        f"{SITE}: unknown or missing auth token",
                        reason="token"))
                    self._drop_conn(conn)
                    return False
                conn.tenants = grant
            sp.tag(outcome="accepted", version=wp.WIRE_VERSION)
        self._send(conn, [wp.encode_frame(
            wp.T_WELCOME, 0,
            {"version": wp.WIRE_VERSION, "server": self.name,
             "n_sets": getattr(self._target, "n_sets",
                               len(getattr(self._target, "_sets", ()))),
             "tenants": list(conn.tenants)})])
        return True

    def _handle(self, conn: _Conn, ftype: int, req_id: int,
                header: dict, blobs: list) -> None:
        if ftype == wp.T_PING:
            self._send(conn, [wp.encode_frame(wp.T_PONG, req_id, {})])
            return
        if ftype == wp.T_SUBMIT:
            self._handle_submit(conn, req_id, header, blobs)
            return
        if ftype == wp.T_DELTA:
            self._handle_delta(conn, req_id, header)
            return
        if ftype in (wp.T_MIG_BEGIN, wp.T_MIG_STATE, wp.T_MIG_DELTA,
                     wp.T_MIG_COMMIT):
            self._handle_migration(conn, ftype, req_id, header, blobs)
            return
        self._send_error(conn, req_id, errors.CorruptInput(
            f"{SITE}: unexpected frame type {ftype} "
            f"({wp.FRAME_NAMES.get(ftype, '?')})"))

    def _handle_submits(self, conn: _Conn, batch: list) -> None:
        """Admit a burst of SUBMIT frames under ONE loop-lock
        acquisition (RLock — the per-frame handler's own take nests).
        The pump cannot interleave a partial-pool dispatch between the
        batch's admits, so the assembled pools reflect the client's
        pipelining depth.  Per-frame semantics (auth, backpressure,
        decode, typed rejections) are unchanged."""
        if len(batch) == 1:
            self._handle_submit(conn, *batch[0])
            return
        with self._target._lock:
            for req_id, header, blobs in batch:
                self._handle_submit(conn, req_id, header, blobs)

    def _handle_submit(self, conn: _Conn, req_id: int, header: dict,
                       blobs: list) -> None:
        ctx = header.get("trace")
        tenant = str(header.get("tenant", "default"))
        with obs_trace.span_from(ctx, "rpc.submit", site=SITE,
                                 req_id=req_id, tenant=tenant) as sp:
            # boundary checks BEFORE any bytes reach the loop: grant,
            # then pipelining window, then the decode
            if not conn.allows(tenant):
                sp.tag(outcome="auth_rejected")
                self._send_error(conn, req_id, errors.AuthRejected(
                    f"{SITE}: tenant {tenant!r} outside this "
                    f"connection's grant", reason="tenant",
                    tenant=tenant))
                return
            if len(conn.inflight) >= self._max_inflight:
                sp.tag(outcome="backpressure")
                self._send_error(conn, req_id, errors.WireBackpressure(
                    f"{SITE}: {len(conn.inflight)} requests in flight "
                    f"(cap {self._max_inflight}) — drain responses and "
                    f"resubmit", inflight=len(conn.inflight),
                    cap=self._max_inflight))
                return
            try:
                query = wp.decode_query(header.get("query") or {}, blobs)
                request = ServingRequest(
                    set_id=int(header.get("set_id", 0)), query=query,
                    tenant=tenant,
                    deadline_ms=header.get("deadline_ms"))
                # submit and register under the TARGET's lock: the pump
                # thread fires the completion listener while holding
                # it, so a ticket cannot complete in the gap between
                # admission and its req_id registration (which would be
                # a silent drop)
                with self._target._lock:
                    ticket = self._target.submit(request)
                    with self._lock:
                        self._pending[id(ticket)] = (conn, req_id)
            except (AdmissionRejected, errors.RoaringRuntimeError,
                    errors.CorruptInput) as exc:
                sp.tag(outcome=wp.error_fields(exc)["code"])
                self._send_error(conn, req_id, exc)
                return
            except Exception as exc:
                # a malformed submit (bad set_id, bad op) must die as a
                # typed frame, never a raw traceback or a dropped conn
                sp.tag(outcome="corrupt_input")
                self._send_error(conn, req_id, errors.CorruptInput(
                    f"{SITE}: unserviceable submit: "
                    f"{type(exc).__name__}: {exc}"))
                return
            sp.tag(outcome="admitted", set_id=request.set_id)
        conn.inflight.add(req_id)
        self.stats["submits"] += 1
        self._kick.set()

    def _handle_delta(self, conn: _Conn, req_id: int,
                      header: dict) -> None:
        tenant = str(header.get("tenant", "default"))
        if not conn.allows(tenant):
            self._send_error(conn, req_id, errors.AuthRejected(
                f"{SITE}: tenant {tenant!r} outside this connection's "
                f"grant", reason="tenant", tenant=tenant))
            return
        try:
            sid = int(header.get("set_id", 0))
            adds = {int(k): v for k, v in
                    (header.get("adds") or {}).items()}
            removes = {int(k): v for k, v in
                       (header.get("removes") or {}).items()}
            # serialize with the pump: an escalated repack frees the
            # set's device buffers, and a dispatch mid-flight on the
            # OLD buffers would die unclassified ("buffer deleted"),
            # losing its pool's tickets — the loop lock is the same
            # RLock _pump_locked holds across assemble+dispatch
            with self._target._lock:
                if hasattr(self._target, "apply_delta"):
                    report = self._target.apply_delta(
                        sid, adds or None, removes or None)
                    report = report[0] if isinstance(report, list) \
                        else report
                else:
                    ds = self._target._engine._engines[sid]._ds
                    report = ds.apply_delta(adds or None,
                                            removes or None)
        except (errors.RoaringRuntimeError, errors.CorruptInput) as exc:
            self._send_error(conn, req_id, exc)
            return
        except Exception as exc:
            self._send_error(conn, req_id, errors.CorruptInput(
                f"{SITE}: unserviceable delta: "
                f"{type(exc).__name__}: {exc}"))
            return
        self.stats["deltas"] += 1
        h, bl = wp.encode_result({k: v for k, v in report.items()
                                  if isinstance(v, (int, float, str,
                                                    bool, type(None)))})
        self._send(conn, [wp.encode_frame(wp.T_RESULT, req_id, h,
                                          tuple(bl))])

    # ------------------------------------------------- completion delivery

    def _on_complete(self, tickets: list) -> None:
        """Completion-listener seam: map each completed ticket that a
        connection is waiting on to its response frame, coalesced into
        one write per connection."""
        per_conn: dict = {}
        with self._lock:
            routed = []
            for t in tickets:
                got = self._pending.pop(id(t), None)
                if got is not None:
                    routed.append((t, got[0], got[1]))
        for t, conn, req_id in routed:
            conn.inflight.discard(req_id)
            frame = self._ticket_frame(t, req_id)
            per_conn.setdefault(id(conn), (conn, []))[1].append(frame)
        for conn, frames in per_conn.values():
            self._send(conn, frames)

    def _ticket_frame(self, t, req_id: int) -> bytes:
        with obs_trace.span_from(t.trace_ctx, "rpc.result", site=SITE,
                                 req_id=req_id, outcome=t.status) as sp:
            if t.status == "done":
                self.stats["results"] += 1
                h, bl = wp.encode_result(t.result, degraded=t.degraded,
                                         wall_ms=t.wall_ms,
                                         missed=bool(t.missed))
                frame = wp.encode_frame(wp.T_RESULT, req_id, h,
                                        tuple(bl))
            else:
                self.stats["errors"] += 1
                exc = t.error if t.error is not None \
                    else errors.RemoteFailed(
                        f"{SITE}: ticket finished {t.status!r} with no "
                        f"error attached")
                obs_metrics.counter("rb_wire_error_frames_total",
                                    code=wp.error_fields(exc)["code"]
                                    ).inc()
                frame = wp.encode_frame(wp.T_ERROR, req_id,
                                        wp.error_fields(exc))
            sp.tag(frame_bytes=len(frame))
        return frame

    # ------------------------------------------------------------- pumping

    def _backlog(self) -> int:
        if hasattr(self._target, "backlog"):
            return self._target.backlog()
        return self._target._backlog()

    def _pump_loop(self) -> None:
        while self._alive:
            self._kick.wait(timeout=0.05)
            self._kick.clear()
            if not self._alive:
                return
            try:
                self._target.pump()
                # the wire batching window: wait a beat for more
                # pipelined arrivals, then force the partial pool out
                # so a lone request never waits for deadline pressure
                while self._alive and self._backlog() > 0:
                    if self._kick.wait(timeout=self._coalesce_s):
                        self._kick.clear()
                        self._target.pump()
                        continue
                    self._target.drain()
                    break
            except Exception:
                _log.exception("%s: pump thread error", SITE)

    # ----------------------------------------------------------- migration

    def _handle_migration(self, conn: _Conn, ftype: int, req_id: int,
                          header: dict, blobs: list) -> None:
        from . import migrate as wire_migrate

        mid = str(header.get("mig_id", "0"))
        tenant = str(header.get("tenant", "default"))
        if not conn.allows(tenant):
            self._send_error(conn, req_id, errors.AuthRejected(
                f"{SITE}: tenant {tenant!r} outside this connection's "
                f"grant", reason="tenant", tenant=tenant))
            return
        try:
            if ftype == wp.T_MIG_BEGIN:
                conn.mig[mid] = {"tenant": tenant,
                                 "meta": header.get("meta"),
                                 "blobs": [], "records": []}
                ack = {"phase": "begin"}
            elif ftype == wp.T_MIG_STATE:
                conn.mig[mid]["blobs"].extend(blobs)
                ack = {"phase": "state",
                       "got": len(conn.mig[mid]["blobs"])}
            elif ftype == wp.T_MIG_DELTA:
                conn.mig[mid]["records"].extend(
                    header.get("records") or [])
                ack = {"phase": "delta",
                       "got": len(conn.mig[mid]["records"])}
            else:                                      # T_MIG_COMMIT
                mig = conn.mig.pop(mid)
                state = wire_migrate.unflatten_state(
                    mig["meta"], mig["blobs"])
                ds = durability.restore_state(state)
                for rec in mig["records"]:
                    durability.replay_record(ds, rec)
                crcs = wire_migrate.source_crcs(ds)
                if self._on_migrate is not None:
                    self._on_migrate(mig["tenant"], ds)
                else:
                    self.migrated[mig["tenant"]] = ds
                self.stats["migrations"] += 1
                obs_metrics.counter("rb_wire_migrations_total").inc()
                ack = {"phase": "commit", "source_crcs": crcs,
                       "records": len(mig["records"]),
                       "bytes": sum(len(b) for b in mig["blobs"])}
        except KeyError:
            self._send_error(conn, req_id, errors.CorruptInput(
                f"{SITE}: migration frame for unknown stream {mid!r} "
                f"(begin never arrived?)"))
            return
        except (errors.RoaringRuntimeError, errors.CorruptInput) as exc:
            self._send_error(conn, req_id, exc)
            return
        except Exception as exc:
            self._send_error(conn, req_id, errors.CorruptInput(
                f"{SITE}: unserviceable migration frame: "
                f"{type(exc).__name__}: {exc}"))
            return
        self._send(conn, [wp.encode_frame(wp.T_MIG_ACK, req_id, ack)])
