"""Binary RPC data plane: the network boundary of the serving stack.

``wire`` turns the library-with-a-loop into a service (docs/WIRE.md):
a length+CRC framed, versioned binary protocol over TCP whose bitmap
payloads are the portable ``format/spec.py`` bytes verbatim, with
per-connection request pipelining + frame coalescing, typed wire error
frames for every outcome (admission rejections, sheds, auth refusals,
backpressure — never a dropped connection), auth/tenancy checked at
the boundary before any bytes reach a ServingLoop, and ``rpc.*`` spans
riding the trace-propagation envelope across the socket.

- :mod:`.protocol` — frame grammar + codecs (transport-free);
- :mod:`.server` — threaded front door over a ServingLoop/PodFrontDoor;
- :mod:`.client` — pipelining client (``submit_many`` coalesces);
- :mod:`.migrate` — live tenant migration streamed as wire frames;
- :mod:`.bootstrap` — ``python -m roaringbitmap_tpu.wire.bootstrap``:
  a deterministic second-process server for tests and benches.
"""

from .client import WireClient, WireTicket
from .migrate import WireMigrationSession, migrate_tenant_wire
from .protocol import (MAX_FRAME_BYTES, WIRE_MAGIC, WIRE_VERSION,
                       WireResult)
from .server import WireServer

__all__ = ["WireServer", "WireClient", "WireTicket", "WireResult",
           "WireMigrationSession", "migrate_tenant_wire",
           "WIRE_MAGIC", "WIRE_VERSION", "MAX_FRAME_BYTES"]
