"""Second-process wire server: ``python -m roaringbitmap_tpu.wire.bootstrap``.

The cross-process half of every wire test and the ``pod_replay`` bench
lane: builds the SAME seeded dataset as the parent (both sides call
``serving.replay.build_dataset`` with identical knobs — bit-exact
parity needs no data shipping), stands up a :class:`WireServer` over a
``ServingLoop`` (or a ``PodFrontDoor`` with ``--frontdoor N``, the
migration-capable shape), prints ONE JSON line::

    {"port": 12345, "host": "127.0.0.1", "sets": 2, "pid": 4242}

to stdout, then serves until **stdin closes** — the parent owns the
child's lifetime through the pipe, so a dead parent can never leak a
listening server.  Tracing, faults, and metrics all arrive through the
usual env knobs (``ROARING_TPU_TRACE``, ``ROARING_TPU_FAULTS``), which
the spawning process sets on the child's environment.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _parse_auth(pairs) -> dict | None:
    """``token=t0,t1`` / ``token=*`` CLI grants -> WireServer auth."""
    if not pairs:
        return None
    auth = {}
    for p in pairs:
        token, _, grants = p.partition("=")
        auth[token] = [g for g in grants.split(",") if g] or ["*"]
    return auth


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m roaringbitmap_tpu.wire.bootstrap",
        description="deterministic second-process wire server")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sets", type=int, default=2)
    ap.add_argument("--sources", type=int, default=8)
    ap.add_argument("--tenants", type=int, default=8)
    ap.add_argument("--density", type=int, default=4096)
    ap.add_argument("--users", type=int, default=1 << 20)
    ap.add_argument("--no-columns", action="store_true",
                    help="skip the analytics column attach")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (the printed JSON says which)")
    ap.add_argument("--auth", action="append", default=None,
                    metavar="TOKEN=T0,T1",
                    help="repeatable auth grant (TOKEN=* grants all "
                         "tenants); omitted = auth off")
    ap.add_argument("--frontdoor", type=int, default=0, metavar="HOSTS",
                    help="serve a PodFrontDoor over a simulated N-host "
                         "pod instead of a bare ServingLoop (the "
                         "migration-capable target)")
    ap.add_argument("--pool-target", type=int, default=8)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--deadline-ms", type=float, default=10_000.0)
    ap.add_argument("--max-inflight", type=int, default=256)
    ap.add_argument("--coalesce-s", type=float, default=0.002)
    args = ap.parse_args(argv)

    # keep the child off any accelerator the parent owns: the wire
    # boundary is a CPU-path contract and the tests spawn many of these
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from ..parallel.multiset import DeviceBitmapSet, MultiSetBatchEngine
    from ..runtime import guard
    from ..serving import replay
    from ..serving.loop import ServingLoop, ServingPolicy
    from .server import WireServer

    profile = replay.ReplayProfile(
        sets=args.sets, sources=args.sources, tenants=args.tenants,
        density=args.density, users=args.users, seed=args.seed,
        analytics_col="" if args.no_columns else "v")
    bitmap_sets, columns = replay.build_dataset(profile)
    sets = [DeviceBitmapSet(b, layout="dense") for b in bitmap_sets]
    replay.attach_columns(sets, profile, columns)

    policy = ServingPolicy(
        pool_target=args.pool_target, max_queue=args.max_queue,
        default_deadline_ms=args.deadline_ms,
        guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None))
    if args.frontdoor:
        from ..parallel import podmesh
        from ..serving.frontdoor import PodFrontDoor

        target = PodFrontDoor(
            sets, pod=podmesh.PodMesh.simulate(args.frontdoor),
            policy=policy)
    else:
        target = ServingLoop(MultiSetBatchEngine(sets), policy)

    server = WireServer(target, host=args.host, port=args.port,
                        auth=_parse_auth(args.auth),
                        max_inflight=args.max_inflight,
                        coalesce_s=args.coalesce_s,
                        name=f"bootstrap-{args.seed}")
    server.start()
    host, port = server.address
    print(json.dumps({"port": port, "host": host, "sets": args.sets,
                      "pid": os.getpid()}), flush=True)
    try:
        sys.stdin.buffer.read()       # parent closes the pipe -> exit
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
