"""Live tenant migration over the wire (docs/WIRE.md "Migration").

The in-process :class:`serving.migration.MigrationSession` hands the
captured snapshot dict to ``durability.restore_state`` directly — fine
inside one process, useless across two.  This module ships the SAME
snapshot (``durability.capture_state``, spec.py bitmap bytes verbatim)
plus the journal-tail catch-up records as wire frames when source and
destination are separate OS processes:

    MIG_BEGIN  {mig_id, tenant, meta}       snapshot metadata, blob
                                            slots as {"__blob__": i}
    MIG_STATE  {mig_id} + blobs             snapshot bytes, chunked
    MIG_DELTA  {mig_id, records: [...]}     journal-vocabulary records
                                            (the dual-write window's
                                            catch-up tail)
    MIG_COMMIT {mig_id}                     destination restores +
                                            replays + installs
    MIG_ACK    {source_crcs, bytes, ...}    bit-exactness evidence

The destination re-applies records through ``durability.replay_record``
— replay is apply, so the commit ACK's per-source CRCs must equal the
source's own post-drain CRCs; :func:`migrate_tenant_wire` checks that
pin and reports the mismatch typed.  The source keeps serving the
tenant untouched throughout (ownership of the local routing tables
never moves — the REMOTE process gains a bit-exact live twin), so the
zero-non-expired-failure property of in-process migration holds by
construction.
"""

from __future__ import annotations

import time
import zlib

from ..mutation import delta as mut_delta
from ..mutation import durability
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..runtime import errors
from . import protocol as wp

SITE = "wire"

#: blob bytes per MIG_STATE frame before a new frame starts (well under
#: protocol.MAX_FRAME_BYTES; small enough to interleave with traffic)
STATE_CHUNK_BYTES = 4 << 20
#: catch-up records per MIG_DELTA frame
DELTA_CHUNK_RECORDS = 64


# ------------------------------------------------------ state flattening

def flatten_state(state: dict) -> tuple:
    """Snapshot dict -> (pure-JSON meta, ordered blob list): every
    ``bytes`` value is replaced by ``{"__blob__": index}`` so the
    metadata rides a frame header and the bitmap bytes ride as frame
    blobs verbatim."""
    blobs: list = []

    def walk(v):
        if isinstance(v, (bytes, bytearray, memoryview)):
            blobs.append(bytes(v))
            return {"__blob__": len(blobs) - 1}
        if isinstance(v, dict):
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [walk(x) for x in v]
        return v

    return walk(dict(state)), blobs


def unflatten_state(meta, blobs: list) -> dict:
    """Inverse of :func:`flatten_state`; malformed slots die typed."""

    def walk(v):
        if isinstance(v, dict):
            if set(v.keys()) == {"__blob__"}:
                i = int(v["__blob__"])
                if not 0 <= i < len(blobs):
                    raise errors.CorruptInput(
                        f"{SITE}: migration blob slot {i} out of range "
                        f"(got {len(blobs)} blobs)")
                return blobs[i]
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, list):
            return [walk(x) for x in v]
        return v

    out = walk(meta)
    if not isinstance(out, dict):
        raise errors.CorruptInput(
            f"{SITE}: migration meta is not an object")
    return out


def source_crcs(ds) -> list:
    """Per-source CRC32 of the spec.py serialization — the bit-exact
    fingerprint both ends of a wire migration compare."""
    return [zlib.crc32(bm.serialize())
            for bm in mut_delta.host_bitmaps(ds)]


# --------------------------------------------------------- source session

class WireMigrationSession:
    """Source half of a cross-process migration: rides the front door's
    dual-write window (``fd._dual_writes``) exactly like the in-process
    session, but forwards the snapshot and catch-up tail as frames."""

    def __init__(self, fd, sid: int, client, tenant: str | None = None):
        self.fd = fd
        self.sid = int(sid)
        self.client = client
        self.tenant = tenant or f"sid{int(sid)}"
        self.mig_id = f"{self.tenant}-{id(self):x}"
        self.state: dict | None = None
        self.bytes_streamed = 0
        self._records: list = []      # journal-vocabulary catch-up tail
        self._seq = 0
        self.trace_ctx = obs_trace.inject()

    # the dual-write window hook (called by PodFrontDoor.apply_delta
    # under the front-door lock)
    def on_delta(self, adds, removes, repack: str = "auto") -> None:
        with obs_trace.span_from(self.trace_ctx, "pod.dual_write",
                                 site=SITE, set_id=self.sid,
                                 to="wire", buffered=True):
            self._seq += 1
            self._records.append({
                "kind": "delta", "seq": self._seq,
                "adds": durability._jsonable_delta(adds or {}),
                "removes": durability._jsonable_delta(removes or {})})

    def begin(self) -> None:
        from ..serving.migration import MigrationError

        fd, sid = self.fd, self.sid
        if fd.plan.regime(sid) == "sharded":
            raise MigrationError(
                f"tenant {sid} is sharded-regime: it already spans "
                f"every pod host — it has no single image to ship")
        with fd._lock:
            if sid in fd._dual_writes:
                raise MigrationError(
                    f"tenant {sid} is already migrating")
            self.state = durability.capture_state(fd._sets[sid],
                                                  tenant=self.tenant)
            fd._dual_writes[sid] = self

    def copy(self) -> None:
        """Ship the snapshot: BEGIN + chunked STATE frames, pipelined
        in one coalesced write, acked by the destination."""
        meta, blobs = flatten_state(self.state)
        frames = [(wp.T_MIG_BEGIN,
                   {"mig_id": self.mig_id, "tenant": self.tenant,
                    "meta": meta}, ())]
        chunk: list = []
        size = 0
        for b in blobs:
            chunk.append(b)
            size += len(b)
            if size >= STATE_CHUNK_BYTES:
                frames.append((wp.T_MIG_STATE,
                               {"mig_id": self.mig_id,
                                "tenant": self.tenant}, tuple(chunk)))
                chunk, size = [], 0
        if chunk:
            frames.append((wp.T_MIG_STATE,
                           {"mig_id": self.mig_id,
                            "tenant": self.tenant}, tuple(chunk)))
        self.bytes_streamed = sum(len(b) for b in blobs)
        obs_metrics.counter("rb_migration_bytes_total").inc(
            self.bytes_streamed)
        self.client.migrate_frames(frames)

    def finish(self) -> dict:
        """Drain the catch-up tail, commit on the destination, verify
        the bit-exact pin, close the dual-write window."""
        fd, sid = self.fd, self.sid
        t0 = time.perf_counter()
        with fd._lock:
            records, self._records = self._records, []
            fd._dual_writes.pop(sid, None)
            local_crcs = source_crcs(fd._sets[sid])
        frames = []
        for i in range(0, len(records), DELTA_CHUNK_RECORDS):
            frames.append((wp.T_MIG_DELTA,
                           {"mig_id": self.mig_id, "tenant": self.tenant,
                            "records":
                                records[i:i + DELTA_CHUNK_RECORDS]}, ()))
        frames.append((wp.T_MIG_COMMIT,
                       {"mig_id": self.mig_id, "tenant": self.tenant},
                       ()))
        ack = self.client.migrate_frames(frames)
        blip_ms = (time.perf_counter() - t0) * 1e3
        remote_crcs = list(ack.get("source_crcs") or ())
        if remote_crcs != local_crcs:
            raise errors.ShadowMismatch(
                f"{SITE}: migrated tenant {self.tenant!r} diverged from "
                f"the source after catch-up: remote CRCs {remote_crcs} "
                f"!= local {local_crcs}")
        return {"set_id": sid, "tenant": self.tenant, "to": "wire",
                "bytes": self.bytes_streamed,
                "catch_up_records": len(records),
                "source_crcs": local_crcs,
                "blip_ms": round(blip_ms, 3)}


def migrate_tenant_wire(fd, sid: int, client, during=None,
                        tenant: str | None = None) -> dict:
    """One-shot cross-process migration: begin -> copy -> [``during``
    drives traffic + deltas inside the dual-write window] -> finish.
    The whole move is one ``pod.migrate`` span (``to="wire"``) with
    ``rpc.*`` spans nested under the frame exchanges."""
    with obs_trace.span("pod.migrate", site=SITE, set_id=int(sid),
                        to="wire") as sp:
        session = WireMigrationSession(fd, sid, client, tenant=tenant)
        session.begin()
        try:
            session.copy()
            if during is not None:
                during(fd)
            report = session.finish()
        except BaseException:
            with fd._lock:
                fd._dual_writes.pop(int(sid), None)
            obs_metrics.counter("rb_migration_total",
                                status="failed").inc()
            raise
        sp.tag(bytes=report["bytes"], blip_ms=report["blip_ms"],
               records=report["catch_up_records"])
        obs_metrics.counter("rb_migration_total", status="ok").inc()
    return report
