"""Pallas TPU kernels — fused fast paths for the hot aggregation ops.

The flagship kernel is the ragged segmented reduction: one sequential grid
pass over M densified containers, accumulating each key's segment in VMEM and
flushing to HBM once per key.  Versus the jnp doubling tier
(ops.dense.segmented_reduce, O(M log G) HBM traffic) this touches each input
row exactly once: O(M) reads + O(K) writes.

It is the TPU re-design of the reference's lazy-or chain
(Container.lazyOR/lazyIOR -> BitmapContainer.lazyor, BitmapContainer.java:878-909):
"lazy" (skip per-step cardinality) becomes "accumulate in VMEM"; the final
repairAfterLazy popcount (Container.java:869-873) runs as one fused pass on
the way out.

Layout note: container word images are reshaped u32[2048] -> u32[16, 128] so
every block meets the (8, 128) fp32/i32 tile floor without padding waste.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import dense

WORDS32 = 2048
_SUB, _LANE = 16, 128  # 16*128 = 2048 u32 words = 2^16 bits

#: Ceiling on the scalar-prefetch array length (seg_ids / blk_seg) for the
#: segmented kernels: the whole array is prefetched into SMEM, so callers
#: must fall back to the XLA doubling engine past this many entries.
SMEM_PREFETCH_MAX = 1 << 17


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


def _seg_reduce_kernel(op):
    def kernel(seg_ref, words_ref, out_ref):
        i = pl.program_id(0)
        prev = seg_ref[jnp.maximum(i - 1, 0)]
        is_head = jnp.logical_or(i == 0, seg_ref[i] != prev)

        @pl.when(is_head)
        def _init():
            out_ref[...] = words_ref[...]

        @pl.when(jnp.logical_not(is_head))
        def _accum():
            out_ref[...] = op(out_ref[...], words_ref[...])

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "num_segments"))
def segmented_reduce_pallas(op: str, words: jnp.ndarray, seg_ids: jnp.ndarray,
                            num_segments: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Ragged per-key reduce: (u32[M,2048], sorted i32[M]) -> (u32[K,2048], i32[K]).

    seg_ids must be sorted ascending; padding rows carry segment id K and land
    in a scratch row that is dropped.  Sequential-grid VMEM accumulation: the
    output BlockSpec maps every row of a segment to the same block, so the
    accumulator stays on-chip until the segment ends (same mechanism as a
    matmul k-loop).
    """
    ops = dense.OPS
    m = words.shape[0]
    w3 = words.reshape(m, _SUB, _LANE)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m,),
        in_specs=[pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (seg[i], 0, 0)),
    )
    out = pl.pallas_call(
        _seg_reduce_kernel(ops[op]),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments + 1, _SUB, _LANE), jnp.uint32),
        interpret=_use_interpret(),
    )(seg_ids, w3)
    heads = out[:num_segments].reshape(num_segments, WORDS32)
    cards = jnp.sum(jax.lax.population_count(heads).astype(jnp.int32), axis=-1)
    return heads, cards


def _seg_reduce_blocked_kernel(op, block):
    def kernel(seg_ref, words_ref, out_ref):
        i = pl.program_id(0)
        prev = seg_ref[jnp.maximum(i - 1, 0)]
        is_head = jnp.logical_or(i == 0, seg_ref[i] != prev)
        # static tree-reduce over the block axis (lax.reduce has no Pallas
        # TPU lowering); block is a power of two
        parts = [words_ref[0, j] for j in range(block)]
        while len(parts) > 1:
            parts = [op(parts[j], parts[j + 1])
                     for j in range(0, len(parts), 2)]
        r = parts[0]

        @pl.when(is_head)
        def _init():
            out_ref[0] = r

        @pl.when(jnp.logical_not(is_head))
        def _accum():
            out_ref[0] = op(out_ref[0], r)

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "num_segments", "block"))
def segmented_reduce_pallas_blocked(
        op: str, words: jnp.ndarray, blk_seg: jnp.ndarray,
        num_segments: int, block: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Blocked ragged reduce over segment-padded rows (ops.packing.pack_blocked_compact).

    Each grid step reduces `block` same-segment rows in VMEM before touching
    the accumulator — cutting grid steps (and their fixed overhead) by
    `block`x versus the row-per-step kernel.  OR/XOR only (padding rows are
    zero, their identity).
    """
    assert op in ("or", "xor")
    ops = dense.OPS
    mb = words.shape[0]
    w3 = words.reshape(mb // block, block, _SUB, _LANE)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(mb // block,),
        in_specs=[pl.BlockSpec((1, block, _SUB, _LANE),
                               lambda i, seg: (i, 0, 0, 0))],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (seg[i], 0, 0)),
    )
    out = pl.pallas_call(
        _seg_reduce_blocked_kernel(ops[op], block),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments + 1, _SUB, _LANE),
                                       jnp.uint32),
        interpret=_use_interpret(),
    )(blk_seg, w3)
    heads = out[:num_segments].reshape(num_segments, WORDS32)
    cards = jnp.sum(jax.lax.population_count(heads).astype(jnp.int32), axis=-1)
    return heads, cards


def _nibble_reduce_kernel(op_name: str, op):
    def kernel(seg_ref, counts_ref, dp_ref, out_ref):
        i = pl.program_id(0)
        prev = seg_ref[jnp.maximum(i - 1, 0)]
        is_head = jnp.logical_or(i == 0, seg_ref[i] != prev)
        # (4, 16, 128) plane-major nibble counts -> bit words, in-register
        word = dense.counts_tile_to_word(counts_ref[0], op_name)

        @pl.when(is_head)
        def _init():
            # fold the dense-wire rows' partial in exactly once per segment
            out_ref[0] = op(word, dp_ref[0])

        @pl.when(jnp.logical_not(is_head))
        def _accum():
            out_ref[0] = op(out_ref[0], word)

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "num_segments"))
def fused_nibble_reduce(op: str, counts: jnp.ndarray,
                        dense_partial: jnp.ndarray, grp_seg: jnp.ndarray,
                        num_segments: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fused compact-layout wide reduce: per-group nibble counts
    (ops.dense.nibble_counts_impl) + per-segment dense-row partials ->
    (u32[K, 2048] per-key words, i32[K] cardinalities), OR/XOR only.

    One sequential grid pass over the count groups, converting counts to
    bits in-register (VPU SWAR) and accumulating each segment in VMEM —
    the round-3 verdict's missing fusion: the compact layout previously
    materialized the full row image to HBM and read it back per query.
    Count groups of a segment are consecutive (grp_seg sorted); the
    trailing scratch group/partial row lands in out row K and is dropped.
    """
    ops = dense.OPS
    n_blocks = counts.shape[0]
    c4 = counts.reshape(n_blocks, 4, _SUB, _LANE)
    dp3 = dense_partial.reshape(num_segments + 1, _SUB, _LANE)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((1, 4, _SUB, _LANE), lambda i, seg: (i, 0, 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (seg[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (seg[i], 0, 0)),
    )
    out = pl.pallas_call(
        _nibble_reduce_kernel(op, ops[op]),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments + 1, _SUB, _LANE),
                                       jnp.uint32),
        interpret=_use_interpret(),
    )(grp_seg, c4, dp3)
    heads = out[:num_segments].reshape(num_segments, WORDS32)
    cards = jnp.sum(jax.lax.population_count(heads).astype(jnp.int32), axis=-1)
    return heads, cards


# ---------------------------------------------------------- Pallas densify
#
# The compact layout's per-query rebuild was scatter-bound: XLA lowers the
# value scatter-add of ops.dense.densify_streams to a serial ~13 ns/value
# update loop on TPU (~13 ms/query at 10^6 values — the r5 verdict's "weak"
# item 2, which effectively excluded the capacity rung of the residency
# ladder from hot queries).  This kernel replaces the scatter with per-block
# one-hot accumulation in VMEM: the value stream arrives pre-chunked
# (ops.packing.chunk_value_stream — every chunk owns ONE destination row),
# each grid step converts its chunk to a (16, 128) word tile and ORs it into
# the row's VMEM accumulator (the segmented-reduce output-BlockSpec
# mechanism, so a row's tile stays on-chip across its chunks).
#
# The chunk -> tile conversion runs on the MXU, not as 2048 VPU compares per
# value: per byte plane p and sublane s, A[16p+s, j] = [sub_j == s] *
# byte_p(bit_j); B[l, j] = [lane_j == l]; then tile bytes = A @ B^T — one
# (64, C) x (C, 128) f32 matmul per chunk.  Exactness: values within a
# container are distinct, so each (word, bit) contributes at most once and
# every byte-plane sum stays <= 255 (exact in f32); padding slots carry the
# CHUNK_PAD sentinel and are masked to zero (a SUM is not duplicate-
# idempotent the way the OR it replaces was).

#: Values per chunk — must match ops.packing.CHUNK_VALUES.
DENSIFY_CHUNK = 128


def _densify_chunk_kernel(chunk: int):
    def kernel(row_ref, vals_ref, out_ref):
        i = pl.program_id(0)
        prev = row_ref[jnp.maximum(i - 1, 0)]
        is_head = jnp.logical_or(i == 0, row_ref[i] != prev)
        v = vals_ref[...].astype(jnp.uint32)                  # (1, chunk)
        valid = v <= jnp.uint32(0xFFFF)
        w = ((v & jnp.uint32(0xFFFF)) >> 5).astype(jnp.int32)  # word 0..2047
        sub = w >> 7                                           # sublane 0..15
        lane = w & 127                                         # lane 0..127
        bit = jnp.where(valid, jnp.uint32(1) << (v & 31), jnp.uint32(0))
        sub_iota = jax.lax.broadcasted_iota(jnp.int32, (_SUB, chunk), 0)
        mask_sub = (sub_iota == sub).astype(jnp.float32)       # (16, chunk)
        a = jnp.concatenate(
            [mask_sub * ((bit >> (8 * p)) & jnp.uint32(0xFF)
                         ).astype(jnp.float32)
             for p in range(4)], axis=0)                       # (64, chunk)
        lane_iota = jax.lax.broadcasted_iota(jnp.int32, (_LANE, chunk), 0)
        b = (lane_iota == lane).astype(jnp.float32)            # (128, chunk)
        r = jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        planes = [r[16 * p:16 * (p + 1)].astype(jnp.uint32) for p in range(4)]
        tile = (planes[0] | (planes[1] << 8)
                | (planes[2] << 16) | (planes[3] << 24))

        @pl.when(is_head)
        def _init():
            out_ref[0] = tile

        @pl.when(jnp.logical_not(is_head))
        def _accum():
            out_ref[0] = out_ref[0] | tile

    return kernel


@functools.partial(jax.jit, static_argnames=("n_rows",))
def densify_chunks_pallas(chunk_vals: jnp.ndarray, chunk_row: jnp.ndarray,
                          row_live: jnp.ndarray, n_rows: int) -> jnp.ndarray:
    """Chunked value stream -> dense u32[n_rows, 2048] container image.

    chunk_vals u32[NC, CHUNK], chunk_row i32[NC] sorted ascending (padding
    chunks carry n_rows, the scratch row); row_live u32[n_rows + 1] is 1 for
    rows that own at least one chunk.  Rows with no chunks are never touched
    by the grid, so their (undefined) buffer contents are masked to zero on
    the way out — dense-wire rows are overwritten by the caller's row .set
    either way.  Bit-exact vs ops.dense.densify_streams' value scatter.
    """
    return densify_chunks_impl(chunk_vals, chunk_row, row_live, n_rows)


def densify_chunks_impl(chunk_vals, chunk_row, row_live,
                        n_rows: int) -> jnp.ndarray:
    """Traceable body of densify_chunks_pallas (callers inline it inside
    chained loops / larger one-dispatch programs)."""
    nc, chunk = chunk_vals.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nc,),
        in_specs=[pl.BlockSpec((1, chunk), lambda i, row: (i, 0))],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i, row: (row[i], 0, 0)),
    )
    out = pl.pallas_call(
        _densify_chunk_kernel(chunk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_rows + 1, _SUB, _LANE), jnp.uint32),
        interpret=_use_interpret(),
    )(chunk_row, chunk_vals)
    out = jnp.where(row_live[:, None, None] != 0, out, jnp.uint32(0))
    return out[:n_rows].reshape(n_rows, WORDS32)


def _counts_reduce_kernel(op_name: str, op, groups: int):
    def kernel(seg_ref, counts_ref, out_ref):
        i = pl.program_id(0)
        prev = seg_ref[jnp.maximum(i - 1, 0)]
        is_head = jnp.logical_or(i == 0, seg_ref[i] != prev)
        parts = [dense.counts_tile_to_word(counts_ref[0, gidx], op_name)
                 for gidx in range(groups)]
        # static tree-reduce; groups is a power of two (enforced by the
        # counts-layout block validation)
        while len(parts) > 1:
            parts = [op(parts[j], parts[j + 1])
                     for j in range(0, len(parts), 2)]
        word = parts[0]

        @pl.when(is_head)
        def _init():
            out_ref[0] = word

        @pl.when(jnp.logical_not(is_head))
        def _accum():
            out_ref[0] = op(out_ref[0], word)

    return kernel


@functools.partial(jax.jit, static_argnames=("op", "num_segments",
                                             "groups_per_step"))
def counts_segmented_reduce(op: str, counts: jnp.ndarray,
                            grp_seg: jnp.ndarray, num_segments: int,
                            groups_per_step: int = 1
                            ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Wide OR/XOR straight off a counts-resident layout
    (ops.dense.build_group_counts): one sequential pass converting nibble
    counts to bits in-register and accumulating per segment in VMEM —
    no scatter, no row image, half the HBM reads of the dense layout.

    counts u32[G, NIBBLE_WORDS] with G a groups_per_step multiple (pad
    groups carry segment id K); grp_seg i32[G] sorted, SMEM-prefetched at
    super-step granularity.
    """
    ops = dense.OPS
    g_all = counts.shape[0]
    assert g_all % groups_per_step == 0
    n_steps = g_all // groups_per_step
    c4 = counts.reshape(n_steps, groups_per_step, 4, _SUB, _LANE)
    step_seg = grp_seg.reshape(n_steps, groups_per_step)[:, 0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_steps,),
        in_specs=[pl.BlockSpec((1, groups_per_step, 4, _SUB, _LANE),
                               lambda i, seg: (i, 0, 0, 0, 0))],
        out_specs=pl.BlockSpec((1, _SUB, _LANE), lambda i, seg: (seg[i], 0, 0)),
    )
    out = pl.pallas_call(
        _counts_reduce_kernel(op, ops[op], groups_per_step),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_segments + 1, _SUB, _LANE),
                                       jnp.uint32),
        interpret=_use_interpret(),
    )(step_seg, c4)
    heads = out[:num_segments].reshape(num_segments, WORDS32)
    cards = jnp.sum(jax.lax.population_count(heads).astype(jnp.int32), axis=-1)
    return heads, cards
