"""Host->device packing: the group-by-key rotation, vectorized.

The reference's ParallelAggregation.groupByKey (ParallelAggregation.java:136-152)
rotates N bitmaps into key -> List<Container> before the fork-join reduce.
Here the same rotation produces flat, fixed-shape tensors ready for HBM:

  words    u32[M, 2048]   every container densified to its 2^16-bit word image
  seg_ids  i32[M]         index into the distinct-key axis, sorted ascending
  head_idx i32[K]         first row of each segment
  keys     [K]            distinct container keys, sorted — u16 for the
                          32-bit tier, u64 high-48 keys for core.bitmap64

Densifying everything to words is what the reference's own wide paths do on
CPU anyway (FastAggregation.java:395-399 and ParallelAggregation.java:108,214
accumulate into dense BitmapContainers); on TPU it additionally buys fully
static shapes and a perfectly regular memory layout.

Rows are padded to a bucket size (next power of two) so recompiles stop once
the workload shape stabilizes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core.bitmap import RoaringBitmap
from ..core.containers import ARRAY_MAX_SIZE, WORDS_PER_CONTAINER

WORDS32 = 2 * WORDS_PER_CONTAINER  # 2048 u32 words per container


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (int(n - 1).bit_length())


def container_words_u32(c) -> np.ndarray:
    """Dense u32[2048] image of one container (little-endian word split)."""
    return c.words().view(np.uint32)


def _expand_runs_batch(run_arrays: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    """Batched run expansion: interleaved (start, len-1) u16 arrays ->
    (concatenated member values i64, per-container value counts i64).

    One cumsum over the whole run stream — the multi-container form of
    core.containers.runs_to_values' delta trick; no per-run Python loop.
    Every input array must be non-empty (empty run containers hold no bits;
    callers skip them).
    """
    starts = np.concatenate([r[0::2] for r in run_arrays]).astype(np.int64)
    lens = np.concatenate([r[1::2] for r in run_arrays]).astype(np.int64) + 1
    n_runs = np.array([r.size // 2 for r in run_arrays], dtype=np.int64)
    deltas = np.ones(int(lens.sum()), dtype=np.int64)
    ends = np.cumsum(lens)
    deltas[0] = starts[0]
    deltas[ends[:-1]] = starts[1:] - (starts[:-1] + lens[:-1] - 1)
    values = np.cumsum(deltas)
    run_heads = np.concatenate(([0], np.cumsum(n_runs)[:-1]))
    counts = np.add.reduceat(lens, run_heads)
    return values, counts


#: Containers per packbits scatter chunk.  Small on purpose: the scatter is
#: random-access within the bit buffer, so the buffer must stay cache-resident
#: (16 * 64 KiB = 1 MiB); measured 4x faster than a 256-container chunk.
_PACK_CHUNK = 16


def densify_containers(conts: list, dest, n_rows: int) -> np.ndarray:
    """Vectorized dense u32[n_rows, 2048] image of a container list.

    conts[i] lands in row dest[i]; remaining rows stay zero.  This is the
    whole-tensor construction SURVEY §7 hard part (a) calls for — the Python
    loop only does list bookkeeping, never data movement:

    - bitmap containers: one stacked fancy-index row assignment,
    - array containers: values scattered via one np.packbits pass per
      _PACK_CHUNK-container chunk,
    - run containers: batched delta-cumsum expansion, then the same scatter.
    """
    out = np.zeros((n_rows, WORDS32), dtype=np.uint32)
    if not conts:
        return out
    from ..core import containers as C

    dest = np.asarray(dest, dtype=np.int64)
    bm_rows: list[int] = []
    bm_words: list[np.ndarray] = []
    run_rows: list[int] = []
    run_arrays: list[np.ndarray] = []
    scatter: list[tuple[int, np.ndarray]] = []  # (row, member values)
    for r, c in zip(dest, conts):
        if isinstance(c, C.BitmapContainer):
            bm_rows.append(r)
            bm_words.append(c.words())
        elif isinstance(c, C.RunContainer):
            if c.runs.size:  # empty run container: row stays zero
                run_rows.append(r)
                run_arrays.append(c.runs)
        else:
            scatter.append((r, c.values()))
    if bm_rows:
        out[np.asarray(bm_rows)] = np.stack(bm_words).view(np.uint32)
    if run_arrays:
        values, counts = _expand_runs_batch(run_arrays)
        pieces = np.split(values, np.cumsum(counts)[:-1])
        scatter.extend(zip(run_rows, pieces))
    buf = np.empty(_PACK_CHUNK << 16, dtype=np.uint8)
    for lo in range(0, len(scatter), _PACK_CHUNK):
        chunk = scatter[lo:lo + _PACK_CHUNK]
        k = len(chunk)
        sizes = np.array([v.size for _, v in chunk], dtype=np.int64)
        flat = (np.repeat(np.arange(k, dtype=np.int64) << 16, sizes)
                + np.concatenate([v for _, v in chunk]))
        bits = buf[:k << 16]
        bits[:] = 0
        bits[flat] = 1
        packed = np.packbits(bits, bitorder="little").view(np.uint32)
        out[np.asarray([r for r, _ in chunk])] = packed.reshape(k, WORDS32)
    return out


@dataclass
class PackedAggregation:
    """One wide-aggregation problem, rotated and densified."""

    keys: np.ndarray          # [K] distinct keys, sorted (u16 or u64 tier)
    words: np.ndarray         # u32[M_pad, 2048]; rows >= M are zero
    seg_ids: np.ndarray       # i32[M_pad]; padding rows get segment K (out of range)
    head_idx: np.ndarray      # i32[K] first row of each segment
    seg_sizes: np.ndarray     # i32[K]
    m: int                    # true row count
    max_group: int            # largest segment size

    @property
    def num_keys(self) -> int:
        return int(self.keys.size)


def pack_for_aggregation(bitmaps: list[RoaringBitmap],
                         pad_rows: bool = True) -> PackedAggregation:
    """Rotate + densify N bitmaps for a wide OR/XOR (ragged segments)."""
    all_keys = [b.keys for b in bitmaps]
    flat_keys = np.concatenate(all_keys) if all_keys else np.empty(0, np.uint16)
    order = np.argsort(flat_keys, kind="stable")
    keys, seg_of_row = np.unique(flat_keys, return_inverse=True)
    m = flat_keys.size

    conts = [c for b in bitmaps for c in b.containers]
    m_pad = next_pow2(m) if pad_rows else m
    words = densify_containers([conts[s] for s in order], np.arange(m), m_pad)

    seg_ids = np.full(m_pad, keys.size, dtype=np.int32)
    seg_ids[:m] = seg_of_row[order]
    head_idx = np.searchsorted(seg_ids[:m], np.arange(keys.size)).astype(np.int32)
    seg_sizes = np.diff(np.append(head_idx, m)).astype(np.int32)
    # keys keep the input dtype: u16 for 32-bit bitmaps, u64 high-48 keys for
    # the longlong tier (core.bitmap64) — the kernels only see seg_ids.
    return PackedAggregation(
        keys=keys, words=words, seg_ids=seg_ids,
        head_idx=head_idx, seg_sizes=seg_sizes, m=m,
        max_group=int(seg_sizes.max()) if keys.size else 0)


def blocked_block_count(bitmaps: list, block: int = 8) -> int:
    """Block count pack_blocked_compact would produce — cheap (key counts
    only), so engine selection can test the SMEM ceiling before building
    any stream."""
    flat_keys = np.concatenate([_keys_of(b) for b in bitmaps])
    _, counts = np.unique(flat_keys, return_counts=True)
    return int((-(-counts // block)).sum())


# ------------------------------------------------------- stream (byte) ingest
#
# The buffer package's real capability (SURVEY §2.2): aggregate straight off
# the serialized layout without materializing per-container heap objects
# (buffer/ImmutableRoaringArray.java:166-194, BufferFastAggregation.java:187).
# Here the serialized stream splits into two transfer-minimal device streams:
#   - dense containers (bitmap + large-run) ship their 8 KB wire image as-is,
#   - sparse containers (array + small-run) ship raw u16 member values.
# The dense [rows, 2048] image is then built ON DEVICE by ops.dense.
# densify_streams (scatter-add of per-value bit contributions — collision-free
# because (row, word, bit) triples are unique), so host packing never touches
# an 8 KB row for sparse data and the host->HBM transfer is ~serialized size.

#: Run containers above this cardinality ship as dense wire images instead of
#: expanded value streams (break-even: 4096 u16 values = one 8 KB dense row).
RUN_DENSIFY_THRESHOLD = ARRAY_MAX_SIZE


@dataclass
class CompactStreams:
    """Transfer-minimal ingest form of a rotated container batch."""

    n_rows: int               # dense image row count (excluding scratch row)
    dense_words: np.ndarray   # u32[Md, 2048] wire images (bitmap / big-run)
    dense_dest: np.ndarray    # i32[Md] destination rows
    values: np.ndarray        # u16[V] concat member values (array / small-run)
    val_counts: np.ndarray    # i32[Mv] values per sparse container
    val_dest: np.ndarray      # i32[Mv] destination row per sparse container

    @property
    def total_values(self) -> int:
        return int(self.values.size)

    def transfer_bytes(self) -> int:
        return (self.dense_words.nbytes + self.dense_dest.nbytes
                + self.values.nbytes + self.val_counts.nbytes
                + self.val_dest.nbytes)


def _keys_of(b) -> np.ndarray:
    """Container key array of any bitmap-like input (object, immutable view,
    or raw serialized bytes) without materializing containers."""
    v = _as_view(b)
    return b.keys if v is None else v.keys


def _as_view(b):
    """SerializedView of ``b`` when it is byte-backed, else None."""
    from ..format import spec

    if isinstance(b, (bytes, bytearray, memoryview)):
        return spec.SerializedView(b)
    if isinstance(b, spec.SerializedView):
        return b
    view = getattr(b, "_view", None)
    if isinstance(view, spec.SerializedView):
        return view
    return None


def _emit_container_streams(sources: list, order: np.ndarray, dest: np.ndarray,
                            n_rows: int) -> CompactStreams:
    """Classify every container of the rotated batch into the dense / sparse
    stream, in ``order`` (rows sorted by segment), destinations ``dest``."""
    from ..core import containers as C

    # flat (source index, container index) in input order
    sizes = [ _keys_of(s).size for s in sources ]
    src_of = np.repeat(np.arange(len(sources)), sizes)
    idx_in_src = np.concatenate([np.arange(k) for k in sizes]) if sizes \
        else np.empty(0, np.int64)

    from ..format.spec import InvalidRoaringFormat, validate_runs

    dense_rows: list[int] = []
    dense_words: list[np.ndarray] = []
    pieces: list[np.ndarray] = []       # sparse per-container value arrays
    val_dest: list[int] = []
    views = [_as_view(s) for s in sources]
    for pos, row in zip(order, np.asarray(dest, dtype=np.int64)):
        s, i = int(src_of[pos]), int(idx_in_src[pos])
        view = views[s]
        if view is not None:
            # byte path: same corruption guards the eager SerializedView.
            # container() applies, minus the bitmap popcount (O(8 KB)/row on
            # the ingest hot path; a wrong declared bitmap cardinality cannot
            # shift the stream — payloads are fixed 8 KB — and every device
            # aggregate recomputes cardinalities exactly anyway)
            payload = view.container_payload(i)
            if view.is_bitmap[i]:
                if len(payload) != 8192:
                    raise InvalidRoaringFormat(
                        f"container {i}: truncated bitmap payload")
                dense_rows.append(row)
                dense_words.append(np.frombuffer(payload, dtype="<u4"))
                continue
            if view.is_run[i]:
                nruns = int(np.frombuffer(payload[:2], dtype="<u2")[0])
                runs = np.frombuffer(payload[2:2 + 4 * nruns], dtype="<u2")
                if runs.size != 2 * nruns:
                    raise InvalidRoaringFormat(
                        f"container {i}: truncated run payload")
                # shared structural checks (sorted, non-overlapping, within
                # the 2^16 chunk — else runs_to_values' uint16 wrap would
                # corrupt low values); spec.validate_runs is the one
                # definition both decode paths use
                starts, ends = validate_runs(runs, i)
                if int((ends - starts + 1).sum()) != int(view.cardinalities[i]):
                    raise InvalidRoaringFormat(
                        f"container {i}: run cardinality mismatch")
                vals = C.runs_to_values(runs.astype(np.uint16))
            else:
                vals = np.frombuffer(payload, dtype="<u2")
                if vals.size > 1 and bool(np.any(vals[1:] <= vals[:-1])):
                    raise InvalidRoaringFormat(
                        f"container {i}: array values not strictly increasing")
        else:
            c = sources[s].containers[i]
            if isinstance(c, C.BitmapContainer):
                dense_rows.append(row)
                dense_words.append(container_words_u32(c))
                continue
            vals = c.values() if not isinstance(c, C.RunContainer) \
                else C.runs_to_values(c.runs)
        if vals.size > RUN_DENSIFY_THRESHOLD:
            # dense is the smaller wire form past 4096 values
            dense_rows.append(row)
            dense_words.append(C.values_to_words(vals).view(np.uint32))
        elif vals.size:
            pieces.append(vals)
            val_dest.append(row)
    values = (np.ascontiguousarray(np.concatenate(pieces)).astype(np.uint16)
              if pieces else np.empty(0, np.uint16))
    return CompactStreams(
        n_rows=n_rows,
        dense_words=(np.stack(dense_words).astype(np.uint32) if dense_words
                     else np.empty((0, WORDS32), np.uint32)),
        dense_dest=np.asarray(dense_rows, dtype=np.int32),
        values=values,
        val_counts=np.array([p.size for p in pieces], dtype=np.int32),
        val_dest=np.asarray(val_dest, dtype=np.int32))


def pad_streams_pow2(s: CompactStreams) -> CompactStreams:
    """Pad stream array lengths to powers of two so ad-hoc call sites stop
    recompiling once the workload shape stabilizes (same role as pack_for_
    aggregation's pow2 row padding).  Padding is absorbed by the densify
    scratch row (index n_rows): padded values carry value 0 under a sentinel
    count entry destined for the scratch row; padded dense rows are zero rows
    also destined there."""
    v, mv, md = s.values.size, s.val_counts.size, s.dense_words.shape[0]
    vpad, mvpad, mdpad = next_pow2(v), next_pow2(mv + 1), next_pow2(md)
    values = np.zeros(vpad, np.uint16)
    values[:v] = s.values
    val_counts = np.zeros(mvpad, np.int32)
    val_counts[:mv] = s.val_counts
    val_counts[mv] = vpad - v  # sentinel soaks up the value padding
    val_dest = np.full(mvpad, s.n_rows, np.int32)
    val_dest[:mv] = s.val_dest
    dense_words = np.zeros((mdpad, WORDS32), np.uint32)
    dense_words[:md] = s.dense_words
    dense_dest = np.full(mdpad, s.n_rows, np.int32)
    dense_dest[:md] = s.dense_dest
    return CompactStreams(n_rows=s.n_rows, dense_words=dense_words,
                          dense_dest=dense_dest, values=values,
                          val_counts=val_counts, val_dest=val_dest)


#: Values per densify chunk (ops.kernels.densify_chunks_pallas): one VPU
#: lane row.  Each chunk belongs to exactly one destination row, so padding
#: waste is bounded by (CHUNK_VALUES - 1) values per non-empty container.
CHUNK_VALUES = 128

#: Chunk-slot sentinel: any u32 > 0xFFFF is outside the 2^16-bit container
#: domain; the kernel masks its contribution to zero.  (In-chunk padding
#: does NOT use it — see chunk_value_stream.)
CHUNK_PAD = np.uint32(0xFFFFFFFF)


def chunk_value_stream(values: np.ndarray, val_counts: np.ndarray,
                       val_dest: np.ndarray, n_rows: int,
                       chunk: int = CHUNK_VALUES,
                       pad_chunks_pow2: bool = True
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Sparse value streams -> fixed-shape chunks for the Pallas densify
    kernel: (u32[NC, chunk] chunk values, i32[NC] chunk destination rows).

    Every chunk's values land in ONE destination row, so the kernel's
    output BlockSpec can route consecutive same-row chunks to one VMEM
    accumulator tile (the segmented-reduce mechanism).  All padding — a
    container's final partial chunk AND whole padding chunks (pow2 rounding
    of the chunk count, destination n_rows = the scratch row) — carries the
    CHUNK_PAD sentinel: the kernel accumulates per-word BYTE-PLANE SUMS on
    the MXU (exact only while every contributing value is distinct), so
    padding must contribute zero, not a duplicated value.  chunk
    destinations ascend whenever val_dest does (every packer emits it
    sorted).
    """
    counts = np.asarray(val_counts, dtype=np.int64)
    nz = counts > 0
    counts_nz = counts[nz]
    dest_nz = np.asarray(val_dest, dtype=np.int64)[nz]
    m = -(-counts_nz // chunk)                       # chunks per container
    nc = int(m.sum())
    nc_pad = max(next_pow2(nc), 1) if pad_chunks_pow2 else max(nc, 1)
    chunk_vals = np.full((nc_pad, chunk), CHUNK_PAD, dtype=np.uint32)
    chunk_row = np.full(nc_pad, n_rows, dtype=np.int32)
    if nc:
        cont_of = np.repeat(np.arange(counts_nz.size), m)
        chunk_head = np.concatenate(([0], np.cumsum(m)[:-1]))
        within = np.arange(nc) - chunk_head[cont_of]
        starts = np.concatenate(([0], np.cumsum(counts_nz)[:-1]))
        base = starts[cont_of] + within * chunk
        idx = base[:, None] + np.arange(chunk)
        last = (starts + counts_nz - 1)[cont_of][:, None]
        cv = np.asarray(values, dtype=np.uint32)[np.minimum(idx, last)]
        cv[idx > last] = CHUNK_PAD  # partial-chunk slots must contribute 0
        chunk_vals[:nc] = cv
        chunk_row[:nc] = dest_nz[cont_of]
    return chunk_vals, chunk_row


@dataclass
class PackedBlockedCompact:
    """Blocked-layout metadata + compact transfer streams (no host densify)."""

    keys: np.ndarray         # [K] distinct keys, sorted
    blk_seg: np.ndarray      # i32[n_rows/block]; padding blocks get segment K
    block: int
    n_blocks: int            # true block count
    seg_sizes: np.ndarray    # i64[K] true rows per segment
    seg_offsets: np.ndarray  # i64[K] first (padded) row of each segment
    streams: CompactStreams
    carry_row: int           # a padding row of segment 0 (loop-carry slot)
    row_src: np.ndarray = None  # i32[n_rows] source-bitmap index per row
    #                             (-1 for padding rows) — the batch engine's
    #                             query-subset selector (parallel.batch_engine)

    @property
    def n_rows(self) -> int:
        return int(self.blk_seg.size) * self.block


def choose_block(seg_sizes: np.ndarray, min_block: int = 8) -> int:
    """Per-set Pallas block size: larger blocks amortize grid-step overhead
    (wikileaks-noquotes chained marginal ~2x faster at 32 vs 16; census1881
    ~3x faster at 16-32 vs 8) but pad every segment to a block multiple, so
    the ladder climbs only while the median segment keeps padding waste
    small.  Always a power of two times NIBBLE_GROUP (the blocked kernels
    tree-reduce statically; the counts/compact layouts tile 8-row groups).

    min_block=4 opens a downward rung for DENSE-layout sets whose median
    segment is tiny (the uscensus2000 shape: ~4,800 mostly-singleton
    containers — block 8 pads every 1-row segment 8x, inflating the image
    the kernel must stream; see docs/USCENSUS2000_CLIFF.md).  The counts/
    compact fused layouts keep min_block=8: their group tiling needs
    NIBBLE_GROUP (8) to divide the block."""
    if seg_sizes.size == 0:
        return max(min_block, 8) if min_block >= 8 else 8
    med = float(np.median(seg_sizes))
    if med >= 32:
        return 32
    if med >= 16:
        return 16
    if med >= 4 or min_block >= 8:
        return 8
    return 4


def pack_blocked_compact(sources: list, block: int | None = None,
                         round_blocks: int = 8,
                         carry_slot: bool = True,
                         min_block: int = 8) -> PackedBlockedCompact:
    """Group-by-key rotation emitting compact streams instead of a host-built
    dense tensor.  ``sources`` may mix RoaringBitmaps, ImmutableRoaringBitmaps,
    SerializedViews, and raw serialized bytes.

    carry_slot guarantees segment 0 has at least one zero padding row, used by
    DeviceBitmapSet.chained_wide_or as the loop-carried write-back slot.
    round_blocks pads the block count to a multiple (NOT pow2 — a resident set
    compiles for one shape, so tight padding wins back HBM).
    min_block (see choose_block) lets dense-layout residents drop to block 4
    on ultra-sparse key-heavy shapes.
    """
    if block is None and min_block < 8 and sources:
        # the downward rung must bind BEFORE the native fast path (the C++
        # engine's internal ladder stops at 8); key counts are cheap to read
        # off any source kind
        _, counts = np.unique(
            np.concatenate([_keys_of(s) for s in sources]),
            return_counts=True)
        block = choose_block(counts, min_block=min_block)
    # native fast path: pure-bytes 32-bit inputs go through the C++ ingest
    # engine (roaringbitmap_tpu.native) — same semantics, same hostile-input
    # guards, one pass over the wire bytes; falls back to this NumPy
    # implementation (the oracle) whenever unavailable
    if sources and all(isinstance(s, (bytes, bytearray)) for s in sources):
        from .. import native

        packed = native.pack_blocked_compact_native(
            [bytes(s) for s in sources], block, round_blocks, carry_slot)
        if packed is not None:
            if packed.row_src is None:
                packed.row_src = _row_sources(packed, sources)
            return packed

    # parse byte-backed sources ONCE; _as_view is idempotent on views
    sources = [v if (v := _as_view(s)) is not None else s for s in sources]
    all_keys = [_keys_of(s) for s in sources]
    flat_keys = (np.concatenate(all_keys) if all_keys
                 else np.empty(0, np.uint16))
    order = np.argsort(flat_keys, kind="stable")
    keys, seg_of_row = np.unique(flat_keys, return_inverse=True)
    m, k = flat_keys.size, keys.size
    seg_sorted = seg_of_row[order]
    head = np.searchsorted(seg_sorted, np.arange(k)).astype(np.int64)
    g = np.diff(np.append(head, m))
    if block is None:
        block = choose_block(g)
    gp = -(-g // block) * block
    if carry_slot and k and gp[0] == g[0]:
        gp[0] += block  # ensure a spare zero row in segment 0
    offs = np.concatenate(([0], np.cumsum(gp)))
    n_blocks = int(offs[-1]) // block
    nb_pad = -(-n_blocks // round_blocks) * round_blocks
    within = np.arange(m) - head[seg_sorted]
    dest = offs[seg_sorted] + within
    streams = _emit_container_streams(sources, order, dest, nb_pad * block)
    blk_seg = np.full(nb_pad, k, dtype=np.int32)
    blk_seg[:n_blocks] = np.repeat(np.arange(k, dtype=np.int32),
                                   (gp // block).astype(np.int64))
    row_src = np.full(nb_pad * block, -1, dtype=np.int32)
    row_src[dest] = np.repeat(np.arange(len(sources), dtype=np.int32),
                              [k_.size for k_ in all_keys])[order]
    return PackedBlockedCompact(
        keys=keys, blk_seg=blk_seg, block=block, n_blocks=n_blocks,
        seg_sizes=g, seg_offsets=offs[:-1], streams=streams,
        # without a reserved slot, g[0] may be a live row of segment 1 —
        # poison the field instead of pointing consumers at foreign data
        carry_row=int(g[0]) if (carry_slot and k) else -1,
        row_src=row_src)


def _row_sources(packed: PackedBlockedCompact, sources: list) -> np.ndarray:
    """i32[n_rows] source index per row of an already-packed blocked layout
    (-1 padding), rebuilt from key arrays alone.  Used for native-engine
    packs: the layout contract (rows sorted by segment, within a segment by
    source order — the stable-argsort rotation both engines implement)
    fully determines row placement from the per-source key sets."""
    all_keys = [_keys_of(v if (v := _as_view(s)) is not None else s)
                for s in sources]
    flat_keys = (np.concatenate(all_keys) if all_keys
                 else np.empty(0, np.uint16))
    order = np.argsort(flat_keys, kind="stable")
    seg_sorted = np.searchsorted(packed.keys, flat_keys[order])
    head = np.searchsorted(seg_sorted, np.arange(packed.keys.size))
    within = np.arange(flat_keys.size) - head[seg_sorted]
    dest = packed.seg_offsets[seg_sorted] + within
    row_src = np.full(packed.n_rows, -1, dtype=np.int32)
    row_src[dest] = np.repeat(np.arange(len(sources), dtype=np.int32),
                              [k.size for k in all_keys])[order]
    return row_src


def blocked_ragged_meta(blk_seg: np.ndarray, block: int, n_blocks: int,
                        num_keys: int):
    """Row-level ragged metadata of a blocked layout, for the XLA doubling
    engine: (seg_rows i32[rows], head_idx i32[K], n_steps).  Group sizes
    terminate at the TRUE row count so round_blocks padding rows (segment
    id K) never inflate the doubling-pass depth."""
    seg_rows = np.repeat(blk_seg, block).astype(np.int32)
    head_idx = np.searchsorted(seg_rows, np.arange(num_keys)).astype(np.int32)
    seg_sizes = np.diff(np.append(head_idx, n_blocks * block))
    from . import dense

    n_steps = dense.n_steps_for(int(seg_sizes.max()) if num_keys else 0)
    return seg_rows, head_idx, n_steps


@dataclass
class PackedIntersection:
    """Wide-AND problem: only keys present in every bitmap survive
    (FastAggregation.workShyAnd key-set intersection, FastAggregation.java:356-380),
    so the payload is a perfectly regular [K, N, 2048] block."""

    keys: np.ndarray    # [K] surviving keys (u16 or u64 tier)
    words: np.ndarray   # u32[K, N, 2048]


def _container_at(b, i: int):
    """One container of a bitmap-like source.  Byte-backed sources
    (ImmutableRoaringBitmap) wrap just this payload slice — a wide AND must
    not materialize the keys its intersection already eliminated
    (BufferFastAggregation's workShyAnd touches only surviving containers,
    buffer/BufferFastAggregation.java:699)."""
    get = getattr(b, "_container", None)
    return get(i) if get is not None else b.containers[i]


def pack_for_intersection(bitmaps: list[RoaringBitmap],
                          keys: np.ndarray) -> PackedIntersection:
    """keys is the precomputed surviving key set (every bitmap must hold a
    container for each — see parallel.aggregation._intersect_keys)."""
    n = len(bitmaps)
    conts, dest = [], []
    for j, b in enumerate(bitmaps):
        for i, bi in enumerate(np.searchsorted(b.keys, keys)):
            conts.append(_container_at(b, int(bi)))
            dest.append(i * n + j)
    words = densify_containers(conts, dest, keys.size * n)
    return PackedIntersection(keys=keys,
                              words=words.reshape(keys.size, n, WORDS32))


def key_presence_masks(bitmaps: list[RoaringBitmap]) -> np.ndarray:
    """u32[N, 2048] — 65,536-bit key presence mask per bitmap.

    The device form of workShyAnd's 1024-long key bitset
    (FastAggregation.java:359-363): key-set intersection of N bitmaps is one
    vectorized AND-reduce over this tensor.
    """
    n = len(bitmaps)
    masks = np.zeros((n, WORDS32), dtype=np.uint32)
    for i, b in enumerate(bitmaps):
        k = b.keys.astype(np.int64)
        np.bitwise_or.at(masks[i], k >> 5, np.uint32(1) << (k & 31).astype(np.uint32))
    return masks


@dataclass
class PackedPairwiseCompact:
    """P bitmap pairs aligned on per-pair key unions, as compact transfer
    streams for the batched pairwise kernel (ops.dense.pairwise — XLA's
    multi-output fusion, the single pairwise engine).  Zero rows are the
    identity for or/xor/andnot and annihilate correctly for and, so one
    union alignment serves all ops.

    Like pack_blocked_compact, the host never builds an 8 KB dense row for
    sparse data: both operand sides ship as CompactStreams and the aligned
    u32[n_rows, 2048] images are built ON DEVICE by ops.dense.
    densify_streams — the fix for the round-3 pairwise e2e loss, where the
    host-side densify dominated pack time."""

    keys: np.ndarray          # [M] per-pair union keys, concatenated
    heads: np.ndarray         # i64[P+1] row bounds of each pair's segment
    m: int                    # true row count
    n_rows: int               # padded row count (>= m; padding rows zero)
    a_streams: CompactStreams
    b_streams: CompactStreams


def pack_pairwise(pairs, pad_rows: bool = True) -> PackedPairwiseCompact:
    """Align each pair's containers on its key union; emit one compact
    stream per side (device densify builds the aligned images).

    The batched-device form of the reference's per-pair key merge loop
    (RoaringBitmap.or two-pointer skeleton, RoaringBitmap.java:864-894).
    Pairs may mix RoaringBitmaps, ImmutableRoaringBitmaps, SerializedViews,
    and raw serialized bytes — byte-backed operands stream straight off the
    wire layout without materializing Container objects.
    """
    # native fast path: pure-bytes pairs go through the C++ ingest engine
    # (same semantics, same hostile-input guards); NumPy path = oracle +
    # fallback, RB_NATIVE=0 disables
    if pairs and all(isinstance(a, (bytes, bytearray))
                     and isinstance(b, (bytes, bytearray)) for a, b in pairs):
        from .. import native

        packed = native.pack_pairwise_native(
            [bytes(a) for a, _ in pairs], [bytes(b) for _, b in pairs],
            pad_rows)
        if packed is not None:
            return packed

    a_srcs = [v if (v := _as_view(a)) is not None else a for a, _ in pairs]
    b_srcs = [v if (v := _as_view(b)) is not None else b for _, b in pairs]
    a_keys = [_keys_of(s) for s in a_srcs]
    b_keys = [_keys_of(s) for s in b_srcs]
    key_sets = [np.union1d(ka, kb) for ka, kb in zip(a_keys, b_keys)]
    heads = np.concatenate(
        ([0], np.cumsum([k.size for k in key_sets]))).astype(np.int64)
    m = int(heads[-1])
    n_rows = next_pow2(m) if pad_rows else m

    def side(srcs, src_keys):
        if srcs:
            dest = np.concatenate(
                [heads[p] + np.searchsorted(key_sets[p], k)
                 for p, k in enumerate(src_keys)])
        else:
            dest = np.empty(0, np.int64)
        # containers already arrive in destination order per source; the
        # rotation argsort of the wide path is unnecessary here
        return _emit_container_streams(srcs, np.arange(dest.size), dest,
                                       n_rows)

    keys = (np.concatenate(key_sets) if key_sets
            else np.empty(0, np.uint16))
    return PackedPairwiseCompact(
        keys=keys, heads=heads, m=m, n_rows=n_rows,
        a_streams=side(a_srcs, a_keys), b_streams=side(b_srcs, b_keys))


def unpack_result(keys: np.ndarray, words: np.ndarray,
                  cards: np.ndarray, out_cls=None) -> RoaringBitmap:
    """Device dense result -> host bitmap (normalize by cardinality).

    out_cls selects the host class: RoaringBitmap (default, u16 keys) or
    core.bitmap64.Roaring64Bitmap (u64 high-48 keys) — both share the
    (keys, containers) structure-of-arrays constructor.
    """
    from ..core import containers as C

    if out_cls is None:
        if keys.dtype != np.uint16:
            # u64 high-48 keys: the 64-bit tier rides the same engines
            from ..core.bitmap64 import Roaring64Bitmap

            out_cls = Roaring64Bitmap
        else:
            out_cls = RoaringBitmap
    words = np.asarray(words, dtype=np.uint32)
    cards = np.asarray(cards)
    out_keys, out_conts = [], []
    for i in range(keys.size):
        card = int(cards[i])
        if card == 0:
            continue
        w64 = words[i].view(np.uint64)
        out_keys.append(keys[i])
        if card > C.ARRAY_MAX_SIZE:
            out_conts.append(C.BitmapContainer(w64.copy(), card))
        else:
            out_conts.append(C.ArrayContainer(C.words_to_values(w64)))
    return out_cls(np.array(out_keys, dtype=keys.dtype), out_conts)
