"""Host->device packing: the group-by-key rotation, vectorized.

The reference's ParallelAggregation.groupByKey (ParallelAggregation.java:136-152)
rotates N bitmaps into key -> List<Container> before the fork-join reduce.
Here the same rotation produces flat, fixed-shape tensors ready for HBM:

  words    u32[M, 2048]   every container densified to its 2^16-bit word image
  seg_ids  i32[M]         index into the distinct-key axis, sorted ascending
  head_idx i32[K]         first row of each segment
  keys     [K]            distinct container keys, sorted — u16 for the
                          32-bit tier, u64 high-48 keys for core.bitmap64

Densifying everything to words is what the reference's own wide paths do on
CPU anyway (FastAggregation.java:395-399 and ParallelAggregation.java:108,214
accumulate into dense BitmapContainers); on TPU it additionally buys fully
static shapes and a perfectly regular memory layout.

Rows are padded to a bucket size (next power of two) so recompiles stop once
the workload shape stabilizes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core.bitmap import RoaringBitmap
from ..core.containers import WORDS_PER_CONTAINER

WORDS32 = 2 * WORDS_PER_CONTAINER  # 2048 u32 words per container


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (int(n - 1).bit_length())


def container_words_u32(c) -> np.ndarray:
    """Dense u32[2048] image of one container (little-endian word split)."""
    return c.words().view(np.uint32)


def _expand_runs_batch(run_arrays: list[np.ndarray]) -> tuple[np.ndarray, np.ndarray]:
    """Batched run expansion: interleaved (start, len-1) u16 arrays ->
    (concatenated member values i64, per-container value counts i64).

    One cumsum over the whole run stream — the multi-container form of
    core.containers.runs_to_values' delta trick; no per-run Python loop.
    Every input array must be non-empty (empty run containers hold no bits;
    callers skip them).
    """
    starts = np.concatenate([r[0::2] for r in run_arrays]).astype(np.int64)
    lens = np.concatenate([r[1::2] for r in run_arrays]).astype(np.int64) + 1
    n_runs = np.array([r.size // 2 for r in run_arrays], dtype=np.int64)
    deltas = np.ones(int(lens.sum()), dtype=np.int64)
    ends = np.cumsum(lens)
    deltas[0] = starts[0]
    deltas[ends[:-1]] = starts[1:] - (starts[:-1] + lens[:-1] - 1)
    values = np.cumsum(deltas)
    run_heads = np.concatenate(([0], np.cumsum(n_runs)[:-1]))
    counts = np.add.reduceat(lens, run_heads)
    return values, counts


#: Containers per packbits scatter chunk.  Small on purpose: the scatter is
#: random-access within the bit buffer, so the buffer must stay cache-resident
#: (16 * 64 KiB = 1 MiB); measured 4x faster than a 256-container chunk.
_PACK_CHUNK = 16


def densify_containers(conts: list, dest, n_rows: int) -> np.ndarray:
    """Vectorized dense u32[n_rows, 2048] image of a container list.

    conts[i] lands in row dest[i]; remaining rows stay zero.  This is the
    whole-tensor construction SURVEY §7 hard part (a) calls for — the Python
    loop only does list bookkeeping, never data movement:

    - bitmap containers: one stacked fancy-index row assignment,
    - array containers: values scattered via one np.packbits pass per
      _PACK_CHUNK-container chunk,
    - run containers: batched delta-cumsum expansion, then the same scatter.
    """
    out = np.zeros((n_rows, WORDS32), dtype=np.uint32)
    if not conts:
        return out
    from ..core import containers as C

    dest = np.asarray(dest, dtype=np.int64)
    bm_rows: list[int] = []
    bm_words: list[np.ndarray] = []
    run_rows: list[int] = []
    run_arrays: list[np.ndarray] = []
    scatter: list[tuple[int, np.ndarray]] = []  # (row, member values)
    for r, c in zip(dest, conts):
        if isinstance(c, C.BitmapContainer):
            bm_rows.append(r)
            bm_words.append(c.words())
        elif isinstance(c, C.RunContainer):
            if c.runs.size:  # empty run container: row stays zero
                run_rows.append(r)
                run_arrays.append(c.runs)
        else:
            scatter.append((r, c.values()))
    if bm_rows:
        out[np.asarray(bm_rows)] = np.stack(bm_words).view(np.uint32)
    if run_arrays:
        values, counts = _expand_runs_batch(run_arrays)
        pieces = np.split(values, np.cumsum(counts)[:-1])
        scatter.extend(zip(run_rows, pieces))
    buf = np.empty(_PACK_CHUNK << 16, dtype=np.uint8)
    for lo in range(0, len(scatter), _PACK_CHUNK):
        chunk = scatter[lo:lo + _PACK_CHUNK]
        k = len(chunk)
        sizes = np.array([v.size for _, v in chunk], dtype=np.int64)
        flat = (np.repeat(np.arange(k, dtype=np.int64) << 16, sizes)
                + np.concatenate([v for _, v in chunk]))
        bits = buf[:k << 16]
        bits[:] = 0
        bits[flat] = 1
        packed = np.packbits(bits, bitorder="little").view(np.uint32)
        out[np.asarray([r for r, _ in chunk])] = packed.reshape(k, WORDS32)
    return out


@dataclass
class PackedAggregation:
    """One wide-aggregation problem, rotated and densified."""

    keys: np.ndarray          # [K] distinct keys, sorted (u16 or u64 tier)
    words: np.ndarray         # u32[M_pad, 2048]; rows >= M are zero
    seg_ids: np.ndarray       # i32[M_pad]; padding rows get segment K (out of range)
    head_idx: np.ndarray      # i32[K] first row of each segment
    seg_sizes: np.ndarray     # i32[K]
    m: int                    # true row count
    max_group: int            # largest segment size

    @property
    def num_keys(self) -> int:
        return int(self.keys.size)


def pack_for_aggregation(bitmaps: list[RoaringBitmap],
                         pad_rows: bool = True) -> PackedAggregation:
    """Rotate + densify N bitmaps for a wide OR/XOR (ragged segments)."""
    all_keys = [b.keys for b in bitmaps]
    flat_keys = np.concatenate(all_keys) if all_keys else np.empty(0, np.uint16)
    order = np.argsort(flat_keys, kind="stable")
    keys, seg_of_row = np.unique(flat_keys, return_inverse=True)
    m = flat_keys.size

    conts = [c for b in bitmaps for c in b.containers]
    m_pad = next_pow2(m) if pad_rows else m
    words = densify_containers([conts[s] for s in order], np.arange(m), m_pad)

    seg_ids = np.full(m_pad, keys.size, dtype=np.int32)
    seg_ids[:m] = seg_of_row[order]
    head_idx = np.searchsorted(seg_ids[:m], np.arange(keys.size)).astype(np.int32)
    seg_sizes = np.diff(np.append(head_idx, m)).astype(np.int32)
    # keys keep the input dtype: u16 for 32-bit bitmaps, u64 high-48 keys for
    # the longlong tier (core.bitmap64) — the kernels only see seg_ids.
    return PackedAggregation(
        keys=keys, words=words, seg_ids=seg_ids,
        head_idx=head_idx, seg_sizes=seg_sizes, m=m,
        max_group=int(seg_sizes.max()) if keys.size else 0)


@dataclass
class PackedBlocked:
    """Segment-padded layout for the blocked Pallas reduce: every segment's
    rows are padded with zero rows (the OR/XOR identity) to a multiple of
    `block`, so each grid step reduces `block` same-segment rows in VMEM."""

    keys: np.ndarray      # [K] distinct keys, sorted
    words: np.ndarray     # u32[Mb_pad, 2048]
    blk_seg: np.ndarray   # i32[Mb_pad/block]; padding blocks get segment K
    block: int
    n_blocks: int         # true block count
    seg_sizes: np.ndarray    # i64[K] true rows per segment
    seg_offsets: np.ndarray  # i64[K] first (padded) row of each segment


def blocked_block_count(bitmaps: list[RoaringBitmap], block: int = 8) -> int:
    """Block count pack_blocked would produce — cheap (key counts only), so
    engine selection can test the SMEM ceiling before densifying anything."""
    flat_keys = np.concatenate([b.keys for b in bitmaps])
    _, counts = np.unique(flat_keys, return_counts=True)
    return int((-(-counts // block)).sum())


def pack_blocked(bitmaps: list[RoaringBitmap], block: int = 8) -> PackedBlocked:
    """Group-by-key rotation with per-segment zero padding (OR/XOR only)."""
    flat_keys = np.concatenate([b.keys for b in bitmaps])
    order = np.argsort(flat_keys, kind="stable")
    keys, seg_of_row = np.unique(flat_keys, return_inverse=True)
    m, k = flat_keys.size, keys.size
    seg_sorted = seg_of_row[order]
    head = np.searchsorted(seg_sorted, np.arange(k)).astype(np.int64)
    g = np.diff(np.append(head, m))
    gp = -(-g // block) * block
    offs = np.concatenate(([0], np.cumsum(gp)))
    n_blocks = int(offs[-1]) // block
    nb_pad = next_pow2(n_blocks)
    within = np.arange(m) - head[seg_sorted]
    dest = offs[seg_sorted] + within
    conts = [c for b in bitmaps for c in b.containers]
    words = densify_containers([conts[s] for s in order], dest,
                               nb_pad * block)
    blk_seg = np.full(nb_pad, k, dtype=np.int32)
    blk_seg[:n_blocks] = np.repeat(np.arange(k, dtype=np.int32),
                                   (gp // block).astype(np.int64))
    return PackedBlocked(keys=keys, words=words, blk_seg=blk_seg,
                         block=block, n_blocks=n_blocks,
                         seg_sizes=g, seg_offsets=offs[:-1])


@dataclass
class PackedIntersection:
    """Wide-AND problem: only keys present in every bitmap survive
    (FastAggregation.workShyAnd key-set intersection, FastAggregation.java:356-380),
    so the payload is a perfectly regular [K, N, 2048] block."""

    keys: np.ndarray    # [K] surviving keys (u16 or u64 tier)
    words: np.ndarray   # u32[K, N, 2048]


def pack_for_intersection(bitmaps: list[RoaringBitmap],
                          keys: np.ndarray) -> PackedIntersection:
    """keys is the precomputed surviving key set (every bitmap must hold a
    container for each — see parallel.aggregation._intersect_keys)."""
    n = len(bitmaps)
    conts, dest = [], []
    for j, b in enumerate(bitmaps):
        for i, bi in enumerate(np.searchsorted(b.keys, keys)):
            conts.append(b.containers[bi])
            dest.append(i * n + j)
    words = densify_containers(conts, dest, keys.size * n)
    return PackedIntersection(keys=keys,
                              words=words.reshape(keys.size, n, WORDS32))


def key_presence_masks(bitmaps: list[RoaringBitmap]) -> np.ndarray:
    """u32[N, 2048] — 65,536-bit key presence mask per bitmap.

    The device form of workShyAnd's 1024-long key bitset
    (FastAggregation.java:359-363): key-set intersection of N bitmaps is one
    vectorized AND-reduce over this tensor.
    """
    n = len(bitmaps)
    masks = np.zeros((n, WORDS32), dtype=np.uint32)
    for i, b in enumerate(bitmaps):
        k = b.keys.astype(np.int64)
        np.bitwise_or.at(masks[i], k >> 5, np.uint32(1) << (k & 31).astype(np.uint32))
    return masks


@dataclass
class PackedPairwise:
    """P bitmap pairs aligned on per-pair key unions for the batched
    pairwise kernels (ops.kernels.pairwise_popcount_pallas /
    ops.dense.pairwise).  Zero rows are the identity for or/xor/andnot and
    annihilate correctly for and, so one union alignment serves all ops."""

    keys: np.ndarray      # [M] per-pair union keys, concatenated
    a_words: np.ndarray   # u32[M, 2048]
    b_words: np.ndarray   # u32[M, 2048]
    heads: np.ndarray     # i64[P+1] row bounds of each pair's segment


def pack_pairwise(pairs: list[tuple[RoaringBitmap, RoaringBitmap]]
                  ) -> PackedPairwise:
    """Align each pair's containers on its key union; one densify per side.

    The batched-device form of the reference's per-pair key merge loop
    (RoaringBitmap.or two-pointer skeleton, RoaringBitmap.java:864-894).
    """
    key_sets = [np.union1d(a.keys, b.keys) for a, b in pairs]
    heads = np.concatenate(
        ([0], np.cumsum([k.size for k in key_sets]))).astype(np.int64)
    m = int(heads[-1])
    a_conts, a_dest, b_conts, b_dest = [], [], [], []
    for p, (a, b) in enumerate(pairs):
        ku, base = key_sets[p], heads[p]
        a_conts.extend(a.containers)
        a_dest.extend(base + np.searchsorted(ku, a.keys))
        b_conts.extend(b.containers)
        b_dest.extend(base + np.searchsorted(ku, b.keys))
    keys = (np.concatenate(key_sets) if key_sets
            else np.empty(0, np.uint16))
    return PackedPairwise(
        keys=keys,
        a_words=densify_containers(a_conts, a_dest, m),
        b_words=densify_containers(b_conts, b_dest, m),
        heads=heads)


def unpack_result(keys: np.ndarray, words: np.ndarray,
                  cards: np.ndarray, out_cls=None) -> RoaringBitmap:
    """Device dense result -> host bitmap (normalize by cardinality).

    out_cls selects the host class: RoaringBitmap (default, u16 keys) or
    core.bitmap64.Roaring64Bitmap (u64 high-48 keys) — both share the
    (keys, containers) structure-of-arrays constructor.
    """
    from ..core import containers as C

    if out_cls is None:
        out_cls = RoaringBitmap
    words = np.asarray(words, dtype=np.uint32)
    cards = np.asarray(cards)
    out_keys, out_conts = [], []
    for i in range(keys.size):
        card = int(cards[i])
        if card == 0:
            continue
        w64 = words[i].view(np.uint64)
        out_keys.append(keys[i])
        if card > C.ARRAY_MAX_SIZE:
            out_conts.append(C.BitmapContainer(w64.copy(), card))
        else:
            out_conts.append(C.ArrayContainer(C.words_to_values(w64)))
    return out_cls(np.array(out_keys, dtype=keys.dtype), out_conts)
