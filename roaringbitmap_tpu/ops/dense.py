"""Dense device kernels (jnp/XLA tier): word algebra, popcount, segment reduce.

This is the XLA-composable reference tier for every device op; the Pallas
tier (roaringbitmap_tpu.ops.kernels) provides fused fast paths for the hot
ones and is checked against these in tests.

Containers live on device as u32[..., 2048] word tensors (2^16 bits per
container, u32 because TPUs have no native 64-bit integer lanes).  The ops
here replace the reference's word-loop kernels in Util.java (e.g.
cardinalityInBitmapRange :415, fillArrayAND :300) and the lazy-or/repair
machinery (BitmapContainer.lazyor :878-909, Container.repairAfterLazy :869):
on TPU the "repair" popcount is just a fused reduction after the bitwise op.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

WORDS32 = 2048

#: Single source of truth for the bitwise op vocabulary (kernels.py and
#: parallel/sharding.py dispatch through this table too).
OPS = {
    "or": jnp.bitwise_or,
    "and": jnp.bitwise_and,
    "xor": jnp.bitwise_xor,
    "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
}
_OPS = OPS


def popcount(words: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Set-bit count along an axis of u32 words -> int32 cardinalities."""
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32), axis=axis)


@functools.partial(jax.jit, static_argnames=("op",))
def pairwise(op: str, a: jnp.ndarray, b: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Batched pairwise container op with fused cardinality.

    a, b: u32[K, 2048] aligned container payloads -> (u32[K, 2048], i32[K]).
    The device analog of the 9-way container dispatch (Container.java:63-181)
    collapsed to one uniform word kernel.
    """
    out = _OPS[op](a, b)
    return out, popcount(out)


def doubling_pass(fn, words: jnp.ndarray, seg_ids: jnp.ndarray,
                  n_steps: int) -> jnp.ndarray:
    """Parallel-doubling segmented scan: after the pass, row i holds the
    reduction of rows [i, i + 2^n_steps) of its own segment; segment head
    rows therefore hold the full per-segment reduction once
    n_steps >= ceil(log2(max segment size)).  seg_ids must be sorted."""
    m = words.shape[0]
    d = 1
    for _ in range(n_steps):
        if d >= m:
            break
        shifted = jnp.concatenate(
            [words[d:], jnp.zeros((d, words.shape[1]), words.dtype)])
        same = jnp.concatenate(
            [seg_ids[d:] == seg_ids[:-d], jnp.zeros((d,), dtype=bool)])
        words = jnp.where(same[:, None], fn(words, shifted), words)
        d *= 2
    return words


@functools.partial(jax.jit, static_argnames=("op", "n_steps"))
def segmented_reduce(op: str, words: jnp.ndarray, seg_ids: jnp.ndarray,
                     head_idx: jnp.ndarray, n_steps: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Ragged per-key reduction by parallel doubling over sorted segments.

    words u32[M, 2048], seg_ids i32[M] sorted.  This is the device
    replacement for ParallelAggregation's per-key fork-join reduce
    (ParallelAggregation.java:160-222): O(M log G) word ops, no data-dependent
    control flow, shapes static under jit.

    Returns (u32[K, 2048] per-key words, i32[K] per-key cardinalities).
    """
    heads = doubling_pass(OPS[op], words, seg_ids, n_steps)[head_idx]
    return heads, popcount(heads)


@jax.jit
def regular_reduce_and(words: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Wide AND over a regular block u32[K, N, 2048] (post key-intersection).

    workShyAnd's per-key iand chain (FastAggregation.java:393-411) as one
    reduction; the all-ones temporary disappears because the block is regular.
    """
    out = jax.lax.reduce(words, jnp.uint32(0xFFFFFFFF),
                         jax.lax.bitwise_and, (1,))
    return out, popcount(out)


@jax.jit
def range_cardinality(words: jnp.ndarray, start: jnp.ndarray,
                      stop: jnp.ndarray) -> jnp.ndarray:
    """Popcount of bits [start, stop) inside a u32[2048] container image.

    Util.cardinalityInBitmapRange (Util.java:415) without the word loop:
    build the range mask with vectorized clamped shifts.
    """
    idx = jnp.arange(WORDS32, dtype=jnp.int32)
    word_lo = idx * 32
    lo = jnp.clip(start - word_lo, 0, 32)
    hi = jnp.clip(stop - word_lo, 0, 32)
    n = (hi - lo).astype(jnp.uint32)
    mask = jnp.where(n >= 32, jnp.uint32(0xFFFFFFFF),
                     ((jnp.uint32(1) << n) - 1) << lo.astype(jnp.uint32))
    return popcount(words & mask, axis=-1)


def n_steps_for(max_group: int) -> int:
    return max(1, int(max(1, max_group - 1)).bit_length())


def densify_streams_impl(dense_words, dense_dest, values, val_counts, val_dest,
                         n_rows: int, total_values: int) -> jnp.ndarray:
    """Build the dense u32[n_rows, 2048] container image from compact streams
    (ops.packing.CompactStreams) on device.

    Sparse containers arrive as raw u16 member values; each value contributes
    one bit at flat position row*2048 + (v>>5).  A scatter-ADD is exact here:
    (row, word, bit) triples are unique (values are unique within a container
    and containers own distinct rows), so sums never carry across bits.  This
    replaces the host-side packbits scatter of densify_containers for device
    ingest — the host ships ~serialized-size bytes instead of 8 KB per
    container (the ImmutableRoaringArray zero-copy ingest seam,
    buffer/ImmutableRoaringArray.java:166-194, rebuilt device-side).

    One scratch row (index n_rows) absorbs sentinel-padded stream entries.
    Traceable (no jit here) so callers can inline it inside larger programs.
    """
    flat = jnp.zeros(((n_rows + 1) * WORDS32,), jnp.uint32)
    if total_values:
        rows = jnp.repeat(val_dest.astype(jnp.int32), val_counts,
                          total_repeat_length=total_values)
        v = values.astype(jnp.int32)
        g = rows * WORDS32 + (v >> 5)
        bits = jnp.uint32(1) << (v & 31).astype(jnp.uint32)
        flat = flat.at[g].add(bits, unique_indices=False)
    out = flat.reshape(n_rows + 1, WORDS32)
    if dense_words.shape[0]:
        out = out.at[dense_dest.astype(jnp.int32)].set(dense_words)
    return out[:n_rows]


@functools.partial(jax.jit, static_argnames=("n_rows", "total_values"))
def densify_streams(dense_words, dense_dest, values, val_counts, val_dest,
                    n_rows: int, total_values: int) -> jnp.ndarray:
    return densify_streams_impl(dense_words, dense_dest, values, val_counts,
                                val_dest, n_rows, total_values)


# ----------------------------------------------- fused compact-layout reduce
#
# The round-3 compact layout paid a full materialize-then-reduce round trip
# per query: scatter the M x 2048 dense image to HBM, then read it all back
# in the segmented reduce (~3x the HBM traffic of the dense-resident path).
# The fused form never materializes rows.  Sparse values scatter-add 4-bit
# OCCURRENCE COUNTS per bit position, grouped by NIBBLE_GROUP-row windows:
# within one container values are unique, and a group holds at most
# NIBBLE_GROUP containers, so every nibble stays < 16 — the scatter-add is
# carry-free and therefore exact.  Counts are half the size of the rows they
# replace (4 bits/bit vs 8 KB/row over 8 rows), and the count -> bit
# conversion (OR: nibble != 0, XOR: nibble parity) fuses into the Pallas
# segmented accumulator (ops.kernels.fused_nibble_reduce), so the only HBM
# traffic is one counts write + one counts read.

#: Rows per nibble-count group.  Must divide the blocked layout's block size
#: and stay below 16 so per-bit occurrence counts fit a nibble carry-free.
NIBBLE_GROUP = 8
#: u32 count words per group: 2^16 bit positions x 4 bits = 4 x 2048 words,
#: laid out plane-major (plane j holds bits [8j, 8j+8) of every word) so the
#: kernel's byte recombine is elementwise across planes.
NIBBLE_WORDS = 4 * WORDS32


def nibble_counts_impl(values, val_counts, val_dest, n_groups: int,
                       total_values: int) -> jnp.ndarray:
    """Sparse streams -> u32[n_groups + 1, NIBBLE_WORDS] occurrence counts.

    Value v of destination row r contributes count 1 to group r >> 3, plane
    (v >> 3) & 3, word v >> 5, nibble v & 7.  The trailing group absorbs
    sentinel-padded entries (val_dest == n_rows, n_rows a NIBBLE_GROUP
    multiple).  Traceable; callers inline it inside chained loops.
    """
    flat = jnp.zeros(((n_groups + 1) * NIBBLE_WORDS,), jnp.uint32)
    if total_values:
        rows = jnp.repeat(val_dest.astype(jnp.int32), val_counts,
                          total_repeat_length=total_values)
        v = values.astype(jnp.int32)
        g = ((rows >> 3) * NIBBLE_WORDS + ((v >> 3) & 3) * WORDS32
             + (v >> 5))
        nib = jnp.uint32(1) << (4 * (v & 7)).astype(jnp.uint32)
        flat = flat.at[g].add(nib, unique_indices=False)
    return flat.reshape(n_groups + 1, NIBBLE_WORDS)


def spread_bits_to_nibbles(words: jnp.ndarray) -> jnp.ndarray:
    """u32[..., 2048] bit image -> u32[..., 4, 2048] plane-major nibble
    counts (each set bit becomes count 1; the exact inverse of the fused
    kernel's SWAR compress).  Used to fold dense-wire rows into a resident
    counts tensor at build time."""
    planes = []
    for j in range(4):
        b = (words >> (8 * j)) & jnp.uint32(0xFF)
        s = (b | (b << 12)) & jnp.uint32(0x000F000F)
        s = (s | (s << 6)) & jnp.uint32(0x03030303)
        s = (s | (s << 3)) & jnp.uint32(0x11111111)
        planes.append(s)
    return jnp.stack(planes, axis=-2)


def counts_tile_to_word(c: jnp.ndarray, op: str) -> jnp.ndarray:
    """Plane-axis-0 nibble counts u32[4, ...] -> bit words u32[...]
    (OR: bit = count != 0; XOR: bit = count odd, i.e. the nibble's LSB).

    THE single SWAR conversion, shared by the Pallas kernels (on (4, 16,
    128) VMEM tiles) and the XLA reference path counts_to_words — one
    definition so the engines cannot silently diverge.
    """
    if op == "or":
        t = c | (c >> 1)
        t = t | (t >> 2)
        m = t & jnp.uint32(0x11111111)
    else:  # xor
        m = c & jnp.uint32(0x11111111)
    # compress the 8 nibble flags (bits 0,4,..,28) into the low byte
    v = (m | (m >> 3)) & jnp.uint32(0x03030303)
    w = (v | (v >> 6)) & jnp.uint32(0x000F000F)
    r = (w | (w >> 12)) & jnp.uint32(0xFF)
    return r[0] | (r[1] << 8) | (r[2] << 16) | (r[3] << 24)


def counts_to_words(counts: jnp.ndarray, op: str) -> jnp.ndarray:
    """u32[..., 4, 2048] plane-major nibble counts -> u32[..., 2048] words
    — the XLA-engine path over a counts-resident layout and the parity
    oracle for the Pallas counts kernels."""
    return counts_tile_to_word(jnp.moveaxis(counts, -2, 0), op)


@functools.partial(jax.jit, static_argnames=("n_groups", "total_values"))
def build_group_counts(dense_words, dense_dest, values, val_counts, val_dest,
                       n_groups: int, total_values: int) -> jnp.ndarray:
    """One-time build of a counts-resident layout: sparse values scatter
    their nibble counts, dense-wire rows fold in via the bit->nibble
    spread.  u32[n_groups + 1, NIBBLE_WORDS]; exact (each row contributes
    at most one occurrence per bit, <= NIBBLE_GROUP rows per group).

    This runs ONCE per set: the value scatter costs milliseconds at ~10^6
    values (XLA lowers scatter-add to a serial update loop on TPU — the
    same cost class as the dense layout's one-time densify), which is
    precisely why the per-query layouts must not re-run it.
    """
    counts = nibble_counts_impl(values, val_counts, val_dest, n_groups,
                                total_values)
    if dense_words.shape[0]:
        spread = spread_bits_to_nibbles(dense_words)
        g = (dense_dest.astype(jnp.int32) >> 3)
        counts = (counts.reshape(n_groups + 1, 4, WORDS32)
                  .at[g].add(spread)
                  .reshape(n_groups + 1, 4 * WORDS32))
    return counts


def dense_partial_impl(op: str, dense_words, dseg, head_idx, head_valid,
                       n_steps: int, num_segments: int) -> jnp.ndarray:
    """Per-segment reduction of the dense-wire rows only:
    u32[Md, 2048] (+ sorted i32[Md] segment ids) -> u32[K + 1, 2048].

    Segments with no dense rows get zero rows (head_valid False); the
    trailing row is the scratch segment's.  Traceable.
    """
    if dense_words.shape[0] == 0:
        return jnp.zeros((num_segments + 1, WORDS32), jnp.uint32)
    red = doubling_pass(OPS[op], dense_words, dseg, n_steps)
    safe = jnp.minimum(head_idx, dense_words.shape[0] - 1)
    return jnp.where(head_valid[:, None], red[safe], jnp.uint32(0))
