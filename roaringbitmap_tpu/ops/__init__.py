from . import dense, kernels, megakernel, packing

__all__ = ["dense", "kernels", "megakernel", "packing"]
