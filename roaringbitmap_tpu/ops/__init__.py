from . import dense, kernels, packing

__all__ = ["dense", "kernels", "packing"]
