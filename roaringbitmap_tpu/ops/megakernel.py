"""One-kernel hot path: a Pallas persistent megakernel for the fused
expression pipeline (ROADMAP item 3).

The PR 8 fused path still lowers to gather -> segmented reduce ->
key-aligned combine passes as SEPARATE XLA ops: every stage round-trips
its ``u32[K, 2048]`` blocks through HBM, which is exactly the
intermediate-materialization cost the Roaring lazy/horizontal-aggregation
argument says to avoid (PAPERS.md §1 — ``lazyor``/``repairAfterLazy``
keep the accumulator hot and repair once at the end).  This module is
the kernel-level analog: the WHOLE per-bucket expression pipeline — the
operand gathers, every segmented reduce, the interior or/and/xor/andnot
combine passes (alignment masking included), and the root popcount /
bitmap outputs — executes as ONE ``pallas_call`` whose per-segment and
per-node intermediates live in a VMEM scratch accumulator and never
touch HBM.

Execution model
---------------
The plan-time **assembler** (:func:`build_full` / :func:`build_combines`)
flattens a bucketed batch plan plus its compiled expression sections
(parallel.expr.ExprSection) into a static instruction stream — one grid
step per instruction, seven scalar-prefetched i32 arrays (opcode /
dst-slot / src-slot / row / bank / out-row / card-row).  The kernel body
is a 15-way ``lax.select_n`` over bitwise micro-ops against a
``u32[S, 16, 128]`` VMEM scratch (``pltpu.VMEM`` — never flushed to
HBM):

- **row ops** stream one operand row per step straight from the resident
  image via the input BlockSpec's prefetched index map (``row[i]``) —
  the gather never materializes an HBM copy;
- **reduce** = LOAD_ROW for a segment's first row + OP_ROW for the rest
  (the host assembler walks only REAL rows, so padding work and the
  ``is_head`` recomputation of the multi-op kernels disappear, and the
  AND identity/workShyAnd masking folds into plan-time ZEROs);
- **combine** = slot-to-slot bitwise ops; key-UNaligned children resolve
  through plan-time index arrays into per-key slot/row sources, with
  absent keys constant-folded to the op identity (skip for or/xor,
  ZERO for and) — the ``force_heads_sig`` machinery of the multi-op
  path folds into the kernel body: expr-feeding reduce heads simply
  stay VMEM slots;
- **outputs**: OUT flushes a slot's 8 KiB row to HBM only for
  bitmap-form results; CARD writes a 512 B per-lane popcount partial
  per key — the cardinality-only short circuit costs 16x less output
  than a row, and nothing else leaves the chip.

Three banks feed row ops: bank 0 is the resident (or pooled) row image,
bank 1 ships ad-hoc leaf rows (and, in combine-only mode, the
pre-gathered leaf rows), bank 2 is the **column operand bank** — the
attached analytics columns' slice planes and existence rows, flattened
in section/column-slot order.  ``mode="combine"``
(:func:`build_combines`) is the mesh composition: the sharded engine
keeps its shard-local reduce + ppermute butterfly and hands the
REPLICATED post-butterfly head tensors to the megakernel as bank 0, so
the interior combine passes fuse into one kernel on every device.

Megakernel v2 (analytics opcodes — ROADMAP item 2).  Fused
filter-then-aggregate expressions no longer demote: a ``vscan`` step
lowers to the O'Neil comparator as instruction-stream micro-ops
(:data:`VSCAN_HI` / :data:`VSCAN_LO` fuse one state update each, so
every slice costs exactly TWO steps per bound regardless of the
predicate's bit value — predicate VALUES select opcodes, never step
counts, so one compiled program serves every predicate at a given
shape, the property the sealed lattice's "steady state compiles
nothing" contract needs); a ``vagg`` step lowers sum to
:data:`VAGG_CARD` masked-popcount partials (one step per (slice, key))
and top-k to the branch-free Kaser scan (:data:`ACC_POP` popcount
accumulation + :data:`TAKE` broadcasting the per-slice take decision
against the ``imm`` operand).  Both mirror ``bsi.device`` word for
word, so the one-kernel rung stays bit-exact against the host oracle.

Budget math (docs/EXPRESSIONS.md "Megakernel lowering"): the scratch
holds ``n_slots`` 8 KiB rows in VMEM (:data:`MAX_SLOTS` bounds it) and
the instruction stream prefetches into SMEM (:data:`MAX_STEPS`); a plan
past either bound reports ``fits() == False`` and the engines demote to
the multi-op pallas rung — counted on
``rb_mega_capacity_demotions_total{reason}`` plus a
``mega.capacity_demotion`` trace event (:func:`note_capacity_demotion`;
capacity demotions are never silent) — the existing pallas -> xla
ladder is the safety net below that.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import packing

WORDS32 = packing.WORDS32
_SUB, _LANE = 16, 128

#: bytes of one accumulator slot (u32[16, 128] = one container row)
SLOT_BYTES = _SUB * _LANE * 4

#: VMEM accumulator ceiling: slots past this demote to the multi-op
#: pallas rung (8 MiB of a ~16 MiB/core VMEM, leaving room for the
#: streamed operand blocks and the double-buffered output windows)
MAX_SLOTS = (8 << 20) // SLOT_BYTES

#: instruction-stream ceiling: 7 i32 arrays prefetch into SMEM, so the
#: stream is bounded well under the segmented kernels' loose
#: SMEM_PREFETCH_MAX (7 * 4 B * 2^14 ≈ 448 KiB of SMEM)
MAX_STEPS = 1 << 14

# --------------------------------------------------------------- opcodes
#
# Every step reads acc[dst] (cur), acc[src] (srcv) and the banked row,
# computes one value and writes it back to acc[dst]; OUT/CARD steps point
# dst at the dead slot and route srcv to the output/card row their
# prefetched orow/crow arrays select.  NOP-like steps are absorbed by
# the dead slot / dead rows, so padding the stream to a pow2 costs
# nothing but grid steps.
#
# The v2 analytics opcodes keep the same one-read-one-write discipline:
# VSCAN_HI/VSCAN_LO fuse one O'Neil comparator state update each
# (``lt |= eq & ~w`` / ``gt |= eq & w`` — bsi.device.oneil_scan's two
# conditional accumulations), VAGG_CARD routes ``popcount(srcv & row)``
# to a card row (the sum_ per-(slice, key) partial — a dead-slot write
# on the accumulator side), ACC_POP accumulates per-word popcounts into
# a counter slot and TAKE broadcasts the Kaser take decision
# (``sum(counter) < imm``) as an all-ones/zero mask slot.

(NOP, LOAD_ROW, OR_ROW, AND_ROW, XOR_ROW, ANDNOT_ROW_REV, ZERO,
 COPY_SLOT, OR_SLOT, AND_SLOT, XOR_SLOT, ANDNOT_SLOT, ANDNOT_ROW,
 OUT, CARD, VSCAN_HI, VSCAN_LO, VAGG_CARD, ACC_POP, TAKE) = range(20)

#: opcodes whose accumulator write is the dead slot (their payload
#: leaves through the out/card rows instead)
_DEAD_DST = (OUT, CARD, VAGG_CARD)

_OP_ROW = {"or": OR_ROW, "and": AND_ROW, "xor": XOR_ROW}
_OP_SLOT = {"or": OR_SLOT, "and": AND_SLOT, "xor": XOR_SLOT}


def _kernel(opc_ref, dst_ref, src_ref, row_ref, bank_ref, orow_ref,
            crow_ref, imm_ref, wa_ref, wb_ref, wc_ref, out_ref,
            card_ref, acc_ref):
    i = pl.program_id(0)
    opc = opc_ref[i]
    dst = dst_ref[i]
    src = src_ref[i]
    row = jax.lax.select_n(bank_ref[i], wa_ref[0], wb_ref[0], wc_ref[0])
    cur = acc_ref[dst]
    srcv = acc_ref[src]
    pop = jax.lax.population_count(srcv)
    take = jnp.where(
        jnp.sum(srcv.astype(jnp.int32)) < imm_ref[i],
        jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    acc_ref[dst] = jax.lax.select_n(
        opc,
        cur,                    # NOP
        row,                    # LOAD_ROW
        cur | row,              # OR_ROW
        cur & row,              # AND_ROW
        cur ^ row,              # XOR_ROW
        row & ~cur,             # ANDNOT_ROW_REV (head & ~rest-union)
        jnp.zeros_like(cur),    # ZERO
        srcv,                   # COPY_SLOT
        cur | srcv,             # OR_SLOT
        cur & srcv,             # AND_SLOT
        cur ^ srcv,             # XOR_SLOT
        cur & ~srcv,            # ANDNOT_SLOT
        cur & ~row,             # ANDNOT_ROW
        cur,                    # OUT (dead-slot write)
        cur,                    # CARD (dead-slot write)
        cur | (srcv & ~row),    # VSCAN_HI (lt |= eq & ~w)
        cur | (srcv & row),     # VSCAN_LO (gt |= eq & w)
        cur,                    # VAGG_CARD (dead-slot write)
        cur + pop,              # ACC_POP (per-word popcount partials)
        jnp.zeros_like(cur) | take,     # TAKE (broadcast take mask)
    )
    # unconditional output writes: non-OUT/CARD steps land on the dead
    # out/card row their index maps select, real steps carry acc[src];
    # VAGG_CARD's card payload is the masked partial popcount(srcv & w)
    cval = jnp.where(opc == VAGG_CARD, srcv & row, srcv)
    out_ref[0] = srcv
    card_ref[0] = jnp.sum(
        jax.lax.population_count(cval).astype(jnp.int32), axis=0)


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


class _Emitter:
    """Instruction-stream builder: one append per micro-op, pow2-padded
    into the eight prefetch arrays at finish()."""

    def __init__(self):
        self.ops: list = []  # (opc, dst, src, row, bank, orow, crow, imm)

    def emit(self, opc, dst=0, src=0, row=0, bank=0, orow=None,
             crow=None, imm=0):
        self.ops.append((opc, dst, src, row, bank, orow, crow, imm))

    def finish(self, n_slots: int, out_pad: int, card_pad: int) -> dict:
        n = max(1, len(self.ops))
        n_pad = packing.next_pow2(n)
        host = {
            "opc": np.zeros(n_pad, np.int32),
            "dst": np.full(n_pad, n_slots, np.int32),
            "src": np.zeros(n_pad, np.int32),
            "row": np.zeros(n_pad, np.int32),
            "bank": np.zeros(n_pad, np.int32),
            "orow": np.full(n_pad, out_pad, np.int32),
            "crow": np.full(n_pad, card_pad, np.int32),
            "imm": np.zeros(n_pad, np.int32),
        }
        for i, (opc, dst, src, row, bank, orow, crow, imm) in enumerate(
                self.ops):
            host["opc"][i] = opc
            host["dst"][i] = dst if opc not in _DEAD_DST else n_slots
            host["src"][i] = src
            host["row"][i] = row
            host["bank"][i] = bank
            host["imm"][i] = imm
            if orow is not None:
                host["orow"][i] = orow
            if crow is not None:
                host["crow"][i] = crow
        return host


@dataclasses.dataclass
class MegaPlan:
    """One assembled megakernel program: the instruction stream (host
    NumPy, device twins uploaded lazily — the multiset donate path
    re-uploads fresh per launch like every other operand dict), the
    static kernel shape, and the output-layout metadata the traced
    wrappers slice the HBM outputs back through."""

    mode: str                 # "full" | "combine"
    n_steps: int              # real instruction count (pre-pad)
    steps_pad: int
    n_slots: int              # real accumulator slots (pre-pad)
    slots_pad: int
    out_pad: int              # pow2-padded OUT rows (0 = none)
    card_pad: int
    host: dict | None         # instr arrays + "extra" (bank-1 rows) +
    #                           "leafidx" (combine mode bank-1 gather)
    arrays: dict | None = None
    #: per bucket: (card_base, out_base | None, n_real, k_pad)
    bucket_out: tuple = ()
    #: per fused section: (card_base, out_base | None, k_root,
    #: agg_layout) — agg_layout is None for standard roots,
    #: ("sum", S, K, K_found) for weighted-popcount contractions (the
    #: card rows carry the i32[S, K] partials then the K_found found
    #: cards), ("topk",) for Kaser-scan roots (standard heads+cards
    #: rows, heads always materialized)
    expr_out: tuple = ()
    #: combine mode: heads-bank row base per op group (-1 = group
    #: produces no heads and is never referenced)
    group_base: tuple = ()
    #: static bank-1 row count (survives the host drop — part of the
    #: program-shape signature)
    extra_rows: int = 1
    leaf_rows: int = 0
    #: static bank-2 row count (column slice planes + existence rows)
    col_rows: int = 0
    #: analytics IR-step counts (observability: expr.megakernel event)
    n_vscan: int = 0
    n_vagg: int = 0

    @property
    def signature(self) -> tuple:
        return (self.mode, self.steps_pad, self.slots_pad, self.out_pad,
                self.card_pad, self.extra_rows, self.leaf_rows,
                self.col_rows, self.bucket_out, self.expr_out)

    def fits(self) -> bool:
        return (self.slots_pad + 1 <= MAX_SLOTS
                and self.steps_pad <= MAX_STEPS)

    @property
    def vmem_bytes(self) -> int:
        return (self.slots_pad + 1) * SLOT_BYTES

    def stats_event(self) -> dict:
        """The ``expr.megakernel`` span-event payload
        (docs/OBSERVABILITY.md; tools/check_trace.py pins the schema)."""
        return {"mode": self.mode, "steps": int(self.n_steps),
                "slots": int(self.n_slots),
                "vmem_bytes": int(self.vmem_bytes),
                "out_rows": int(self.out_pad),
                "card_rows": int(self.card_pad),
                "sections": len(self.expr_out),
                "vscan_steps": int(self.n_vscan),
                "vagg_steps": int(self.n_vagg),
                "col_rows": int(self.col_rows)}

    def device_arrays(self, fresh: bool = False) -> dict:
        if fresh:
            if self.host is None:
                raise RuntimeError(
                    "fresh=True needs the host instruction stream, which "
                    "this plan dropped after its cached upload")
            return {k: jnp.asarray(v) for k, v in self.host.items()}
        if self.arrays is None:
            self.arrays = {k: jnp.asarray(v) for k, v in self.host.items()}
        return self.arrays


def capacity_reason(mega: MegaPlan) -> str | None:
    """Which budget a non-fitting plan blew: "slots" (VMEM accumulator)
    or "steps" (SMEM instruction stream); None when the plan fits."""
    if mega.slots_pad + 1 > MAX_SLOTS:
        return "slots"
    if mega.steps_pad > MAX_STEPS:
        return "steps"
    return None


def note_capacity_demotion(site: str, mega: MegaPlan) -> None:
    """Count + trace a capacity demotion (a plan that assembled but
    resolves below the megakernel rung because ``fits()`` failed) —
    ``rb_mega_capacity_demotions_total{reason}`` plus a tagged
    ``mega.capacity_demotion`` span event, so the silent fall-through
    the PR 11 ladder allowed is always visible."""
    from ..obs import metrics as obs_metrics
    from ..obs import trace as obs_trace
    reason = capacity_reason(mega) or "unknown"
    obs_metrics.counter("rb_mega_capacity_demotions_total",
                        site=site, reason=reason).inc()
    obs_trace.current().event(
        "mega.capacity_demotion", site=site, reason=reason,
        steps=int(mega.steps_pad), slots=int(mega.slots_pad),
        vmem_bytes=int(mega.vmem_bytes))


# ------------------------------------------------------------- assembler

def _emit_bucket(em: _Emitter, b, base: int, card_base: int,
                 out_base) -> None:
    """One shape bucket's whole pipeline: per-(query, key) segmented
    reduce over REAL rows only, plan-time masking (heads_ok / workShyAnd
    key_keep / andnot head pass), per-slot popcount partials, and OUT
    rows when the bucket's own needs_words demands them."""
    host = b.host
    n_real, k_pad = len(b.qids), b.k_pad
    red = OR_ROW if b.op in ("or", "andnot") else _OP_ROW[b.op]
    for qi in range(n_real):
        valid = host["valid"][qi]
        rows = host["gather"][qi][valid]
        segs = host["seg_local"][qi][valid]
        for k in range(k_pad):
            slot = base + qi * k_pad + k
            ok = bool(host["heads_ok"][qi, k])
            if b.op == "and" and not bool(host["key_keep"][qi, k]):
                ok = False
            seg_rows = rows[segs == k] if ok else rows[:0]
            if b.op == "andnot":
                if not bool(host["head_ok"][qi, k]):
                    em.emit(ZERO, dst=slot)
                elif seg_rows.size == 0:
                    # no rest rows: head & ~0 == the head row itself
                    em.emit(LOAD_ROW, dst=slot,
                            row=int(host["head_gather"][qi, k]))
                else:
                    em.emit(LOAD_ROW, dst=slot, row=int(seg_rows[0]))
                    for r in seg_rows[1:]:
                        em.emit(OR_ROW, dst=slot, row=int(r))
                    em.emit(ANDNOT_ROW_REV, dst=slot,
                            row=int(host["head_gather"][qi, k]))
            elif not ok or seg_rows.size == 0:
                em.emit(ZERO, dst=slot)
            else:
                em.emit(LOAD_ROW, dst=slot, row=int(seg_rows[0]))
                for r in seg_rows[1:]:
                    em.emit(red, dst=slot, row=int(r))
    for qi in range(n_real):
        for k in range(k_pad):
            slot = base + qi * k_pad + k
            em.emit(CARD, src=slot, crow=card_base + qi * k_pad + k)
            if out_base is not None:
                em.emit(OUT, src=slot, orow=out_base + qi * k_pad + k)


class _SectionCtx:
    """Per-section assembly state: maps compiled steps to (slot | row)
    sources for each of the node's keys."""

    def __init__(self, sec, slot_of_reduce, extra_base, leaf_row,
                 col_base=None):
        self.sec = sec
        self.slot_of_reduce = slot_of_reduce
        self.extra_base = extra_base
        self.leaf_row = leaf_row
        self.combine_base: dict = {}
        #: col slot -> (bank-2 row base, depth_pad, K) for this section
        self.col_base: dict = col_base or {}
        #: vscan step -> per-key source list (result slots, or bank-2
        #: existence rows for the "col:all" short circuit)
        self.vscan_src: dict = {}

    def ebm_row(self, ci_col: int, j: int) -> int:
        base, s, k = self.col_base[ci_col]
        return base + s * k + j

    def slice_row(self, ci_col: int, s_i: int, j: int) -> int:
        base, _s, k = self.col_base[ci_col]
        return base + s_i * k + j

    def source(self, ci: int, j: int):
        """("slot", s) | ("row", bank, r) for step ``ci``'s key ``j``."""
        st = self.sec.steps[ci]
        kind = st[0]
        if kind == "leaf":
            bank, row = self.leaf_row(self.sec, ci, j)
            return ("row", bank, row)
        if kind == "adhoc":
            return ("row", 1, self.extra_base[ci] + j)
        if kind == "reduce":
            _, bi, slot, _kq = st
            return self.slot_of_reduce(bi, slot, j)
        if kind == "vscan":
            return self.vscan_src[ci][j]
        return ("slot", self.combine_base[ci] + j)


def _emit_combine(em: _Emitter, ctx: _SectionCtx, si: int) -> None:
    """One interior combine node: per key, resolve each child through
    the plan-time alignment arrays into a slot/row source, constant-fold
    absent keys to the op identity, and chain the bitwise micro-ops."""
    sec = ctx.sec
    _, op, children, kq = sec.steps[si]
    base = ctx.combine_base[si]
    host = sec.host
    for j in range(kq):
        dst = base + j
        parts = []
        for k, (ci, aligned) in enumerate(children):
            if aligned:
                jj, ok = j, True
            else:
                jj = int(host[f"i{si}_{k}"][j])
                ok = bool(host[f"o{si}_{k}"][j])
            parts.append((ok, ctx.source(ci, jj) if ok else None))
        if op == "andnot":
            # head is key-aligned by construction (node keys ARE its
            # keys); absent rest children contribute ~0 == all-ones
            _, head = parts[0]
            _emit_set(em, dst, head)
            for ok, srcp in parts[1:]:
                if ok:
                    _emit_op(em, dst, srcp, ANDNOT_SLOT, ANDNOT_ROW)
        elif op == "and":
            if not all(ok for ok, _ in parts):
                # an absent AND child annihilates the key (cannot
                # happen for intersection key spaces — kept as the
                # plan-time guard the traced path encodes as a mask)
                em.emit(ZERO, dst=dst)
                continue
            _emit_set(em, dst, parts[0][1])
            for _, srcp in parts[1:]:
                _emit_op(em, dst, srcp, AND_SLOT, AND_ROW)
        else:
            live = [srcp for ok, srcp in parts if ok]
            if not live:
                em.emit(ZERO, dst=dst)
                continue
            _emit_set(em, dst, live[0])
            s_op, r_op = (_OP_SLOT[op], _OP_ROW[op])
            for srcp in live[1:]:
                _emit_op(em, dst, srcp, s_op, r_op)


def _emit_set(em: _Emitter, dst: int, srcp) -> None:
    if srcp[0] == "slot":
        em.emit(COPY_SLOT, dst=dst, src=srcp[1])
    else:
        em.emit(LOAD_ROW, dst=dst, row=srcp[2], bank=srcp[1])


def _emit_op(em: _Emitter, dst: int, srcp, slot_op: int,
             row_op: int) -> None:
    if srcp[0] == "slot":
        em.emit(slot_op, dst=dst, src=srcp[1])
    else:
        em.emit(row_op, dst=dst, row=srcp[2], bank=srcp[1])


def _col_layout(sections) -> tuple:
    """Bank-2 row layout: per (section, col slot), the column's padded
    slice planes (``S * K`` rows, slice-major) followed by its ``K``
    existence rows — matching the trace-time ``_col_bank`` concat order
    exactly.  Returns ({(sid, ci): (base, S, K)}, total_rows)."""
    shapes: dict = {}
    for sid, sec in enumerate(sections):
        for st in sec.steps:
            if st[0] == "vscan":
                shapes[(sid, st[1])] = (int(st[3]), int(st[4]))
            elif st[0] == "vagg":
                shapes[(sid, st[4])] = (int(st[5]), int(st[6]))
    bases, off = {}, 0
    for key in sorted(shapes):
        s, k = shapes[key]
        bases[key] = (off, s, k)
        off += s * k + k
    return bases, off


def _emit_vscan(em: _Emitter, ctx: _SectionCtx, si: int,
                n_slots: int) -> int:
    """One value-predicate step as instruction-stream micro-ops: the
    descending O'Neil pass of bsi.device (oneil_scan / oneil_scan2),
    one (VSCAN_HI|VSCAN_LO, AND_ROW|ANDNOT_ROW) pair per (slice, key)
    per bound — the predicate's BITS select which opcode lands in each
    pair but never how many steps there are, so every predicate value
    at a given (tag, depth, K) shape shares one compiled program.
    Padded zero planes carry zero bits, so their pairs reduce to exact
    no-ops, matching the traced scan's pow2-closure property."""
    sec = ctx.sec
    _, ci, tag, depth, kq = sec.steps[si]
    kind, _, op = tag.partition(":")
    if op == "all":
        ctx.vscan_src[si] = [("row", 2, ctx.ebm_row(ci, j))
                             for j in range(kq)]
        return n_slots
    bits = np.asarray(sec.host[f"b{si}"])
    bits2 = np.asarray(sec.host[f"b2{si}"])
    scan2 = op in ("RANGE", "between")
    srcs: list = []
    for j in range(kq):
        erow = ctx.ebm_row(ci, j)
        if scan2:
            g1, e1, l2, e2 = range(n_slots, n_slots + 4)
            n_slots += 4
            em.emit(ZERO, dst=g1)
            em.emit(LOAD_ROW, dst=e1, row=erow, bank=2)
            em.emit(ZERO, dst=l2)
            em.emit(LOAD_ROW, dst=e2, row=erow, bank=2)
            for t in range(depth):
                w = ctx.slice_row(ci, depth - 1 - t, j)
                if int(bits[t]):
                    em.emit(NOP)
                    em.emit(AND_ROW, dst=e1, row=w, bank=2)
                else:
                    em.emit(VSCAN_LO, dst=g1, src=e1, row=w, bank=2)
                    em.emit(ANDNOT_ROW, dst=e1, row=w, bank=2)
                if int(bits2[t]):
                    em.emit(VSCAN_HI, dst=l2, src=e2, row=w, bank=2)
                    em.emit(AND_ROW, dst=e2, row=w, bank=2)
                else:
                    em.emit(NOP)
                    em.emit(ANDNOT_ROW, dst=e2, row=w, bank=2)
            # (gt1 | eq1) & (lt2 | eq2) — the found mask is the
            # existence plane every scan state already lives inside
            em.emit(OR_SLOT, dst=g1, src=e1)
            em.emit(OR_SLOT, dst=l2, src=e2)
            em.emit(AND_SLOT, dst=g1, src=l2)
            srcs.append(("slot", g1))
            continue
        gt, lt, eq = range(n_slots, n_slots + 3)
        n_slots += 3
        em.emit(ZERO, dst=gt)
        em.emit(ZERO, dst=lt)
        em.emit(LOAD_ROW, dst=eq, row=erow, bank=2)
        for t in range(depth):
            w = ctx.slice_row(ci, depth - 1 - t, j)
            if int(bits[t]):
                em.emit(VSCAN_HI, dst=lt, src=eq, row=w, bank=2)
                em.emit(AND_ROW, dst=eq, row=w, bank=2)
            else:
                em.emit(VSCAN_LO, dst=gt, src=eq, row=w, bank=2)
                em.emit(ANDNOT_ROW, dst=eq, row=w, bank=2)
        if op in ("EQ", "eq"):
            res = eq
        elif op in ("NEQ", "neq"):
            # ebm & ~eq — gt's slot is free to carry the complement
            em.emit(LOAD_ROW, dst=gt, row=erow, bank=2)
            em.emit(ANDNOT_SLOT, dst=gt, src=eq)
            res = gt
        elif op == "GT":
            res = gt
        elif op == "LT":
            res = lt
        elif op in ("LE", "lte"):
            em.emit(OR_SLOT, dst=lt, src=eq)
            res = lt
        elif op in ("GE", "gte"):
            em.emit(OR_SLOT, dst=gt, src=eq)
            res = gt
        else:
            raise ValueError(f"unknown scan tag {tag!r}")
        srcs.append(("slot", res))
    ctx.vscan_src[si] = srcs
    return n_slots


def _emit_vagg(em: _Emitter, ctx: _SectionCtx, si: int, n_slots: int,
               n_card: int, n_out: int) -> tuple:
    """One aggregate root as instruction-stream micro-ops.  ``sum``:
    align the found step onto the column keys (plan-time searchsorted
    masks, the combine discipline), then one VAGG_CARD per (slice, key)
    routes ``popcount(found & slice)`` partials to the card rows, plus
    the found step's own K_found cards (both halves of the traced
    eval_section sum pair — the 2^i weighting stays host-side).
    ``top_k``: the branch-free Kaser scan — per slice, candidate rows
    ``x = g | (e & w)``, an ACC_POP counter contraction, one TAKE
    broadcasting ``sum < k`` (k rides the imm operand: one program per
    shape, any k), and masked g/e updates ``g |= x & F``,
    ``e &= w ^ F``.  Returns (n_slots, n_card, n_out, expr_out entry)."""
    sec = ctx.sec
    _, akind, fi, aligned, ci, _depth, kq = sec.steps[si]
    host = sec.host
    base, s_depth, K = ctx.col_base[ci]
    k_found = int(sec.steps[fi][-1])
    idx = host.get(f"i{si}")
    okm = host.get(f"o{si}")
    # key-aligned found slots (ok-masked; NOT existence-masked — sum's
    # traced twin intersects with the slice planes only)
    fc = list(range(n_slots, n_slots + kq))
    n_slots += kq
    for k in range(kq):
        ok, jj = (True, k) if aligned else (bool(okm[k]), int(idx[k]))
        if ok:
            _emit_set(em, fc[k], ctx.source(fi, jj))
        else:
            em.emit(ZERO, dst=fc[k])
    if akind == "sum":
        cb = n_card
        for s_i in range(s_depth):
            for k in range(kq):
                em.emit(VAGG_CARD, src=fc[k],
                        row=ctx.slice_row(ci, s_i, k), bank=2,
                        crow=cb + s_i * kq + k)
        # the found set's own cards ride the same card block, computed
        # from the PRE-alignment value (eval_section's found_cards)
        tmp = n_slots
        n_slots += 1
        for j in range(k_found):
            srcp = ctx.source(fi, j)
            if srcp[0] == "slot":
                em.emit(CARD, src=srcp[1], crow=cb + s_depth * kq + j)
            else:
                _emit_set(em, tmp, srcp)
                em.emit(CARD, src=tmp, crow=cb + s_depth * kq + j)
        n_card += s_depth * kq + k_found
        return (n_slots, n_card, n_out,
                (cb, None, kq, ("sum", s_depth, kq, k_found)))
    # top_k: e starts as found ∩ existence, g empty
    kk = int(host[f"k{si}"])
    e = fc
    for k in range(kq):
        em.emit(AND_ROW, dst=e[k], row=ctx.ebm_row(ci, k), bank=2)
    g = list(range(n_slots, n_slots + kq))
    x = list(range(n_slots + kq, n_slots + 2 * kq))
    counter, flag, t2 = range(n_slots + 2 * kq, n_slots + 2 * kq + 3)
    n_slots += 2 * kq + 3
    for k in range(kq):
        em.emit(ZERO, dst=g[k])
    for s_i in range(s_depth - 1, -1, -1):      # descending slice pass
        for k in range(kq):
            w = ctx.slice_row(ci, s_i, k)
            em.emit(COPY_SLOT, dst=x[k], src=e[k])
            em.emit(AND_ROW, dst=x[k], row=w, bank=2)
            em.emit(OR_SLOT, dst=x[k], src=g[k])
        em.emit(ZERO, dst=counter)
        for k in range(kq):
            em.emit(ACC_POP, dst=counter, src=x[k])
        em.emit(TAKE, dst=flag, src=counter, imm=kk)
        for k in range(kq):
            # g' = where(take, x, g) == g | (x & F)  (g ⊆ x)
            em.emit(AND_SLOT, dst=x[k], src=flag)
            em.emit(OR_SLOT, dst=g[k], src=x[k])
        for k in range(kq):
            # e' = where(take, e & ~w, e & w) == e & (w ^ F)
            em.emit(COPY_SLOT, dst=t2, src=flag)
            em.emit(XOR_ROW, dst=t2, row=ctx.slice_row(ci, s_i, k),
                    bank=2)
            em.emit(AND_SLOT, dst=e[k], src=t2)
    cb, ob = n_card, n_out
    for k in range(kq):
        em.emit(OR_SLOT, dst=g[k], src=e[k])
        em.emit(CARD, src=g[k], crow=cb + k)
        em.emit(OUT, src=g[k], orow=ob + k)
    n_card += kq
    n_out += kq
    return n_slots, n_card, n_out, (cb, ob, kq, ("topk",))


def _pack_extra(sections) -> tuple:
    """Bank-1 rows: every ad-hoc leaf's container rows, concatenated;
    per-(section-id, step) base offsets for the assembler."""
    rows, bases = [], {}
    off = 0
    for sid, sec in enumerate(sections):
        for ci, st in enumerate(sec.steps):
            if st[0] == "adhoc":
                w = sec.host[f"w{ci}"]
                bases[(sid, ci)] = off
                rows.append(np.asarray(w, np.uint32))
                off += int(w.shape[0])
    if rows:
        return np.concatenate(rows, axis=0), bases
    return np.zeros((1, WORDS32), np.uint32), bases


def _assemble(mode: str, buckets, sections, slot_of_reduce, leaf_row,
              extra, extra_bases, emit_buckets: bool) -> MegaPlan:
    """Shared assembly tail of :func:`build_full` /
    :func:`build_combines`: allocate accumulator slots and output rows,
    walk buckets (full mode) then every section's combine steps in
    topological order, and close with the CARD/OUT output phase."""
    n_slots = 0
    bucket_base: list = []
    if emit_buckets:
        for b in buckets:
            bucket_base.append(n_slots)
            n_slots += len(b.qids) * b.k_pad
    n_card = n_out = 0
    bucket_out: list = []
    if emit_buckets:
        for b in buckets:
            ob = n_out if b.needs_words else None
            bucket_out.append((n_card, ob, len(b.qids), b.k_pad))
            n_card += len(b.qids) * b.k_pad
            if ob is not None:
                n_out += len(b.qids) * b.k_pad

    em = _Emitter()
    if emit_buckets:
        for b, base, (cb, ob, _n, _k) in zip(buckets, bucket_base,
                                             bucket_out):
            _emit_bucket(em, b, base, cb, ob)

    col_bases, col_rows = _col_layout(sections)
    n_vscan = n_vagg = 0
    ctxs: list = []
    for sid, sec in enumerate(sections):
        ctx = _SectionCtx(
            sec,
            slot_of_reduce=slot_of_reduce(bucket_base),
            extra_base={ci: extra_bases.get((sid, ci), 0)
                        for ci, st in enumerate(sec.steps)
                        if st[0] == "adhoc"},
            leaf_row=leaf_row,
            col_base={ci: v for (s, ci), v in col_bases.items()
                      if s == sid})
        for si, st in enumerate(sec.steps):
            if st[0] == "combine":
                ctx.combine_base[si] = n_slots
                n_slots += int(st[3])
        ctxs.append(ctx)
    for ctx in ctxs:
        for si, st in enumerate(ctx.sec.steps):
            if st[0] == "vscan":
                n_vscan += 1
                n_slots = _emit_vscan(em, ctx, si, n_slots)
            elif st[0] == "combine":
                _emit_combine(em, ctx, si)

    expr_out: list = []
    for ctx in ctxs:
        sec = ctx.sec
        root_st = sec.steps[sec.root]
        if root_st[0] == "vagg":
            n_vagg += 1
            n_slots, n_card, n_out, entry = _emit_vagg(
                em, ctx, sec.root, n_slots, n_card, n_out)
            expr_out.append(entry)
            continue
        k_root = int(sec.root_keys.size)
        root_srcs = [ctx.source(sec.root, j) for j in range(k_root)]
        if any(s[0] == "row" for s in root_srcs):
            # a combine that collapsed to its only live child (bare
            # leaf/ad-hoc root — or a reduce root in combine mode, where
            # reduce values are bank rows): give the root its own slots
            # so OUT/CARD have a slot source
            base = n_slots
            n_slots += k_root
            for j, s in enumerate(root_srcs):
                _emit_set(em, base + j, s)
            root_slots = [base + j for j in range(k_root)]
        else:
            root_slots = [s[1] for s in root_srcs]
        ob = n_out if sec.form == "bitmap" else None
        expr_out.append((n_card, ob, k_root, None))
        for j in range(k_root):
            em.emit(CARD, src=root_slots[j], crow=n_card + j)
            if ob is not None:
                em.emit(OUT, src=root_slots[j], orow=n_out + j)
        n_card += k_root
        if ob is not None:
            n_out += k_root

    slots_pad = packing.next_pow2(max(1, n_slots))
    out_pad = packing.next_pow2(n_out) if n_out else 0
    card_pad = packing.next_pow2(max(1, n_card))
    from ..runtime import lattice as rt_lattice

    n_real = len(em.ops)
    if rt_lattice.active() is not None:
        # the lattice snap, instruction-stream level (docs/LATTICE.md):
        # pow2 already bounds each dimension, but floor-quantizing the
        # small end too makes near-identical DAG variants share one
        # program shape — padding steps is pure NOPs against the dead
        # slot, padding slots is unread VMEM
        slots_pad = max(slots_pad, 4)
        card_pad = max(card_pad, 8)
        if out_pad:
            out_pad = max(out_pad, 8)
        while len(em.ops) < 16:
            em.emit(NOP)
    host = em.finish(slots_pad, out_pad, card_pad)
    host["extra"] = extra
    return MegaPlan(
        mode=mode, n_steps=n_real,
        steps_pad=int(host["opc"].shape[0]),
        n_slots=n_slots, slots_pad=slots_pad,
        out_pad=out_pad, card_pad=card_pad, host=host,
        bucket_out=tuple(bucket_out), expr_out=tuple(expr_out),
        extra_rows=int(extra.shape[0]), col_rows=int(col_rows),
        n_vscan=n_vscan, n_vagg=n_vagg)


def build_full(buckets, sections) -> MegaPlan:
    """Assemble the FULL pipeline megakernel for a bucketed plan with
    fused expression sections: every bucket's segmented reduce + post
    passes AND every section's combine/output steps in one instruction
    stream.  Bucket/section host arrays must still be alive (the
    engines call this at plan time, before the upload-and-drop
    discipline runs); row indices are whatever image space the plan
    gathers from (set-local for BatchEngine, pooled for the multiset
    planner — the assembler just copies them into the stream)."""
    fused = [s for s in sections if s.kind == "fused"]
    extra, extra_bases = _pack_extra(fused)

    def slot_of_reduce(bucket_base):
        def fn(bi, slot, j):
            return ("slot", bucket_base[bi] + slot * buckets[bi].k_pad + j)
        return fn

    def leaf_row(sec, ci, j):
        # full mode streams leaves straight from the row image (bank 0)
        return 0, int(sec.host[f"g{ci}"][j])

    return _assemble("full", buckets, fused, slot_of_reduce, leaf_row,
                     extra, extra_bases, emit_buckets=True)


def build_combines(buckets, op_groups, sections, expr_bis) -> MegaPlan:
    """Assemble the COMBINE-ONLY megakernel (the mesh composition):
    reduce-node values arrive as rows of the post-butterfly flat head
    bank (bank 0 — the padded ``q * (k_pad + 1)`` layout of
    ``expr.traced_bucket_heads``), resident leaves as pre-gathered rows
    and ad-hoc leaves as shipped rows (bank 1); only the combine steps
    and root outputs run in-kernel."""
    fused = [s for s in sections if s.kind == "fused"]
    extra, extra_bases = _pack_extra(fused)

    # bank-0 layout: concat of every head-PRODUCING group's flat tensor
    produces = [g.needs_words or any(bi in expr_bis
                                     for bi in g.bucket_idx)
                for g in op_groups]
    group_base, off = [], 0
    for g, p in zip(op_groups, produces):
        group_base.append(off if p else -1)
        if p:
            off += int(g.nseg)
    bucket_row0 = {}
    for g, gb in zip(op_groups, group_base):
        for bi, s0 in zip(g.bucket_idx, g.seg_offs):
            bucket_row0[bi] = (gb + s0) if gb >= 0 else -1

    def slot_of_reduce(_bucket_base):
        def fn(bi, slot, j):
            r0 = bucket_row0[bi]
            if r0 < 0:
                raise AssertionError(
                    f"expr-feeding bucket {bi} in a headless op group")
            return ("row", 0, r0 + slot * (buckets[bi].k_pad + 1) + j)
        return fn

    # bank-1 layout: pre-gathered leaf rows first, ad-hoc rows after
    leaf_parts, leaf_bases = [], {}
    off = 0
    for sid, sec in enumerate(fused):
        for ci, st in enumerate(sec.steps):
            if st[0] == "leaf":
                g = np.asarray(sec.host[f"g{ci}"], np.int64)
                leaf_bases[(sid, ci)] = off
                leaf_parts.append(g)
                off += int(g.size)
    leaf_idx = (np.concatenate(leaf_parts) if leaf_parts
                else np.zeros(0, np.int64)).astype(np.int32)
    n_leaf = int(leaf_idx.size)
    sec_id = {id(sec): sid for sid, sec in enumerate(fused)}

    def leaf_row(sec, ci, j):
        # combine mode pre-gathers leaves into bank 1, before the extras
        return 1, leaf_bases[(sec_id[id(sec)], ci)] + j

    # extra-bank rows sit AFTER the gathered leaf rows in bank 1
    extra_bases = {k: v + n_leaf for k, v in extra_bases.items()}
    mega = _assemble("combine", buckets, fused, slot_of_reduce, leaf_row,
                     extra, extra_bases, emit_buckets=False)
    mega.host["leafidx"] = leaf_idx
    mega.group_base = tuple(group_base)
    mega.leaf_rows = n_leaf
    return mega


# --------------------------------------------------------- traced eval

def _raw_call(mega: MegaPlan, bank_a, bank_b, bank_c, arrs):
    """The pallas_call: one sequential grid pass over the instruction
    stream.  Returns the raw padded (out, cards) buffers."""
    steps = int(arrs["opc"].shape[0])
    out_pad = max(1, mega.out_pad)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow,
                         imm: (jnp.where(bank[i] == 0, row[i], 0), 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow,
                         imm: (jnp.where(bank[i] == 1, row[i], 0), 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow,
                         imm: (jnp.where(bank[i] == 2, row[i], 0), 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow,
                         imm: (orow[i], 0, 0)),
            pl.BlockSpec((1, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow,
                         imm: (crow[i], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((mega.slots_pad + 1, _SUB, _LANE), jnp.uint32)],
    )
    out, cards = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((out_pad + 1, _SUB, _LANE), jnp.uint32),
            jax.ShapeDtypeStruct((mega.card_pad + 1, _LANE), jnp.int32),
        ],
        interpret=_use_interpret(),
    )(arrs["opc"], arrs["dst"], arrs["src"], arrs["row"], arrs["bank"],
      arrs["orow"], arrs["crow"], arrs["imm"],
      bank_a.reshape(-1, _SUB, _LANE), bank_b.reshape(-1, _SUB, _LANE),
      bank_c.reshape(-1, _SUB, _LANE))
    return out, cards


def _col_bank(mega: MegaPlan, cols_list):
    """Trace-time bank-2 build: every fused section's column operands —
    slice planes reshaped slice-major, existence rows after — in the
    exact (section, col slot) order :func:`_col_layout` laid bases out
    in.  Stays an operand (never a baked constant): the planes are the
    RESIDENT column arrays, shared across dispatches and versions."""
    parts = []
    for seccols in (cols_list or []):
        for slices, ebm in seccols:
            parts.append(slices.reshape(-1, WORDS32))
            parts.append(ebm.reshape(-1, WORDS32))
    if not parts:
        return jnp.zeros((1, WORDS32), jnp.uint32)
    bank = (parts[0] if len(parts) == 1
            else jnp.concatenate(parts, axis=0))
    return bank


def _call(mega: MegaPlan, bank_a, bank_b, bank_c, arrs, wrap=None):
    """One megakernel dispatch -> (out_rows u32[out_pad, 2048] | None,
    card_rows i32[card_pad, 128]).  ``wrap`` (the mesh composition)
    wraps the raw call — e.g. a fully-replicated ``shard_map`` so every
    device runs the whole kernel on its replica instead of letting the
    SPMD partitioner slice the grid."""
    fn = lambda a, b, c, r: _raw_call(mega, a, b, c, r)
    if wrap is not None:
        fn = wrap(fn)
    out, cards = fn(bank_a, bank_b, bank_c, arrs)
    out_rows = (out[:mega.out_pad].reshape(mega.out_pad, WORDS32)
                if mega.out_pad else None)
    return out_rows, cards[:mega.card_pad]


def _slice_outputs(mega: MegaPlan, out_rows, card_rows):
    """HBM outputs -> (per-bucket outs, per-section expr outs), the
    engines' run-fn contract: buckets get (heads|None, cards[n, k_pad]),
    fused sections get (heads|None, cards[K]); aggregate sections get
    their eval_section-shaped pair — sum: (i32[S, K] slice cards,
    i32[K_found] found cards), topk: (u32[K, W] words, i32[K] cards)."""
    cards = jnp.sum(card_rows, axis=1)
    outs = []
    for cb, ob, n, k_pad in mega.bucket_out:
        c = cards[cb:cb + n * k_pad].reshape(n, k_pad)
        h = (out_rows[ob:ob + n * k_pad].reshape(n, k_pad, WORDS32)
             if ob is not None else None)
        outs.append((h, c))
    expr_outs = []
    for cb, ob, k_root, agg in mega.expr_out:
        if agg is not None and agg[0] == "sum":
            _, s_depth, kq, k_found = agg
            slice_cards = cards[cb:cb + s_depth * kq].reshape(
                s_depth, kq)
            found_cards = cards[cb + s_depth * kq:
                                cb + s_depth * kq + k_found]
            expr_outs.append((slice_cards, found_cards))
            continue
        c = cards[cb:cb + k_root]
        h = out_rows[ob:ob + k_root] if ob is not None else None
        expr_outs.append((h, c))
    return outs, expr_outs


def eval_full(mega: MegaPlan, words, arrs, cols=None):
    """Traced FULL-mode evaluation: ``words`` is the resident (or
    pooled) row image the stream's bank-0 rows index, ``cols`` the
    per-fused-section column operands (``expr.launch_cols`` — bank 2);
    returns the ``(bucket_outs, expr_outs)`` pair the engines' fused
    run fns return."""
    out_rows, card_rows = _call(mega, words, arrs["extra"],
                                _col_bank(mega, cols), arrs)
    return _slice_outputs(mega, out_rows, card_rows)


def eval_combines(mega: MegaPlan, group_heads, pool_words, arrs,
                  wrap=None, cols=None):
    """Traced COMBINE-mode evaluation (the sharded engine's replicated
    post-butterfly side): bank 0 = the producing groups' flat head
    tensors, bank 1 = pre-gathered leaf rows + ad-hoc rows, bank 2 =
    the replicated column operands.  The leaf gather runs OUTSIDE the
    kernel (it may cross shards on a rows-sharded pool; ``wrap``'s
    replicated in_specs then hand every device the full banks).
    Returns the per-section expr outs only (bucket outputs stay with
    the group bodies)."""
    bank_a = [h for h, _ in group_heads if h is not None]
    bank_a = (jnp.concatenate(bank_a, axis=0) if bank_a
              else jnp.zeros((1, WORDS32), jnp.uint32))
    leaf_idx = arrs["leafidx"]
    parts = []
    if int(leaf_idx.shape[0]):
        parts.append(pool_words[leaf_idx])
    parts.append(arrs["extra"])
    bank_b = parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                              axis=0)
    kernel_arrs = {k: v for k, v in arrs.items() if k != "leafidx"}
    out_rows, card_rows = _call(mega, bank_a, bank_b,
                                _col_bank(mega, cols), kernel_arrs,
                                wrap=wrap)
    _outs, expr_outs = _slice_outputs(mega, out_rows, card_rows)
    return expr_outs
