"""One-kernel hot path: a Pallas persistent megakernel for the fused
expression pipeline (ROADMAP item 3).

The PR 8 fused path still lowers to gather -> segmented reduce ->
key-aligned combine passes as SEPARATE XLA ops: every stage round-trips
its ``u32[K, 2048]`` blocks through HBM, which is exactly the
intermediate-materialization cost the Roaring lazy/horizontal-aggregation
argument says to avoid (PAPERS.md §1 — ``lazyor``/``repairAfterLazy``
keep the accumulator hot and repair once at the end).  This module is
the kernel-level analog: the WHOLE per-bucket expression pipeline — the
operand gathers, every segmented reduce, the interior or/and/xor/andnot
combine passes (alignment masking included), and the root popcount /
bitmap outputs — executes as ONE ``pallas_call`` whose per-segment and
per-node intermediates live in a VMEM scratch accumulator and never
touch HBM.

Execution model
---------------
The plan-time **assembler** (:func:`build_full` / :func:`build_combines`)
flattens a bucketed batch plan plus its compiled expression sections
(parallel.expr.ExprSection) into a static instruction stream — one grid
step per instruction, seven scalar-prefetched i32 arrays (opcode /
dst-slot / src-slot / row / bank / out-row / card-row).  The kernel body
is a 15-way ``lax.select_n`` over bitwise micro-ops against a
``u32[S, 16, 128]`` VMEM scratch (``pltpu.VMEM`` — never flushed to
HBM):

- **row ops** stream one operand row per step straight from the resident
  image via the input BlockSpec's prefetched index map (``row[i]``) —
  the gather never materializes an HBM copy;
- **reduce** = LOAD_ROW for a segment's first row + OP_ROW for the rest
  (the host assembler walks only REAL rows, so padding work and the
  ``is_head`` recomputation of the multi-op kernels disappear, and the
  AND identity/workShyAnd masking folds into plan-time ZEROs);
- **combine** = slot-to-slot bitwise ops; key-UNaligned children resolve
  through plan-time index arrays into per-key slot/row sources, with
  absent keys constant-folded to the op identity (skip for or/xor,
  ZERO for and) — the ``force_heads_sig`` machinery of the multi-op
  path folds into the kernel body: expr-feeding reduce heads simply
  stay VMEM slots;
- **outputs**: OUT flushes a slot's 8 KiB row to HBM only for
  bitmap-form results; CARD writes a 512 B per-lane popcount partial
  per key — the cardinality-only short circuit costs 16x less output
  than a row, and nothing else leaves the chip.

Two banks feed row ops: bank 0 is the resident (or pooled) row image,
bank 1 ships ad-hoc leaf rows (and, in combine-only mode, the
pre-gathered leaf rows).  ``mode="combine"`` (:func:`build_combines`) is
the mesh composition: the sharded engine keeps its shard-local reduce +
ppermute butterfly and hands the REPLICATED post-butterfly head tensors
to the megakernel as bank 0, so the interior combine passes fuse into
one kernel on every device.

Budget math (docs/EXPRESSIONS.md "Megakernel lowering"): the scratch
holds ``n_slots`` 8 KiB rows in VMEM (:data:`MAX_SLOTS` bounds it) and
the instruction stream prefetches into SMEM (:data:`MAX_STEPS`); a plan
past either bound reports ``fits() == False`` and the engines demote to
the multi-op pallas rung — the existing pallas -> xla ladder is the
safety net below that.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import packing

WORDS32 = packing.WORDS32
_SUB, _LANE = 16, 128

#: bytes of one accumulator slot (u32[16, 128] = one container row)
SLOT_BYTES = _SUB * _LANE * 4

#: VMEM accumulator ceiling: slots past this demote to the multi-op
#: pallas rung (8 MiB of a ~16 MiB/core VMEM, leaving room for the
#: streamed operand blocks and the double-buffered output windows)
MAX_SLOTS = (8 << 20) // SLOT_BYTES

#: instruction-stream ceiling: 7 i32 arrays prefetch into SMEM, so the
#: stream is bounded well under the segmented kernels' loose
#: SMEM_PREFETCH_MAX (7 * 4 B * 2^14 ≈ 448 KiB of SMEM)
MAX_STEPS = 1 << 14

# --------------------------------------------------------------- opcodes
#
# Every step reads acc[dst] (cur), acc[src] (srcv) and the banked row,
# computes one value and writes it back to acc[dst]; OUT/CARD steps point
# dst at the dead slot and route srcv to the output/card row their
# prefetched orow/crow arrays select.  NOP-like steps are absorbed by
# the dead slot / dead rows, so padding the stream to a pow2 costs
# nothing but grid steps.

(NOP, LOAD_ROW, OR_ROW, AND_ROW, XOR_ROW, ANDNOT_ROW_REV, ZERO,
 COPY_SLOT, OR_SLOT, AND_SLOT, XOR_SLOT, ANDNOT_SLOT, ANDNOT_ROW,
 OUT, CARD) = range(15)

_OP_ROW = {"or": OR_ROW, "and": AND_ROW, "xor": XOR_ROW}
_OP_SLOT = {"or": OR_SLOT, "and": AND_SLOT, "xor": XOR_SLOT}


def _kernel(opc_ref, dst_ref, src_ref, row_ref, bank_ref, orow_ref,
            crow_ref, wa_ref, wb_ref, out_ref, card_ref, acc_ref):
    i = pl.program_id(0)
    opc = opc_ref[i]
    dst = dst_ref[i]
    src = src_ref[i]
    row = jnp.where(bank_ref[i] == 1, wb_ref[0], wa_ref[0])
    cur = acc_ref[dst]
    srcv = acc_ref[src]
    acc_ref[dst] = jax.lax.select_n(
        opc,
        cur,                    # NOP
        row,                    # LOAD_ROW
        cur | row,              # OR_ROW
        cur & row,              # AND_ROW
        cur ^ row,              # XOR_ROW
        row & ~cur,             # ANDNOT_ROW_REV (head & ~rest-union)
        jnp.zeros_like(cur),    # ZERO
        srcv,                   # COPY_SLOT
        cur | srcv,             # OR_SLOT
        cur & srcv,             # AND_SLOT
        cur ^ srcv,             # XOR_SLOT
        cur & ~srcv,            # ANDNOT_SLOT
        cur & ~row,             # ANDNOT_ROW
        cur,                    # OUT (dead-slot write)
        cur,                    # CARD (dead-slot write)
    )
    # unconditional output writes: non-OUT/CARD steps land on the dead
    # out/card row their index maps select, real steps carry acc[src]
    out_ref[0] = srcv
    card_ref[0] = jnp.sum(
        jax.lax.population_count(srcv).astype(jnp.int32), axis=0)


def _use_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


class _Emitter:
    """Instruction-stream builder: one append per micro-op, pow2-padded
    into the seven prefetch arrays at finish()."""

    def __init__(self):
        self.ops: list = []     # (opc, dst, src, row, bank, orow, crow)

    def emit(self, opc, dst=0, src=0, row=0, bank=0, orow=None,
             crow=None):
        self.ops.append((opc, dst, src, row, bank, orow, crow))

    def finish(self, n_slots: int, out_pad: int, card_pad: int) -> dict:
        n = max(1, len(self.ops))
        n_pad = packing.next_pow2(n)
        host = {
            "opc": np.zeros(n_pad, np.int32),
            "dst": np.full(n_pad, n_slots, np.int32),
            "src": np.zeros(n_pad, np.int32),
            "row": np.zeros(n_pad, np.int32),
            "bank": np.zeros(n_pad, np.int32),
            "orow": np.full(n_pad, out_pad, np.int32),
            "crow": np.full(n_pad, card_pad, np.int32),
        }
        for i, (opc, dst, src, row, bank, orow, crow) in enumerate(
                self.ops):
            host["opc"][i] = opc
            host["dst"][i] = dst if opc not in (OUT, CARD) else n_slots
            host["src"][i] = src
            host["row"][i] = row
            host["bank"][i] = bank
            if orow is not None:
                host["orow"][i] = orow
            if crow is not None:
                host["crow"][i] = crow
        return host


@dataclasses.dataclass
class MegaPlan:
    """One assembled megakernel program: the instruction stream (host
    NumPy, device twins uploaded lazily — the multiset donate path
    re-uploads fresh per launch like every other operand dict), the
    static kernel shape, and the output-layout metadata the traced
    wrappers slice the HBM outputs back through."""

    mode: str                 # "full" | "combine"
    n_steps: int              # real instruction count (pre-pad)
    steps_pad: int
    n_slots: int              # real accumulator slots (pre-pad)
    slots_pad: int
    out_pad: int              # pow2-padded OUT rows (0 = none)
    card_pad: int
    host: dict | None         # instr arrays + "extra" (bank-1 rows) +
    #                           "leafidx" (combine mode bank-1 gather)
    arrays: dict | None = None
    #: per bucket: (card_base, out_base | None, n_real, k_pad)
    bucket_out: tuple = ()
    #: per fused section: (card_base, out_base | None, k_root)
    expr_out: tuple = ()
    #: combine mode: heads-bank row base per op group (-1 = group
    #: produces no heads and is never referenced)
    group_base: tuple = ()
    #: static bank-1 row count (survives the host drop — part of the
    #: program-shape signature)
    extra_rows: int = 1
    leaf_rows: int = 0

    @property
    def signature(self) -> tuple:
        return (self.mode, self.steps_pad, self.slots_pad, self.out_pad,
                self.card_pad, self.extra_rows, self.leaf_rows,
                self.bucket_out, self.expr_out)

    def fits(self) -> bool:
        return (self.slots_pad + 1 <= MAX_SLOTS
                and self.steps_pad <= MAX_STEPS)

    @property
    def vmem_bytes(self) -> int:
        return (self.slots_pad + 1) * SLOT_BYTES

    def stats_event(self) -> dict:
        """The ``expr.megakernel`` span-event payload
        (docs/OBSERVABILITY.md; tools/check_trace.py pins the schema)."""
        return {"mode": self.mode, "steps": int(self.n_steps),
                "slots": int(self.n_slots),
                "vmem_bytes": int(self.vmem_bytes),
                "out_rows": int(self.out_pad),
                "card_rows": int(self.card_pad),
                "sections": len(self.expr_out)}

    def device_arrays(self, fresh: bool = False) -> dict:
        if fresh:
            if self.host is None:
                raise RuntimeError(
                    "fresh=True needs the host instruction stream, which "
                    "this plan dropped after its cached upload")
            return {k: jnp.asarray(v) for k, v in self.host.items()}
        if self.arrays is None:
            self.arrays = {k: jnp.asarray(v) for k, v in self.host.items()}
        return self.arrays


# ------------------------------------------------------------- assembler

def _emit_bucket(em: _Emitter, b, base: int, card_base: int,
                 out_base) -> None:
    """One shape bucket's whole pipeline: per-(query, key) segmented
    reduce over REAL rows only, plan-time masking (heads_ok / workShyAnd
    key_keep / andnot head pass), per-slot popcount partials, and OUT
    rows when the bucket's own needs_words demands them."""
    host = b.host
    n_real, k_pad = len(b.qids), b.k_pad
    red = OR_ROW if b.op in ("or", "andnot") else _OP_ROW[b.op]
    for qi in range(n_real):
        valid = host["valid"][qi]
        rows = host["gather"][qi][valid]
        segs = host["seg_local"][qi][valid]
        for k in range(k_pad):
            slot = base + qi * k_pad + k
            ok = bool(host["heads_ok"][qi, k])
            if b.op == "and" and not bool(host["key_keep"][qi, k]):
                ok = False
            seg_rows = rows[segs == k] if ok else rows[:0]
            if b.op == "andnot":
                if not bool(host["head_ok"][qi, k]):
                    em.emit(ZERO, dst=slot)
                elif seg_rows.size == 0:
                    # no rest rows: head & ~0 == the head row itself
                    em.emit(LOAD_ROW, dst=slot,
                            row=int(host["head_gather"][qi, k]))
                else:
                    em.emit(LOAD_ROW, dst=slot, row=int(seg_rows[0]))
                    for r in seg_rows[1:]:
                        em.emit(OR_ROW, dst=slot, row=int(r))
                    em.emit(ANDNOT_ROW_REV, dst=slot,
                            row=int(host["head_gather"][qi, k]))
            elif not ok or seg_rows.size == 0:
                em.emit(ZERO, dst=slot)
            else:
                em.emit(LOAD_ROW, dst=slot, row=int(seg_rows[0]))
                for r in seg_rows[1:]:
                    em.emit(red, dst=slot, row=int(r))
    for qi in range(n_real):
        for k in range(k_pad):
            slot = base + qi * k_pad + k
            em.emit(CARD, src=slot, crow=card_base + qi * k_pad + k)
            if out_base is not None:
                em.emit(OUT, src=slot, orow=out_base + qi * k_pad + k)


class _SectionCtx:
    """Per-section assembly state: maps compiled steps to (slot | row)
    sources for each of the node's keys."""

    def __init__(self, sec, slot_of_reduce, extra_base, leaf_row):
        self.sec = sec
        self.slot_of_reduce = slot_of_reduce
        self.extra_base = extra_base
        self.leaf_row = leaf_row
        self.combine_base: dict = {}

    def source(self, ci: int, j: int):
        """("slot", s) | ("row", bank, r) for step ``ci``'s key ``j``."""
        st = self.sec.steps[ci]
        kind = st[0]
        if kind == "leaf":
            bank, row = self.leaf_row(self.sec, ci, j)
            return ("row", bank, row)
        if kind == "adhoc":
            return ("row", 1, self.extra_base[ci] + j)
        if kind == "reduce":
            _, bi, slot, _kq = st
            return self.slot_of_reduce(bi, slot, j)
        return ("slot", self.combine_base[ci] + j)


def _emit_combine(em: _Emitter, ctx: _SectionCtx, si: int) -> None:
    """One interior combine node: per key, resolve each child through
    the plan-time alignment arrays into a slot/row source, constant-fold
    absent keys to the op identity, and chain the bitwise micro-ops."""
    sec = ctx.sec
    _, op, children, kq = sec.steps[si]
    base = ctx.combine_base[si]
    host = sec.host
    for j in range(kq):
        dst = base + j
        parts = []
        for k, (ci, aligned) in enumerate(children):
            if aligned:
                jj, ok = j, True
            else:
                jj = int(host[f"i{si}_{k}"][j])
                ok = bool(host[f"o{si}_{k}"][j])
            parts.append((ok, ctx.source(ci, jj) if ok else None))
        if op == "andnot":
            # head is key-aligned by construction (node keys ARE its
            # keys); absent rest children contribute ~0 == all-ones
            _, head = parts[0]
            _emit_set(em, dst, head)
            for ok, srcp in parts[1:]:
                if ok:
                    _emit_op(em, dst, srcp, ANDNOT_SLOT, ANDNOT_ROW)
        elif op == "and":
            if not all(ok for ok, _ in parts):
                # an absent AND child annihilates the key (cannot
                # happen for intersection key spaces — kept as the
                # plan-time guard the traced path encodes as a mask)
                em.emit(ZERO, dst=dst)
                continue
            _emit_set(em, dst, parts[0][1])
            for _, srcp in parts[1:]:
                _emit_op(em, dst, srcp, AND_SLOT, AND_ROW)
        else:
            live = [srcp for ok, srcp in parts if ok]
            if not live:
                em.emit(ZERO, dst=dst)
                continue
            _emit_set(em, dst, live[0])
            s_op, r_op = (_OP_SLOT[op], _OP_ROW[op])
            for srcp in live[1:]:
                _emit_op(em, dst, srcp, s_op, r_op)


def _emit_set(em: _Emitter, dst: int, srcp) -> None:
    if srcp[0] == "slot":
        em.emit(COPY_SLOT, dst=dst, src=srcp[1])
    else:
        em.emit(LOAD_ROW, dst=dst, row=srcp[2], bank=srcp[1])


def _emit_op(em: _Emitter, dst: int, srcp, slot_op: int,
             row_op: int) -> None:
    if srcp[0] == "slot":
        em.emit(slot_op, dst=dst, src=srcp[1])
    else:
        em.emit(row_op, dst=dst, row=srcp[2], bank=srcp[1])


def _pack_extra(sections) -> tuple:
    """Bank-1 rows: every ad-hoc leaf's container rows, concatenated;
    per-(section-id, step) base offsets for the assembler."""
    rows, bases = [], {}
    off = 0
    for sid, sec in enumerate(sections):
        for ci, st in enumerate(sec.steps):
            if st[0] == "adhoc":
                w = sec.host[f"w{ci}"]
                bases[(sid, ci)] = off
                rows.append(np.asarray(w, np.uint32))
                off += int(w.shape[0])
    if rows:
        return np.concatenate(rows, axis=0), bases
    return np.zeros((1, WORDS32), np.uint32), bases


def _assemble(mode: str, buckets, sections, slot_of_reduce, leaf_row,
              extra, extra_bases, emit_buckets: bool) -> MegaPlan:
    """Shared assembly tail of :func:`build_full` /
    :func:`build_combines`: allocate accumulator slots and output rows,
    walk buckets (full mode) then every section's combine steps in
    topological order, and close with the CARD/OUT output phase."""
    n_slots = 0
    bucket_base: list = []
    if emit_buckets:
        for b in buckets:
            bucket_base.append(n_slots)
            n_slots += len(b.qids) * b.k_pad
    n_card = n_out = 0
    bucket_out: list = []
    if emit_buckets:
        for b in buckets:
            ob = n_out if b.needs_words else None
            bucket_out.append((n_card, ob, len(b.qids), b.k_pad))
            n_card += len(b.qids) * b.k_pad
            if ob is not None:
                n_out += len(b.qids) * b.k_pad

    em = _Emitter()
    if emit_buckets:
        for b, base, (cb, ob, _n, _k) in zip(buckets, bucket_base,
                                             bucket_out):
            _emit_bucket(em, b, base, cb, ob)

    ctxs: list = []
    for sid, sec in enumerate(sections):
        ctx = _SectionCtx(
            sec,
            slot_of_reduce=slot_of_reduce(bucket_base),
            extra_base={ci: extra_bases.get((sid, ci), 0)
                        for ci, st in enumerate(sec.steps)
                        if st[0] == "adhoc"},
            leaf_row=leaf_row)
        for si, st in enumerate(sec.steps):
            if st[0] == "combine":
                ctx.combine_base[si] = n_slots
                n_slots += int(st[3])
        ctxs.append(ctx)
    for ctx in ctxs:
        for si, st in enumerate(ctx.sec.steps):
            if st[0] == "combine":
                _emit_combine(em, ctx, si)

    expr_out: list = []
    for ctx in ctxs:
        sec = ctx.sec
        k_root = int(sec.root_keys.size)
        root_srcs = [ctx.source(sec.root, j) for j in range(k_root)]
        if any(s[0] == "row" for s in root_srcs):
            # a combine that collapsed to its only live child (bare
            # leaf/ad-hoc root — or a reduce root in combine mode, where
            # reduce values are bank rows): give the root its own slots
            # so OUT/CARD have a slot source
            base = n_slots
            n_slots += k_root
            for j, s in enumerate(root_srcs):
                _emit_set(em, base + j, s)
            root_slots = [base + j for j in range(k_root)]
        else:
            root_slots = [s[1] for s in root_srcs]
        ob = n_out if sec.form == "bitmap" else None
        expr_out.append((n_card, ob, k_root))
        for j in range(k_root):
            em.emit(CARD, src=root_slots[j], crow=n_card + j)
            if ob is not None:
                em.emit(OUT, src=root_slots[j], orow=n_out + j)
        n_card += k_root
        if ob is not None:
            n_out += k_root

    slots_pad = packing.next_pow2(max(1, n_slots))
    out_pad = packing.next_pow2(n_out) if n_out else 0
    card_pad = packing.next_pow2(max(1, n_card))
    from ..runtime import lattice as rt_lattice

    n_real = len(em.ops)
    if rt_lattice.active() is not None:
        # the lattice snap, instruction-stream level (docs/LATTICE.md):
        # pow2 already bounds each dimension, but floor-quantizing the
        # small end too makes near-identical DAG variants share one
        # program shape — padding steps is pure NOPs against the dead
        # slot, padding slots is unread VMEM
        slots_pad = max(slots_pad, 4)
        card_pad = max(card_pad, 8)
        if out_pad:
            out_pad = max(out_pad, 8)
        while len(em.ops) < 16:
            em.emit(NOP)
    host = em.finish(slots_pad, out_pad, card_pad)
    host["extra"] = extra
    return MegaPlan(
        mode=mode, n_steps=n_real,
        steps_pad=int(host["opc"].shape[0]),
        n_slots=n_slots, slots_pad=slots_pad,
        out_pad=out_pad, card_pad=card_pad, host=host,
        bucket_out=tuple(bucket_out), expr_out=tuple(expr_out),
        extra_rows=int(extra.shape[0]))


def build_full(buckets, sections) -> MegaPlan:
    """Assemble the FULL pipeline megakernel for a bucketed plan with
    fused expression sections: every bucket's segmented reduce + post
    passes AND every section's combine/output steps in one instruction
    stream.  Bucket/section host arrays must still be alive (the
    engines call this at plan time, before the upload-and-drop
    discipline runs); row indices are whatever image space the plan
    gathers from (set-local for BatchEngine, pooled for the multiset
    planner — the assembler just copies them into the stream)."""
    fused = [s for s in sections if s.kind == "fused"]
    extra, extra_bases = _pack_extra(fused)

    def slot_of_reduce(bucket_base):
        def fn(bi, slot, j):
            return ("slot", bucket_base[bi] + slot * buckets[bi].k_pad + j)
        return fn

    def leaf_row(sec, ci, j):
        # full mode streams leaves straight from the row image (bank 0)
        return 0, int(sec.host[f"g{ci}"][j])

    return _assemble("full", buckets, fused, slot_of_reduce, leaf_row,
                     extra, extra_bases, emit_buckets=True)


def build_combines(buckets, op_groups, sections, expr_bis) -> MegaPlan:
    """Assemble the COMBINE-ONLY megakernel (the mesh composition):
    reduce-node values arrive as rows of the post-butterfly flat head
    bank (bank 0 — the padded ``q * (k_pad + 1)`` layout of
    ``expr.traced_bucket_heads``), resident leaves as pre-gathered rows
    and ad-hoc leaves as shipped rows (bank 1); only the combine steps
    and root outputs run in-kernel."""
    fused = [s for s in sections if s.kind == "fused"]
    extra, extra_bases = _pack_extra(fused)

    # bank-0 layout: concat of every head-PRODUCING group's flat tensor
    produces = [g.needs_words or any(bi in expr_bis
                                     for bi in g.bucket_idx)
                for g in op_groups]
    group_base, off = [], 0
    for g, p in zip(op_groups, produces):
        group_base.append(off if p else -1)
        if p:
            off += int(g.nseg)
    bucket_row0 = {}
    for g, gb in zip(op_groups, group_base):
        for bi, s0 in zip(g.bucket_idx, g.seg_offs):
            bucket_row0[bi] = (gb + s0) if gb >= 0 else -1

    def slot_of_reduce(_bucket_base):
        def fn(bi, slot, j):
            r0 = bucket_row0[bi]
            if r0 < 0:
                raise AssertionError(
                    f"expr-feeding bucket {bi} in a headless op group")
            return ("row", 0, r0 + slot * (buckets[bi].k_pad + 1) + j)
        return fn

    # bank-1 layout: pre-gathered leaf rows first, ad-hoc rows after
    leaf_parts, leaf_bases = [], {}
    off = 0
    for sid, sec in enumerate(fused):
        for ci, st in enumerate(sec.steps):
            if st[0] == "leaf":
                g = np.asarray(sec.host[f"g{ci}"], np.int64)
                leaf_bases[(sid, ci)] = off
                leaf_parts.append(g)
                off += int(g.size)
    leaf_idx = (np.concatenate(leaf_parts) if leaf_parts
                else np.zeros(0, np.int64)).astype(np.int32)
    n_leaf = int(leaf_idx.size)
    sec_id = {id(sec): sid for sid, sec in enumerate(fused)}

    def leaf_row(sec, ci, j):
        # combine mode pre-gathers leaves into bank 1, before the extras
        return 1, leaf_bases[(sec_id[id(sec)], ci)] + j

    # extra-bank rows sit AFTER the gathered leaf rows in bank 1
    extra_bases = {k: v + n_leaf for k, v in extra_bases.items()}
    mega = _assemble("combine", buckets, fused, slot_of_reduce, leaf_row,
                     extra, extra_bases, emit_buckets=False)
    mega.host["leafidx"] = leaf_idx
    mega.group_base = tuple(group_base)
    mega.leaf_rows = n_leaf
    return mega


# --------------------------------------------------------- traced eval

def _raw_call(mega: MegaPlan, bank_a, bank_b, arrs):
    """The pallas_call: one sequential grid pass over the instruction
    stream.  Returns the raw padded (out, cards) buffers."""
    steps = int(arrs["opc"].shape[0])
    out_pad = max(1, mega.out_pad)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(steps,),
        in_specs=[
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow:
                         (jnp.where(bank[i] == 0, row[i], 0), 0, 0)),
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow:
                         (jnp.where(bank[i] == 1, row[i], 0), 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _SUB, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow:
                         (orow[i], 0, 0)),
            pl.BlockSpec((1, _LANE),
                         lambda i, opc, dst, src, row, bank, orow, crow:
                         (crow[i], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((mega.slots_pad + 1, _SUB, _LANE), jnp.uint32)],
    )
    out, cards = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((out_pad + 1, _SUB, _LANE), jnp.uint32),
            jax.ShapeDtypeStruct((mega.card_pad + 1, _LANE), jnp.int32),
        ],
        interpret=_use_interpret(),
    )(arrs["opc"], arrs["dst"], arrs["src"], arrs["row"], arrs["bank"],
      arrs["orow"], arrs["crow"],
      bank_a.reshape(-1, _SUB, _LANE), bank_b.reshape(-1, _SUB, _LANE))
    return out, cards


def _call(mega: MegaPlan, bank_a, bank_b, arrs, wrap=None):
    """One megakernel dispatch -> (out_rows u32[out_pad, 2048] | None,
    card_rows i32[card_pad, 128]).  ``wrap`` (the mesh composition)
    wraps the raw call — e.g. a fully-replicated ``shard_map`` so every
    device runs the whole kernel on its replica instead of letting the
    SPMD partitioner slice the grid."""
    fn = lambda a, b, r: _raw_call(mega, a, b, r)
    if wrap is not None:
        fn = wrap(fn)
    out, cards = fn(bank_a, bank_b, arrs)
    out_rows = (out[:mega.out_pad].reshape(mega.out_pad, WORDS32)
                if mega.out_pad else None)
    return out_rows, cards[:mega.card_pad]


def _slice_outputs(mega: MegaPlan, out_rows, card_rows):
    """HBM outputs -> (per-bucket outs, per-section expr outs), the
    engines' run-fn contract: buckets get (heads|None, cards[n, k_pad]),
    fused sections get (heads|None, cards[K])."""
    cards = jnp.sum(card_rows, axis=1)
    outs = []
    for cb, ob, n, k_pad in mega.bucket_out:
        c = cards[cb:cb + n * k_pad].reshape(n, k_pad)
        h = (out_rows[ob:ob + n * k_pad].reshape(n, k_pad, WORDS32)
             if ob is not None else None)
        outs.append((h, c))
    expr_outs = []
    for cb, ob, k_root in mega.expr_out:
        c = cards[cb:cb + k_root]
        h = out_rows[ob:ob + k_root] if ob is not None else None
        expr_outs.append((h, c))
    return outs, expr_outs


def eval_full(mega: MegaPlan, words, arrs):
    """Traced FULL-mode evaluation: ``words`` is the resident (or
    pooled) row image the stream's bank-0 rows index; returns the
    ``(bucket_outs, expr_outs)`` pair the engines' fused run fns
    return."""
    out_rows, card_rows = _call(mega, words, arrs["extra"], arrs)
    return _slice_outputs(mega, out_rows, card_rows)


def eval_combines(mega: MegaPlan, group_heads, pool_words, arrs,
                  wrap=None):
    """Traced COMBINE-mode evaluation (the sharded engine's replicated
    post-butterfly side): bank 0 = the producing groups' flat head
    tensors, bank 1 = pre-gathered leaf rows + ad-hoc rows.  The leaf
    gather runs OUTSIDE the kernel (it may cross shards on a
    rows-sharded pool; ``wrap``'s replicated in_specs then hand every
    device the full banks).  Returns the per-section expr outs only
    (bucket outputs stay with the group bodies)."""
    bank_a = [h for h, _ in group_heads if h is not None]
    bank_a = (jnp.concatenate(bank_a, axis=0) if bank_a
              else jnp.zeros((1, WORDS32), jnp.uint32))
    leaf_idx = arrs["leafidx"]
    parts = []
    if int(leaf_idx.shape[0]):
        parts.append(pool_words[leaf_idx])
    parts.append(arrs["extra"])
    bank_b = parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                              axis=0)
    kernel_arrs = {k: v for k, v in arrs.items() if k != "leafidx"}
    out_rows, card_rows = _call(mega, bank_a, bank_b, kernel_arrs,
                                wrap=wrap)
    _outs, expr_outs = _slice_outputs(mega, out_rows, card_rows)
    return expr_outs
