"""roaringbitmap_tpu — a TPU-native compressed-bitmap set-algebra framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of the reference
RoaringBitmap Java library (/root/reference): container-partitioned compressed
bitmaps, the portable RoaringFormatSpec serialization, pairwise and *wide*
set algebra (OR/AND/XOR over thousands of bitmaps) executing on device,
bit-sliced indexes and range indexes on top.

Execution model (two-tier, see SURVEY.md §7):
- Host tier: NumPy struct-of-arrays container model for point ops,
  construction, and serialization (roaringbitmap_tpu.core).
- Device tier: containers packed into HBM-resident u32 word tensors; wide
  aggregation, key-set algebra, and cardinality run as vmapped/pallas
  kernels (roaringbitmap_tpu.ops, .parallel) and scale over a
  jax.sharding.Mesh via shard_map.
"""

from .core.bitmap import (
    RoaringBitmap,
    and_,
    and_cardinality,
    andnot,
    andnot_cardinality,
    flip,
    or_,
    or_cardinality,
    or_not,
    xor,
    xor_cardinality,
)
from .core import containers

# camelCase-familiar aliases (RoaringBitmap.andNot / andNotCardinality)
and_not = andnot
and_not_cardinality = andnot_cardinality

from .core.bitmap64 import Roaring64Bitmap, Roaring64NavigableMap
from .core.bitset import RoaringBitSet
from .core.fastrank import FastRankRoaringBitmap
from .core.rangebitmap import RangeBitmap
from .core.writer import RoaringBitmapWriter
from .format import spec
from .format.spec import InvalidRoaringFormat

# hardened query runtime: typed error taxonomy, guarded dispatch with the
# engine fallback chain, deterministic fault injection (docs/ROBUSTNESS.md)
from . import runtime

# query-path observability: span tracing (ROARING_TPU_TRACE), unified
# metrics registry, Prometheus/JSON export (docs/OBSERVABILITY.md)
from . import obs

__all__ = [
    "RoaringBitmap", "Roaring64Bitmap", "Roaring64NavigableMap",
    "RangeBitmap", "FastRankRoaringBitmap", "RoaringBitSet",
    "RoaringBitmapWriter",
    "and_", "or_", "xor", "andnot", "and_not", "or_not", "flip",
    "and_cardinality", "or_cardinality", "xor_cardinality",
    "andnot_cardinality", "and_not_cardinality",
    "containers", "spec", "InvalidRoaringFormat", "runtime", "obs",
]

__version__ = "0.1.0"
