"""Build + run the C++ CPU baseline and record baselines/cpu_baseline.json.

The driver metric (BASELINE.json) compares our TPU wide aggregation against
the CPU `ParallelAggregation.or`.  No JVM exists in this image (no `java`
binary, zero egress to fetch one), so the baseline is the single-file C++
translation of the same algorithm in wide_or_cpu.cpp, compiled -O3 — see
that file's header for the algorithm mapping.  This script:

1. serializes each dataset's bitmaps to the portable format and frames them
   into a temp file (u32 count, then u32 len + payload each),
2. compiles wide_or_cpu.cpp (cached on mtime),
3. runs wide_or/wide_xor/wide_and/pairwise ops, asserting cardinality
   parity against our host tier,
4. writes baselines/cpu_baseline.json for bench.py's vs_baseline.

Usage: python baselines/run_cpu_baseline.py [--reps N]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import struct
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

SRC = os.path.join(HERE, "wide_or_cpu.cpp")
BIN = os.path.join(HERE, "wide_or_cpu")
OUT = os.path.join(HERE, "cpu_baseline.json")

DATASETS = ("census1881", "wikileaks-noquotes", "census1881_srt",
            "wikileaks-noquotes_srt", "uscensus2000")


def build() -> str:
    if (not os.path.exists(BIN)
            or os.path.getmtime(BIN) < os.path.getmtime(SRC)):
        subprocess.run(
            ["g++", "-O3", "-march=native", "-std=c++17", "-o", BIN, SRC],
            check=True)
    return BIN


def frame_file(bitmaps, path: str) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack("<I", len(bitmaps)))
        for b in bitmaps:
            data = b.serialize()
            f.write(struct.pack("<I", len(data)))
            f.write(data)


def run_dataset(name: str, reps: int) -> dict:
    from roaringbitmap_tpu.parallel import fast_aggregation
    from roaringbitmap_tpu.utils import datasets

    bms = datasets.load_bitmaps(name)
    with tempfile.NamedTemporaryFile(suffix=".frames", delete=False) as tf:
        frame_file(bms, tf.name)
        frames = tf.name
    try:
        out = subprocess.run([BIN, frames, str(reps), "all"], check=True,
                             capture_output=True, text=True).stdout
    finally:
        os.unlink(frames)
    rows = {}
    for line in out.splitlines():
        row = json.loads(line)
        rows[row["op"]] = row
    # parity: the C++ result cardinalities must match our host tier
    expect = {
        "wide_or": fast_aggregation.or_(*bms).cardinality,
        "wide_xor": fast_aggregation.xor(*bms).cardinality,
        "wide_and": fast_aggregation.and_(*bms).cardinality,
    }
    for op, want in expect.items():
        got = rows[op]["result_cardinality"]
        assert got == want, f"{name}/{op}: C++ {got} != host {want}"
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=100)
    ap.add_argument("--datasets", nargs="*", default=list(DATASETS))
    args = ap.parse_args()

    build()
    result = {
        "description": "C++ -O3 single-thread CPU baseline "
                       "(ParallelAggregation.or algorithm; no JVM in image)",
        "host": {
            "cpu_count": os.cpu_count(),
            "platform": platform.platform(),
            "compiler": subprocess.run(["g++", "--version"], check=True,
                                       capture_output=True,
                                       text=True).stdout.splitlines()[0],
        },
        "reps": args.reps,
        "datasets": {},
    }
    for name in args.datasets:
        print(f"measuring {name} ...", file=sys.stderr)
        result["datasets"][name] = run_dataset(name, args.reps)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result["datasets"], indent=2))


if __name__ == "__main__":
    main()
