// CPU baseline for the driver metric: wide aggregation over N roaring
// bitmaps, single host thread, -O3.
//
// This is the stand-in for the reference JVM baseline
// (org.roaringbitmap.ParallelAggregation.or, ParallelAggregation.java:160-222):
// no JVM exists in this image (no `java` binary, zero egress), so the best
// available CPU implementation is this C++ translation of the same
// algorithm — group containers by key, accumulate each key slice into a
// dense 1024xu64 word block (the OrCollector / lazy-or strategy the JVM
// uses for every slice >= 16 containers), then one popcount "repair" pass
// (Container.repairAfterLazy, Container.java:869-873) that downgrades to an
// array container at cardinality <= 4096.  On this 1-core host the JVM's
// ForkJoinPool would be sequential anyway, so a single thread is the
// faithful equivalent.
//
// Input: a frame file produced by baselines/run_cpu_baseline.py:
//   u32 n_bitmaps, then per bitmap { u32 byte_len, portable-format payload }.
// The payload is the RoaringFormatSpec portable serialization
// (https spec; cookies 12346/12347 — RoaringArray.java:23-24,851-893), so
// parsing it here is also an interop check of our serializer.
//
// Output: one JSON line per requested op with ns/op over `reps` repetitions
// plus the result cardinality for parity checking.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <chrono>
#include <string>
#include <vector>

namespace {

constexpr uint32_t SERIAL_COOKIE_NO_RUNCONTAINER = 12346;
constexpr uint32_t SERIAL_COOKIE = 12347;
constexpr int NO_OFFSET_THRESHOLD = 4;
constexpr int WORDS = 1024;           // u64 words per 2^16-bit container
constexpr int ARRAY_MAX = 4096;       // ArrayContainer.DEFAULT_MAX_SIZE

enum class Kind : uint8_t { Array, Bitmap, Run };

struct Cont {
  uint16_t key;
  Kind kind;
  uint16_t card_minus_one;  // serialized cardinality - 1 (array/bitmap)
  const uint8_t* payload;   // into the mapped frame buffer (zero-copy)
  uint16_t n_runs;          // run containers only
};

struct Bitmap {
  std::vector<Cont> conts;
};

uint16_t rd16(const uint8_t* p) { uint16_t v; std::memcpy(&v, p, 2); return v; }
uint32_t rd32(const uint8_t* p) { uint32_t v; std::memcpy(&v, p, 4); return v; }

// Parse one portable-format bitmap (RoaringArray.deserialize, :276-361).
Bitmap parse(const uint8_t* buf, size_t len) {
  Bitmap bm;
  if (len < 4) { std::fprintf(stderr, "short stream\n"); std::exit(2); }
  uint32_t cookie = rd32(buf);
  size_t pos = 4;
  int size;
  bool has_run = (cookie & 0xFFFF) == SERIAL_COOKIE;
  std::vector<uint8_t> run_bits;
  if (has_run) {
    size = (cookie >> 16) + 1;
    size_t nb = (size + 7) / 8;
    run_bits.assign(buf + pos, buf + pos + nb);
    pos += nb;
  } else {
    if (cookie != SERIAL_COOKIE_NO_RUNCONTAINER) {
      std::fprintf(stderr, "bad cookie %u\n", cookie); std::exit(2);
    }
    size = static_cast<int>(rd32(buf + pos));
    pos += 4;
  }
  bm.conts.resize(size);
  for (int i = 0; i < size; ++i) {
    bm.conts[i].key = rd16(buf + pos);
    bm.conts[i].card_minus_one = rd16(buf + pos + 2);
    pos += 4;
  }
  if (!has_run || size >= NO_OFFSET_THRESHOLD) pos += 4u * size;  // offsets
  for (int i = 0; i < size; ++i) {
    Cont& c = bm.conts[i];
    bool is_run = has_run && (run_bits[i / 8] >> (i % 8)) & 1;
    if (is_run) {
      c.kind = Kind::Run;
      c.n_runs = rd16(buf + pos);
      pos += 2;
      c.payload = buf + pos;
      pos += 4u * c.n_runs;
    } else if (c.card_minus_one + 1 > ARRAY_MAX) {
      c.kind = Kind::Bitmap;
      c.payload = buf + pos;
      pos += 8u * WORDS;
    } else {
      c.kind = Kind::Array;
      c.payload = buf + pos;
      pos += 2u * (c.card_minus_one + 1);
    }
  }
  return bm;
}

// OR one container into a dense word accumulator
// (BitmapContainer.lazyor variants, BitmapContainer.java:878-909).
void or_into(const Cont& c, uint64_t* w) {
  switch (c.kind) {
    case Kind::Bitmap: {
      uint64_t tmp[WORDS];
      std::memcpy(tmp, c.payload, 8 * WORDS);  // payload may be unaligned
      for (int i = 0; i < WORDS; ++i) w[i] |= tmp[i];
      break;
    }
    case Kind::Array: {
      int n = c.card_minus_one + 1;
      for (int i = 0; i < n; ++i) {
        uint16_t v = rd16(c.payload + 2 * i);
        w[v >> 6] |= uint64_t{1} << (v & 63);
      }
      break;
    }
    case Kind::Run: {
      for (int r = 0; r < c.n_runs; ++r) {
        uint32_t start = rd16(c.payload + 4 * r);
        uint32_t end = start + rd16(c.payload + 4 * r + 2);  // inclusive
        // Util.setBitmapRange (Util.java:616)
        int fw = start >> 6, lw = end >> 6;
        if (fw == lw) {
          w[fw] |= (~uint64_t{0} << (start & 63)) &
                   (~uint64_t{0} >> (63 - (end & 63)));
        } else {
          w[fw] |= ~uint64_t{0} << (start & 63);
          for (int i = fw + 1; i < lw; ++i) w[i] = ~uint64_t{0};
          w[lw] |= ~uint64_t{0} >> (63 - (end & 63));
        }
      }
      break;
    }
  }
}

void and_into(const Cont& c, uint64_t* w) {
  uint64_t tmp[WORDS];
  std::memset(tmp, 0, sizeof tmp);
  or_into(c, tmp);
  for (int i = 0; i < WORDS; ++i) w[i] &= tmp[i];
}

void xor_into(const Cont& c, uint64_t* w) {
  uint64_t tmp[WORDS];
  std::memset(tmp, 0, sizeof tmp);
  or_into(c, tmp);
  for (int i = 0; i < WORDS; ++i) w[i] ^= tmp[i];
}

// Result sink: the repaired output container set.  Mirrors what the JVM
// materializes (repairAfterLazy converts card<=4096 down to arrays); values
// are written so the work can't be dead-code-eliminated.
struct Result {
  std::vector<uint16_t> keys;
  std::vector<int> cards;
  std::vector<uint16_t> array_values;       // concatenated array containers
  std::vector<uint64_t> bitmap_words;       // concatenated bitmap containers
  uint64_t total_card = 0;
  void clear() {
    keys.clear(); cards.clear(); array_values.clear(); bitmap_words.clear();
    total_card = 0;
  }
  void emit(uint16_t key, const uint64_t* w) {
    int card = 0;
    for (int i = 0; i < WORDS; ++i) card += __builtin_popcountll(w[i]);
    if (card == 0) return;
    keys.push_back(key);
    cards.push_back(card);
    total_card += card;
    if (card <= ARRAY_MAX) {  // repairAfterLazy downgrade
      for (int i = 0; i < WORDS; ++i) {
        uint64_t x = w[i];
        while (x) {
          int b = __builtin_ctzll(x);
          array_values.push_back(static_cast<uint16_t>((i << 6) | b));
          x &= x - 1;
        }
      }
    } else {
      bitmap_words.insert(bitmap_words.end(), w, w + WORDS);
    }
  }
};

// ParallelAggregation.groupByKey (:136-152) + per-key reduce (:198-222).
void wide_or(const std::vector<Bitmap>& bms, Result& res) {
  static std::vector<const Cont*> slices[65536];
  std::vector<uint16_t> present;
  for (const Bitmap& b : bms)
    for (const Cont& c : b.conts) {
      if (slices[c.key].empty()) present.push_back(c.key);
      slices[c.key].push_back(&c);
    }
  std::sort(present.begin(), present.end());
  res.clear();
  uint64_t w[WORDS];
  for (uint16_t key : present) {
    std::memset(w, 0, sizeof w);
    for (const Cont* c : slices[key]) or_into(*c, w);
    res.emit(key, w);
    slices[key].clear();
  }
}

void wide_xor(const std::vector<Bitmap>& bms, Result& res) {
  static std::vector<const Cont*> slices[65536];
  std::vector<uint16_t> present;
  for (const Bitmap& b : bms)
    for (const Cont& c : b.conts) {
      if (slices[c.key].empty()) present.push_back(c.key);
      slices[c.key].push_back(&c);
    }
  std::sort(present.begin(), present.end());
  res.clear();
  uint64_t w[WORDS];
  for (uint16_t key : present) {
    std::memset(w, 0, sizeof w);
    for (const Cont* c : slices[key]) xor_into(*c, w);
    res.emit(key, w);
    slices[key].clear();
  }
}

// FastAggregation.workShyAnd (:356-411): key-presence intersection, then a
// dense AND chain per surviving key.
void wide_and(const std::vector<Bitmap>& bms, Result& res) {
  uint64_t keymask[WORDS];
  std::memset(keymask, 0, sizeof keymask);
  for (const Cont& c : bms[0].conts)
    keymask[c.key >> 6] |= uint64_t{1} << (c.key & 63);
  uint64_t other[WORDS];
  for (size_t j = 1; j < bms.size(); ++j) {
    std::memset(other, 0, sizeof other);
    for (const Cont& c : bms[j].conts)
      other[c.key >> 6] |= uint64_t{1} << (c.key & 63);
    for (int i = 0; i < WORDS; ++i) keymask[i] &= other[i];
  }
  res.clear();
  uint64_t w[WORDS];
  for (int ki = 0; ki < WORDS; ++ki) {
    uint64_t x = keymask[ki];
    while (x) {
      int b = __builtin_ctzll(x);
      x &= x - 1;
      uint16_t key = static_cast<uint16_t>((ki << 6) | b);
      std::memset(w, 0xFF, sizeof w);
      for (const Bitmap& bm : bms) {
        // binary search this bitmap's sorted key array
        const auto& cs = bm.conts;
        size_t lo = 0, hi = cs.size();
        while (lo < hi) {
          size_t mid = (lo + hi) / 2;
          if (cs[mid].key < key) lo = mid + 1; else hi = mid;
        }
        and_into(cs[lo], w);
      }
      res.emit(key, w);
    }
  }
}

// Successive pairwise a[i] OP a[i+1] over the whole set, simplebenchmark
// style (simplebenchmark.java:70-76): result cardinality only.
uint64_t pairwise_card(const std::vector<Bitmap>& bms, bool is_and) {
  uint64_t total = 0;
  uint64_t w[WORDS], t[WORDS];
  for (size_t i = 0; i + 1 < bms.size(); ++i) {
    const Bitmap &a = bms[i], &b = bms[i + 1];
    size_t ia = 0, ib = 0;
    while (ia < a.conts.size() || ib < b.conts.size()) {
      uint16_t ka = ia < a.conts.size() ? a.conts[ia].key : 0xFFFF;
      uint16_t kb = ib < b.conts.size() ? b.conts[ib].key : 0xFFFF;
      if (ia < a.conts.size() && (ib >= b.conts.size() || ka < kb)) {
        if (!is_and) {
          std::memset(w, 0, sizeof w);
          or_into(a.conts[ia], w);
          for (int k = 0; k < WORDS; ++k) total += __builtin_popcountll(w[k]);
        }
        ++ia;
      } else if (ib < b.conts.size() && (ia >= a.conts.size() || kb < ka)) {
        if (!is_and) {
          std::memset(w, 0, sizeof w);
          or_into(b.conts[ib], w);
          for (int k = 0; k < WORDS; ++k) total += __builtin_popcountll(w[k]);
        }
        ++ib;
      } else {
        std::memset(w, 0, sizeof w);
        or_into(a.conts[ia], w);
        std::memset(t, 0, sizeof t);
        or_into(b.conts[ib], t);
        for (int k = 0; k < WORDS; ++k) {
          uint64_t r = is_and ? (w[k] & t[k]) : (w[k] | t[k]);
          total += __builtin_popcountll(r);
        }
        ++ia; ++ib;
      }
    }
  }
  return total;
}

double now_ns() {
  return std::chrono::duration<double, std::nano>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr,
                 "usage: %s FRAMEFILE REPS [op]\n  op: wide_or (default), "
                 "wide_and, wide_xor, pairwise_and, pairwise_or, all\n",
                 argv[0]);
    return 1;
  }
  FILE* f = std::fopen(argv[1], "rb");
  if (!f) { std::perror("open"); return 1; }
  std::fseek(f, 0, SEEK_END);
  long flen = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(flen);
  if (std::fread(buf.data(), 1, flen, f) != static_cast<size_t>(flen)) {
    std::fprintf(stderr, "short read\n");
    return 1;
  }
  std::fclose(f);

  uint32_t n = rd32(buf.data());
  size_t pos = 4;
  std::vector<Bitmap> bms;
  bms.reserve(n);
  uint64_t serialized_bytes = 0;
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t blen = rd32(buf.data() + pos);
    pos += 4;
    bms.push_back(parse(buf.data() + pos, blen));
    pos += blen;
    serialized_bytes += blen;
  }

  int reps = std::atoi(argv[2]);
  std::string op = argc > 3 ? argv[3] : "wide_or";
  Result res;

  auto bench_wide = [&](const char* name, auto fn) {
    fn(bms, res);  // warmup + parity value
    uint64_t card = res.total_card;
    double best = 1e30, total = 0;
    for (int r = 0; r < reps; ++r) {
      double t0 = now_ns();
      fn(bms, res);
      double dt = now_ns() - t0;
      total += dt;
      if (dt < best) best = dt;
    }
    std::printf(
        "{\"op\": \"%s\", \"n_bitmaps\": %u, \"reps\": %d, "
        "\"ns_per_op_avg\": %.0f, \"ns_per_op_best\": %.0f, "
        "\"result_cardinality\": %llu, \"serialized_bytes\": %llu}\n",
        name, n, reps, total / reps, best,
        static_cast<unsigned long long>(card),
        static_cast<unsigned long long>(serialized_bytes));
  };
  auto bench_pair = [&](const char* name, bool is_and) {
    uint64_t card = pairwise_card(bms, is_and);
    double best = 1e30, total = 0;
    for (int r = 0; r < reps; ++r) {
      double t0 = now_ns();
      uint64_t c = pairwise_card(bms, is_and);
      double dt = now_ns() - t0;
      if (c != card) { std::fprintf(stderr, "parity drift\n"); std::exit(3); }
      total += dt;
      if (dt < best) best = dt;
    }
    std::printf(
        "{\"op\": \"%s\", \"n_bitmaps\": %u, \"reps\": %d, "
        "\"ns_per_op_avg\": %.0f, \"ns_per_op_best\": %.0f, "
        "\"result_cardinality\": %llu, \"serialized_bytes\": %llu}\n",
        name, n, reps, total / reps, best,
        static_cast<unsigned long long>(card),
        static_cast<unsigned long long>(serialized_bytes));
  };

  if (op == "wide_or" || op == "all") bench_wide("wide_or", wide_or);
  if (op == "wide_xor" || op == "all") bench_wide("wide_xor", wide_xor);
  if (op == "wide_and" || op == "all") bench_wide("wide_and", wide_and);
  if (op == "pairwise_and" || op == "all") bench_pair("pairwise_and", true);
  if (op == "pairwise_or" || op == "all") bench_pair("pairwise_or", false);
  return 0;
}
