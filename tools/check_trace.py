"""Validate a ROARING_TPU_TRACE JSONL dump against the span schema.

CI's observability lane runs::

    python tools/check_trace.py --workload /tmp/rb_trace.jsonl

which (1) runs a small batch workload with ``ROARING_TPU_TRACE`` pointed
at the path — a clean Q=64 batch, a fault-injected pallas->xla demotion,
and a wide aggregation — then (2) validates every emitted line:

- each line parses as a JSON object with the required fields and types
  (name, span_id, parent_id, trace_id, pid, t_start, dur_ms, tags,
  events), dur_ms >= 0;
- in strict-refs mode (implied by --workload, whose dump is complete):
  every non-null parent_id / trace_id resolves to a span id present in
  the file (parents close after children, so ids are collected first).
  Plain validation tolerates dangling refs — a dump from a crashed or
  still-serving process legitimately lacks spans that never closed;
- every event carries name + t_offset_ms;
- in --workload mode, semantic checks: a ``guard.dispatch`` span exists,
  a ``demote`` event records the pallas->xla hop with its classified
  error class, and the batch.execute -> guard.dispatch nesting holds;
  every ``batch.dispatch`` span carries a ``batch.memory`` event whose
  ``predicted_bytes`` is a positive number (``residual_x`` numeric when
  measurement is available), and the workload's tiny
  ``ROARING_TPU_HBM_BUDGET`` batch produced a ``proactive_split`` event
  recording predicted vs budget bytes (docs/OBSERVABILITY.md, "Memory
  observability");
- multiset semantics (same --workload run): the pooled cross-tenant
  lane emits the ``multiset.execute`` / ``multiset.plan`` /
  ``multiset.pool`` / ``multiset.dispatch`` / ``multiset.pipeline``
  span vocabulary, every ``multiset.dispatch`` carries a
  ``multiset.memory`` event with positive ``predicted_bytes``, the
  pipeline span reports its ``launches`` / ``overlap_ratio`` tags, and
  the tiny-budget pool produced a ``site="multiset"``
  ``proactive_split`` (the forced POOL split);
- cost/SLO semantics (ISSUE 6): every ``batch.dispatch`` additionally
  carries a ``batch.cost`` event (``device_ms``, and where the backend
  reports cost analysis, ``flops`` / ``bytes_accessed`` / the
  ``roofline_fraction`` in (0, 1]); sync pooled dispatches carry the
  ``multiset.cost`` twin; and the workload's forced tiny
  ``ROARING_TPU_SLO_MS`` produced an ``slo`` event whose ``phases_ms``
  breakdown sums to within 5% of its ``wall_ms``.  On arbitrary dumps
  these event schemas are validated wherever the events appear.
- expression semantics (ISSUE 8): the --workload run drives a fused
  3-node expression pool (including one forced pallas->xla demotion,
  bit-exact) — ``expr.compile`` spans must appear with numeric
  ``nodes`` / ``depth`` tags (``reduce_nodes`` / ``combine_nodes`` on
  fused compilations) and the run must have credited
  ``rb_expr_launches_saved_total``.  On arbitrary dumps the
  ``expr.compile`` tag schema is validated wherever the span appears
  (presence is a --workload-only demand, the PR 5 convention);
- serving semantics (ISSUE 10): the --workload run drives an OVERLOADED
  continuous-batching loop (tiny per-tenant queue caps force a typed
  admission rejection; a virtually-expired deadline forces a typed
  shed) — the ``serving.admit`` / ``serving.assemble`` /
  ``serving.dispatch`` / ``serving.shed`` span vocabulary must appear,
  every ``serving.dispatch`` must carry positive ``predicted_bytes``
  and non-negative ``resident_bytes`` tags, and wherever a numeric
  ``budget_bytes`` tag is present the backpressure property
  ``predicted + resident <= budget`` must hold (the ISSUE 10
  acceptance assertion, checked on every dump);
- mesh-sharded semantics (ISSUE 7): the --workload run drives a 2x2
  dry-run mesh dispatch (the workload forces an 8-device CPU host
  platform for exactly this) — the ``sharded.*`` span vocabulary must
  appear, every ``sharded.dispatch`` must carry a ``batch.shard`` event
  naming the mesh shape (``mesh=[2,2]``, ``rows_per_shard``,
  ``shard_balance >= 1``) plus ``sharded.memory`` / ``sharded.cost``
  twins with per-shard predicted bytes.  On arbitrary dumps the
  ``batch.shard`` / ``sharded.memory`` event schemas are validated
  wherever they appear (presence is a --workload-only demand, the PR 5
  convention);
- closed-lattice semantics (ISSUE 13, docs/LATTICE.md): the
  ``lattice.warmup`` span tags (positive ``points``, a ``profile``
  string, ``sealed=true``, a ``compiled`` count) and every
  ``lattice.escape`` event's schema (``site`` / ``engine`` /
  ``in_vocabulary`` / ``compile_ms``) are validated on arbitrary
  dumps, plus range checks on the memory events'
  ``lattice_padding_fraction``; the --workload run warms a small
  vocabulary and then forces ONE deliberate out-of-lattice query,
  asserting it executes bit-exactly, emits a traced escape, AND moves
  ``rb_lattice_escapes_total`` — an escape is never silent.
- resident-queue semantics (ISSUE 16, docs/SERVING.md "Resident
  pump"): the ``mega.resident`` (served: numeric descriptor
  coordinates; demoted: a typed escape reason), ``mega.queue``
  (descriptor-ring counters, ``head >= tail >= completed``,
  ``depth <= capacity``) and ``mega.capacity_demotion`` (blown budget
  + plan stats) event schemas are validated on arbitrary dumps, as are
  the Megakernel v2 ``vscan_steps`` / ``vagg_steps`` / ``col_rows``
  counters on ``expr.megakernel`` events; the --workload run replays
  fused filter-then-aggregate pools through the persistent ring
  (zero per-pool host dispatches, bit-exact vs the host BSI oracle)
  and then WEDGES the ring for one pool, requiring at least one
  served and one demoted ``mega.resident`` event.
- durability semantics (ISSUE 17, docs/DURABILITY.md): the
  ``durability.snapshot`` span tags (tenant, monotone ``seq``,
  ``sources`` / ``columns`` counts, and — once durable — ``bytes`` +
  ``journal_kept``), the ``durability.replay`` span tags
  (``snapshot_seq`` / ``records`` / ``torn`` / ``version``) plus the
  torn recovery's ``torn_tail`` event schema, and the ``pod.migrate``
  span tags (``set_id`` / ``to`` / ``from_host``, plus ``bytes`` /
  ``blip_ms`` / ``records`` once the flip completed) are validated on
  arbitrary dumps; the --workload run crashes a journaled tenant with
  a TORN tail, recovers it bit-exactly from snapshot + journal-tail
  replay, and live-migrates a served tenant across a 2-host pod under
  traffic — all three span kinds (and the torn_tail event) must
  appear, with zero failed requests.

- observability-plane semantics (this PR, docs/OBSERVABILITY.md): the
  ``serving.request`` / ``pod.dual_write`` / ``mutation.maintenance``
  span schemas are validated on arbitrary dumps; flight-recorder dumps
  (``"kind": "rb_flight"``) and statusz documents
  (``"kind": "rb_statusz"``) — whether passed as extra paths or
  interleaved in a combined dump — validate against their own schemas;
  the --workload run additionally demands ONE trace id stitching the
  full forwarded+rerouted request lifecycle (``pod.route`` →
  ``serving.admit`` → ``pod.reroute`` → ``serving.request``), a
  schema-valid flight dump from the forced host loss, and a merged
  ``fd.statusz()`` reporting both simulated hosts.

- wire RPC semantics (ISSUE 20, docs/WIRE.md): the ``rpc.call`` /
  ``rpc.submit`` / ``rpc.result`` / ``rpc.hello`` span schemas are
  validated on arbitrary dumps (``rpc.submit`` must always carry a
  boundary ``outcome`` — admitted or typed-rejected, never silent);
  the --workload run additionally drives ONE cross-process submit over
  TCP against a real ``wire.bootstrap`` server process tracing into
  its own dump (``<path>.wire``, pooled automatically), demanding a
  single trace id that covers ``rpc.call`` → ``rpc.submit`` →
  ``serving.admit`` → ``serving.request`` across the socket.

Validation-only mode (``python tools/check_trace.py <path> [path ...]``)
checks existing dumps, e.g. captured from serving processes: several
paths validate as ONE pooled span set, so per-host dumps of a forwarded
trace stitch and cross-file parent/trace refs resolve.

Exit code 0 = valid; 1 = violations (printed one per line).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED = {
    "name": str, "span_id": str, "pid": int,
    "t_start": (int, float), "dur_ms": (int, float),
    "tags": dict, "events": list,
    # present on every span: parent_id is null on roots, trace_id is the
    # root span's own id
    "parent_id": (str, type(None)), "trace_id": str,
}

#: non-span observability artifacts this tool also validates: flight-
#: recorder dumps and statusz documents are single-line JSON docs
#: self-describing via "kind", so they can be passed as extra paths or
#: appear interleaved in a combined dump
DOC_KINDS = ("rb_flight", "rb_statusz")


def _flight_doc_errors(doc: dict, where: str) -> list[str]:
    """Schema of one flight-recorder dump (obs.flight, ``rb_flight``)."""
    errors: list[str] = []
    if not isinstance(doc.get("version"), int) or doc["version"] < 1:
        errors.append(f"{where}: flight doc without a positive integer "
                      f"version: {doc.get('version')!r}")
    if not doc.get("trigger") or not isinstance(doc["trigger"], str):
        errors.append(f"{where}: flight doc without a trigger reason")
    if not isinstance(doc.get("pid"), int):
        errors.append(f"{where}: flight doc without an integer pid")
    if not isinstance(doc.get("t"), (int, float)):
        errors.append(f"{where}: flight doc without a numeric t")
    if not isinstance(doc.get("context"), dict):
        errors.append(f"{where}: flight doc without a context object")
    events = doc.get("events")
    if not isinstance(events, list):
        errors.append(f"{where}: flight doc without an events list")
    else:
        for j, ev in enumerate(events):
            if not isinstance(ev, dict) or not ev.get("kind") \
                    or not isinstance(ev.get("t"), (int, float)):
                errors.append(f"{where}: flight event {j} malformed "
                              f"(needs kind + numeric t): {ev!r}")
    if not isinstance(doc.get("metrics_delta"), dict):
        errors.append(f"{where}: flight doc without a metrics_delta "
                      f"object")
    return errors


def _statusz_counters_errors(counters, where: str) -> list[str]:
    errors: list[str] = []
    if not isinstance(counters, dict):
        return [f"{where}: counters is not an object: {counters!r}"]
    for name, entries in counters.items():
        if not isinstance(entries, list):
            errors.append(f"{where}: counter {name!r} entries not a "
                          f"list")
            continue
        for e in entries:
            if not isinstance(e, dict) \
                    or not isinstance(e.get("labels"), dict) \
                    or not isinstance(e.get("value"), (int, float)):
                errors.append(f"{where}: counter {name!r} entry "
                              f"malformed (needs labels + numeric "
                              f"value): {e!r}")
    return errors


def _statusz_doc_errors(doc: dict, where: str) -> list[str]:
    """Schema of a statusz document (obs.statusz, ``rb_statusz``) —
    either shape: one host's local doc or the pod-level merged doc."""
    errors: list[str] = []
    if not isinstance(doc.get("version"), int) or doc["version"] < 1:
        errors.append(f"{where}: statusz doc without a positive integer "
                      f"version: {doc.get('version')!r}")
    if not isinstance(doc.get("t"), (int, float)):
        errors.append(f"{where}: statusz doc without a numeric t")
    if doc.get("merged"):
        hosts = doc.get("hosts")
        if not isinstance(hosts, dict) or not hosts:
            errors.append(f"{where}: merged statusz doc without a "
                          f"non-empty hosts map")
        else:
            for h, sub in hosts.items():
                if not isinstance(sub, dict):
                    errors.append(f"{where}: host {h!r} entry not an "
                                  f"object")
                    continue
                errors += _statusz_doc_errors(sub, f"{where}[host {h}]")
        errors += _statusz_counters_errors(doc.get("counters"), where)
    else:
        if not doc.get("host") or not isinstance(doc["host"], str):
            errors.append(f"{where}: local statusz doc without a host")
        if not isinstance(doc.get("pid"), int):
            errors.append(f"{where}: local statusz doc without an "
                          f"integer pid")
        if not isinstance(doc.get("obs"), dict):
            errors.append(f"{where}: local statusz doc without the obs "
                          f"registry snapshot")
        if not isinstance(doc.get("flight"), dict):
            errors.append(f"{where}: local statusz doc without the "
                          f"flight recorder section")
        for opt, types in (("journal", list), ("lattice", dict),
                           ("sections", dict)):
            if opt in doc and not isinstance(doc[opt], types):
                errors.append(f"{where}: statusz section {opt!r} has "
                              f"type {type(doc[opt]).__name__}")
    return errors


def validate_doc(doc: dict, where: str) -> list[str]:
    """Dispatch a self-describing observability doc to its schema."""
    kind = doc.get("kind")
    if kind == "rb_flight":
        return _flight_doc_errors(doc, where)
    if kind == "rb_statusz":
        return _statusz_doc_errors(doc, where)
    return [f"{where}: unknown doc kind {kind!r}"]


def _parse_file(path: str):
    """Parse one JSONL artifact into span records + self-describing
    docs (flight / statusz lines validate their own schema in place).
    Returns ``(errors, spans)`` where spans are ``(where, rec)``."""
    errors: list[str] = []
    spans: list = []
    try:
        with open(path) as f:
            raw = f.readlines()
    except OSError as e:
        return [f"cannot read {path}: {e}"], spans
    if not raw:
        return [f"{path} is empty — no spans were emitted"], spans
    for i, line in enumerate(raw, 1):
        where = f"{path}:{i}"
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"{where}: not valid JSON ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{where}: not a JSON object")
            continue
        if rec.get("kind") in DOC_KINDS:
            errors += validate_doc(rec, where)
            continue
        for field, types in REQUIRED.items():
            if field not in rec:
                errors.append(f"{where}: missing field {field!r}")
            elif not isinstance(rec[field], types):
                errors.append(
                    f"{where}: field {field!r} has type "
                    f"{type(rec[field]).__name__}, want {types}")
        if not rec.get("name"):
            errors.append(f"{where}: empty span name")
        if isinstance(rec.get("dur_ms"), (int, float)) and rec["dur_ms"] < 0:
            errors.append(f"{where}: negative dur_ms {rec['dur_ms']}")
        for j, ev in enumerate(rec.get("events") or []):
            if not isinstance(ev, dict) or not ev.get("name") \
                    or not isinstance(ev.get("t_offset_ms"), (int, float)):
                errors.append(
                    f"{where}: event {j} malformed (needs name + "
                    f"t_offset_ms): {ev!r}")
        spans.append((where, rec))
    return errors, spans


def validate(paths, workload_semantics: bool = False,
             strict_refs: bool | None = None,
             budget_semantics: bool = False) -> list[str]:
    """``paths`` is one dump path or a list of them: multiple hosts'
    dumps (plus flight/statusz artifacts) validate as ONE pooled span
    set, so a trace forwarded across processes stitches — parent/trace
    refs resolve against the union, and the propagation semantics see
    the whole pod.  ``strict_refs`` controls whether a
    parent_id/trace_id that resolves to no span in the union is a
    violation.  Defaults to ``workload_semantics``: the CI workload
    produces a COMPLETE dump, but a dump captured from a crashed or
    still-serving process legitimately lacks the enclosing spans that
    never closed (spans flush on close, parents after children) — those
    dumps must validate."""
    if strict_refs is None:
        strict_refs = workload_semantics
    errors: list[str] = []
    spans: list = []
    for path in ([paths] if isinstance(paths, str) else list(paths)):
        errs, recs = _parse_file(path)
        errors += errs
        spans += recs
    if strict_refs:
        ids = {s.get("span_id") for _, s in spans}
        for i, s in spans:
            for ref in ("parent_id", "trace_id"):
                v = s.get(ref)
                if v is not None and v not in ids:
                    errors.append(
                        f"{i}: {ref} {v!r} not present in the dump")
    if workload_semantics:
        errors += _workload_semantics([s for _, s in spans],
                                      budget_semantics)
    else:
        # arbitrary dumps still get per-EVENT schema checks for whatever
        # pooled spans they happen to contain (an existing
        # multiset.memory event must be well-formed); completeness and
        # span presence are only demanded of the --workload run
        errors += _multiset_semantics([s for _, s in spans])
        errors += _cost_slo_semantics([s for _, s in spans])
        errors += _sharded_semantics([s for _, s in spans])
        errors += _expr_semantics([s for _, s in spans])
        errors += _serving_semantics([s for _, s in spans])
        errors += _mutation_semantics([s for _, s in spans])
        errors += _lattice_semantics([s for _, s in spans])
        errors += _pod_semantics([s for _, s in spans])
        errors += _analytics_semantics([s for _, s in spans])
        errors += _resident_semantics([s for _, s in spans])
        errors += _durability_semantics([s for _, s in spans])
        errors += _propagation_semantics([s for _, s in spans])
        errors += _rpc_semantics([s for _, s in spans])
    return errors


def _require_proactive_split(spans: list[dict], site: str,
                             case: str) -> list[str]:
    """One site's forced-budget-split contract: some ``proactive_split``
    event at ``site`` must carry numeric predicted_bytes > budget_bytes."""
    splits = [ev for s in spans for ev in s.get("events", [])
              if ev.get("name") == "proactive_split"
              and ev.get("site") == site]
    if not any(isinstance(ev.get("predicted_bytes"), (int, float))
               and isinstance(ev.get("budget_bytes"), (int, float))
               and ev["predicted_bytes"] > ev["budget_bytes"]
               for ev in splits):
        return [f"no site={site} proactive_split event with "
                f"predicted_bytes > budget_bytes ({case}; "
                f"saw: {splits!r})"]
    return []


def _workload_semantics(spans: list[dict],
                        budget_semantics: bool = False) -> list[str]:
    errors: list[str] = []
    by_id = {s["span_id"]: s for s in spans if "span_id" in s}
    dispatches = [s for s in spans if s.get("name") == "guard.dispatch"]
    if not dispatches:
        errors.append("no guard.dispatch span — the guarded query path "
                      "was not traced")
    demotes = [ev for s in dispatches for ev in s.get("events", [])
               if ev.get("name") == "demote"]
    if not any(ev.get("engine_from") == "pallas"
               and ev.get("engine_to") == "xla"
               and ev.get("error_class") == "EngineLoweringError"
               for ev in demotes):
        errors.append(
            "no demote event with engine_from=pallas engine_to=xla "
            f"error_class=EngineLoweringError (saw: {demotes!r})")
    nested = [s for s in dispatches
              if by_id.get(s.get("parent_id"), {}).get("name")
              == "batch.execute"]
    if not nested:
        errors.append("no guard.dispatch span nested under batch.execute")
    # memory accounting: every device dispatch must report predicted (and,
    # where the backend exposes memory_analysis, measured) bytes
    batch_dispatches = [s for s in spans
                        if s.get("name") == "batch.dispatch"]
    mems = [ev for s in batch_dispatches for ev in s.get("events", [])
            if ev.get("name") == "batch.memory"]
    if not batch_dispatches:
        errors.append("no batch.dispatch span — the batch path was not "
                      "traced")
    elif len(mems) < len(batch_dispatches):
        errors.append(
            f"{len(batch_dispatches) - len(mems)} batch.dispatch span(s) "
            "lack a batch.memory event")
    for ev in mems:
        p = ev.get("predicted_bytes")
        if not isinstance(p, (int, float)) or p <= 0:
            errors.append(f"batch.memory event with non-positive "
                          f"predicted_bytes: {ev!r}")
        if ("residual_x" in ev
                and not isinstance(ev["residual_x"], (int, float))):
            errors.append(f"batch.memory residual_x not numeric: {ev!r}")
    if budget_semantics:
        # only the --workload run guarantees a budget case (it forces one
        # with a tiny ROARING_TPU_HBM_BUDGET); arbitrary dumps need not
        # contain a proactive split to be valid
        errors += _require_proactive_split(
            spans, "batch_engine", "the ROARING_TPU_HBM_BUDGET workload "
            "case")
    errors += _multiset_semantics(spans, budget_semantics,
                                  complete=True)
    errors += _cost_slo_semantics(spans, complete=True,
                                  require_miss=budget_semantics)
    errors += _sharded_semantics(spans, require=budget_semantics,
                                 complete=True)
    errors += _expr_semantics(spans, require=budget_semantics)
    errors += _serving_semantics(spans, require=budget_semantics)
    errors += _mutation_semantics(spans, require=budget_semantics)
    errors += _lattice_semantics(spans, require=budget_semantics)
    errors += _pod_semantics(spans, require=budget_semantics)
    errors += _analytics_semantics(spans, require=budget_semantics)
    errors += _resident_semantics(spans, require=budget_semantics)
    errors += _durability_semantics(spans, require=budget_semantics)
    errors += _propagation_semantics(spans, require=budget_semantics)
    errors += _rpc_semantics(spans, require=budget_semantics)
    return errors


def _analytics_semantics(spans: list[dict],
                         require: bool = False) -> list[str]:
    """The device-native analytics lane's span/event vocabulary
    (roaringbitmap_tpu.analytics, docs/ANALYTICS.md).  Arbitrary dumps
    validate the ``analytics.column`` span, the dispatch-site
    ``analytics.scan`` event, and the ``analytics.delta`` event SCHEMAS
    wherever they appear; ``require`` (the --workload run, which drives
    one fused filter-then-aggregate OLAP query plus a column delta)
    additionally demands an attached-column span, at least one scan
    event carrying an aggregate, and the delta's exact-invalidation
    record."""
    errors: list[str] = []
    col_spans = [s for s in spans
                 if s.get("name") == "analytics.column"]
    for s in col_spans:
        tags = s.get("tags") or {}
        if tags.get("kind") not in ("bsi_column", "range_column"):
            errors.append(f"analytics.column span with unknown kind: "
                          f"{tags!r}")
        if not tags.get("col"):
            errors.append(f"analytics.column span without a col tag: "
                          f"{tags!r}")
        for field in ("uid", "depth", "depth_pad", "keys", "hbm_bytes"):
            if not isinstance(tags.get(field), int) or tags[field] < 0:
                errors.append(f"analytics.column span without a numeric "
                              f"{field} tag: {tags!r}")
        if isinstance(tags.get("depth_pad"), int) \
                and isinstance(tags.get("depth"), int) \
                and tags["depth_pad"] < max(1, tags["depth"]):
            errors.append(f"analytics.column depth_pad below depth "
                          f"(pow2 padding broken): {tags!r}")
    scans = [ev for s in spans for ev in s.get("events", [])
             if ev.get("name") == "analytics.scan"]
    for ev in scans:
        if not ev.get("site"):
            errors.append(f"analytics.scan event without a site: {ev!r}")
        for field in ("scans", "aggs", "bsi_depth"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"analytics.scan event without a numeric "
                              f"{field}: {ev!r}")
        if (ev.get("scans") or 0) + (ev.get("aggs") or 0) < 1:
            errors.append(f"analytics.scan event recording no analytics "
                          f"steps: {ev!r}")
    deltas = [ev for s in spans for ev in s.get("events", [])
              if ev.get("name") == "analytics.delta"]
    for ev in deltas:
        if not ev.get("col") or ev.get("kind") not in ("bsi_column",
                                                       "range_column"):
            errors.append(f"analytics.delta event without col/kind: "
                          f"{ev!r}")
        for field in ("uid", "version", "structure_version",
                      "cache_dropped", "hbm_bytes"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"analytics.delta event without a numeric "
                              f"{field}: {ev!r}")
        if isinstance(ev.get("version"), int) and ev["version"] < 1:
            errors.append(f"analytics.delta event with a pre-bump "
                          f"version: {ev!r}")
    if require:
        if not col_spans:
            errors.append("no analytics.column span — the workload's "
                          "column attach was not traced")
        if not any((ev.get("aggs") or 0) >= 1 for ev in scans):
            errors.append("no analytics.scan event with aggs >= 1 — "
                          "the workload's fused filter-then-aggregate "
                          "query did not record")
        if not deltas:
            errors.append("no analytics.delta event — the workload's "
                          "column delta did not record")
    return errors


def _pod_semantics(spans: list[dict], require: bool = False) -> list[str]:
    """The pod data plane's span vocabulary (parallel.podmesh +
    serving.frontdoor, docs/POD.md).  Arbitrary dumps validate the
    ``pod.place`` / ``pod.route`` / ``pod.reroute`` span schemas
    wherever they appear; ``require`` (the --workload run, which routes
    a simulated 2-host pod and forces one host drop) additionally
    demands all three — a host loss must be traced, never silent."""
    errors: list[str] = []
    places = [s for s in spans if s.get("name") == "pod.place"]
    for s in places:
        tags = s.get("tags") or {}
        if not isinstance(tags.get("hosts"), int) or tags["hosts"] < 1:
            errors.append(f"pod.place span without a positive hosts "
                          f"tag: {tags!r}")
        if not isinstance(tags.get("tenants"), int) \
                or tags["tenants"] < 0:
            errors.append(f"pod.place span without a tenants count: "
                          f"{tags!r}")
        if not isinstance(tags.get("regimes"), dict):
            errors.append(f"pod.place span without a regimes "
                          f"histogram: {tags!r}")
        bph = tags.get("bytes_per_host")
        if not isinstance(bph, list) or not all(
                isinstance(b, (int, float)) and b >= 0 for b in bph):
            errors.append(f"pod.place span without non-negative "
                          f"bytes_per_host: {tags!r}")
    routes = [s for s in spans if s.get("name") == "pod.route"]
    for s in routes:
        tags = s.get("tags") or {}
        if not isinstance(tags.get("set_id"), int) or tags["set_id"] < 0:
            errors.append(f"pod.route span without a set_id: {tags!r}")
        if not tags.get("host"):
            errors.append(f"pod.route span without a host: {tags!r}")
        if not isinstance(tags.get("forwarded"), bool):
            errors.append(f"pod.route span without the forwarded "
                          f"verdict: {tags!r}")
        if not tags.get("regime"):
            errors.append(f"pod.route span without a regime: {tags!r}")
    reroutes = [s for s in spans if s.get("name") == "pod.reroute"]
    for s in reroutes:
        tags = s.get("tags") or {}
        if not isinstance(tags.get("set_id"), int) or tags["set_id"] < 0:
            errors.append(f"pod.reroute span without a set_id: {tags!r}")
        if not tags.get("to"):
            errors.append(f"pod.reroute span without a destination: "
                          f"{tags!r}")
        if not tags.get("reason"):
            errors.append(f"pod.reroute span without a reason: {tags!r}")
        if tags.get("rung") != "reroute":
            errors.append(f"pod.reroute span not tagged with the "
                          f"reroute rung: {tags!r}")
    if require:
        if not places:
            errors.append("no pod.place span — the workload's pod "
                          "placement was not traced")
        if not any((s.get("tags") or {}).get("forwarded") is True
                   for s in routes):
            errors.append("no forwarded pod.route span — the workload's "
                          "mis-routed arrival did not record")
        if not reroutes:
            errors.append("no pod.reroute span — the workload's forced "
                          "host drop did not record")
    return errors


#: the request-lifecycle span names one stitched cross-host trace must
#: contain: admission on the entry host, the routing hop, the reroute
#: after a host loss, and the per-request outcome span on the host that
#: finally served it (obs.trace inject/extract, docs/OBSERVABILITY.md
#: "Cross-host trace propagation")
STITCHED_NAMES = ("pod.route", "serving.admit", "pod.reroute",
                  "serving.request")


def _propagation_semantics(spans: list[dict],
                           require: bool = False) -> list[str]:
    """Cross-host trace propagation (this PR's tentpole).  Arbitrary
    dumps validate the request-scoped span schemas wherever they
    appear — ``serving.request`` (the per-ticket outcome span), the
    migration ``pod.dual_write``, and the worker-thread
    ``mutation.maintenance`` span; ``require`` (the --workload run,
    which forwards an arrival and then drops its host) additionally
    demands ONE trace id whose spans cover the full forwarded+rerouted
    lifecycle — the stitched-trace acceptance assertion."""
    errors: list[str] = []
    for s in spans:
        if s.get("name") != "serving.request":
            continue
        tags = s.get("tags") or {}
        if not tags.get("outcome"):
            errors.append(f"serving.request span without an outcome: "
                          f"{tags!r}")
        if "wall_ms" in tags \
                and not isinstance(tags["wall_ms"], (int, float)):
            errors.append(f"serving.request wall_ms not numeric: "
                          f"{tags!r}")
    for s in spans:
        if s.get("name") != "pod.dual_write":
            continue
        tags = s.get("tags") or {}
        if not isinstance(tags.get("set_id"), int):
            errors.append(f"pod.dual_write span without a set_id: "
                          f"{tags!r}")
        if "to" not in tags:
            errors.append(f"pod.dual_write span without a destination: "
                          f"{tags!r}")
    for s in spans:
        if s.get("name") != "mutation.maintenance":
            continue
        tags = s.get("tags") or {}
        if not tags.get("kind"):
            errors.append(f"mutation.maintenance span without a job "
                          f"kind: {tags!r}")
        if not isinstance(tags.get("ok"), bool):
            errors.append(f"mutation.maintenance span without an ok "
                          f"verdict: {tags!r}")
    if require:
        by_trace: dict = {}
        for s in spans:
            tid = s.get("trace_id")
            if tid:
                by_trace.setdefault(tid, set()).add(s.get("name"))
        stitched = [tid for tid, names in by_trace.items()
                    if set(STITCHED_NAMES) <= names]
        if not stitched:
            best = max(by_trace.values(),
                       key=lambda n: len(set(STITCHED_NAMES) & n),
                       default=set())
            errors.append(
                "no single trace id stitches the forwarded+rerouted "
                f"request lifecycle {STITCHED_NAMES} — closest trace "
                f"held {sorted(set(STITCHED_NAMES) & best)}")
    return errors


#: one trace id must cover the client's call, the server's boundary
#: decision, admission, and the per-ticket outcome — across the SOCKET
#: (the client and server dumps are separate files pooled by main()).
WIRE_STITCHED_NAMES = ("rpc.call", "rpc.submit", "serving.admit",
                       "serving.request")


def _rpc_semantics(spans: list[dict], require: bool = False) -> list[str]:
    """Binary wire RPC vocabulary (ISSUE 20, wire/, docs/WIRE.md).
    Arbitrary dumps validate the ``rpc.*`` span schemas wherever they
    appear: ``rpc.call`` (client-side framing), ``rpc.submit`` (the
    server boundary decision — ``outcome`` is mandatory: every inbound
    submit is admitted or typed-rejected, never silent), ``rpc.result``
    (completion delivery, outcome = the ticket's terminal status) and
    ``rpc.hello`` (handshake verdict).  ``require`` (the --workload
    run, which drives one cross-process submit over TCP with the server
    process tracing into its own dump) additionally demands ONE trace
    id whose pooled spans cover ``WIRE_STITCHED_NAMES`` — proof trace
    propagation survives the socket."""
    errors: list[str] = []
    for s in spans:
        name = s.get("name")
        if name not in ("rpc.call", "rpc.submit", "rpc.result",
                        "rpc.hello"):
            continue
        tags = s.get("tags") or {}
        if name == "rpc.call":
            if not isinstance(tags.get("req_id"), int):
                errors.append(
                    f"rpc.call span without an integer req_id: {tags!r}")
            if not isinstance(tags.get("set_id"), int):
                errors.append(
                    f"rpc.call span without an integer set_id: {tags!r}")
        elif name == "rpc.submit":
            if not isinstance(tags.get("req_id"), int):
                errors.append(f"rpc.submit span without an integer "
                              f"req_id: {tags!r}")
            if not tags.get("tenant"):
                errors.append(
                    f"rpc.submit span without a tenant: {tags!r}")
            if not tags.get("outcome"):
                errors.append(f"rpc.submit span without a boundary "
                              f"outcome (silent drop?): {tags!r}")
        elif name == "rpc.result":
            if not isinstance(tags.get("req_id"), int):
                errors.append(f"rpc.result span without an integer "
                              f"req_id: {tags!r}")
            if not tags.get("outcome"):
                errors.append(f"rpc.result span without the ticket's "
                              f"terminal outcome: {tags!r}")
        elif name == "rpc.hello":
            if not tags.get("outcome"):
                errors.append(f"rpc.hello span without a handshake "
                              f"verdict: {tags!r}")
        # frame_bytes is written after the encode — type-check only
        # when present (a span closed on an encode error lacks it)
        if "frame_bytes" in tags \
                and not isinstance(tags["frame_bytes"], (int, float)):
            errors.append(f"{name} frame_bytes not numeric: {tags!r}")
    if require:
        by_trace: dict = {}
        for s in spans:
            tid = s.get("trace_id")
            if tid:
                by_trace.setdefault(tid, set()).add(s.get("name"))
        if not any(set(WIRE_STITCHED_NAMES) <= names
                   for names in by_trace.values()):
            best = max(by_trace.values(),
                       key=lambda n: len(set(WIRE_STITCHED_NAMES) & n),
                       default=set())
            errors.append(
                "no single trace id stitches the cross-process wire "
                f"submit {WIRE_STITCHED_NAMES} — closest trace held "
                f"{sorted(set(WIRE_STITCHED_NAMES) & best)}")
    return errors


def _durability_semantics(spans: list[dict],
                          require: bool = False) -> list[str]:
    """Durable-tenant vocabulary (ISSUE 17, mutation.durability +
    serving.migration, docs/DURABILITY.md).  Arbitrary dumps validate
    the ``durability.snapshot`` / ``durability.replay`` / ``pod.migrate``
    span schemas (and the ``torn_tail`` event) wherever they appear;
    tags written AFTER the risky work (``bytes`` / ``journal_kept`` on
    snapshots, ``snapshot_seq``..``version`` on replays, the blip
    stats on migrations) are type-checked only when present — a span
    that closed on an exception legitimately lacks them.  ``require``
    (the --workload run, which crashes a journaled tenant with a torn
    tail, recovers it, and live-migrates a served tenant) additionally
    demands a completed snapshot, a torn replay with its torn_tail
    event, and a completed migration flip."""
    errors: list[str] = []
    snaps = [s for s in spans if s.get("name") == "durability.snapshot"]
    for s in snaps:
        tags = s.get("tags") or {}
        if not tags.get("tenant"):
            errors.append(f"durability.snapshot span without a tenant: "
                          f"{tags!r}")
        for field in ("seq", "sources", "columns"):
            if not isinstance(tags.get(field), int) or tags[field] < 0:
                errors.append(f"durability.snapshot span without a "
                              f"non-negative {field} tag: {tags!r}")
        for field in ("bytes", "journal_kept"):
            if field in tags and (not isinstance(tags[field], int)
                                  or tags[field] < 0):
                errors.append(f"durability.snapshot {field} tag not a "
                              f"non-negative int: {tags!r}")
    replays = [s for s in spans if s.get("name") == "durability.replay"]
    for s in replays:
        tags = s.get("tags") or {}
        if not tags.get("tenant"):
            errors.append(f"durability.replay span without a tenant: "
                          f"{tags!r}")
        for field in ("snapshot_seq", "records", "version"):
            if field in tags and (not isinstance(tags[field], int)
                                  or tags[field] < 0):
                errors.append(f"durability.replay {field} tag not a "
                              f"non-negative int: {tags!r}")
        if "torn" in tags and not isinstance(tags["torn"], bool):
            errors.append(f"durability.replay torn tag not a bool: "
                          f"{tags!r}")
    torn_evs = [ev for s in replays for ev in s.get("events", [])
                if ev.get("name") == "torn_tail"]
    for ev in torn_evs:
        if not isinstance(ev.get("truncated_bytes"), int) \
                or ev["truncated_bytes"] < 1:
            errors.append(f"torn_tail event without positive "
                          f"truncated_bytes: {ev!r}")
        if not isinstance(ev.get("valid_end"), int) \
                or ev["valid_end"] < 0:
            errors.append(f"torn_tail event without a non-negative "
                          f"valid_end: {ev!r}")
    migrates = [s for s in spans if s.get("name") == "pod.migrate"]
    for s in migrates:
        tags = s.get("tags") or {}
        if not isinstance(tags.get("set_id"), int) or tags["set_id"] < 0:
            errors.append(f"pod.migrate span without a set_id: {tags!r}")
        for field in ("to", "from_host"):
            if field in tags and not (isinstance(tags[field], str)
                                      and tags[field]):
                errors.append(f"pod.migrate {field} tag not a non-empty "
                              f"string: {tags!r}")
        for field in ("bytes", "records"):
            if field in tags and (not isinstance(tags[field], int)
                                  or tags[field] < 0):
                errors.append(f"pod.migrate {field} tag not a "
                              f"non-negative int: {tags!r}")
        if "blip_ms" in tags and (not isinstance(tags["blip_ms"],
                                                 (int, float))
                                  or tags["blip_ms"] < 0):
            errors.append(f"pod.migrate blip_ms tag not a non-negative "
                          f"number: {tags!r}")
    if require:
        if not any("journal_kept" in (s.get("tags") or {})
                   for s in snaps):
            errors.append("no completed durability.snapshot span — the "
                          "workload's journaled tenant never snapshot")
        if not any((s.get("tags") or {}).get("torn") is True
                   for s in replays):
            errors.append("no torn durability.replay span — the "
                          "workload's torn-tail crash recovery did not "
                          "record")
        if not torn_evs:
            errors.append("no torn_tail event — the torn recovery's "
                          "truncation was not traced")
        if not any(isinstance((s.get("tags") or {}).get("blip_ms"),
                              (int, float)) for s in migrates):
            errors.append("no completed pod.migrate span — the "
                          "workload's live migration flip did not "
                          "record")
    return errors


def _lattice_semantics(spans: list[dict],
                       require: bool = False) -> list[str]:
    """Closed-lattice vocabulary (ISSUE 13, docs/LATTICE.md): validate
    the ``lattice.warmup`` span tags and every ``lattice.escape``
    event's schema wherever they appear; ``require`` (the --workload
    run, which warms a lattice and then forces one deliberate
    out-of-lattice query) additionally demands both exist — an escape
    must be traced and metered, never silent."""
    errors: list[str] = []
    warmups = [s for s in spans if s.get("name") == "lattice.warmup"]
    for s in warmups:
        tags = s.get("tags", {})
        if not isinstance(tags.get("points"), int) or tags["points"] < 1:
            errors.append(f"lattice.warmup span without a positive "
                          f"points tag: {tags!r}")
        if not isinstance(tags.get("profile"), str):
            errors.append(f"lattice.warmup span without a profile tag: "
                          f"{tags!r}")
        if tags.get("sealed") is not True:
            errors.append(f"lattice.warmup span did not seal the "
                          f"lattice: {tags!r}")
        if not isinstance(tags.get("compiled"), int):
            errors.append(f"lattice.warmup span without a compiled "
                          f"count: {tags!r}")
    escapes = [ev for s in spans for ev in s.get("events", [])
               if ev.get("name") == "lattice.escape"]
    for ev in escapes:
        if not isinstance(ev.get("site"), str) or not ev["site"]:
            errors.append(f"lattice.escape event without a site: {ev!r}")
        if not isinstance(ev.get("engine"), str):
            errors.append(f"lattice.escape event without an engine: "
                          f"{ev!r}")
        if not isinstance(ev.get("in_vocabulary"), bool):
            errors.append(f"lattice.escape event without the "
                          f"in_vocabulary verdict: {ev!r}")
        if not isinstance(ev.get("compile_ms"), (int, float)) \
                or ev["compile_ms"] < 0:
            errors.append(f"lattice.escape event without a compile_ms "
                          f"cost: {ev!r}")
    # padding accounting rides the memory events of snapped dispatches
    for s in spans:
        for ev in s.get("events", []):
            if "lattice_padding_fraction" not in ev:
                continue
            f = ev["lattice_padding_fraction"]
            if not isinstance(f, (int, float)) or not 0.0 <= f <= 1.0:
                errors.append(f"memory event with out-of-range "
                              f"lattice_padding_fraction: {ev!r}")
    if require:
        if not warmups:
            errors.append("no lattice.warmup span — the workload's "
                          "lattice boot was not traced")
        if not escapes:
            errors.append("no lattice.escape event — the workload's "
                          "deliberate out-of-lattice query was not "
                          "traced")
    return errors


def _mutation_semantics(spans: list[dict],
                        require: bool = False) -> list[str]:
    """The mutation subsystem's span/event vocabulary
    (roaringbitmap_tpu.mutation, docs/MUTATION.md).  Arbitrary dumps
    validate the ``mutation.delta`` span and ``expr.cache`` event
    schemas wherever they appear; ``require`` (the --workload run, which
    drives one in-place patch, one escalated repack, and a cache-served
    re-execute) additionally demands both delta modes and at least one
    cache hit."""
    errors: list[str] = []
    deltas = [s for s in spans if s.get("name") == "mutation.delta"]
    for s in deltas:
        tags = s.get("tags") or {}
        if tags.get("status") == "error":
            # a delta killed mid-apply (ISSUE 17's injected crashes)
            # closes with status=error and never reaches the post-apply
            # mode/version tagging — that partial span is legitimate
            if not tags.get("error_class"):
                errors.append(f"error-status mutation.delta span "
                              f"without an error_class: {tags!r}")
            continue
        if tags.get("mode") not in ("patch", "repack", "repack_queued",
                                    "noop"):
            errors.append(f"mutation.delta span with bad mode: {tags!r}")
        if not isinstance(tags.get("version"), int) \
                or tags["version"] < 0:
            errors.append(f"mutation.delta span without a numeric "
                          f"version tag: {tags!r}")
        if tags.get("mode") == "patch" and (
                not isinstance(tags.get("rows"), int)
                or tags["rows"] < 1):
            errors.append(f"patch-mode mutation.delta span without a "
                          f"positive rows tag: {tags!r}")
        for field in ("values_added", "values_removed"):
            if not isinstance(tags.get(field), int) or tags[field] < 0:
                errors.append(f"mutation.delta span without a numeric "
                              f"{field} tag: {tags!r}")
    caches = [ev for s in spans for ev in s.get("events", [])
              if ev.get("name") == "expr.cache"]
    for ev in caches:
        for field in ("hits", "misses"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"expr.cache event without a numeric "
                              f"{field}: {ev!r}")
    if require:
        modes = {(s.get("tags") or {}).get("mode") for s in deltas}
        if "patch" not in modes:
            errors.append("no patch-mode mutation.delta span — the "
                          "in-place delta workload case did not record")
        if "repack" not in modes:
            errors.append("no repack-mode mutation.delta span — the "
                          "escalated repack workload case did not "
                          "record")
        if not any(ev.get("hits", 0) >= 1 for ev in caches):
            errors.append("no expr.cache event with hits >= 1 — the "
                          "result-cache workload case did not record")
    return errors


def _serving_semantics(spans: list[dict],
                       require: bool = False) -> list[str]:
    """The serving loop's span vocabulary (roaringbitmap_tpu.serving,
    docs/SERVING.md).  Arbitrary dumps validate the schemas wherever the
    spans appear — including the HBM backpressure PROPERTY on every
    ``serving.dispatch`` that carries a numeric budget tag; ``require``
    (the --workload run, which drives an overloaded loop) additionally
    demands the span vocabulary, a rejected admission, and a typed
    shed."""
    errors: list[str] = []
    dispatches = [s for s in spans if s.get("name") == "serving.dispatch"]
    for s in dispatches:
        tags = s.get("tags") or {}
        if not isinstance(tags.get("pool"), int) or tags["pool"] < 1:
            errors.append(f"serving.dispatch span without a positive "
                          f"pool tag: {tags!r}")
        p = tags.get("predicted_bytes")
        if not isinstance(p, (int, float)) or p <= 0:
            errors.append(f"serving.dispatch span without positive "
                          f"predicted_bytes: {tags!r}")
        r = tags.get("resident_bytes")
        if not isinstance(r, (int, float)) or r < 0:
            errors.append(f"serving.dispatch span without non-negative "
                          f"resident_bytes: {tags!r}")
        b = tags.get("budget_bytes")
        if isinstance(b, (int, float)) \
                and isinstance(p, (int, float)) \
                and isinstance(r, (int, float)) and p + r > b:
            errors.append(
                "serving.dispatch violates the backpressure property "
                f"predicted + resident <= budget: {tags!r}")
    sheds = [s for s in spans if s.get("name") == "serving.shed"]
    for s in sheds:
        tags = s.get("tags") or {}
        if not tags.get("reason") or not tags.get("tenant"):
            errors.append(f"serving.shed span lacks reason/tenant tags: "
                          f"{tags!r}")
    admits = [s for s in spans if s.get("name") == "serving.admit"]
    for s in admits:
        out = (s.get("tags") or {}).get("outcome")
        if out not in ("admitted", "rejected"):
            errors.append(f"serving.admit span outcome not "
                          f"admitted/rejected: {s.get('tags')!r}")
    if require:
        for required in ("serving.admit", "serving.assemble",
                         "serving.dispatch", "serving.shed"):
            if not any(s.get("name") == required for s in spans):
                errors.append(f"no {required} span — the serving loop "
                              "was not traced")
        if not any((s.get("tags") or {}).get("outcome") == "rejected"
                   for s in admits):
            errors.append("no rejected serving.admit span — the forced "
                          "queue-cap admission case did not record")
        if not any((s.get("tags") or {}).get("reason") == "expired"
                   for s in sheds):
            errors.append("no expired serving.shed span — the forced "
                          "deadline-shed case did not record")
    return errors


def _expr_semantics(spans: list[dict], require: bool = False) -> list[str]:
    """The expression compiler's span vocabulary (parallel.expr,
    docs/EXPRESSIONS.md).  Arbitrary dumps validate the ``expr.compile``
    tag schema — and the ``expr.megakernel`` dispatch-event schema
    (ops.megakernel, docs/EXPRESSIONS.md "Megakernel lowering") —
    wherever they appear; ``require`` (the --workload run, which drives
    a fused 3-node expression clean, demoted AND through the megakernel
    rung) demands at least one fused compilation and one megakernel
    dispatch event."""
    errors: list[str] = []
    megas = [ev for s in spans for ev in s.get("events", [])
             if ev.get("name") == "expr.megakernel"]
    for ev in megas:
        if ev.get("mode") not in ("full", "combine"):
            errors.append(f"expr.megakernel event with bad mode: {ev!r}")
        for field in ("steps", "slots", "vmem_bytes", "card_rows",
                      "sections"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"expr.megakernel event without a numeric "
                              f"{field}: {ev!r}")
        if not (isinstance(ev.get("steps"), int) and ev["steps"] > 0):
            errors.append(f"expr.megakernel event with no instructions: "
                          f"{ev!r}")
        # Megakernel v2 analytics counters (VSCAN/VAGG opcodes + the
        # column-operand bank) — optional on pre-v2 dumps, validated
        # wherever present
        for field in ("vscan_steps", "vagg_steps", "col_rows"):
            if field in ev and (not isinstance(ev[field], int)
                                or ev[field] < 0):
                errors.append(f"expr.megakernel event with non-numeric "
                              f"{field}: {ev!r}")
    compiles = [s for s in spans if s.get("name") == "expr.compile"]
    for s in compiles:
        tags = s.get("tags") or {}
        # a value-only analytics DAG (e.g. a bare column predicate or a
        # whole-domain aggregate, as lattice warmup synthesizes) has no
        # boolean nodes — nodes may be 0 iff value_steps carries the
        # work instead
        if not isinstance(tags.get("nodes"), int) or tags["nodes"] < 1:
            if not (tags.get("nodes") == 0
                    and isinstance(tags.get("value_steps"), int)
                    and tags["value_steps"] >= 1):
                errors.append(f"expr.compile span without a positive "
                              f"nodes tag: {tags!r}")
        if not isinstance(tags.get("depth"), int) or tags["depth"] < 0:
            errors.append(f"expr.compile span without a numeric depth "
                          f"tag: {tags!r}")
        if tags.get("kind") == "fused":
            for field in ("reduce_nodes", "combine_nodes"):
                if not isinstance(tags.get(field), int) \
                        or tags[field] < 0:
                    errors.append(
                        f"fused expr.compile span without a numeric "
                        f"{field} tag: {tags!r}")
    if require:
        if not compiles:
            errors.append("no expr.compile span — the expression "
                          "workload was not traced")
        elif not any((s.get("tags") or {}).get("kind") == "fused"
                     for s in compiles):
            errors.append(
                "no fused expr.compile span — the 3-node expression "
                f"did not fuse (saw kinds: "
                f"{[(s.get('tags') or {}).get('kind') for s in compiles]!r})")
        if not megas:
            errors.append("no expr.megakernel event — the one-kernel "
                          "workload case did not record")
        elif not any(isinstance(ev.get("vagg_steps"), int)
                     and ev["vagg_steps"] >= 1
                     and isinstance(ev.get("vscan_steps"), int)
                     and ev["vscan_steps"] >= 1 for ev in megas):
            errors.append(
                "no expr.megakernel event with vscan_steps >= 1 and "
                "vagg_steps >= 1 — the fused filter-then-aggregate "
                "workload case did not run in the one-kernel rung")
    return errors


_RESIDENT_REASONS = ("vocabulary", "wedged", "backend", "inactive")
_CAPACITY_REASONS = ("slots", "steps", "unknown")


def _resident_semantics(spans: list[dict],
                        require: bool = False) -> list[str]:
    """The persistent device-resident pool queue's event vocabulary
    (serving.resident, docs/SERVING.md "Resident pump"): every
    ``mega.resident`` event records one pool's outcome (``served`` with
    its descriptor coordinates, or ``demoted`` with a typed escape
    reason), every ``mega.queue`` event snapshots the descriptor ring's
    counters, and every ``mega.capacity_demotion`` event names the blown
    budget.  Arbitrary dumps validate the schemas wherever they appear;
    ``require`` (the --workload run, which replays fused-analytics pools
    through the ring AND forces one wedged-ring escape) demands at least
    one served pool, one demoted pool, and one ring snapshot."""
    errors: list[str] = []
    residents = [ev for s in spans for ev in s.get("events", [])
                 if ev.get("name") == "mega.resident"]
    for ev in residents:
        if not isinstance(ev.get("site"), str):
            errors.append(f"mega.resident event without a site: {ev!r}")
        outcome = ev.get("outcome")
        if outcome == "served":
            for field in ("sig_id", "seq", "slot", "pool"):
                if not isinstance(ev.get(field), int) or ev[field] < 0:
                    errors.append(f"served mega.resident event without "
                                  f"a numeric {field}: {ev!r}")
        elif outcome == "demoted":
            if ev.get("reason") not in _RESIDENT_REASONS:
                errors.append(f"demoted mega.resident event with an "
                              f"untyped reason: {ev!r}")
        else:
            errors.append(f"mega.resident event with bad outcome: {ev!r}")
    queues = [ev for s in spans for ev in s.get("events", [])
              if ev.get("name") == "mega.queue"]
    for ev in queues:
        for field in ("capacity", "depth", "in_flight", "head", "tail",
                      "completed"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"mega.queue event without a numeric "
                              f"{field}: {ev!r}")
        if not isinstance(ev.get("wedged"), bool):
            errors.append(f"mega.queue event without a boolean wedged "
                          f"flag: {ev!r}")
        if isinstance(ev.get("capacity"), int) \
                and isinstance(ev.get("depth"), int) \
                and ev["depth"] > ev["capacity"]:
            errors.append(f"mega.queue event with depth > capacity "
                          f"(ring overflow): {ev!r}")
        if all(isinstance(ev.get(f), int)
               for f in ("head", "tail", "completed")) \
                and not ev["head"] >= ev["tail"] >= ev["completed"]:
            errors.append(f"mega.queue event violates the counter order "
                          f"head >= tail >= completed: {ev!r}")
    caps = [ev for s in spans for ev in s.get("events", [])
            if ev.get("name") == "mega.capacity_demotion"]
    for ev in caps:
        if not isinstance(ev.get("site"), str):
            errors.append(f"mega.capacity_demotion event without a "
                          f"site: {ev!r}")
        if ev.get("reason") not in _CAPACITY_REASONS:
            errors.append(f"mega.capacity_demotion event with an "
                          f"untyped reason: {ev!r}")
        for field in ("steps", "slots", "vmem_bytes"):
            if not isinstance(ev.get(field), int) or ev[field] < 0:
                errors.append(f"mega.capacity_demotion event without a "
                              f"numeric {field}: {ev!r}")
    if require:
        if not any(ev.get("outcome") == "served" for ev in residents):
            errors.append("no served mega.resident event — the resident "
                          "ring served no pool")
        if not any(ev.get("outcome") == "demoted" for ev in residents):
            errors.append("no demoted mega.resident event — the forced "
                          "wedged-ring escape did not record")
        if not queues:
            errors.append("no mega.queue event — the descriptor ring "
                          "was never snapshotted")
    return errors


def _multiset_semantics(spans: list[dict],
                        budget_semantics: bool = False,
                        complete: bool = False) -> list[str]:
    """The pooled cross-tenant lane's span vocabulary (parallel.multiset,
    docs/BATCH_ENGINE.md "Multi-set pooling & pipelining")."""
    errors: list[str] = []
    if budget_semantics:
        # only the --workload run guarantees the pooled lane ran;
        # arbitrary dumps validate multiset span SCHEMAS where present
        for required in ("multiset.execute", "multiset.plan",
                         "multiset.pool", "multiset.dispatch",
                         "multiset.pipeline"):
            if not any(s.get("name") == required for s in spans):
                errors.append(f"no {required} span — the pooled "
                              "multi-set path was not traced")
    dispatches = [s for s in spans if s.get("name") == "multiset.dispatch"]
    mems = [ev for s in dispatches for ev in s.get("events", [])
            if ev.get("name") == "multiset.memory"]
    if complete:
        # completeness is a workload-dump guarantee only: a production
        # dispatch aborted by a real device fault is written (status=
        # error) before its memory event by design, and a pipeline span
        # unwound by an exception closes without its stat tags — neither
        # makes an arbitrary dump invalid
        if dispatches and len(mems) < len(dispatches):
            errors.append(
                f"{len(dispatches) - len(mems)} multiset.dispatch "
                "span(s) lack a multiset.memory event")
        for s in spans:
            if s.get("name") != "multiset.pipeline":
                continue
            tags = s.get("tags") or {}
            if not isinstance(tags.get("launches"), int) \
                    or not isinstance(tags.get("overlap_ratio"),
                                      (int, float)):
                errors.append("multiset.pipeline span lacks launches / "
                              f"overlap_ratio tags: {tags!r}")
    for ev in mems:
        p = ev.get("predicted_bytes")
        if not isinstance(p, (int, float)) or p <= 0:
            errors.append(f"multiset.memory event with non-positive "
                          f"predicted_bytes: {ev!r}")
    if budget_semantics:
        errors += _require_proactive_split(
            spans, "multiset", "the forced POOL split workload case")
    return errors


def _sharded_semantics(spans: list[dict], require: bool = False,
                       complete: bool = False) -> list[str]:
    """The mesh-sharded engine's span/event vocabulary
    (parallel.sharded_engine, docs/BATCH_ENGINE.md "Mesh-sharded
    execution").  Arbitrary dumps validate the ``batch.shard`` /
    ``sharded.memory`` event SCHEMAS wherever they appear; ``complete``
    additionally demands a shard event on every ``sharded.dispatch``
    present; ``require`` (only the full --workload run, which drives a
    2x2 dry-run mesh) demands the span vocabulary and the 2x2 mesh
    shape — matching the multiset presence convention, so batch-only
    dumps validated with workload semantics stay valid."""
    errors: list[str] = []
    dispatches = [s for s in spans if s.get("name") == "sharded.dispatch"]
    shard_evs = [ev for s in spans for ev in s.get("events", [])
                 if ev.get("name") == "batch.shard"]
    for ev in shard_evs:
        mesh = ev.get("mesh")
        if not (isinstance(mesh, list) and mesh
                and all(isinstance(m, int) and m >= 1 for m in mesh)):
            errors.append(f"batch.shard event without a mesh shape "
                          f"list: {ev!r}")
        rps = ev.get("rows_per_shard")
        if not isinstance(rps, (int, float)) or rps <= 0:
            errors.append(f"batch.shard event without positive "
                          f"rows_per_shard: {ev!r}")
        bal = ev.get("shard_balance")
        if not isinstance(bal, (int, float)) or bal < 1.0:
            errors.append(f"batch.shard shard_balance not >= 1: {ev!r}")
        psb = ev.get("per_shard_predicted_bytes")
        if psb is not None and (not isinstance(psb, (int, float))
                                or psb <= 0):
            errors.append(f"batch.shard per_shard_predicted_bytes not "
                          f"positive: {ev!r}")
    mems = [ev for s in dispatches for ev in s.get("events", [])
            if ev.get("name") == "sharded.memory"]
    for ev in mems:
        p = ev.get("predicted_bytes")
        if not isinstance(p, (int, float)) or p <= 0:
            errors.append(f"sharded.memory event with non-positive "
                          f"predicted_bytes: {ev!r}")
    if require:
        for required in ("sharded.execute", "sharded.plan",
                         "sharded.dispatch", "sharded.readback"):
            if not any(s.get("name") == required for s in spans):
                errors.append(f"no {required} span — the mesh-sharded "
                              "path was not traced")
        if not any(ev.get("mesh") == [2, 2] for ev in shard_evs):
            errors.append("no batch.shard event from the 2x2 dry-run "
                          f"mesh dispatch (saw meshes: "
                          f"{[ev.get('mesh') for ev in shard_evs]!r})")
    if complete:
        for s in dispatches:
            names = {ev.get("name") for ev in s.get("events", [])}
            for needed in ("batch.shard", "sharded.memory",
                           "sharded.cost"):
                if needed not in names:
                    errors.append(
                        f"sharded.dispatch span lacks a {needed} event")
    return errors


def _cost_slo_semantics(spans: list[dict], complete: bool = False,
                        require_miss: bool = False) -> list[str]:
    """Cost/SLO event schemas (obs.cost / obs.slo, ISSUE 6).  Arbitrary
    dumps validate whatever ``batch.cost`` / ``multiset.cost`` / ``slo``
    events they contain; ``complete`` additionally demands a cost event
    on every batch dispatch and (with ``require_miss``) the forced
    SLO-miss case the --workload run produces."""
    errors: list[str] = []
    costs = [ev for s in spans for ev in s.get("events", [])
             if ev.get("name") in ("batch.cost", "multiset.cost")]
    for ev in costs:
        if not isinstance(ev.get("device_ms"), (int, float)) \
                or ev["device_ms"] < 0:
            errors.append(f"{ev.get('name')} event without a "
                          f"non-negative device_ms: {ev!r}")
        for field in ("flops", "bytes_accessed", "achieved_flops_per_s",
                      "achieved_bytes_per_s"):
            if field in ev and (not isinstance(ev[field], (int, float))
                                or ev[field] < 0):
                errors.append(
                    f"{ev.get('name')} {field} not a non-negative "
                    f"number: {ev!r}")
        rf = ev.get("roofline_fraction")
        if rf is not None and (not isinstance(rf, (int, float))
                               or not 0.0 < rf <= 1.0):
            errors.append(f"{ev.get('name')} roofline_fraction not in "
                          f"(0, 1]: {ev!r}")
    slos = [ev for s in spans for ev in s.get("events", [])
            if ev.get("name") == "slo"]
    for ev in slos:
        wall = ev.get("wall_ms")
        if not isinstance(wall, (int, float)) or wall <= 0:
            errors.append(f"slo event without positive wall_ms: {ev!r}")
            continue
        phases = ev.get("phases_ms")
        if not isinstance(phases, dict) or not phases \
                or not all(isinstance(v, (int, float)) and v >= 0
                           for v in phases.values()):
            errors.append(f"slo event phases_ms malformed: {ev!r}")
            continue
        total = sum(phases.values())
        if abs(total - wall) > 0.05 * wall + 0.5:
            errors.append(
                f"slo event phases_ms sum {total:.3f} not within 5% of "
                f"wall_ms {wall:.3f}: {ev!r}")
    if complete:
        dispatches = [s for s in spans if s.get("name") == "batch.dispatch"]
        with_cost = [s for s in dispatches
                     if any(ev.get("name") == "batch.cost"
                            for ev in s.get("events", []))]
        if dispatches and len(with_cost) < len(dispatches):
            errors.append(
                f"{len(dispatches) - len(with_cost)} batch.dispatch "
                "span(s) lack a batch.cost event")
        sync_ms_dispatches = [
            s for s in spans if s.get("name") == "multiset.dispatch"
            and not (s.get("tags") or {}).get("pipelined")]
        if sync_ms_dispatches and not any(
                ev.get("name") == "multiset.cost"
                for s in sync_ms_dispatches
                for ev in s.get("events", [])):
            errors.append("no sync multiset.dispatch span carries a "
                          "multiset.cost event")
    if require_miss and not any(ev.get("missed") is True for ev in slos):
        errors.append("no missed slo event — the forced tiny "
                      "ROARING_TPU_SLO_MS workload case did not record "
                      f"(saw: {slos!r})")
    return errors


def run_workload(path: str) -> None:
    """Small batch workload with the tracer on via the env knob (the
    activation path production uses), including one fault-injected
    demotion so the trace carries a demotion chain.

    The workload is a CPU-proxy validation harness: it forces an
    8-device CPU host platform BEFORE the first jax import (the
    ``dryrun_multichip`` pattern — REPLACE, never append) so the
    mesh-sharded section can drive a real 2x2 mesh dispatch on any
    machine."""
    if os.path.exists(path):
        os.unlink(path)
    os.environ["ROARING_TPU_TRACE"] = path
    import re

    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() != "cpu" or len(jax.devices()) < 4:
        raise RuntimeError(
            "check_trace --workload needs a fresh process: the "
            f"{jax.default_backend()!r} backend was initialised before "
            "the CPU dry-run environment could take effect")
    from jax.sharding import Mesh

    import numpy as np

    from roaringbitmap_tpu import obs
    from roaringbitmap_tpu.parallel import aggregation
    from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                         random_query_pool)
    from roaringbitmap_tpu.parallel.multiset import (MultiSetBatchEngine,
                                                     random_multiset_pool)
    from roaringbitmap_tpu.parallel.sharded_engine import ShardedBatchEngine
    from roaringbitmap_tpu.runtime import faults
    from roaringbitmap_tpu.utils import datasets

    obs.refresh_from_env()
    assert obs.enabled(), "tracer did not enable from ROARING_TPU_TRACE"
    try:
        bms = datasets.synthetic_bitmaps(16, seed=3, universe=1 << 18,
                                         density=0.01)
        eng = BatchEngine.from_bitmaps(bms)
        pool = random_query_pool(16, 64)
        clean = [r.cardinality for r in eng.execute(pool)]
        with faults.inject("lowering@pallas=1.0:7"):
            demoted = [r.cardinality
                       for r in eng.execute(pool, engine="pallas")]
        assert demoted == clean, "demoted batch diverged from clean batch"
        # proactive HBM-budget split: a budget far under the Q=64 batch's
        # predicted dispatch peak must halve it BEFORE dispatch, bit-exact
        os.environ["ROARING_TPU_HBM_BUDGET"] = "16M"
        try:
            budgeted = [r.cardinality for r in eng.execute(pool)]
        finally:
            del os.environ["ROARING_TPU_HBM_BUDGET"]
        assert budgeted == clean, "budget-split batch diverged"
        assert eng.proactive_split_count > 0, \
            "tiny ROARING_TPU_HBM_BUDGET did not force a proactive split"
        # forced SLO miss: a microsecond deadline no real execute can
        # make — the slo event (phase breakdown included) must ride the
        # batch.execute span (obs.slo, ISSUE 6)
        os.environ["ROARING_TPU_SLO_MS"] = "0.001"
        try:
            missed = [r.cardinality for r in eng.execute(pool)]
        finally:
            del os.environ["ROARING_TPU_SLO_MS"]
        assert missed == clean, "SLO-missing batch diverged (accounting "\
            "must never change results)"
        aggregation.or_(*bms[:8])

        # expression lane (ISSUE 8): a fused 3-node DAG — (A|B) & ~C —
        # clean, then under a forced pallas demotion, bit-exact; the
        # expr.compile spans + launches-saved credit are what the
        # semantics checks above pin
        from roaringbitmap_tpu import obs as _obs
        from roaringbitmap_tpu.parallel import expr

        e3 = expr.and_(expr.or_(0, 1), expr.not_(2))
        expr_pool = [expr.ExprQuery(e3, form="bitmap"),
                     expr.ExprQuery(expr.xor(expr.or_(3, 4),
                                             expr.and_(5, 6)))]
        expr_clean = [r.cardinality for r in eng.execute(expr_pool)]
        with faults.inject("lowering@pallas=1.0:9"):
            expr_demoted = [r.cardinality
                            for r in eng.execute(expr_pool,
                                                 engine="pallas")]
        assert expr_demoted == expr_clean, \
            "demoted fused expression diverged from clean run"
        host = expr.evaluate_host(e3, bms)
        assert expr_clean[0] == host.cardinality, \
            "fused expression diverged from host sequential evaluation"
        saved = _obs.snapshot()["counters"].get(
            "rb_expr_launches_saved_total", [])
        assert sum(r["value"] for r in saved) > 0, \
            "fused expressions credited no saved launches"
        # one-kernel lane (ISSUE 11): the SAME pool through the
        # megakernel rung — bit-exact vs an EXPLICIT multi-op rung (on
        # TPU engine="auto" resolves expression pools to the megakernel
        # itself, which would make this a self-comparison), and its
        # dispatch span must carry the expr.megakernel event the schema
        # checks above pin
        expr_multiop = [r.cardinality
                        for r in eng.execute(expr_pool, engine="xla")]
        expr_mega = [r.cardinality
                     for r in eng.execute(expr_pool,
                                          engine="megakernel")]
        assert expr_mega == expr_multiop, \
            "megakernel expression diverged from multi-op run"

        # pooled cross-tenant lane: 3 tenants, one pooled launch
        # (multiset.* spans), then a tiny budget forcing a POOL split
        tenants = [datasets.synthetic_bitmaps(
            8, seed=30 + i, universe=1 << 17, density=0.01)
            for i in range(3)]
        ms = MultiSetBatchEngine.from_bitmap_sets(tenants, layout="dense")
        ms_pool = random_multiset_pool([8] * 3, 24, seed=11)
        ms_clean = [[r.cardinality for r in rows]
                    for rows in ms.execute(ms_pool)]
        budget = max(1, ms.predict_dispatch_bytes(ms_pool) // 3)
        os.environ["ROARING_TPU_HBM_BUDGET"] = str(budget)
        try:
            ms_budgeted = [[r.cardinality for r in rows]
                           for rows in ms.execute(ms_pool)]
        finally:
            del os.environ["ROARING_TPU_HBM_BUDGET"]
        assert ms_budgeted == ms_clean, "budget-split pool diverged"
        assert ms.proactive_split_count > 0, \
            "tiny budget did not force a proactive POOL split"

        # mesh-sharded lane (ISSUE 7): the same tenants pooled over a
        # 2x2 dry-run mesh — sharded.* spans + the batch.shard event the
        # schema checks above pin, bit-exact vs the single-device pool
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("rows", "data"))
        sharded = ShardedBatchEngine(ms._engines, mesh=mesh)
        sh_got = [[r.cardinality for r in rows]
                  for rows in sharded.execute(ms_pool)]
        assert sh_got == ms_clean, "2x2 mesh dispatch diverged from the "\
            "single-device pool"

        # mutation lane (ISSUE 12): an in-place delta patch, a
        # structural escalation to repack, and a result-cache-served
        # re-execute — the mutation.delta spans + expr.cache events the
        # semantics checks above pin, bit-exact vs the host oracle
        from roaringbitmap_tpu.mutation import ResultCache
        from roaringbitmap_tpu.parallel.batch_engine import BatchQuery

        mut_bms = datasets.synthetic_bitmaps(6, seed=77,
                                             universe=1 << 16,
                                             density=0.01)
        mut_eng = BatchEngine.from_bitmaps(mut_bms, layout="dense")
        mut_eng.result_cache = ResultCache(8 << 20)
        mut_q = [BatchQuery("or", (0, 1, 2))]
        mut_eng.execute(mut_q)
        mut_eng.execute(mut_q)               # the cache hit
        rep = mut_eng._ds.apply_delta(adds={0: [3, 4]})
        assert rep["mode"] == "patch", rep
        rep2 = mut_eng._ds.apply_delta(
            adds={0: [(0xEE00 << 16) + 1]})  # new key: escalates
        assert rep2["mode"] == "repack", rep2
        got = mut_eng.execute(mut_q)[0].cardinality
        want = mut_eng._ds.host_bitmaps()[0] \
            | mut_eng._ds.host_bitmaps()[1] \
            | mut_eng._ds.host_bitmaps()[2]
        assert got == want.cardinality, \
            "post-delta batch diverged from the host oracle"

        # analytics lane (ISSUE 15, docs/ANALYTICS.md): attach a value
        # column (analytics.column span), drive ONE fused
        # filter-then-aggregate OLAP query (the dispatch span's
        # analytics.scan event must carry the vagg step), then a column
        # delta (analytics.delta event; exact result-cache
        # invalidation) and a bit-exact re-execute vs the host oracle
        from roaringbitmap_tpu.analytics import BsiColumn

        col_rng = np.random.default_rng(0xA11)
        col_ids = np.unique(col_rng.integers(0, 1 << 16, 3000)
                            ).astype(np.uint32)
        col = BsiColumn("price", col_ids,
                        col_rng.integers(0, 5000, col_ids.size)
                        .astype(np.int64))
        mut_eng._ds.attach_column(col)
        olap_q = expr.ExprQuery(expr.sum_(
            "price", found=expr.and_(expr.or_(0, 1),
                                     expr.range_("price", 100, 4000))))
        olap_got = mut_eng.execute([olap_q])[0]
        card, value, _ = expr.evaluate_host_agg(
            olap_q.expr, mut_eng._ds.host_bitmaps(), {"price": col})
        assert (olap_got.cardinality, olap_got.value) == (card, value), \
            "fused OLAP query diverged from the host BSI oracle"
        col.apply_delta(set_values={int(col_ids[0]): 4999})
        olap_again = mut_eng.execute([olap_q])[0]
        card, value, _ = expr.evaluate_host_agg(
            olap_q.expr, mut_eng._ds.host_bitmaps(), {"price": col})
        assert (olap_again.cardinality, olap_again.value) \
            == (card, value), \
            "post-column-delta OLAP query diverged from the host oracle"

        # serving lane (ISSUE 10): an OVERLOADED continuous-batching
        # burst over the same tenants — a tiny per-tenant queue cap
        # forces a typed AdmissionRejected, a virtually-expired deadline
        # forces a typed shed, and the served remainder is bit-exact;
        # the serving.* span vocabulary + the backpressure property tags
        # are what the semantics checks above pin
        from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
        from roaringbitmap_tpu.runtime import guard as rt_guard
        from roaringbitmap_tpu.serving import (AdmissionRejected,
                                               RequestShed, ServingLoop,
                                               ServingPolicy,
                                               ServingRequest)

        loop = ServingLoop(ms, ServingPolicy(
            pool_target=4, max_queue=3, default_deadline_ms=60_000.0,
            guard=rt_guard.GuardPolicy(backoff_base=0.0,
                                       sleep=lambda s: None)))
        tickets, rejected = [], 0
        for i in range(15):
            try:
                tickets.append(loop.submit(ServingRequest(
                    i % 3, BatchQuery("or", (0, 1, 2)),
                    tenant=f"t{i % 3}")))
            except AdmissionRejected as exc:
                assert exc.reason == "queue_full"
                rejected += 1
        assert rejected > 0, "tiny queue cap did not reject"
        loop.drain()                     # serve the admitted backlog
        doomed = loop.submit(ServingRequest(
            0, BatchQuery("or", (0, 1)), tenant="t0", deadline_ms=1.0))
        faults.advance_clock(0.05)       # virtual: the deadline passed
        loop.drain()
        assert doomed.status == "shed" \
            and isinstance(doomed.error, RequestShed), doomed.status
        for t in tickets:
            assert t.status == "done", t.status
            ref = ms._engines[t.request.set_id]._sequential_one(
                t.request.query)
            assert t.result.cardinality == ref.cardinality, \
                "serving result diverged from the sequential reference"

        # closed-lattice lane (ISSUE 13): warm a small vocabulary on a
        # FRESH engine (lattice.warmup span), serve an in-lattice batch
        # compile-free, then force ONE deliberate out-of-lattice query
        # — it must execute bit-exactly, emit a lattice.escape event,
        # and move rb_lattice_escapes_total (traced AND metered, never
        # silent; the semantics checks above pin both schemas)
        from roaringbitmap_tpu.obs import metrics as obs_metrics
        from roaringbitmap_tpu.runtime import lattice as rt_lattice

        def lattice_escape_metric() -> int:
            return int(sum(
                inst.value for name, _l, inst
                in obs_metrics.REGISTRY.instruments()
                if name == "rb_lattice_escapes_total"))

        lat_eng = BatchEngine.from_bitmaps(mut_bms, layout="dense")
        try:
            lat_eng.warmup(
                profile="q=8,;rows=8,;keys=1,;heads=both;pool=8,")
            in_lattice = [BatchQuery("or", (0, 1)),
                          BatchQuery("and", (1, 2, 3))]
            got_in = [r.cardinality for r in lat_eng.execute(in_lattice)]
            ref_in = [r.cardinality
                      for r in lat_eng._execute_sequential(in_lattice)]
            assert got_in == ref_in, "in-lattice batch diverged"
            assert rt_lattice.escape_total() == 0, \
                "in-lattice traffic escaped"
            e0 = lattice_escape_metric()
            # 9 same-op queries > the q=8 rung: out of vocabulary
            oov = [BatchQuery("or", (0, 1)) for _ in range(9)]
            got_oov = [r.cardinality for r in lat_eng.execute(oov)]
            ref_oov = [r.cardinality
                       for r in lat_eng._execute_sequential(oov)]
            assert got_oov == ref_oov, "out-of-lattice batch diverged"
            assert lattice_escape_metric() > e0, \
                "out-of-lattice compile was not metered on " \
                "rb_lattice_escapes_total"
        finally:
            rt_lattice.deactivate()

        # resident lane (ISSUE 16, docs/SERVING.md "Resident pump"):
        # fused filter-then-aggregate pools replayed through the
        # persistent descriptor ring — every pool must be ring-served
        # with ZERO per-pool host dispatches (the counter pin below),
        # bit-exact vs the host BSI oracle; then the ring is WEDGED for
        # one pool, whose typed escape demotes it to the one-shot path
        # (still bit-exact) and records the demoted mega.resident event
        # the semantics checks above require
        from roaringbitmap_tpu.analytics import BsiColumn as ResBsi
        from roaringbitmap_tpu.parallel.aggregation import \
            DeviceBitmapSet

        def res_tenant(seed: int, uni: int, vmax: int):
            bms = datasets.synthetic_bitmaps(4, seed=seed, universe=uni,
                                             density=0.004)
            ds = DeviceBitmapSet(bms)
            rng = np.random.default_rng(seed + 1)
            ids = np.unique(rng.integers(0, uni, 2000)
                            ).astype(np.uint32)
            col = ResBsi("price", ids,
                         rng.integers(0, vmax, ids.size)
                         .astype(np.int64))
            ds.attach_column(col)
            return bms, ds, col

        res_tenants = [res_tenant(0x71, 1 << 12, 400),
                       res_tenant(0x81, 1 << 11, 120)]
        res_depth = max(c.depth_pad for _, _, c in res_tenants)
        res_eng = MultiSetBatchEngine([ds for _, ds, _ in res_tenants])
        res_loop = ServingLoop(res_eng, ServingPolicy(
            resident=True, pool_target=2, engine="megakernel",
            default_deadline_ms=600_000.0,
            guard=rt_guard.GuardPolicy(backoff_base=0.0,
                                       sleep=lambda s: None)))
        try:
            res_loop.warmup(
                profile=f"q=4,;rows=16,;keys=4,;ops=or,and;heads=both;"
                        f"pool=16,;expr=2;bsi={res_depth},")
            d0 = obs_metrics.counter("rb_serving_dispatches_total",
                                     site="serving").value
            res_tickets = []
            for i in range(8):
                if i % 2:
                    q = expr.ExprQuery(expr.sum_(
                        "price", found=expr.and_(
                            expr.or_(0, 1),
                            expr.cmp("price", "ge", 10 + i))))
                else:
                    q = expr.ExprQuery(expr.and_(
                        expr.or_(0, 1),
                        expr.cmp("price", "le", 100 + i)))
                res_tickets.append(res_loop.submit(ServingRequest(
                    i % 2, q, tenant=f"r{i % 2}")))
            res_loop.drain()
            d_served = obs_metrics.counter(
                "rb_serving_dispatches_total", site="serving").value
            assert d_served == d0, \
                "ring-served pools still paid per-pool host dispatches"
            assert res_loop._resident.stats["served"] >= 4, \
                res_loop._resident.stats
            for t in res_tickets:
                assert t.status == "done", (t.status, t.error)
                bms_x, _, col_x = res_tenants[t.request.set_id]
                q = t.request.query
                if isinstance(q.expr, expr.Agg):
                    card, value, _ = expr.evaluate_host_agg(
                        q.expr, bms_x, {"price": col_x})
                    assert (t.result.cardinality, t.result.value) \
                        == (card, value), \
                        "ring-served aggregate diverged from the host " \
                        "BSI oracle"
                else:
                    ref = expr.evaluate_host(q.expr, bms_x,
                                             {"price": col_x})
                    assert t.result.cardinality == ref.cardinality, \
                        "ring-served filter diverged from the host " \
                        "oracle"
            # forced escape: wedge the ring, serve one more pool — the
            # typed ResidentEscape demotes it to the one-shot dispatch
            # path (counter moves), still bit-exact
            res_loop._resident.ring.wedge()
            doomed_q = expr.ExprQuery(expr.and_(
                expr.or_(0, 1), expr.cmp("price", "le", 300)))
            wt = [res_loop.submit(ServingRequest(0, doomed_q,
                                                 tenant="r0"))
                  for _ in range(2)]
            res_loop.drain()
            d_after = obs_metrics.counter(
                "rb_serving_dispatches_total", site="serving").value
            assert d_after > d_served, \
                "the wedged-ring pool did not demote to host dispatch"
            for t in wt:
                assert t.status == "done", (t.status, t.error)
                ref = expr.evaluate_host(
                    doomed_q.expr, res_tenants[0][0],
                    {"price": res_tenants[0][2]})
                assert t.result.cardinality == ref.cardinality, \
                    "the demoted pool diverged from the host oracle"
        finally:
            rt_lattice.deactivate()

        # pod lane (ISSUE 14, docs/POD.md): a simulated 2-host pod over
        # the same tenant universe — one mis-routed arrival (forwarded),
        # then a forced host drop whose tickets walk the reroute rung;
        # the pod.place / pod.route / pod.reroute schemas + presence are
        # what the semantics checks above pin, bit-exact throughout
        import shutil
        import tempfile

        from roaringbitmap_tpu.obs import flight as obs_flight
        from roaringbitmap_tpu.parallel import podmesh
        from roaringbitmap_tpu.serving import PodFrontDoor

        flight_dir = tempfile.mkdtemp(prefix="rb_trace_flight_")
        obs_flight.configure(dir=flight_dir)
        obs_flight.reset()
        pod_plan = podmesh.PlacementPlan(
            regimes=("replicated-2", "local", "local"),
            hosts=((0, 1), (0,), (1,)), bytes_per_host=(0, 0))
        fd = PodFrontDoor(
            [e._ds for e in ms._engines],
            pod=podmesh.PodMesh.simulate(2), plan=pod_plan,
            policy=ServingPolicy(
                pool_target=4, default_deadline_ms=600_000.0,
                guard=rt_guard.GuardPolicy(backoff_base=0.0,
                                           sleep=lambda s: None)))
        podmesh.place([e._ds for e in ms._engines], fd.pod)
        pod_tickets = [fd.submit(ServingRequest(
            i % 3, BatchQuery("or", (0, 1, 2)), tenant=f"t{i % 3}"),
            via_host=1 - (i % 2)) for i in range(8)]
        victim = next(h for h in (0, 1)
                      if any(t.pod_host == h for t in pod_tickets))
        fd.fail_host(victim)
        fd.drain()
        for t in pod_tickets:
            assert t.status == "done", (t.status, t.error)
            ref = ms._engines[t.pod_sid]._sequential_one(t.query)
            assert t.result.cardinality == ref.cardinality, \
                "routed pod result diverged from the sequential " \
                "reference"
        assert fd.stats["forwarded"] > 0, "no arrival was forwarded"
        assert fd.stats["reroutes"] > 0, \
            "the forced host drop rerouted nothing"
        # flight recorder (this PR): the host loss must have dumped a
        # schema-valid black-box artifact, and the merged fleet statusz
        # must report BOTH simulated hosts' state
        flight_dumps = sorted(
            os.path.join(flight_dir, f) for f in os.listdir(flight_dir)
            if f.startswith("flight-") and f.endswith(".json"))
        assert flight_dumps, \
            "the forced host drop left no flight-recorder dump"
        for fp in flight_dumps:
            with open(fp) as fh:
                doc = json.load(fh)
            doc_errs = validate_doc(doc, fp)
            assert not doc_errs, doc_errs
        sz = fd.statusz()
        sz_errs = _statusz_doc_errors(sz, "fd.statusz()")
        assert not sz_errs, sz_errs
        assert {"0", "1"} <= set(sz.get("hosts") or {}), \
            f"fd.statusz() did not report both hosts: " \
            f"{sorted(sz.get('hosts') or {})}"

        # durability lane (ISSUE 17, docs/DURABILITY.md): a journaled
        # tenant crashed mid-apply with a TORN journal tail, recovered
        # bit-exactly from snapshot + journal-tail replay (the
        # durability.snapshot / durability.replay spans + torn_tail
        # event the semantics checks above pin), then a served tenant
        # live-migrated across a fresh 2-host pod under traffic — the
        # pod.migrate flip must record with zero failed requests
        import shutil
        import tempfile

        from roaringbitmap_tpu.mutation import durability
        from roaringbitmap_tpu.runtime import errors as rt_errors
        from roaringbitmap_tpu.serving import migrate_tenant

        dur_root = tempfile.mkdtemp(prefix="rb_trace_dur_")
        try:
            dt = durability.DurableTenant(
                DeviceBitmapSet(datasets.synthetic_bitmaps(
                    3, seed=0xD7, universe=1 << 14, density=0.01)),
                root=dur_root, tenant="wl",
                policy=durability.FlushPolicy(mode="batch", every_n=2),
                snapshot_every=3)
            for i in range(5):
                dt.apply_delta(adds={i % 3: [1000 + 7 * i]})
            dur_want = dt.ds.host_bitmaps()
            crashed = False
            with faults.inject("crash@torn=1.0:17"):
                try:
                    dt.apply_delta(adds={0: [12345]})
                except rt_errors.InjectedCrash:
                    crashed = True
            assert crashed, "crash@torn did not fire"
            rec, rep = durability.recover_tenant(root=dur_root,
                                                 tenant="wl")
            assert rep["torn"], "the torn crash left no torn tail"
            assert rep["replayed"] >= 1, rep
            assert rec.ds.host_bitmaps() == dur_want, \
                "torn recovery diverged from the pre-crash image"

            mig_fd = PodFrontDoor(
                [DeviceBitmapSet(datasets.synthetic_bitmaps(
                    3, seed=0xE0 + i, universe=1 << 14, density=0.01))
                 for i in range(2)],
                pod=podmesh.PodMesh.simulate(2),
                policy=ServingPolicy(
                    pool_target=2, default_deadline_ms=600_000.0,
                    guard=rt_guard.GuardPolicy(backoff_base=0.0,
                                               sleep=lambda s: None)))

            def mig_ask(sid: int) -> int:
                t = mig_fd.submit(ServingRequest(
                    sid, BatchQuery("or", (0, 1, 2)), tenant=f"m{sid}"))
                done = mig_fd.drain()
                bad = [x for x in done
                       if x.status == "failed"
                       or (x.status == "shed"
                           and x.shed_reason != "expired")]
                assert not bad, [(x.status, x.error) for x in bad]
                assert t.status == "done", (t.status, t.error)
                return int(t.result.cardinality)

            mig_sid = next(s for s in range(2)
                           if mig_fd.plan.regime(s) != "sharded")
            mig_to = next(h for h in mig_fd.pod.alive()
                          if h != mig_fd.owner_host(mig_sid))
            mig_before = mig_ask(mig_sid)

            def mig_during(_fd):
                # traffic + a delta INSIDE the dual-write window
                mig_fd.apply_delta(mig_sid,
                                   adds={0: [999_991, 999_992]})
                assert mig_ask(mig_sid) == mig_before + 2, \
                    "serving diverged inside the dual-write window"

            mig_rep = migrate_tenant(mig_fd, mig_sid, mig_to,
                                     during=mig_during)
            assert mig_fd.owner_host(mig_sid) == mig_to, \
                "the migration flip did not move ownership"
            assert mig_rep["catch_up_records"] >= 1, mig_rep
            assert mig_ask(mig_sid) == mig_before + 2, \
                "post-flip serving diverged"
        finally:
            shutil.rmtree(dur_root, ignore_errors=True)
            shutil.rmtree(flight_dir, ignore_errors=True)

        # wire lane (ISSUE 20, docs/WIRE.md): ONE cross-process submit
        # over TCP against a REAL second OS process (wire.bootstrap).
        # The client's rpc.call spans land in THIS dump; the server
        # traces rpc.submit / serving.* into its OWN dump at
        # path + ".wire" via the same env activation knob — main()
        # pools both files, and _rpc_semantics demands one trace id
        # covering the whole cross-socket lifecycle
        import subprocess

        from roaringbitmap_tpu.wire import WireClient

        wire_path = path + ".wire"
        if os.path.exists(wire_path):
            os.unlink(wire_path)
        wire_env = dict(os.environ)
        wire_env["ROARING_TPU_TRACE"] = wire_path
        wire_srv = subprocess.Popen(
            [sys.executable, "-m", "roaringbitmap_tpu.wire.bootstrap",
             "--seed", "3", "--sets", "2", "--sources", "6",
             "--tenants", "4", "--density", "400",
             "--users", str(1 << 16), "--no-columns"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=wire_env)
        try:
            winfo = json.loads(wire_srv.stdout.readline())
            wcl = WireClient((winfo["host"], winfo["port"]))
            wq = BatchQuery("or", (0, 1, 2))
            assert wcl.call(ServingRequest(0, wq, tenant="t0"),
                            300).cardinality >= 0
            wts = wcl.submit_many([ServingRequest(s, wq,
                                                  tenant=f"t{s}")
                                   for s in (0, 1)])
            for wt_ in wts:
                assert wt_.value(timeout=300).cardinality >= 0, \
                    "pipelined cross-process submit failed"
            wcl.close()
        finally:
            wire_srv.stdin.close()
            try:
                wire_srv.wait(timeout=20)
            except subprocess.TimeoutExpired:
                wire_srv.kill()
    finally:
        obs.disable()


def main() -> int:
    args = [a for a in sys.argv[1:]]
    workload = "--workload" in args
    if workload:
        args.remove("--workload")
    if not args or (workload and len(args) != 1):
        print(__doc__)
        return 2
    if workload:
        run_workload(args[0])
        # the wire server subprocess traced into its own dump: pool it
        # with the client's so the cross-socket stitch can resolve
        if os.path.exists(args[0] + ".wire"):
            args.append(args[0] + ".wire")
    # several paths (per-host dumps + flight/statusz artifacts) validate
    # as one pooled span set: refs and the stitched-trace semantics
    # resolve against the union
    errors = validate(args if len(args) > 1 else args[0],
                      workload_semantics=workload,
                      budget_semantics=workload)
    if errors:
        for e in errors:
            print(f"check_trace: {e}", file=sys.stderr)
        return 1
    n = sum(sum(1 for _ in open(p)) for p in args)
    print(f"check_trace: {', '.join(args)} OK ({n} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
