"""Bench-trajectory regression sentry: gate the WHOLE round history.

``tools/bench_diff.py`` compares two bench documents; a reviewer still
had to run it by hand and eyeball which pair to compare.  This sentry
generalizes it to the committed trajectory::

    python tools/bench_sentry.py                      # BENCH_r0*.json, report
    python tools/bench_sentry.py --fail               # CI gate
    python tools/bench_sentry.py r01.json r02.json …  # explicit rounds

It loads every round document (driver captures with ``parsed: null``
get bench_diff's truncated-tail salvage; rounds with no recoverable
summary — e.g. a failed run whose tail is a traceback — are recorded as
unusable and skipped), aligns lanes across rounds by dotted-path suffix
(salvaged tails recover different depths per round), and fits each
directional lane's trajectory:

- **step**: the newest transition moved against the lane's direction by
  more than ``--threshold`` (fractional, default 0.25) — the "this round
  regressed it" signal;
- **drift**: the lane moved against its direction on every one of the
  last >= 3 transitions and the cumulative move exceeds
  ``--drift-threshold`` (default 0.25) — the slow-bleed signal a
  pairwise diff's per-step threshold never fires on;
- **removed**: a lane the previous round emitted that the newest round
  lost (bench_diff.lane_changes) — a bench phase that stopped reporting
  looks exactly like a regression that hid itself.  Reported always;
  gated only under ``--fail-removed`` (salvaged tails legitimately
  recover different lane subsets, so removal alone is a warning).

Only the NEWEST round is gated — historical steps between committed
rounds already shipped and are reported as context, not failures.

Output: a markdown trajectory table (stdout, or ``--md PATH``) and a
one-line JSON verdict as the final stdout line (the driver-parsable
shape bench.py's summary established).  ``--fail`` exits 1 when any
gated lane regressed.  The CI observability lane runs this over the
committed BENCH_r01..r05 files, so the next round's regression is
caught by the suite, not a reviewer.

``--smoke-sharded`` (ISSUE 7) prepends a mesh-sharded engine smoke to
the trajectory run: a pooled workload executed on 4x1 and 2x2 dry-run
meshes must match the single-device pooled engine bit-exactly
(cardinalities AND materialized bitmaps).  It needs >= 4 devices — the
CI observability lane forces an 8-device CPU host platform for the
whole step, which also puts check_trace / check_obs_overhead on the
same virtual mesh the test suite runs on.  The sharded bench lanes the
smoke guards (``sharded.m{R}x1_q{Q}.pooled_qps``, ``shard_balance``,
``warm_restart_x``) feed the sentry's direction table through
bench_diff's lane vocabulary.

``--smoke-serving`` (ISSUE 10) prepends the serving-loop robustness
smoke: an overloaded burst through the continuous-batching front-end
must serve every completed request bit-exactly vs the sequential
reference, shed/reject the rest with TYPED errors (never silently),
respect the HBM backpressure property on every dispatched pool, and
return the HBM ledger to its pre-burst baseline — pinning the
``serving.x{R}`` bench lanes' correctness before their trend is gated.

``--smoke-expr`` (ISSUE 8) prepends the fused-expression bit-exactness
smoke: a depth-2/3 expression pool executed FUSED (the expression-DAG
compiler, one launch) must match the host-side sequential evaluator
exactly, clean and through a forced pallas demotion — pinning the
``expression.d{D}_q{Q}.fused_qps`` / ``fused_vs_node_x`` bench lanes'
correctness before their trend is gated.

``--smoke-pod`` (ISSUE 14, docs/POD.md) prepends the pod front-door
smoke: a routed 2-host simulated pod serving a mixed stream must
forward mis-routed arrivals, degrade a forced host drop through the
``reroute`` rung with typed errors only (zero silent failures), and
serve every routed result bit-exactly vs the sequential reference —
pinning the ``pod.*`` bench lanes' correctness before their trend is
gated.

``--smoke-olap`` (ISSUE 15, docs/ANALYTICS.md) prepends the analytics
OLAP smoke: fused filter-then-aggregate queries (``sum_`` / ``top_k``
roots, value-predicate filters) over attached BSI and RangeBitmap
columns must match the host oracle bit-exactly on every engine rung,
through a forced fault demotion to the sequential oracle floor, and
vs the two-phase baseline, with typed-only failures — pinning the
``olap.q{Q}.*`` / ``fused_vs_twophase_x`` bench lanes' correctness
before their trend is gated.

``--smoke-resident`` (ISSUE 16, docs/SERVING.md "Resident pump")
prepends the persistent resident-queue smoke: pools served through the
descriptor ring must match BOTH the one-shot megakernel dispatch and
the host oracle bit-exactly on flat boolean, expression-DAG, and
filter-then-aggregate roots; a wedged ring must escape with the typed
``ResidentEscape`` and demote the pool to the one-shot host-dispatch
path (still bit-exact, never silent) — pinning the
``resident.resident_vs_dispatch_x`` bench lane's correctness before
its trend is gated.

``--smoke-durability`` (ISSUE 17, docs/DURABILITY.md) prepends the
durable-tenant smoke: a journaled delta stream crashed CLEAN (record
durable, not applied) and TORN (last record truncated mid-frame) must
recover bit-exactly from snapshot + journal-tail replay with typed
``InjectedCrash`` on the way down, and a live tenant migration under
traffic must serve bit-exactly with zero failed requests — pinning the
``durability.*`` bench lanes' correctness (``journal_overhead_x``,
``recovery_ms_*``, ``migration_blip_ms``) before their trend is gated.

``--smoke-obs`` (docs/OBSERVABILITY.md) prepends the observability-plane
smoke: a forwarded-then-rerouted request on a 2-host simulated pod must
stitch into ONE trace id (``pod.route`` → ``serving.admit`` →
``pod.reroute`` → ``serving.request``), the forced host loss must leave
a schema-valid flight-recorder dump, and the merged ``fd.statusz()``
must report both hosts with an idempotent monotone counter merge —
nothing about the trace/flight/statusz plane may go silent before the
bench trends it rides on are gated.

``--smoke-wire`` (ISSUE 20, docs/WIRE.md) prepends the binary wire
front-door smoke: pipelined mixed flat/expression/analytics traffic
over a loopback ``WireServer`` must come back bit-exact vs the
sequential per-set reference; a full tenant queue, an unknown token,
and an ungranted tenant must each answer TYPED wire error frames on a
connection that keeps serving; a garbled inbound frame must die as
``CorruptInput`` — zero silent drops, zero raw socket/struct escapes —
guarding the ``pod_replay.*`` bench lanes' correctness before their
trend is gated.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)


def _load_bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(_HERE, "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_diff = _load_bench_diff()


def load_rounds(paths: list) -> tuple[list, list]:
    """([(name, lanes)] usable rounds in input order, [unusable names]).
    A round whose document yields no lanes (bench_diff's loader raises
    on a driver capture with neither ``parsed`` nor a salvageable tail)
    is skipped, not fatal — r01-class failed rounds are part of real
    trajectories."""
    rounds, unusable = [], []
    for p in paths:
        name = os.path.splitext(os.path.basename(p))[0]
        try:
            lanes = bench_diff.load_lanes(p)
        except (SystemExit, OSError, ValueError, KeyError):
            unusable.append(name)
            continue
        if not lanes:
            unusable.append(name)
            continue
        rounds.append((name, lanes))
    return rounds, unusable


def build_series(rounds: list) -> dict:
    """{canonical lane: {round name: value}} — lanes keyed by the NEWEST
    round's dotted paths, earlier rounds mapped onto them by
    bench_diff.suffix_align (depth-shifted salvage tails pair by unique
    path suffix).  A lane only the newest round emits still appears,
    with a single point."""
    if not rounds:
        return {}
    canonical = rounds[-1][1]
    series: dict = {lane: {} for lane in canonical}
    for name, lanes in rounds[:-1]:
        aligned = bench_diff.suffix_align(lanes, canonical)
        for old_lane, new_lane in aligned.items():
            series[new_lane][name] = lanes[old_lane]
    last_name = rounds[-1][0]
    for lane, v in canonical.items():
        series[lane][last_name] = v
    return series


def fit_trend(values: list) -> float | None:
    """Least-squares relative slope per round (fraction of the mean) —
    the direction-aware trend figure the table reports.  None when
    under 2 points or the mean is 0."""
    n = len(values)
    if n < 2:
        return None
    mean = sum(values) / n
    if mean == 0:
        return None
    xs = range(n)
    x_mean = (n - 1) / 2.0
    denom = sum((x - x_mean) ** 2 for x in xs)
    slope = sum((x - x_mean) * (v - mean)
                for x, v in zip(xs, values)) / denom
    return slope / abs(mean)


def analyze_lane(points: list, direction: int, threshold: float,
                 drift_threshold: float) -> dict:
    """One lane's trajectory verdict over ``points`` (round-ordered
    values; the last is the newest round).

    Returns {"trend", "steps": [(i, frac)], "step_latest": frac|None,
    "drift": frac|None} where ``steps`` are ALL against-direction
    transitions past the threshold (history, informational),
    ``step_latest`` is set only when the newest transition is one (the
    gated case), and ``drift`` is the cumulative against-direction move
    when the last >= 3 transitions were all monotone against the lane
    (gated)."""
    out = {"trend": fit_trend(points), "steps": [], "step_latest": None,
           "drift": None}
    if direction == 0 or len(points) < 2:
        return out
    deltas = []
    for i in range(1, len(points)):
        prev, cur = points[i - 1], points[i]
        d = (cur - prev) / abs(prev) if prev else (
            0.0 if cur == prev else float("inf"))
        deltas.append(d)
        if direction * d < -threshold:
            out["steps"].append((i, round(d, 4)))
    if out["steps"] and out["steps"][-1][0] == len(points) - 1:
        out["step_latest"] = out["steps"][-1][1]
    # monotone drift ending at the newest round: every one of the last
    # >= 3 transitions moved against the direction
    run = 0
    for d in reversed(deltas):
        if direction * d < 0:
            run += 1
        else:
            break
    if run >= 3:
        base = points[-1 - run]
        cum = ((points[-1] - base) / abs(base)) if base else float("inf")
        if direction * cum < -drift_threshold:
            out["drift"] = round(cum, 4)
    return out


def analyze(series: dict, round_names: list, threshold: float,
            drift_threshold: float) -> dict:
    """Full-trajectory analysis: per-lane verdicts + the gated lists."""
    lanes: dict = {}
    steps, drifts = [], []
    for lane in sorted(series):
        by_round = series[lane]
        points = [by_round[r] for r in round_names if r in by_round]
        direction = bench_diff.direction(lane)
        row = analyze_lane(points, direction, threshold, drift_threshold)
        row["direction"] = direction
        row["points"] = len(points)
        lanes[lane] = row
        if row["step_latest"] is not None:
            steps.append(lane)
        if row["drift"] is not None:
            drifts.append(lane)
    return {"lanes": lanes, "step_regressions": steps,
            "drift_regressions": drifts}


def markdown_table(series: dict, round_names: list, analysis: dict,
                   top: int = 40) -> str:
    """Lane x round trajectory table, flagged lanes first."""
    arrow = {1: "^", -1: "v", 0: "-"}
    flagged = set(analysis["step_regressions"]) \
        | set(analysis["drift_regressions"])

    def fmt(v):
        if v is None:
            return ""
        return f"{v:g}" if abs(v) < 1e6 else f"{v:.3e}"

    ordered = sorted(series, key=lambda ln: (ln not in flagged, ln))
    rows = []
    for lane in ordered[:max(top, len(flagged))]:
        a = analysis["lanes"][lane]
        flags = []
        if a["step_latest"] is not None:
            flags.append(f"STEP {a['step_latest']:+.0%}")
        if a["drift"] is not None:
            flags.append(f"DRIFT {a['drift']:+.0%}")
        trend = ("" if a["trend"] is None
                 else f"{a['trend']:+.1%}/round")
        cells = [fmt(series[lane].get(r)) for r in round_names]
        rows.append("| " + " | ".join(
            [f"{arrow[a['direction']]} {lane}", *cells, trend,
             " ".join(flags)]) + " |")
    header = ("| lane | " + " | ".join(round_names)
              + " | trend | flags |")
    sep = "|" + "---|" * (len(round_names) + 3)
    note = (f"\n({len(series) - len(rows)} more lanes not shown)"
            if len(series) > len(rows) else "")
    return "\n".join([header, sep, *rows]) + note


def sharded_smoke() -> int:
    """Mesh-sharded engine parity smoke (see module docstring): pooled
    execution on 4x1 and 2x2 meshes bit-exact vs the single-device
    pooled engine.  Returns 0 on parity, 1 on divergence, 2 when the
    environment cannot host a 4-device mesh."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import (BatchEngine, BatchGroup,
                                            BatchQuery,
                                            MultiSetBatchEngine,
                                            ShardedBatchEngine)

    if len(jax.devices()) < 4:
        print("bench_sentry: --smoke-sharded needs >= 4 devices (set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              file=sys.stderr)
        return 2
    rng = np.random.default_rng(0x57A8)
    tenants = [[RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(6)] for _ in range(3)]
    engines = [BatchEngine.from_bitmaps(t, layout="dense")
               for t in tenants]
    pool = [BatchGroup(sid, [
        BatchQuery("or", (0, 1, 2), form="bitmap"),
        BatchQuery("and", (1, 2, 3), form="bitmap"),
        BatchQuery("xor", (0, 2, 4), form="bitmap"),
        BatchQuery("andnot", (0, 1, 3), form="bitmap"),
    ]) for sid in range(3)]
    want = MultiSetBatchEngine(engines).execute(pool, engine="xla")
    shapes, mismatches = [], 0
    for rows, data in ((4, 1), (2, 2)):
        mesh = Mesh(np.array(jax.devices()[:rows * data]).reshape(
            rows, data), ("rows", "data"))
        got = ShardedBatchEngine(engines, mesh=mesh).execute(pool)
        ok = all(a.cardinality == b.cardinality and a.bitmap == b.bitmap
                 for grows, wrows in zip(got, want)
                 for a, b in zip(grows, wrows))
        shapes.append({"mesh": [rows, data], "ok": ok})
        mismatches += not ok
    print(json.dumps({"smoke_sharded": shapes,
                      "ok": mismatches == 0}))
    return 1 if mismatches else 0


def expr_smoke() -> int:
    """Fused-expression bit-exactness smoke (ISSUE 8 + 11): a depth-2/3
    expression pool executed fused (one launch) must match the
    host-side sequential evaluator exactly — clean AND through a forced
    pallas demotion, AND on the one-kernel megakernel rung (clean +
    demoted down its megakernel -> pallas ladder).  Returns 0 on
    parity, 1 on divergence."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import BatchEngine
    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.runtime import faults

    rng = np.random.default_rng(0xE5A)
    bms = [RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 1500).astype(np.uint32)))
        for _ in range(8)]
    eng = BatchEngine.from_bitmaps(bms, layout="dense")
    pool = (expr.random_expr_pool(8, 6, depth=2, seed=41, form="bitmap")
            + expr.random_expr_pool(8, 6, depth=3, seed=42,
                                    form="bitmap"))
    want = [expr.evaluate_host(q.expr, bms) for q in pool]
    cells, mismatches = [], 0
    got = eng.execute(pool, engine="xla")
    ok = all(g.cardinality == w.cardinality and g.bitmap == w
             for g, w in zip(got, want))
    cells.append({"case": "fused", "ok": ok})
    mismatches += not ok
    with faults.inject("lowering@pallas=1.0:43"):
        got = eng.execute(pool, engine="pallas")
    ok = all(g.cardinality == w.cardinality and g.bitmap == w
             for g, w in zip(got, want))
    cells.append({"case": "fused-demoted", "ok": ok})
    mismatches += not ok
    # one-kernel hot path (ISSUE 11): the megakernel rung clean, and
    # its demotion ladder (megakernel -> pallas) under an injected
    # lowering fault — both pinned bit-exact vs the host evaluator
    got = eng.execute(pool, engine="megakernel")
    ok = all(g.cardinality == w.cardinality and g.bitmap == w
             for g, w in zip(got, want))
    cells.append({"case": "megakernel", "ok": ok})
    mismatches += not ok
    with faults.inject("lowering@megakernel=1.0:44"):
        got = eng.execute(pool, engine="megakernel")
    ok = all(g.cardinality == w.cardinality and g.bitmap == w
             for g, w in zip(got, want))
    cells.append({"case": "megakernel-demoted", "ok": ok})
    mismatches += not ok
    print(json.dumps({"smoke_expr": cells, "ok": mismatches == 0}))
    return 1 if mismatches else 0


def serving_smoke() -> int:
    """Serving-loop robustness smoke (ISSUE 10, see module docstring).
    Returns 0 when every contract holds, 1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.obs import memory as obs_memory
    from roaringbitmap_tpu.parallel import (BatchEngine, BatchQuery,
                                            MultiSetBatchEngine)
    from roaringbitmap_tpu.runtime import errors, faults, guard
    from roaringbitmap_tpu.serving import (AdmissionRejected, RequestShed,
                                           ServingLoop, ServingPolicy,
                                           ServingRequest)

    rng = np.random.default_rng(0x5E12)
    tenants = [[RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 16, 900).astype(np.uint32)))
        for _ in range(6)] for _ in range(3)]
    engine = MultiSetBatchEngine(
        [BatchEngine.from_bitmaps(t, layout="dense") for t in tenants])
    loop = ServingLoop(engine, ServingPolicy(
        pool_target=4, max_queue=6, default_deadline_ms=120_000.0,
        guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)))
    baseline = obs_memory.LEDGER.snapshot()
    checks: dict = {}
    tickets, rejected = [], []
    ops = ("or", "and", "xor", "andnot")
    for i in range(24):
        try:
            tickets.append(loop.submit(ServingRequest(
                i % 3, BatchQuery(ops[i % 4], (0, 1, 2)),
                tenant=f"t{i % 3}")))
        except AdmissionRejected as exc:
            rejected.append(exc)
    loop.drain()                         # serve the admitted backlog
    doomed = loop.submit(ServingRequest(
        0, BatchQuery("or", (0, 1)), tenant="t0", deadline_ms=1.0))
    faults.advance_clock(0.05)
    loop.drain()
    checks["typed_rejections"] = bool(rejected) and all(
        isinstance(e, errors.RoaringRuntimeError) and e.reason
        for e in rejected)
    checks["typed_shed"] = (doomed.status == "shed"
                            and isinstance(doomed.error, RequestShed)
                            and doomed.error.reason == "expired")
    checks["nothing_silent"] = all(
        t.status == "done" or t.error is not None for t in tickets)
    served = [t for t in tickets if t.status == "done"]
    checks["served"] = bool(served)
    checks["bit_exact"] = all(
        t.result.cardinality == engine._engines[
            t.request.set_id]._sequential_one(t.request.query).cardinality
        for t in served)
    checks["ledger_baseline"] = \
        obs_memory.LEDGER.snapshot() == baseline
    ok = all(checks.values())
    print(json.dumps({"smoke_serving": checks, "ok": ok}))
    return 0 if ok else 1


def lattice_smoke() -> int:
    """Closed-lattice smoke (ISSUE 13, docs/LATTICE.md): a diverse-
    tenant trace (>= 32 distinct pool shapes) replayed through a
    warmed-lattice serving loop must compile ZERO new programs, record
    zero escapes, and serve bit-exactly vs an unwarmed control engine.
    Returns 0 when every contract holds, 1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu.obs import metrics as obs_metrics
    from roaringbitmap_tpu.parallel import (BatchQuery,
                                            MultiSetBatchEngine)
    from roaringbitmap_tpu.runtime import faults, guard
    from roaringbitmap_tpu.runtime import lattice as rt_lattice
    from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                           ServingRequest)
    from roaringbitmap_tpu.utils import datasets

    misses = obs_metrics.compile_miss_total

    faults.reset_clock()
    s, per_tenant = 4, 8
    tenants = [datasets.synthetic_bitmaps(
        per_tenant, seed=0x7A + i, universe=1 << 16, density=0.008)
        for i in range(s)]
    rng = np.random.default_rng(0x1A5E)
    ops = ("or", "and", "xor", "andnot")
    reqs, shapes = [], set()
    for i in range(96):
        op = ops[int(rng.integers(4))]
        operands = tuple(int(x) for x in rng.choice(
            per_tenant, size=int(rng.integers(2, 6)), replace=False))
        sid = int(rng.integers(s))
        reqs.append(ServingRequest(sid, BatchQuery(op, operands),
                                   tenant=f"t{sid}"))
        shapes.add((sid, op, operands))
    checks: dict = {"distinct_shapes": len(shapes) >= 32}

    # unwarmed control: the same trace through a lattice-free engine
    rt_lattice.deactivate()
    control = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                   layout="dense")
    want = [control._engines[r.set_id]._sequential_one(
        r.query).cardinality for r in reqs]

    engine = MultiSetBatchEngine.from_bitmap_sets(tenants,
                                                  layout="dense")
    loop = ServingLoop(engine, ServingPolicy(
        pool_target=8, max_queue=4096, default_deadline_ms=600_000.0,
        guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda _s: None)))
    rep = loop.warmup(profile="q=8,;rows=8,;keys=1,;heads=both;pool=8,")
    checks["sealed"] = bool(rep["lattice"]["sealed"])
    m0 = misses()
    tickets = loop.replay((i * 1e-3, r) for i, r in enumerate(reqs))
    checks["all_served"] = all(t.ok for t in tickets)
    checks["zero_new_compiles"] = misses() == m0
    checks["zero_escapes"] = rt_lattice.escape_total() == 0
    checks["bit_exact_vs_control"] = all(
        t.ok and t.result.cardinality == w
        for t, w in zip(tickets, want))
    rt_lattice.deactivate()
    ok = all(checks.values())
    print(json.dumps({"smoke_lattice": checks,
                      "compiled_points": rep["lattice"]["compiled"],
                      "ok": ok}))
    return 0 if ok else 1


def olap_smoke() -> int:
    """Analytics OLAP smoke (ISSUE 15, docs/ANALYTICS.md): fused
    filter-then-aggregate queries — ``sum_`` / ``top_k`` roots and
    value-predicate filters over attached BSI and RangeBitmap columns —
    bit-exact vs the host oracle (``expr.evaluate_host_agg``) on every
    engine rung, through a forced fault demotion to the sequential
    oracle floor, and vs the two-phase baseline; failures must be
    TYPED (unattached column -> KeyError, sum_ bitmap form ->
    ValueError), never silent.  Returns 0 when every contract holds,
    1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.analytics import (BsiColumn, RangeColumn,
                                             two_phase_execute)
    from roaringbitmap_tpu.parallel import BatchEngine, expr
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.runtime import faults

    faults.reset_clock()
    rng = np.random.default_rng(0x01A5)
    uni = 1 << 15
    bms = [RoaringBitmap.from_values(np.unique(
        rng.integers(0, uni, 1200)).astype(np.uint32))
        for _ in range(4)]
    ds = DeviceBitmapSet(bms, layout="dense")
    ids = np.unique(rng.integers(0, uni, 4000)).astype(np.uint32)
    price = BsiColumn("price", ids,
                      rng.integers(0, 5000, ids.size).astype(np.int64))
    lat = RangeColumn("lat",
                      rng.integers(0, 1 << 34, 2000).astype(np.int64))
    ds.attach_column(price)
    ds.attach_column(lat)
    eng = BatchEngine(ds, result_cache=None)
    cols = {"price": price, "lat": lat}

    queries = [
        expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                 expr.range_("price", 100, 3000)),
                       form="bitmap"),
        expr.ExprQuery(expr.andnot(expr.cmp("lat", "ge", 1 << 32),
                                   expr.ref(2)), form="bitmap"),
        expr.ExprQuery(expr.sum_(
            "price", found=expr.and_(expr.or_(0, 1),
                                     expr.range_("price", 50, 4000)))),
        expr.ExprQuery(expr.top_k("price", 7, found=expr.or_(0, 2)),
                       form="bitmap"),
    ]

    def oracle(q):
        if expr.is_agg(q.expr):
            card, value, bm = expr.evaluate_host_agg(q.expr, bms, cols)
            return card, value, bm
        bm = expr.evaluate_host(q.expr, bms, cols)
        return bm.cardinality, None, bm

    def exact(rows) -> bool:
        for q, r in zip(queries, rows):
            card, value, bm = oracle(q)
            if (r.cardinality, r.value) != (card, value):
                return False
            if q.form == "bitmap" and bm is not None \
                    and r.bitmap != bm:
                return False
        return True

    checks: dict = {}
    for rung in ("xla", "xla-vmap", "pallas"):
        checks[f"bit_exact_{rung}"] = exact(
            eng.execute(queries, engine=rung, fallback=False))
    with faults.inject("lowering@batch_engine=1.0:5"):
        checks["bit_exact_demoted_to_oracle_floor"] = exact(
            eng.execute(queries))
    aggs = [q for q in queries if expr.is_agg(q.expr)]
    tp = two_phase_execute(eng, aggs)
    fused = eng.execute(aggs)
    checks["two_phase_agrees"] = all(
        (a.cardinality, a.value) == (b.cardinality, b.value)
        and a.bitmap == b.bitmap for a, b in zip(fused, tp))
    try:
        eng.execute([expr.ExprQuery(expr.cmp("nope", "le", 1))])
        checks["unattached_column_typed"] = False
    except KeyError:
        checks["unattached_column_typed"] = True
    try:
        expr.ExprQuery(expr.sum_("price"), form="bitmap")
        checks["sum_bitmap_form_typed"] = False
    except ValueError:
        checks["sum_bitmap_form_typed"] = True
    ok = all(checks.values())
    print(json.dumps({"smoke_olap": checks, "ok": ok}))
    return 0 if ok else 1


def mutation_smoke() -> int:
    """Mutation-subsystem smoke (ISSUE 12, docs/MUTATION.md): (a) a
    random in-place delta is bit-exact vs the host oracle across
    or/xor/and, with patch AND escalated-repack modes both exercised
    and typed-only failure (``repack="never"`` raises); (b) the
    materialized result cache serves repeated queries bit-exactly and a
    version bump invalidates EXACTLY the dependent entries, with the
    HBM ledger balanced after the drop.  Nothing silent: every contract
    is an explicit check.  Returns 0 when all hold, 1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.mutation import ResultCache
    from roaringbitmap_tpu.obs import memory as obs_memory
    from roaringbitmap_tpu.parallel import BatchEngine, BatchQuery
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet

    rng = np.random.default_rng(0x12A)
    bms = [RoaringBitmap.from_values(
        np.unique(rng.integers(0, 1 << 17, 1800).astype(np.uint32)))
        for _ in range(6)]
    ds = DeviceBitmapSet(bms, layout="dense")
    checks: dict = {}

    def oracle(hosts):
        o = x = a = hosts[0].clone()
        o, x, a = hosts[0].clone(), hosts[0].clone(), hosts[0].clone()
        for b in hosts[1:]:
            o, x, a = o | b, x ^ b, a & b
        return o, x, a

    hosts = list(bms)
    adds = {1: rng.integers(0, 1 << 17, 16).astype(np.uint32)}
    removes = {2: np.unique(rng.integers(0, 1 << 17, 8)
                            ).astype(np.uint32)}
    rep = ds.apply_delta(adds=adds, removes=removes)
    arb = RoaringBitmap()
    arb.add_many(adds[1])
    rrb = RoaringBitmap()
    rrb.add_many(removes[2])
    hosts[1] = hosts[1] | arb
    hosts[2] = hosts[2] - rrb
    o, x, a = oracle(hosts)
    checks["patch_mode"] = rep["mode"] == "patch"
    checks["patch_bit_exact"] = (ds.aggregate("or") == o
                                 and ds.aggregate("xor") == x
                                 and ds.aggregate("and") == a)
    new_val = int((0xF1F0 << 16) + 1)
    rep2 = ds.apply_delta(adds={0: [new_val]})
    hosts[0] = hosts[0].clone()
    hosts[0].add(new_val)
    checks["repack_mode"] = (rep2["mode"] == "repack"
                             and rep2["repack_reason"] == "structural")
    checks["repack_bit_exact"] = ds.aggregate("or") == oracle(hosts)[0]
    try:
        ds.apply_delta(adds={0: [(0xF2F0 << 16) + 1]}, repack="never")
        checks["typed_never"] = False
    except ValueError:
        checks["typed_never"] = True

    rc = ResultCache(8 << 20)
    eng_a = BatchEngine(DeviceBitmapSet(bms[:3], layout="dense"),
                        result_cache=rc)
    eng_b = BatchEngine(DeviceBitmapSet(bms[3:], layout="dense"),
                        result_cache=rc)
    qa = [BatchQuery("or", (0, 1)), BatchQuery("xor", (1, 2),
                                               form="bitmap")]
    qb = [BatchQuery("or", (0, 2), form="bitmap")]
    first = [r.cardinality for r in eng_a.execute(qa)]
    eng_b.execute(qb)
    second = [r.cardinality for r in eng_a.execute(qa)]
    checks["cache_bit_exact"] = (first == second
                                 and first[0] == (bms[0] | bms[1]
                                                  ).cardinality)
    checks["cache_hits"] = rc.stats()["hits"] >= 2
    entries0 = rc.stats()["entries"]
    eng_a._ds.apply_delta(adds={1: [5]})
    s = rc.stats()
    # exactly the two entries referencing set A source 1 drop; set B's
    # entry survives, and the ledger mirrors the cache's bytes
    checks["exact_invalidation"] = (
        entries0 == 3 and s["entries"] == 1 and s["invalidations"] == 2)
    checks["ledger_balanced"] = (
        obs_memory.LEDGER.resident_bytes("result_cache") >= rc.nbytes
        and rc.nbytes > 0)
    post = [r.cardinality for r in eng_a.execute(qa)]
    hosts_a = eng_a._ds.host_bitmaps()
    checks["post_invalidation_bit_exact"] = (
        post[0] == (hosts_a[0] | hosts_a[1]).cardinality)
    ok = all(checks.values())
    print(json.dumps({"smoke_mutation": checks, "ok": ok}))
    return 0 if ok else 1


def pod_smoke() -> int:
    """Pod front-door smoke (ISSUE 14, docs/POD.md): a routed
    2-host simulated pod serving a mixed stream — mis-routed arrivals
    forward, a forced host drop degrades through the ``reroute`` rung
    with typed errors only (nothing silent), and every routed result is
    bit-exact vs the sequential reference.  Returns 0 when every
    contract holds, 1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            MultiSetBatchEngine, podmesh)
    from roaringbitmap_tpu.runtime import errors, faults, guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                           ServingRequest)

    rng = np.random.default_rng(0x90D5)
    sets = [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
        rng.integers(0, 1 << 16, 800).astype(np.uint32)))
        for _ in range(5)], layout="dense") for _ in range(3)]
    plan = podmesh.PlacementPlan(
        regimes=("replicated-2", "local", "local"),
        hosts=((0, 1), (0,), (1,)), bytes_per_host=(0, 0))
    fd = PodFrontDoor(
        sets, pod=podmesh.PodMesh.simulate(2), plan=plan,
        policy=ServingPolicy(
            pool_target=4, default_deadline_ms=600_000.0,
            guard=guard.GuardPolicy(backoff_base=0.0,
                                    sleep=lambda s: None)))
    ref = MultiSetBatchEngine(sets)
    ops = ("or", "and", "xor", "andnot")
    tickets = [fd.submit(ServingRequest(
        i % 3, BatchQuery(ops[i % 4], (0, 1, 2)), tenant=f"t{i % 3}"),
        via_host=i % 2) for i in range(16)]
    victim = next(h for h in (0, 1)
                  if any(t.pod_host == h for t in tickets))
    with faults.inject(f"coordinator@host{victim}=1.0:13"):
        fd.pump()                        # the host drop fires here
        fd.drain()
    checks: dict = {}
    checks["host_dropped_typed"] = (fd.stats["host_drops"] == 1
                                    and not fd.pod.is_alive(victim))
    checks["rerouted"] = fd.stats["reroutes"] > 0
    checks["forwarded"] = fd.stats["forwarded"] > 0
    checks["nothing_silent"] = all(
        t.status == "done" or isinstance(
            t.error, errors.RoaringRuntimeError) for t in tickets)
    served = [t for t in tickets if t.status == "done"]
    checks["all_served"] = len(served) == len(tickets)
    checks["bit_exact"] = all(
        t.result.cardinality == ref._engines[t.pod_sid]._sequential_one(
            t.query).cardinality for t in served)
    ok = all(checks.values())
    print(json.dumps({"smoke_pod": checks, "ok": ok}))
    return 0 if ok else 1


def resident_smoke() -> int:
    """Persistent resident-queue smoke (ISSUE 16, docs/SERVING.md
    "Resident pump"): fused pools served through the descriptor ring
    must be bit-exact vs BOTH the one-shot megakernel dispatch and the
    host oracle on flat boolean, expression-DAG, and
    filter-then-aggregate roots; a WEDGED ring must escape typed
    (``ResidentEscape(reason="wedged")``, never silent) and the
    serving loop must demote that pool to the one-shot host-dispatch
    path, still bit-exact.  Returns 0 when every contract holds, 1
    otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.analytics import BsiColumn
    from roaringbitmap_tpu.obs import metrics as obs_metrics
    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
    from roaringbitmap_tpu.parallel.multiset import (BatchGroup,
                                                     MultiSetBatchEngine)
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.runtime import lattice as rt_lattice
    from roaringbitmap_tpu.serving import (ResidentEscape, ResidentQueue,
                                           ServingLoop, ServingPolicy,
                                           ServingRequest)

    def tenant(seed: int, uni: int, vmax: int):
        r = np.random.default_rng(seed)
        bms = [RoaringBitmap.from_values(np.unique(
            r.integers(0, uni, 600)).astype(np.uint32))
            for _ in range(4)]
        ds = DeviceBitmapSet(bms, layout="dense")
        ids = np.unique(r.integers(0, uni, 1500)).astype(np.uint32)
        col = BsiColumn("price", ids,
                        r.integers(0, vmax, ids.size).astype(np.int64))
        ds.attach_column(col)
        return bms, ds, col

    tenants = [tenant(0x161, 1 << 12, 400), tenant(0x162, 1 << 11, 120)]
    depth = max(c.depth_pad for _, _, c in tenants)
    eng = MultiSetBatchEngine([ds for _, ds, _ in tenants])
    checks: dict = {}
    try:
        eng.warmup(profile=f"q=4,;rows=16,;keys=4,;"
                           f"ops=or,and,xor,andnot;heads=both;pool=16,;"
                           f"expr=2;bsi={depth},")
        rq = ResidentQueue(eng)
        checks["vocab_sealed"] = rq.seal_vocab() and rq.active

        # flat queries ride the ring inside a FUSED pool: a pool with
        # no fused section assembles no one-kernel program at all (the
        # megakernel is the expression assembler), so the flat case
        # anchors one depth-2 expression and pools flat BatchQuerys
        # around it — the one kernel executes both
        pools = {
            "flat": [BatchGroup(0, [BatchQuery("or", (0, 1, 2)),
                                    BatchQuery("and", (1, 2))]),
                     BatchGroup(1, [BatchQuery("xor", (0, 3)),
                                    expr.ExprQuery(expr.andnot(
                                        expr.or_(0, 1), expr.ref(2)))])],
            "expression": [
                BatchGroup(0, [expr.ExprQuery(
                    expr.andnot(expr.or_(0, 1), expr.ref(2)))]),
                BatchGroup(1, [expr.ExprQuery(
                    expr.and_(expr.or_(0, 1),
                              expr.cmp("price", "le", 90)),
                    form="bitmap")])],
            "filter_then_aggregate": [
                BatchGroup(0, [expr.ExprQuery(expr.sum_(
                    "price", found=expr.and_(
                        expr.or_(0, 1),
                        expr.cmp("price", "ge", 50))))]),
                BatchGroup(1, [expr.ExprQuery(
                    expr.top_k("price", 5, found=expr.or_(0, 2)),
                    form="bitmap")])],
        }

        import functools
        import operator
        _FLAT_OPS = {"or": operator.or_, "and": operator.and_,
                     "xor": operator.xor, "andnot": lambda a, b: a - b}

        def exact(groups, rows) -> bool:
            for g, rs in zip(groups, rows):
                bms_x, _, col_x = tenants[g.set_id]
                cols = {"price": col_x}
                for q, r in zip(g.queries, rs):
                    if isinstance(q, BatchQuery):
                        want = functools.reduce(
                            _FLAT_OPS[q.op],
                            [bms_x[i] for i in q.operands])
                        if r.cardinality != want.cardinality:
                            return False
                        continue
                    if expr.is_agg(q.expr):
                        card, value, bm = expr.evaluate_host_agg(
                            q.expr, bms_x, cols)
                    else:
                        bm = expr.evaluate_host(q.expr, bms_x, cols)
                        card, value = bm.cardinality, None
                    if (r.cardinality, r.value) != (card, value):
                        return False
                    if q.form == "bitmap" and bm is not None \
                            and r.bitmap != bm:
                        return False
            return True

        for name, groups in pools.items():
            ring_rows = rq.serve(groups)
            one_shot = eng.execute(groups, engine="megakernel",
                                   fallback=False)
            checks[f"ring_bit_exact_{name}"] = exact(groups, ring_rows)
            checks[f"one_shot_agrees_{name}"] = all(
                (a.cardinality, a.value, a.bitmap)
                == (b.cardinality, b.value, b.bitmap)
                for ga, gb in zip(ring_rows, one_shot)
                for a, b in zip(ga, gb))
        checks["ring_served_all"] = rq.stats["served"] == len(pools)

        # wedged ring: the direct lane must raise the TYPED escape ...
        rq.ring.wedge()
        try:
            rq.serve(pools["flat"])
            checks["wedged_escape_typed"] = False
        except ResidentEscape as exc:
            checks["wedged_escape_typed"] = exc.reason == "wedged"
        # ... and the serving loop must demote that pool to the
        # one-shot host-dispatch path (counter moves), still bit-exact
        loop = ServingLoop(eng, ServingPolicy(
            resident=True, pool_target=2, engine="megakernel",
            default_deadline_ms=600_000.0,
            guard=guard.GuardPolicy(backoff_base=0.0,
                                    sleep=lambda s: None)))
        loop._resident.ring.wedge()
        d0 = obs_metrics.counter("rb_serving_dispatches_total",
                                 site="serving").value
        wq = expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                      expr.cmp("price", "le", 200)))
        wt = [loop.submit(ServingRequest(0, wq, tenant="w"))
              for _ in range(2)]
        loop.drain()
        d1 = obs_metrics.counter("rb_serving_dispatches_total",
                                 site="serving").value
        ref = expr.evaluate_host(wq.expr, tenants[0][0],
                                 {"price": tenants[0][2]})
        checks["wedged_demotes_to_dispatch"] = d1 > d0
        checks["demoted_bit_exact"] = all(
            t.status == "done"
            and t.result.cardinality == ref.cardinality for t in wt)
    finally:
        rt_lattice.deactivate()
    ok = all(checks.values())
    print(json.dumps({"smoke_resident": checks, "ok": ok}))
    return 0 if ok else 1


def durability_smoke() -> int:
    """Durable-tenant smoke (ISSUE 17, docs/DURABILITY.md): a journaled
    delta stream crashed clean AND torn recovers bit-exactly from
    snapshot + journal tail (typed ``InjectedCrash`` on the way down,
    nothing silent), and a live migration under traffic serves exactly
    with zero failed requests.  Returns 0 when every contract holds, 1
    otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import tempfile

    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap
    from roaringbitmap_tpu.mutation.durability import (DurableTenant,
                                                       FlushPolicy,
                                                       recover_tenant)
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            podmesh)
    from roaringbitmap_tpu.runtime import errors, faults, guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                           ServingRequest,
                                           migrate_tenant)

    rng = np.random.default_rng(0xD07B)
    checks: dict = {}

    def mk_hosts():
        return [RoaringBitmap.from_values(np.unique(
            rng.integers(0, 1 << 15, 500).astype(np.uint32))
            .astype(np.uint32)) for _ in range(3)]

    with tempfile.TemporaryDirectory(prefix="rb_dur_smoke_") as root:
        policy = FlushPolicy(mode="never")
        # clean crash at the durable-not-applied point: recovery replays
        hosts = mk_hosts()
        t = DurableTenant(DeviceBitmapSet(hosts), root=root,
                          tenant="clean", policy=policy,
                          snapshot_every=None)
        t.apply_delta(adds={0: [70001]})
        crashed_typed = False
        with faults.inject("crash@pre_apply=1.0:3"):
            try:
                t.apply_delta(adds={1: [70002, 70003]})
            except errors.InjectedCrash:
                crashed_typed = True
        rec, rep = recover_tenant(root=root, tenant="clean",
                                  policy=policy)
        want = list(hosts)
        want[0] = want[0] | RoaringBitmap.from_values(
            np.asarray([70001], np.uint32))
        want[1] = want[1] | RoaringBitmap.from_values(
            np.asarray([70002, 70003], np.uint32))
        checks["clean_crash_typed"] = crashed_typed
        checks["clean_crash_replayed"] = (
            rep["replayed"] >= 1 and rec.ds.host_bitmaps() == want)
        rec.close()
        # torn crash: the tail truncates, the torn record is NOT
        # replayed, prior records survive
        hosts = mk_hosts()
        t = DurableTenant(DeviceBitmapSet(hosts), root=root,
                          tenant="torn", policy=policy,
                          snapshot_every=None)
        t.apply_delta(adds={0: [70001]})
        crashed_typed = False
        with faults.inject("crash@torn=1.0:3"):
            try:
                t.apply_delta(adds={1: [70002]})
            except errors.InjectedCrash:
                crashed_typed = True
        rec, rep = recover_tenant(root=root, tenant="torn",
                                  policy=policy)
        want = list(hosts)
        want[0] = want[0] | RoaringBitmap.from_values(
            np.asarray([70001], np.uint32))
        checks["torn_crash_typed"] = crashed_typed
        checks["torn_tail_truncated"] = (
            rep["torn"] and rec.ds.host_bitmaps() == want)
        rec.close()
        # live migration under traffic: bit-exact, zero failed requests
        sets = [DeviceBitmapSet(mk_hosts()) for _ in range(2)]
        fd = PodFrontDoor(
            sets, pod=podmesh.PodMesh.simulate(2),
            plan=podmesh.PlacementPlan(
                regimes=("local", "local"), hosts=((0,), (1,)),
                bytes_per_host=(0, 0)),
            policy=ServingPolicy(
                pool_target=4, default_deadline_ms=600_000.0,
                guard=guard.GuardPolicy(backoff_base=0.0,
                                        sleep=lambda s: None)))
        tickets = []

        def ask():
            tickets.append(fd.submit(ServingRequest(
                0, BatchQuery("or", (0, 1, 2)), tenant="t0")))
            fd.drain()
            return int(tickets[-1].result.cardinality)

        base = ask()
        rep = migrate_tenant(
            fd, 0, 1,
            during=lambda _fd: (_fd.apply_delta(0, adds={0: [80001]}),
                                ask()))
        checks["migration_flipped"] = fd.owner_host(0) == 1
        checks["migration_bit_exact"] = ask() == base + 1
        checks["migration_zero_failed"] = all(
            t.status == "done" for t in tickets)
        checks["migration_blip_bounded"] = rep["blip_ms"] < 60_000
    ok = all(checks.values())
    print(json.dumps({"smoke_durability": checks, "ok": ok}))
    return 0 if ok else 1


def obs_smoke() -> int:
    """Observability-plane smoke (docs/OBSERVABILITY.md): cross-host
    trace stitching, the black-box flight recorder, and the merged
    fleet statusz — see the module docstring.  Returns 0 when every
    contract holds, 1 otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import tempfile

    import numpy as np

    from roaringbitmap_tpu import RoaringBitmap, obs
    from roaringbitmap_tpu.obs import flight as obs_flight
    from roaringbitmap_tpu.obs import statusz as obs_statusz
    from roaringbitmap_tpu.parallel import (BatchQuery, DeviceBitmapSet,
                                            podmesh)
    from roaringbitmap_tpu.runtime import guard
    from roaringbitmap_tpu.serving import (PodFrontDoor, ServingPolicy,
                                           ServingRequest)

    rng = np.random.default_rng(0x0B5)
    checks: dict = {}
    with tempfile.TemporaryDirectory(prefix="rb_obs_smoke_") as root:
        trace_path = os.path.join(root, "trace.jsonl")
        obs_flight.configure(dir=os.path.join(root, "flight"))
        obs_flight.reset()
        obs.enable(trace_path)
        try:
            sets = [DeviceBitmapSet([RoaringBitmap.from_values(np.unique(
                rng.integers(0, 1 << 15, 600).astype(np.uint32)))
                for _ in range(4)], layout="dense") for _ in range(3)]
            fd = PodFrontDoor(
                sets, pod=podmesh.PodMesh.simulate(2),
                plan=podmesh.PlacementPlan(
                    regimes=("replicated-2", "local", "local"),
                    hosts=((0, 1), (0,), (1,)), bytes_per_host=(0, 0)),
                policy=ServingPolicy(
                    pool_target=4, default_deadline_ms=600_000.0,
                    guard=guard.GuardPolicy(backoff_base=0.0,
                                            sleep=lambda s: None)))
            tickets = [fd.submit(ServingRequest(
                i % 3, BatchQuery("or", (0, 1, 2)), tenant=f"t{i % 3}"),
                via_host=1 - (i % 2)) for i in range(8)]
            victim = next(h for h in (0, 1)
                          if any(t.pod_host == h for t in tickets))
            fd.fail_host(victim)
            fd.drain()
            checks["all_served"] = all(t.status == "done"
                                       for t in tickets)
            sz = fd.statusz()
        finally:
            obs.disable()
        # one trace id must stitch the forwarded + rerouted lifecycle
        spans = [json.loads(ln) for ln in open(trace_path)]
        by_trace: dict = {}
        for s in spans:
            by_trace.setdefault(s["trace_id"], set()).add(s["name"])
        need = {"pod.route", "serving.admit", "pod.reroute",
                "serving.request"}
        checks["stitched_trace"] = any(need <= names
                                       for names in by_trace.values())
        # the host loss must have dumped a schema-shaped flight artifact
        dumps = []
        fdir = os.path.join(root, "flight")
        if os.path.isdir(fdir):
            dumps = [json.load(open(os.path.join(fdir, f)))
                     for f in sorted(os.listdir(fdir))
                     if f.startswith("flight-")]
        checks["flight_dumped"] = any(
            d.get("kind") == "rb_flight" and d.get("trigger")
            and isinstance(d.get("events"), list) and d["events"]
            and isinstance(d.get("metrics_delta"), dict)
            for d in dumps)
        # merged statusz reports both hosts; re-merging is idempotent
        checks["statusz_hosts"] = (
            sz.get("merged") is True
            and {"0", "1"} <= set(sz.get("hosts") or {}))
        checks["statusz_idempotent"] = (
            obs_statusz.merge([sz])["counters"] == sz["counters"])
    ok = all(checks.values())
    print(json.dumps({"smoke_obs": checks, "ok": ok}))
    return 0 if ok else 1


def wire_smoke() -> int:
    """Binary wire front-door smoke (ISSUE 20, docs/WIRE.md): pipelined
    mixed traffic over a loopback WireServer must come back bit-exact
    vs the sequential per-set reference; overload (full tenant queue)
    and the auth boundary (unknown token, ungranted tenant) must answer
    TYPED wire error frames on a live connection; a garbled inbound
    frame must die as CorruptInput — zero silent drops, zero raw
    socket/struct escapes.  Returns 0 when every contract holds, 1
    otherwise."""
    sys.path.insert(0, os.path.dirname(_HERE))
    import numpy as np

    from roaringbitmap_tpu.parallel import expr
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
    from roaringbitmap_tpu.parallel.multiset import MultiSetBatchEngine
    from roaringbitmap_tpu.runtime import errors, guard
    from roaringbitmap_tpu.serving import (AdmissionRejected,
                                           ServingLoop, ServingPolicy,
                                           ServingRequest, replay)
    from roaringbitmap_tpu.wire import WireClient, WireServer
    from roaringbitmap_tpu.wire import protocol as wp

    profile = replay.ReplayProfile(sets=2, sources=6, tenants=4,
                                   density=400, users=1 << 16, seed=7)
    nosleep = guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)

    def mk_loop(**kw):
        bitmap_sets, columns = replay.build_dataset(profile)
        sets = [DeviceBitmapSet(b, layout="dense") for b in bitmap_sets]
        replay.attach_columns(sets, profile, columns)
        kw.setdefault("pool_target", 4)
        kw.setdefault("guard", nosleep)
        kw.setdefault("default_deadline_ms", 600_000.0)
        return ServingLoop(MultiSetBatchEngine(sets),
                           ServingPolicy(**kw))

    rng = np.random.default_rng(0x31)

    def mk_reqs(n):
        out = []
        for i in range(n):
            sid = int(rng.integers(2))
            form = "bitmap" if i % 3 == 0 else "cardinality"
            if i % 5 == 2:
                q = expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                             expr.not_(2)), form=form)
            elif i % 5 == 4:
                q = expr.ExprQuery(expr.sum_("v", expr.or_(0, 1)),
                                   form="cardinality")
            else:
                op = ("or", "and", "xor")[int(rng.integers(3))]
                q = BatchQuery(op, tuple(int(x) for x in rng.choice(
                    6, size=3, replace=False)), form=form)
            out.append(ServingRequest(sid, q, tenant=f"t{sid}"))
        return out

    checks: dict = {}
    # (a) pipelined parity: mixed shapes over TCP vs the sequential ref
    loop = mk_loop()
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        reqs = mk_reqs(18)
        tickets = cl.submit_many(reqs)
        exact = True
        for t, r in zip(tickets, reqs):
            res = t.value(timeout=120)
            ref = loop._engine._engines[r.set_id]._sequential_result(
                r.query)
            exact = exact and res.cardinality == ref.cardinality
            if r.query.form == "bitmap" and not res.degraded:
                exact = exact and res.bitmap == ref.bitmap
            if ref.value is not None:
                exact = exact and res.value == ref.value
        checks["pipelined_parity"] = exact
        cl.close()
    # (b) overload answers typed on a LIVE connection, zero silent
    q = BatchQuery("or", (0, 1, 2))
    loop = mk_loop(max_queue=2, pool_target=64)
    with WireServer(loop, coalesce_s=0.05) as srv:
        cl = WireClient(srv.address)
        tickets = cl.submit_many(
            [ServingRequest(0, q, tenant="t0") for _ in range(10)])
        for t in tickets:
            t.wait(60)
        rej = [t for t in tickets if t.status == "failed"]
        done = [t for t in tickets if t.ok]
        checks["overload_typed"] = (
            bool(rej)
            and all(isinstance(t.error, AdmissionRejected)
                    for t in rej)
            and len(done) + len(rej) == 10)
        try:
            cl.ping()
            checks["conn_survives_rejection"] = True
        except errors.RoaringRuntimeError:
            checks["conn_survives_rejection"] = False
        cl.close()
    # (c) auth boundary: unknown token refused before the loop, tenant
    # grants enforced per request on a connection that stays live
    loop = mk_loop()
    with WireServer(loop, auth={"tok": ["t0"]}) as srv:
        try:
            WireClient(srv.address, token="evil")
            checks["auth_token"] = False
        except errors.AuthRejected:
            checks["auth_token"] = loop.stats["admitted"] == 0
        cl = WireClient(srv.address, token="tok")
        bad = cl.submit(ServingRequest(0, q, tenant="t1"))
        try:
            bad.value(60)
            checks["auth_tenant"] = False
        except errors.AuthRejected:
            checks["auth_tenant"] = True
        cl.close()
    # (d) a garbled inbound frame dies as CorruptInput, never a raw
    # struct/socket escape
    loop = mk_loop()
    with WireServer(loop) as srv:
        cl = WireClient(srv.address)
        t = cl._reserve()
        with cl._wlock:
            cl._sock.sendall(wp.garble(wp.encode_frame(
                wp.T_PING, 99, {})))
        t.wait(30)
        checks["garbage_typed"] = (t.status == "failed" and isinstance(
            t.error, errors.CorruptInput))
        cl.close()
    ok = all(checks.values())
    print(json.dumps({"smoke_wire": checks, "ok": ok}))
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(
        description="trajectory regression sentry over bench round files")
    ap.add_argument("files", nargs="*",
                    help="round documents, oldest first (default: "
                         "BENCH_r[0-9]*.json in the repo root, sorted)")
    ap.add_argument("--threshold", type=float, default=0.25,
                    help="fractional newest-round step against a lane's "
                         "direction that gates (default 0.25)")
    ap.add_argument("--drift-threshold", type=float, default=0.25,
                    help="cumulative monotone move against direction over "
                         "the last >= 3 transitions that gates "
                         "(default 0.25)")
    ap.add_argument("--fail", action="store_true",
                    help="exit 1 when any gated lane regressed")
    ap.add_argument("--fail-removed", action="store_true",
                    help="also exit 1 when the newest round lost lanes")
    ap.add_argument("--md", default="",
                    help="write the trajectory table to this path instead "
                         "of stdout")
    ap.add_argument("--lanes", default="",
                    help="only analyze lanes whose dotted path contains "
                         "this substring")
    ap.add_argument("--top", type=int, default=40,
                    help="max table rows (flagged lanes always shown)")
    ap.add_argument("--smoke-sharded", action="store_true",
                    help="first run the mesh-sharded parity smoke "
                         "(needs >= 4 devices; exit 1 on divergence)")
    ap.add_argument("--smoke-expr", action="store_true",
                    help="first run the fused-expression bit-exactness "
                         "smoke vs host sequential evaluation (exit 1 "
                         "on divergence)")
    ap.add_argument("--smoke-serving", action="store_true",
                    help="first run the serving-loop robustness smoke "
                         "(typed shed/reject, bit-exact served results, "
                         "ledger baseline; exit 1 on violation)")
    ap.add_argument("--smoke-mutation", action="store_true",
                    help="first run the mutation smoke (bit-exact delta "
                         "patch + escalated repack, exact result-cache "
                         "invalidation, balanced ledger, nothing "
                         "silent; exit 1 on violation)")
    ap.add_argument("--smoke-pod", action="store_true",
                    help="first run the pod front-door smoke (typed "
                         "host-loss degradation through the reroute "
                         "rung, mis-route forwarding, zero silent "
                         "failures, bit-exact routed results; exit 1 "
                         "on violation)")
    ap.add_argument("--smoke-lattice", action="store_true",
                    help="first run the closed-lattice smoke (warmed "
                         "diverse-tenant replay compiles zero programs, "
                         "zero escapes, bit-exact vs unwarmed control; "
                         "exit 1 on violation)")
    ap.add_argument("--smoke-olap", action="store_true",
                    help="first run the analytics OLAP smoke (fused "
                         "filter-then-aggregate bit-exact vs the host "
                         "BSI/RangeBitmap oracle across engine rungs "
                         "incl. fault demotion, typed-only failures; "
                         "exit 1 on violation)")
    ap.add_argument("--smoke-durability", action="store_true",
                    help="first run the durable-tenant smoke (clean + "
                         "torn crash recovery bit-exact from snapshot "
                         "+ journal tail, typed InjectedCrash, live "
                         "migration serving exactly with zero failed "
                         "requests; exit 1 on violation)")
    ap.add_argument("--smoke-resident", action="store_true",
                    help="first run the resident-queue smoke (ring-"
                         "served pools bit-exact vs one-shot megakernel "
                         "AND the host oracle on flat/expression/"
                         "aggregate roots, typed wedged-ring escape + "
                         "demotion to host dispatch; exit 1 on "
                         "violation)")
    ap.add_argument("--smoke-obs", action="store_true",
                    help="first run the observability-plane smoke (one "
                         "stitched cross-host trace id for a forwarded+"
                         "rerouted request, a schema-valid flight dump "
                         "on host loss, merged 2-host statusz; exit 1 "
                         "on violation)")
    ap.add_argument("--smoke-wire", action="store_true",
                    help="first run the binary wire front-door smoke "
                         "(pipelined TCP parity vs the sequential "
                         "reference, typed overload/auth/garbage "
                         "outcomes on live connections, zero silent "
                         "drops; exit 1 on violation)")
    args = ap.parse_args()

    if args.smoke_sharded:
        rc = sharded_smoke()
        if rc:
            return rc
    if args.smoke_serving:
        rc = serving_smoke()
        if rc:
            return rc
    if args.smoke_expr:
        rc = expr_smoke()
        if rc:
            return rc
    if args.smoke_mutation:
        rc = mutation_smoke()
        if rc:
            return rc
    if args.smoke_pod:
        rc = pod_smoke()
        if rc:
            return rc
    if args.smoke_lattice:
        rc = lattice_smoke()
        if rc:
            return rc
    if args.smoke_olap:
        rc = olap_smoke()
        if rc:
            return rc
    if args.smoke_resident:
        rc = resident_smoke()
        if rc:
            return rc
    if args.smoke_durability:
        rc = durability_smoke()
        if rc:
            return rc
    if args.smoke_obs:
        rc = obs_smoke()
        if rc:
            return rc
    if args.smoke_wire:
        rc = wire_smoke()
        if rc:
            return rc

    paths = args.files or sorted(glob.glob(os.path.join(
        os.path.dirname(_HERE), "BENCH_r[0-9]*.json")))
    if not paths:
        print("bench_sentry: no round files found", file=sys.stderr)
        return 2
    rounds, unusable = load_rounds(paths)
    if len(rounds) < 2:
        print(f"bench_sentry: need >= 2 usable rounds, got {len(rounds)} "
              f"(unusable: {unusable})", file=sys.stderr)
        return 2
    series = build_series(rounds)
    if args.lanes:
        series = {ln: v for ln, v in series.items() if args.lanes in ln}
    round_names = [name for name, _ in rounds]
    analysis = analyze(series, round_names, args.threshold,
                       args.drift_threshold)
    added, removed = bench_diff.lane_changes(rounds[-2][1], rounds[-1][1])

    table = markdown_table(series, round_names, analysis, args.top)
    if args.md:
        with open(args.md, "w") as f:
            f.write(table + "\n")
    else:
        print(table)
    regressed = (analysis["step_regressions"]
                 + analysis["drift_regressions"])
    verdict = {
        "rounds": round_names, "unusable": unusable,
        "lanes": len(series),
        "step_regressions": analysis["step_regressions"],
        "drift_regressions": analysis["drift_regressions"],
        "added_lanes": added, "removed_lanes": removed,
        "thresholds": {"step": args.threshold,
                       "drift": args.drift_threshold},
        "ok": not regressed and not (args.fail_removed and removed),
    }
    print(json.dumps(verdict, separators=(",", ":")))
    if args.fail and not verdict["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
