"""Parallel test execution — SURVEY §2.7.2/2.7.3's test-parallelism analog.

The reference runs its JUnit suites in parallel forks (gradle
``maxParallelForks`` / the CI matrix); pytest here runs serially by default
and no xdist plugin is baked into the image, so this runner shards the test
FILES across worker processes:

    python tools/partest.py [-n WORKERS] [pytest args...]

Each worker is a fresh interpreter running ``pytest <its files> -q`` (every
worker re-applies tests/conftest.py's 8-virtual-device CPU pinning, so
shards are hermetic), files are balanced across workers by size as a
runtime proxy (largest first), and the aggregate exit code is nonzero iff
any shard fails.  On a single-core host this degrades gracefully to ~serial
wall-clock; on a many-core host wall-clock approaches the largest shard.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _file_part(sel: str) -> str:
    """The filesystem path of a pytest selector (strip ::Class::test)."""
    return sel.split("::", 1)[0]


def shard_files(files: list[str], n: int) -> list[list[str]]:
    """Greedy longest-processing-time balance, file size as runtime proxy
    (selectors weigh as their file)."""
    size = {f: os.path.getsize(_file_part(f)) for f in files}
    sized = sorted(files, key=lambda f: -size[f])
    buckets: list[tuple[int, list[str]]] = [(0, []) for _ in range(n)]
    for f in sized:
        i = min(range(n), key=lambda j: buckets[j][0])
        total, fs = buckets[i]
        buckets[i] = (total + size[f], fs + [f])
    return [fs for _, fs in buckets if fs]


def main() -> int:
    # hand-rolled parse over sys.argv IN ORDER: argparse's parse_known_args
    # reorders positionals away from their preceding flags, which breaks the
    # flag/value pairing below (--ignore tests/x.py must stay a pair)
    argv = sys.argv[1:]
    workers = max(os.cpu_count() or 1, 1)
    value_flags = {"-k", "-m", "-o", "-p", "-c", "--ignore", "--ignore-glob",
                   "--deselect", "--rootdir", "--confcutdir", "--junitxml"}
    picked: list[str] = []
    through: list[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a in ("-n", "--workers") or a.startswith("--workers="):
            val = (a.split("=", 1)[1] if "=" in a
                   else argv[i + 1] if i + 1 < len(argv) else "")
            try:
                workers = max(int(val), 1)
            except ValueError:
                print(f"partest: {a} needs an integer worker count "
                      f"(got {val!r}); see --help", file=sys.stderr)
                return 2
            i += 1 if "=" in a else 2
        elif a in value_flags and i + 1 < len(argv):
            # a path that is the VALUE of a value-taking pytest flag must
            # stay with its flag, not become a sharded file
            through.extend(argv[i:i + 2])
            i += 2
        elif (_file_part(a).endswith(".py")
              and os.path.exists(os.path.join(REPO, _file_part(a)))):
            picked.append(a)
            i += 1
        else:
            through.append(a)
            i += 1

    if picked:
        files = [os.path.join(REPO, a) for a in picked]
    else:
        test_dir = os.path.join(REPO, "tests")
        files = sorted(
            os.path.join(test_dir, f) for f in os.listdir(test_dir)
            if f.startswith("test_") and f.endswith(".py"))
    shards = shard_files(files, workers)
    t0 = time.perf_counter()
    procs = []
    for i, shard in enumerate(shards):
        cmd = [sys.executable, "-m", "pytest", "-q", *through, *shard]
        # log to a temp FILE, not a pipe: a failing shard's tracebacks can
        # exceed the pipe buffer and stall that worker mid-run
        log = tempfile.TemporaryFile()
        procs.append((i, shard, log, subprocess.Popen(
            cmd, cwd=REPO, stdout=log, stderr=subprocess.STDOUT)))
    rcs = []
    for i, shard, log, p in procs:
        p.wait()
        log.seek(0)
        out = log.read().decode(errors="replace")
        log.close()
        tail = out.strip().splitlines()
        summary = tail[-1] if tail else "(no output)"
        names = ",".join(os.path.basename(_file_part(f)) for f in shard)
        print(f"[shard {i}] {summary}   <- {names}")
        rcs.append(p.returncode)
        if p.returncode not in (0, 5):  # 5 = no tests collected (xdist rule)
            sys.stdout.write(out)
    # a -k filter legitimately empties some shards (rc 5); fail only when a
    # shard really failed, or when NO shard collected anything at all
    hard = [r for r in rcs if r not in (0, 5)]
    rc = hard[0] if hard else (5 if rcs and all(r == 5 for r in rcs) else 0)
    print(f"partest: {len(shards)} shards, rc={rc}, "
          f"{time.perf_counter() - t0:.1f}s wall")
    return rc


if __name__ == "__main__":
    sys.exit(main())
