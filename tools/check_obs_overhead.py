"""CI pin: disabled-tracer overhead on BatchEngine.execute stays < 2%.

The observability contract (docs/OBSERVABILITY.md) promises the span
tracer is effectively free when ``ROARING_TPU_TRACE`` is unset: the
``span()`` fast path is one module-flag check returning a shared no-op.
This check measures that claim against a real Q=64 batch execute:

1. median execute wall time for a Q=64 mixed-op batch (tracer disabled);
2. the span count one execute emits (measured by tracing a single
   execute to a scratch file and counting lines);
3. the per-call cost of a disabled ``span(name, **tags)`` (measured over
   200k calls, kwargs included — the full price an instrumentation site
   pays);
4. the per-call cost of a disabled ``slo.phase(name)`` and a suppressed
   ``slo.query(site)`` (ISSUE 6: the cost/SLO instrumentation is
   compiled in but must stay no-op without an SLO configured — the
   phase sites ride the same bound as the spans).

overhead_fraction = (spans * span_cost + PHASE_SITES * phase_cost
                     + query_cost) * SAFETY / median_execute_seconds
(SAFETY = 3x, which also covers the no-op tag/event/sync calls riding
each span site).  The check fails when the fraction reaches 2% — i.e.
someone made a disabled path allocate, take a lock, or read the
environment per call.

Timing-dependence note: both numerator and denominator are measured on
the same loaded CI host, and the 3x safety margin plus the ~two orders
of magnitude of headroom (measured ~0.05%) keep this stable where an
absolute-time assertion would flake.
"""

from __future__ import annotations

import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MAX_OVERHEAD_FRACTION = 0.02
SAFETY = 3.0
#: slo.phase() sites one execute touches (plan, program_build, dispatch,
#: sync, readback + headroom for future phases)
PHASE_SITES = 8


def main() -> int:
    os.environ.pop("ROARING_TPU_TRACE", None)
    os.environ.pop("ROARING_TPU_SLO_MS", None)

    from roaringbitmap_tpu import obs
    from roaringbitmap_tpu.obs import flight as obs_flight
    from roaringbitmap_tpu.obs import slo as obs_slo
    from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                         random_query_pool)
    from roaringbitmap_tpu.utils import datasets

    obs.refresh_from_env()
    assert not obs.enabled()
    # the flight recorder is ALWAYS on (its span feed hooks trace close,
    # its ring accepts record() calls regardless of the tracer) — the
    # 2% bound below is measured with it armed, which is the production
    # configuration: a disabled tracer must stay free even while the
    # black box runs
    assert obs.trace._on_close is not None, \
        "flight recorder span feed is not installed"
    obs_flight.record("probe", site="check_obs_overhead")
    assert obs_flight.snapshot()["occupancy"] >= 1, \
        "flight ring did not record — the always-on black box is off"
    assert obs.span("probe", q=1) is obs.trace._NOOP, \
        "disabled span() must return the shared no-op"
    assert obs_slo.phase("dispatch") is obs_slo._NOOP, \
        "inactive slo.phase() must return the shared no-op"
    assert obs_slo.query("batch_engine") is obs_slo._NOOP, \
        "slo.query() without a deadline or forced attribution must be "\
        "the shared no-op"

    bms = datasets.synthetic_bitmaps(16, seed=3, universe=1 << 18,
                                     density=0.01)
    eng = BatchEngine.from_bitmaps(bms)
    pool = random_query_pool(16, 64)
    eng.execute(pool)                      # warm: plan + compile
    times = []
    for _ in range(15):
        t0 = time.perf_counter()
        eng.execute(pool)
        times.append(time.perf_counter() - t0)
    execute_s = statistics.median(times)

    # spans one execute emits, counted from a real single-execute trace
    fd, scratch = tempfile.mkstemp(suffix=".jsonl")
    os.close(fd)
    obs.enable(scratch)
    try:
        eng.execute(pool)
    finally:
        obs.disable()
    spans_per_execute = sum(1 for _ in open(scratch))
    os.unlink(scratch)

    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.span("batch.execute", site="batch_engine", q=64,
                 engine="auto", fallback=True)
    per_span_s = (time.perf_counter() - t0) / n

    t0 = time.perf_counter()
    for _ in range(n):
        obs_slo.phase("dispatch")
    per_phase_s = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        obs_slo.query("batch_engine")
    per_query_s = (time.perf_counter() - t0) / n

    overhead = (spans_per_execute * per_span_s
                + PHASE_SITES * per_phase_s + per_query_s) * SAFETY
    frac = overhead / execute_s
    print(f"check_obs_overhead: execute={execute_s * 1e3:.2f} ms, "
          f"{spans_per_execute} spans/execute, "
          f"{per_span_s * 1e9:.0f} ns/disabled-span, "
          f"{per_phase_s * 1e9:.0f} ns/disabled-phase, "
          f"{per_query_s * 1e9:.0f} ns/suppressed-query, "
          f"overhead({SAFETY:g}x safety)={overhead * 1e6:.1f} us "
          f"= {frac * 100:.3f}% (limit "
          f"{MAX_OVERHEAD_FRACTION * 100:.0f}%)")
    if frac >= MAX_OVERHEAD_FRACTION:
        print("check_obs_overhead: FAIL — the disabled-tracer fast path "
              "regressed", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
