"""Diff two bench.py result documents and report per-lane deltas.

Usage::

    python tools/bench_diff.py OLD.json NEW.json [--threshold 0.15] [--fail]

Accepted document shapes (the loader walks a ladder):

1. a **driver capture** (``BENCH_r0*.json``: ``{n, cmd, rc, tail,
   parsed}``) — uses ``parsed`` when the driver managed to parse the
   summary line, else salvages the truncated final JSON line from the
   bounded ``tail`` (rounds before the SUMMARY_MAX_BYTES cap lost the
   line's head; the tail's END is intact, so scanning forward for the
   first parseable suffix recovers the trailing lanes);
2. a **bench.py summary line** (``{metric, value, ...}``) or the full
   ``benchmarks/bench_full.json`` document.

Lanes are the numeric leaves of the recovered document, flattened to
dotted paths.  Direction is inferred from the lane name: ``*qps*`` /
``*ops_per_sec*`` / ``value`` / ``*vs_baseline*`` / ``*amortization*`` /
``*speedup*`` are higher-is-better, ``*_us*`` / ``*_ms*`` /
``*_seconds*`` / ``*bytes*`` lower-is-better; anything else is reported
as informational and never gated — notably bare ``*_x`` ratio lanes
(``demotion_overhead_x``, ``residual_x``), whose good direction depends
on the lane, unless a directional token above also matches
(``q64_vs_q1_amortization_x`` is gated upward via ``amortization``).  A directional lane that moved against its
direction by more than ``--threshold`` (fractional, default 0.15) is a
**regression**; with ``--fail`` the exit code is 1 when any lane
regressed (without it the tool always exits 0 — the CI smoke lane diffs
the committed trajectory files, whose rounds legitimately move).

Lanes present in only ONE document are no longer silently absent: the
verdict reports them as ``added`` (new-only) / ``removed`` (old-only)
after suffix alignment, so a lane that disappears between rounds — a
bench phase that stopped emitting — is visible (tools/bench_sentry.py
relies on this to notice vanished lanes across a trajectory).  They are
informational, never gated: salvaged truncated tails legitimately
recover different lane subsets per round.

Full documents (``benchmarks/bench_full.json``) additionally declare a
``lane_schema`` — per lane group, the ``platforms`` it runs on and the
engine ``rungs`` it exercises — plus the capturing ``platform``.  A
lane one side emitted whose group declares platforms EXCLUDING the
other side's platform is reported as ``~ skipped lane (platform)``
instead of added/removed: a TPU-only lane absent from a CPU round is a
capture difference, not a vanished lane (the BENCH_r06 hardware-capture
groundwork).  Summary-line documents carry no schema, so committed
driver captures diff exactly as before.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

#: lane-name fragments -> direction (checked in order; first hit wins).
#: The multiset lane (bench.py multiset_phase) adds pooled-vs-per-set
#: ratio and pipeline-overlap paths: ``*_qps`` matches via ``qps``,
#: ``pooled_vs_per_set_x`` via ``pooled_vs``, ``overlap_ratio`` and
#: ``launches_saved`` explicitly.
#: The sharded lane (bench.py sharded_phase, ISSUE 7) adds
#: ``sharded.m{R}x1_q{Q}.pooled_qps`` (via ``qps``),
#: ``sharded_vs_single_x`` (the mesh-vs-single-device throughput ratio,
#: explicit), ``shard_balance`` (max/mean per-shard resident rows — 1.0
#: is perfect, so lower is better) and ``warm_restart_x`` (warm
#: first-query over steady marginal — the cold-path ratio ROADMAP item 3
#: drives down).
#: The expression lane (bench.py expression_phase, ISSUE 8) adds
#: ``expression.d{D}_q{Q}.{fused,node}_qps`` (via ``qps``),
#: ``fused_vs_node_x`` (the fusion headline, explicit via ``fused_vs``)
#: and its ``launches_saved`` counts (explicit).  The one-kernel lane
#: (ISSUE 11) adds ``mega_vs_multiop_x`` — the per-dispatch
#: transient-byte DROP ratio of the megakernel lowering vs the multi-op
#: one — gated HIGHER via ``mega_vs`` (checked before the generic
#: ``bytes`` lower-is-better fragment).
#: The serving lane (bench.py serving_phase, ISSUE 10) adds per-rate
#: ``serving.x{R}`` cells ([p50_ms, p99_ms, slo_attainment, shed_rate])
#: and the ``overload_attainment`` headline — attainment is gated HIGHER
#: (via ``attain``); the cells' latency entries ride the ``_ms`` rule.
#: The mutation lane (bench.py mutation_phase, ISSUE 12) adds
#: ``delta_vs_repack_x`` (single-segment in-place patch speedup over a
#: full re-pack, via ``vs_repack``) and ``cache_vs_recompute_x``
#: (materialized-result-cache replay QPS over the recompute path, via
#: ``vs_recompute``); its ``delta_ms`` / ``repack_ms`` cells ride the
#: ``_ms`` rule.
#: The closed-lattice lane (bench.py lattice_phase, ISSUE 13) adds
#: ``lattice.warmed.{compiles,escapes,p50_ms,p99_ms,padding_fraction}``
#: and the ``lattice_p99_over_p50`` / ``padding_byte_fraction`` /
#: ``compiles_warm`` headlines — all gated LOWER (``escapes`` /
#: ``padding`` / ``p99_over_p50`` / ``compiles`` fragments); the cold
#: control's compile count (``compiles_cold``) is NEUTRAL like the
#: other control arms (it measures the disease, not the cure).
#: The pod lane (bench.py pod_phase, ISSUE 14, docs/POD.md) adds
#: ``pod.pod_vs_single_x`` (routed front-door QPS over the single loop,
#: via ``pod_vs``) and ``pod.cluster2_vs_single_x`` (2-process
#: aggregate over the 1-process control, via ``cluster2_vs``) — both
#: HIGHER; ``route_us`` and ``host_drop_recovery_ms`` ride the generic
#: ``_us`` / ``_ms`` LOWER fragments.
#: The analytics OLAP lane (bench.py olap_phase, ISSUE 15,
#: docs/ANALYTICS.md) adds ``olap.q{Q}.fused_qps`` (via ``qps``) and
#: ``fused_vs_twophase_x`` (the fused filter-then-aggregate headline,
#: via ``fused_vs``) — HIGHER; ``olap.warmed.warmed_compiles`` /
#: ``escapes`` ride the ``compiles`` / ``escapes`` LOWER fragments and
#: ``replay_p50_ms`` the ``_ms`` rule.  ``twophase_qps`` is the
#: two-dispatch CONTROL arm (NEUTRAL via ``twophase``, checked before
#: the generic ``qps`` fragment): the baseline getting faster or
#: slower measures the disease, not the cure.
#: The Megakernel v2 lanes (bench.py olap_phase mega sub-cell +
#: resident_phase, ISSUE 16) add ``mega_olap_x`` (fused analytics on
#: the one-kernel rung vs the multi-op auto rung, via ``mega_olap``)
#: and ``resident_vs_dispatch_x`` (ring-served steady-state serving
#: over the per-pool host-dispatch arm, via ``resident_vs``) — both
#: HIGHER; the resident arm's ``host_dispatches`` count rides nothing
#: (it is a 0/1 pin asserted in-phase, not a trend lane).
#: The durability lane (bench.py durability_phase, ISSUE 17,
#: docs/DURABILITY.md) adds ``journal_overhead_x`` — NEUTRAL (the WAL's
#: price is pinned, not gated: a flush-policy change legitimately moves
#: it either way; durability semantics are gated by tests, not trend);
#: ``recovery_ms_tenants{N}`` and ``migration_blip_ms`` ride the ``_ms``
#: LOWER fragment, ``migration_failed`` is a 0-pin asserted in-phase;
#: ``group_fsync_per_delta`` (ISSUE 20 group commit) rides the
#: ``fsync`` LOWER fragment and ``group_overhead_x`` the
#: ``journal_overhead``/``overhead_x`` NEUTRAL rule.
#: The pod_replay wire lane (bench.py pod_replay_phase, ISSUE 20,
#: docs/WIRE.md) adds ``pipelined_vs_rtt_x`` — HIGHER (via
#: ``pipelined_vs``: the tentpole amortization claim, many-in-flight
#: coalesced submission vs one request per round trip on the SAME
#: socket) and ``sustained_qps_{wire,inproc}`` (via the generic
#: ``qps``); ``overload_p99_ms`` rides the ``_ms`` LOWER fragment.
#: ``wire_vs_inproc_x`` is NEUTRAL (via ``wire_vs``): the network
#: boundary's price is pinned, not gated — a faster in-process engine
#: legitimately moves the ratio down with the wire arm unchanged.
HIGHER = ("qps", "ops_per_sec", "vs_baseline", "amortization", "speedup",
          "overlap_ratio", "launches_saved", "pooled_vs", "sharded_vs",
          "fused_vs", "mega_olap", "mega_vs", "resident_vs",
          "vs_repack", "vs_recompute", "attain",
          "pod_vs", "cluster2_vs", "pipelined_vs")
LOWER = ("_us", "_ms", "_seconds", "us_per", "ms_per", "bytes",
         "shard_balance", "warm_restart", "escapes", "padding",
         "p99_over_p50", "compiles", "fsync")
#: checked before HIGHER/LOWER: lanes whose good direction is genuinely
#: ambiguous.  host_overlapped_ms scales with total host time in BOTH
#: directions (more overlap at fixed host_ms is good, but so is less
#: host work overall) — overlap_ratio is the gated pipelining signal,
#: so the raw overlapped milliseconds stay informational instead of
#: being caught by the ``_ms`` lower-is-better fragment.  phase_ms
#: breakdowns (ISSUE 6) are single-sample attribution of ONE execute —
#: trend inputs for the sentry's table, not gate fields; a
#: sub-millisecond residual phase swinging 2x between rounds is noise,
#: and time moving BETWEEN phases (more dispatch, less other) is not a
#: regression at all.  The serving control/outcome lanes are neutral
#: too: ``noshed_attainment`` is the attainment-COLLAPSE control (lower
#: is the expected proof, higher is not a regression), and ``shed_rate``
#: at overload is a policy outcome, not a quality axis (more shedding
#: with higher survivor attainment can be the better trade); the
#: ``x4`` cells' serving direction signal is ``slo_attainment``.
NEUTRAL = ("host_overlapped", "phase_ms", "noshed", "shed_rate",
           "compiles_cold", "twophase", "journal_overhead",
           "wire_vs", "group_overhead")


def salvage_tail_json(tail: str) -> dict | None:
    """Recover the truncated final JSON line of a bounded tail capture.

    The summary is the LAST stdout line; the tail keeps its end but may
    cut its head mid-token.  Scan forward over `", "` key boundaries,
    re-open an object there, and trim unbalanced trailing braces until
    something parses — the recovered suffix loses the leading lanes but
    keeps every complete trailing one.
    """
    line = tail.strip().splitlines()[-1] if tail.strip() else ""
    if not line:
        return None
    # candidate re-open points: the line head, every `{"` object start,
    # and every `, "` key boundary (re-opened as an object there)
    starts = sorted({0}
                    | {m.start() for m in re.finditer(r'\{"', line)}
                    | {m.start() + 2 for m in re.finditer(r', "', line)})
    best: dict | None = None
    for s in starts[:400]:
        frag = line[s:].strip()
        body = frag if frag.startswith("{") else "{" + frag
        # a suffix cut inside nested objects carries unmatched trailing
        # closers; trim them (or re-close an unterminated object)
        for trim in range(8):
            cand = (body[:-trim] if trim else body).rstrip().rstrip(",")
            for close in range(4):
                try:
                    doc = json.loads(cand + "}" * close)
                except (json.JSONDecodeError, ValueError):
                    continue
                if isinstance(doc, dict) and doc and (
                        best is None or len(doc) > len(best)):
                    best = doc
                break
        if best is not None and s == 0:
            break
    return best


def load_doc(path: str) -> dict:
    """The recovered document itself via the document-shape ladder —
    the ``lane_schema`` / ``platform`` declarations (full documents
    only) live here alongside the numeric lanes."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "tail" in doc and "cmd" in doc:
        parsed = doc.get("parsed")
        doc = parsed if isinstance(parsed, dict) \
            else salvage_tail_json(doc.get("tail", ""))
        if doc is None:
            raise SystemExit(
                f"bench_diff: {path}: driver capture has no parseable "
                f"summary (parsed is null and the tail salvage failed)")
    return doc


def load_lanes(path: str) -> dict:
    """Path -> {dotted lane: float} via the document-shape ladder."""
    lanes: dict = {}
    _flatten(load_doc(path), "", lanes)
    return lanes


def doc_platform(doc: dict) -> str | None:
    """The platform a document's lanes ran on: the full document's
    ``platform`` declaration, else the summary/detail ``backend``."""
    return (doc.get("platform") or doc.get("backend")
            or (doc.get("detail") or {}).get("backend"))


def platform_skipped(lane: str, schema, platform) -> bool:
    """True when ``lane`` belongs to a schema group whose declared
    ``platforms`` EXCLUDE ``platform`` — the lane is legitimately absent
    from the other document (captured on that platform), so the diff
    skips it instead of reporting it added/removed.  Lanes with no
    declaration (or ``"any"``) never skip."""
    if not isinstance(schema, dict) or not platform:
        return False
    for group, decl in schema.items():
        if lane != group and not lane.startswith(group + ".") \
                and not lane.startswith(group + "["):
            continue
        plats = (decl or {}).get("platforms")
        if isinstance(plats, list) and platform not in plats:
            return True
    return False


def _flatten(node, prefix: str, out: dict) -> None:
    if isinstance(node, bool):
        return
    if isinstance(node, (int, float)):
        out[prefix] = float(node)
    elif isinstance(node, dict):
        for k, v in node.items():
            _flatten(v, f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            _flatten(v, f"{prefix}[{i}]", out)


def direction(lane: str) -> int:
    """+1 higher-is-better, -1 lower-is-better, 0 informational."""
    low = lane.lower()
    if any(t in low for t in NEUTRAL):
        return 0
    if low == "value" or any(t in low for t in HIGHER):
        return 1
    if any(t in low for t in LOWER):
        return -1
    return 0


def suffix_align(old: dict, new: dict) -> dict:
    """{old lane: new lane} by longest unique dotted-path suffix (>= 2
    components) — salvaged tails recover suffixes of the full document at
    different depths, so ``detail.wikileaks-noquotes.pack_ms`` must pair
    with ``wikileaks-noquotes.pack_ms``.  Ambiguous suffixes are skipped.
    Lanes already paired exactly are passed over unchanged."""
    pairs: dict = {}
    for lo in old:
        if lo in new:
            pairs[lo] = lo      # exact path match always wins
            continue
        co = lo.split(".")
        best, best_k, dup = None, 0, False
        for ln in new:
            cn = ln.split(".")
            k = 0
            while (k < min(len(co), len(cn))
                   and co[-1 - k] == cn[-1 - k]):
                k += 1
            if k > best_k:
                best, best_k, dup = ln, k, False
            elif k == best_k and k and ln != best:
                dup = True
        if best is not None and best_k >= 2 and not dup:
            pairs[lo] = best
    return pairs


def lane_changes(old: dict, new: dict) -> tuple[list, list]:
    """(added, removed) lane paths after suffix alignment: ``added`` are
    new-document lanes no old lane mapped onto, ``removed`` are old
    lanes that found no partner — a lane that stopped (or started) being
    emitted between the two documents."""
    aligned = suffix_align(old, new)
    matched_new = set(aligned.values())
    added = sorted(ln for ln in new if ln not in matched_new)
    removed = sorted(lo for lo in old if lo not in aligned)
    return added, removed


def diff_lanes(old: dict, new: dict, threshold: float) -> tuple[list, list]:
    """([(lane, old, new, delta_frac, direction, regressed)], regressions)
    over lanes present in BOTH documents — exact dotted-path matches
    first, depth-shifted salvaged lanes paired by unique path suffix —
    sorted worst-first."""
    aligned = suffix_align(old, new)
    rows, regressions = [], []
    for lane in sorted(aligned):
        o, n = old[lane], new[aligned[lane]]
        if o == 0 and n == 0:
            continue
        d = (n - o) / abs(o) if o else float("inf")
        sgn = direction(lane)
        regressed = sgn != 0 and sgn * d < -threshold
        rows.append((lane, o, n, d, sgn, regressed))
        if regressed:
            regressions.append(lane)
    rows.sort(key=lambda r: (not r[5], r[4] * r[3]))
    return rows, regressions


def main() -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench.py result documents per lane")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="fractional move against a lane's direction that "
                         "counts as a regression (default 0.15)")
    ap.add_argument("--fail", action="store_true",
                    help="exit 1 when any lane regressed past the "
                         "threshold (default: report-only, exit 0)")
    ap.add_argument("--lanes", default="",
                    help="only report lanes whose dotted path contains "
                         "this substring (e.g. 'qps')")
    args = ap.parse_args()

    old_doc, new_doc = load_doc(args.old), load_doc(args.new)
    old, new = {}, {}
    _flatten(old_doc, "", old)
    _flatten(new_doc, "", new)
    rows, regressions = diff_lanes(old, new, args.threshold)
    if args.lanes:
        rows = [r for r in rows if args.lanes in r[0]]
    shared = len(rows)
    if not shared:
        print(f"bench_diff: no shared numeric lanes between {args.old} "
              f"and {args.new}", file=sys.stderr)
        return 2
    arrow = {1: "^", -1: "v", 0: "-"}
    for lane, o, n, d, sgn, bad in rows:
        flag = " REGRESSION" if bad else ""
        print(f"{arrow[sgn]} {lane}: {o:g} -> {n:g} "
              f"({d:+.1%}){flag}")
    added, removed = lane_changes(old, new)
    # a lane only one side emitted is SKIPPED (not added/removed) when
    # its own document's lane_schema declares platforms excluding the
    # other document's platform: a TPU-only lane absent from a CPU
    # round is a capture difference, not a vanished lane
    skipped = [(lane, "new") for lane in added
               if platform_skipped(lane, new_doc.get("lane_schema"),
                                   doc_platform(old_doc))] \
        + [(lane, "old") for lane in removed
           if platform_skipped(lane, old_doc.get("lane_schema"),
                               doc_platform(new_doc))]
    skip_names = {lane for lane, _ in skipped}
    added = [lane for lane in added if lane not in skip_names]
    removed = [lane for lane in removed if lane not in skip_names]
    for lane, side in skipped:
        other = doc_platform(old_doc if side == "new" else new_doc)
        print(f"~ skipped lane (platform): {lane} — declared absent "
              f"on {other!r}")
    for lane in removed:
        print(f"! removed lane: {lane} (was {old[lane]:g})")
    for lane in added:
        print(f"+ added lane: {lane} ({new[lane]:g})")
    print(f"bench_diff: {shared} shared lanes, {len(regressions)} "
          f"regression(s) past {args.threshold:.0%}, "
          f"{len(added)} added, {len(removed)} removed, "
          f"{len(skipped)} platform-skipped "
          f"({args.old} -> {args.new})")
    return 1 if (args.fail and regressions) else 0


if __name__ == "__main__":
    sys.exit(main())
