"""Durable tenants acceptance (ISSUE 17): write-ahead journal,
crash-consistent snapshots, live migration (mutation.durability +
serving.migration, docs/DURABILITY.md).

Pins:
- journal framing round-trips; torn tails are recoverable-typed
  (``TornJournalTail`` semantics inside ``scan_journal``), mid-file
  corruption is hard-typed ``CorruptInput``; compaction drops only
  records a durable snapshot covers;
- THE property: a randomized interleaved delta/query stream crashed at
  every journal/apply boundary (pre_append, pre_apply clean + torn,
  post_apply) recovers BIT-EXACTLY vs the never-crashed oracle across
  layouts, including BSI/Range column state — with the WAL's
  at-most-once-unacked gap re-supplied by client retry exactly when the
  crash point says the record was lost;
- snapshots are spec-portable (``format.spec`` deserializes every
  source file) and the ``utils.fuzz`` mutation corpus makes a corrupt
  snapshot die typed, never misparse;
- live migration serves bit-exactly end to end with zero non-expired
  failures and emits the ``pod.migrate`` span; sharded tenants refuse
  typed; host join/drain keep serving; a LOST host's tenants rebuild
  from durable state (``restore_host_tenants``);
- the PR 12 sharded-pool debt: a bounded delta journal that overflowed
  re-places the pool AND says so (``rb_sharded_journal_overflows_total``
  + trace event), never silently.
"""

import json
import os
import struct

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.analytics.column import BsiColumn, RangeColumn
from roaringbitmap_tpu.format import spec as fmt_spec
from roaringbitmap_tpu.mutation import durability
from roaringbitmap_tpu.mutation import delta as mut_delta
from roaringbitmap_tpu.mutation.durability import (DeltaJournal,
                                                   DurableTenant,
                                                   FlushPolicy,
                                                   load_snapshot,
                                                   recover_tenant,
                                                   scan_journal)
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.parallel import podmesh
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchQuery
from roaringbitmap_tpu.runtime import errors, faults, guard
from roaringbitmap_tpu.serving import (MigrationError, PodFrontDoor,
                                       ServingPolicy, ServingRequest,
                                       host_join, host_leave,
                                       migrate_tenant,
                                       restore_host_tenants)
from roaringbitmap_tpu.utils import fuzz


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()


NEVER = FlushPolicy(mode="never")      # tests don't need real fsyncs


def mk_bitmaps(seed, n=3, uni=1 << 14, card=300):
    rng = np.random.default_rng(seed)
    return [RoaringBitmap.from_values(
        np.unique(rng.integers(0, uni, card)).astype(np.uint32))
        for _ in range(n)]


def _read_trace(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ----------------------------------------------------------- journal core

def test_flush_policy_typed():
    with pytest.raises(ValueError, match="unknown flush mode"):
        FlushPolicy(mode="sometimes")
    with pytest.raises(ValueError, match="every_n"):
        FlushPolicy(mode="batch", every_n=0)


def test_journal_roundtrip_compact_and_metrics(tmp_path):
    path = str(tmp_path / "j.wal")
    j = DeltaJournal(path, NEVER)
    for i in range(5):
        j.append({"kind": "delta", "adds": {"0": [i]}, "removes": {}})
    j.close()
    records, torn, _ = scan_journal(path)
    assert not torn
    assert [r["seq"] for r in records] == [1, 2, 3, 4, 5]
    assert records[2]["adds"] == {"0": [2]}
    # compact drops only covered records and survives reopen
    j = DeltaJournal(path, NEVER, start_seq=5)
    kept = j.compact(3)
    assert kept == 2
    j.append({"kind": "delta", "adds": {"0": [99]}, "removes": {}})
    j.close()
    records, torn, _ = scan_journal(path)
    assert [r["seq"] for r in records] == [4, 5, 6]
    assert not torn


def test_torn_tail_recoverable_but_midfile_corruption_hard(tmp_path):
    path = str(tmp_path / "j.wal")
    j = DeltaJournal(path, NEVER)
    for i in range(3):
        j.append({"kind": "delta", "adds": {"0": [i]}, "removes": {}})
    j.close()
    whole = open(path, "rb").read()
    # torn tail: final record cut mid-frame -> recoverable, prior kept
    open(path, "wb").write(whole[:-5])
    records, torn, valid_end = scan_journal(path)
    assert torn and [r["seq"] for r in records] == [1, 2]
    assert valid_end < len(whole) - 5
    # CRC damage with bytes FOLLOWING it is not a tail: hard typed
    open(path, "wb").write(whole)
    blob = bytearray(whole)
    blob[len(durability.JOURNAL_MAGIC) + durability._FRAME.size + 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(errors.CorruptInput):
        scan_journal(path)
    # bad magic is typed too
    open(path, "wb").write(b"NOTAWAL0" + whole[8:])
    with pytest.raises(errors.CorruptInput):
        scan_journal(path)


def test_fresh_tenant_refuses_existing_state(tmp_path):
    ds = DeviceBitmapSet(mk_bitmaps(1))
    t = DurableTenant(ds, root=str(tmp_path), tenant="t0", policy=NEVER)
    t.close()
    with pytest.raises(ValueError, match="recover_tenant"):
        DurableTenant(DeviceBitmapSet(mk_bitmaps(1)),
                      root=str(tmp_path), tenant="t0", policy=NEVER)


# --------------------------------------------- crash-recovery property

def _mk_tenant(seed, layout, root, tenant, snapshot_every=3):
    ds = DeviceBitmapSet(mk_bitmaps(seed), layout=layout)
    rng = np.random.default_rng(seed + 1)
    ids = np.unique(rng.integers(0, 1 << 14, 200)).astype(np.uint32)
    ds.attach_column(BsiColumn(
        "price", ids, rng.integers(0, 500, ids.size).astype(np.int64)))
    ds.attach_column(RangeColumn(
        "lat", rng.integers(0, 1 << 30, 64).astype(np.int64)))
    return DurableTenant(ds, root=root, tenant=tenant, policy=NEVER,
                         snapshot_every=snapshot_every)


class _Oracle:
    """Host-side never-crashed twin: plain RoaringBitmaps + dict/array
    column models, mutated by the same delta stream."""

    def __init__(self, seed):
        self.hosts = mk_bitmaps(seed)
        rng = np.random.default_rng(seed + 1)
        ids = np.unique(rng.integers(0, 1 << 14, 200)).astype(np.uint32)
        vals = rng.integers(0, 500, ids.size).astype(np.int64)
        self.bsi = dict(zip(ids.tolist(), vals.tolist()))
        self.lat = rng.integers(0, 1 << 30, 64).astype(np.int64)

    def apply(self, step):
        kind, payload = step
        if kind == "delta":
            adds, removes = payload
            for src, vs in adds.items():
                a = RoaringBitmap()
                a.add_many(np.asarray(vs, np.uint32))
                self.hosts[src] = self.hosts[src] | a
            for src, vs in removes.items():
                r = RoaringBitmap()
                r.add_many(np.asarray(vs, np.uint32))
                self.hosts[src] = self.hosts[src] - r
        elif kind == "bsi":
            set_values, removes = payload
            self.bsi.update(set_values)
            for i in removes:
                self.bsi.pop(i, None)
        else:
            self.lat = self.lat.copy()
            for i, v in payload.items():
                self.lat[i] = v

    def check(self, ds):
        assert ds.host_bitmaps() == self.hosts
        col = ds.columns["price"]
        assert col.host_sum(None) == (sum(self.bsi.values()),
                                      len(self.bsi))
        assert np.array_equal(ds.columns["lat"].values, self.lat)


def _stream(seed, steps):
    rng = np.random.default_rng(seed)
    out = []
    for k in range(steps):
        if k % 4 == 2:
            ids = rng.integers(0, 1 << 14, 4).tolist()
            out.append(("bsi", ({int(i): int(rng.integers(1, 500))
                                 for i in ids[:3]}, [int(ids[3])])))
        elif k % 4 == 3:
            out.append(("range", {int(i): int(rng.integers(0, 1 << 30))
                                  for i in rng.integers(0, 64, 3)}))
        else:
            adds = {int(s): np.unique(rng.integers(
                0, 1 << 14, 20)).tolist() for s in rng.integers(0, 3, 2)}
            rems = {0: rng.integers(0, 1 << 14, 5).tolist()}
            out.append(("delta", (adds, rems)))
    return out


def _apply_step(tenant, step):
    kind, payload = step
    if kind == "delta":
        tenant.apply_delta(adds=payload[0], removes=payload[1])
    elif kind == "bsi":
        tenant.apply_column_delta("price", set_values=payload[0],
                                  removes=payload[1])
    else:
        tenant.apply_column_delta("lat", updates=payload)


@pytest.mark.parametrize("layout", ["dense", "counts"])
@pytest.mark.parametrize("point", ["pre_append", "pre_apply", "torn",
                                   "post_apply"])
def test_crash_recovery_property(tmp_path, layout, point):
    """Crash at every journal/apply boundary of a randomized interleaved
    delta/query stream; recovery (+ client retry of the un-acked record
    exactly when the WAL says it was lost) is bit-exact vs the
    never-crashed oracle, columns included."""
    steps = _stream(0xD0 + hash(layout) % 97, 8)
    committed = point in ("pre_apply", "post_apply")
    for k in range(len(steps)):
        root = str(tmp_path / f"{layout}-{point}-{k}")
        tenant = _mk_tenant(40, layout, root, "t0")
        oracle = _Oracle(40)
        for step in steps[:k]:
            _apply_step(tenant, step)
            oracle.apply(step)
        with faults.inject(f"crash@{point}=1.0:1"):
            with pytest.raises(errors.InjectedCrash):
                _apply_step(tenant, steps[k])
        # the crashed process is gone; attach from durable state
        recovered, report = recover_tenant(root=root, tenant="t0",
                                           policy=NEVER)
        assert report["torn"] == (point == "torn")
        if committed:
            oracle.apply(steps[k])
        oracle.check(recovered.ds)   # the crash-boundary state, exact
        if not committed:
            _apply_step(recovered, steps[k])     # client retry
            oracle.apply(steps[k])
        for step in steps[k + 1:]:
            _apply_step(recovered, step)
            oracle.apply(step)
        oracle.check(recovered.ds)   # the full-stream state, exact
        recovered.close()
    # the stream's query half: the final recovered set serves through a
    # device engine bit-exactly (replay is apply, same engine path)
    from roaringbitmap_tpu.parallel.batch_engine import BatchEngine
    got = BatchEngine(recovered.ds, result_cache=None).execute(
        [BatchQuery("or", (0, 1, 2), form="bitmap")])[0]
    ref = oracle.hosts[0] | oracle.hosts[1] | oracle.hosts[2]
    assert got.bitmap == ref and got.cardinality == ref.cardinality


def test_recovery_replays_snapshot_plus_tail(tmp_path):
    """Auto-snapshots mid-stream: recovery loads the LATEST snapshot and
    replays only the journal tail past it."""
    root = str(tmp_path)
    tenant = _mk_tenant(7, "dense", root, "t0", snapshot_every=3)
    oracle = _Oracle(7)
    for step in _stream(9, 7):
        _apply_step(tenant, step)
        oracle.apply(step)
    tenant.close()
    recovered, report = recover_tenant(root=root, tenant="t0",
                                       policy=NEVER)
    assert report["snapshot_seq"] >= 3      # a mid-stream snapshot won
    assert report["replayed"] <= 4          # only the tail replayed
    oracle.check(recovered.ds)
    recovered.close()


# ------------------------------------------------- snapshot portability

def test_snapshot_is_spec_portable_and_fuzz_typed(tmp_path):
    """Every snapshot source file deserializes through format.spec (the
    interchange guarantee), a mutated one dies typed CorruptInput, and a
    clean snapshot re-ingests bit-exactly across layouts."""
    rng = np.random.default_rng(3)
    for layout in ("dense", "counts"):
        root = str(tmp_path / layout)
        tenant = _mk_tenant(50, layout, root, "t0", snapshot_every=None)
        tenant.snapshot()
        tenant.close()
        tdir = os.path.join(root, "t0")
        snap = os.path.join(
            tdir, open(os.path.join(tdir, durability.CURRENT_FILE))
            .read().strip())
        srcs = sorted(f for f in os.listdir(snap)
                      if f.startswith("src-"))
        assert srcs, snap
        for f in srcs:
            blob = open(os.path.join(snap, f), "rb").read()
            rb = RoaringBitmap.deserialize(blob)     # spec-portable
            assert rb.serialize() == blob
        # clean re-ingest is bit-exact, columns included
        bitmaps, columns, manifest = load_snapshot(tdir)
        assert bitmaps == tenant.ds.host_bitmaps()
        assert manifest["layout"] == tenant.ds.layout
        assert set(columns) == {"price", "lat"}
        # fuzz corpus: every mutation kind dies typed, never misparses
        target = os.path.join(snap, srcs[0])
        blob = open(target, "rb").read()
        for kind in fuzz.MUTATION_KINDS:
            mutated = fuzz.mutate_serialized(rng, blob, kind)
            if mutated == blob:
                continue
            open(target, "wb").write(mutated)
            with pytest.raises(errors.CorruptInput):
                load_snapshot(tdir)
        open(target, "wb").write(blob)
        # a manifest that lies about the CRC is typed too
        mpath = os.path.join(snap, durability.MANIFEST_FILE)
        manifest_raw = json.load(open(mpath))
        manifest_raw["sources"][0]["crc32"] ^= 1
        json.dump(manifest_raw, open(mpath, "w"))
        with pytest.raises(errors.CorruptInput):
            load_snapshot(tdir)


# ------------------------------------------------------- live migration

def _front_door(n_hosts=2, seed=21):
    pod = podmesh.PodMesh.simulate(n_hosts)
    sets = [DeviceBitmapSet(mk_bitmaps(seed + i)) for i in range(3)]
    fd = PodFrontDoor(sets, pod=pod,
                      policy=ServingPolicy(default_deadline_ms=60_000,
                                           pool_target=2))
    return fd


def _ask(fd, sid):
    t = fd.submit(ServingRequest(sid, BatchQuery("or", (0, 1, 2)),
                                 tenant=f"t{sid}"))
    done = fd.drain()
    bad = [x for x in done
           if x.status == "failed"
           or (x.status == "shed" and x.shed_reason != "expired")]
    assert not bad, [(x.status, x.error) for x in bad]
    assert t.status == "done", (t.status, t.error)
    return int(t.result.cardinality)


def test_live_migration_bit_exact_zero_failures(tmp_path):
    obs.enable(str(tmp_path / "mig.jsonl"))
    fd = _front_door()
    sid = next(s for s in range(3) if fd.plan.regime(s) != "sharded")
    src = fd.owner_host(sid)
    target = next(h for h in fd.pod.alive() if h != src)
    before = _ask(fd, sid)

    def during(fd):
        # traffic + a delta INSIDE the dual-write window
        fd.apply_delta(sid, adds={0: [999991, 999992]})
        assert _ask(fd, sid) == before + 2

    rep = migrate_tenant(fd, sid, target, during=during)
    assert rep["catch_up_records"] >= 1 and rep["bytes"] > 0
    assert fd.owner_host(sid) == target
    assert _ask(fd, sid) == before + 2           # bit-exact after flip
    fd.apply_delta(sid, adds={0: [999993]})      # writes keep landing
    assert _ask(fd, sid) == before + 3
    obs.disable()
    spans = [s for s in _read_trace(tmp_path / "mig.jsonl")
             if s["name"] == "pod.migrate"]
    assert spans, "migration must emit the pod.migrate span"
    tags = spans[0]["tags"]
    assert tags["set_id"] == sid and tags["to"] == str(target)
    assert tags["from_host"] == str(src)
    assert tags["bytes"] > 0 and tags["blip_ms"] >= 0
    c = obs_metrics.REGISTRY.counter("rb_migration_total", status="ok")
    assert c.value >= 1


def test_migration_typed_refusals():
    fd = _front_door(seed=33)
    sid = next(s for s in range(3) if fd.plan.regime(s) != "sharded")
    with pytest.raises(MigrationError, match="unknown"):
        migrate_tenant(fd, sid, 99)
    fd.pod.mark_down(1)
    if fd.owner_host(sid) != 0:
        sid = next(s for s in range(3) if fd.owner_host(s) == 0)
    with pytest.raises(MigrationError, match="down"):
        migrate_tenant(fd, sid, 1)
    fd.pod.mark_up(1)
    # a second concurrent migration of the same tenant refuses typed
    from roaringbitmap_tpu.serving import begin_migration
    s1 = begin_migration(fd, sid, 1)
    with pytest.raises(MigrationError, match="already migrating"):
        begin_migration(fd, sid, 1)
    s1.finish()


def test_host_join_and_leave_keep_serving():
    fd = _front_door(seed=44)
    sid = next(s for s in range(3) if fd.plan.regime(s) != "sharded")
    base = _ask(fd, sid)
    j = host_join(fd)
    assert j["host"] == 2 and j["changed"] in (True, False)
    assert _ask(fd, sid) == base
    # force a tenant onto the new host, then drain it
    migrate_tenant(fd, sid, j["host"])
    assert fd.owner_host(sid) == j["host"]
    assert _ask(fd, sid) == base
    rep = host_leave(fd, j["host"])
    assert sid in rep["moved"]
    assert fd.owner_host(sid) != j["host"]
    assert _ask(fd, sid) == base
    # draining the last host refuses typed
    for h in list(fd.pod.alive())[1:]:
        fd.pod.mark_down(h)
    with pytest.raises(MigrationError, match="last alive"):
        host_leave(fd, fd.pod.alive()[0])


def test_restore_host_tenants_from_durable_state(tmp_path):
    """Host LOSS beyond the reroute rung: a single-copy tenant on the
    dead host rebuilds from its journal+snapshot, bit-exact."""
    root = str(tmp_path)
    fd = _front_door(seed=55)
    sid = next(s for s in range(3)
               if fd.plan.regime(s) != "sharded"
               and len(fd.plan.hosts_of(s)) == 1)
    lost = fd.owner_host(sid)
    tenant = DurableTenant(fd._sets[sid], root=root, tenant=f"sid{sid}",
                           policy=NEVER, snapshot_every=None)
    tenant.apply_delta(adds={0: [777777, 777778]})
    expect = _ask(fd, sid)
    tenant.close()
    fd.fail_host(lost)
    rep = restore_host_tenants(fd, lost, root, {sid: f"sid{sid}"})
    assert rep["restored"] == [sid]
    assert fd.owner_host(sid) in fd.pod.alive()
    assert _ask(fd, sid) == expect               # durable bits, exact
    assert rep["reports"][sid]["replayed"] >= 1  # the journal tail ran
    # an alive host refuses the loss rung
    with pytest.raises(MigrationError, match="alive"):
        restore_host_tenants(fd, fd.pod.alive()[0], root, {})


# -------------------------------------- sharded journal overflow (PR 12)

def test_sharded_journal_overflow_counted(monkeypatch):
    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu.parallel.multiset import (
        MultiSetBatchEngine, random_multiset_pool)
    from roaringbitmap_tpu.parallel.sharded_engine import \
        ShardedBatchEngine

    monkeypatch.setattr(mut_delta, "JOURNAL_DEPTH", 2)
    tenants = [mk_bitmaps(60 + i, n=4, uni=1 << 16, card=900)
               for i in range(2)]
    ms = MultiSetBatchEngine(
        [DeviceBitmapSet(b, layout="dense") for b in tenants],
        result_cache=None)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("rows", "data"))
    sh = ShardedBatchEngine(ms._engines, mesh=mesh, placement="sharded",
                            result_cache=None)
    pool = random_multiset_pool([4] * 2, 6, seed=8)
    sh.execute(pool)
    c0 = obs_metrics.REGISTRY.counter(
        "rb_sharded_journal_overflows_total", site="sharded_engine").value
    ds = ms._engines[0]._ds
    for i in range(4):      # > JOURNAL_DEPTH in-place patches
        ds.apply_delta(adds={1: [40000 + i]})
    assert ds._journal_dropped_version > 0
    got = [[r.cardinality for r in rows] for rows in sh.execute(pool)]
    assert obs_metrics.REGISTRY.counter(
        "rb_sharded_journal_overflows_total",
        site="sharded_engine").value > c0
    # ...and the wholesale re-place is still bit-exact
    refs = [[ms._engines[g.set_id]._sequential_one(q).cardinality
             for q in g.queries] for g in pool]
    assert got == refs
