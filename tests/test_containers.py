"""Container model unit tests — promotion boundary, run codec, pairwise algebra.

Oracle: Python sets / NumPy set ops, the strategy of the reference's
randomized container tests (TestArrayContainer/TestBitmapContainer/
TestRunContainer, SURVEY.md §4)."""

import numpy as np
import pytest

from roaringbitmap_tpu.core import containers as C


def random_values(rng, n, style):
    if style == "sparse":
        v = rng.choice(1 << 16, size=n, replace=False)
    elif style == "dense":
        v = rng.choice(1 << 16, size=min(60000, n * 16), replace=False)
    else:  # runs
        starts = rng.integers(0, 1 << 16, 32)
        v = np.unique(np.concatenate(
            [np.arange(s, min(s + int(l), 1 << 16))
             for s, l in zip(starts, rng.integers(1, 500, 32))]))
    return np.sort(v.astype(np.uint16))


STYLES = ["sparse", "dense", "runs"]


@pytest.mark.parametrize("style", STYLES)
def test_roundtrip_representations(rng, style):
    v = random_values(rng, 1000, style)
    for c in (C.from_values(v), C.RunContainer(C.values_to_runs(v)),
              C.BitmapContainer(C.values_to_words(v))):
        assert c.cardinality == v.size
        np.testing.assert_array_equal(c.values(), v)
        np.testing.assert_array_equal(C.words_to_values(c.words()), v)


def test_promotion_boundary():
    v = np.arange(0, 2 * C.ARRAY_MAX_SIZE, 2, dtype=np.uint16)  # card 4096
    assert isinstance(C.from_values(v), C.ArrayContainer)
    v2 = np.arange(0, 2 * (C.ARRAY_MAX_SIZE + 1), 2, dtype=np.uint16)
    assert isinstance(C.from_values(v2), C.BitmapContainer)


@pytest.mark.parametrize("s1", STYLES)
@pytest.mark.parametrize("s2", STYLES)
def test_pairwise_ops_match_sets(rng, s1, s2):
    a = random_values(rng, 800, s1)
    b = random_values(rng, 800, s2)
    reps_a = [C.from_values(a), C.RunContainer(C.values_to_runs(a)),
              C.BitmapContainer(C.values_to_words(a))]
    reps_b = [C.from_values(b), C.BitmapContainer(C.values_to_words(b))]
    sa, sb = set(a.tolist()), set(b.tolist())
    expected = {
        "and": sorted(sa & sb), "or": sorted(sa | sb),
        "xor": sorted(sa ^ sb), "andnot": sorted(sa - sb),
    }
    fns = {"and": C.container_and, "or": C.container_or,
           "xor": C.container_xor, "andnot": C.container_andnot}
    for ca in reps_a:
        for cb in reps_b:
            for op, fn in fns.items():
                got = fn(ca, cb)
                assert got.values().tolist() == expected[op], (op, type(ca), type(cb))
                # result respects the serialization type invariant
                if not got.is_run():
                    assert (got.cardinality <= C.ARRAY_MAX_SIZE) == \
                        isinstance(got, C.ArrayContainer)


def test_run_optimize_picks_smallest():
    runs = C.from_values(np.arange(0, 10000, dtype=np.uint16)).run_optimize()
    assert runs.is_run() and runs.n_runs == 1
    sparse = C.from_values(np.arange(0, 4000, 2, dtype=np.uint16)).run_optimize()
    assert isinstance(sparse, C.ArrayContainer)


def test_point_ops(rng):
    v = random_values(rng, 500, "sparse")
    c = C.from_values(v)
    x = int(v[10])
    assert c.contains(x) and not C.from_values(v).remove(x).contains(x)
    assert c.rank(x) == 11
    assert c.select(10) == x
    assert c.first() == int(v[0]) and c.last() == int(v[-1])
    run = C.RunContainer(C.values_to_runs(v))
    assert run.contains(x) and not run.contains(int(v[10]) + 1 if int(v[10]) + 1 not in set(v.tolist()) else 0)


def test_full_and_range_containers():
    f = C.full_container()
    assert f.cardinality == 1 << 16
    r = C.range_container(100, 200)
    assert r.values().tolist() == list(range(100, 200))
    tiny = C.range_container(5, 7)
    assert isinstance(tiny, C.ArrayContainer)
