"""Two-process jax.distributed bootstrap (parallel/multihost.py:29-43).

VERDICT r5 weak item 4: ``multihost.initialize`` had zero coverage —
``_arrange``/``global_mesh`` are unit-tested in test_sharding.py but the
``jax.distributed.initialize`` path itself never executed.  This spawns a
real 2-process cluster on the CPU backend (coordinator on 127.0.0.1) and
asserts both processes join, see the global device set, and build the
host-pure global mesh.  Runs in ~5 s; subprocesses are fully isolated from
the suite's 8-virtual-device pinning."""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)          # no virtual-device pinning here
sys.path.insert(0, {repo!r})
pid, port = int(sys.argv[1]), sys.argv[2]
from roaringbitmap_tpu.parallel import multihost
multihost.initialize(f"127.0.0.1:{{port}}", num_processes=2, process_id=pid)
import jax
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid, (jax.process_index(), pid)
devs = jax.devices()
assert len(devs) == 2, devs                # global view spans both procs
assert len(jax.local_devices()) == 1
mesh = multihost.global_mesh()
assert mesh.devices.shape == (1, 2), mesh.devices.shape
# host-pure columns: each column's devices belong to one process
for col in mesh.devices.T:
    assert len({{d.process_index for d in col}}) == 1
print("MULTIHOST_OK", pid)
""".format(repo=REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_initialize(tmp_path):
    worker = tmp_path / "mh_worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    # no XLA device pinning, and no injected faults: this test proves the
    # REAL two-process bring-up; the injection seam has its own test below
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "ROARING_TPU_FAULTS")}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"MULTIHOST_OK {i}" in out


# Coordinator-timeout hardening (runtime satellite): a missing peer must
# surface as a typed CoordinatorTimeout naming the coordinator address and
# process id, not a hang or a raw gRPC traceback.  Runs in a subprocess so
# jax.distributed's process-global state never leaks into the suite.
_TIMEOUT_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
sys.path.insert(0, {repo!r})
port = sys.argv[1]
from roaringbitmap_tpu.parallel import multihost
from roaringbitmap_tpu.runtime import errors
try:
    # nobody serves this port: the handshake must die within the timeout
    multihost.initialize(f"127.0.0.1:{{port}}", num_processes=2,
                         process_id=1, timeout=5)
except errors.CoordinatorTimeout as e:
    msg = str(e)
    assert f"127.0.0.1:{{port}}" in msg, msg
    assert "process_id 1" in msg, msg
    print("COORD_TIMEOUT_OK")
else:
    print("NO_ERROR_RAISED")
""".format(repo=REPO)


def test_unreachable_coordinator_times_out_typed(tmp_path):
    worker = tmp_path / "mh_timeout_worker.py"
    worker.write_text(_TIMEOUT_WORKER)
    port = _free_port()   # bound then released: nothing listens on it
    # the timeout must come from the real unreachable socket, not from an
    # injected fault riding the CI fault shard's environment
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "ROARING_TPU_FAULTS")}
    p = subprocess.Popen([sys.executable, str(worker), str(port)],
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, env=env)
    try:
        out, _ = p.communicate(timeout=120)
    finally:
        p.kill()
    text = out.decode(errors="replace")
    assert "COORD_TIMEOUT_OK" in text, text


def test_injected_coordinator_fault_is_typed():
    """In-process coverage of the fault-injection seam: a coordinator
    fault at the multihost site becomes CoordinatorTimeout with the
    address and process id in the message (no jax.distributed involved)."""
    from roaringbitmap_tpu.parallel import multihost
    from roaringbitmap_tpu.runtime import errors, faults

    import pytest

    with faults.inject("coordinator@multihost=1.0:11"):
        with pytest.raises(errors.CoordinatorTimeout) as ei:
            multihost.initialize("10.1.2.3:9999", num_processes=2,
                                 process_id=0, timeout=7)
    assert "10.1.2.3:9999" in str(ei.value)
    assert "process_id 0" in str(ei.value)
