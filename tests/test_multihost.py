"""Two-process jax.distributed bootstrap (parallel/multihost.py:29-43).

VERDICT r5 weak item 4: ``multihost.initialize`` had zero coverage —
``_arrange``/``global_mesh`` are unit-tested in test_sharding.py but the
``jax.distributed.initialize`` path itself never executed.  This spawns a
real 2-process cluster on the CPU backend (coordinator on 127.0.0.1) and
asserts both processes join, see the global device set, and build the
host-pure global mesh.  Runs in ~5 s; subprocesses are fully isolated from
the suite's 8-virtual-device pinning."""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)          # no virtual-device pinning here
sys.path.insert(0, {repo!r})
pid, port = int(sys.argv[1]), sys.argv[2]
from roaringbitmap_tpu.parallel import multihost
multihost.initialize(f"127.0.0.1:{{port}}", num_processes=2, process_id=pid)
import jax
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid, (jax.process_index(), pid)
devs = jax.devices()
assert len(devs) == 2, devs                # global view spans both procs
assert len(jax.local_devices()) == 1
mesh = multihost.global_mesh()
assert mesh.devices.shape == (1, 2), mesh.devices.shape
# host-pure columns: each column's devices belong to one process
for col in mesh.devices.T:
    assert len({{d.process_index for d in col}}) == 1
print("MULTIHOST_OK", pid)
""".format(repo=REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_initialize(tmp_path):
    worker = tmp_path / "mh_worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(i), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out.decode(errors="replace"))
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"MULTIHOST_OK {i}" in out
