"""Container-kind-mix aggregation matrix — TestFastAggregation's
parameterized `bitmaps()` corpus (TestFastAggregation.java:189-241),
rebuilt: triples of bitmaps with bitmap/array/run containers at chosen
chunks, pushed through every wide engine, layout, and cardinality path
against the host oracle (testWorkShyAnd :247, testAndCardinality :261,
testOrCardinality :273).
"""

from __future__ import annotations

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import aggregation, fast_aggregation
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet

def _with_kind_at(kinds: list[tuple[str, int]],
                  rng: np.random.Generator) -> RoaringBitmap:
    """SeededTestData.testCase().with{Bitmap,Array,Run}At analog: one
    bitmap whose chunk `key` holds a container of the requested kind."""
    parts = []
    for kind, key in kinds:
        base = np.uint32(key) << np.uint32(16)
        if kind == "bitmap":
            vals = rng.choice(1 << 16, size=9000, replace=False)
        elif kind == "array":
            vals = rng.choice(1 << 16, size=300, replace=False)
        else:  # run
            start = int(rng.integers(0, 1 << 15))
            vals = np.arange(start, start + 5000)
        parts.append(base + vals.astype(np.uint32))
    rb = RoaringBitmap.from_values(
        np.unique(np.concatenate(parts)).astype(np.uint32))
    rb.run_optimize()
    # pin the kinds: if promotion/run_optimize heuristics drift, the
    # matrix must fail loudly rather than silently stop covering kinds
    from roaringbitmap_tpu.core import containers as C

    expected = {"bitmap": C.BitmapContainer, "array": C.ArrayContainer,
                "run": C.RunContainer}
    key_to_kind = {key: kind for kind, key in kinds}
    for k, cont in zip(rb.keys, rb.containers):
        want = expected[key_to_kind[int(k)]]
        assert isinstance(cont, want), (int(k), type(cont), want)
    return rb


# the ten kind-mix triples of TestFastAggregation.bitmaps():189-241
TRIPLES = [
    [[("bitmap", 0), ("array", 1), ("run", 2)]] * 3,
    [[("bitmap", 0), ("run", 1), ("array", 2)]] * 3,
    [[("array", 0), ("run", 1), ("bitmap", 2)]] * 3,
    [[("bitmap", 0), ("array", 1), ("run", 2)],
     [("bitmap", 0), ("array", 3), ("run", 4)],
     [("bitmap", 0), ("array", 1), ("run", 2)]],
    [[("array", 0), ("bitmap", 1), ("run", 2)],
     [("run", 0), ("array", 1), ("bitmap", 2)],
     [("bitmap", 0), ("run", 1), ("array", 2)]],
    [[("bitmap", 0), ("array", 1), ("run", 2)],
     [("bitmap", 0), ("array", 2), ("run", 4)],
     [("bitmap", 0), ("array", 1), ("run", 2)]],
    [[("array", 0), ("array", 1), ("array", 2)],
     [("bitmap", 0), ("bitmap", 2), ("bitmap", 4)],
     [("run", 0), ("run", 1), ("run", 2)]],
    [[("array", 0), ("array", 1), ("array", 2)],
     [("bitmap", 0), ("bitmap", 2), ("array", 4)],
     [("run", 0), ("run", 1), ("array", 2)]],
    [[("array", 0), ("array", 1), ("bitmap", 2)],
     [("bitmap", 0), ("bitmap", 2), ("bitmap", 4)],
     [("run", 0), ("run", 1), ("bitmap", 2)]],
    [[("array", 20)],
     [("bitmap", 0), ("bitmap", 1), ("bitmap", 4)],
     [("run", 0), ("run", 1), ("bitmap", 3)]],
]


@pytest.fixture(scope="module", params=range(len(TRIPLES)),
                ids=lambda i: f"triple{i}")
def triple(request):
    # per-param seed: a failing triple reproduces identically when run
    # alone with -k
    rng = np.random.default_rng(0xFA57 + request.param)
    bms = [_with_kind_at(spec, rng) for spec in TRIPLES[request.param]]
    # the host ORACLE is the pure-Python naive fold chain — NOT the device
    # engines under test (fast_aggregation.or_/and_/xor delegate to them)
    oracle = {"or": fast_aggregation.naive_or(*bms),
              "xor": fast_aggregation.naive_xor(*bms),
              "and": fast_aggregation.naive_and(*bms)}
    return bms, oracle


@pytest.mark.parametrize("engine", ["xla", "pallas"])
def test_wide_ops_every_kind_mix(triple, engine):
    bms, oracle = triple
    # fallback=False: a broken engine must FAIL this parity test, not
    # silently demote to a rung that still passes (runtime.guard)
    assert aggregation.or_(*bms, engine=engine, fallback=False) \
        == oracle["or"]
    assert aggregation.xor(*bms, engine=engine, fallback=False) \
        == oracle["xor"]
    assert aggregation.and_(*bms, engine=engine, fallback=False) \
        == oracle["and"]


def test_cardinality_paths_every_kind_mix(triple):
    # testAndCardinality :261 / testOrCardinality :273
    bms, oracle = triple
    assert aggregation.or_cardinality(bms) == oracle["or"].cardinality
    assert aggregation.and_cardinality(bms) == oracle["and"].cardinality
    assert aggregation.xor_cardinality(bms) == oracle["xor"].cardinality


@pytest.mark.parametrize("layout", ["dense", "compact", "counts"])
def test_resident_layouts_every_kind_mix(triple, layout):
    bms, oracle = triple
    ds = DeviceBitmapSet(bms, layout=layout)
    for op in ("or", "xor", "and"):
        assert ds.aggregate(op) == oracle[op], (layout, op)


def test_byte_ingest_every_kind_mix(triple):
    # serialized-bytes path through the native engine (or NumPy fallback)
    bms, oracle = triple
    blobs = [b.serialize() for b in bms]
    ds = DeviceBitmapSet(blobs)
    assert ds.aggregate("or") == oracle["or"]
