"""64-bit tier tests — longlong package parity (SURVEY §2.3).

Model-based checks against NumPy u64 set oracles, mirroring the reference's
TestRoaring64Bitmap / TestRoaring64NavigableMap strategies, plus
serialization round-trips for the portable spec and the legacy Java format.
"""

import numpy as np
import pytest

from roaringbitmap_tpu import Roaring64Bitmap, Roaring64NavigableMap
from roaringbitmap_tpu.core import bitmap64
from roaringbitmap_tpu.parallel import aggregation


def _sample(seed, n=5000):
    """u64 values spread over low ints, >2^32, and near 2^64."""
    rng = np.random.default_rng(seed)
    parts = [
        rng.integers(0, 1 << 20, n // 3, dtype=np.uint64),
        (np.uint64(1) << np.uint64(33)) + rng.integers(0, 1 << 18, n // 3,
                                                       dtype=np.uint64),
        np.uint64(0xFFFFFFFFFF000000) + rng.integers(0, 1 << 22, n // 3,
                                                     dtype=np.uint64),
    ]
    return np.unique(np.concatenate(parts))


class TestRoaring64Bitmap:
    def test_build_contains_cardinality(self):
        v = _sample(1)
        rb = Roaring64Bitmap.from_values(v)
        assert rb.cardinality == v.size
        assert np.array_equal(rb.to_array(), v)
        for x in v[::511]:
            assert int(x) in rb
        assert (1 << 63) + 12345 not in rb

    def test_point_mutation(self):
        rb = Roaring64Bitmap()
        big = (1 << 40) + 7
        rb.add(big)
        rb.add(3)
        rb.add(2**64 - 1)
        assert sorted(rb) == [3, big, 2**64 - 1]
        rb.remove(big)
        assert big not in rb and rb.cardinality == 2
        rb.flip(5)
        assert 5 in rb
        rb.flip(5)
        assert 5 not in rb

    def test_algebra_matches_oracle(self):
        a_v, b_v = _sample(2), _sample(3)
        a = Roaring64Bitmap.from_values(a_v)
        b = Roaring64Bitmap.from_values(b_v)
        assert np.array_equal((a | b).to_array(), np.union1d(a_v, b_v))
        assert np.array_equal((a & b).to_array(), np.intersect1d(a_v, b_v))
        assert np.array_equal((a - b).to_array(), np.setdiff1d(a_v, b_v))
        assert np.array_equal((a ^ b).to_array(), np.setxor1d(a_v, b_v))
        c = a.clone()
        c.ior(b)
        assert c == (a | b)

    def test_rank_select_navigation(self):
        v = _sample(4, 900)
        rb = Roaring64Bitmap.from_values(v)
        for j in range(0, v.size, 97):
            assert rb.select(j) == int(v[j])
            assert rb.rank(int(v[j])) == j + 1
        assert rb.first() == int(v[0])
        assert rb.last() == int(v[-1])
        assert rb.next_value(int(v[0]) + 1) == (
            int(v[1]) if v[1] > v[0] + 1 else int(v[0]) + 1)
        assert rb.previous_value(int(v[-1]) - 1) <= int(v[-1])
        assert rb.next_value(2**64 - 1) in (-1, int(v[-1]))

    def test_ranges(self):
        base = (1 << 35) + 1000
        rb = Roaring64Bitmap.from_range(base, base + 200000)
        assert rb.cardinality == 200000
        assert rb.first() == base and rb.last() == base + 199999
        rb.remove_range(base + 50, base + 100)
        assert rb.cardinality == 200000 - 50
        rb.flip_range(base, base + 50)
        assert rb.cardinality == 200000 - 100
        assert not rb.contains(base)

    def test_run_optimize_preserves(self):
        rb = Roaring64Bitmap.from_range(1 << 40, (1 << 40) + 70000)
        arr = rb.to_array()
        assert rb.run_optimize()
        assert rb.has_run_compression()
        assert np.array_equal(rb.to_array(), arr)

    def test_portable_serialization_roundtrip(self):
        v = _sample(5)
        rb = Roaring64Bitmap.from_values(v)
        rb.run_optimize()
        data = rb.serialize()
        assert len(data) == rb.serialized_size_in_bytes()
        back = Roaring64Bitmap.deserialize(data)
        assert back == rb

    def test_empty_serialization(self):
        rb = Roaring64Bitmap()
        assert Roaring64Bitmap.deserialize(rb.serialize()).is_empty()

    def test_batch_iterator(self):
        v = _sample(6)
        rb = Roaring64Bitmap.from_values(v)
        got = np.concatenate(list(rb.batch_iterator(1024)))
        assert np.array_equal(got, v)


class TestRoaring64NavigableMap:
    def test_build_and_membership(self):
        v = _sample(7)
        nm = Roaring64NavigableMap.from_values(v)
        assert nm.cardinality == v.size
        assert np.array_equal(nm.to_array(), v)
        assert int(v[17]) in nm
        nm.add(123456789012345)
        assert 123456789012345 in nm
        nm.remove(123456789012345)
        assert 123456789012345 not in nm

    def test_add_int_zero_extends(self):
        nm = Roaring64NavigableMap()
        nm.add_int(-1 & 0xFFFFFFFF)
        assert 0xFFFFFFFF in nm

    def test_algebra(self):
        a_v, b_v = _sample(8), _sample(9)
        a = Roaring64NavigableMap.from_values(a_v)
        b = Roaring64NavigableMap.from_values(b_v)
        c = Roaring64NavigableMap.from_values(a_v)
        c.ior(b)
        assert np.array_equal(c.to_array(), np.union1d(a_v, b_v))
        c = Roaring64NavigableMap.from_values(a_v)
        c.iand(b)
        assert np.array_equal(c.to_array(), np.intersect1d(a_v, b_v))
        c = Roaring64NavigableMap.from_values(a_v)
        c.iandnot(b)
        assert np.array_equal(c.to_array(), np.setdiff1d(a_v, b_v))
        c = Roaring64NavigableMap.from_values(a_v)
        c.ixor(b)
        assert np.array_equal(c.to_array(), np.setxor1d(a_v, b_v))
        assert a == Roaring64NavigableMap.from_values(a_v)

    def test_rank_select_unsigned(self):
        v = _sample(10, 600)
        nm = Roaring64NavigableMap.from_values(v)
        for j in range(0, v.size, 71):
            assert nm.select(j) == int(v[j])
            assert nm.rank(int(v[j])) == j + 1
        assert nm.first() == int(v[0]) and nm.last() == int(v[-1])

    def test_signed_ordering(self):
        # In signed order, negative longs (top bit set) come first.
        vals = [5, -3 & (2**64 - 1), 100, -1 & (2**64 - 1)]
        nm = Roaring64NavigableMap(signed_longs=True)
        for x in vals:
            nm.add(x)
        it = list(nm)
        assert it == [-3 & (2**64 - 1), -1 & (2**64 - 1), 5, 100]
        assert nm.first() == -3 & (2**64 - 1)
        assert nm.last() == 100
        assert nm.select(0) == -3 & (2**64 - 1)
        assert nm.rank(2**64 - 1) == 2  # all "negative" longs are <= -1

    def test_legacy_serialization_roundtrip(self):
        v = _sample(11)
        nm = Roaring64NavigableMap.from_values(v, signed_longs=True)
        data = nm.serialize_legacy()
        assert len(data) == nm.serialized_size_in_bytes(
            bitmap64.SERIALIZATION_MODE_LEGACY)
        back = Roaring64NavigableMap.deserialize_legacy(data)
        assert back == nm and back.signed_longs

    def test_portable_serialization_roundtrip(self):
        v = _sample(12)
        nm = Roaring64NavigableMap.from_values(v)
        data = nm.serialize_portable()
        back = Roaring64NavigableMap.deserialize_portable(data)
        assert back == nm

    def test_serialization_mode_global(self):
        v = _sample(13, 300)
        nm = Roaring64NavigableMap.from_values(v)
        assert nm.serialize() == nm.serialize_legacy()  # default mode legacy
        old = bitmap64.SERIALIZATION_MODE
        try:
            bitmap64.SERIALIZATION_MODE = bitmap64.SERIALIZATION_MODE_PORTABLE
            assert nm.serialize() == nm.serialize_portable()
        finally:
            bitmap64.SERIALIZATION_MODE = old

    def test_cross_class_portable_interop(self):
        """Portable bytes are interchangeable between the two 64-bit classes
        (the RoaringFormatSpec 64-bit extension is one format)."""
        v = _sample(14)
        rb = Roaring64Bitmap.from_values(v)
        nm = Roaring64NavigableMap.deserialize_portable(rb.serialize())
        assert np.array_equal(nm.to_array(), v)
        rb2 = Roaring64Bitmap.deserialize(nm.serialize_portable())
        assert rb2 == rb
        assert nm.to_roaring64() == rb
        assert Roaring64NavigableMap.from_roaring64(rb) == nm

    def test_add_range(self):
        lo = (1 << 33) - 100
        nm = Roaring64NavigableMap()
        nm.add_range(lo, lo + 300)  # crosses the 2^32 bucket boundary
        assert nm.cardinality == 300
        assert nm.first() == lo and nm.last() == lo + 299


TESTDATA = "/root/reference/RoaringBitmap/src/test/resources/testdata"


@pytest.mark.skipif(not __import__("os").path.isdir(TESTDATA),
                    reason="reference corpus not mounted")
class TestCRoaringPortableFixtures:
    """CRoaring-produced portable 64-bit files (TestRoaring64NavigableMap
    :1645-1731): parse, check cardinality/selects, re-serialize
    byte-identically."""

    def _load(self, name):
        with open(f"{TESTDATA}/{name}", "rb") as f:
            return f.read()

    @pytest.mark.parametrize("cls", [Roaring64Bitmap,
                                     Roaring64NavigableMap])
    def test_empty(self, cls):
        data = self._load("64mapempty.bin")
        rb = (cls.deserialize(data) if cls is Roaring64Bitmap
              else cls.deserialize_portable(data))
        assert rb.cardinality == 0
        out = rb.serialize() if cls is Roaring64Bitmap else rb.serialize_portable()
        assert out == data

    def test_32bitvals(self):
        data = self._load("64map32bitvals.bin")
        nm = Roaring64NavigableMap.deserialize_portable(data)
        assert nm.cardinality == 10
        assert len(nm._map) == 1
        assert nm.select(0) == 0 and nm.select(9) == 9
        assert nm.serialize_portable() == data
        rb = Roaring64Bitmap.deserialize(data)
        assert rb.cardinality == 10 and rb.serialize() == data

    def test_spreadvals(self):
        data = self._load("64mapspreadvals.bin")
        nm = Roaring64NavigableMap.deserialize_portable(data)
        assert nm.cardinality == 100 and len(nm._map) == 10
        assert nm.select(0) == 0 and nm.select(9) == 9
        assert nm.select(90) == (9 << 32)
        assert nm.select(91) == (9 << 32) + 1
        assert nm.select(99) == (9 << 32) + 9
        assert nm.serialize_portable() == data
        rb = Roaring64Bitmap.deserialize(data)
        assert rb.cardinality == 100 and rb.serialize() == data

    def test_highvals(self):
        data = self._load("64maphighvals.bin")
        nm = Roaring64NavigableMap.deserialize_portable(data)
        m = 0xFFFFFFFF
        assert nm.cardinality == 121 and len(nm._map) == 11
        assert nm.select(0) == ((m - 10) << 32) + (m - 10)
        assert nm.select(10) == ((m - 10) << 32) + m
        assert nm.select(110) == (m << 32) + (m - 10)
        assert nm.select(111) == (m << 32) + (m - 9)
        assert nm.select(120) == (m << 32) + m
        assert nm.serialize_portable() == data
        rb = Roaring64Bitmap.deserialize(data)
        assert rb.cardinality == 121 and rb.serialize() == data


class TestWideAggregation64:
    def test_wide_or64_matches_oracle(self):
        rng = np.random.default_rng(20)
        arrs = [
            np.unique((np.uint64(1) << np.uint64(34))
                      + rng.integers(0, 1 << 20, 4000, dtype=np.uint64))
            for _ in range(12)
        ]
        bms = [Roaring64Bitmap.from_values(a) for a in arrs]
        got = aggregation.or64(bms, engine="xla")
        oracle = np.unique(np.concatenate(arrs))
        assert np.array_equal(got.to_array(), oracle)
        assert isinstance(got, Roaring64Bitmap)

    def test_wide_xor64_matches_oracle(self):
        rng = np.random.default_rng(21)
        arrs = [np.unique(rng.integers(0, 1 << 22, 3000, dtype=np.uint64)
                          + np.uint64(1 << 45)) for _ in range(7)]
        bms = [Roaring64Bitmap.from_values(a) for a in arrs]
        got = aggregation.xor64(bms, engine="xla")
        acc = Roaring64Bitmap()
        for b in bms:
            acc.ixor(b)
        assert got == acc

    def test_wide_and64_matches_oracle(self):
        rng = np.random.default_rng(22)
        base = np.unique(rng.integers(0, 1 << 18, 5000, dtype=np.uint64)
                         + np.uint64(1 << 50))
        arrs = [np.union1d(base, rng.integers(0, 1 << 18, 1000,
                                              dtype=np.uint64))
                for _ in range(6)]
        bms = [Roaring64Bitmap.from_values(a) for a in arrs]
        got = aggregation.and64(bms)
        oracle = arrs[0]
        for a in arrs[1:]:
            oracle = np.intersect1d(oracle, a)
        assert np.array_equal(got.to_array(), oracle)


def test_device_set_with_u64_keys(rng):
    """DeviceBitmapSet over the 64-bit tier: u64 high-48 keys ride the same
    blocked engine (SURVEY §2.3 — same packed container pools)."""
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
    from roaringbitmap_tpu.parallel import aggregation

    bms = []
    for i in range(6):
        vals = (np.uint64(1) << np.uint64(33)) * np.uint64(i % 3) \
            + rng.integers(0, 1 << 18, 3000).astype(np.uint64)
        bms.append(Roaring64Bitmap.from_values(vals))
    want = aggregation.or64(*bms)
    ds = DeviceBitmapSet(bms)
    got = ds.aggregate("or", engine="xla")
    assert got == want
    assert np.array_equal(got.to_array(), want.to_array())
    # all three residency layouts serve the 64-bit tier (key dtype rides
    # through packing; unpack restores the class)
    for layout in ("counts", "compact"):
        dsl = DeviceBitmapSet(bms, layout=layout)
        gl = dsl.aggregate("or")
        assert isinstance(gl, Roaring64Bitmap) and gl == want, layout


def test_long_tail_surface():
    """Roaring64Bitmap's visitor/iterator long tail (forEach family,
    getLongIterator(From), limit, aliases)."""
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    vals = np.array([5, 100, (1 << 40) + 3, (1 << 63) + 9], dtype=np.uint64)
    rb = Roaring64Bitmap.from_values(vals)
    seen = []
    rb.for_each(seen.append)
    assert seen == vals.tolist()
    seen2 = []
    rb.for_each_in_range(6, 1 << 41, seen2.append)
    assert seen2 == [100, (1 << 40) + 3]
    bits = []
    rb.for_all_in_range(99, 102, lambda rel, p: bits.append((rel, p)))
    assert bits == [(0, False), (1, True), (2, False)]
    assert list(rb.long_iterator()) == vals.tolist()
    assert list(rb.long_iterator_from(100)) == vals[1:].tolist()
    assert list(rb.reverse_long_iterator()) == vals[::-1].tolist()
    assert list(rb.reverse_long_iterator_from(1 << 40)) == [100, 5]
    assert rb.limit(2).to_array().tolist() == [5, 100]
    assert rb.rank_long((1 << 40) + 3) == 3
    assert rb.int_cardinality == rb.cardinality == 4
    assert rb.get_long_size_in_bytes() == rb.get_size_in_bytes()
    rb.trim()


def test_long_tail_u64_boundaries():
    """stop=2^64 covers the top of the universe; iterators stay lazy."""
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    top = (1 << 64) - 1
    rb = Roaring64Bitmap.from_values(np.array([5, top], dtype=np.uint64))
    seen = []
    rb.for_each_in_range(0, 1 << 64, seen.append)
    assert seen == [5, top]
    bits = []
    rb.for_all_in_range(top - 1, 1 << 64, lambda r, p: bits.append((r, p)))
    assert bits == [(0, False), (1, True)]
    assert list(rb.long_iterator_from(6)) == [top]
    assert list(rb.reverse_long_iterator_from(top)) == [top, 5]
    assert list(rb.reverse_long_iterator_from(top - 1)) == [5]
    assert rb.limit(1).to_array().tolist() == [5]


# ------------------------------------------------------------ ART wire codec
# HighLowContainer.serialize:155-185 / Art.serializeArt / Containers.serialize
# — the reference Roaring64Bitmap's native format (VERDICT r4 missing #2).

def _art_workloads(rng):
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    yield Roaring64Bitmap()                                     # empty tag
    yield Roaring64Bitmap.from_values(
        np.array([42], dtype=np.uint64))                        # leaf root
    # >48 distinct second bytes under one first byte -> Node256 on that level,
    # plus spread over first bytes for Node4/16/48 shapes, plus container mix
    vals = [rng.integers(0, 1 << 20, 300).astype(np.uint64),    # arrays
            np.arange(5 << 16, (5 << 16) + 30000, dtype=np.uint64),  # bitmap
            (np.arange(0, 300, dtype=np.uint64) << np.uint64(24)) + 7,
            (np.arange(0, 60, dtype=np.uint64) << np.uint64(17)),
            np.array([0, (1 << 48) - 1, (1 << 63), (1 << 64) - 1],
                     dtype=np.uint64)]
    rb = Roaring64Bitmap.from_values(np.unique(np.concatenate(vals)))
    rb.run_optimize()
    yield rb


def test_art_roundtrip(rng):
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    for rb in _art_workloads(rng):
        blob = rb.serialize_art()
        back = Roaring64Bitmap.deserialize_art(blob)
        assert back == rb
        # deserialize() auto-detects the ART stream (and still reads its own)
        assert Roaring64Bitmap.deserialize(blob) == rb
        assert Roaring64Bitmap.deserialize(rb.serialize()) == rb


@pytest.mark.parametrize("fan,kind", [(3, 0), (12, 1), (40, 2), (60, 3)])
def test_art_node_kind_coverage(fan, kind):
    """The canonical writer emits Node4/16/48/256 by fanout; the root kind
    byte directly follows the i64 key count.  Each shape round-trips."""
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    # i << 56 puts i in the top byte of the high-48 key -> root fanout == fan
    vals = (np.arange(fan, dtype=np.uint64) << np.uint64(56)) + np.uint64(9)
    rb = Roaring64Bitmap.from_values(vals)
    blob = rb.serialize_art()
    assert blob[9] == kind
    assert Roaring64Bitmap.deserialize_art(blob) == rb


def test_art_hostile_streams(rng):
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap
    from roaringbitmap_tpu.format.spec import InvalidRoaringFormat

    rb = list(_art_workloads(rng))[-1]
    blob = bytearray(rb.serialize_art())
    hostile = [
        b"", b"\x07", b"\x01", b"\x01" + b"\x00" * 8,
        bytes(blob[:40]),                      # truncated node stream
        bytes(blob[:len(blob) - 7]),           # truncated trailer
        b"\x01" + (2 ** 62).to_bytes(8, "little") + bytes(blob[9:]),
        b"\x01" + (8).to_bytes(8, "little", signed=True)
        + b"\x00\x01\x00\x00" * 500,           # NODE4 chain nesting attack
    ]
    for h in hostile:
        with pytest.raises(InvalidRoaringFormat):
            Roaring64Bitmap.deserialize_art(h)
    # the auto-detecting entry names both formats on garbage
    with pytest.raises(InvalidRoaringFormat, match="neither portable"):
        Roaring64Bitmap.deserialize(b"\x07\x03" * 9)


def test_navigable_map_supplier(rng):
    """BitmapDataProviderSupplier analog: the 32-bit bucket backend is
    pluggable (Roaring64NavigableMap.java ctor overloads) — FastRank for
    rank-heavy use, MutableRoaringBitmap for the buffer tier."""
    from roaringbitmap_tpu.buffer import MutableRoaringBitmap
    from roaringbitmap_tpu.core.bitmap64 import Roaring64NavigableMap
    from roaringbitmap_tpu.core.fastrank import FastRankRoaringBitmap

    vals = rng.integers(0, 1 << 40, 5000, dtype=np.uint64)
    plain = Roaring64NavigableMap.from_values(vals)
    for supplier in (FastRankRoaringBitmap, MutableRoaringBitmap):
        nm = Roaring64NavigableMap.from_values(vals, supplier=supplier)
        assert all(isinstance(b, supplier) for b in nm._map.values())
        assert nm.cardinality == plain.cardinality
        assert nm.select(17) == plain.select(17)
        assert nm.rank(int(vals[0])) == plain.rank(int(vals[0]))
        nm.add((1 << 52) + 5)         # fresh high word: add() allocates
        assert isinstance(nm._map[(1 << 52) >> 32], supplier)
        nm.add_range(1 << 50, (1 << 50) + 10)  # and so does add_range()
        assert isinstance(nm._map[(1 << 50) >> 32], supplier)
        # supplier-backed buckets serialize interchangeably with plain ones
        rt = Roaring64NavigableMap.deserialize_portable(
            nm.serialize_portable())
        nm_plain = Roaring64NavigableMap.from_values(nm.to_array())
        assert rt == nm_plain
        import pickle

        back = pickle.loads(pickle.dumps(nm))
        assert back == nm_plain
        assert all(isinstance(b, supplier) for b in back._map.values())


def test_navigable_map_long_tail_surface(rng):
    """The NavigableMap's remaining reference surface (clear/flip/forEach/
    limit/iterators/size accessors/lazy aliases), against the
    Roaring64Bitmap twin as oracle."""
    vals = np.unique(rng.integers(0, 1 << 40, 4000, dtype=np.uint64))
    nm = Roaring64NavigableMap.from_values(vals)
    seen = []
    nm.for_each(seen.append)
    assert seen == vals.tolist()
    assert list(nm.get_long_iterator()) == vals.tolist()
    assert list(nm.get_reverse_long_iterator()) == vals.tolist()[::-1]
    assert np.array_equal(nm.limit(100).to_array(), vals[:100])
    assert nm.limit(1 << 30) == nm
    assert nm.long_cardinality == nm.cardinality == vals.size
    assert nm.int_cardinality == vals.size
    assert nm.get_size_in_bytes() == nm.get_long_size_in_bytes() > 0
    nm.trim()
    x = int(vals[7])
    nm.flip(x)
    assert x not in nm
    nm.flip(x)
    assert x in nm
    lazy = Roaring64NavigableMap.from_values(vals[:100])
    lazy.naive_lazy_or(Roaring64NavigableMap.from_values(vals[100:]))
    lazy.repair_after_lazy()
    assert lazy == nm
    d = Roaring64NavigableMap.from_values(vals)
    d.and_not(Roaring64NavigableMap.from_values(vals[::2]))
    assert np.array_equal(d.to_array(), vals[1::2])
    d.clear()
    assert d.cardinality == 0
    # signed order: reverse iterator follows the signed sequence
    sv = np.array([5, (1 << 63) + 9, 100], dtype=np.uint64)
    sn = Roaring64NavigableMap.from_values(sv, signed_longs=True)
    assert list(sn.get_reverse_long_iterator()) == [100, 5, (1 << 63) + 9]
    # Roaring64Bitmap twins of the new aliases
    rb = Roaring64Bitmap.from_values(vals)
    assert rb.long_cardinality == vals.size
    rb.and_not(Roaring64Bitmap.from_values(vals[::2]))
    assert np.array_equal(rb.to_array(), vals[1::2])
