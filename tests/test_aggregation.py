"""Device wide-aggregation parity: every engine vs the host fold oracle.

The ParallelAggregationTest strategy (ParallelAggregationTest.java:18-40) —
same op under different execution regimes must agree exactly."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import DeviceBitmapSet, aggregation
from roaringbitmap_tpu.utils import datasets


@pytest.fixture(scope="module")
def workload():
    return datasets.synthetic_bitmaps(24, seed=7, universe=1 << 21, density=0.015)


@pytest.fixture(scope="module")
def oracles(workload):
    o, x = RoaringBitmap(), RoaringBitmap()
    a = workload[0].clone()
    for b in workload:
        o.ior(b)
        x.ixor(b)
    for b in workload[1:]:
        a.iand(b)
    return {"or": o, "xor": x, "and": a}


@pytest.mark.parametrize("engine", ["xla", "pallas"])
@pytest.mark.parametrize("op", ["or", "xor"])
def test_ragged_engines_match_host(workload, oracles, op, engine):
    fn = aggregation.or_ if op == "or" else aggregation.xor
    # fallback=False pins the engine: a regression must fail here, not
    # demote down the runtime.guard chain and still pass
    assert fn(workload, engine=engine, fallback=False) == oracles[op]


def test_wide_and_matches_host(workload, oracles):
    assert aggregation.and_(workload) == oracles["and"]


def test_wide_and_nonempty_result():
    base = RoaringBitmap.from_values(np.arange(0, 300000, 3, dtype=np.uint32))
    bms = [base.clone() for _ in range(8)]
    bms[3] = base | RoaringBitmap.bitmap_of(1, 2)
    got = aggregation.and_(bms)
    assert got == base and got.cardinality == base.cardinality


def test_cardinality_only_paths(workload, oracles):
    assert aggregation.or_cardinality(workload) == oracles["or"].cardinality
    assert aggregation.xor_cardinality(workload) == oracles["xor"].cardinality
    assert aggregation.and_cardinality(workload) == oracles["and"].cardinality


def test_edge_cases():
    assert aggregation.or_().is_empty()
    assert aggregation.and_().is_empty()
    one = RoaringBitmap.bitmap_of(1, 2, 3)
    assert aggregation.or_(one) == one
    assert aggregation.and_(one, RoaringBitmap()) .is_empty()
    # disjoint key sets
    a = RoaringBitmap.from_values(np.arange(100, dtype=np.uint32))
    b = RoaringBitmap.from_values(np.arange(1 << 20, (1 << 20) + 100, dtype=np.uint32))
    assert aggregation.and_(a, b).is_empty()
    assert aggregation.or_(a, b).cardinality == 200


def test_device_bitmap_set_reuse(workload, oracles):
    ds = DeviceBitmapSet(workload)
    assert ds.aggregate("or", engine="xla") == oracles["or"]
    assert ds.aggregate("or", engine="pallas") == oracles["or"]
    assert ds.aggregate("xor", engine="xla") == oracles["xor"]
    assert ds.hbm_bytes() > 0


def test_single_immutable_input():
    """len==1 paths must not call clone() on a clone-less immutable
    (ADVICE r1): materialize via to_bitmap() instead."""
    from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap

    rb = RoaringBitmap.bitmap_of(1, 5, 70000)
    imm = ImmutableRoaringBitmap.from_bitmap(rb)
    assert aggregation.or_(imm) == rb
    assert aggregation.xor(imm) == rb
    assert aggregation.and_(imm) == rb


def test_densify_trailing_empty_run_container():
    """Empty run containers (incl. as the last scatter entry) must densify
    to zero rows, not crash the batched run expansion."""
    from roaringbitmap_tpu.core import containers as C
    from roaringbitmap_tpu.ops import packing

    conts = [
        C.RunContainer(np.array([5, 2], dtype=np.uint16)),   # {5,6,7}
        C.RunContainer(np.empty(0, dtype=np.uint16)),
        C.ArrayContainer(np.array([1], dtype=np.uint16)),
        C.RunContainer(np.empty(0, dtype=np.uint16)),        # trailing empty
    ]
    out = packing.densify_containers(conts, [0, 1, 2, 3], 4)
    assert out[0].view(np.uint64)[0] == (1 << 5) | (1 << 6) | (1 << 7)
    assert not out[1].any() and not out[3].any()
    assert out[2].view(np.uint64)[0] == 2
    # all-empty list of run containers
    out2 = packing.densify_containers(
        [C.RunContainer(np.empty(0, dtype=np.uint16))], [0], 1)
    assert not out2.any()


def test_xor_empty_container_dropped():
    a = RoaringBitmap.bitmap_of(5, 70000)
    b = RoaringBitmap.bitmap_of(5, 70001)
    got = aggregation.xor(a, b)
    assert got.to_array().tolist() == [70000, 70001]
    # key 0 cancelled entirely; container must be dropped, not kept empty
    assert got.container_count() == 1


def test_device_set_and(workload, oracles):
    """Resident AND: gathered full segments, missing keys annihilate."""
    ds = DeviceBitmapSet(workload)
    assert ds.aggregate("and") == oracles["and"]
    # disjoint key sets: segmented AND must NOT ignore missing containers
    ds2 = DeviceBitmapSet(
        [RoaringBitmap.bitmap_of(1), RoaringBitmap.bitmap_of(0x10002)])
    assert ds2.aggregate("and").is_empty()
    with pytest.raises(ValueError):
        ds2.aggregate("andnot")


def test_device_set_range_cardinality(workload, oracles):
    ds = DeviceBitmapSet(workload)
    union = oracles["or"]
    for start, stop in [(0, 1 << 21), (1000, 250000), (65536, 65536 * 3 + 17)]:
        want = int(np.count_nonzero(
            (union.to_array() >= start) & (union.to_array() < stop)))
        assert ds.aggregate_range_cardinality("or", start, stop) == want


@pytest.mark.parametrize("op", ["or", "and", "xor", "andnot"])
def test_batched_pairwise(workload, op):
    # single engine by design: pairwise Pallas variants lost to XLA's
    # fused op+popcount on every measured dataset (realdata_r04) and were
    # deleted; the engine kwarg is accepted and ignored
    from roaringbitmap_tpu.core.bitmap import and_ as h_and, andnot as h_andnot
    from roaringbitmap_tpu.core.bitmap import or_ as h_or, xor as h_xor

    host = {"or": h_or, "and": h_and, "xor": h_xor, "andnot": h_andnot}[op]
    pairs = list(zip(workload[0::2], workload[1::2]))
    got = aggregation.pairwise(op, pairs)
    want = [host(a, b) for a, b in pairs]
    assert got == want
    cards = aggregation.pairwise_cardinality(op, pairs)
    assert cards.tolist() == [w.cardinality for w in want]


def test_batched_pairwise_empty_and_disjoint():
    e = RoaringBitmap()
    a = RoaringBitmap.bitmap_of(1, 2, 3)
    b = RoaringBitmap.bitmap_of(0x20001)
    got = aggregation.pairwise("or", [(e, e), (a, b)])
    assert got[0].is_empty() and got[1] == (a | b)
    cards = aggregation.pairwise_cardinality("and", [(e, e), (a, b)])
    assert cards.tolist() == [0, 0]


def test_chained_wide_or_parity(workload, oracles):
    ds = DeviceBitmapSet(workload)
    for eng in ("xla", "pallas"):
        total = int(np.asarray(ds.chained_wide_or(4, engine=eng)(ds.words)))
        assert total == 4 * oracles["or"].cardinality


def test_chained_aggregate_parity_all_ops_layouts(rng):
    """chained_aggregate (optimization_barrier methodology) must agree with
    the host tier for every op x engine x layout — and with chained_wide_or
    (write-back methodology) for OR: two independent anti-elision mechanisms
    cross-checking each other."""
    from roaringbitmap_tpu.parallel import fast_aggregation
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet

    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 19, 4000).astype(np.uint32)) for _ in range(12)]
    # guarantee a non-empty wide-AND: give every bitmap a shared run
    common = np.arange(100, 600, dtype=np.uint32)
    bms = [b | RoaringBitmap.from_values(common) for b in bms]
    want = {"or": fast_aggregation.or_(*bms).cardinality,
            "xor": fast_aggregation.xor(*bms).cardinality,
            "and": fast_aggregation.and_(*bms).cardinality}
    assert want["and"] >= 500
    reps = 5
    for layout in ("dense", "compact"):
        ds = DeviceBitmapSet(bms, layout=layout)
        for op in ("or", "xor", "and"):
            for eng in ("xla", "pallas"):
                got = int(np.asarray(
                    ds.chained_aggregate(op, reps, engine=eng)(ds.words)))
                assert got == (reps * want[op]) % 2**32, (layout, op, eng)
        got_wb = int(np.asarray(
            ds.chained_wide_or(reps, engine="xla")(ds.words)))
        assert got_wb == (reps * want["or"]) % 2**32, layout


def test_counts_layout_parity():
    """The counts-resident layout (nibble counts built once, queries run
    straight off them) must match host and the other layouts for or/xor on
    both engines, fall back correctly for and, and hold half the dense
    image's HBM."""
    from roaringbitmap_tpu.parallel import fast_aggregation

    rng = np.random.default_rng(11)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 19, 5000).astype(np.uint32)) for _ in range(10)]
    common = np.arange(50, 800, dtype=np.uint32)
    bms = [b | RoaringBitmap.from_values(common) for b in bms]
    # a dense chunk so build_group_counts' bit->nibble spread is exercised
    bms[0] = bms[0] | RoaringBitmap.from_values(
        np.arange(1 << 16, (1 << 16) + 30000, dtype=np.uint32))
    want = {op: fn(*bms) for op, fn in
            (("or", fast_aggregation.or_), ("xor", fast_aggregation.xor),
             ("and", fast_aggregation.and_))}
    ds = DeviceBitmapSet(bms, layout="counts")
    dense_ds = DeviceBitmapSet(bms, layout="dense")
    # sparse-dominated workload: counts + streams stays under the dense
    # image (bitmap-heavy sets can exceed it — see the layout docstring)
    assert ds.hbm_bytes() < dense_ds.hbm_bytes()
    with pytest.raises(ValueError):
        DeviceBitmapSet(bms, block=24, layout="counts")  # gps=3 not 2^k
    for op in ("or", "xor"):
        for eng in ("pallas", "xla"):
            assert ds.aggregate(op, engine=eng) == want[op], (op, eng)
    assert ds.aggregate("and") == want["and"]
    reps = 3
    for op in ("or", "xor"):
        got = int(np.asarray(ds.chained_aggregate(op, reps,
                                                  engine="pallas")(None)))
        assert got == (reps * want[op].cardinality) % 2**32, op
    got = int(np.asarray(ds.chained_wide_or(reps)(None)))
    assert got == (reps * want["or"].cardinality) % 2**32


@pytest.mark.parametrize("n,block,gps", [(24, 16, 2), (40, 32, 4)])
def test_counts_layout_multi_group_steps(n, block, gps):
    """block=16/32 -> 2/4 groups per kernel super-step (the adaptive
    ladder's upper rungs); super-steps must not split segments and parity
    must hold."""
    from roaringbitmap_tpu.parallel import fast_aggregation

    rng = np.random.default_rng(13)
    # n bitmaps sharing every key -> median segment n -> ladder picks block
    bms = [RoaringBitmap.from_values(np.concatenate(
        [c * (1 << 16) + rng.integers(0, 1 << 14, 800) for c in range(3)]
        ).astype(np.uint32)) for _ in range(n)]
    ds = DeviceBitmapSet(bms, layout="counts")
    assert ds.block == block and ds._gps == gps
    # dense layout at the same rung: blocked kernel tree-reduces `block`
    # rows per step
    ds2 = DeviceBitmapSet(bms, layout="dense")
    assert ds2.block == block
    for op, fn in (("or", fast_aggregation.or_),
                   ("xor", fast_aggregation.xor)):
        want = fn(*bms)
        assert ds.aggregate(op, engine="pallas") == want, op
        assert ds2.aggregate(op, engine="pallas") == want, op


def test_fused_compact_nibble_count_saturation():
    """The fused compact reduce (ops.kernels.fused_nibble_reduce) encodes
    per-bit occurrence COUNTS in nibbles, exact only while a count group
    holds <= NIBBLE_GROUP containers.  Worst case: every container of a
    full group sets the SAME bits — count 8, the nibble ceiling — mixed
    with odd/even overlap so OR and XOR diverge, plus dense rows in the
    same segments so the dense-partial head fold is exercised."""
    from roaringbitmap_tpu.parallel import fast_aggregation

    same = np.arange(0, 4000, 7, dtype=np.uint32)        # count == N bits
    odd = np.arange(1, 3000, 9, dtype=np.uint32)
    bms = []
    for i in range(8):                                    # one full group
        vals = [same]
        if i < 3:                                         # count-3 bits
            vals.append(odd)
        if i == 0:                                        # dense row, same key
            vals.append(np.arange(20000, 30000, dtype=np.uint32))
        bms.append(RoaringBitmap.from_values(
            np.unique(np.concatenate(vals))))
    want_or = fast_aggregation.or_(*bms)
    want_xor = fast_aggregation.xor(*bms)
    assert want_xor.cardinality < want_or.cardinality    # overlap is real
    ds = DeviceBitmapSet(bms, layout="compact")
    assert ds.aggregate("or", engine="pallas") == want_or
    assert ds.aggregate("xor", engine="pallas") == want_xor


class TestDeviceQueryPlans:
    """DeviceBitmap: aggregate results compose on device (SURVEY §7 hard
    part (d) — no host round trip inside a query plan)."""

    def _sets(self, rng):
        mk = lambda seed: [RoaringBitmap.from_values(
            np.random.default_rng(seed + i).integers(
                0, 1 << 19, 4000).astype(np.uint32)) for i in range(8)]
        return mk(100), mk(200)

    def test_compose_two_aggregates(self, rng):
        from roaringbitmap_tpu.parallel import fast_aggregation
        from roaringbitmap_tpu.parallel.aggregation import (
            DeviceBitmap, DeviceBitmapSet)

        a_bms, b_bms = self._sets(rng)
        ua = DeviceBitmap.aggregate(DeviceBitmapSet(a_bms), "or")
        ub = DeviceBitmap.aggregate(DeviceBitmapSet(b_bms), "or")
        host_a = fast_aggregation.or_(*a_bms)
        host_b = fast_aggregation.or_(*b_bms)
        for op, host in (
                ("__and__", host_a & host_b), ("__or__", host_a | host_b),
                ("__xor__", host_a ^ host_b), ("__sub__", host_a - host_b)):
            got = getattr(ua, op)(ub)
            assert got.materialize() == host, op
            assert got.cardinality() == host.cardinality, op

    def test_plan_chains_without_host(self, rng):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        a = RoaringBitmap.from_values(np.arange(0, 100000, 3, dtype=np.uint32))
        b = RoaringBitmap.from_values(np.arange(0, 100000, 5, dtype=np.uint32))
        c = RoaringBitmap.from_values(np.arange(0, 100000, 7, dtype=np.uint32))
        da, db, dc = (DeviceBitmap.from_host(x) for x in (a, b, c))
        plan = (da | db) & dc - (da & db)     # composes in HBM
        want = ((a | b) & c) - (a & b)
        assert plan.materialize() == want
        assert plan.range_cardinality(1000, 50000) == \
            want.range_cardinality(1000, 50000)

    def test_disjoint_key_spaces(self):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        a = RoaringBitmap.bitmap_of(1, 2, 3)
        b = RoaringBitmap.bitmap_of((5 << 16) + 1)
        got = DeviceBitmap.from_host(a) | DeviceBitmap.from_host(b)
        assert got.materialize() == (a | b)
        empty = DeviceBitmap.from_host(a) & DeviceBitmap.from_host(b)
        assert empty.cardinality() == 0
        assert empty.materialize() == RoaringBitmap()

    def test_contains_batch_on_device(self, rng):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        rb = RoaringBitmap.from_values(
            rng.integers(0, 1 << 20, 30000).astype(np.uint32))
        db = DeviceBitmap.from_host(rb)
        probes = np.concatenate([
            rb.to_array()[::37],                       # present
            rng.integers(0, 1 << 21, 500).astype(np.uint32),  # mixed
            np.array([0, 0xFFFFFFFF], dtype=np.uint32)])
        got = db.contains_batch(probes)
        want = np.array([rb.contains(int(v)) for v in probes])
        assert np.array_equal(got, want)
        empty = DeviceBitmap.from_host(RoaringBitmap())
        assert not empty.contains_batch(np.array([1, 2], np.uint32)).any()

    def test_u64_plan_materialize_and_contains(self, rng):
        from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap
        from roaringbitmap_tpu.parallel.aggregation import (
            DeviceBitmap, DeviceBitmapSet)

        bms = [Roaring64Bitmap.from_values(
            (np.uint64(1) << np.uint64(48)) * np.uint64(i % 2)
            + np.arange(i * 100, 4000, dtype=np.uint64)) for i in range(4)]
        from roaringbitmap_tpu.parallel import aggregation as agg
        want = agg.or64(*bms)
        db = DeviceBitmap.aggregate(DeviceBitmapSet(bms), "or")
        got = db.materialize()
        assert isinstance(got, Roaring64Bitmap) and got == want
        probes = np.array([0, 50, 1 << 48, (1 << 48) + 399,
                           (1 << 48) + 999999, 1 << 52], dtype=np.uint64)
        res = db.contains_batch(probes)
        assert res.tolist() == [want.contains(int(v)) for v in probes]

    def test_contains_batch_out_of_range_probes(self):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        db = DeviceBitmap.from_host(RoaringBitmap.bitmap_of(5))
        probes = np.array([5, 5 + (1 << 32), (1 << 63) + 5], dtype=np.uint64)
        assert db.contains_batch(probes).tolist() == [True, False, False]
        assert db.contains_batch(
            np.array([-1, 5], dtype=np.int64)).tolist() == [False, True]

    def test_u64_range_cardinality_top_half(self):
        from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap
        from roaringbitmap_tpu.parallel.aggregation import (
            DeviceBitmap, DeviceBitmapSet)

        vals = (np.uint64(1) << np.uint64(63)) + np.arange(100, dtype=np.uint64)
        db = DeviceBitmap.aggregate(
            DeviceBitmapSet([Roaring64Bitmap.from_values(vals)]), "or")
        assert db.range_cardinality(0, 1 << 64) == 100
        assert db.range_cardinality((1 << 63) + 50, 1 << 64) == 50
        assert db.range_cardinality(0, 1 << 63) == 0


class TestDevicePairSet:
    """Resident pair batch: pack once (compact streams, device densify),
    query many — the pairwise analog of DeviceBitmapSet."""

    @pytest.fixture(scope="class")
    def pairs(self, workload):
        return list(zip(workload[0::2], workload[1::2]))

    @pytest.fixture(scope="class")
    def want(self, pairs):
        from roaringbitmap_tpu.core.bitmap import and_ as h_and, andnot as h_andnot
        from roaringbitmap_tpu.core.bitmap import or_ as h_or, xor as h_xor

        return {op: [f(a, b) for a, b in pairs]
                for op, f in (("or", h_or), ("and", h_and), ("xor", h_xor),
                              ("andnot", h_andnot))}

    @pytest.mark.parametrize("layout", ["dense", "compact"])
    @pytest.mark.parametrize("op", ["or", "and", "xor", "andnot"])
    def test_all_ops_both_layouts(self, pairs, want, op, layout):
        ps = aggregation.DevicePairSet(pairs, layout=layout)
        assert ps.pairwise(op) == want[op]
        assert ps.cardinalities(op).tolist() == [
            w.cardinality for w in want[op]]

    def test_engine_kwarg_accepted_and_ignored(self, pairs, want):
        # pairwise runs one engine (see aggregation module docstring);
        # legacy engine values must still be accepted
        ps = aggregation.DevicePairSet(pairs)
        for engine in ("auto", "xla", "pallas"):
            assert ps.pairwise("xor", engine=engine) == want["xor"]

    @pytest.mark.parametrize("layout", ["dense", "compact"])
    def test_chained_cardinality(self, pairs, want, layout):
        ps = aggregation.DevicePairSet(pairs, layout=layout)
        total = sum(w.cardinality for w in want["and"])
        got = int(np.asarray(ps.chained_cardinality("and", 3)()))
        assert got == (3 * total) % (1 << 32)

    def test_byte_backed_operands(self, pairs, want):
        """Serialized blobs and ImmutableRoaringBitmaps stream straight off
        the wire layout — parity with the object path."""
        from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap

        mixed = [(a.serialize(), ImmutableRoaringBitmap(b.serialize()))
                 for a, b in pairs]
        ps = aggregation.DevicePairSet(mixed)
        assert ps.pairwise("or") == want["or"]

    def test_empty_and_disjoint(self):
        e = RoaringBitmap()
        a = RoaringBitmap.bitmap_of(1, 2, 3)
        b = RoaringBitmap.bitmap_of(0x20001)
        ps = aggregation.DevicePairSet([(e, e), (a, b)])
        got = ps.pairwise("or")
        assert got[0].is_empty() and got[1] == (a | b)
        assert ps.cardinalities("and").tolist() == [0, 0]
        assert ps.hbm_bytes() > 0


def test_contains_batch_rejects_non_integer_probes():
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

    db = DeviceBitmap.from_host(RoaringBitmap.bitmap_of(5))
    with pytest.raises(TypeError, match="integer probes"):
        db.contains_batch(np.array([5.0, 4294967296.0]))
    with pytest.raises(TypeError, match="integer probes"):
        db.contains_batch(np.array([True, False]))


def test_device_bitmap_tier_mismatch_rejected():
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap
    from roaringbitmap_tpu.parallel.aggregation import (
        DeviceBitmap, DeviceBitmapSet)

    d32 = DeviceBitmap.from_host(RoaringBitmap.bitmap_of(1, 2))
    d64 = DeviceBitmap.aggregate(DeviceBitmapSet(
        [Roaring64Bitmap.from_values(
            np.array([1 << 40], dtype=np.uint64))]), "or")
    with pytest.raises(TypeError, match="tiers"):
        _ = d32 | d64


# -- relocated out of test_realdata.py: its module-level census1881 skip
# gate must not swallow tests that need no census data (review finding)

def _has_range_dataset():
    return datasets.has_range_dataset()


@pytest.mark.skipif(not _has_range_dataset(),
                    reason="random_range.zip not mounted")
def test_range_retriever_builds_bitmaps():
    """ZipRealDataRangeRetriever analog (ZipRealDataRangeRetriever.java
    :40-66): interval rows build via add_range, bit-exact with expansion."""
    rows = datasets.load_range_arrays()
    assert rows, "range dataset parsed to nothing"
    for intervals in rows[:5]:
        assert intervals.ndim == 2 and intervals.shape[1] == 2
        rb = RoaringBitmap()
        oracle = set()
        # intervals arrive unsorted and OVERLAPPING — the retriever hands
        # them through raw; union semantics are the consumer's job
        for start, end in intervals:
            rb.add_range(int(start), int(end))
            oracle.update(range(int(start), int(end)))
        assert rb.cardinality == len(oracle)
        assert set(rb.to_array().tolist()) == oracle


def test_naive_andnot_strategy():
    """naive_andnot (the difference chain: first \\ or(rest)) against the
    set oracle — the one FastAggregation strategy the equivalence fuzz
    catalog didn't name."""
    from roaringbitmap_tpu.parallel import fast_aggregation

    rng = np.random.default_rng(41)
    bms = [RoaringBitmap.from_values(
        rng.integers(0, 1 << 18, 3000).astype(np.uint32)) for _ in range(4)]
    got = fast_aggregation.naive_andnot(bms[0], *bms[1:])
    oracle = set(bms[0].to_array().tolist())
    for b in bms[1:]:
        oracle -= set(b.to_array().tolist())
    assert set(got.to_array().tolist()) == oracle


def test_aggregation_accepts_iterators():
    """FastAggregation.and/or/xor(Iterator<RoaringBitmap>) analog
    (TestFastAggregation.testAndWithIterator:85-105 etc.): generator and
    iterator inputs work on both the host strategy set and the device
    engine, with subclass inputs (the ExtendedRoaringBitmap case) too."""
    from roaringbitmap_tpu.core.fastrank import FastRankRoaringBitmap
    from roaringbitmap_tpu.parallel import fast_aggregation

    a, b = RoaringBitmap.bitmap_of(1, 2), RoaringBitmap.bitmap_of(2, 3)
    for mod in (fast_aggregation, aggregation):
        assert mod.and_(iter([a, b])).to_array().tolist() == [2]
        assert mod.or_(iter([a, b])).to_array().tolist() == [1, 2, 3]
        assert mod.xor(x for x in (a, b)).to_array().tolist() == [1, 3]
    ea = FastRankRoaringBitmap.from_values(np.array([1, 2], np.uint32))
    eb = FastRankRoaringBitmap.from_values(np.array([2, 3], np.uint32))
    assert fast_aggregation.and_(iter([ea, eb])).to_array().tolist() == [2]
