"""On-TPU parity lane: the engine-parity suite on the REAL backend.

Everything here runs compiled Mosaic kernels (no interpret mode) against a
real dataset slice + synthetic mixed-container inputs, asserting
bit-equality with the host tier — the lane VERDICT r2 item 5 asked for
(the CPU-pinned main suite never compiles a Mosaic kernel; reference
analog: the jmh correctness tests, jmh/src/test/.../realdata/*Test.java).

Run (one command, ~2 min incl. first compiles; the persistent compilation
cache in this module makes reruns fast):

    RB_TPU_TESTS=1 python -m pytest tests/test_on_tpu.py -q

Skipped entirely unless RB_TPU_TESTS=1 and the backend is a TPU.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("RB_TPU_TESTS") != "1",
    reason="on-TPU lane: set RB_TPU_TESTS=1 and run only this file")

jax = pytest.importorskip("jax")

if os.environ.get("RB_TPU_TESTS") == "1":
    jax.config.update("jax_compilation_cache_dir", "/tmp/rb_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    if jax.default_backend() != "tpu":  # pragma: no cover
        pytestmark = pytest.mark.skip(reason="no TPU backend available")

from roaringbitmap_tpu import RoaringBitmap  # noqa: E402
from roaringbitmap_tpu.parallel import aggregation, fast_aggregation  # noqa: E402
from roaringbitmap_tpu.utils import datasets  # noqa: E402


def _mixed(rng, n=10):
    out = []
    for i in range(n):
        vals = [rng.integers(0, 1 << 20, 800),
                (2 << 16) + rng.integers(0, 9000, 6000)]
        start = (3 << 16) + int(rng.integers(0, 500))
        vals.append(np.arange(start, start + 4000 + 50 * i))
        out.append(RoaringBitmap.from_values(
            np.concatenate(vals).astype(np.uint32)))
    return out


@pytest.fixture(scope="module")
def census():
    if not datasets.has_dataset("census1881"):
        pytest.skip("dataset not in mirror")
    return datasets.load_bitmaps("census1881")[:60]


@pytest.fixture(scope="module")
def mixed(rng):
    return _mixed(rng)


class TestWideOpsOnChip:
    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    @pytest.mark.parametrize("op", ["or", "xor", "and"])
    def test_wide_parity_census(self, census, engine, op):
        host = {"or": fast_aggregation.or_, "xor": fast_aggregation.xor,
                "and": fast_aggregation.and_}[op](*census)
        ds = aggregation.DeviceBitmapSet(census)
        assert ds.aggregate(op, engine=engine) == host

    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    def test_wide_parity_mixed_containers(self, mixed, engine):
        for op, fn in (("or", fast_aggregation.or_),
                       ("xor", fast_aggregation.xor)):
            got = {"or": aggregation.or_, "xor": aggregation.xor}[op](
                *mixed, engine=engine)
            assert got == fn(*mixed), op

    @pytest.mark.parametrize("layout", ["dense", "compact", "counts"])
    def test_chained_loop_compiled(self, census, layout):
        """The bench measurement loop itself, compiled on the chip.
        (compact runs the fused nibble reduce, counts the counts kernel —
        Mosaic-compiled SWAR, not the interpret path tests use on CPU)."""
        want = fast_aggregation.or_(*census).cardinality
        ds = aggregation.DeviceBitmapSet(census, layout=layout)
        reps = 2 if layout == "compact" else 5  # compact reps cost ~13 ms
        fn = ds.chained_wide_or(reps, engine="pallas")
        assert int(np.asarray(fn(ds.words))) == (reps * want) % 2**32

    @pytest.mark.parametrize("op", ["or", "xor"])
    def test_counts_layout_compiled(self, census, op):
        """counts-resident layout on the real chip: build (scatter +
        bit->nibble spread) and the counts kernel, both engines."""
        host = {"or": fast_aggregation.or_,
                "xor": fast_aggregation.xor}[op](*census)
        ds = aggregation.DeviceBitmapSet(census, layout="counts")
        assert ds.aggregate(op, engine="pallas") == host
        assert ds.aggregate(op, engine="xla") == host

    def test_byte_path_ingest(self, census):
        blobs = [b.serialize() for b in census]
        ds = aggregation.DeviceBitmapSet(blobs)
        assert ds.aggregate("or", engine="pallas") == \
            fast_aggregation.or_(*census)


class TestPairwiseOnChip:
    def test_pairwise_parity(self, census):
        pairs = list(zip(census[:-1], census[1:]))[:20]
        got = aggregation.pairwise("and", pairs)
        want = [a & b for a, b in pairs]
        assert got == want


class TestIndexTiersOnChip:
    def test_bsi_device_parity(self, census, rng):
        from roaringbitmap_tpu.bsi.device import DeviceBSI
        from roaringbitmap_tpu.bsi.slice_index import (
            Operation, RoaringBitmapSliceIndex)

        union = fast_aggregation.or_(*census)
        vals = union.to_array()[:50000].astype(np.uint64)
        bsi = RoaringBitmapSliceIndex.from_pairs(
            np.arange(vals.size, dtype=np.uint32), vals)
        dev = DeviceBSI(bsi)
        thr = int(np.median(vals))
        for op in (Operation.LT, Operation.GE, Operation.EQ):
            assert dev.compare(op, thr) == bsi.compare(op, thr, 0, None), op
        assert dev.sum() == bsi.sum()
        assert dev.top_k(500) == bsi.top_k(500)

    def test_rangebitmap_device_parity(self, census):
        from roaringbitmap_tpu.bsi.device import DeviceRangeBitmap
        from roaringbitmap_tpu.core.rangebitmap import RangeBitmap

        union = fast_aggregation.or_(*census)
        vals = union.to_array()[:50000].astype(np.uint64)
        app = RangeBitmap.appender(int(vals.max()))
        app.add_many(vals)
        rbm = app.build()
        dev = DeviceRangeBitmap(rbm)
        thr = int(np.median(vals))
        assert dev.lte(thr) == rbm.lte(thr)
        assert dev.between(thr // 2, thr * 2) == rbm.between(thr // 2, thr * 2)
        assert dev.lte_cardinality(thr) == rbm.lte_cardinality(thr)


class TestPlansAndNativeOnChip:
    """Round-3 additions on compiled Mosaic/XLA: device query plans,
    native byte ingest, membership probes."""

    def test_native_ingest_to_aggregate(self, census):
        from roaringbitmap_tpu import native

        if native.load() is None:
            pytest.skip("native engine unavailable")
        blobs = [b.serialize() for b in census]
        ds = aggregation.DeviceBitmapSet(blobs)
        assert ds.aggregate("or", engine="pallas") == \
            fast_aggregation.or_(*census)

    def test_query_plan_composes_on_chip(self, census):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        half = len(census) // 2
        ua = DeviceBitmap.aggregate(
            aggregation.DeviceBitmapSet(census[:half]), "or")
        ub = DeviceBitmap.aggregate(
            aggregation.DeviceBitmapSet(census[half:]), "or")
        plan = (ua | ub) - (ua & ub)
        want = fast_aggregation.or_(*census[:half]) ^ \
            fast_aggregation.or_(*census[half:])
        assert plan.materialize() == want
        assert plan.cardinality() == want.cardinality

    def test_contains_batch_on_chip(self, census):
        from roaringbitmap_tpu.parallel.aggregation import DeviceBitmap

        union = fast_aggregation.or_(*census)
        db = DeviceBitmap.from_host(union)
        arr = union.to_array()
        probes = np.concatenate(
            [arr[::997], np.arange(0, 1 << 22, 65521, dtype=np.uint32)])
        got = db.contains_batch(probes)
        want = np.array([union.contains(int(v)) for v in probes])
        assert np.array_equal(got, want)
