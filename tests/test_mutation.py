"""Mutable-tenant acceptance (ISSUE 12): versioned delta ingest +
materialized expression-result cache (roaringbitmap_tpu.mutation,
docs/MUTATION.md).

Pins:
- in-place dense-layout patches are bit-exact vs the host oracle across
  ops, with monotone version / per-source / per-row dirty stamps;
- escalation rules: structural adds, non-dense layouts, drift, and
  ``repack="always"`` all take the full-repack path (bit-exact);
  ``repack="never"`` raises typed;
- the property stream: N random interleaved ``apply_delta`` / query
  steps stay bit-exact vs a host ``RoaringBitmap`` oracle across
  layouts and engine rungs, including under ``ROARING_TPU_FAULTS``;
- ``warmup(rungs=("delta:N",))`` pre-compiles the patch program so the
  first in-band ``apply_delta`` is a compile-cache hit;
- the result cache: root-level serving + fills, flat/expression key
  sharing, plan-time subtree injection, EXACT leaf invalidation (bump
  one leaf -> only its dependent entries drop), byte-budget eviction
  with a balanced HBM ledger;
- the sharded engine's tenant-aligned row sharding (a tenant's delta
  patch never straddles a row-shard boundary) + journal-replay pool
  sync and repack re-place, bit-exact;
- serving-loop integration: cached pools serve, estimates drop, and the
  snapshot/admission paths see the cache's ledger bytes;
- CPU-proxy performance acceptance (slow lane): single-segment
  ``apply_delta`` >= 100x faster than a full re-pack; replayed
  repeated-expression trace >= 5x the recompute-path QPS.
"""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap, obs
from roaringbitmap_tpu.mutation import ResultCache
from roaringbitmap_tpu.mutation import delta as mut_delta
from roaringbitmap_tpu.mutation import result_cache as mut_cache
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.parallel import expr
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import BatchEngine, BatchQuery
from roaringbitmap_tpu.parallel.multiset import (BatchGroup,
                                                 MultiSetBatchEngine,
                                                 random_multiset_pool)
from roaringbitmap_tpu.runtime import faults, guard


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()


def mk_bitmaps(seed, n=5, uni=1 << 17, card=2500):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        rb = RoaringBitmap()
        rb.add_many(rng.choice(uni, card, replace=False).astype(np.uint32))
        out.append(rb)
    return out


def host_apply(hosts, adds, removes):
    out = list(hosts)
    for src in set(adds) | set(removes):
        bm = out[src].clone()
        if src in adds:
            a = RoaringBitmap()
            a.add_many(np.asarray(adds[src], np.uint32))
            bm = bm | a
        if src in removes:
            r = RoaringBitmap()
            r.add_many(np.asarray(removes[src], np.uint32))
            bm = bm - r
        out[src] = bm
    return out


def wide_refs(hosts):
    acc_or = hosts[0].clone()
    acc_xor = hosts[0].clone()
    acc_and = hosts[0].clone()
    for b in hosts[1:]:
        acc_or = acc_or | b
        acc_xor = acc_xor ^ b
        acc_and = acc_and & b
    return acc_or, acc_xor, acc_and


# --------------------------------------------------------- delta ingest

def test_patch_bit_exact_and_versioned():
    bms = mk_bitmaps(1)
    ds = DeviceBitmapSet(bms, layout="dense")
    hosts = list(bms)
    adds = {0: np.array([11, 12, 13], np.uint32),
            2: np.array([500, 777], np.uint32)}
    removes = {1: np.asarray(
        [v for v in (1, 2, 3) ], np.uint32)}
    rep = ds.apply_delta(adds=adds, removes=removes)
    assert rep["mode"] == "patch"
    assert rep["rows_patched"] >= 1
    hosts = host_apply(hosts, adds, removes)
    ro, rx, ra = wide_refs(hosts)
    assert ds.aggregate("or") == ro
    assert ds.aggregate("xor") == rx
    assert ds.aggregate("and") == ra
    # version lineage: monotone version, touched sources stamped, only
    # patched rows dirty
    assert ds.version == 1
    assert ds.structure_version == 0
    assert set(np.flatnonzero(ds.source_versions == 1)) == {0, 1, 2}
    assert int((ds.row_versions == 1).sum()) == rep["rows_patched"]
    # removes win over adds for a value in both
    rep2 = ds.apply_delta(adds={0: [99]}, removes={0: [99]})
    assert rep2["mode"] == "patch"
    hosts = host_apply(hosts, {0: [99]}, {0: [99]})
    assert ds.aggregate("or") == wide_refs(hosts)[0]
    assert 99 not in ds.host_bitmaps()[0]
    # removals aimed entirely at containers the source doesn't hold are
    # a semantic NO-OP: no patch, no version bump, no invalidation
    v0 = ds.version
    rep3 = ds.apply_delta(removes={0: [(0x7F7F << 16) + 1]})
    assert rep3["mode"] == "noop" and rep3["rows_patched"] == 0
    assert ds.version == v0


def test_structural_add_escalates_to_repack():
    bms = mk_bitmaps(2)
    ds = DeviceBitmapSet(bms, layout="dense")
    eng = BatchEngine(ds, result_cache=None)
    queries = [BatchQuery("or", (0, 1, 2)), BatchQuery("xor", (1, 3))]
    pre = [r.cardinality for r in eng.execute(queries)]
    assert pre[0] == (bms[0] | bms[1] | bms[2]).cardinality
    new_key_value = np.uint32((0xBEEF << 16) + 7)
    rep = ds.apply_delta(adds={1: [int(new_key_value)]})
    assert rep["mode"] == "repack"
    assert rep["repack_reason"] == "structural"
    assert ds.structure_version == 1
    hosts = host_apply(bms, {1: [int(new_key_value)]}, {})
    assert ds.aggregate("or") == wide_refs(hosts)[0]
    # a second value in the SAME (now resident) key patches in place
    rep2 = ds.apply_delta(adds={1: [int(new_key_value) + 1]})
    assert rep2["mode"] == "patch"
    # a repack that GROWS the packed image (many new keys, past the
    # round_blocks padding) must retire the engine's compiled programs:
    # a bucket-identical plan against the re-laid image would otherwise
    # hit an executable compiled for the old operand shape
    many = {0: [(0xA000 + k) << 16 for k in range(12)]}
    rep3 = ds.apply_delta(adds=many)
    assert rep3["mode"] == "repack"
    hosts = host_apply(hosts, {1: [int(new_key_value) + 1]}, {})
    hosts = host_apply(hosts, many, {})
    post = [r.cardinality for r in eng.execute(queries)]
    assert post[0] == (hosts[0] | hosts[1] | hosts[2]).cardinality
    assert post[1] == (hosts[1] ^ hosts[3]).cardinality


def test_layout_and_drift_escalation_and_never():
    bms = mk_bitmaps(3)
    ds = DeviceBitmapSet(bms, layout="counts")
    rep = ds.apply_delta(adds={0: [5]})
    assert rep["mode"] == "repack" and rep["repack_reason"] == "layout"
    hosts = host_apply(bms, {0: [5]}, {})
    assert ds.aggregate("or") == wide_refs(hosts)[0]

    ds2 = DeviceBitmapSet(mk_bitmaps(4), layout="dense")
    # a tiny drift limit fires the heuristic on the first delta
    rep2 = ds2.apply_delta(adds={0: [21]}, drift_limit=0)
    assert rep2["mode"] == "repack" and rep2["repack_reason"] == "drift"
    assert rep2["drift"]["fired"]

    ds3 = DeviceBitmapSet(mk_bitmaps(5), layout="dense")
    with pytest.raises(ValueError, match="repack"):
        ds3.apply_delta(adds={0: [(0x7777 << 16) + 1]}, repack="never")
    # the failed call mutated nothing
    assert ds3.version == 0


@pytest.mark.parametrize("layout", ["dense", "counts"])
@pytest.mark.parametrize("fault_spec", [None, "transient@batch_engine=0.4:1337"])
def test_property_interleaved_delta_query_stream(layout, fault_spec):
    """N random interleaved apply_delta/query steps stay bit-exact vs
    the host oracle — across layouts and (via the guard) engine rungs,
    including under fault injection."""
    rng = np.random.default_rng(0xD17A)
    bms = mk_bitmaps(6, n=4, uni=1 << 16, card=800)
    ds = DeviceBitmapSet(bms, layout=layout)
    eng = BatchEngine(ds, result_cache=ResultCache(4 << 20))
    hosts = list(bms)
    ctx = faults.inject(fault_spec) if fault_spec else None
    if ctx:
        ctx.__enter__()
    try:
        for step in range(10):
            if step % 2 == 0:
                src = int(rng.integers(4))
                universe = 1 << 16 if rng.random() < 0.8 else 1 << 18
                adds = {src: rng.integers(0, universe, 5).astype(np.uint32)}
                rem_src = int(rng.integers(4))
                pool = np.asarray(hosts[rem_src].to_array()
                                  if hasattr(hosts[rem_src], "to_array")
                                  else [], np.uint32)
                removes = {}
                if pool.size:
                    removes = {rem_src: rng.choice(pool, 3)}
                ds.apply_delta(adds=adds, removes=removes)
                hosts = host_apply(hosts, adds, removes)
            queries = [
                BatchQuery("or", (0, 1, 2)),
                BatchQuery("xor", (1, 3), form="bitmap"),
                BatchQuery("andnot", (2, 0)),
                expr.ExprQuery(expr.and_(expr.or_(0, 1),
                                         expr.not_(3))),
            ]
            got = eng.execute(queries)
            exp_or = hosts[0] | hosts[1] | hosts[2]
            exp_xor = hosts[1] ^ hosts[3]
            exp_andnot = hosts[2] - hosts[0]
            exp_e = expr.evaluate_host(
                expr.and_(expr.or_(0, 1), expr.not_(3)), hosts)
            assert got[0].cardinality == exp_or.cardinality, step
            assert got[1].bitmap == exp_xor, step
            assert got[2].cardinality == exp_andnot.cardinality, step
            assert got[3].cardinality == exp_e.cardinality, step
    finally:
        if ctx:
            ctx.__exit__(None, None, None)


def _compile_misses(site="mutation"):
    return int(sum(
        inst.count for name, labels, inst
        in obs_metrics.REGISTRY.instruments()
        if name == "rb_compile_seconds" and labels.get("site") == site
        and labels.get("cache") == "miss"))


def test_warmup_delta_rung_cache_hit():
    bms = mk_bitmaps(7)
    ds = DeviceBitmapSet(bms, layout="dense")
    eng = BatchEngine(ds, result_cache=None)
    rep = eng.warmup(rungs=("delta:4",))
    assert any(p.get("delta_rung") == 4 for p in rep["programs"])
    miss0 = _compile_misses()
    # <= 4 patch rows pad to the warmed pow2 rung: no in-band compile
    out = ds.apply_delta(adds={0: [7, 9], 1: [70000]})
    assert out["mode"] == "patch"
    assert _compile_misses() == miss0
    assert obs_metrics.REGISTRY.counter(
        "rb_delta_rows_patched_total").value >= 2


# --------------------------------------------------------- result cache

def test_result_cache_serves_flat_and_expr():
    bms = mk_bitmaps(8)
    rc = ResultCache(8 << 20)
    eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                      result_cache=rc)
    q = [BatchQuery("or", (0, 1, 2)),
         BatchQuery("xor", (1, 3), form="bitmap")]
    r1 = eng.execute(q)
    assert rc.stats()["misses"] == 2 and rc.stats()["entries"] == 2
    r2 = eng.execute(q)
    assert rc.stats()["hits"] == 2
    assert [r.cardinality for r in r1] == [r.cardinality for r in r2]
    assert r2[1].bitmap == r1[1].bitmap
    # an ExprQuery with the same canonical DAG shares the flat entry
    r3 = eng.execute([expr.ExprQuery(expr.or_(2, 0, 1))])
    assert rc.stats()["hits"] == 3
    assert r3[0].cardinality == r1[0].cardinality
    # bitmap-form query cannot be served from a cardinality-only entry
    r4 = eng.execute([BatchQuery("or", (0, 1, 2), form="bitmap")])
    ref = bms[0] | bms[1] | bms[2]
    assert r4[0].bitmap == ref
    # ... but its fill upgrades the entry: cardinality form now hits too
    assert eng.execute(q)[0].cardinality == ref.cardinality


def test_subtree_injection_prunes_reduce():
    bms = mk_bitmaps(9, n=6)
    rc = ResultCache(8 << 20)
    eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                      result_cache=rc)
    eng.execute([expr.ExprQuery(expr.or_(0, 4), form="bitmap")])
    e = expr.and_(expr.or_(0, 4), expr.not_(5))
    got = eng.execute([expr.ExprQuery(e)])
    host = expr.evaluate_host(e, bms)
    assert got[0].cardinality == host.cardinality
    plan = eng.plan((expr.ExprQuery(e),))
    assert plan.exprs[0].n_cached >= 1
    # the injected plan executes bit-exactly on a later (cached) serve
    assert eng.execute([expr.ExprQuery(e)])[0].cardinality \
        == host.cardinality


def _cache_ledger_base():
    """The ledger's result_cache bytes from OTHER live caches: the
    ledger is process-global, and caches from sibling tests may not be
    collected yet — assertions below are deltas against this."""
    import gc

    gc.collect()
    return obs_memory.LEDGER.resident_bytes("result_cache")


def test_exact_invalidation_and_ledger_balance():
    base = _cache_ledger_base()
    bms_a, bms_b = mk_bitmaps(10), mk_bitmaps(11)
    rc = ResultCache(8 << 20)
    eng_a = BatchEngine(DeviceBitmapSet(bms_a, layout="dense"),
                        result_cache=rc)
    eng_b = BatchEngine(DeviceBitmapSet(bms_b, layout="dense"),
                        result_cache=rc)
    eng_a.execute([BatchQuery("or", (0, 1)),
                   BatchQuery("xor", (2, 3), form="bitmap")])
    eng_b.execute([BatchQuery("or", (0, 1), form="bitmap")])
    assert rc.stats()["entries"] == 3
    assert obs_memory.LEDGER.resident_bytes("result_cache") \
        == base + rc.nbytes
    # bump ONE leaf: set A source 0 — exactly its dependents drop
    eng_a._ds.apply_delta(adds={0: [123]})
    s = rc.stats()
    assert s["entries"] == 2 and s["invalidations"] == 1
    # set B's entry and set A's untouched (2,3) entry still hit
    assert rc.stats()["hits"] == 0
    eng_b.execute([BatchQuery("or", (0, 1), form="bitmap")])
    eng_a.execute([BatchQuery("xor", (2, 3), form="bitmap")])
    assert rc.stats()["hits"] == 2
    # the dropped entry re-fills with the POST-delta result
    got = eng_a.execute([BatchQuery("or", (0, 1))])
    ref = host_apply(bms_a, {0: [123]}, {})[0] | bms_a[1]
    assert got[0].cardinality == ref.cardinality
    # ledger balanced after the drop + re-fill
    assert obs_memory.LEDGER.resident_bytes("result_cache") \
        == base + rc.nbytes


def test_byte_budget_eviction_balances_ledger():
    bms = mk_bitmaps(12, n=8)
    # budget fits ~2 materialized bitmap entries of this shape
    probe_rc = ResultCache(1 << 30)
    probe = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                        result_cache=probe_rc)
    probe.execute([BatchQuery("or", (0, 1), form="bitmap")])
    one_entry = probe_rc.nbytes
    probe_rc.clear()
    base = _cache_ledger_base()
    rc = ResultCache(int(one_entry * 2.5))
    eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                      result_cache=rc)
    for i in range(6):
        eng.execute([BatchQuery("or", (i % 7, (i + 1) % 7),
                                form="bitmap")])
    s = rc.stats()
    assert s["evictions"] >= 1
    assert rc.nbytes <= rc.max_bytes
    assert obs_memory.LEDGER.resident_bytes("result_cache") \
        == base + rc.nbytes


def test_multiset_cache_and_tenant_invalidation():
    tenants = [mk_bitmaps(20 + i, n=4, uni=1 << 16, card=900)
               for i in range(3)]
    rc = ResultCache(16 << 20)
    ms = MultiSetBatchEngine(
        [DeviceBitmapSet(b, layout="dense") for b in tenants],
        result_cache=rc)
    pool = random_multiset_pool([4] * 3, 12, seed=5)
    c1 = [[r.cardinality for r in rows] for rows in ms.execute(pool)]
    assert [[r.cardinality for r in rows]
            for rows in ms.execute(pool)] == c1
    assert rc.stats()["hits"] >= len(c1)
    assert ms.count_cache_hits(pool) > 0
    inval0 = rc.stats()["invalidations"]
    ms._engines[1]._ds.apply_delta(adds={0: [3]})
    assert rc.stats()["invalidations"] > inval0
    # post-delta pool is bit-exact vs per-set sequential
    got = [[r.cardinality for r in rows] for rows in ms.execute(pool)]
    for gi, g in enumerate(pool):
        e = ms._engines[g.set_id]
        assert got[gi] == [e._sequential_one(q).cardinality
                           for q in g.queries]
    # an image-growing structural repack must retire the pooled
    # programs too (the operand-shape half of the plan/program split)
    ms._engines[0]._ds.apply_delta(
        adds={1: [(0xB000 + k) << 16 for k in range(12)]})
    got2 = [[r.cardinality for r in rows] for rows in ms.execute(pool)]
    for gi, g in enumerate(pool):
        e = ms._engines[g.set_id]
        assert got2[gi] == [e._sequential_one(q).cardinality
                            for q in g.queries]


# ------------------------------------------------------- sharded tenant

def test_sharded_tenant_alignment_and_patch_sync():
    import jax
    from jax.sharding import Mesh

    from roaringbitmap_tpu.parallel.sharded_engine import \
        ShardedBatchEngine

    tenants = [mk_bitmaps(30 + i, n=4, uni=1 << 16, card=900)
               for i in range(3)]
    ms = MultiSetBatchEngine(
        [DeviceBitmapSet(b, layout="dense") for b in tenants],
        result_cache=None)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("rows", "data"))
    sh = ShardedBatchEngine(ms._engines, mesh=mesh, placement="sharded",
                            result_cache=None)
    # residency pin: every tenant not larger than a row shard lives in
    # exactly ONE shard (PR 7's named debt — one-shard delta writes)
    u = sh.pool_rows // sh.mesh_shape[0]
    for sid in range(3):
        b, n = int(sh._base[sid]), sh._rows[sid]
        if n and n <= u:
            assert b // u == (b + n - 1) // u, (sid, b, n, u)
    pool = random_multiset_pool([4] * 3, 12, seed=6)

    def refs():
        return [[ms._engines[g.set_id]._sequential_one(q).cardinality
                 for q in g.queries] for g in pool]

    assert [[r.cardinality for r in rows]
            for rows in sh.execute(pool)] == refs()
    patches0 = obs_metrics.REGISTRY.counter(
        "rb_sharded_pool_patches_total", site="sharded_engine",
        mesh=sh._mesh_label).value
    ms._engines[2]._ds.apply_delta(adds={1: [9, 10]},
                                   removes={0: [1]})
    assert [[r.cardinality for r in rows]
            for rows in sh.execute(pool)] == refs()
    assert obs_metrics.REGISTRY.counter(
        "rb_sharded_pool_patches_total", site="sharded_engine",
        mesh=sh._mesh_label).value > patches0
    # structural repack re-places the pool wholesale, still bit-exact,
    # ledger swapped (no double count)
    ms._engines[2]._ds.apply_delta(adds={1: [(0xCAFE << 16) + 3]})
    assert [[r.cardinality for r in rows]
            for rows in sh.execute(pool)] == refs()
    assert obs_memory.LEDGER.resident_bytes("sharded_pool") \
        == sh.pool_rows * 8192 * sh.mesh_shape[1]


# ------------------------------------------------------------- serving

def test_serving_loop_serves_from_cache():
    from roaringbitmap_tpu.serving import (ServingLoop, ServingPolicy,
                                           ServingRequest)

    tenants = [mk_bitmaps(40 + i, n=4, uni=1 << 16, card=700)
               for i in range(2)]
    rc = ResultCache(16 << 20)
    ms = MultiSetBatchEngine(
        [DeviceBitmapSet(b, layout="dense") for b in tenants],
        result_cache=rc)
    loop = ServingLoop(ms, ServingPolicy(
        pool_target=4, default_deadline_ms=60_000.0,
        guard=guard.GuardPolicy(backoff_base=0.0, sleep=lambda s: None)))
    q = BatchQuery("or", (0, 1, 2))
    done = []
    for round_i in range(3):
        for i in range(4):
            done.append(loop.submit(ServingRequest(
                i % 2, q, tenant=f"t{i % 2}")))
        loop.drain()
    assert all(t.status == "done" for t in done)
    assert rc.stats()["hits"] > 0
    ref0 = ms._engines[0]._sequential_one(q).cardinality
    ref1 = ms._engines[1]._sequential_one(q).cardinality
    for t in done:
        assert t.result.cardinality == (ref0 if t.request.set_id == 0
                                        else ref1)
    snap = loop.snapshot()
    assert snap["result_cache"]["hits"] == rc.stats()["hits"]
    # a fully-cached pool's execute-time estimate floors out: the
    # predictor scales by the would-hit fraction (count_cache_hits)
    assert loop._estimate_seconds([done[0]]) <= 2e-4


# ------------------------------------------------------------ obs/trace

def test_mutation_spans_and_cache_events(tmp_path):
    trace_path = tmp_path / "trace.jsonl"
    obs.enable(str(trace_path))
    try:
        bms = mk_bitmaps(50)
        rc = ResultCache(8 << 20)
        eng = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                          result_cache=rc)
        eng.execute([BatchQuery("or", (0, 1))])
        eng.execute([BatchQuery("or", (0, 1))])
        eng._ds.apply_delta(adds={0: [2]})
        eng._ds.apply_delta(adds={1: [(0xD00D << 16) + 1]})
    finally:
        obs.disable()
    import json

    spans = [json.loads(l) for l in open(trace_path)]
    deltas = [s for s in spans if s["name"] == "mutation.delta"]
    assert {s["tags"]["mode"] for s in deltas} == {"patch", "repack"}
    for s in deltas:
        assert isinstance(s["tags"]["version"], int)
    cache_evs = [ev for s in spans for ev in s["events"]
                 if ev.get("name") == "expr.cache"]
    assert any(ev["hits"] >= 1 for ev in cache_evs)
    assert all(isinstance(ev["hits"], int)
               and isinstance(ev["misses"], int) for ev in cache_evs)
    # the dump validates against the trace schema checker
    import sys
    sys.path.insert(0, "tools")
    import check_trace

    assert check_trace.validate(str(trace_path)) == []


# ------------------------------------------------------ slow acceptance

@pytest.mark.slow
def test_delta_vs_repack_100x():
    """Acceptance: single-segment apply_delta >= 100x faster than a full
    re-pack on the CPU proxy, bit-exact vs the host oracle.  Same shape
    as the bench mutation lane's delta cell (repack is ~8M values of
    honest pack work; the warmed patch is a flat ~0.4 ms)."""
    import time

    from roaringbitmap_tpu.utils import datasets

    bms = datasets.synthetic_bitmaps(64, seed=90, universe=1 << 25,
                                     density=0.03)
    ds = DeviceBitmapSet(bms, layout="dense")
    ds.warmup_delta(1)
    ds.apply_delta(adds={0: [1]})        # warm the whole patch path
    # min-of-reps, the bench methodology: a single draw under CI load
    # is not the marginal being claimed
    delta_s = float("inf")
    for i in range(10):
        t0 = time.perf_counter()
        ds.apply_delta(adds={3: [i + 2]})
        delta_s = min(delta_s, time.perf_counter() - t0)
    hosts = ds.host_bitmaps()
    t0 = time.perf_counter()
    ds2 = DeviceBitmapSet(hosts, layout="dense")
    repack_s = time.perf_counter() - t0
    ref = wide_refs(hosts)[0]
    assert ds.aggregate("or") == ref
    assert ds2.aggregate("or") == ref
    ratio = repack_s / delta_s
    assert ratio >= 100, (delta_s, repack_s, ratio)


@pytest.mark.slow
def test_cache_vs_recompute_5x_qps():
    """Acceptance: a replayed repeated-expression trace serves >= 5x the
    recompute-path QPS from the result cache."""
    import time

    bms = mk_bitmaps(61, n=8, uni=1 << 20, card=20000)
    trace = expr.random_expr_pool(8, 24, depth=3, seed=3)

    def replay(engine, rounds=6):
        engine.execute(trace)            # warm compiles + (maybe) fill
        t0 = time.perf_counter()
        for _ in range(rounds):
            engine.execute(trace)
        wall = time.perf_counter() - t0
        return rounds * len(trace) / wall

    cold = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                       result_cache=None)
    qps_recompute = replay(cold)
    warm = BatchEngine(DeviceBitmapSet(bms, layout="dense"),
                       result_cache=ResultCache(64 << 20))
    qps_cached = replay(warm)
    # bit-exactness of the cached replay
    ref = [r.cardinality for r in cold.execute(trace)]
    got = [r.cardinality for r in warm.execute(trace)]
    assert got == ref
    assert qps_cached >= 5 * qps_recompute, (qps_cached, qps_recompute)
