"""Observability subsystem (roaringbitmap_tpu/obs) acceptance + contracts.

ISSUE 3 acceptance pins:
- a fault-injected demoted query (ROARING_TPU_FAULTS lowering fault)
  produces a JSONL trace whose spans show the pallas->xla demotion with
  the classified error tag;
- obs.snapshot() histograms record per-engine execute latencies for a
  Q=64 batch;
- reset()/snapshot() symmetry for the metrics registry;
- dispatch_stats() / cache_stats() keep their exact legacy dict shapes
  (docs/ROBUSTNESS.md + operator tooling reference them);
- disabled-mode span() is the shared no-op (the <2% overhead pin rides
  on it; CI measures the fraction in tools/check_obs_overhead.py).
"""

import importlib.util
import json
import logging
import os

import pytest

from roaringbitmap_tpu import obs
from roaringbitmap_tpu.obs import metrics as obs_metrics
from roaringbitmap_tpu.parallel import aggregation
from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                     random_query_pool)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.utils import datasets

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts from a fresh registry / disabled tracer and
    leaves no global state behind."""
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()


@pytest.fixture(scope="module")
def engine():
    bms = datasets.synthetic_bitmaps(16, seed=11, universe=1 << 18,
                                     density=0.01)
    return BatchEngine.from_bitmaps(bms)


@pytest.fixture(scope="module")
def pool():
    return random_query_pool(16, 64)


def _read_trace(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


# ------------------------------------------------------------ acceptance

def test_demoted_query_trace_shows_demotion_chain(tmp_path, monkeypatch,
                                                  engine, pool):
    """ROARING_TPU_FAULTS lowering fault on the pallas rung -> the JSONL
    trace records the pallas->xla demotion with the classified error."""
    trace_path = tmp_path / "trace.jsonl"
    monkeypatch.setenv("ROARING_TPU_TRACE", str(trace_path))
    monkeypatch.setenv("ROARING_TPU_FAULTS", "lowering@pallas=1.0:7")
    obs.refresh_from_env()
    try:
        got = [r.cardinality for r in engine.execute(pool[:8],
                                                     engine="pallas")]
    finally:
        obs.disable()
    # degraded, still bit-exact
    want = [r.cardinality for r in engine._execute_sequential(pool[:8])]
    assert got == want

    spans = _read_trace(trace_path)
    by_id = {s["span_id"]: s for s in spans}
    dispatches = [s for s in spans if s["name"] == "guard.dispatch"]
    assert dispatches, [s["name"] for s in spans]
    demotes = [ev for s in dispatches for ev in s["events"]
               if ev["name"] == "demote"]
    assert any(ev["engine_from"] == "pallas" and ev["engine_to"] == "xla"
               and ev["error_class"] == "EngineLoweringError"
               and ev["site"] == "batch_engine" for ev in demotes), demotes
    # the dispatch span records where the query actually landed
    d = dispatches[-1]
    assert d["tags"]["rung_used"] == "xla"
    assert d["tags"]["demotion_chain"] == ["pallas->xla"]
    # nesting: guard.dispatch rides under batch.execute
    assert by_id[d["parent_id"]]["name"] == "batch.execute"


def test_snapshot_histograms_record_per_engine_latency(engine, pool):
    """Q=64 batch -> obs.snapshot() carries per-(site, engine) execute
    latency histograms; a second engine gets its own row."""
    engine.execute(pool)                       # Q=64, auto -> xla on CPU
    engine.execute(pool[:8], engine="xla-vmap")
    rows = obs.snapshot()["histograms"]["rb_execute_latency_seconds"]
    by_labels = {tuple(sorted(r["labels"].items())): r for r in rows}
    xla = by_labels[(("engine", "xla"), ("site", "batch_engine"))]
    vmap = by_labels[(("engine", "xla-vmap"), ("site", "batch_engine"))]
    assert xla["count"] >= 1 and vmap["count"] >= 1
    assert xla["sum"] > 0
    # cumulative buckets end at +Inf == count
    assert xla["buckets"]["+Inf"] == xla["count"]


def test_sequential_landing_records_sequential_histogram(engine, pool):
    with faults.inject("lowering=1.0:0xBEEF"):
        engine.execute(pool[:4])
    rows = obs.snapshot()["histograms"]["rb_execute_latency_seconds"]
    assert any(r["labels"] == {"engine": "sequential",
                               "site": "batch_engine"} and r["count"] >= 1
               for r in rows), rows


# ------------------------------------------------- registry contracts

def test_reset_snapshot_symmetry():
    """reset() returns the registry to its fresh state: a snapshot after
    reset equals one taken right after a previous reset.  (Gauges backed
    by collectors — rb_cache_size over live caches — are recomputed at
    every snapshot, so they appear identically on both sides.)"""
    baseline = obs.snapshot()
    assert baseline["counters"] == {} and baseline["histograms"] == {}
    assert baseline["trace"] == {"enabled": False, "path": None}
    obs.counter("rb_t_total", site="x").inc()
    obs.gauge("rb_g", site="x").set(3)
    obs.histogram("rb_h_seconds", site="x").observe(0.5)
    assert obs.snapshot() != baseline
    obs.reset()
    assert obs.snapshot() == baseline


def test_registry_kind_conflict_raises():
    obs.counter("rb_conflict_total", a="b")
    with pytest.raises(TypeError):
        obs.gauge("rb_conflict_total", a="b")


def test_histogram_bucket_conflict_raises():
    obs.histogram("rb_bconf_seconds", buckets=(0.1, 1.0), site="s")
    with pytest.raises(ValueError):
        obs.histogram("rb_bconf_seconds", buckets=(0.5,), site="s")
    # same spec: fine
    obs.histogram("rb_bconf_seconds", buckets=(1.0, 0.1), site="s")


def test_mixed_type_label_values_stringify():
    obs.counter("rb_mixed_total", q=64).inc()
    obs.counter("rb_mixed_total", q="auto").inc()
    rows = obs.snapshot()["counters"]["rb_mixed_total"]
    assert sorted(r["labels"]["q"] for r in rows) == ["64", "auto"]
    assert "rb_mixed_total" in obs.render_prometheus()


def test_snapshot_delta_counters_and_histograms():
    before = obs.snapshot()
    obs.counter("rb_d_total").inc(2)
    h = obs.histogram("rb_d_seconds")
    h.observe(0.001)
    h.observe(0.2)
    delta = obs.snapshot_delta(before, obs.snapshot())
    assert delta["counters"]["rb_d_total"][0]["value"] == 2
    hrow = delta["histograms"]["rb_d_seconds"][0]
    assert hrow["count"] == 2
    assert abs(hrow["sum"] - 0.201) < 1e-9
    # second delta over an unchanged registry is empty
    snap = obs.snapshot()
    assert obs.snapshot_delta(snap, snap) == {
        "counters": {}, "gauges": {}, "histograms": {}}


def test_legacy_dispatch_stats_shape(engine, pool):
    """docs/ROBUSTNESS.md + operator tooling pin these exact dict shapes;
    the registry is a superset view, never a replacement."""
    with faults.inject("lowering@pallas=1.0:3"):
        engine.execute(pool[:4], engine="pallas")
    row = guard.dispatch_stats("batch_engine")
    assert set(row) == {"retries", "demotions", "sequential"}
    assert all(isinstance(v, int) for v in row.values())
    assert row["demotions"] == 1
    full = guard.dispatch_stats()
    assert set(full["batch_engine"]) == {"retries", "demotions",
                                         "sequential"}


def test_legacy_cache_stats_shape(engine, pool):
    engine.execute(pool[:8])
    cs = engine.cache_stats()
    assert set(cs) == {"plans", "programs", "splits"}
    for key in ("plans", "programs"):
        assert set(cs[key]) == {"size", "maxsize", "hits", "misses",
                                "evictions"}
    assert isinstance(cs["splits"], int)


def test_dispatch_and_cache_events_absorbed_in_registry():
    # fresh engine: its cache misses/puts must land AFTER the registry
    # reset for the counter/gauge assertions below
    bms = datasets.synthetic_bitmaps(8, seed=13, universe=1 << 16,
                                     density=0.02)
    engine = BatchEngine.from_bitmaps(bms)
    qs = random_query_pool(8, 4)
    with faults.inject("lowering@pallas=1.0:3"):
        engine.execute(qs, engine="pallas")
    engine.execute(qs)                          # plan-cache hit this time
    snap = obs.snapshot()
    ev = {(r["labels"]["site"], r["labels"]["event"]): r["value"]
          for r in snap["counters"]["rb_dispatch_events_total"]}
    assert ev[("batch_engine", "demotions")] >= 1
    cache = {(r["labels"]["cache"], r["labels"]["event"]): r["value"]
             for r in snap["counters"]["rb_cache_events_total"]}
    assert cache[("batch_plans", "hit")] >= 1
    sizes = {r["labels"]["cache"]: r["value"]
             for r in snap["gauges"]["rb_cache_size"]}
    assert sizes["batch_plans"] >= 1


# ------------------------------------------------- structured logging

def test_guard_demotion_log_carries_structured_fields(caplog, engine,
                                                      pool):
    with caplog.at_level(logging.WARNING, "roaringbitmap_tpu.runtime"):
        with faults.inject("lowering@pallas=1.0:5"):
            engine.execute(pool[:2], engine="pallas")
    recs = [r for r in caplog.records
            if getattr(r, "rb_event", None) == "demote"]
    assert recs, [r.message for r in caplog.records]
    r = recs[0]
    assert r.rb_site == "batch_engine"
    assert r.rb_engine_from == "pallas"
    assert r.rb_engine_to == "xla"
    assert r.rb_error_class == "EngineLoweringError"


# ------------------------------------------------------- tracer details

def test_disabled_span_is_shared_noop():
    assert not obs.enabled()
    sp = obs.span("anything", q=64, engine="xla")
    assert sp is obs.trace._NOOP
    # the full no-op surface instrumentation sites touch
    with sp as s:
        assert s.tag(a=1) is s
        assert s.event("x", y=2) is s
        assert s.sync("payload") == "payload"
        assert s.span_id is None


def test_bad_trace_path_fails_at_enable_not_in_queries(tmp_path,
                                                       monkeypatch):
    """A misconfigured trace path must surface at configuration time (or
    as one warning via the env route), never out of a query's span exit
    — the robustness ladder must not see tracer OSErrors."""
    bad = str(tmp_path / "no" / "such" / "dir" / "t.jsonl")
    with pytest.raises(OSError):
        obs.enable(bad)
    assert not obs.enabled()
    # env route: import-time/refresh survives with a warning, no raise
    monkeypatch.setenv("ROARING_TPU_TRACE", bad)
    obs.refresh_from_env()
    assert not obs.enabled()
    with obs.span("q"):        # still the no-op fast path
        pass


def test_span_nesting_and_error_status(tmp_path):
    obs.enable(str(tmp_path / "t.jsonl"))
    with pytest.raises(ValueError):
        with obs.span("outer", q=1):
            with obs.span("inner"):
                raise ValueError("boom")
    obs.disable()
    inner, outer = _read_trace(tmp_path / "t.jsonl")
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["parent_id"] == outer["span_id"]
    assert inner["trace_id"] == outer["span_id"]
    assert inner["tags"]["status"] == "error"
    assert inner["tags"]["error_class"] == "ValueError"
    assert outer["dur_ms"] >= inner["dur_ms"] >= 0


def test_aggregation_wide_span_and_histogram(tmp_path):
    bms = datasets.synthetic_bitmaps(6, seed=5, universe=1 << 16,
                                     density=0.02)
    obs.enable(str(tmp_path / "agg.jsonl"))
    try:
        aggregation.or_(*bms)
    finally:
        obs.disable()
    spans = _read_trace(tmp_path / "agg.jsonl")
    wide = [s for s in spans if s["name"] == "aggregation.wide"]
    assert wide and wide[0]["tags"]["op"] == "or"
    assert wide[0]["tags"]["rung_used"] in ("pallas", "xla")
    rows = obs.snapshot()["histograms"]["rb_execute_latency_seconds"]
    assert any(r["labels"]["site"] == "aggregation" for r in rows)


# --------------------------------------------------- export + validator

def test_prometheus_render():
    obs.counter("rb_p_total", site="s").inc(3)
    obs.histogram("rb_p_seconds", buckets=(0.1, 1.0), site="s").observe(0.5)
    text = obs.render_prometheus()
    assert '# TYPE rb_p_total counter' in text
    assert 'rb_p_total{site="s"} 3' in text
    assert '# TYPE rb_p_seconds histogram' in text
    assert 'rb_p_seconds_bucket{le="0.1",site="s"} 0' in text
    assert 'rb_p_seconds_bucket{le="1.0",site="s"} 1' in text
    assert 'rb_p_seconds_bucket{le="+Inf",site="s"} 1' in text
    assert 'rb_p_seconds_sum{site="s"} 0.5' in text
    assert 'rb_p_seconds_count{site="s"} 1' in text


def _load_check_trace():
    spec = importlib.util.spec_from_file_location(
        "check_trace", os.path.join(REPO, "tools", "check_trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_trace_validates_real_dump(tmp_path, engine, pool):
    path = tmp_path / "dump.jsonl"
    obs.enable(str(path))
    try:
        with faults.inject("lowering@pallas=1.0:7"):
            engine.execute(pool[:4], engine="pallas")
    finally:
        obs.disable()
    ct = _load_check_trace()
    assert ct.validate(str(path), workload_semantics=True) == []


def test_check_trace_rejects_malformed(tmp_path):
    ct = _load_check_trace()
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"name": "", "span_id": "a", "pid": 1, '
                   '"t_start": 0, "dur_ms": -1, "tags": {}, '
                   '"events": [{}], "parent_id": "ghost"}\n'
                   'not json\n')
    errs = ct.validate(str(bad), strict_refs=True)
    assert any("empty span name" in e for e in errs)
    assert any("negative dur_ms" in e for e in errs)
    assert any("event 0 malformed" in e for e in errs)
    assert any("not valid JSON" in e for e in errs)
    assert any("ghost" in e for e in errs)
    assert ct.validate(str(tmp_path / "missing.jsonl"))


def test_check_trace_tolerates_crash_dump(tmp_path):
    """A dump whose enclosing spans never closed (crash / live capture)
    must pass plain validation: dangling parent refs are only violations
    in strict-refs (complete-dump) mode."""
    ct = _load_check_trace()
    crash = tmp_path / "crash.jsonl"
    crash.write_text(
        '{"name": "guard.dispatch", "span_id": "x-2", '
        '"parent_id": "x-1", "trace_id": "x-1", "pid": 1, '
        '"t_start": 0, "dur_ms": 1.0, "tags": {}, "events": []}\n')
    assert ct.validate(str(crash)) == []
    assert any("x-1" in e for e in ct.validate(str(crash),
                                               strict_refs=True))


def test_cache_size_gauge_sums_across_instances():
    """rb_cache_size is computed at scrape time over the live caches
    sharing a name — two instances report their SUM, one instance's
    clear() never erases the other's entries, and obs.reset() cannot
    desync it (the collector recomputes on the next snapshot)."""
    from roaringbitmap_tpu.runtime.cache import LRUCache

    def scraped():
        rows = obs.snapshot()["gauges"].get("rb_cache_size", [])
        return {r["labels"]["cache"]: r["value"] for r in rows}

    a = LRUCache(4, name="gauge_probe")
    b = LRUCache(2, name="gauge_probe")
    for i in range(3):
        a.put(i, i)
    for i in range(3):          # cap 2: one eviction
        b.put(i, i)
    assert scraped()["gauge_probe"] == len(a) + len(b) == 5
    b.clear()
    assert scraped()["gauge_probe"] == len(a) == 3
    a.put(0, 99)                # overwrite: no size change
    assert scraped()["gauge_probe"] == 3
    obs.reset()                 # collector survives; gauge resyncs
    assert scraped()["gauge_probe"] == 3


def test_oom_split_counted_and_traced(tmp_path):
    """An OOM on the top rung splits the batch; the split shows up as a
    registry counter and an event on the dispatch span."""
    bms = datasets.synthetic_bitmaps(8, seed=9, universe=1 << 16,
                                     density=0.02)
    eng = BatchEngine.from_bitmaps(bms)
    qs = random_query_pool(8, 8)
    want = [r.cardinality for r in eng.execute(qs)]
    obs.enable(str(tmp_path / "oom.jsonl"))
    try:
        # xla (the CPU top rung) OOMs on EVERY dispatch: the batch splits
        # down to Q=1 halves which then demote to xla-vmap — guaranteed
        # splits, still bit-exact
        with faults.inject("oom@xla=1.0:21"):
            got = [r.cardinality for r in eng.execute(qs)]
    finally:
        obs.disable()
    assert got == want
    assert eng.split_count > 0
    snap = obs.snapshot()
    splits = snap["counters"]["rb_batch_oom_splits_total"][0]["value"]
    assert splits == eng.split_count
    spans = _read_trace(tmp_path / "oom.jsonl")
    evs = [e for s in spans for e in s["events"]
           if e["name"] == "oom_split"]
    assert evs and evs[0]["site"] == "batch_engine"
