"""RoaringBitmap API tests vs Python-set oracle (randomized, seeded).

Mirrors the reference's model-checking strategy: ops verified against
java.util.BitSet / algebraic identities (Fuzzer.verifyInvariance,
fuzz-tests/.../Fuzzer.java:31-80)."""

import numpy as np
import pytest

import roaringbitmap_tpu as rt
from roaringbitmap_tpu import RoaringBitmap


def rand_bitmap(rng, style="mixed", universe=1 << 22):
    kind = style if style != "mixed" else ["sparse", "dense", "runs"][int(rng.integers(3))]
    if kind == "sparse":
        v = rng.integers(0, universe, 5000)
    elif kind == "dense":
        v = rng.integers(0, universe >> 6, 50000)
    else:
        starts = rng.integers(0, universe, 40)
        v = np.concatenate([np.arange(s, s + int(l))
                            for s, l in zip(starts, rng.integers(1, 3000, 40))])
    return RoaringBitmap.from_values((v % universe).astype(np.uint32))


def test_point_mutation(rng):
    rb = RoaringBitmap()
    ref = set()
    for x in rng.integers(0, 1 << 20, 2000).tolist():
        rb.add(x)
        ref.add(x)
    for x in rng.integers(0, 1 << 20, 2000).tolist():
        rb.remove(x)
        ref.discard(x)
    assert set(rb.to_array().tolist()) == ref
    assert rb.cardinality == len(ref)
    x = rb.to_array()[0] if rb.cardinality else 0
    assert rb.checked_remove(int(x)) == (int(x) in ref)
    assert rb.checked_add(int(x)) is True


def test_pairwise_algebra_vs_sets(rng):
    for _ in range(5):
        a, b = rand_bitmap(rng), rand_bitmap(rng)
        sa, sb = set(a.to_array().tolist()), set(b.to_array().tolist())
        assert set((a | b).to_array().tolist()) == sa | sb
        assert set((a & b).to_array().tolist()) == sa & sb
        assert set((a ^ b).to_array().tolist()) == sa ^ sb
        assert set((a - b).to_array().tolist()) == sa - sb
        assert rt.and_cardinality(a, b) == len(sa & sb)
        assert rt.or_cardinality(a, b) == len(sa | sb)
        assert rt.xor_cardinality(a, b) == len(sa ^ sb)
        assert rt.andnot_cardinality(a, b) == len(sa - sb)
        assert a.intersects(b) == bool(sa & sb)


def test_inplace_variants(rng):
    a, b = rand_bitmap(rng), rand_bitmap(rng)
    expect = (a | b, a & b, a ^ b, a - b)
    for op, want in zip(("ior", "iand", "ixor", "iandnot"), expect):
        c = a.clone()
        getattr(c, op)(b)
        assert c == want


def test_rank_select_navigation(rng):
    rb = rand_bitmap(rng)
    arr = rb.to_array()
    for j in rng.integers(0, arr.size, 50).tolist():
        assert rb.select(j) == int(arr[j])
        assert rb.rank(int(arr[j])) == j + 1
    assert rb.first() == int(arr[0]) and rb.last() == int(arr[-1])
    # nextValue / previousValue
    probe = int(arr[arr.size // 2])
    assert rb.next_value(probe) == probe
    assert rb.previous_value(probe) == probe
    assert rb.next_value(int(arr[-1]) + 1) == -1
    gap = int(arr[0]) - 1
    if gap >= 0:
        assert rb.previous_value(gap) == -1


def test_range_ops_vs_sets(rng):
    for _ in range(5):
        rb = rand_bitmap(rng, universe=1 << 19)
        ref = set(rb.to_array().tolist())
        lo = int(rng.integers(0, 1 << 19))
        hi = lo + int(rng.integers(1, 1 << 18))
        r = rb.clone()
        r.add_range(lo, hi)
        assert set(r.to_array().tolist()) == ref | set(range(lo, hi))
        r = rb.clone()
        r.remove_range(lo, hi)
        assert set(r.to_array().tolist()) == ref - set(range(lo, hi))
        r = rb.clone()
        r.flip_range(lo, hi)
        assert set(r.to_array().tolist()) == ref ^ set(range(lo, hi))
        assert rb.contains_range(lo, hi) == set(range(lo, hi)).issubset(ref)
        assert rb.intersects_range(lo, hi) == bool(ref & set(range(lo, hi)))


def test_subset_and_similarity(rng):
    a = rand_bitmap(rng)
    sub = a.limit(a.cardinality // 2)
    assert sub.is_subset_of(a)
    assert not a.is_subset_of(sub) or a == sub
    assert a.is_hamming_similar(a, 0)
    b = a.clone()
    b.add(4242424242)
    assert a.is_hamming_similar(b, 1) and not a.is_hamming_similar(b, 0)


def test_iteration_and_batches(rng):
    rb = rand_bitmap(rng)
    arr = rb.to_array()
    got = np.concatenate(list(rb.batch_iterator(1000)))
    np.testing.assert_array_equal(got, arr)
    assert list(rb)[:100] == arr[:100].tolist()


def test_add_offset(rng):
    rb = rand_bitmap(rng, universe=1 << 20)
    off = rb.add_offset(1 << 21)
    np.testing.assert_array_equal(off.to_array(),
                                  rb.to_array().astype(np.int64) + (1 << 21))
    back = off.add_offset(-(1 << 21))
    assert back == rb


@pytest.mark.parametrize("offset", [
    0, 1, -1, 7, -7, 65535, -65535, 1 << 16, -(1 << 16), (1 << 16) + 3,
    (3 << 16) - 5, 1 << 31, -(1 << 31), (1 << 32) - 1, -((1 << 32) - 1),
    1 << 33, -(1 << 33)])
def test_add_offset_fuzz_vs_naive(rng, offset):
    """Container-granular add_offset == the value-array shift oracle, for
    straddling/aligned/sign/overflow offsets over mixed container kinds
    (VERDICT r4 weak #2: the rewrite must keep to_array-shift semantics)."""
    for style in ("sparse", "dense", "runs"):
        rb = rand_bitmap(rng, style=style)
        rb.run_optimize()
        snapshot = RoaringBitmap.from_values(rb.to_array())
        want = rb.to_array().astype(np.int64) + offset
        want = want[(want >= 0) & (want <= 0xFFFFFFFF)]
        got = rb.add_offset(offset)
        np.testing.assert_array_equal(got.to_array().astype(np.int64), want)
        assert rb == snapshot  # shifting must not mutate the source


def test_add_offset_shares_containers_when_aligned(rng):
    rb = rand_bitmap(rng)
    shifted = rb.add_offset(5 << 16)
    assert all(a is b for a, b in zip(rb.containers, shifted.containers))


def test_inplace_xor_kills_emptied_keys_then_inserts(rng):
    """ixor where a shared key cancels to empty AND a new key arrives in
    the same delta — the kill-then-splice ordering of the O(delta) merge."""
    a = RoaringBitmap.from_values(np.array([1, 2, 1 << 20], dtype=np.uint32))
    b = RoaringBitmap.from_values(np.array([1, 2, 5 << 20], dtype=np.uint32))
    a.ixor(b)
    assert a.to_array().tolist() == [1 << 20, 5 << 20]
    # chunk 0 must be gone entirely, not present-but-empty
    assert a.keys.tolist() == [(1 << 20) >> 16, (5 << 20) >> 16]


def test_inplace_delta_ops_fuzz(rng):
    """In-place delta merges == static algebra across kind mixes, incl.
    empties and self-application."""
    for _ in range(4):
        a, b = rand_bitmap(rng), rand_bitmap(rng)
        b.run_optimize()
        for op, fn in (("ior", rt.or_), ("ixor", rt.xor),
                       ("iandnot", rt.andnot), ("iand", rt.and_)):
            c = a.clone()
            getattr(c, op)(b)
            assert c == fn(a, b), op
            c = a.clone()
            getattr(c, op)(RoaringBitmap())
            assert c == fn(a, RoaringBitmap()), op
    c = a.clone()
    c.ixor(a)
    assert c.is_empty()


def test_equality_across_container_kinds(rng):
    """Word-level __eq__ must be kind-agnostic: the same set stored as
    run/array/bitmap containers compares equal, near-misses don't."""
    v = np.concatenate([np.arange(100, 8000, dtype=np.uint32),
                        np.array([1 << 18], dtype=np.uint32)])
    as_bitmap = RoaringBitmap.from_values(v)
    as_runs = RoaringBitmap.from_values(v)
    as_runs.run_optimize()
    assert as_runs.containers[0].is_run()
    assert as_bitmap == as_runs and as_runs == as_bitmap
    tweak = as_runs.clone()
    tweak.remove(4000)
    assert tweak != as_bitmap
    tweak.add(50)  # same cardinality, different content
    assert tweak.cardinality == as_bitmap.cardinality
    assert tweak != as_bitmap


def test_flip_static(rng):
    rb = rand_bitmap(rng, universe=1 << 18)
    ref = set(rb.to_array().tolist())
    flipped = rt.flip(rb, 0, 1 << 18)
    assert set(flipped.to_array().tolist()) == set(range(1 << 18)) - ref
    assert rb == RoaringBitmap.from_values(np.array(sorted(ref), dtype=np.uint32))


def test_or_not(rng):
    a = rand_bitmap(rng, universe=1 << 18)
    b = rand_bitmap(rng, universe=1 << 18)
    sa, sb = set(a.to_array().tolist()), set(b.to_array().tolist())
    got = rt.or_not(a, b, 1 << 18)
    want = sa | (set(range(1 << 18)) - sb)
    assert set(got.to_array().tolist()) == want


def test_absent_value_navigation():
    # regression: contiguous container tail must yield last+1, not next chunk
    rb = RoaringBitmap.bitmap_of(5, 6, 7)
    assert rb.next_absent_value(5) == 8
    assert rb.next_absent_value(4) == 4
    rb2 = RoaringBitmap.bitmap_of(0xFFFE, 0xFFFF, 0x10000)
    assert rb2.next_absent_value(0xFFFE) == 0x10001
    assert rb2.previous_absent_value(0x10000) == 0xFFFD
    full = RoaringBitmap.from_range(0, 0x20000)
    assert full.next_absent_value(0) == 0x20000
    assert full.previous_absent_value(0x1FFFF) == -1
    assert RoaringBitmap.bitmap_of(0).previous_absent_value(0) == -1


def test_or_not_drops_b_above_range():
    # regression: b's containers above range_end must not leak into the result
    a = RoaringBitmap()
    b = RoaringBitmap.bitmap_of(3, 0x20000)
    got = rt.or_not(a, b, 10)
    assert set(got.to_array().tolist()) == set(range(10)) - {3}
    # a's values above range_end are kept
    a2 = RoaringBitmap.bitmap_of(0x30000)
    got2 = rt.or_not(a2, b, 10)
    assert 0x30000 in got2 and 0x20000 not in got2


def test_bitmap_container_point_ops_stay_wordlevel(rng):
    dense = RoaringBitmap.from_values(np.arange(0, 20000, 2, dtype=np.uint32))
    assert dense.containers[0].cardinality == 10000
    dense.add(1)
    dense.remove(0)
    assert 1 in dense and 0 not in dense
    # demotion at the 4096 boundary on remove
    from roaringbitmap_tpu.core import containers as C
    c = C.from_values(np.arange(4097, dtype=np.uint16))
    assert isinstance(c, C.BitmapContainer)
    c2 = c.remove(0)
    assert isinstance(c2, C.ArrayContainer) and c2.cardinality == 4096


def test_or_not_property(rng):
    # randomized sweep incl. range ends off/on chunk boundaries and empty sides
    for trial in range(8):
        a = rand_bitmap(rng, universe=1 << 18)
        b = rand_bitmap(rng, universe=1 << 18)
        end = int(rng.integers(1, 1 << 18)) if trial % 4 else (trial // 4 + 1) << 16
        sa, sb = set(a.to_array().tolist()), set(b.to_array().tolist())
        got = rt.or_not(a, b, end)
        want = sa | (set(range(end)) - sb)
        assert set(got.to_array().tolist()) == want, (trial, end)
    assert rt.or_not(RoaringBitmap.bitmap_of(7), RoaringBitmap(), 0) == \
        RoaringBitmap.bitmap_of(7)


class TestRoaringBatchIterator:
    """Seekable batch iterator (RoaringBatchIterator.java:19-80, seek :53)."""

    @staticmethod
    def _rb():
        rng = np.random.default_rng(5)
        vals = np.unique(np.concatenate([
            rng.integers(0, 1 << 22, 30000),
            np.arange(1 << 20, (1 << 20) + 5000),     # a dense run
            [0, 0xFFFF, 0x10000, (1 << 22) - 1]]))
        return RoaringBitmap.from_values(vals.astype(np.uint32))

    def test_batches_cover_exactly(self):
        rb = self._rb()
        it = rb.get_batch_iterator(997)   # deliberately not a divisor
        got = np.concatenate(list(it))
        assert np.array_equal(got, rb.to_array())

    def test_seek_parity_with_value_iterator(self):
        rb = self._rb()
        arr = rb.to_array()
        for target in [0, 1, 70000, 1 << 20, (1 << 20) + 4999,
                       int(arr[-1]), int(arr[-1]) + 1]:
            it = rb.get_batch_iterator(256)
            it.advance_if_needed(target)
            rest = np.concatenate(list(it)) if it.has_next() \
                else np.empty(0, np.uint32)
            assert np.array_equal(rest, arr[arr >= target]), target

    def test_seek_mid_stream_only_moves_forward(self):
        rb = self._rb()
        arr = rb.to_array()
        it = rb.get_batch_iterator(1000)
        first = it.next_batch()
        # seeking BACKWARD must not rewind (reference contract: advance only)
        it.advance_if_needed(0)
        nxt = it.next_batch()
        assert int(nxt[0]) == int(arr[1000])
        # forward seek from mid-stream
        it.advance_if_needed(int(arr[5000]))
        assert int(it.next_batch()[0]) == int(arr[5000])
        assert first.size == 1000

    def test_empty_and_exhausted(self):
        it = RoaringBitmap().get_batch_iterator(10)
        assert not it.has_next() and it.next_batch().size == 0
        rb = RoaringBitmap.bitmap_of(1, 2, 3)
        it = rb.get_batch_iterator(10)
        assert it.next_batch().tolist() == [1, 2, 3]
        assert not it.has_next()
        it.advance_if_needed(1 << 30)   # seek past the end: harmless
        assert it.next_batch().size == 0

    def test_immutable_seek_skips_decode(self):
        from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap

        parts = [np.arange(0, 4000, dtype=np.uint32) + (k << 16)
                 for k in range(200)]
        rb = RoaringBitmap.from_values(np.concatenate(parts))
        im = ImmutableRoaringBitmap(rb.serialize())
        it = im.get_batch_iterator(100)
        it.advance_if_needed(150 << 16)
        assert int(it.next_batch()[0]) == (150 << 16)
        assert len(im._cache) <= 2     # skipped containers never decoded


def test_select_range_container_granular(rng):
    """select_range == the array-slice oracle across container boundaries,
    and wholly-included containers are SHARED, not copied."""
    rb = rand_bitmap(rng)
    rb.run_optimize()
    arr = rb.to_array()
    card = arr.size
    for start, end in [(0, card), (1, card - 1), (card // 3, 2 * card // 3),
                       (0, 1), (card - 1, card), (card // 2, card + 500)]:
        got = rb.select_range(start, end)
        np.testing.assert_array_equal(got.to_array(),
                                      arr[start:min(end, card)])
    full = rb.select_range(0, card)
    assert all(a is b for a, b in zip(rb.containers, full.containers))
    # deterministic shape so the boundary container provably has >1 value:
    # chunk 0 holds 10 values, later chunks shared untouched
    det = RoaringBitmap.from_values(np.concatenate(
        [np.arange(10, dtype=np.uint32),
         (np.arange(3, dtype=np.uint32) + 2) << 16]).astype(np.uint32))
    mid = det.select_range(1, det.cardinality)
    assert mid.containers[0] is not det.containers[0]  # sliced boundary
    assert all(a is b for a, b in
               zip(det.containers[1:], mid.containers[1:]))
    with pytest.raises(ValueError):
        rb.select_range(card, card + 5)
    with pytest.raises(ValueError):
        rb.select_range(3, 3)


@pytest.mark.parametrize("batch_size", [1, 3, 63, 64, 65, 256, 1024, 65536])
def test_batch_iterator_rebuilds_random_shapes(rng, batch_size):
    """RoaringBitmapBatchIteratorTest.test / testBatchIteratorAsIntIterator:
    paging any random container mix through any batch size and feeding the
    values back through the constant-memory writer reproduces the bitmap."""
    from roaringbitmap_tpu import RoaringBitmapWriter

    for style in ("sparse", "dense", "runs", "mixed"):
        rb = rand_bitmap(rng, style=style)
        rb.run_optimize()
        it = rb.get_batch_iterator(batch_size)
        parts = list(it)
        got = (np.concatenate(parts) if parts
               else np.empty(0, np.uint32))
        np.testing.assert_array_equal(got, rb.to_array())
        assert all(p.size <= batch_size for p in parts)
        w = RoaringBitmapWriter.wizard().constant_memory().get()
        for p in parts:
            w.add_many(p)
        assert w.get() == rb, (style, batch_size)
