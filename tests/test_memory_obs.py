"""Device-memory observability (ISSUE 4) acceptance + contracts.

Pins:
- HBM ledger register/release/reset-snapshot symmetry, and the
  rb_hbm_resident_bytes gauges tracking live DeviceBitmapSets;
- the unified footprint model: predict_resident_bytes (host metadata
  only, no device) equals the measured hbm_bytes() of the built set for
  the dense and counts layouts (compact pinned too);
- BatchEngine.explain(): deterministic, JSON-serializable, documented
  schema, and its predicted dispatch peak equal to the predictor the
  proactive splitter uses;
- predicted dispatch HBM within 2x of Compiled.memory_analysis()
  (temp + output) on a Q=64 CPU-proxy batch — the acceptance bound;
- proactive HBM-budget split: a batch predicted past
  ROARING_TPU_HBM_BUDGET is halved BEFORE dispatch (proactive counter
  moves, reactive OOM counter does not), bit-exact vs the unsplit run,
  and every dispatched sub-batch's prediction respects the budget;
- the budget machinery composes with the fault harness's oom kind
  (reactive splits still fire underneath, results stay bit-exact);
- tools/bench_diff.py lane extraction, salvage, and regression logic.
"""

import importlib.util
import json
import os
import sys

import pytest

from roaringbitmap_tpu import obs
from roaringbitmap_tpu.insights import analysis as insights
from roaringbitmap_tpu.obs import memory as obs_memory
from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet
from roaringbitmap_tpu.parallel.batch_engine import (BatchEngine,
                                                     random_query_pool)
from roaringbitmap_tpu.runtime import faults, guard
from roaringbitmap_tpu.utils import datasets

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()
    yield
    obs.disable()
    obs.reset()
    guard.reset_dispatch_stats()


@pytest.fixture(scope="module")
def bitmaps():
    return datasets.synthetic_bitmaps(16, seed=11, universe=1 << 18,
                                      density=0.01)


@pytest.fixture(scope="module")
def engine(bitmaps):
    return BatchEngine.from_bitmaps(bitmaps)


@pytest.fixture(scope="module")
def pool():
    return random_query_pool(16, 64)


# ----------------------------------------------------------------- ledger

class TestLedger:
    def test_register_release_symmetry(self):
        led = obs_memory.HbmLedger()
        baseline = led.snapshot()
        assert baseline == {"total_bytes": 0, "entries": 0, "by_kind": {}}
        h1 = led.register("bitmap_set", "dense", 1000)
        h2 = led.register("bitmap_set", "counts", 500)
        h3 = led.register("pair_set", "dense", 250)
        snap = led.snapshot()
        assert snap["total_bytes"] == 1750 and snap["entries"] == 3
        assert snap["by_kind"]["bitmap_set"] == {"dense": 1000,
                                                 "counts": 500}
        assert led.resident_bytes("bitmap_set") == 1500
        assert led.resident_bytes("bitmap_set", "counts") == 500
        led.release(h2)
        led.release(h2)   # idempotent: GC finalizer after manual release
        assert led.snapshot()["total_bytes"] == 1250
        led.release(h1)
        led.release(h3)
        assert led.snapshot() == baseline
        led.register("bitmap_set", "dense", 1)
        led.reset()
        assert led.snapshot() == baseline

    def test_owner_gc_releases(self, bitmaps):
        led = obs_memory.LEDGER
        before = led.resident_bytes("bitmap_set", "counts")
        ds = DeviceBitmapSet(bitmaps[:4], layout="counts")
        held = ds.hbm_bytes()
        assert led.resident_bytes("bitmap_set", "counts") == before + held
        del ds
        import gc

        gc.collect()
        assert led.resident_bytes("bitmap_set", "counts") == before

    def test_resident_gauges_exported(self, bitmaps):
        ds = DeviceBitmapSet(bitmaps[:4])
        rows = obs.snapshot()["gauges"]["rb_hbm_resident_bytes"]
        dense = [r for r in rows if r["labels"] == {"kind": "bitmap_set",
                                                    "layout": "dense"}]
        assert dense and dense[0]["value"] >= ds.hbm_bytes()
        assert "hbm" in obs.snapshot()
        text = obs.render_prometheus()
        assert "rb_hbm_resident_bytes" in text


# ------------------------------------------------- unified footprint model

class TestFootprintModel:
    @pytest.mark.parametrize("layout", ["dense", "counts", "compact"])
    def test_predictor_matches_measured(self, bitmaps, layout):
        """predict_resident_bytes from host metadata alone equals the
        measured bytes of the built set — the model parity pin."""
        predicted = insights.predict_resident_bytes(bitmaps, layout=layout)
        ds = DeviceBitmapSet(bitmaps, layout=layout)
        measured = insights.resident_set_bytes(ds)
        assert set(predicted) == set(measured)
        assert predicted == {k: int(v) for k, v in measured.items()}
        assert sum(predicted.values()) == ds.hbm_bytes()

    def test_footprint_shares_row_constant(self, bitmaps):
        rb = bitmaps[0]
        assert insights.hbm_footprint_bytes(rb) == \
            rb.container_count() * insights.ROW_BYTES
        assert insights.dense_rows_bytes(3) == 3 * insights.ROW_BYTES


# ----------------------------------------------------------------- explain

class TestExplain:
    def test_schema_and_determinism(self, engine, pool):
        engine.explain(pool)              # warm the plan cache
        a = engine.explain(pool)
        b = engine.explain(pool)
        assert a == b                     # deterministic
        json.loads(json.dumps(a))         # JSON-serializable
        assert {"site", "q", "engine", "engine_chain", "layout",
                "plan_cache_hit", "program_cache_hit", "resident",
                "buckets", "queries", "predicted", "hbm_budget_bytes",
                "proactive_split", "sequential_floor"} <= set(a)
        assert a["q"] == len(pool) and a["plan_cache_hit"]
        assert a["resident"]["hbm_bytes"] == engine.hbm_bytes()
        assert a["predicted"]["peak_bytes"] == \
            engine.predict_dispatch_bytes(pool)
        # every query maps to a real bucket, and buckets cover the batch
        assert sorted(q for b_ in a["buckets"] for q in b_["queries"]) \
            == list(range(len(pool)))
        for row in a["queries"]:
            assert row["bucket"] < len(a["buckets"])
            assert row["rung"] >= 1 and row["op"] in (
                "or", "xor", "and", "andnot")

    def test_program_cache_hit_after_execute(self, engine, pool):
        engine.execute(pool[:8])
        rep = engine.explain(pool[:8])
        assert rep["program_cache_hit"] and rep["plan_cache_hit"]

    def test_explain_wide_and_sharded(self, bitmaps):
        from roaringbitmap_tpu.parallel import aggregation, sharding

        rep = aggregation.explain_wide("or", bitmaps)
        json.loads(json.dumps(rep))
        assert rep["n"] == len(bitmaps) and rep["engine_chain"][-1] == \
            guard.SEQUENTIAL
        assert rep["predicted_hbm_bytes"] > 0
        import jax
        from jax.sharding import Mesh

        devs = jax.devices()
        mesh = Mesh(
            __import__("numpy").array(devs).reshape(len(devs), 1),
            ("rows", "lanes"))
        srep = sharding.explain_sharded(mesh, "or", bitmaps)
        json.loads(json.dumps(srep))
        assert srep["num_keys"] > 0 and srep["passes"]
        assert all(p["per_device_accumulator_bytes"]
                   <= insights.dense_rows_bytes(
                       sharding.MAX_KEYS_PER_SHARD_PASS + 1)
                   for p in srep["passes"])


# ---------------------------------------------------- predicted vs actual

class TestDispatchMemory:
    def test_predicted_within_2x_of_measured(self, engine, pool):
        """Acceptance: Q=64 CPU-proxy batch — predicted dispatch HBM
        within 2x of Compiled.memory_analysis() (temp + output)."""
        engine.execute(pool)
        mem = engine.last_dispatch_memory
        assert mem is not None and mem["q"] == 64
        assert mem["predicted_bytes"] > 0
        measured = mem["measured_peak_bytes"]
        assert measured > 0
        ratio = mem["predicted_bytes"] / measured
        assert 0.5 <= ratio <= 2.0, \
            f"predicted {mem['predicted_bytes']} vs measured {measured}"
        # the gauges moved with the dispatch
        g = obs.snapshot()["gauges"]
        assert g["rb_hbm_predicted_bytes"][0]["value"] == \
            mem["predicted_bytes"]
        assert g["rb_hbm_measured_peak_bytes"][0]["value"] == measured

    def test_batch_memory_event_in_trace(self, engine, pool, tmp_path):
        path = str(tmp_path / "trace.jsonl")
        obs.enable(path)
        engine.execute(pool[:8])
        obs.disable()
        spans = [json.loads(l) for l in open(path)]
        dispatches = [s for s in spans if s["name"] == "batch.dispatch"]
        assert dispatches
        evs = [ev for s in dispatches for ev in s["events"]
               if ev["name"] == "batch.memory"]
        assert evs and evs[0]["predicted_bytes"] > 0
        assert evs[0]["residual_x"] > 0


# ------------------------------------------------------- proactive splits

class TestProactiveSplit:
    def test_budget_splits_before_dispatch_bit_exact(self, bitmaps,
                                                     tmp_path):
        eng = BatchEngine.from_bitmaps(bitmaps)
        pool = random_query_pool(16, 64, seed=0xB4)
        clean = [r.cardinality for r in eng.execute(pool)]
        assert eng.proactive_split_count == 0

        budget = 16 << 20
        path = str(tmp_path / "trace.jsonl")
        obs.enable(path)
        policy = guard.GuardPolicy(hbm_budget=budget)
        split = [r.cardinality for r in eng.execute(pool, policy=policy)]
        obs.disable()

        assert split == clean                      # bit-exact
        assert eng.proactive_split_count > 0       # split BEFORE dispatch
        assert eng.split_count == 0                # zero reactive splits
        snap = obs.snapshot()
        pro = snap["counters"]["rb_batch_proactive_splits_total"]
        assert pro[0]["value"] == eng.proactive_split_count
        assert "rb_batch_oom_splits_total" not in snap["counters"]
        # budget-respected property: every dispatched sub-batch's
        # prediction fits the budget, and splits are traced
        spans = [json.loads(l) for l in open(path)]
        mems = [ev for s in spans if s["name"] == "batch.dispatch"
                for ev in s["events"] if ev["name"] == "batch.memory"]
        assert mems and all(ev["predicted_bytes"] <= budget for ev in mems)
        splits = [ev for s in spans for ev in s["events"]
                  if ev["name"] == "proactive_split"]
        assert len(splits) == eng.proactive_split_count
        assert all(ev["predicted_bytes"] > ev["budget_bytes"]
                   for ev in splits)
        # explain agrees with what execute just did
        rep = eng.explain(pool, policy=policy)
        assert rep["proactive_split"]["would_split"]
        assert sum(rep["proactive_split"]["dispatches"]) == len(pool)

    def test_budget_env_knob(self, bitmaps, monkeypatch):
        eng = BatchEngine.from_bitmaps(bitmaps[:8])
        pool = random_query_pool(8, 32, seed=0xE2)
        clean = [r.cardinality for r in eng.execute(pool)]
        monkeypatch.setenv(guard.ENV_HBM_BUDGET, "8M")
        assert guard.resolve_hbm_budget() == 8 << 20
        got = [r.cardinality for r in eng.execute(pool)]
        assert got == clean and eng.proactive_split_count > 0

    def test_budget_unlimited_values(self):
        assert guard.parse_bytes("0") == 0
        assert guard.parse_bytes("64M") == 64 << 20
        assert guard.parse_bytes("2g") == 2 << 30
        assert guard.parse_bytes("1024") == 1024
        with pytest.raises(ValueError):
            guard.parse_bytes("lots")
        # <= 0 means explicitly unlimited
        assert guard.resolve_hbm_budget(
            guard.GuardPolicy(hbm_budget=0)) is None

    def test_budget_composes_with_oom_faults(self, bitmaps):
        """The proactive splitter and the reactive OOM machinery stack:
        with a tiny budget AND injected allocator failures, both split
        kinds fire and the results stay bit-exact."""
        eng = BatchEngine.from_bitmaps(bitmaps)
        pool = random_query_pool(16, 16, seed=0x00F)
        clean = [r.cardinality for r in eng.execute(pool)]
        assert eng.predict_dispatch_bytes(pool) > 8 << 20, \
            "workload too small to exercise the budget"
        policy = guard.GuardPolicy(hbm_budget=8 << 20)
        with faults.inject("oom@xla=1.0:5"):
            got = [r.cardinality for r in eng.execute(pool, policy=policy)]
        assert got == clean
        assert eng.proactive_split_count > 0
        assert eng.split_count > 0      # reactive halvings underneath
        # legacy stat shapes untouched by the new counter
        assert set(eng.cache_stats()) == {"plans", "programs", "splits"}


# -------------------------------------------------------- tools/bench_diff

def _load_bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "tools", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchDiff:
    def test_lane_diff_and_regression(self, tmp_path):
        bd = _load_bench_diff()
        old = {"metric": "m", "value": 100.0, "detail": {
            "q64_e2e_qps": 1000.0, "pack_ms": 5.0}}
        new = {"metric": "m", "value": 50.0, "detail": {
            "q64_e2e_qps": 1100.0, "pack_ms": 4.0}}
        po, pn = tmp_path / "o.json", tmp_path / "n.json"
        po.write_text(json.dumps(old))
        pn.write_text(json.dumps(new))
        rows, regressions = bd.diff_lanes(
            bd.load_lanes(str(po)), bd.load_lanes(str(pn)), 0.15)
        assert regressions == ["value"]       # -50% on higher-is-better
        by_lane = {r[0]: r for r in rows}
        assert not by_lane["detail.q64_e2e_qps"][5]   # +10% is fine
        assert not by_lane["detail.pack_ms"][5]       # lower is better

    def test_salvages_committed_trajectory_tails(self):
        """The CI smoke case: the pre-cap driver captures (parsed: null,
        truncated tail) must still yield lanes."""
        bd = _load_bench_diff()
        lanes4 = bd.load_lanes(os.path.join(REPO, "BENCH_r04.json"))
        lanes2 = bd.load_lanes(os.path.join(REPO, "BENCH_r02.json"))
        assert lanes4 and lanes2
        rows, _ = bd.diff_lanes(lanes2, lanes4, 0.15)
        assert rows, "suffix alignment found no shared lanes r02->r04"

    def test_driver_capture_with_parsed(self, tmp_path):
        bd = _load_bench_diff()
        doc = {"n": 9, "cmd": "x", "rc": 0, "tail": "noise",
               "parsed": {"value": 7.5, "vs_baseline": 12.0}}
        p = tmp_path / "cap.json"
        p.write_text(json.dumps(doc))
        assert bd.load_lanes(str(p)) == {"value": 7.5, "vs_baseline": 12.0}

    def test_multiset_lane_directions(self, tmp_path):
        """ISSUE 5: the multiset lane's dotted paths gate in the right
        direction — pooled/per-set QPS, pooled-vs-per-set ratio, overlap
        ratio, and launches saved are higher-is-better."""
        bd = _load_bench_diff()
        for lane in ("multiset.s4_q64.pooled_qps",
                     "multiset.s4_q64.per_set_qps",
                     "multiset.s4_q64.pooled_vs_per_set_x",
                     "multiset.overlap_ratio",
                     "multiset.s16_pipeline.overlap_ratio",
                     "rb_multiset_launches_saved_total"):
            assert bd.direction(lane) == 1, lane
        assert bd.direction("multiset.s4_pipeline.host_ms") == -1
        # a halved pooled ratio past the threshold is a regression
        old = {"multiset": {"s4_q64": {"pooled_vs_per_set_x": 3.2},
                            "overlap_ratio": 0.8}}
        new = {"multiset": {"s4_q64": {"pooled_vs_per_set_x": 1.4},
                            "overlap_ratio": 0.82}}
        po, pn = tmp_path / "o.json", tmp_path / "n.json"
        po.write_text(json.dumps(old))
        pn.write_text(json.dumps(new))
        rows, regressions = bd.diff_lanes(
            bd.load_lanes(str(po)), bd.load_lanes(str(pn)), 0.15)
        assert regressions == ["multiset.s4_q64.pooled_vs_per_set_x"]
