"""Byte-stream ingest parity: pack_blocked_compact + device densify must be
bit-identical to the host densify path, for every input form (heap bitmaps,
serialized bytes, SerializedViews, ImmutableRoaringBitmaps) and layout.

Reference capability being mirrored: aggregation straight off mmap'd buffers
without heap materialization (buffer/BufferFastAggregation.java:187,
buffer/ImmutableRoaringArray.java:166-194).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.buffer import ImmutableRoaringBitmap
from roaringbitmap_tpu.format import spec
from roaringbitmap_tpu.ops import dense, packing
from roaringbitmap_tpu.parallel import aggregation
from roaringbitmap_tpu.utils import datasets


def _mixed_bitmaps(seed=3, n=12):
    """Bitmaps exercising all three container kinds incl. big runs."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        vals = [rng.integers(0, 1 << 20, 500)]          # sparse arrays
        vals.append((2 << 16) + rng.integers(0, 9000, 6000))  # dense chunk
        start = (3 << 16) + int(rng.integers(0, 1000))
        vals.append(np.arange(start, start + 5000 + 100 * i))  # big run
        vals.append((4 << 16) + np.arange(0, 40))       # small run
        b = RoaringBitmap.from_values(
            np.concatenate(vals).astype(np.uint32))
        b.run_optimize()
        out.append(b)
    return out


def _densify_host(bitmaps, blocked):
    """Host reference image for the same blocked layout."""
    order = np.argsort(np.concatenate([b.keys for b in bitmaps]),
                       kind="stable")
    conts = [c for b in bitmaps for c in b.containers]
    seg_of = np.concatenate(
        [np.searchsorted(blocked.keys, b.keys) for b in bitmaps])[order]
    heads = np.searchsorted(seg_of, np.arange(blocked.keys.size))
    within = np.arange(order.size) - heads[seg_of]
    dest = blocked.seg_offsets[seg_of] + within
    return packing.densify_containers(
        [conts[i] for i in order], dest, blocked.n_rows)


def test_stream_densify_matches_host_densify():
    bitmaps = _mixed_bitmaps()
    blocked = packing.pack_blocked_compact(bitmaps, block=8)
    s = blocked.streams
    dev = np.asarray(dense.densify_streams(
        jnp.asarray(s.dense_words), jnp.asarray(s.dense_dest),
        jnp.asarray(s.values), jnp.asarray(s.val_counts),
        jnp.asarray(s.val_dest), s.n_rows, s.total_values))
    host = _densify_host(bitmaps, blocked)
    np.testing.assert_array_equal(dev, host)


def test_padded_streams_same_image():
    bitmaps = _mixed_bitmaps(seed=5, n=7)
    blocked = packing.pack_blocked_compact(bitmaps, block=8)
    s = blocked.streams
    p = packing.pad_streams_pow2(s)
    img = lambda st: np.asarray(dense.densify_streams(
        jnp.asarray(st.dense_words), jnp.asarray(st.dense_dest),
        jnp.asarray(st.values), jnp.asarray(st.val_counts),
        jnp.asarray(st.val_dest), st.n_rows, st.total_values))
    np.testing.assert_array_equal(img(s), img(p))


@pytest.mark.parametrize("form", ["objects", "bytes", "views", "immutable"])
@pytest.mark.parametrize("layout", ["dense", "compact"])
def test_device_set_all_input_forms(form, layout):
    bitmaps = _mixed_bitmaps(seed=11, n=9)
    blobs = [b.serialize() for b in bitmaps]
    if form == "objects":
        inputs = bitmaps
    elif form == "bytes":
        inputs = blobs
    elif form == "views":
        inputs = [spec.deserialize_meta(x) for x in blobs]
    else:
        inputs = [ImmutableRoaringBitmap(x) for x in blobs]
    ds = aggregation.DeviceBitmapSet(inputs, layout=layout)
    if form == "immutable":
        # the whole point: ingest must not have materialized containers
        assert all(not b._cache for b in inputs)
    for op in ("or", "xor", "and"):
        got = ds.aggregate(op)
        want = bitmaps[0]
        for b in bitmaps[1:]:
            want = (want | b) if op == "or" else (
                want ^ b if op == "xor" else want & b)
        assert got == want, (form, layout, op)


def test_compact_layout_footprint_smaller_on_sparse():
    if not datasets.has_dataset("wikileaks-noquotes"):
        pytest.skip("dataset not in mirror")
    bitmaps = datasets.load_bitmaps("wikileaks-noquotes")[:50]
    d = aggregation.DeviceBitmapSet(bitmaps, layout="dense")
    c = aggregation.DeviceBitmapSet(bitmaps, layout="compact")
    assert c.hbm_bytes() * 4 < d.hbm_bytes()
    assert c.aggregate("or") == d.aggregate("or")


def test_chained_wide_or_compact_parity():
    bitmaps = _mixed_bitmaps(seed=2, n=6)
    expected = aggregation.or_(*bitmaps, engine="xla").cardinality
    for layout in ("dense", "compact"):
        ds = aggregation.DeviceBitmapSet(bitmaps, layout=layout)
        fn = ds.chained_wide_or(5, engine="xla")
        total = int(np.asarray(fn(ds.words)))
        assert total == (5 * expected) % 2**32, layout


def test_one_shot_blocked_path_uses_streams():
    bitmaps = _mixed_bitmaps(seed=7, n=8)
    want = aggregation.or_(*bitmaps, engine="xla")
    got = aggregation.or_(*bitmaps, engine="pallas")
    assert got == want


class TestHostileBytes:
    """Corrupt serialized input must raise InvalidRoaringFormat at ingest,
    never produce a silently wrong aggregate (the guard SerializedView.
    container() applies on the eager path, mirrored on the stream path)."""

    def _blob(self):
        rb = RoaringBitmap.from_values(
            np.concatenate([np.arange(0, 200, 2),            # array
                            np.arange(1 << 16, (1 << 16) + 300)]))  # run-able
        rb.run_optimize()
        return bytearray(rb.serialize())

    def test_unsorted_array_values_rejected(self):
        blob = self._blob()
        view = spec.SerializedView(bytes(blob))
        arr_i = int(np.flatnonzero(~view.is_run & ~view.is_bitmap)[0])
        off = int(view.payload_offsets[arr_i])
        blob[off:off + 2], blob[off + 2:off + 4] = \
            blob[off + 2:off + 4], blob[off:off + 2]  # swap first two values
        with pytest.raises(spec.InvalidRoaringFormat):
            packing.pack_blocked_compact([bytes(blob)])

    def test_run_cardinality_mismatch_rejected(self):
        blob = self._blob()
        view = spec.SerializedView(bytes(blob))
        run_i = int(np.flatnonzero(view.is_run)[0])
        off = int(view.payload_offsets[run_i])
        corrupted = bytearray(blob)
        # inflate the run length: expanded size != declared cardinality
        corrupted[off + 4:off + 6] = (500).to_bytes(2, "little")
        with pytest.raises(spec.InvalidRoaringFormat):
            packing.pack_blocked_compact([bytes(corrupted)])

    @staticmethod
    def _run_blob(runs: list[tuple[int, int]]) -> bytes:
        """Hand-built single-container run blob with declared cardinality
        consistent with `runs` [(start, len-1), ...] — so only the
        structural run guards can fire, not the cardinality check."""
        card = sum(l + 1 for _, l in runs)
        out = bytearray()
        out += (spec.SERIAL_COOKIE | (0 << 16)).to_bytes(4, "little")
        out += bytes([1])                                # run marker: c0 is run
        out += (0).to_bytes(2, "little")                 # key 0
        out += (card - 1).to_bytes(2, "little")          # cardinality-1
        out += len(runs).to_bytes(2, "little")
        for s, l in runs:
            out += s.to_bytes(2, "little") + l.to_bytes(2, "little")
        return bytes(out)

    def test_overlapping_runs_rejected(self):
        # two runs, second starts inside the first; total expanded size
        # matches the declared cardinality so ONLY the overlap guard fires
        blob = self._run_blob([(10, 99), (50, 99)])
        with pytest.raises(spec.InvalidRoaringFormat, match="overlap"):
            packing.pack_blocked_compact([blob])

    def test_run_past_chunk_end_rejected(self):
        # start + len-1 crosses 65535: uint16 expansion would wrap to low
        # values and silently corrupt the aggregate
        blob = self._run_blob([(65000, 999)])
        with pytest.raises(spec.InvalidRoaringFormat, match="past 65535"):
            packing.pack_blocked_compact([blob])

    def test_wellformed_two_run_blob_accepted(self):
        blob = self._run_blob([(10, 9), (100, 9)])
        packed = packing.pack_blocked_compact([blob])
        assert packed.keys.size == 1

    def test_good_blob_accepted(self):
        packed = packing.pack_blocked_compact([bytes(self._blob())])
        assert packed.keys.size == 2


def test_wide_and_immutable_materializes_only_survivors():
    """Wide AND over immutables: keys eliminated by the intersection must
    never be materialized (the workShyAnd discipline, BufferFastAggregation
    .java:699) — and the full container list must never be built."""
    rng = np.random.default_rng(11)
    bms = []
    for i in range(5):
        vals = [np.arange(10, 500),                       # shared key 0
                ((i + 1) << 16) + rng.integers(0, 9000, 200)]  # private key
        bms.append(RoaringBitmap.from_values(
            np.concatenate(vals).astype(np.uint32)))
    want = bms[0] & bms[1] & bms[2] & bms[3] & bms[4]
    assert want.cardinality
    imms = [ImmutableRoaringBitmap(b.serialize()) for b in bms]
    got = aggregation.and_(*imms)
    assert got == want
    for im in imms:
        assert set(im._cache) == {0}    # only the surviving key's container


class TestNativeIngest:
    """C++ ingest engine (roaringbitmap_tpu.native) vs the NumPy oracle:
    identical metadata and densified image, identical hostile-input
    behavior.  Skips when the toolchain can't build the library."""

    @pytest.fixture(scope="class")
    def lib(self):
        from roaringbitmap_tpu import native
        if native.load() is None:
            pytest.skip("native ingest unavailable")
        return native

    def test_block32_ladder_parity(self, lib):
        """Median segment >= 32 must select block 32 on BOTH the native
        and NumPy paths (the choose_block ladder is mirrored in
        stream_ingest.cpp; a divergence silently mismatches layouts
        between byte and object ingest)."""
        from roaringbitmap_tpu import RoaringBitmap

        rng = np.random.default_rng(31)
        bitmaps = [RoaringBitmap.from_values(np.concatenate(
            [c * (1 << 16) + rng.integers(0, 1 << 14, 400)
             for c in range(3)]).astype(np.uint32)) for _ in range(40)]
        blobs = [b.serialize() for b in bitmaps]
        nat = packing.pack_blocked_compact(blobs)
        py = packing.pack_blocked_compact(
            [spec.SerializedView(x) for x in blobs])
        assert nat.block == py.block == 32
        assert np.array_equal(nat.blk_seg, py.blk_seg)
        assert (nat.n_blocks, nat.carry_row) == (py.n_blocks, py.carry_row)

    def test_metadata_and_image_parity(self, lib):
        bitmaps = _mixed_bitmaps(seed=21, n=10)
        blobs = [b.serialize() for b in bitmaps]
        nat = packing.pack_blocked_compact(blobs)               # native path
        py = packing.pack_blocked_compact(
            [spec.SerializedView(x) for x in blobs])            # oracle path
        assert np.array_equal(nat.keys, py.keys)
        assert np.array_equal(nat.blk_seg, py.blk_seg)
        assert (nat.block, nat.n_blocks, nat.carry_row) == \
            (py.block, py.n_blocks, py.carry_row)
        assert np.array_equal(nat.seg_sizes, py.seg_sizes)
        assert np.array_equal(nat.seg_offsets, py.seg_offsets)

        def image(p):
            out = np.zeros((p.streams.n_rows, packing.WORDS32), np.uint32)
            s = p.streams
            if s.dense_dest.size:
                out[s.dense_dest] = s.dense_words
            heads = np.concatenate(([0], np.cumsum(s.val_counts)))
            for i in range(s.val_counts.size):
                vals = s.values[heads[i]:heads[i + 1]].astype(np.int64)
                np.bitwise_or.at(out[s.val_dest[i]], vals >> 5,
                                 np.uint32(1) << (vals & 31).astype(np.uint32))
            return out
        # emission order differs by design (input-major vs key-major);
        # the scattered image is the semantic content
        np.testing.assert_array_equal(image(nat), image(py))

    def test_device_aggregate_through_native(self, lib):
        bitmaps = _mixed_bitmaps(seed=22, n=8)
        want = bitmaps[0]
        for b in bitmaps[1:]:
            want = want | b
        ds = aggregation.DeviceBitmapSet([b.serialize() for b in bitmaps])
        assert ds.aggregate("or") == want

    def test_native_disabled_env(self, lib, monkeypatch):
        # RB_NATIVE=0 must silently use the NumPy path
        from roaringbitmap_tpu import native as nat_mod
        monkeypatch.setattr(nat_mod, "_lib", None)
        monkeypatch.setattr(nat_mod, "_lib_failed", False)
        monkeypatch.setenv("RB_NATIVE", "0")
        bitmaps = _mixed_bitmaps(seed=23, n=4)
        blobs = [b.serialize() for b in bitmaps]
        p = packing.pack_blocked_compact(blobs)
        assert p.keys.size
        monkeypatch.setattr(nat_mod, "_lib_failed", False)


class TestNativePairwise:
    """Native pairwise ingest (rb_ingest_pairwise) vs the NumPy oracle:
    identical alignment and stream content, identical hostile-input
    behavior."""

    @pytest.fixture(scope="class")
    def lib(self):
        from roaringbitmap_tpu import native
        if native.load() is None:
            pytest.skip("native ingest unavailable")
        return native

    def test_pack_parity(self, lib):
        bms = _mixed_bitmaps(seed=31, n=10)
        pairs = list(zip(bms[0::2], bms[1::2]))
        bpairs = [(a.serialize(), b.serialize()) for a, b in pairs]
        nat = packing.pack_pairwise(bpairs)                    # native path
        py = packing.pack_pairwise(pairs)                      # oracle path
        assert np.array_equal(nat.keys, py.keys)
        assert np.array_equal(nat.heads, py.heads)
        assert (nat.m, nat.n_rows) == (py.m, py.n_rows)
        for side in ("a_streams", "b_streams"):
            sn, sp = getattr(nat, side), getattr(py, side)
            assert np.array_equal(sn.dense_words, sp.dense_words)
            assert np.array_equal(sn.dense_dest, sp.dense_dest)
            assert np.array_equal(sn.values, sp.values)
            assert np.array_equal(sn.val_counts, sp.val_counts)
            assert np.array_equal(sn.val_dest, sp.val_dest)

    def test_device_pairwise_through_native(self, lib):
        bms = _mixed_bitmaps(seed=32, n=8)
        pairs = list(zip(bms[0::2], bms[1::2]))
        bpairs = [(a.serialize(), b.serialize()) for a, b in pairs]
        got = aggregation.pairwise("xor", bpairs)
        assert got == [a ^ b for a, b in pairs]

    def test_hostile_bytes_rejected(self, lib):
        good = RoaringBitmap.bitmap_of(1, 2, 3).serialize()
        bad = bytearray(RoaringBitmap.from_values(
            np.arange(0, 200, 2, dtype=np.uint32)).serialize())
        view = spec.SerializedView(bytes(bad))
        off = int(view.payload_offsets[0])
        bad[off:off + 2], bad[off + 2:off + 4] = \
            bad[off + 2:off + 4], bad[off:off + 2]   # unsorted array values
        with pytest.raises(spec.InvalidRoaringFormat):
            packing.pack_pairwise([(good, bytes(bad))])
        with pytest.raises(spec.InvalidRoaringFormat):
            packing.pack_pairwise([(b"\x00\x01", good)])
