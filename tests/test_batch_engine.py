"""Batched multi-query engine parity: every mixed-op batch must be
bit-exact, query by query, against the sequential ParallelAggregation path
(parallel.aggregation or_/and_/xor over the same subset), across engines
(Pallas vs XLA vs the vmapped-XLA cross-check), jit vs eager, and resident
layouts (dense vs compact)."""

import numpy as np
import pytest

from roaringbitmap_tpu import RoaringBitmap
from roaringbitmap_tpu.parallel import (BatchEngine, BatchQuery,
                                        aggregation, batch_engine)

N = 16


@pytest.fixture(scope="module")
def workload():
    """Mixed container kinds with a guaranteed shared range so wide ANDs
    are non-empty, plus one dense chunk (bitmap containers)."""
    rng = np.random.default_rng(0xBA7C)
    common = np.arange(500, 900, dtype=np.uint32)
    bms = []
    for i in range(N):
        vals = [rng.integers(0, 1 << 18, 3000).astype(np.uint32), common]
        if i % 5 == 0:  # dense rows exercise the dense-wire stream
            vals.append(np.arange(1 << 16, (1 << 16) + 20000,
                                  dtype=np.uint32))
        bms.append(RoaringBitmap.from_values(
            np.unique(np.concatenate(vals))))
    return bms


@pytest.fixture(scope="module")
def engine(workload):
    return BatchEngine.from_bitmaps(workload)


def _mixed_queries(q, form="cardinality", seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(q):
        op = ("or", "and", "xor", "andnot")[i % 4]
        k = int(rng.integers(2, min(9, N)))
        sub = tuple(int(x) for x in rng.choice(N, size=k, replace=False))
        out.append(BatchQuery(op=op, operands=sub, form=form))
    return out


def _sequential(bms, q: BatchQuery) -> RoaringBitmap:
    sub = [bms[i] for i in q.operands]
    if q.op == "or":
        return aggregation.or_(*sub)
    if q.op == "and":
        return aggregation.and_(*sub)
    if q.op == "xor":
        return aggregation.xor(*sub)
    rest = aggregation.or_(*sub[1:]) if len(sub) > 1 else RoaringBitmap()
    return _sequential(bms, BatchQuery("or", (q.operands[0],))) - rest


@pytest.fixture(scope="module")
def oracle(workload):
    cache = {}

    def get(q: BatchQuery) -> RoaringBitmap:
        key = (q.op, q.operands)
        if key not in cache:
            cache[key] = _sequential(workload, q)
        return cache[key]

    return get


@pytest.mark.parametrize("q,engines", [
    (8, ("xla", "xla-vmap", "pallas")),
    (64, ("xla", "pallas")),
    (256, ("xla",)),  # interpret-mode Pallas at Q=256 is CI-prohibitive;
    #                   the TPU lane runs the full matrix on census1881
])
def test_mixed_op_batches_match_sequential(workload, engine, oracle,
                                           q, engines):
    queries = _mixed_queries(q, form="bitmap", seed=q)
    want = [oracle(x) for x in queries]
    assert any(w.cardinality for w in want)
    for eng in engines:
        res = engine.execute(queries, engine=eng)
        for x, r, w in zip(queries, res, want):
            assert r.cardinality == w.cardinality, (eng, x)
            assert r.bitmap == w, (eng, x)


def test_jit_vs_eager(engine, oracle):
    queries = _mixed_queries(8, form="bitmap", seed=99)
    want = [oracle(x) for x in queries]
    jitted = engine.execute(queries, engine="xla", jit=True)
    eager = engine.execute(queries, engine="xla", jit=False)
    for w, a, b in zip(want, jitted, eager):
        assert a.bitmap == w and b.bitmap == w
        assert a.cardinality == b.cardinality == w.cardinality


def test_and_partial_presence_annihilates(workload, engine):
    """A key missing from ANY operand must annihilate that key's AND —
    the workShyAnd rule, exercised through the batched mask path."""
    a = RoaringBitmap.bitmap_of(1, 2, 3)
    b = RoaringBitmap.bitmap_of(2, 3, 0x20001)       # extra key
    c = RoaringBitmap.bitmap_of(2, 0x20001, 0x30005)
    eng = BatchEngine.from_bitmaps([a, b, c])
    res = eng.execute([
        BatchQuery("and", (0, 1, 2), form="bitmap"),
        BatchQuery("and", (1, 2), form="bitmap"),
        BatchQuery("andnot", (1, 0), form="bitmap"),
    ], engine="xla")
    assert res[0].bitmap.to_array().tolist() == [2]
    assert res[1].bitmap.to_array().tolist() == [2, 0x20001]
    assert res[2].bitmap.to_array().tolist() == [0x20001]


def test_edge_queries(workload, engine, oracle):
    queries = [
        BatchQuery("or", (3,), form="bitmap"),          # single operand
        BatchQuery("or", (), form="bitmap"),            # empty subset
        BatchQuery("andnot", (2,), form="bitmap"),      # head, no rest
        BatchQuery("xor", (5, 5, 7), form="bitmap"),    # duplicate operand
        BatchQuery("or", (0, 1), form="cardinality"),
        BatchQuery("or", (0, 1), form="cardinality"),   # duplicate query
    ]
    res = engine.execute(queries, engine="xla")
    assert res[0].bitmap == workload[3]
    assert res[1].cardinality == 0 and res[1].bitmap.is_empty()
    assert res[2].bitmap == workload[2]
    # operands are set-semantic: {5, 5, 7} == {5, 7}
    assert res[3].bitmap == oracle(BatchQuery("xor", (5, 7)))
    assert res[4].cardinality == res[5].cardinality \
        == oracle(BatchQuery("or", (0, 1))).cardinality


def test_invalid_queries(engine):
    with pytest.raises(ValueError, match="unsupported batch op"):
        BatchQuery("nand", (0, 1))
    with pytest.raises(ValueError, match="result form"):
        BatchQuery("or", (0, 1), form="words")
    with pytest.raises(IndexError):
        engine.execute([BatchQuery("or", (0, N + 3))])
    assert engine.execute([]) == []


@pytest.mark.skipif(bool(__import__("os").environ.get("ROARING_TPU_FAULTS")),
                    reason="fault injection demotes engines, which adds "
                           "extra program signatures by design")
def test_bucketing_bounds_recompiles(engine):
    """Same (op, operand-rung, padded-shape) signature must reuse the
    compiled program; a novel rung adds exactly the new signature."""
    q1 = [BatchQuery("or", (0, 1)), BatchQuery("or", (2, 3))]
    engine._programs.clear()
    engine.execute(q1, engine="xla")
    n1 = len(engine._programs)
    engine.execute([BatchQuery("or", (4, 5)), BatchQuery("or", (6, 7))],
                   engine="xla")
    assert len(engine._programs) == n1  # same signature -> cache hit
    engine.execute([BatchQuery("or", tuple(range(12)))], engine="xla")
    assert len(engine._programs) == n1 + 1  # new operand rung


def test_plan_shapes_are_pow2(engine):
    plan = engine.plan(_mixed_queries(10, seed=4))
    for b in plan:
        for v in (b.q, b.r_pad, b.k_pad):
            assert v & (v - 1) == 0 and v >= 1
    # mixed ops split into per-op buckets
    assert len({b.op for b in plan}) == 4


@pytest.mark.parametrize("engine_name", ["xla", "pallas"])
def test_compact_layout_batches(workload, oracle, engine_name):
    """Compact residents rebuild the image inside the batch program (the
    chunked one-hot kernel under pallas) — parity must hold."""
    eng = BatchEngine.from_bitmaps(workload, layout="compact")
    queries = _mixed_queries(8, form="bitmap", seed=21)
    res = eng.execute(queries, engine=engine_name)
    for x, r in zip(queries, res):
        w = oracle(x)
        assert r.cardinality == w.cardinality and r.bitmap == w, \
            (engine_name, x)


def test_chained_batch_cardinality(workload, engine, oracle):
    queries = _mixed_queries(12, seed=7)
    total = sum(oracle(x).cardinality for x in queries)
    for eng_name in ("xla", "pallas"):
        fn = engine.chained_cardinality(queries, 4, engine=eng_name)
        got = int(np.asarray(fn()))
        assert got == (4 * total) % 2**32, eng_name


def test_u64_tier_batch():
    from roaringbitmap_tpu.core.bitmap64 import Roaring64Bitmap

    bms = [Roaring64Bitmap.from_values(
        (np.uint64(i % 3) << np.uint64(40))
        + np.arange(i * 50, 3000, dtype=np.uint64)) for i in range(6)]
    eng = BatchEngine.from_bitmaps(bms)
    res = eng.execute([BatchQuery("or", (0, 3), form="bitmap"),
                       BatchQuery("and", (1, 4), form="bitmap")],
                      engine="xla")
    want_or = aggregation.or64(bms[0], bms[3])
    want_and = aggregation.and64(bms[1], bms[4])
    assert isinstance(res[0].bitmap, Roaring64Bitmap)
    assert res[0].bitmap == want_or
    assert res[1].bitmap == want_and


def test_one_shot_helper(workload, oracle):
    from roaringbitmap_tpu.parallel.aggregation import DeviceBitmapSet

    ds = DeviceBitmapSet(workload)
    res = batch_engine.execute_batch(
        ds, [BatchQuery("or", (0, 1, 2), form="bitmap")], engine="xla")
    assert res[0].bitmap == oracle(BatchQuery("or", (0, 1, 2)))


@pytest.mark.slow
@pytest.mark.skipif(not __import__(
    "roaringbitmap_tpu.utils.datasets", fromlist=["has_dataset"]
).has_dataset("census1881"), reason="census1881 zip not mounted")
@pytest.mark.parametrize("q", [8, 64, 256])
def test_census1881_mixed_batches(q):
    """The acceptance matrix on real data (runs where the dataset is
    mounted — the TPU lane): mixed-op batches at Q in {8, 64, 256},
    bit-exact vs the sequential path, Pallas vs XLA, jit vs eager."""
    from roaringbitmap_tpu.utils import datasets

    bms = datasets.load_bitmaps("census1881")
    eng = BatchEngine.from_bitmaps(bms)
    rng = np.random.default_rng(q)
    queries = []
    for i in range(q):
        op = ("or", "and", "xor", "andnot")[i % 4]
        k = int(rng.integers(2, 17))
        queries.append(BatchQuery(
            op=op, operands=tuple(
                int(x) for x in rng.choice(len(bms), size=k,
                                           replace=False)),
            form="bitmap"))
    want = [_sequential(bms, x) for x in queries]
    import jax

    engines = ["xla"]
    if jax.default_backend() == "tpu":
        engines.append("pallas")
    for eng_name in engines:
        res = eng.execute(queries, engine=eng_name)
        for x, r, w in zip(queries, res, want):
            assert r.cardinality == w.cardinality, (eng_name, x)
            assert r.bitmap == w, (eng_name, x)
    eager = eng.execute(queries[:8], engine="xla", jit=False)
    assert all(r.bitmap == w for r, w in zip(eager, want[:8]))


def test_byte_backed_resident_set(workload, oracle):
    """Serialized-bytes ingest (native or NumPy packer) must still carry
    the row_src metadata the planner needs."""
    blobs = [b.serialize() for b in workload]
    eng = BatchEngine.from_bitmaps(blobs)
    queries = _mixed_queries(8, form="bitmap", seed=33)
    res = eng.execute(queries, engine="xla")
    for x, r in zip(queries, res):
        assert r.bitmap == oracle(x), x
